"""In-graph cycle telemetry (ISSUE 3 acceptance).

- Equality: decisions (and their sha256 fingerprints) are bit-identical
  with telemetry on vs off, across the scan path, both pallas interpret
  paths, and conf presets.
- Counter correctness: the kernel's CycleTelemetry block equals the CPU
  reference oracle's mirror exactly on the scan path (rejection counts
  per family, attempts, placements, discards, ties, rounds/pops,
  committed f32 sums, unplaced-reason histogram).
- Flight recorder: bounded ring semantics, scheduler + dashboard wiring.
- Trace counters and the metrics bridge.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import urllib.request

import jax
import numpy as np
import pytest

from volcano_tpu.arrays import pack
from volcano_tpu.ops.allocate_scan import (AllocateConfig, AllocateExtras,
                                           derive_batching,
                                           make_allocate_cycle)
from volcano_tpu.runtime.cpu_reference import allocate_cpu
from volcano_tpu.telemetry import (FlightRecorder, cycle_telemetry_size,
                                   unpack_cycle_telemetry)
from volcano_tpu.telemetry.cycle import (PRED_FAMILIES, UNPLACED_REASONS,
                                         CycleTelemetry)

from fixtures import build_job, build_node, build_task, make_cluster, \
    simple_cluster


def _scarce_cluster():
    """3 small nodes, 8 gangs of 4x(2cpu) with min_available=3: forces
    breaks, gang discards, give-up rounds, and unplaced tasks."""
    ci = simple_cluster(n_nodes=3, node_cpu="4", node_mem="8Gi")
    for j in range(8):
        job = build_job(f"default/g{j}", min_available=3,
                        creation_timestamp=float(j))
        for t in range(4):
            job.add_task(build_task(f"g{j}-t{t}", cpu="2", memory="2Gi"))
        ci.add_job(job)
    return ci


def _tie_cluster():
    """Identical empty nodes => exactly tied scores in f32 and f64, so
    the argmax tie counter is comparable against the oracle."""
    ci = simple_cluster(n_nodes=4, node_cpu="8", node_mem="16Gi")
    job = build_job("default/j", min_available=1, creation_timestamp=0.0)
    for t in range(3):
        job.add_task(build_task(f"j-t{t}", cpu="1", memory="1Gi"))
    ci.add_job(job)
    return ci


def _snap_extras(ci):
    snap, _maps = pack(ci)
    return snap, AllocateExtras.neutral(snap)


def _cfg(**kw):
    return dataclasses.replace(
        derive_batching(AllocateConfig(binpack_weight=1.0, enable_gpu=False,
                                       **kw),
                        has_proportion=False), use_pallas=False)


def _sha(res):
    return hashlib.sha256(
        np.asarray(res.task_node).tobytes()
        + np.asarray(res.task_mode).tobytes()).hexdigest()


def _kernel_tel(res, snap):
    R = np.asarray(snap.nodes.idle).shape[1]
    return unpack_cycle_telemetry(np.asarray(res.telemetry.packed()), R)


class TestDecisionEquality:
    """Telemetry must be decision-neutral: shas bit-identical on/off."""

    @pytest.mark.parametrize("build", [make_cluster, _scarce_cluster])
    def test_scan_path(self, build):
        snap, extras = _snap_extras(build())
        cfg = _cfg()
        off = jax.jit(make_allocate_cycle(cfg))(snap, extras)
        on = jax.jit(make_allocate_cycle(
            dataclasses.replace(cfg, telemetry=True)))(snap, extras)
        assert _sha(off) == _sha(on)
        assert np.array_equal(np.asarray(off.task_gpu),
                              np.asarray(on.task_gpu))
        assert np.array_equal(np.asarray(off.job_ready),
                              np.asarray(on.job_ready))
        assert off.telemetry is None and on.telemetry is not None

    @pytest.mark.parametrize("dyn", [False, True])
    def test_pallas_interpret_paths(self, dyn):
        snap, extras = _snap_extras(make_cluster())
        base = derive_batching(
            AllocateConfig(binpack_weight=1.0, enable_gpu=False,
                           drf_job_order=dyn), has_proportion=False)
        cfg = dataclasses.replace(base, use_pallas="interpret")
        off = jax.jit(make_allocate_cycle(cfg))(snap, extras)
        on = jax.jit(make_allocate_cycle(
            dataclasses.replace(cfg, telemetry=True)))(snap, extras)
        assert _sha(off) == _sha(on)
        tel = _kernel_tel(on, snap)
        total_placed = int(np.asarray(on.task_mode > 0).sum())
        assert tel["placed_now"] + tel["placed_future"] == total_placed
        if dyn:
            assert tel["dyn_launches"] >= 1
            assert tel["dyn_pops"] >= tel["dyn_launches"]

    def test_default_conf_cycle(self):
        from volcano_tpu.framework.compiled_session import make_conf_cycle
        from volcano_tpu.framework.conf import DEFAULT_SCHEDULER_CONF
        snap, _ = _snap_extras(make_cluster())
        off = make_conf_cycle(DEFAULT_SCHEDULER_CONF)
        on = make_conf_cycle("telemetry: true\n" + DEFAULT_SCHEDULER_CONF)
        r_off = jax.jit(lambda s: off(s))(snap)
        r_on = jax.jit(lambda s: on(s))(snap)
        assert _sha(r_off) == _sha(r_on)
        assert r_on.telemetry is not None


@pytest.mark.slow
def test_all_conf_presets_equal():
    """Full preset sweep (slow tail): every shipped conf places
    identically with telemetry compiled in."""
    from volcano_tpu.analysis.entrypoints import _conf_presets
    from volcano_tpu.framework.compiled_session import make_conf_cycle
    snap, _ = _snap_extras(make_cluster())
    for name, text in _conf_presets(fast=False):
        r_off = jax.jit(lambda s, c=make_conf_cycle(text): c(s))(snap)
        r_on = jax.jit(lambda s, c=make_conf_cycle(
            "telemetry: true\n" + text): c(s))(snap)
        assert _sha(r_off) == _sha(r_on), name


class TestCounterCorrectness:
    """Kernel counters == CPU oracle mirror, exactly (scan path)."""

    @pytest.mark.parametrize("build,kw", [
        (make_cluster, {}),
        (_scarce_cluster, {}),
        (make_cluster, dict(drf_job_order=True)),
    ])
    def test_oracle_equality(self, build, kw):
        snap, extras = _snap_extras(build())
        cfg = dataclasses.replace(_cfg(**kw), telemetry=True)
        res = jax.jit(make_allocate_cycle(cfg))(snap, extras)
        cpu = allocate_cpu(snap, extras, cfg, collect_telemetry=True)
        assert np.array_equal(np.asarray(res.task_node), cpu["task_node"])
        assert np.array_equal(np.asarray(res.task_mode), cpu["task_mode"])
        ktel, otel = _kernel_tel(res, snap), cpu["telemetry"]
        assert ktel == otel

    def test_scarce_fixture_exercises_counters(self):
        """The fixture must actually hit the interesting counters, or the
        equality above proves nothing."""
        snap, extras = _snap_extras(_scarce_cluster())
        cfg = dataclasses.replace(_cfg(), telemetry=True)
        res = jax.jit(make_allocate_cycle(cfg))(snap, extras)
        tel = _kernel_tel(res, snap)
        assert sum(tel["pred_reject"].values()) > 0
        assert tel["gang_discarded"] > 0
        assert tel["unplaced"]["job_failed"] > 0
        assert tel["attempts"] > tel["placed_now"]

    def test_argmax_ties_counted(self):
        snap, extras = _snap_extras(_tie_cluster())
        cfg = dataclasses.replace(_cfg(), telemetry=True)
        res = jax.jit(make_allocate_cycle(cfg))(snap, extras)
        cpu = allocate_cpu(snap, extras, cfg, collect_telemetry=True)
        tel = _kernel_tel(res, snap)
        # first placement: 4 identical empty nodes tie 4-ways -> 3 extras
        assert tel["argmax_ties"] >= 3
        assert tel["argmax_ties"] == cpu["telemetry"]["argmax_ties"]

    def test_unplaced_reason_names_stable(self):
        # the metrics bridge and dashboards key on these label sets
        assert PRED_FAMILIES[0] == "template" and len(PRED_FAMILIES) == 11
        assert UNPLACED_REASONS == ("job_not_popped", "job_failed",
                                    "job_kept_leftover")


class TestPackedRoundtrip:
    def test_zeros_roundtrip(self):
        tel = CycleTelemetry.zeros(3)
        d = unpack_cycle_telemetry(np.asarray(tel.packed()), 3)
        assert sum(d["pred_reject"].values()) == 0
        assert d["committed"] == [0.0, 0.0, 0.0]
        assert d["rounds"] == 0

    def test_f32_bitcast_roundtrip(self):
        tel = dataclasses.replace(
            CycleTelemetry.zeros(2),
            committed=np.asarray([1.5, 3.25e9], np.float32))
        d = unpack_cycle_telemetry(np.asarray(tel.packed()), 2)
        assert d["committed"] == [1.5, float(np.float32(3.25e9))]
        assert len(np.asarray(tel.packed())) == cycle_telemetry_size(2)


class TestBackfillPreemptBlocks:
    def test_backfill_counts(self):
        ci = simple_cluster(n_nodes=2)
        job = build_job("default/be", min_available=1)
        job.add_task(build_task("be-0", cpu=0, memory=0))
        ci.add_job(job)
        snap, _ = _snap_extras(ci)
        from volcano_tpu.ops.backfill import make_backfill_pass
        tn_off, pl_off = jax.jit(make_backfill_pass())(snap)
        tn_on, pl_on, tel = jax.jit(make_backfill_pass(telemetry=True))(snap)
        assert np.array_equal(np.asarray(tn_off), np.asarray(tn_on))
        assert np.array_equal(np.asarray(pl_off), np.asarray(pl_on))
        host = tel.to_host()
        assert host["candidates"] >= 1
        assert host["placed"] == int(np.asarray(pl_on).sum())

    def test_preempt_counts(self):
        import sys
        sys.path.insert(0, "/root/repo")
        from scripts.preempt_profile import scenario
        from volcano_tpu.ops.preempt import PreemptConfig, make_preempt_cycle
        snap, _maps = pack(scenario(n_nodes=32, n_jobs=24, n_gangs=2,
                                    gang_tasks=4, min_avail=2))
        extras = AllocateExtras.neutral(snap)
        T = np.asarray(snap.tasks.status).shape[0]
        veto = np.zeros(T, bool)
        skip = np.zeros(T, bool)
        pcfg = PreemptConfig(scoring=AllocateConfig(binpack_weight=1.0,
                                                    enable_gpu=False))
        off = jax.jit(make_preempt_cycle(pcfg))(snap, extras, veto, skip)
        on = jax.jit(make_preempt_cycle(dataclasses.replace(
            pcfg, telemetry=True)))(snap, extras, veto, skip)
        assert np.array_equal(np.asarray(off.evicted), np.asarray(on.evicted))
        assert np.array_equal(np.asarray(off.task_mode),
                              np.asarray(on.task_mode))
        assert off.telemetry is None
        host = on.telemetry.to_host()
        assert host["evicted"] == int(np.asarray(on.evicted).sum())
        assert host["pipelined_tasks"] == int(
            (np.asarray(on.task_mode) == 2).sum())
        assert host["rounds"] >= 1


class TestFlightRecorder:
    def test_ring_is_bounded(self):
        fr = FlightRecorder(capacity=5)
        for i in range(17):
            fr.record(now=float(i), cycle=i)
        assert len(fr) == 5
        assert fr.recorded_total == 17
        snaps = fr.snapshots()
        assert [e["cycle"] for e in snaps] == list(range(12, 17))
        assert snaps[-1]["seq"] == 17
        body = json.loads(fr.to_json())
        assert body["capacity"] == 5 and len(body["cycles"]) == 5

    def test_capacity_validation(self):
        with pytest.raises(ValueError):
            FlightRecorder(capacity=0)

    def test_concurrent_append_is_thread_safe(self):
        """The pipelined loop's drain/dispatch split and the sidecar's
        deferred finish() append from different threads: N writers x M
        records must lose nothing, keep the ring bounded, and hand out
        unique seq numbers."""
        import threading
        fr = FlightRecorder(capacity=64)
        n_threads, per_thread = 8, 50
        start = threading.Barrier(n_threads)

        def writer(t):
            start.wait()
            for i in range(per_thread):
                fr.record(now=float(i), thread=t, i=i)

        threads = [threading.Thread(target=writer, args=(t,))
                   for t in range(n_threads)]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        assert fr.recorded_total == n_threads * per_thread
        snaps = fr.snapshots()
        assert len(snaps) == 64
        seqs = [e["seq"] for e in snaps]
        assert len(set(seqs)) == len(seqs)       # no duplicated slot
        json.dumps(snaps)                        # still JSON-clean

    def test_pickle_roundtrip_with_span_summary(self):
        """vcctl --state pickles the recorder; entries carrying the span
        summary (plain {phase: ms} dicts from drain_cycle_summary) must
        survive the round trip, and the restored recorder must record
        again (its lock is recreated, not pickled)."""
        import pickle
        from volcano_tpu.telemetry import spans
        spans.reset()
        with spans.span("pack"):
            pass
        fr = FlightRecorder(capacity=4)
        fr.record(now=1.0, cycle=1, spans=spans.drain_cycle_summary())
        clone = pickle.loads(pickle.dumps(fr))
        entry = clone.snapshots()[-1]
        assert entry["cycle"] == 1
        assert isinstance(entry["spans"], dict) and "pack" in entry["spans"]
        clone.record(now=2.0, cycle=2)           # lock usable post-restore
        assert clone.recorded_total == 2


TELEMETRY_CONF = """
telemetry: true
actions: "enqueue, allocate, backfill"
tiers:
- plugins:
  - name: gang
  - name: binpack
"""


def _run_scheduler(conf_text=TELEMETRY_CONF, cycles=3):
    from volcano_tpu.framework import parse_conf
    from volcano_tpu.runtime.fake_cluster import FakeCluster
    from volcano_tpu.runtime.scheduler import Scheduler
    ci = simple_cluster(n_nodes=4, node_cpu="8", node_mem="16Gi")
    for j in range(3):
        job = build_job(f"default/j{j}", min_available=1,
                        creation_timestamp=float(j))
        for t in range(2):
            job.add_task(build_task(f"j{j}-t{t}", cpu="1", memory="1Gi"))
        ci.add_job(job)
    # one forever-unplaceable gang so unschedule reasons are non-trivial
    big = build_job("default/huge", min_available=1, creation_timestamp=9.0)
    big.add_task(build_task("huge-0", cpu="64", memory="1Gi"))
    ci.add_job(big)
    sched = Scheduler(FakeCluster(ci), conf=parse_conf(conf_text))
    for _ in range(cycles):
        sched.run_once()
    return sched


class TestSchedulerIntegration:
    def setup_method(self):
        # isolate BOTH process-global registries: the metrics bridge and
        # the jit trace counters. Without the tracecount reset the
        # fused-cycle assertions below depended on which test files ran
        # earlier in the process (the file was red standalone, green in
        # the full suite — the ISSUE 9 order-dependence fix).
        from volcano_tpu.metrics import METRICS
        from volcano_tpu.telemetry import tracecount
        METRICS.reset()
        tracecount.reset()

    def test_session_last_telemetry_and_flight(self):
        sched = _run_scheduler()
        assert len(sched.flight) == 3
        entry = sched.flight.snapshots()[-1]
        assert entry["cycle"] == 3 and "wall_ts" in entry
        tel = entry["telemetry"]["allocate"]
        assert set(tel["pred_reject"]) == set(PRED_FAMILIES)
        # the 64-cpu task never places: counted with a reason every cycle
        assert sum(tel["unplaced"].values()) >= 1
        json.dumps(entry)   # flight entries must stay JSON-serializable

    def test_metrics_bridge(self):
        from volcano_tpu.metrics import METRICS
        _run_scheduler()
        text = METRICS.exposition()
        assert 'volcano_schedule_attempts_total{result="scheduled"}' in text
        assert "volcano_unschedule_task_count{reason=" in text
        assert "volcano_jit_traces{" in text
        # steady state: the fused cycle called every cycle. The scheduler's
        # default path is the delta-upload entry (`fused_cycle_delta` —
        # ops/fused_io); the plain `fused_cycle` entry only exists when a
        # full-upload test ran earlier in the process, which is exactly
        # the order dependence this test used to have.
        from volcano_tpu.telemetry.tracecount import counts
        c = counts().get("fused_cycle_delta")
        assert c is not None and c["calls"] >= 3
        assert c["cache_hits"] == c["calls"] - c["traces"]

    def test_telemetry_off_by_default(self):
        sched = _run_scheduler(conf_text=TELEMETRY_CONF.replace(
            "telemetry: true\n", ""), cycles=1)
        entry = sched.flight.snapshots()[-1]
        assert entry["telemetry"] is None

    def test_dashboard_serves_flight_ring(self):
        sched = _run_scheduler(cycles=2)

        class _Sys:          # dashboard only needs the flight recorder path
            scheduler = sched
        from volcano_tpu.runtime.dashboard import Dashboard, _flight_of
        assert _flight_of(_Sys()) is sched.flight
        dash = Dashboard(_Sys())
        port = dash.serve(port=0)
        try:
            body = json.loads(urllib.request.urlopen(
                f"http://127.0.0.1:{port}/api/telemetry").read())
            assert len(body["cycles"]) == 2
            assert body["cycles"][-1]["telemetry"]["allocate"]["placed_now"] \
                >= 0
        finally:
            dash.shutdown()


class TestTraceCount:
    def test_counted_jit_counts_traces_not_calls(self):
        from volcano_tpu.telemetry import tracecount as tc

        def f(x):
            return x * 2.0

        g = tc.counted_jit(f, "test_entry_xyz")
        a = np.ones(4, np.float32)
        for _ in range(3):
            np.asarray(g(a))
        np.asarray(g(np.ones(5, np.float32)))   # new shape bucket
        c = tc.counts()["test_entry_xyz"]
        assert c["calls"] == 4 and c["traces"] == 2 and c["cache_hits"] == 2


class TestSidecarFlight:
    def test_served_cycles_recorded(self):
        from volcano_tpu.native.wire import serialize
        from volcano_tpu.runtime.sidecar import SchedulerSidecar
        ci = make_cluster()
        buf, _maps = serialize(ci)
        car = SchedulerSidecar(cfg=AllocateConfig(binpack_weight=1.0))
        car.schedule_buffer(buf)
        car.schedule_buffer(buf)
        assert len(car.flight) == 2
        e = car.flight.snapshots()[-1]
        assert e["buffer_bytes"] == len(buf) and e["tasks"] > 0
