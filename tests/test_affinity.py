"""InterPodAffinity: predicate + batch scorer semantics.

Mirrors the behavior of the k8s InterPodAffinity plugin the reference wraps
(pkg/scheduler/plugins/predicates/predicates.go:196-200 + 261-273 filter,
pkg/scheduler/plugins/nodeorder/nodeorder.go:273-306 batch scorer): required
affinity/anti-affinity by topology domain, the symmetric anti-affinity of
existing pods, the k8s first-pod escape, preferred-term scoring, and gang
discard rollback of in-cycle affinity state.
"""

import dataclasses

import jax
import numpy as np
import pytest

from volcano_tpu.api import (ClusterInfo, JobInfo, NodeInfo, PodAffinityTerm,
                             PodGroupPhase, QueueInfo, Resource, TaskInfo,
                             TaskStatus)
from volcano_tpu.arrays import pack
from volcano_tpu.arrays.affinity import AffinityArrays, build_affinity
from volcano_tpu.ops.allocate_scan import (AllocateConfig, AllocateExtras,
                                           make_allocate_cycle)
from volcano_tpu.runtime.cpu_reference import allocate_cpu

R = Resource.from_resource_list

CFG = AllocateConfig(binpack_weight=1.0, least_allocated_weight=0.0,
                     balanced_weight=0.0, taint_prefer_weight=0.0,
                     enable_pod_affinity=True)


def make_zone_cluster(n_nodes=4, zones=("a", "a", "b", "b"),
                      cpu="8", mem="16Gi"):
    ci = ClusterInfo()
    ci.add_queue(QueueInfo("default", weight=1))
    for i in range(n_nodes):
        n = NodeInfo(f"n{i}", R({"cpu": cpu, "memory": mem}),
                     R({"cpu": cpu, "memory": mem}))
        n.labels["zone"] = zones[i % len(zones)]
        n.labels["kubernetes.io/hostname"] = f"n{i}"
        ci.add_node(n)
    return ci


def task(name, labels=None, cpu="1", mem="1Gi", **kw):
    t = TaskInfo(name, name, resreq=R({"cpu": cpu, "memory": mem}),
                 labels=labels or {})
    for k, v in kw.items():
        setattr(t, k, v)
    return t


def run_cycle(ci, cfg=CFG):
    snap, maps = pack(ci)
    N = snap.nodes.idle.shape[0]
    T = snap.tasks.resreq.shape[0]
    extras = dataclasses.replace(
        AllocateExtras.neutral(snap),
        affinity=build_affinity(ci, maps, N, T))
    fn = jax.jit(make_allocate_cycle(cfg))
    res = fn(snap, extras)
    node_of = {}
    mode_of = {}
    tn, tm = np.asarray(res.task_node), np.asarray(res.task_mode)
    for uid, ti in maps.task_index.items():
        node_of[uid] = maps.node_names[int(tn[ti])] if tm[ti] > 0 else None
        mode_of[uid] = int(tm[ti])
    return res, node_of, maps, (snap, extras)


class TestRequiredTerms:
    def test_anti_affinity_spreads_by_hostname(self):
        ci = make_zone_cluster()
        job = JobInfo("default/j", min_available=3, queue="default",
                      pod_group_phase=PodGroupPhase.INQUEUE)
        for i in range(3):
            t = task(f"c{i}", labels={"app": "c"})
            t.pod_anti_affinity = [PodAffinityTerm(
                topology_key="kubernetes.io/hostname",
                match_labels={"app": "c"})]
            job.add_task(t)
        ci.add_job(job)
        _, node_of, _, _ = run_cycle(ci)
        nodes = [node_of[f"c{i}"] for i in range(3)]
        assert None not in nodes
        assert len(set(nodes)) == 3, f"anti-affinity must spread: {nodes}"

    def test_affinity_follows_zone(self):
        ci = make_zone_cluster()
        job = JobInfo("default/j", min_available=2, queue="default",
                      pod_group_phase=PodGroupPhase.INQUEUE)
        leader = task("leader", labels={"role": "leader"})
        job.add_task(leader)
        follower = task("follower", labels={"role": "follower"})
        follower.pod_affinity = [PodAffinityTerm(
            topology_key="zone", match_labels={"role": "leader"})]
        job.add_task(follower)
        ci.add_job(job)
        _, node_of, _, _ = run_cycle(ci)
        assert node_of["leader"] and node_of["follower"]
        zone = {"n0": "a", "n1": "a", "n2": "b", "n3": "b"}
        assert zone[node_of["leader"]] == zone[node_of["follower"]]

    def test_first_pod_escape_self_match(self):
        """k8s: required affinity with no matching pod anywhere admits the
        pod on topology-key-bearing nodes IF it matches its own selector."""
        ci = make_zone_cluster()
        job = JobInfo("default/j", min_available=1, queue="default",
                      pod_group_phase=PodGroupPhase.INQUEUE)
        t = task("solo", labels={"app": "x"})
        t.pod_affinity = [PodAffinityTerm(topology_key="zone",
                                          match_labels={"app": "x"})]
        job.add_task(t)
        ci.add_job(job)
        _, node_of, _, _ = run_cycle(ci)
        assert node_of["solo"] is not None

    def test_no_escape_when_selector_mismatch(self):
        """Without the self-match, required affinity with no matching pod
        is unsatisfiable — the gang stays pending."""
        ci = make_zone_cluster()
        job = JobInfo("default/j", min_available=1, queue="default",
                      pod_group_phase=PodGroupPhase.INQUEUE)
        t = task("solo", labels={"app": "y"})
        t.pod_affinity = [PodAffinityTerm(topology_key="zone",
                                          match_labels={"app": "x"})]
        job.add_task(t)
        ci.add_job(job)
        _, node_of, _, _ = run_cycle(ci)
        assert node_of["solo"] is None

    def test_existing_pod_blocks_incoming_by_anti_affinity(self):
        """Symmetric anti-affinity: a RUNNING pod carrying a required
        anti-affinity term excludes matching incoming pods from its domain."""
        ci = make_zone_cluster()
        holder = JobInfo("default/holder", min_available=1, queue="default",
                         pod_group_phase=PodGroupPhase.RUNNING)
        h = task("holder-0", labels={"team": "red"})
        h.pod_anti_affinity = [PodAffinityTerm(
            topology_key="zone", match_labels={"team": "red"})]
        h.status = TaskStatus.RUNNING
        holder.add_task(h)
        ci.add_job(holder)
        ci.nodes["n0"].add_task(h, force=True)

        job = JobInfo("default/j", min_available=1, queue="default",
                      pod_group_phase=PodGroupPhase.INQUEUE)
        newcomer = task("new-0", labels={"team": "red"})
        job.add_task(newcomer)
        ci.add_job(job)
        _, node_of, _, _ = run_cycle(ci)
        zone = {"n0": "a", "n1": "a", "n2": "b", "n3": "b"}
        assert node_of["new-0"] is not None
        assert zone[node_of["new-0"]] == "b", \
            "must avoid the holder's zone (symmetric anti-affinity)"

    def test_incoming_anti_vs_existing_pod(self):
        """The incoming pod's own anti term avoids domains holding
        matching existing pods."""
        ci = make_zone_cluster()
        holder = JobInfo("default/holder", min_available=1, queue="default",
                         pod_group_phase=PodGroupPhase.RUNNING)
        h = task("holder-0", labels={"db": "pg"})
        h.status = TaskStatus.RUNNING
        holder.add_task(h)
        ci.add_job(holder)
        ci.nodes["n2"].add_task(h, force=True)

        job = JobInfo("default/j", min_available=1, queue="default",
                      pod_group_phase=PodGroupPhase.INQUEUE)
        t = task("new-0", labels={"app": "web"})
        t.pod_anti_affinity = [PodAffinityTerm(
            topology_key="zone", match_labels={"db": "pg"})]
        job.add_task(t)
        ci.add_job(job)
        _, node_of, _, _ = run_cycle(ci)
        zone = {"n0": "a", "n1": "a", "n2": "b", "n3": "b"}
        assert zone[node_of["new-0"]] == "a"

    def test_gang_discard_rolls_back_affinity_counts(self):
        """A discarded gang's placements must not satisfy a later job's
        required affinity (statement.go:352-374 undo analog)."""
        ci = make_zone_cluster(n_nodes=2, zones=("a", "a"), cpu="2",
                               mem="4Gi")
        # gang too big to fit -> discarded, but its tasks carry app=ghost
        ghost = JobInfo("default/ghost", min_available=5, queue="default",
                        pod_group_phase=PodGroupPhase.INQUEUE, priority=10)
        for i in range(5):
            ghost.add_task(task(f"g{i}", labels={"app": "ghost"}, cpu="1"))
        ci.add_job(ghost)
        seeker = JobInfo("default/seeker", min_available=1, queue="default",
                         pod_group_phase=PodGroupPhase.INQUEUE)
        s = task("s0", labels={"app": "seeker"})
        s.pod_affinity = [PodAffinityTerm(topology_key="zone",
                                          match_labels={"app": "ghost"})]
        seeker.add_task(s)
        ci.add_job(seeker)
        res, node_of, _, _ = run_cycle(ci)
        # the discarded gang's tasks must be unplaced
        assert node_of.get("g0") is None
        # ghost cannot fit (5 tasks x 1cpu on 2x2cpu) -> discarded;
        # seeker's affinity must NOT be satisfied by ghost's rolled-back
        # placements
        assert node_of["s0"] is None


class TestPreferredTerms:
    def test_preferred_affinity_steers_score(self):
        ci = make_zone_cluster()
        holder = JobInfo("default/holder", min_available=1, queue="default",
                         pod_group_phase=PodGroupPhase.RUNNING)
        h = task("holder-0", labels={"cache": "hot"})
        h.status = TaskStatus.RUNNING
        holder.add_task(h)
        ci.add_job(holder)
        ci.nodes["n3"].add_task(h, force=True)

        job = JobInfo("default/j", min_available=1, queue="default",
                      pod_group_phase=PodGroupPhase.INQUEUE)
        t = task("web-0", labels={"app": "web"})
        t.pod_affinity_preferred = [PodAffinityTerm(
            topology_key="zone", match_labels={"cache": "hot"}, weight=10)]
        job.add_task(t)
        ci.add_job(job)
        _, node_of, _, _ = run_cycle(ci)
        zone = {"n0": "a", "n1": "a", "n2": "b", "n3": "b"}
        assert zone[node_of["web-0"]] == "b"

    def test_preferred_anti_affinity_repels(self):
        ci = make_zone_cluster()
        holder = JobInfo("default/holder", min_available=1, queue="default",
                         pod_group_phase=PodGroupPhase.RUNNING)
        h = task("holder-0", labels={"noisy": "yes"})
        h.status = TaskStatus.RUNNING
        holder.add_task(h)
        ci.add_job(holder)
        ci.nodes["n0"].add_task(h, force=True)

        job = JobInfo("default/j", min_available=1, queue="default",
                      pod_group_phase=PodGroupPhase.INQUEUE)
        t = task("quiet-0", labels={"app": "quiet"})
        t.pod_anti_affinity_preferred = [PodAffinityTerm(
            topology_key="zone", match_labels={"noisy": "yes"}, weight=10)]
        job.add_task(t)
        ci.add_job(job)
        _, node_of, _, _ = run_cycle(ci)
        zone = {"n0": "a", "n1": "a", "n2": "b", "n3": "b"}
        assert zone[node_of["quiet-0"]] == "b"

    def test_symmetric_preferred_from_existing_pod(self):
        """An existing pod's preferred-affinity term scores incoming pods
        that match it toward the pod's domain."""
        ci = make_zone_cluster()
        holder = JobInfo("default/holder", min_available=1, queue="default",
                         pod_group_phase=PodGroupPhase.RUNNING)
        h = task("holder-0", labels={"role": "hub"})
        h.pod_affinity_preferred = [PodAffinityTerm(
            topology_key="zone", match_labels={"role": "spoke"}, weight=10)]
        h.status = TaskStatus.RUNNING
        holder.add_task(h)
        ci.add_job(holder)
        ci.nodes["n2"].add_task(h, force=True)

        job = JobInfo("default/j", min_available=1, queue="default",
                      pod_group_phase=PodGroupPhase.INQUEUE)
        t = task("spoke-0", labels={"role": "spoke"})
        job.add_task(t)
        ci.add_job(job)
        _, node_of, _, _ = run_cycle(ci)
        zone = {"n0": "a", "n1": "a", "n2": "b", "n3": "b"}
        assert zone[node_of["spoke-0"]] == "b"


class TestExpressionsAndNamespaces:
    def test_match_expressions(self):
        term = PodAffinityTerm(
            topology_key="zone",
            match_expressions=[("tier", "In", ("gold", "silver")),
                               ("legacy", "DoesNotExist", ())])
        assert term.matches({"tier": "gold"}, "default", "default")
        assert not term.matches({"tier": "bronze"}, "default", "default")
        assert not term.matches({"tier": "gold", "legacy": "1"},
                                "default", "default")

    def test_namespace_scoping(self):
        """A term without explicit namespaces only matches pods in the
        incoming task's own namespace."""
        term = PodAffinityTerm(topology_key="zone",
                               match_labels={"app": "x"})
        assert term.matches({"app": "x"}, "ns-a", "ns-a")
        assert not term.matches({"app": "x"}, "ns-b", "ns-a")
        term2 = PodAffinityTerm(topology_key="zone",
                                match_labels={"app": "x"},
                                namespaces=["ns-b"])
        assert term2.matches({"app": "x"}, "ns-b", "ns-a")
        assert not term2.matches({"app": "x"}, "ns-a", "ns-a")


class TestEquivalence:
    def test_device_matches_cpu_reference_with_affinity(self):
        """Decision equivalence under a mixed required/preferred workload."""
        rng = np.random.default_rng(7)
        zones = tuple(f"z{i}" for i in range(4))
        ci = make_zone_cluster(n_nodes=16, zones=zones)
        apps = ["a", "b", "c"]
        for j in range(6):
            job = JobInfo(f"default/j{j}", min_available=2, queue="default",
                          pod_group_phase=PodGroupPhase.INQUEUE,
                          creation_timestamp=float(j))
            for i in range(3):
                app = apps[int(rng.integers(len(apps)))]
                t = task(f"j{j}-t{i}", labels={"app": app})
                r = rng.random()
                if r < 0.3:
                    t.pod_anti_affinity = [PodAffinityTerm(
                        topology_key="kubernetes.io/hostname",
                        match_labels={"app": app})]
                elif r < 0.6:
                    t.pod_affinity_preferred = [PodAffinityTerm(
                        topology_key="zone",
                        match_labels={"app": apps[0]}, weight=5)]
                job.add_task(t)
            ci.add_job(job)
        res, _, maps, (snap, extras) = run_cycle(ci)
        cpu = allocate_cpu(snap, extras, CFG)
        np.testing.assert_array_equal(np.asarray(res.task_node),
                                      cpu["task_node"])
        np.testing.assert_array_equal(np.asarray(res.task_mode),
                                      cpu["task_mode"])

    def test_neutral_affinity_keeps_plain_path_identical(self):
        """enable_pod_affinity with no terms must not change decisions."""
        ci = make_zone_cluster()
        job = JobInfo("default/j", min_available=2, queue="default",
                      pod_group_phase=PodGroupPhase.INQUEUE)
        for i in range(2):
            job.add_task(task(f"t{i}"))
        ci.add_job(job)
        snap, maps = pack(ci)
        extras = AllocateExtras.neutral(snap)
        plain = jax.jit(make_allocate_cycle(
            dataclasses.replace(CFG, enable_pod_affinity=False)))(snap, extras)
        aff = jax.jit(make_allocate_cycle(CFG))(snap, extras)
        np.testing.assert_array_equal(np.asarray(plain.task_node),
                                      np.asarray(aff.task_node))
        np.testing.assert_array_equal(np.asarray(plain.task_mode),
                                      np.asarray(aff.task_mode))


class TestSessionIntegration:
    def test_scheduler_runs_affinity_job_end_to_end(self):
        from volcano_tpu.runtime import FakeCluster, Scheduler
        ci = make_zone_cluster()
        job = JobInfo("default/gang", min_available=3, queue="default",
                      pod_group_phase=PodGroupPhase.PENDING,
                      min_resources=R({"cpu": "3", "memory": "3Gi"}))
        for i in range(3):
            t = task(f"m{i}", labels={"app": "m"})
            t.pod_anti_affinity = [PodAffinityTerm(
                topology_key="kubernetes.io/hostname",
                match_labels={"app": "m"})]
            job.add_task(t)
        ci.add_job(job)
        sched = Scheduler(FakeCluster(ci))
        sched.run_once()
        binds = dict(sched.cluster.binds)
        assert len(binds) == 3
        assert len(set(binds.values())) == 3, \
            f"anti-affinity must spread the gang: {binds}"

    def test_pallas_affinity_supported_ports_not(self):
        """The v3 fused placer carries the live affinity counts in VMEM,
        so use_pallas + enable_pod_affinity is now a SUPPORTED pair
        (interpret run must succeed); host ports remain excluded."""
        ci = make_zone_cluster()
        job = JobInfo("default/j", min_available=1, queue="default",
                      pod_group_phase=PodGroupPhase.INQUEUE)
        t = task("t0", labels={"app": "x"})
        t.pod_affinity_preferred = [PodAffinityTerm(
            topology_key="zone", match_labels={"app": "x"}, weight=3)]
        job.add_task(t)
        ci.add_job(job)
        cfg = dataclasses.replace(CFG, use_pallas="interpret")
        _, node_of, _, _ = run_cycle(ci, cfg)
        assert node_of["t0"] is not None
        with pytest.raises(ValueError, match="host-port"):
            run_cycle(ci, dataclasses.replace(
                CFG, use_pallas=True, enable_host_ports=True))

    def test_affinity_arrays_neutral_has_no_terms(self):
        assert not AffinityArrays.neutral(8, 8).has_terms


class TestEquivalenceAtScale:
    @pytest.mark.parametrize("seed", [11, 13])
    def test_device_matches_cpu_reference_256_nodes(self, seed):
        """Randomized affinity+anti-affinity parity at 256 nodes with
        zone/rack topology (BASELINE.json config 5 shape)."""
        rng = np.random.default_rng(seed)
        zones = tuple(f"z{i}" for i in range(8))
        ci = make_zone_cluster(n_nodes=256, zones=zones, cpu="4")
        for i, n in enumerate(ci.nodes.values()):
            n.labels["rack"] = f"r{i % 32}"
        apps = [f"app{i}" for i in range(5)]
        for j in range(24):
            job = JobInfo(f"default/j{j}", min_available=1, queue="default",
                          pod_group_phase=PodGroupPhase.INQUEUE,
                          creation_timestamp=float(j))
            for i in range(int(rng.integers(1, 4))):
                app = apps[int(rng.integers(len(apps)))]
                t = task(f"j{j}-t{i}", labels={"app": app})
                r = rng.random()
                if r < 0.25:
                    t.pod_anti_affinity = [PodAffinityTerm(
                        topology_key="rack", match_labels={"app": app})]
                elif r < 0.5:
                    t.pod_affinity = [PodAffinityTerm(
                        topology_key="zone", match_labels={"app": app})]
                elif r < 0.75:
                    t.pod_affinity_preferred = [PodAffinityTerm(
                        topology_key="zone",
                        match_labels={"app": apps[0]},
                        weight=int(rng.integers(1, 20)))]
                job.add_task(t)
            ci.add_job(job)
        # some running pods seed the static counts
        nodes = list(ci.nodes)
        seedjob = JobInfo("default/seed", min_available=1, queue="default",
                          pod_group_phase=PodGroupPhase.INQUEUE)
        for i in range(12):
            t = task(f"s-{i}", labels={"app": apps[int(rng.integers(3))]},
                     status=TaskStatus.RUNNING)
            seedjob.add_task(t)
            ci.nodes[nodes[int(rng.integers(len(nodes)))]].add_task(t)
        ci.add_job(seedjob)
        res, _, maps, (snap, extras) = run_cycle(ci)
        cpu = allocate_cpu(snap, extras, CFG)
        np.testing.assert_array_equal(np.asarray(res.task_node),
                                      cpu["task_node"])
        np.testing.assert_array_equal(np.asarray(res.task_mode),
                                      cpu["task_mode"])
        assert int((np.asarray(res.task_mode) > 0).sum()) > 10


def _random_affinity_cluster(seed, n_nodes, n_jobs, zones=8, racks=32,
                             tasks_lo=1, tasks_hi=4, running_pods=12,
                             cpu="4"):
    """Randomized mixed required/preferred workload over a zone/rack
    topology with running pods seeding the static counts (the
    BASELINE.json config-5 shape, scalable to any node count)."""
    rng = np.random.default_rng(seed)
    ci = make_zone_cluster(n_nodes=n_nodes,
                           zones=tuple(f"z{i}" for i in range(zones)),
                           cpu=cpu)
    for i, n in enumerate(ci.nodes.values()):
        n.labels["rack"] = f"r{i % racks}"
    apps = [f"app{i}" for i in range(5)]
    for j in range(n_jobs):
        job = JobInfo(f"default/j{j}", min_available=1, queue="default",
                      pod_group_phase=PodGroupPhase.INQUEUE,
                      creation_timestamp=float(j))
        for i in range(int(rng.integers(tasks_lo, tasks_hi))):
            app = apps[int(rng.integers(len(apps)))]
            t = task(f"j{j}-t{i}", labels={"app": app})
            r = rng.random()
            if r < 0.25:
                t.pod_anti_affinity = [PodAffinityTerm(
                    topology_key="rack", match_labels={"app": app})]
            elif r < 0.5:
                t.pod_affinity = [PodAffinityTerm(
                    topology_key="zone", match_labels={"app": app})]
            elif r < 0.75:
                t.pod_affinity_preferred = [PodAffinityTerm(
                    topology_key="zone", match_labels={"app": apps[0]},
                    weight=int(rng.integers(1, 20)))]
            job.add_task(t)
        ci.add_job(job)
    nodes = list(ci.nodes)
    seedjob = JobInfo("default/seed", min_available=1, queue="default",
                      pod_group_phase=PodGroupPhase.INQUEUE)
    for i in range(running_pods):
        t = task(f"s-{i}", labels={"app": apps[int(rng.integers(3))]},
                 status=TaskStatus.RUNNING)
        seedjob.add_task(t)
        ci.nodes[nodes[int(rng.integers(len(nodes)))]].add_task(t)
    ci.add_job(seedjob)
    return ci


class TestPallasAffinityParity:
    """ops/pallas_place v3: the live inter-pod affinity counts are kernel
    state with per-section commit/discard. Both kernels must match the
    scan path and the CPU oracle bitwise."""

    @pytest.mark.parametrize("seed", [0, 2])
    def test_static_k_kernel_parity(self, seed):
        ci = _random_affinity_cluster(seed, n_nodes=16, n_jobs=6, zones=4,
                                      racks=5, running_pods=6)
        snap, maps = pack(ci)
        N = snap.nodes.idle.shape[0]
        T = snap.tasks.resreq.shape[0]
        extras = dataclasses.replace(
            AllocateExtras.neutral(snap),
            affinity=build_affinity(ci, maps, N, T))
        scan = jax.jit(make_allocate_cycle(
            dataclasses.replace(CFG, use_pallas=False)))(snap, extras)
        pls = jax.jit(make_allocate_cycle(dataclasses.replace(
            CFG, use_pallas="interpret", batch_jobs=4)))(snap, extras)
        for f in ("task_node", "task_mode", "job_ready", "job_pipelined"):
            np.testing.assert_array_equal(np.asarray(getattr(scan, f)),
                                          np.asarray(getattr(pls, f)), f)
        cpu = allocate_cpu(snap, extras, CFG)
        np.testing.assert_array_equal(np.asarray(scan.task_node),
                                      cpu["task_node"])

    def test_dyn_kernel_affinity_with_drf(self):
        """Affinity state + in-kernel fairness-key recompute together
        (the dynamic-key kernel with enable_pod_affinity)."""
        ci = _random_affinity_cluster(1, n_nodes=16, n_jobs=6, zones=4,
                                      racks=5, running_pods=6)
        snap, maps = pack(ci)
        N = snap.nodes.idle.shape[0]
        T = snap.tasks.resreq.shape[0]
        extras = dataclasses.replace(
            AllocateExtras.neutral(snap),
            affinity=build_affinity(ci, maps, N, T))
        cfg = dataclasses.replace(CFG, drf_job_order=True)
        scan = jax.jit(make_allocate_cycle(
            dataclasses.replace(cfg, use_pallas=False)))(snap, extras)
        dyn = jax.jit(make_allocate_cycle(dataclasses.replace(
            cfg, use_pallas="interpret", batch_jobs=4,
            batch_rounds=12)))(snap, extras)
        for f in ("task_node", "task_mode", "job_ready", "job_pipelined"):
            np.testing.assert_array_equal(np.asarray(getattr(scan, f)),
                                          np.asarray(getattr(dyn, f)), f)
        cpu = allocate_cpu(snap, extras, cfg)
        np.testing.assert_array_equal(np.asarray(scan.task_node),
                                      cpu["task_node"])


class TestEquivalenceAt1kNodes:
    """Oracle equality at >=1k randomized nodes/tasks (VERDICT r5 item 3
    raised the bar from <=256); the full-scale 10k record is fingerprint-
    guarded in bench.py (affinity_sha256 in BENCH_BASELINE.json)."""

    @pytest.mark.parametrize("seed", [17, 23])
    def test_device_matches_cpu_reference_1k_nodes(self, seed):
        ci = _random_affinity_cluster(seed, n_nodes=1024, n_jobs=96,
                                      zones=16, racks=128, tasks_hi=3,
                                      running_pods=48)
        res, _, maps, (snap, extras) = run_cycle(ci)
        cpu = allocate_cpu(snap, extras, CFG)
        np.testing.assert_array_equal(np.asarray(res.task_node),
                                      cpu["task_node"])
        np.testing.assert_array_equal(np.asarray(res.task_mode),
                                      cpu["task_mode"])
        assert int((np.asarray(res.task_mode) > 0).sum()) > 40
