"""NodePorts predicate + volume-binding seam tests.

Reference: the k8s NodePorts filter wrapped by the predicates plugin
(predicates.go:191) and the defaultVolumeBinder seam at allocate/bind
(cache.go:240-272, session.go:264-338)."""

import numpy as np

from volcano_tpu.api import TaskStatus
from volcano_tpu.api.cluster_info import PersistentVolumeClaim
from volcano_tpu.framework import parse_conf
from volcano_tpu.framework.session import Session
from volcano_tpu.runtime import FakeCluster, Scheduler

from fixtures import build_job, build_node, build_task, simple_cluster

CONF = """
actions: "enqueue, allocate, backfill"
tiers:
- plugins:
  - name: gang
  - name: predicates
  - name: nodeorder
"""


def run_cycle(ci):
    sched = Scheduler(FakeCluster(ci), conf=parse_conf(CONF))
    sched.run_once()
    return sched


class TestNodePorts:
    def test_static_conflict_with_resident_pod(self):
        """A pending task sharing a hostPort with a pod already on n0 must
        land on n1."""
        ci = simple_cluster(n_nodes=2)
        holder = build_job("default/holder", min_available=1)
        t = build_task("h-0", cpu="1", memory="1Gi",
                       status=TaskStatus.RUNNING, node_name="n0")
        t.host_ports = [8080]
        holder.add_task(t)
        ci.nodes["n0"].add_task(t)
        ci.add_job(holder)
        j = build_job("default/web", min_available=1)
        w = build_task("w-0", cpu="1", memory="1Gi")
        w.host_ports = [8080]
        j.add_task(w)
        ci.add_job(j)
        sched = run_cycle(ci)
        assert dict(sched.cluster.binds)["default/w-0"] == "n1"

    def test_in_cycle_conflict_spreads_tasks(self):
        """Two pending tasks with the same hostPort placed in ONE cycle end
        up on different nodes (the dynamic placement state)."""
        ci = simple_cluster(n_nodes=2)
        j = build_job("default/web", min_available=2)
        for i in range(2):
            t = build_task(f"w-{i}", cpu="1", memory="1Gi")
            t.host_ports = [9090]
            j.add_task(t)
        ci.add_job(j)
        sched = run_cycle(ci)
        binds = dict(sched.cluster.binds)
        assert len(binds) == 2
        assert binds["default/w-0"] != binds["default/w-1"]

    def test_port_saturation_blocks(self):
        """One node, two same-port tasks: only one places; the 2-gang
        discards (no node can take the second -> job breaks)."""
        ci = simple_cluster(n_nodes=1)
        j = build_job("default/web", min_available=2)
        for i in range(2):
            t = build_task(f"w-{i}", cpu="1", memory="1Gi")
            t.host_ports = [9090]
            j.add_task(t)
        ci.add_job(j)
        sched = run_cycle(ci)
        assert sched.cluster.binds == []

    def test_different_ports_share_node(self):
        ci = simple_cluster(n_nodes=1)
        j = build_job("default/web", min_available=2)
        for i in range(2):
            t = build_task(f"w-{i}", cpu="1", memory="1Gi")
            t.host_ports = [9090 + i]
            j.add_task(t)
        ci.add_job(j)
        sched = run_cycle(ci)
        assert len(sched.cluster.binds) == 2

    def test_cpu_oracle_parity_with_ports(self):
        from volcano_tpu.runtime.cpu_reference import allocate_cpu
        ci = simple_cluster(n_nodes=3)
        rng = np.random.RandomState(3)
        for jid in range(4):
            j = build_job(f"default/j{jid}", min_available=1)
            for i in range(2):
                t = build_task(f"j{jid}-t{i}", cpu="500m", memory="1Gi")
                if rng.rand() < 0.7:
                    t.host_ports = [int(rng.choice([80, 443, 9090]))]
                j.add_task(t)
            ci.add_job(j)
        ssn = Session(ci, parse_conf(CONF))
        cfg = ssn.allocate_config()
        assert cfg.enable_host_ports
        extras = ssn.allocate_extras()
        import jax
        from volcano_tpu.ops.allocate_scan import make_allocate_cycle
        result = jax.jit(make_allocate_cycle(cfg))(ssn.snap, extras)
        ref = allocate_cpu(ssn.snap, extras, cfg)
        np.testing.assert_array_equal(np.asarray(result.task_node),
                                      ref["task_node"])
        np.testing.assert_array_equal(np.asarray(result.task_mode),
                                      ref["task_mode"])


class TestVolumeBinding:
    def test_unbindable_pvc_blocks_placement(self):
        """FindPodVolumes failing everywhere -> the task never places
        (cache.go:255-262 GetPodVolumes error at allocate)."""
        ci = simple_cluster(n_nodes=2)
        ci.pvcs["data"] = PersistentVolumeClaim("data", bindable=False)
        j = build_job("default/db", min_available=1)
        t = build_task("db-0", cpu="1", memory="1Gi")
        t.pvcs = ["data"]
        j.add_task(t)
        ci.add_job(j)
        sched = run_cycle(ci)
        assert sched.cluster.binds == []

    def test_missing_pvc_blocks_placement(self):
        ci = simple_cluster(n_nodes=2)
        j = build_job("default/db", min_available=1)
        t = build_task("db-0", cpu="1", memory="1Gi")
        t.pvcs = ["ghost"]
        j.add_task(t)
        ci.add_job(j)
        sched = run_cycle(ci)
        assert sched.cluster.binds == []

    def test_local_pv_pins_to_node(self):
        """A claim with local-PV node affinity pins the task to that node
        even when another node scores better."""
        ci = simple_cluster(n_nodes=2)
        # n0 is busier, so nodeorder would prefer n1
        filler = build_job("default/filler", min_available=1)
        f = build_task("f-0", cpu="2", memory="2Gi",
                       status=TaskStatus.RUNNING, node_name="n0")
        filler.add_task(f)
        ci.nodes["n0"].add_task(f)
        ci.add_job(filler)
        ci.pvcs["local-data"] = PersistentVolumeClaim(
            "local-data", node_name="n0")
        j = build_job("default/db", min_available=1)
        t = build_task("db-0", cpu="1", memory="1Gi")
        t.pvcs = ["local-data"]
        j.add_task(t)
        ci.add_job(j)
        sched = run_cycle(ci)
        assert dict(sched.cluster.binds)["default/db-0"] == "n0"

    def test_bind_marks_claims_bound(self):
        ci = simple_cluster(n_nodes=1)
        ci.pvcs["data"] = PersistentVolumeClaim("data")
        j = build_job("default/db", min_available=1)
        t = build_task("db-0", cpu="1", memory="1Gi")
        t.pvcs = ["data"]
        j.add_task(t)
        ci.add_job(j)
        sched = run_cycle(ci)
        assert dict(sched.cluster.binds)["default/db-0"] == "n0"
        assert sched.cluster.ci.pvcs["data"].bound

    def test_claim_turning_unbindable_fails_bind_into_resync(self):
        """The scheduler decided a placement, but BindVolumes fails at
        dispatch -> the bind lands in the retry queue, and succeeds once
        the claim becomes bindable again."""
        ci = simple_cluster(n_nodes=1)
        ci.pvcs["data"] = PersistentVolumeClaim("data")
        j = build_job("default/db", min_available=1)
        t = build_task("db-0", cpu="1", memory="1Gi")
        t.pvcs = ["data"]
        j.add_task(t)
        ci.add_job(j)
        sched = Scheduler(FakeCluster(ci), conf=parse_conf(CONF))
        sched.cluster.volume_bind_failures.add("data")
        sched.run_once(now=100.0)
        assert sched.cluster.binds == []
        assert len(sched.resync) == 1
        sched.cluster.volume_bind_failures.clear()
        sched.run_once(now=101.0)
        assert dict(sched.cluster.binds)["default/db-0"] == "n0"


class TestNodeAffinityPreferred:
    """NodeAffinity preferredDuringScheduling scorer (nodeorder.go:255-266):
    matched term weights steer placement without filtering."""

    CONF = """
actions: "enqueue, allocate, backfill"
tiers:
- plugins:
  - name: gang
  - name: predicates
  - name: nodeorder
"""

    def test_preferred_term_steers_to_matching_node(self):
        ci = simple_cluster(n_nodes=0)
        ci.add_node(build_node("plain", cpu="4", memory="8Gi"))
        ci.add_node(build_node("ssd", cpu="4", memory="8Gi",
                               labels={"disk": "ssd"}))
        j = build_job("default/j", min_available=1)
        t = build_task("t-0", cpu="1", memory="1Gi")
        t.affinity_preferred = [({"disk": "ssd"}, 50.0)]
        j.add_task(t)
        ci.add_job(j)
        sched = Scheduler(FakeCluster(ci), conf=parse_conf(self.CONF))
        sched.run_once()
        assert dict(sched.cluster.binds)["default/t-0"] == "ssd"

    def test_unmatched_term_does_not_filter(self):
        """Preference only: with no matching node the task still places."""
        ci = simple_cluster(n_nodes=1)
        j = build_job("default/j", min_available=1)
        t = build_task("t-0", cpu="1", memory="1Gi")
        t.affinity_preferred = [({"disk": "nvme"}, 100.0)]
        j.add_task(t)
        ci.add_job(j)
        sched = Scheduler(FakeCluster(ci), conf=parse_conf(self.CONF))
        sched.run_once()
        assert dict(sched.cluster.binds)["default/t-0"] == "n0"

    def test_weights_accumulate_and_weight_arg_scales(self):
        """Two matched terms beat one heavier term; nodeaffinity.weight: 0
        disables the scorer."""
        ci = simple_cluster(n_nodes=0)
        ci.add_node(build_node("a", cpu="4", memory="8Gi",
                               labels={"disk": "ssd", "zone": "z1"}))
        ci.add_node(build_node("b", cpu="4", memory="8Gi",
                               labels={"gpu": "yes"}))
        j = build_job("default/j", min_available=1)
        t = build_task("t-0", cpu="1", memory="1Gi")
        t.affinity_preferred = [({"disk": "ssd"}, 30.0),
                                ({"zone": "z1"}, 30.0),
                                ({"gpu": "yes"}, 50.0)]
        j.add_task(t)
        ci.add_job(j)
        sched = Scheduler(FakeCluster(ci), conf=parse_conf(self.CONF))
        sched.run_once()
        assert dict(sched.cluster.binds)["default/t-0"] == "a"  # 60 > 50

    def test_oracle_parity_with_preferred_terms(self):
        import jax
        from volcano_tpu.ops.allocate_scan import make_allocate_cycle
        from volcano_tpu.runtime.cpu_reference import allocate_cpu
        ci = simple_cluster(n_nodes=4)
        for i, n in enumerate(ci.nodes.values()):
            n.labels["rack"] = f"r{i % 2}"
        rng = np.random.RandomState(5)
        for jid in range(4):
            j = build_job(f"default/j{jid}", min_available=1)
            for i in range(3):
                t = build_task(f"j{jid}-t{i}", cpu="500m", memory="512Mi")
                if rng.rand() < 0.7:
                    t.affinity_preferred = [
                        ({"rack": f"r{int(rng.randint(2))}"},
                         float(rng.randint(1, 80)))]
                j.add_task(t)
            ci.add_job(j)
        ssn = Session(ci, parse_conf(self.CONF))
        cfg = ssn.allocate_config()
        extras = ssn.allocate_extras()
        result = jax.jit(make_allocate_cycle(cfg))(ssn.snap, extras)
        ref = allocate_cpu(ssn.snap, extras, cfg)
        np.testing.assert_array_equal(np.asarray(result.task_node),
                                      ref["task_node"])
        np.testing.assert_array_equal(np.asarray(result.task_mode),
                                      ref["task_mode"])


class TestNodeAffinityRequiredOrTerms:
    """Multi-term required node affinity is OR-of-NodeSelectorTerms (k8s
    semantics): satisfying ANY term admits the node. The old encoding
    collapsed terms to their union (AND of everything)."""

    def test_or_terms_admit_either_zone(self):
        ci = simple_cluster(n_nodes=0)
        from fixtures import build_node
        ci.add_node(build_node("za", cpu="1", memory="2Gi",
                               labels={"zone": "a"}))
        ci.add_node(build_node("zb", cpu="4", memory="8Gi",
                               labels={"zone": "b"}))
        # za is nearly full; the task fits only on zb — reachable ONLY
        # under OR semantics (the union collapse required zone=a AND
        # zone=b, satisfiable nowhere)
        filler = build_job("default/filler", min_available=1)
        f = build_task("f-0", cpu="1", memory="1Gi",
                       status=TaskStatus.RUNNING, node_name="za")
        filler.add_task(f)
        ci.nodes["za"].add_task(f)
        ci.add_job(filler)
        j = build_job("default/j", min_available=1)
        t = build_task("t-0", cpu="1", memory="1Gi")
        t.affinity_required = [{"zone": "a"}, {"zone": "b"}]
        j.add_task(t)
        ci.add_job(j)
        sched = run_cycle(ci)
        assert dict(sched.cluster.binds)["default/t-0"] == "zb"

    def test_or_terms_still_filter(self):
        """A node matching NO term stays infeasible."""
        ci = simple_cluster(n_nodes=1)   # unlabeled n0
        j = build_job("default/j", min_available=1)
        t = build_task("t-0", cpu="1", memory="1Gi")
        t.affinity_required = [{"zone": "a"}, {"zone": "b"}]
        j.add_task(t)
        ci.add_job(j)
        sched = run_cycle(ci)
        assert sched.cluster.binds == []

    def test_single_term_unchanged(self):
        ci = simple_cluster(n_nodes=0)
        from fixtures import build_node
        ci.add_node(build_node("plain", cpu="4", memory="8Gi"))
        ci.add_node(build_node("ssd", cpu="4", memory="8Gi",
                               labels={"disk": "ssd"}))
        j = build_job("default/j", min_available=1)
        t = build_task("t-0", cpu="1", memory="1Gi")
        t.affinity_required = [{"disk": "ssd"}]
        j.add_task(t)
        ci.add_job(j)
        sched = run_cycle(ci)
        assert dict(sched.cluster.binds)["default/t-0"] == "ssd"

    def test_oracle_parity_with_or_terms(self):
        import jax
        from volcano_tpu.ops.allocate_scan import make_allocate_cycle
        from volcano_tpu.runtime.cpu_reference import allocate_cpu
        ci = simple_cluster(n_nodes=0)
        from fixtures import build_node
        rng = np.random.RandomState(9)
        for i in range(6):
            ci.add_node(build_node(f"n{i}", cpu="2", memory="4Gi",
                                   labels={"zone": f"z{i % 3}"}))
        for jid in range(4):
            j = build_job(f"default/j{jid}", min_available=1)
            for i in range(2):
                t = build_task(f"j{jid}-t{i}", cpu="500m", memory="512Mi")
                r = rng.rand()
                if r < 0.4:
                    t.affinity_required = [
                        {"zone": f"z{int(rng.randint(3))}"},
                        {"zone": f"z{int(rng.randint(3))}"}]
                elif r < 0.6:
                    t.affinity_required = [{"zone": f"z{int(rng.randint(3))}"}]
                j.add_task(t)
            ci.add_job(j)
        ssn = Session(ci, parse_conf(CONF))
        extras = ssn.allocate_extras()
        cfg = ssn.allocate_config()
        result = jax.jit(make_allocate_cycle(cfg))(ssn.snap, extras)
        ref = allocate_cpu(ssn.snap, extras, cfg)
        np.testing.assert_array_equal(np.asarray(result.task_node),
                                      ref["task_node"])
        np.testing.assert_array_equal(np.asarray(result.task_mode),
                                      ref["task_mode"])

    def test_backfill_respects_or_terms(self):
        """Best-effort tasks go through backfill, which must honor
        required OR-of-terms affinity too (backfill.go runs PredicateFn)."""
        ci = simple_cluster(n_nodes=0)
        from fixtures import build_node
        ci.add_node(build_node("plain", cpu="4", memory="8Gi"))
        ci.add_node(build_node("zb", cpu="4", memory="8Gi",
                               labels={"zone": "b"}))
        j = build_job("default/be", min_available=1)
        t = build_task("be-0", cpu=0, memory=0)
        t.affinity_required = [{"zone": "a"}, {"zone": "b"}]
        j.add_task(t)
        ci.add_job(j)
        sched = run_cycle(ci)
        assert dict(sched.cluster.binds).get("default/be-0") == "zb"

    def test_native_pack_parity_with_multi_term_affinity(self):
        """Python pack and the wire decoders must produce identical
        template structure for multi-term tasks (the OR mask is per TASK,
        so templates merge identically on both paths)."""
        import jax
        from volcano_tpu.arrays import pack as pack_py
        from volcano_tpu.native.wire import serialize
        from volcano_tpu.native.pywire import pack_wire_py
        ci = simple_cluster(n_nodes=2)
        j = build_job("default/j", min_available=1)
        t0 = build_task("t-0", cpu="1", memory="1Gi")
        t0.affinity_required = [{"zone": "a"}, {"zone": "b"}]
        j.add_task(t0)
        j.add_task(build_task("t-1", cpu="1", memory="1Gi"))
        ci.add_job(j)
        snap_p, _ = pack_py(ci)
        buf, _ = serialize(ci)
        snap_w = pack_wire_py(buf)
        for a, b in zip(jax.tree.leaves(snap_p), jax.tree.leaves(snap_w)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
