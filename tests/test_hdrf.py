"""Exact hierarchical-DRF solver tests.

Validates ops.fairshare.hdrf_tree_state / hdrf_level_keys against a direct
recursive transliteration of the fork's tree update and queue comparator
(pkg/scheduler/plugins/drf/drf.go:90-103 resourceSaturated, 693-767
updateHierarchicalShare, 182-218 compareQueues), plus the allocation-outcome
scenarios of drf/hdrf_test.go:48-196 (in test_actions-level suites once the
allocate path consumes the tree).
"""

import numpy as np
import jax.numpy as jnp
import pytest

from volcano_tpu.arrays.hierarchy import HierarchyArrays, build_hierarchy
from volcano_tpu.ops.fairshare import hdrf_level_keys, hdrf_tree_state

_EPS = 1e-9


# ---------------------------------------------------------------------------
# recursive Go-mirror (hierarchicalNode semantics, dict-tree structured)
# ---------------------------------------------------------------------------

def _share(alloc, total):
    frac = [alloc[r] / total[r] for r in range(len(total)) if total[r] > 0]
    return max(frac) if frac else 0.0


def go_hdrf(parent, depth, weight, valid, job_leaf, job_alloc, job_req,
            job_valid, total):
    """Returns (share[H], saturated[H]) per tree node the way drf.go does."""
    H = len(parent)
    children = {i: [] for i in range(H)}
    for i in range(H):
        if valid[i] and parent[i] >= 0:
            children[parent[i]].append(i)
    jobs_at = {i: [] for i in range(H)}
    J = len(job_leaf)
    total_alloc = np.zeros(len(total))
    for j in range(J):
        if job_valid[j] and job_leaf[j] >= 0:
            jobs_at[job_leaf[j]].append(j)
            total_alloc += job_alloc[j]
    demanding = total_alloc < np.asarray(total)

    def job_saturated(j):
        # resourceSaturated, drf.go:90-103
        for r in range(len(total)):
            a, q = job_alloc[j][r], job_req[j][r]
            if a > _EPS and q > _EPS and a >= q - 1e-9:
                return True
            if not demanding[r] and q > _EPS:
                return True
        return False

    share = np.zeros(H)
    sat = np.ones(H, bool)
    alloc = np.zeros((H, len(total)))

    def update(node):
        # children = subtree nodes + job leaves (updateHierarchicalShare)
        kids = []
        for c in children[node]:
            update(c)
            kids.append((share[c], sat[c], alloc[c]))
        for j in jobs_at[node]:
            kids.append((_share(job_alloc[j], total), job_saturated(j),
                         np.asarray(job_alloc[j], float)))
        mdr = 1.0
        for s, st, _a in kids:
            if s != 0 and not st and s < mdr:
                mdr = s
        total_a = np.zeros(len(total))
        all_sat = True
        for s, st, a in kids:
            if not st:
                all_sat = False
            if s != 0:
                total_a += a if st else a * (mdr / s)
        share[node] = _share(total_a, total)
        sat[node] = all_sat
        alloc[node] = total_a

    roots = [i for i in range(H) if valid[i] and parent[i] < 0]
    for r in roots:
        update(r)
    return share, sat


def go_compare(lpath, rpath, share, sat, weight):
    """compareQueues (drf.go:182-218) over node-index paths."""
    d = min(len(lpath), len(rpath))
    for i in range(d):
        ln, rn = lpath[i], rpath[i]
        if not sat[ln] and sat[rn]:
            return -1
        if sat[ln] and not sat[rn]:
            return 1
        ls, rs = share[ln] / weight[ln], share[rn] / weight[rn]
        if ls != rs:
            return -1 if ls < rs else 1
    return 0


def _rand_tree(rng, max_depth=3, max_queues=6, max_jobs=8, R=2):
    """Random HierarchyArrays + job arrays (numpy, unbucketed)."""
    n_q = rng.integers(1, max_queues + 1)
    parent, depth, weight = [-1], [0], [1.0]
    queue_paths = []
    for _ in range(n_q):
        d = rng.integers(0, max_depth + 1)
        path = [0]
        node = 0
        for lvl in range(1, d + 1):
            # either reuse an existing child of `node` or create one
            existing = [i for i in range(len(parent))
                        if parent[i] == node and depth[i] == lvl]
            if existing and rng.random() < 0.5:
                node = int(rng.choice(existing))
            else:
                parent.append(node)
                depth.append(lvl)
                weight.append(float(rng.integers(1, 5)))
                node = len(parent) - 1
            path.append(node)
        queue_paths.append(path)
    H = len(parent)
    D = max(len(p) for p in queue_paths)
    D = max(D, 2)
    qp = np.full((n_q, D), -1, np.int32)
    for qi, p in enumerate(queue_paths):
        qp[qi, :len(p)] = p
    n_j = rng.integers(1, max_jobs + 1)
    job_leaf = np.array([queue_paths[rng.integers(0, n_q)][-1]
                         for _ in range(n_j)], np.int32)
    total = rng.uniform(5, 20, R).astype(np.float32)
    job_alloc = (rng.uniform(0, 4, (n_j, R))
                 * (rng.random((n_j, R)) < 0.7)).astype(np.float32)
    job_req = np.maximum(job_alloc * rng.uniform(0.5, 2.0, (n_j, R)),
                         rng.uniform(0, 3, (n_j, R))).astype(np.float32)
    job_valid = rng.random(n_j) < 0.9
    hier = HierarchyArrays(
        parent=np.asarray(parent, np.int32), depth=np.asarray(depth, np.int32),
        weight=np.asarray(weight, np.float32), valid=np.ones(H, bool),
        queue_path=qp, job_leaf=job_leaf)
    return hier, queue_paths, job_alloc, job_req, job_valid, total


class TestTreeState:
    @pytest.mark.slow
    def test_fuzz_matches_go_recursion(self):
        rng = np.random.default_rng(7)
        for trial in range(60):
            hier, qpaths, ja, jr, jv, total = _rand_tree(rng)
            share, sat, _ = hdrf_tree_state(
                hier, jnp.asarray(ja), jnp.asarray(jr), jnp.asarray(jv),
                jnp.asarray(total))
            share, sat = np.asarray(share), np.asarray(sat)
            gshare, gsat = go_hdrf(
                np.asarray(hier.parent), np.asarray(hier.depth),
                np.asarray(hier.weight), np.asarray(hier.valid),
                np.asarray(hier.job_leaf), ja, jr, jv, total)
            assert np.allclose(share, gshare, atol=1e-4), trial
            assert (sat == gsat).all(), trial

    @pytest.mark.slow
    def test_fuzz_queue_order_matches_compare_queues(self):
        rng = np.random.default_rng(11)
        for trial in range(60):
            hier, qpaths, ja, jr, jv, total = _rand_tree(rng)
            keys = np.asarray(hdrf_level_keys(
                hier, jnp.asarray(ja), jnp.asarray(jr), jnp.asarray(jv),
                jnp.asarray(total)))
            gshare, gsat = go_hdrf(
                np.asarray(hier.parent), np.asarray(hier.depth),
                np.asarray(hier.weight), np.asarray(hier.valid),
                np.asarray(hier.job_leaf), ja, jr, jv, total)
            w = np.asarray(hier.weight)
            nq = len(qpaths)
            for a in range(nq):
                for b in range(nq):
                    g = go_compare(qpaths[a], qpaths[b], gshare, gsat, w)
                    if g == 0:
                        continue  # reference falls to heap order on ties
                    ka, kb = tuple(keys[a]), tuple(keys[b])
                    got = -1 if ka < kb else (1 if ka > kb else 0)
                    # the lexicographic keys may only disagree with the
                    # comparator when the walk ended at differing depths
                    # past a tied common prefix (documented -1 padding)
                    common = min(len(qpaths[a]), len(qpaths[b]))
                    tied_prefix = all(
                        go_compare(qpaths[a][:i + 1], qpaths[b][:i + 1],
                                   gshare, gsat, w) == 0
                        for i in range(common))
                    if not tied_prefix:
                        assert got == g, (trial, a, b)

    def test_rescaling_scenario_tree(self):
        """hdrf_test.go 'rescaling test' tree at its expected final
        allocation: pg1=5c+5G under root/sci, pg21=5c under root/eng/dev,
        pg22=5G under root/eng/prod; 10c/10G cluster. All leaves saturated
        (cluster fully allocated in both dims), every level share balanced."""
        # nodes: 0 root, 1 sci, 2 eng, 3 dev, 4 prod
        hier = HierarchyArrays(
            parent=np.asarray([-1, 0, 0, 2, 2], np.int32),
            depth=np.asarray([0, 1, 1, 2, 2], np.int32),
            weight=np.asarray([1, 50, 50, 50, 50], np.float32),
            valid=np.ones(5, bool),
            queue_path=np.asarray([[0, 1, -1], [0, 2, 3], [0, 2, 4]],
                                  np.int32),
            job_leaf=np.asarray([1, 3, 4], np.int32))
        total = np.asarray([10.0, 10.0], np.float32)
        ja = np.asarray([[5, 5], [5, 0], [0, 5]], np.float32)
        jr = np.asarray([[10, 10], [10, 0], [0, 10]], np.float32)
        share, sat, _ = hdrf_tree_state(
            hier, jnp.asarray(ja), jnp.asarray(jr),
            jnp.ones(3, bool), jnp.asarray(total))
        share, sat = np.asarray(share), np.asarray(sat)
        # nothing is demanding anymore -> every job and node saturated
        assert sat.all()
        # sci holds 5/10 on both dims; eng aggregates dev+prod to 5c+5G
        assert abs(share[1] - 0.5) < 1e-5
        assert abs(share[2] - 0.5) < 1e-5
        # balanced shares at every level -> queue order is a three-way tie
        keys = np.asarray(hdrf_level_keys(
            hier, jnp.asarray(ja), jnp.asarray(jr), jnp.ones(3, bool),
            jnp.asarray(total)))
        assert np.allclose(keys[1][:4], keys[2][:4])

    def test_unsaturated_beats_saturated(self):
        """A queue whose subtree still demands resources pops before one
        whose jobs are saturated (compareQueues, drf.go:200-206)."""
        hier = HierarchyArrays(
            parent=np.asarray([-1, 0, 0, -1], np.int32),
            depth=np.asarray([0, 1, 1, 0], np.int32),
            weight=np.asarray([1, 1, 1, 1], np.float32),
            valid=np.asarray([True, True, True, False]),
            queue_path=np.asarray([[0, 1], [0, 2]], np.int32),
            job_leaf=np.asarray([1, 2], np.int32))
        total = np.asarray([10.0], np.float32)
        # job0 fully met (sat), job1 still wants more (unsat)
        ja = np.asarray([[4.0], [2.0]], np.float32)
        jr = np.asarray([[4.0], [6.0]], np.float32)
        keys = np.asarray(hdrf_level_keys(
            hier, jnp.asarray(ja), jnp.asarray(jr), jnp.ones(2, bool),
            jnp.asarray(total)))
        assert tuple(keys[1]) < tuple(keys[0])


class TestBuildHierarchy:
    def test_materializes_intermediate_nodes(self):
        from volcano_tpu.api import QueueInfo
        from volcano_tpu.arrays import pack
        from fixtures import build_job, build_task, simple_cluster
        ci = simple_cluster(n_nodes=1)
        del ci.queues["default"]
        ci.add_queue(QueueInfo("root-sci", hierarchy="root/sci",
                               hierarchy_weights="100/50"))
        ci.add_queue(QueueInfo("root-eng-dev", hierarchy="root/eng/dev",
                               hierarchy_weights="100/50/50"))
        ci.add_queue(QueueInfo("root-eng-prod", hierarchy="root/eng/prod",
                               hierarchy_weights="100/50/50"))
        j = build_job("default/j1", queue="root-eng-dev")
        j.add_task(build_task("t0"))
        ci.add_job(j)
        snap, maps = pack(ci)
        Q = np.asarray(snap.queues.weight).shape[0]
        J = np.asarray(snap.jobs.valid).shape[0]
        h = build_hierarchy(ci, maps, Q, J)
        valid = np.asarray(h.valid)
        depth = np.asarray(h.depth)
        # root + sci + eng + dev + prod = 5 nodes, "eng" materialized even
        # though no queue is named root/eng
        assert int(valid.sum()) == 5
        assert sorted(depth[valid].tolist()) == [0, 1, 1, 2, 2]
        qp = np.asarray(h.queue_path)
        dev = maps.queue_index["root-eng-dev"]
        prod = maps.queue_index["root-eng-prod"]
        sci = maps.queue_index["root-sci"]
        # dev and prod share the depth-1 "eng" node; sci does not
        assert qp[dev, 1] == qp[prod, 1]
        assert qp[sci, 1] != qp[dev, 1]
        assert qp[sci, 2] == -1
        # the job attaches under dev's leaf
        ji = maps.job_index["default/j1"]
        assert int(np.asarray(h.job_leaf)[ji]) == qp[dev, 2]
        # weights floored at 1, first declarer wins
        assert np.asarray(h.weight)[qp[dev, 1]] == 50.0

    def test_no_annotation_attaches_under_root(self):
        from volcano_tpu.arrays import pack
        from fixtures import build_job, build_task, simple_cluster
        ci = simple_cluster(n_nodes=1)
        j = build_job("default/j1")
        j.add_task(build_task("t0"))
        ci.add_job(j)
        snap, maps = pack(ci)
        Q = np.asarray(snap.queues.weight).shape[0]
        J = np.asarray(snap.jobs.valid).shape[0]
        h = build_hierarchy(ci, maps, Q, J)
        assert int(np.asarray(h.valid).sum()) == 1
        ji = maps.job_index["default/j1"]
        assert int(np.asarray(h.job_leaf)[ji]) == 0


# ---------------------------------------------------------------------------
# allocation-outcome port of drf/hdrf_test.go:48-196 through the full cycle
# ---------------------------------------------------------------------------

def _hdrf_cluster(node_cpu, node_mem, queue_specs, pg_specs):
    """queue_specs: (name, hierarchy, weights); pg_specs: (pg, queue,
    n_tasks, cpu, mem_bytes)."""
    from volcano_tpu.api import QueueInfo
    from fixtures import build_job, build_node, build_task, simple_cluster
    ci = simple_cluster(n_nodes=0)
    ci.add_node(build_node("n", cpu=node_cpu, memory=node_mem))
    del ci.queues["default"]
    for name, hierarchy, weights in queue_specs:
        ci.add_queue(QueueInfo(name, weight=1, hierarchy=hierarchy,
                               hierarchy_weights=weights))
    for pg, queue, n_tasks, cpu, mem in pg_specs:
        # PodGroups in hdrf_test.go carry no MinMember -> always JobReady,
        # so each pop yields after one placement and queues interleave.
        # Memory quantities are powers of two (exact in f32) — hdrf_test.go
        # uses 1G=1e9 under float64; the outcome split is unit-independent
        job = build_job(f"default/{pg}", queue=queue, min_available=0)
        for i in range(n_tasks):
            job.add_task(build_task(f"{pg}-p{i}", cpu=cpu, memory=mem))
        ci.add_job(job)
    return ci


def _run_hdrf(ci, use_pallas=False):
    import jax
    from volcano_tpu.arrays import pack
    from volcano_tpu.ops.allocate_scan import (AllocateConfig,
                                               AllocateExtras,
                                               make_allocate_cycle)
    snap, maps = pack(ci)
    Q = np.asarray(snap.queues.weight).shape[0]
    J = np.asarray(snap.jobs.valid).shape[0]
    extras = AllocateExtras.neutral(snap)
    extras.hierarchy = build_hierarchy(ci, maps, Q, J)
    # the hdrf_test.go session: drf only (hierarchy+job order), no gang
    cfg = AllocateConfig(enable_gang=False, enable_hdrf=True,
                         drf_job_order=True,
                         use_pallas="interpret" if use_pallas else False)
    result = jax.jit(make_allocate_cycle(cfg))(snap, extras)
    return snap, maps, extras, cfg, result


def _job_placed(snap, maps, result):
    """job name -> summed resreq vector of its placed tasks."""
    node = np.asarray(result.task_node)
    tjob = np.asarray(snap.tasks.job)
    rr = np.asarray(snap.tasks.resreq)
    out = {}
    for uid, ji in maps.job_index.items():
        mask = (tjob == ji) & (node >= 0)
        out[uid.split("/")[-1]] = rr[mask].sum(axis=0) if mask.any() \
            else np.zeros(rr.shape[1])
    return out


class TestHDRFOutcomes:
    """The two outcome scenarios of drf/hdrf_test.go with their expected
    per-job allocations (hdrf_test.go:113-117, 188-194)."""

    def _rescaling_cluster(self):
        return _hdrf_cluster(
            "10", str(10 * 2 ** 30),
            [("root-sci", "root/sci", "100/50"),
             ("root-eng-dev", "root/eng/dev", "100/50/50"),
             ("root-eng-prod", "root/eng/prod", "100/50/50")],
            [("pg1", "root-sci", 10, "1", 2 ** 30),
             ("pg21", "root-eng-dev", 10, "1", 0),
             ("pg22", "root-eng-prod", 10, "0", 2 ** 30)])

    # tier-1 keeps the semantic outcome assertion; the scan/pallas and
    # CPU-oracle parity replays of the same cluster run in the full
    # suite (`pytest -m slow`) — tier-1 budget calibration
    def test_rescaling(self):
        snap, maps, extras, cfg, result = _run_hdrf(self._rescaling_cluster())
        got = _job_placed(snap, maps, result)
        cpu, mem = 0, 1
        assert got["pg1"][cpu] == 5000 and got["pg1"][mem] == 5 * 2 ** 30, got
        assert got["pg21"][cpu] == 5000 and got["pg21"][mem] == 0, got
        assert got["pg22"][cpu] == 0 and got["pg22"][mem] == 5 * 2 ** 30, got

    @pytest.mark.slow
    def test_rescaling_pallas_parity(self):
        ci = self._rescaling_cluster()
        _, _, _, _, scan = _run_hdrf(ci)
        _, _, _, _, pls = _run_hdrf(ci, use_pallas=True)
        np.testing.assert_array_equal(np.asarray(scan.task_node),
                                      np.asarray(pls.task_node))
        np.testing.assert_array_equal(np.asarray(scan.task_mode),
                                      np.asarray(pls.task_mode))

    @pytest.mark.slow
    def test_rescaling_cpu_oracle_parity(self):
        from volcano_tpu.runtime.cpu_reference import allocate_cpu
        snap, maps, extras, cfg, result = _run_hdrf(self._rescaling_cluster())
        ref = allocate_cpu(snap, extras, cfg)
        np.testing.assert_array_equal(np.asarray(result.task_node),
                                      ref["task_node"])
        np.testing.assert_array_equal(np.asarray(result.task_mode),
                                      ref["task_mode"])

    @pytest.mark.slow
    def test_blocking_nodes(self):
        ci = _hdrf_cluster(
            "30", str(30 * 2 ** 30),
            [("root-pg1", "root/pg1", "100/25"),
             ("root-pg2", "root/pg2", "100/25"),
             ("root-pg3-pg31", "root/pg3/pg31", "100/25/50"),
             ("root-pg3-pg32", "root/pg3/pg32", "100/25/50"),
             ("root-pg4", "root/pg4", "100/25")],
            [("pg1", "root-pg1", 30, "1", 0),
             ("pg2", "root-pg2", 30, "1", 0),
             ("pg31", "root-pg3-pg31", 30, "1", 0),
             ("pg32", "root-pg3-pg32", 30, "0", 2 ** 30),
             ("pg4", "root-pg4", 30, "0", 2 ** 30)])
        snap, maps, extras, cfg, result = _run_hdrf(ci)
        got = _job_placed(snap, maps, result)
        cpu, mem = 0, 1
        assert got["pg1"][cpu] == 10000, got
        assert got["pg2"][cpu] == 10000, got
        assert got["pg31"][cpu] == 10000, got
        assert got["pg32"][mem] == 15 * 2 ** 30, got
        assert got["pg4"][mem] == 15 * 2 ** 30, got
