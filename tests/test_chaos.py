"""Fault injection + recovery (ISSUE 5 acceptance).

- FaultPlan determinism: same seed -> same schedule sha, same fired-fault
  log, same post-recovery decision sha across two runs (scan AND
  pallas-interpret cycle paths).
- Recoverable-fault sha matrix: under every recoverable fault kind a
  multi-cycle Scheduler run completes and its decision sha equals the
  no-fault run's — including pipelined mode and the sidecar
  socket-drop-and-reconnect path.
- A planted resident-state corruption provably trips the integrity
  digest, triggers the full re-fuse recovery, and the recovery is visible
  in last_telemetry, METRICS and the flight-recorder ring.
- ResyncQueue dead-letters attempts-exhausted intents instead of
  dropping them silently; the leader elector survives a stolen lease.
"""

import hashlib
import struct

import numpy as np
import pytest

from volcano_tpu.chaos import (FAULT_KINDS, ChaosError, FaultInjector,
                               FaultPlan, chaos)
from volcano_tpu.framework import parse_conf
from volcano_tpu.metrics import METRICS
from volcano_tpu.runtime.fake_cluster import FakeCluster
from volcano_tpu.runtime.scheduler import ResyncQueue, Scheduler

from fixtures import build_job, build_task, simple_cluster
from test_delta_pipeline import PARITY_CONF, decisions_sha, digest
from test_runtime_incremental import build_cluster, churn


def run_chaos_sched(plan=None, cycles=6, pipeline=True, conf=PARITY_CONF,
                    deadline_ms=None, slow_s=0.3, cluster_mutator=None):
    """Drive a Scheduler over the incremental-suite cluster + churn with
    an optional fault plan; per-cycle drain so every cycle's record is
    digested. Returns (sha, scheduler, injector)."""
    cluster = FakeCluster(build_cluster(n_nodes=8, n_jobs=10))
    sched = Scheduler(cluster, conf=conf, pipeline=pipeline)
    if deadline_ms is not None:
        sched.cycle_deadline_s = deadline_ms / 1000.0
    injector = FaultInjector(plan, slow_s=slow_s) if plan else None
    digests = []
    import contextlib
    ctx = chaos(injector) if injector else contextlib.nullcontext()
    with ctx:
        for c in range(cycles):
            out = sched.run_once(now=1000.0 + c)
            rec = (sched.drain(now=1000.0 + c) or out) if pipeline else out
            digests.append(digest(rec))
            if cluster_mutator is not None:
                cluster_mutator(cluster, c)
            churn(cluster, c, arrivals=True)
    return decisions_sha(digests), sched, injector


class TestFaultPlan:
    def test_same_seed_same_schedule(self):
        a = FaultPlan(seed=42, cycles=8)
        b = FaultPlan(seed=42, cycles=8)
        assert a.schedule_sha() == b.schedule_sha()
        assert a.faults == b.faults

    def test_different_seed_different_schedule(self):
        shas = {FaultPlan(seed=s, cycles=8).schedule_sha()
                for s in range(5)}
        assert len(shas) > 1

    def test_unknown_kind_rejected(self):
        with pytest.raises(ValueError, match="unknown fault kinds"):
            FaultPlan(seed=0, kinds=("meteor_strike",))

    def test_all_kinds_covered(self):
        plan = FaultPlan(seed=3, cycles=10)
        assert {f.kind for f in plan.faults} == set(FAULT_KINDS)


class TestRecoverableShaMatrix:
    """Acceptance: under an injected fault of each recoverable kind, a
    multi-cycle run completes and its final decision sha256 matches the
    no-fault run (pipelined mode — the hardest: the fault hits an
    in-flight cycle)."""

    @pytest.fixture(scope="class")
    def clean_sha(self):
        sha, sched, _ = run_chaos_sched(None)
        assert sched.degradation_level == 0
        return sha

    @pytest.mark.parametrize("kind", ["backend_loss", "resident_corrupt",
                                      "mirror_drift", "bind_fail"])
    def test_kind_is_decision_neutral(self, kind, clean_sha):
        plan = FaultPlan(seed=11, cycles=6, kinds=(kind,))
        sha, sched, inj = run_chaos_sched(plan)
        assert [f[1] for f in inj.fired] == [kind], inj.fired
        assert sha == clean_sha

    def test_slow_dispatch_trips_watchdog_decision_neutral(self, clean_sha):
        plan = FaultPlan(seed=11, cycles=6, kinds=("slow_dispatch",))
        sha, sched, inj = run_chaos_sched(plan, deadline_ms=120,
                                          slow_s=0.35)
        assert ("slow_dispatch" in [f[1] for f in inj.fired])
        assert sha == clean_sha
        # the watchdog retired the slow cycle and degraded to sync
        assert any(f["stage"].startswith("deadline")
                   for e in sched.flight.snapshots()
                   for f in (e.get("faults") or []))

    def test_sync_loop_recovers_too(self, clean_sha):
        sync_clean, _, _ = run_chaos_sched(None, pipeline=False)
        plan = FaultPlan(seed=5, cycles=6, kinds=("backend_loss",
                                                  "resident_corrupt"))
        sha, _, inj = run_chaos_sched(plan, pipeline=False)
        assert len(inj.fired) == 2
        assert sha == sync_clean == clean_sha


class TestChaosDeterminism:
    def test_two_runs_same_seed_identical(self):
        """Same FaultPlan seed -> same fault schedule, same fired log,
        same post-recovery decision sha (scan path)."""
        plan_kinds = ("backend_loss", "resident_corrupt", "mirror_drift")
        out = []
        for _ in range(2):
            plan = FaultPlan(seed=23, cycles=5, kinds=plan_kinds)
            sha, _, inj = run_chaos_sched(plan, cycles=5)
            out.append((plan.schedule_sha(), tuple(inj.fired), sha))
        assert out[0] == out[1]

    def test_pallas_interpret_path_deterministic(self):
        """The same determinism contract on the pallas-interpret cycle
        path, driven at the DeltaKernel level (the seam the faults
        actually hook)."""
        from volcano_tpu.arrays import pack
        from volcano_tpu.ops import AllocateConfig, make_allocate_cycle
        from volcano_tpu.ops.allocate_scan import AllocateExtras
        from volcano_tpu.ops.fused_io import DeltaKernel, ResidentState

        ci = simple_cluster(n_nodes=4, node_cpu="8", node_mem="16Gi")
        for j in range(4):
            job = build_job(f"default/j{j}", min_available=2)
            for t in range(2):
                job.add_task(build_task(f"j{j}-t{t}", cpu="2",
                                        memory="2Gi"))
            ci.add_job(job)
        snap, _maps = pack(ci)
        extras = AllocateExtras.neutral(snap)
        cfg = AllocateConfig(binpack_weight=1.0, use_pallas="interpret",
                             enable_gpu=False)
        kern = DeltaKernel(make_allocate_cycle(cfg), (snap, extras))

        def one_run():
            state = ResidentState()
            plan = FaultPlan(seed=9, cycles=4,
                             kinds=("resident_corrupt",))
            inj = FaultInjector(plan)
            decs = []
            prio = np.asarray(snap.tasks.priority)
            with chaos(inj):
                for c in range(4):
                    inj.begin_cycle(c)
                    packed = np.asarray(kern.run(state, (snap, extras)))
                    dec, dev_dig = kern.split_digest(packed)
                    if not np.array_equal(dev_dig,
                                          kern.mirror_digest(state)):
                        dec, _ = kern.split_digest(np.asarray(
                            kern.recover(state, (snap, extras))))
                    decs.append(dec.tobytes())
                    prio[c % prio.size] += 1    # steady churn
            prio[:4] -= 1                        # restore shared snapshot
            return (hashlib.sha256(b"".join(decs)).hexdigest(),
                    tuple(inj.fired))

        a, b = one_run(), one_run()
        assert a == b
        assert any(k == "resident_corrupt" for _, k, _p in a[1])


class TestIntegrityDigest:
    def test_planted_mirror_corruption_trips_digest_and_recovers(self):
        """Acceptance: a planted corruption provably trips the in-graph
        digest, triggers full re-fuse, keeps decisions identical, and the
        recovery is visible in last_telemetry + METRICS."""
        from volcano_tpu.framework.session import Session
        ci = build_cluster(n_nodes=6, n_jobs=6)
        ssn = Session(ci.clone(), PARITY_CONF)
        ref = ssn.run_allocate()
        ref_binds = sorted((b.task_uid, b.node_name) for b in ssn.binds)

        ssn2 = Session(ci.clone(), PARITY_CONF)
        before = METRICS.counter_value("resident_digest_mismatch_total")
        pending = ssn2.dispatch_allocate()
        assert pending.state is not None and pending.state.mirror is not None
        # drain the async dispatch BEFORE planting the drift: on the CPU
        # backend device_put can zero-copy alias the mirror's memory, so
        # a flip landing while the compute is still queued corrupts the
        # INPUT — both digests then see the flipped value and agree
        import jax
        jax.block_until_ready(pending.state.device)
        # the mirror drifts from device truth after dispatch (a bit-level
        # flip: value-level nudges can vanish in f32 precision)
        pending.state.mirror[0].view(np.uint32)[3] ^= np.uint32(0x5A5A5A5A)
        result = ssn2.complete_allocate(pending)
        assert METRICS.counter_value(
            "resident_digest_mismatch_total") == before + 1
        integ = ssn2.last_telemetry["integrity"]
        assert integ["reason"] == "digest" and integ["mode"] == "refuse"
        assert ssn2.stats["recovery_ms"] >= 0
        assert sorted((b.task_uid, b.node_name)
                      for b in ssn2.binds) == ref_binds
        np.testing.assert_array_equal(np.asarray(result.task_node),
                                      np.asarray(ref.task_node))

    def test_recovery_visible_in_flight_recorder(self):
        plan = FaultPlan(seed=11, cycles=6, kinds=("mirror_drift",))
        _sha, sched, inj = run_chaos_sched(plan)
        assert inj.fired
        flights = sched.flight.snapshots()
        rec = [e for e in flights
               if any(f["stage"].startswith("integrity")
                      for f in (e.get("faults") or []))]
        assert rec, [e.get("faults") for e in flights]
        assert "recovery_ms" in rec[0]["stats"]
        assert rec[0]["telemetry"]["integrity"]["reason"] == "digest"
        assert rec[0]["degradation"] >= 1


class TestDegradationLadder:
    def test_dispatch_fault_degrades_to_sync_then_recovers(self):
        """A recovered dispatch fault suspends pipelining for the cooldown
        window, then the ladder climbs back to 0."""
        plan = FaultPlan(seed=11, cycles=3, kinds=("backend_loss",))
        _sha, sched, inj = run_chaos_sched(plan, cycles=10)
        assert len(inj.fired) == 1
        degr = [e.get("degradation", 0) for e in sched.flight.snapshots()]
        assert max(degr) >= 1                   # the ladder engaged...
        assert degr[-1] == 0                    # ...and de-escalated
        assert sched.degradation_level == 0

    def test_oracle_rung_decisions_match(self):
        """Force the cpu-oracle rung: with BOTH faults scheduled at cycle
        1 (cycles=2 pins randrange(1, 2) == 1), the pipelined dispatch
        fails AND the sync retry fails, leaving only the pure-host CPU
        oracle — whose decisions must still match the clean run."""
        clean_sha, _, _ = run_chaos_sched(None, cycles=4)
        plan = FaultPlan(seed=2, cycles=2, kinds=("backend_loss",),
                         per_kind=2)
        assert [f.cycle for f in plan.faults] == [1, 1]
        sha, sched, inj = run_chaos_sched(plan, cycles=4)
        assert sha == clean_sha
        assert len(inj.fired) == 2
        assert METRICS.counter_value(
            "cycle_recoveries_total",
            labels={"reason": "dispatch", "mode": "cpu_oracle"}) >= 1
        # the oracle is rung 3 since the elastic-mesh rung landed at 2
        assert 3 in [e.get("degradation", 0)
                     for e in sched.flight.snapshots()]


class TestResyncDeadLetter:
    class _AlwaysFails:
        def bind(self, intent):
            return False

        def evict(self, intent):
            return False

        def resync_task(self, uid):
            self.resynced = uid

    def test_exhausted_attempts_dead_letter_not_dropped(self):
        from volcano_tpu.framework.session import BindIntent
        q = ResyncQueue(base_delay=0.001, max_delay=0.001, max_attempts=3)
        cluster = self._AlwaysFails()
        q.add(BindIntent("default/t0", "default/j0", "n0"), "bind", now=0.0)
        now, dead = 0.0, 0
        for _ in range(6):
            now += 1.0
            stats = q.process(cluster, now)
            dead += stats["dead_lettered"]
        assert dead == 1
        assert len(q) == 0
        letters = q.dead_letter()
        assert len(letters) == 1
        assert letters[0]["intent"].task_uid == "default/t0"
        assert letters[0]["attempts"] == 3
        assert letters[0]["gave_up_at"] == now or letters[0]["gave_up_at"] > 0
        assert cluster.resynced == "default/t0"

    def test_scheduler_surfaces_dead_letter(self):
        cluster = FakeCluster(build_cluster(n_nodes=4, n_jobs=4))
        sched = Scheduler(cluster, conf=PARITY_CONF, pipeline=False)
        sched.resync = ResyncQueue(base_delay=0.001, max_delay=0.001,
                                   max_attempts=2)
        # every bind of one task permanently rejected by the API
        ssn = sched.run_once(now=1000.0)
        assert ssn.binds
        uid = ssn.binds[0].task_uid
        # re-decide with a permanent failure injected
        cluster2 = FakeCluster(build_cluster(n_nodes=4, n_jobs=4))
        cluster2.bind_failures[uid] = "permanent"
        sched2 = Scheduler(cluster2, conf=PARITY_CONF, pipeline=False)
        sched2.resync = ResyncQueue(base_delay=0.001, max_delay=0.001,
                                    max_attempts=2)
        before = METRICS.counter_value("resync_dead_letter_total")
        for c in range(4):
            sched2.run_once(now=2000.0 + c)
        assert len(sched2.resync.dead_letter()) >= 1
        assert METRICS.counter_value("resync_dead_letter_total") > before
        assert sched2.flight.snapshots()[-1]["resync_dead_letter"] >= 1


from volcano_tpu import native


@pytest.mark.skipif(not native.available(),
                    reason=f"native packer unavailable: "
                           f"{native.build_error()}")
class TestSidecarFaults:
    """Acceptance: the sidecar socket-drop-and-reconnect path keeps the
    one-deep pipelined stream bit-identical to the sync responses shifted
    by one — a dropped response is replayed from the server's idempotency
    cache, a partial frame is resent whole, and neither double-applies a
    cycle."""

    def _cluster(self, k: int):
        from volcano_tpu.api import TaskStatus
        ci = simple_cluster(n_nodes=3)
        for j in range(3):
            job = build_job(f"default/j{j}", min_available=2)
            for t in range(2):
                job.add_task(build_task(f"j{j}-t{t}", cpu="1",
                                        memory="1Gi"))
            ci.add_job(job)
        names = sorted(ci.nodes)
        bound = 0
        for job in ci.jobs.values():
            for task in job.tasks.values():
                if bound >= k:
                    break
                job.update_task_status(task, TaskStatus.RUNNING)
                task.node_name = names[bound % len(names)]
                ci.nodes[task.node_name].add_task(task)
                bound += 1
        return ci

    def _fast_backoff(self):
        from volcano_tpu.runtime.backoff import Backoff
        return Backoff(base=0.01, cap=0.05, attempts=5, jitter=0.0, seed=0)

    # partial_frame exercises the same reconnect-and-resend machinery from
    # the send side; socket_drop (the replay-cache path) stays tier-1, the
    # send-side variant rides the slow tail for the budget
    @pytest.mark.parametrize(
        "kind", ["socket_drop",
                 pytest.param("partial_frame", marks=pytest.mark.slow)])
    def test_drop_and_reconnect_stream_intact(self, kind):
        from volcano_tpu.runtime.sidecar import SidecarClient, SidecarServer
        server = SidecarServer()
        server.serve_in_thread()
        try:
            cis = [self._cluster(k) for k in range(4)]
            sync_client = SidecarClient(*server.address)
            sync_outs = [sync_client.schedule(ci) for ci in cis]
            sync_client.close()

            reconnects0 = METRICS.counter_value("sidecar_reconnects_total")
            pipe = SidecarClient(*server.address,
                                 backoff=self._fast_backoff(),
                                 call_timeout=10.0)
            plan = FaultPlan(seed=1, cycles=6, kinds=(kind,))
            with chaos(FaultInjector(plan)) as inj:
                assert pipe.schedule_pipelined(cis[0]) is None   # prime
                pipe_outs = [pipe.schedule_pipelined(ci)
                             for ci in cis[1:]]
                pipe_outs.append(pipe.drain_pipelined())
                assert inj.fired and inj.fired[0][1] == kind
            for k, (s, p) in enumerate(zip(sync_outs, pipe_outs)):
                np.testing.assert_array_equal(s["task_node"],
                                              p["task_node"], f"round {k}")
                assert s["binds"] == p["binds"], f"round {k}"
            assert METRICS.counter_value(
                "sidecar_reconnects_total") > reconnects0
            pipe.close()
        finally:
            server.shutdown()

    def test_seq_replay_serves_cache_without_redispatch(self):
        from volcano_tpu.native.wire import serialize
        from volcano_tpu.ops.allocate_scan import AllocateConfig
        from volcano_tpu.runtime.sidecar import SchedulerSidecar
        sidecar = SchedulerSidecar(AllocateConfig(binpack_weight=1.0))
        buf, _maps = serialize(self._cluster(0))
        st1, p1 = sidecar.schedule_buffer_seq(5, 1, buf)      # prime
        assert st1 == 0
        replays0 = METRICS.counter_value("sidecar_replayed_rounds_total")
        st1b, p1b = sidecar.schedule_buffer_seq(5, 1, buf)    # replay
        assert (st1b, p1b) == (st1, p1)
        assert METRICS.counter_value(
            "sidecar_replayed_rounds_total") == replays0 + 1
        assert sidecar._pending is not None       # pipeline untouched
        st2, p2 = sidecar.schedule_buffer_seq(5, 2, buf)
        assert st2 == 0 and len(p2) > len(p1)     # real decisions now
        # a NEW epoch retires the stale stream's pending cycle first
        st3, p3 = sidecar.schedule_buffer_seq(6, 1, buf)
        assert st3 == 0
        import struct as _struct
        T, J = _struct.unpack("<II", p3[4:12])
        assert (T, J) == (0, 0)                   # clean re-prime

    def test_structured_error_frames(self):
        import socket
        from volcano_tpu.runtime.sidecar import (ERR_BAD_REQUEST,
                                                 ERR_EMPTY_PIPELINE,
                                                 ERROR_MAGIC, SidecarClient,
                                                 SidecarError, SidecarServer)
        server = SidecarServer()
        server.serve_in_thread()
        try:
            # bad magic -> structured FATAL code, connection dropped
            sock = socket.create_connection(server.address, timeout=30)
            sock.sendall(struct.pack("<II", 0xDEAD, 0) + b"")
            status, n = struct.unpack("<II", sock.recv(8))
            payload = sock.recv(n)
            assert status == 1
            magic, code = struct.unpack("<II", payload[:8])
            assert magic == ERROR_MAGIC and code == ERR_BAD_REQUEST
            sock.close()
            # empty-pipeline drain -> benign retryable code
            client = SidecarClient(*server.address)
            with pytest.raises(SidecarError) as ei:
                client._roundtrip(struct.pack("<I", 0x44524356))  # VCRD
            assert ei.value.code == ERR_EMPTY_PIPELINE
            assert ei.value.retryable
            client.close()
        finally:
            server.shutdown()

    def test_connect_retries_through_backoff(self):
        from volcano_tpu.runtime.backoff import Backoff
        calls = []

        def flaky():
            calls.append(1)
            if len(calls) < 3:
                raise OSError("refused")
            return "ok"

        bo = Backoff(base=0.0, cap=0.0, attempts=5, jitter=0.0)
        assert bo.call(flaky) == "ok"
        assert len(calls) == 3
        calls.clear()
        with pytest.raises(OSError):
            Backoff(base=0.0, cap=0.0, attempts=2, jitter=0.0).call(flaky)
        assert len(calls) == 2

    def test_per_call_timeout_distinct_from_connect(self):
        from volcano_tpu.runtime.sidecar import SidecarClient, SidecarServer
        server = SidecarServer()
        server.serve_in_thread()
        try:
            client = SidecarClient(*server.address, timeout=60.0,
                                   call_timeout=1.5)
            assert client.sock.gettimeout() == 1.5
            assert client.connect_timeout == 60.0
            out = client.schedule(self._cluster(0))
            assert out["binds"]
            client.close()
        finally:
            server.shutdown()


class TestLeaderChaos:
    def test_lease_expiry_steps_down_then_reacquires(self):
        from volcano_tpu.runtime.leader import LeaderElector
        from volcano_tpu.runtime.system import VolcanoSystem

        class Clock:
            now = 100.0

            def __call__(self):
                return self.now

        api = VolcanoSystem().api
        clock = Clock()
        el = LeaderElector(api, identity="s0", clock=clock)
        assert el.tick() and el.is_leader
        plan = FaultPlan(seed=1, cycles=2, kinds=("lease_expiry",))
        inj = FaultInjector(plan)
        with chaos(inj):
            inj.begin_cycle(1)
            clock.now += 1
            assert not el.tick() and not el.is_leader   # rival stole it
            assert inj.fired and inj.fired[0][1] == "lease_expiry"
            # the rival never renews: after its lease expires we win back
            clock.now += el.lease_duration + 1
            assert el.tick() and el.is_leader
        lease = api.get("leases", "volcano-system/vc-scheduler")
        assert lease.holder == "s0"
