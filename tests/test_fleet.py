"""Multi-tenant fleet runtime (ISSUE 12 acceptance).

- Transparency: B tenants served through one batched vmapped dispatch per
  shape bucket make bit-identical decisions to B independent single-tenant
  Schedulers over multi-cycle runs with churn — on the scan path and
  against pallas-interpret solo references — and the jit trace counters
  prove ONE compiled program per (bucket, width), never one per tenant.
- Chaos isolation: a fault plan targeting one tenant (resident corruption,
  dispatch failure) leaves every tenant's decision digests bit-identical
  to the clean run, and only the targeted tenant walks its ladder.
- Checkpoint isolation: one corrupt per-tenant envelope cold-starts only
  its owner; the fleet restores everyone else warm and keeps serving.
- Sidecar tenancy: VCRT-prefixed streams interleave pipelined rounds from
  two tenants on one server without cross-talk, and the per-tenant epoch
  LRU evicts (counted) instead of growing without bound.
- Graphcheck family ``fleet`` is clean on the repo and provably fires on
  a planted cross-tenant leak.
"""

import contextlib
import os
import struct

import numpy as np
import pytest

from volcano_tpu.chaos import FaultInjector, FaultPlan, chaos
from volcano_tpu.chaos.probe import (_PROBE_CONF, _churn, _cycle_digest,
                                     _small_cluster)
from volcano_tpu.fleet import FleetScheduler
from volcano_tpu.framework import parse_conf
from volcano_tpu.metrics import METRICS
from volcano_tpu.runtime.fake_cluster import FakeCluster
from volcano_tpu.runtime.scheduler import Scheduler
from volcano_tpu.telemetry import tracecount

SPECS = {
    "tenant-a": dict(n_nodes=5, n_jobs=6, tasks_per_job=2, weight=2.0),
    "tenant-b": dict(n_nodes=5, n_jobs=6, tasks_per_job=2, weight=1.0),
    # the pow2 padding of the node/job/task axes collapses small size
    # differences into one bucket; this shape pads distinctly from
    # (5, 6, 2) — the same two-bucket split the module smoke proves
    "tenant-c": dict(n_nodes=6, n_jobs=8, tasks_per_job=3, weight=1.0),
}


def _bases(specs=SPECS):
    return {n: _small_cluster(**{k: v for k, v in s.items()
                                 if k != "weight"})
            for n, s in specs.items()}


def run_fleet(bases, cycles=4, conf_text=_PROBE_CONF, injector=None,
              specs=SPECS):
    """Drive a FleetScheduler over cloned bases with per-cycle churn;
    returns ({tenant: [digest, ...]}, fleet)."""
    fleet = FleetScheduler(conf=parse_conf(conf_text))
    clusters = {n: FakeCluster(bases[n].clone()) for n in specs}
    for n, s in specs.items():
        fleet.admit(n, clusters[n], conf=parse_conf(conf_text),
                    weight=s["weight"])
    digests = {n: [] for n in specs}
    ctx = chaos(injector) if injector is not None \
        else contextlib.nullcontext()
    with ctx:
        for c in range(cycles):
            served = fleet.run_once(now=1000.0 + c)
            for n, ssn in served.items():
                digests[n].append(_cycle_digest(ssn))
            for n in fleet.tenants:
                _churn(clusters[n], c)
    return digests, fleet


def run_solo(bases, cycles=4, conf_text=_PROBE_CONF, specs=SPECS):
    """N independent single-tenant reference runs over the same bases."""
    out = {}
    for n in specs:
        cluster = FakeCluster(bases[n].clone())
        sched = Scheduler(cluster, conf=parse_conf(conf_text))
        ds = []
        for c in range(cycles):
            ssn = sched.run_once(now=1000.0 + c)
            ds.append(_cycle_digest(ssn))
            _churn(cluster, c)
        out[n] = ds
    return out


# The equivalence matrix, the targeted-fault isolation runs, and the
# fleet restore legs are multi-run probes (a fleet run PLUS N solo
# reference runs each): they sit in the slow tail — tier-1 budget
# recalibration, the PR 1/3/5/8/9/10/11 pattern — while the tier1.sh
# fleet smoke (`python -m volcano_tpu.fleet --smoke`) gates the
# decision-sha matrix + one-trace-per-bucket proof every tier-1 run.
@pytest.mark.slow
class TestFleetEquivalence:
    def test_batched_equals_solo_scan_one_trace_per_bucket(self):
        before = {e: v["traces"] for e, v in tracecount.counts().items()}
        bases = _bases()
        fleet_d, fleet = run_fleet(bases)
        solo_d = run_solo(bases)
        for n in SPECS:
            assert fleet_d[n] == solo_d[n], n
        assert len(fleet.pool.buckets) == 2
        # compile discipline: one program per (bucket, width), each inside
        # the flat kernel's trace budget — never one trace per tenant
        traced = {e: v["traces"] - before.get(e, 0)
                  for e, v in tracecount.counts().items()
                  if e.startswith("fleet_cycle/")
                  and v["traces"] > before.get(e, 0)}
        assert len(traced) == len(fleet.pool.buckets), traced
        assert all(v <= 3 for v in traced.values()), traced

    def test_batched_equals_pallas_interpret_solo(self):
        """The fleet's batched entry (scan by construction — vmap does not
        compose with pallas_call) must match solo references running the
        pallas-interpret cycle: decisions are backend-identical."""
        specs = {n: SPECS[n] for n in ("tenant-a", "tenant-c")}
        bases = _bases(specs)
        fleet_d, _ = run_fleet(bases, cycles=3, specs=specs)
        solo_d = run_solo(bases, cycles=3, specs=specs,
                          conf_text=_PROBE_CONF + 'use_pallas: "interpret"\n')
        for n in specs:
            assert fleet_d[n] == solo_d[n], n

    def test_smoke_with_admission_and_eviction(self):
        """The module smoke (what tier1.sh runs): mid-run admission, a
        mid-run eviction, two shape buckets, sha matrix + trace proof."""
        from volcano_tpu.fleet.__main__ import run_fleet_smoke
        tracecount.reset()      # the smoke asserts absolute trace counts
        report = run_fleet_smoke(cycles=4)
        assert report["decisions_ok"], report["matrix"]
        assert report["trace_ok"], report["fleet_entries"]
        assert report["buckets"] == 2


@pytest.mark.slow
class TestFleetChaosIsolation:
    @pytest.mark.parametrize("conf_text", [
        _PROBE_CONF,
        pytest.param(_PROBE_CONF + 'use_pallas: "interpret"\n',
                     id="pallas-interpret"),
    ])
    def test_targeted_faults_leave_other_tenants_bit_identical(
            self, conf_text):
        bases = _bases()
        clean_d, _ = run_fleet(bases, conf_text=conf_text)
        plan = FaultPlan(seed=9, cycles=4,
                         kinds=("resident_corrupt", "backend_loss"))
        injector = FaultInjector(plan, target_tenant="tenant-a")
        mism0 = METRICS.counter_value("resident_digest_mismatch_total")
        chaos_d, fleet = run_fleet(bases, conf_text=conf_text,
                                   injector=injector)
        assert injector.fired, "fault plan never fired (vacuous test)"
        kinds_fired = {f[1] for f in injector.fired}
        # every tenant bit-identical to clean: the untargeted tenants by
        # isolation, the targeted one by decision-neutral recovery
        for n in SPECS:
            assert chaos_d[n] == clean_d[n], (n, injector.fired)
        # only the targeted tenant saw any of it
        for n in ("tenant-b", "tenant-c"):
            flight = fleet.tenants[n].flight.snapshots()
            assert all((e.get("degradation") or 0) == 0 for e in flight), n
            assert all(e.get("faults") is None for e in flight), n
        if "resident_corrupt" in kinds_fired:
            assert METRICS.counter_value(
                "resident_digest_mismatch_total") > mism0
        if "backend_loss" in kinds_fired:
            a_flight = fleet.tenants["tenant-a"].flight.snapshots()
            assert any((e.get("degradation") or 0) > 0 for e in a_flight)


@pytest.mark.slow
class TestFleetCheckpoint:
    def test_corrupt_tenant_envelope_never_stalls_fleet(self, tmp_path):
        from volcano_tpu.runtime.checkpoint import tenant_checkpoint_path
        bases = _bases()
        _, fleet = run_fleet(bases, cycles=2)
        fleet.checkpoint(str(tmp_path))
        victim = tenant_checkpoint_path(str(tmp_path), "tenant-a")
        assert os.path.exists(victim)
        blob = bytearray(open(victim, "rb").read())
        blob[len(blob) // 2] ^= 0xFF
        open(victim, "wb").write(bytes(blob))

        fleet2 = FleetScheduler(conf=parse_conf(_PROBE_CONF))
        clusters = {n: FakeCluster(bases[n].clone()) for n in SPECS}
        for n, s in SPECS.items():
            fleet2.admit(n, clusters[n], conf=parse_conf(_PROBE_CONF),
                         weight=s["weight"])
        outcomes = fleet2.restore(str(tmp_path))
        assert outcomes["tenant-a"] == "fallback"
        assert outcomes["tenant-b"] == "restored"
        assert outcomes["tenant-c"] == "restored"
        assert fleet2.tenants["tenant-b"].cycles == 2
        # the fleet keeps serving: every tenant, including the cold one
        served = fleet2.run_once(now=2000.0)
        assert set(served) == set(SPECS)

    def test_restore_missing_directory_is_cold_everywhere(self, tmp_path):
        bases = _bases()
        fleet = FleetScheduler(conf=parse_conf(_PROBE_CONF))
        for n, s in SPECS.items():
            fleet.admit(n, FakeCluster(bases[n].clone()),
                        conf=parse_conf(_PROBE_CONF), weight=s["weight"])
        outcomes = fleet.restore(str(tmp_path / "never-written"))
        assert set(outcomes.values()) == {"cold"}
        assert set(fleet.run_once(now=2000.0)) == set(SPECS)


class TestSidecarTenancy:
    @pytest.fixture(autouse=True)
    def _native(self):
        from volcano_tpu import native
        if not native.available():
            pytest.skip(f"native packer unavailable: "
                        f"{native.build_error()}")

    def _cluster(self, n_jobs=3):
        from fixtures import build_job, build_task, simple_cluster
        ci = simple_cluster(n_nodes=3)
        for j in range(n_jobs):
            job = build_job(f"default/j{j}", min_available=2)
            for t in range(2):
                job.add_task(build_task(f"j{j}-t{t}", cpu="1",
                                        memory="1Gi"))
            ci.add_job(job)
        return ci

    def test_interleaved_tenant_streams_no_cross_talk(self):
        """Two VCRT tenants pipelining through ONE server: each stream's
        responses match its own sync reference shifted by one, with the
        rounds fully interleaved (every dispatch retires the other
        tenant's in-flight cycle into its staged slot)."""
        from volcano_tpu.runtime.sidecar import SidecarClient, SidecarServer
        server = SidecarServer()
        server.serve_in_thread()
        try:
            cis_a = [self._cluster(n_jobs=2 + k) for k in range(3)]
            cis_b = [self._cluster(n_jobs=3) for _ in range(3)]
            sync = SidecarClient(*server.address)
            want_a = [sync.schedule(ci) for ci in cis_a]
            want_b = [sync.schedule(ci) for ci in cis_b]
            sync.close()

            ca = SidecarClient(*server.address, tenant_id="tenant-a")
            cb = SidecarClient(*server.address, tenant_id="tenant-b")
            assert ca.tenant_id != cb.tenant_id
            assert ca.schedule_pipelined(cis_a[0]) is None   # prime a
            assert cb.schedule_pipelined(cis_b[0]) is None   # prime b
            got_a, got_b = [], []
            for k in range(1, 3):
                got_a.append(ca.schedule_pipelined(cis_a[k]))
                got_b.append(cb.schedule_pipelined(cis_b[k]))
            got_a.append(ca.drain_pipelined())
            got_b.append(cb.drain_pipelined())
            for k in range(3):
                np.testing.assert_array_equal(
                    want_a[k]["task_node"], got_a[k]["task_node"],
                    f"tenant-a round {k}")
                assert want_a[k]["binds"] == got_a[k]["binds"]
                np.testing.assert_array_equal(
                    want_b[k]["task_node"], got_b[k]["task_node"],
                    f"tenant-b round {k}")
                assert want_b[k]["binds"] == got_b[k]["binds"]
            ca.close()
            cb.close()
        finally:
            server.shutdown()

    def test_epoch_lru_evicts_and_counts(self, monkeypatch):
        """A tenant's known-epoch set is a bounded LRU: pushing more
        client epochs than the cap evicts the oldest (counted on
        ``sidecar_replay_evictions_total``) and a replay under the
        evicted epoch re-primes via ERR_EPOCH_RESTORED instead of
        silently double-dispatching."""
        monkeypatch.setenv("VOLCANO_SIDECAR_EPOCH_CAP", "2")
        from volcano_tpu.runtime.sidecar import (SidecarClient,
                                                 SidecarServer,
                                                 tenant_wire_id)
        server = SidecarServer()
        server.serve_in_thread()
        ev0 = METRICS.counter_value("sidecar_replay_evictions_total")
        try:
            ci = self._cluster()
            clients = [SidecarClient(*server.address, tenant_id="tenant-a",
                                     epoch=100 + k) for k in range(3)]
            for c in clients:
                assert c.schedule_pipelined(ci) is None   # prime: seq 1
            # cap 2, three epochs seen -> epoch 100 evicted, counted
            assert METRICS.counter_value(
                "sidecar_replay_evictions_total") == ev0 + 1
            st = server.sidecar._stream(tenant_wire_id("tenant-a"))
            assert list(st.known_epochs) == [101, 102]
            # the evicted client's next round (seq 2, unknown epoch) gets
            # ERR_EPOCH_RESTORED and transparently re-primes under a new
            # epoch — schedule_pipelined returns None for that round
            assert clients[0].schedule_pipelined(ci) is None
            assert len(st.known_epochs) == 2
            for c in clients:
                c.close()
        finally:
            server.shutdown()


# Slow tail: tier1.sh's standalone `graphcheck.sh --fast` gate already
# compiles and audits the fleet family every run; these add the planted
# cross-tenant-leak proof on top.
@pytest.mark.slow
class TestGraphcheckFleet:
    def test_family_registered_and_clean(self):
        from volcano_tpu.analysis import FAMILIES, run_graphcheck
        assert "fleet" in FAMILIES
        report = run_graphcheck(families=["fleet"], fast=True)
        assert report["clean"], report["findings"]

    def test_planted_cross_tenant_leak_fires(self, monkeypatch):
        from volcano_tpu.analysis.fleet import check_fleet
        from volcano_tpu.fleet import pool
        monkeypatch.setattr(pool, "_LEAK_FOR_TESTS", True)
        findings = check_fleet(fast=True)
        assert any("cross-tenant-flow" in f.key for f in findings), \
            [f.key for f in findings]


class TestFleetWire:
    def test_tenant_wire_id_stable_nonzero(self):
        from volcano_tpu.runtime.sidecar import TENANT_MAGIC, tenant_wire_id
        assert struct.pack("<I", TENANT_MAGIC) == b"VCRT"
        a, b = tenant_wire_id("tenant-a"), tenant_wire_id("tenant-b")
        assert a == tenant_wire_id("tenant-a")      # deterministic
        assert a != b
        assert a != 0 and b != 0                    # 0 = legacy stream
