"""Multi-chip sharded allocate: decision identity vs the single-device run.

Exercises parallel.make_sharded_allocate on the 8-device virtual CPU mesh
(conftest) and asserts BITWISE equality of the decision arrays against the
unsharded cycle — the sharding analog of the reference's parallel
PredicateNodes/PrioritizeNodes producing the same result as a serial scan
(util/scheduler_helper.go:74-195).
"""

import dataclasses

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from volcano_tpu.arrays import pack
from volcano_tpu.ops.allocate_scan import (AllocateConfig, AllocateExtras,
                                           make_allocate_cycle)
from volcano_tpu.parallel import make_sharded_allocate, scheduler_mesh

from fixtures import build_job, build_node, build_task, simple_cluster


def _random_cluster(seed, n_nodes=128, n_jobs=24):
    rng = np.random.RandomState(seed)
    ci = simple_cluster(n_nodes=0)
    from volcano_tpu.api import QueueInfo
    ci.add_queue(QueueInfo("batch", weight=2))
    for i in range(n_nodes):
        ci.add_node(build_node(f"n{i:04d}", cpu=str(2 + int(rng.randint(6))),
                               memory="16Gi"))
    for j in range(n_jobs):
        n_tasks = 1 + int(rng.randint(6))
        job = build_job(f"default/j{j:03d}",
                        queue="default" if j % 2 == 0 else "batch",
                        min_available=max(1, n_tasks - int(rng.randint(2))),
                        priority=int(rng.randint(3)))
        for t in range(n_tasks):
            job.add_task(build_task(
                f"j{j:03d}-t{t}", cpu=f"{int(rng.randint(1, 5)) * 500}m",
                memory="1Gi", priority=int(rng.randint(2))))
        ci.add_job(job)
    return ci


@pytest.fixture(scope="module")
def mesh():
    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device virtual mesh")
    return scheduler_mesh(8)


@pytest.mark.slow
class TestShardedDecisionIdentity:
    @pytest.mark.parametrize("seed", [0, 1, 2])
    def test_sharded_equals_unsharded(self, mesh, seed):
        ci = _random_cluster(seed)
        snap, _maps = pack(ci)
        extras = AllocateExtras.neutral(snap)
        cfg = AllocateConfig(binpack_weight=1.0, use_pallas=False)
        sharded_fn = make_sharded_allocate(cfg, mesh, snap)
        with mesh:
            sharded = sharded_fn(snap, extras)
            sharded.task_node.block_until_ready()
        single = jax.jit(make_allocate_cycle(cfg))(
            jax.tree.map(jnp.asarray, snap), extras)
        np.testing.assert_array_equal(np.asarray(sharded.task_node),
                                      np.asarray(single.task_node))
        np.testing.assert_array_equal(np.asarray(sharded.task_mode),
                                      np.asarray(single.task_mode))
        np.testing.assert_array_equal(np.asarray(sharded.job_ready),
                                      np.asarray(single.job_ready))
        np.testing.assert_array_equal(np.asarray(sharded.job_pipelined),
                                      np.asarray(single.job_pipelined))
        assert int(np.asarray(sharded.task_mode > 0).sum()) > 0

    def test_sharded_with_dynamic_fairness_keys(self, mesh):
        """The in-kernel drf/proportion dynamic keys shard identically
        (segment sums over replicated job state + sharded node axis)."""
        from volcano_tpu.ops.fairshare import proportion_deserved
        ci = _random_cluster(7)
        snap, _maps = pack(ci)
        extras = AllocateExtras.neutral(snap)
        extras.queue_deserved = np.asarray(proportion_deserved(
            jax.tree.map(jnp.asarray, snap.queues),
            jnp.asarray(snap.cluster_capacity)))
        cfg = AllocateConfig(binpack_weight=1.0, use_pallas=False,
                             drf_job_order=True, drf_ns_order=True)
        sharded_fn = make_sharded_allocate(cfg, mesh, snap)
        with mesh:
            sharded = sharded_fn(snap, extras)
            sharded.task_node.block_until_ready()
        single = jax.jit(make_allocate_cycle(cfg))(
            jax.tree.map(jnp.asarray, snap), extras)
        np.testing.assert_array_equal(np.asarray(sharded.task_node),
                                      np.asarray(single.task_node))
        np.testing.assert_array_equal(np.asarray(sharded.task_mode),
                                      np.asarray(single.task_mode))

    def test_node_shards_actually_split(self, mesh):
        """The node-axis tensors really are distributed (one shard per
        device), not silently replicated."""
        ci = _random_cluster(3)
        snap, _maps = pack(ci)
        from volcano_tpu.parallel.sharding import node_sharding_specs
        snap_shardings, _rep = node_sharding_specs(mesh, snap)
        arr = jax.device_put(jnp.asarray(snap.nodes.idle),
                             snap_shardings.nodes.idle)
        assert len(arr.addressable_shards) == 8
        N = arr.shape[0]
        assert all(s.data.shape[0] == N // 8
                   for s in arr.addressable_shards)

    def test_sharded_delta_kernel_8dev_multicycle_identity(self, mesh):
        """ISSUE 7: the full ShardedDeltaKernel loop on the widest mesh —
        cold full upload, cross-shard delta cycles, and recovery — stays
        bit-identical to the unsharded DeltaKernel at every step, with
        zero resharding copies recorded by the live probe."""
        from volcano_tpu.ops.allocate_scan import derive_batching
        from volcano_tpu.ops.fused_io import (DeltaKernel, ResidentState,
                                              ShardedDeltaKernel)
        from volcano_tpu.parallel import node_leaf_mask
        ci = _random_cluster(11)
        snap, _maps = pack(ci)
        extras = AllocateExtras.neutral(snap)
        tree = (snap, extras)
        cfg = dataclasses.replace(
            derive_batching(AllocateConfig(binpack_weight=1.0,
                                           enable_gpu=False),
                            has_proportion=False), use_pallas=False)
        cycle = make_allocate_cycle(cfg)
        kernel = ShardedDeltaKernel(cycle, tree, mesh, node_leaf_mask(tree),
                                    entry="fused_cycle_sharded_8dev")
        oracle = DeltaKernel(cycle, tree)
        state, ostate = ResidentState(), ResidentState()
        idle = np.asarray(snap.nodes.idle)
        rows_per = kernel.rows_per
        for c in range(4):
            packed = np.asarray(kernel.run(state, tree))
            ref = np.asarray(oracle.run(ostate, tree))
            dec, tail = kernel.split_digest(packed)
            ref_dec, _ = oracle.split_digest(ref)
            np.testing.assert_array_equal(dec, ref_dec, err_msg=f"cycle {c}")
            np.testing.assert_array_equal(kernel.mirror_digest(state), tail)
            # touch a different shard each cycle (and one far shard, so
            # the routing crosses shard boundaries every time)
            idle[(c * rows_per) % idle.shape[0]] *= 0.5
            idle[((c + 5) * rows_per + 1) % idle.shape[0]] *= 0.75
        assert state.last_kind == "delta"
        assert state.resharding_copies == 0
        # recovery on the wide mesh, decision-neutral
        rec, _ = kernel.split_digest(
            np.asarray(kernel.recover(state, tree)))
        ref_dec, _ = oracle.split_digest(
            np.asarray(DeltaKernel(cycle, tree).run(ResidentState(), tree)))
        np.testing.assert_array_equal(rec, ref_dec)


@pytest.mark.slow
class TestShardedPreemptIdentity:
    """VERDICT r4 #6: sharded preempt/reclaim decision identity."""

    def _preempt_cluster(self, seed=0):
        import sys
        sys.path.insert(0, __file__.rsplit("/", 1)[0])
        from test_preempt_oracle import random_cluster
        rng = np.random.RandomState(seed)
        return random_cluster(rng, n_nodes=64, n_low=30, n_high=8)

    @pytest.mark.parametrize("seed", [0, 1])
    def test_sharded_preempt_equals_unsharded(self, mesh, seed):
        from volcano_tpu.ops.preempt import PreemptConfig, make_preempt_cycle
        from volcano_tpu.parallel import make_sharded_preempt
        ci = self._preempt_cluster(seed)
        snap, _maps = pack(ci)
        extras = AllocateExtras.neutral(snap)
        pcfg = PreemptConfig(scoring=AllocateConfig(
            binpack_weight=1.0, use_pallas=False, enable_gpu=False))
        T = np.asarray(snap.tasks.status).shape[0]
        veto = np.zeros(T, bool)
        skipm = np.zeros(T, bool)
        single = jax.jit(make_preempt_cycle(pcfg))(snap, extras, veto, skipm)
        fn = make_sharded_preempt(pcfg, mesh, snap)
        with mesh:
            sharded = fn(snap, extras, veto, skipm)
            jax.block_until_ready(sharded)
        for field in ("task_node", "task_mode", "evicted", "job_pipelined"):
            np.testing.assert_array_equal(
                np.asarray(getattr(sharded, field)),
                np.asarray(getattr(single, field)), err_msg=field)
        if seed == 0:
            assert np.asarray(sharded.evicted).any()


@pytest.mark.slow
class TestShardedHDRFAndAffinity:
    def test_sharded_hdrf_ordering_identity(self, mesh):
        """hdrf dynamic queue keys (level-wise tree solve each round) over
        the sharded node axis must reproduce the unsharded decisions."""
        from test_hdrf import _hdrf_cluster
        from volcano_tpu.framework.compiled_session import (
            allocate_config_from_conf, make_conf_cycle)
        from volcano_tpu.framework.conf import parse_conf
        from volcano_tpu.arrays.hierarchy import build_hierarchy
        import dataclasses as _dc
        ci = _hdrf_cluster(
            "10", str(10 * 2 ** 30),
            [("root-sci", "root/sci", "100/50"),
             ("root-eng-dev", "root/eng/dev", "100/50/50"),
             ("root-eng-prod", "root/eng/prod", "100/50/50")],
            [("pg1", "root-sci", 10, "1", 2 ** 30),
             ("pg21", "root-eng-dev", 10, "1", 0),
             ("pg22", "root-eng-prod", 10, "0", 2 ** 30)])
        conf = parse_conf("""
actions: "allocate"
tiers:
- plugins:
  - name: drf
    enableHierarchy: true
""")
        snap, maps = pack(ci)
        Q = np.asarray(snap.queues.weight).shape[0]
        J = np.asarray(snap.jobs.valid).shape[0]
        hier = build_hierarchy(ci, maps, Q, J)
        cycle = make_conf_cycle(conf, hierarchy=hier)
        cfg = allocate_config_from_conf(conf)
        assert cfg.enable_hdrf
        single = jax.jit(cycle)(snap)
        from volcano_tpu.parallel import node_sharding_specs
        snap_sh, rep = node_sharding_specs(mesh, snap)
        fn = jax.jit(cycle, in_shardings=(snap_sh,), out_shardings=rep)
        with mesh:
            sharded = fn(snap)
            jax.block_until_ready(sharded)
        np.testing.assert_array_equal(np.asarray(sharded.task_node),
                                      np.asarray(single.task_node))
        np.testing.assert_array_equal(np.asarray(sharded.task_mode),
                                      np.asarray(single.task_mode))

    def test_sharded_affinity_extras_identity(self, mesh):
        """matchExpressions OR-group masks + preferred score rows ride
        replicated extras against the sharded node axis."""
        from volcano_tpu.api import NodeSelectorTerm
        ci = _random_cluster(5, n_nodes=64, n_jobs=12)
        names = sorted(ci.nodes)
        for i, n in enumerate(names):
            ci.nodes[n].labels["zone"] = ["a", "b", "c"][i % 3]
            ci.nodes[n].labels["cores"] = str(2 ** (i % 5))
        term = NodeSelectorTerm(match_expressions=[
            ("cores", "Gt", ("4",))])
        pref = NodeSelectorTerm(match_expressions=[("zone", "In", ("c",))])
        jobs = list(ci.jobs.values())
        for job in jobs[:4]:
            for t in job.tasks.values():
                t.affinity_required = [term]
        for job in jobs[4:8]:
            for t in job.tasks.values():
                t.affinity_preferred = [(pref, 5.0)]
        from volcano_tpu.framework.host_extras import (
            apply_affinity_sections, node_affinity_sections)
        snap, maps = pack(ci)
        extras = AllocateExtras.neutral(snap)
        sec = node_affinity_sections(ci, maps.node_names, maps.task_index,
                                     1.0, True)
        apply_affinity_sections(extras, sec, snap, len(maps.node_names))
        assert (np.asarray(extras.task_or_group) >= 0).any()
        cfg = AllocateConfig(binpack_weight=1.0, use_pallas=False)
        single = jax.jit(make_allocate_cycle(cfg))(snap, extras)
        fn = make_sharded_allocate(cfg, mesh, snap)
        with mesh:
            sharded = fn(snap, extras)
            jax.block_until_ready(sharded)
        np.testing.assert_array_equal(np.asarray(sharded.task_node),
                                      np.asarray(single.task_node))
        np.testing.assert_array_equal(np.asarray(sharded.task_mode),
                                      np.asarray(single.task_mode))
