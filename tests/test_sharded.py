"""Multi-chip sharded allocate: decision identity vs the single-device run.

Exercises parallel.make_sharded_allocate on the 8-device virtual CPU mesh
(conftest) and asserts BITWISE equality of the decision arrays against the
unsharded cycle — the sharding analog of the reference's parallel
PredicateNodes/PrioritizeNodes producing the same result as a serial scan
(util/scheduler_helper.go:74-195).
"""

import dataclasses

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from volcano_tpu.arrays import pack
from volcano_tpu.ops.allocate_scan import (AllocateConfig, AllocateExtras,
                                           make_allocate_cycle)
from volcano_tpu.parallel import make_sharded_allocate, scheduler_mesh

from fixtures import build_job, build_node, build_task, simple_cluster


def _random_cluster(seed, n_nodes=128, n_jobs=24):
    rng = np.random.RandomState(seed)
    ci = simple_cluster(n_nodes=0)
    from volcano_tpu.api import QueueInfo
    ci.add_queue(QueueInfo("batch", weight=2))
    for i in range(n_nodes):
        ci.add_node(build_node(f"n{i:04d}", cpu=str(2 + int(rng.randint(6))),
                               memory="16Gi"))
    for j in range(n_jobs):
        n_tasks = 1 + int(rng.randint(6))
        job = build_job(f"default/j{j:03d}",
                        queue="default" if j % 2 == 0 else "batch",
                        min_available=max(1, n_tasks - int(rng.randint(2))),
                        priority=int(rng.randint(3)))
        for t in range(n_tasks):
            job.add_task(build_task(
                f"j{j:03d}-t{t}", cpu=f"{int(rng.randint(1, 5)) * 500}m",
                memory="1Gi", priority=int(rng.randint(2))))
        ci.add_job(job)
    return ci


@pytest.fixture(scope="module")
def mesh():
    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device virtual mesh")
    return scheduler_mesh(8)


class TestShardedDecisionIdentity:
    @pytest.mark.parametrize("seed", [0, 1, 2])
    def test_sharded_equals_unsharded(self, mesh, seed):
        ci = _random_cluster(seed)
        snap, _maps = pack(ci)
        extras = AllocateExtras.neutral(snap)
        cfg = AllocateConfig(binpack_weight=1.0, use_pallas=False)
        sharded_fn = make_sharded_allocate(cfg, mesh, snap)
        with mesh:
            sharded = sharded_fn(snap, extras)
            sharded.task_node.block_until_ready()
        single = jax.jit(make_allocate_cycle(cfg))(
            jax.tree.map(jnp.asarray, snap), extras)
        np.testing.assert_array_equal(np.asarray(sharded.task_node),
                                      np.asarray(single.task_node))
        np.testing.assert_array_equal(np.asarray(sharded.task_mode),
                                      np.asarray(single.task_mode))
        np.testing.assert_array_equal(np.asarray(sharded.job_ready),
                                      np.asarray(single.job_ready))
        np.testing.assert_array_equal(np.asarray(sharded.job_pipelined),
                                      np.asarray(single.job_pipelined))
        assert int(np.asarray(sharded.task_mode > 0).sum()) > 0

    def test_sharded_with_dynamic_fairness_keys(self, mesh):
        """The in-kernel drf/proportion dynamic keys shard identically
        (segment sums over replicated job state + sharded node axis)."""
        from volcano_tpu.ops.fairshare import proportion_deserved
        ci = _random_cluster(7)
        snap, _maps = pack(ci)
        extras = AllocateExtras.neutral(snap)
        extras.queue_deserved = np.asarray(proportion_deserved(
            jax.tree.map(jnp.asarray, snap.queues),
            jnp.asarray(snap.cluster_capacity)))
        cfg = AllocateConfig(binpack_weight=1.0, use_pallas=False,
                             drf_job_order=True, drf_ns_order=True)
        sharded_fn = make_sharded_allocate(cfg, mesh, snap)
        with mesh:
            sharded = sharded_fn(snap, extras)
            sharded.task_node.block_until_ready()
        single = jax.jit(make_allocate_cycle(cfg))(
            jax.tree.map(jnp.asarray, snap), extras)
        np.testing.assert_array_equal(np.asarray(sharded.task_node),
                                      np.asarray(single.task_node))
        np.testing.assert_array_equal(np.asarray(sharded.task_mode),
                                      np.asarray(single.task_mode))

    def test_node_shards_actually_split(self, mesh):
        """The node-axis tensors really are distributed (one shard per
        device), not silently replicated."""
        ci = _random_cluster(3)
        snap, _maps = pack(ci)
        from volcano_tpu.parallel.sharding import node_sharding_specs
        snap_shardings, _rep = node_sharding_specs(mesh, snap)
        arr = jax.device_put(jnp.asarray(snap.nodes.idle),
                             snap_shardings.nodes.idle)
        assert len(arr.addressable_shards) == 8
        N = arr.shape[0]
        assert all(s.data.shape[0] == N // 8
                   for s in arr.addressable_shards)
