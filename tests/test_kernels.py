"""Kernel unit tests: packing, predicates, scoring, selection.

Mirrors the assertion style of the reference's predicate/binpack tests
(pkg/scheduler/plugins/binpack/binpack_test.go exact-score assertions)."""

import numpy as np
import jax.numpy as jnp

from volcano_tpu.api import Taint, Toleration
from volcano_tpu.arrays import pack, stable_hash
from volcano_tpu.ops import predicates as P
from volcano_tpu.ops import scoring as S
from volcano_tpu.ops import select as SEL

from fixtures import build_job, build_node, build_task, simple_cluster


def packed_cluster(**kw):
    ci = simple_cluster(**kw)
    job = build_job("default/j1", min_available=1)
    job.add_task(build_task("p1", cpu="1", memory="1Gi"))
    ci.add_job(job)
    return ci


class TestPack:
    def test_shapes_and_masks(self):
        snap, maps = pack(packed_cluster(n_nodes=3))
        assert snap.nodes.idle.shape[0] >= 3
        assert snap.nodes.valid.sum() == 3
        assert snap.tasks.valid.sum() == 1
        assert snap.jobs.valid.sum() == 1
        assert maps.resource_names[:2] == ["cpu", "memory"]

    def test_node_accounting_packed(self):
        ci = packed_cluster(n_nodes=2)
        running_job = build_job("default/j0", min_available=1)
        t = build_task("r1", cpu="1")
        from volcano_tpu.api import TaskStatus
        t.status = TaskStatus.RUNNING
        running_job.add_task(t)
        ci.nodes["n0"].add_task(t)
        ci.add_job(running_job)
        snap, maps = pack(ci)
        n0 = maps.node_index["n0"]
        assert snap.nodes.idle[n0][0] == 3000.0
        assert snap.nodes.used[n0][0] == 1000.0
        assert snap.nodes.pod_count[n0] == 1

    def test_pending_task_table_sorted_by_priority(self):
        ci = simple_cluster()
        job = build_job("default/j1", min_available=2)
        job.add_task(build_task("lo", priority=1))
        job.add_task(build_task("hi", priority=10))
        ci.add_job(job)
        snap, maps = pack(ci)
        ji = maps.job_index["default/j1"]
        first = snap.jobs.task_table[ji][0]
        assert maps.task_uids[first] == "default/hi"


class TestPredicates:
    def test_resource_fit(self):
        snap, maps = pack(packed_cluster(n_nodes=2))
        req = jnp.asarray(snap.tasks.resreq[0])
        fit = P.resource_fit(req, jnp.asarray(snap.nodes.idle))
        assert bool(fit[maps.node_index["n0"]])
        big = req * 100
        assert not bool(P.resource_fit(big, jnp.asarray(snap.nodes.idle))[0])

    def test_selector_match(self):
        ci = simple_cluster(n_nodes=2)
        ci.nodes["n0"].labels = {"zone": "a"}
        job = build_job("default/j1")
        job.add_task(build_task("p1", node_selector={"zone": "a"}))
        ci.add_job(job)
        snap, maps = pack(ci)
        m = P.selector_match(jnp.asarray(snap.tasks.selector[0]),
                             jnp.asarray(snap.nodes.labels))
        assert bool(m[maps.node_index["n0"]])
        assert not bool(m[maps.node_index["n1"]])

    def test_taints(self):
        ci = simple_cluster(n_nodes=2)
        ci.nodes["n0"].taints = [Taint("dedicated", "gpu", "NoSchedule")]
        job = build_job("default/j1")
        job.add_task(build_task("plain"))
        tol = build_task("tolerant",
                         tolerations=[Toleration("dedicated", "Equal", "gpu",
                                                 "NoSchedule")])
        job.add_task(tol)
        ci.add_job(job)
        snap, maps = pack(ci)
        i_plain = maps.task_index["default/plain"]
        i_tol = maps.task_index["default/tolerant"]
        nodes = snap.nodes
        ok_plain = P.taints_tolerated(
            jnp.asarray(snap.tasks.tol_hash[i_plain]),
            jnp.asarray(snap.tasks.tol_effect[i_plain]),
            jnp.asarray(snap.tasks.tol_mode[i_plain]), nodes)
        ok_tol = P.taints_tolerated(
            jnp.asarray(snap.tasks.tol_hash[i_tol]),
            jnp.asarray(snap.tasks.tol_effect[i_tol]),
            jnp.asarray(snap.tasks.tol_mode[i_tol]), nodes)
        n0 = maps.node_index["n0"]
        assert not bool(ok_plain[n0])
        assert bool(ok_tol[n0])
        assert bool(ok_plain[maps.node_index["n1"]])

    def test_prefer_no_schedule_does_not_block(self):
        ci = simple_cluster(n_nodes=1)
        ci.nodes["n0"].taints = [Taint("soft", "x", "PreferNoSchedule")]
        job = build_job("default/j1")
        job.add_task(build_task("p1"))
        ci.add_job(job)
        snap, maps = pack(ci)
        ok = P.taints_tolerated(jnp.asarray(snap.tasks.tol_hash[0]),
                                jnp.asarray(snap.tasks.tol_effect[0]),
                                jnp.asarray(snap.tasks.tol_mode[0]), snap.nodes)
        assert bool(ok[0])

    def test_pod_count(self):
        ci = simple_cluster(n_nodes=1)
        ci.nodes["n0"].max_pods = 0
        snap, _ = pack(ci)
        assert not bool(P.pod_count_fit(snap.nodes)[0])


class TestScoring:
    def test_binpack_exact(self):
        # node: 4 cpu, 8Gi; used 1 cpu, 2Gi; request 1 cpu 2Gi,
        # weights cpu=1 memory=1 -> score = ((2/4) + (4/8))/2 * 100 = 50
        used = jnp.array([[1000.0, 2.0 * 2**30]])
        alloc = jnp.array([[4000.0, 8.0 * 2**30]])
        req = jnp.array([1000.0, 2.0 * 2**30])
        w = jnp.array([1.0, 1.0])
        score = S.binpack_score(used, alloc, req, w)
        np.testing.assert_allclose(score, [50.0], rtol=1e-5)

    def test_binpack_prefers_fuller_node(self):
        used = jnp.array([[3000.0, 0.0], [0.0, 0.0]])
        alloc = jnp.array([[4000.0, 1.0], [4000.0, 1.0]])
        req = jnp.array([1000.0, 0.0])
        s = S.binpack_score(used, alloc, req, jnp.array([1.0, 1.0]))
        assert s[0] > s[1]

    def test_binpack_overflow_zero(self):
        used = jnp.array([[3500.0, 0.0]])
        alloc = jnp.array([[4000.0, 1.0]])
        req = jnp.array([1000.0, 0.0])
        s = S.binpack_score(used, alloc, req, jnp.array([1.0, 0.0]))
        assert float(s[0]) == 0.0

    def test_least_vs_most(self):
        used = jnp.array([[2000.0, 0.0], [0.0, 0.0]])
        alloc = jnp.array([[4000.0, 4.0], [4000.0, 4.0]])
        req = jnp.array([0.0, 0.0])
        least = S.least_allocated_score(used, alloc, req)
        most = S.most_allocated_score(used, alloc, req)
        assert least[1] > least[0]
        assert most[0] > most[1]

    def test_balanced(self):
        # node 0 perfectly balanced, node 1 skewed
        used = jnp.array([[2000.0, 2.0], [4000.0, 0.0]])
        alloc = jnp.array([[4000.0, 4.0], [4000.0, 4.0]])
        req = jnp.array([0.0, 0.0])
        s = S.balanced_allocation_score(used, alloc, req)
        assert s[0] > s[1]


class TestSelect:
    def test_best_node_tie_break_first(self):
        score = jnp.array([5.0, 5.0, 3.0])
        feas = jnp.array([True, True, True])
        idx, found = SEL.best_node(score, feas)
        assert int(idx) == 0 and bool(found)

    def test_best_node_infeasible(self):
        idx, found = SEL.best_node(jnp.array([1.0]), jnp.array([False]))
        assert not bool(found)

    def test_lex_argmin(self):
        k1 = jnp.array([1.0, 1.0, 0.0, 1.0])
        k2 = jnp.array([9.0, 2.0, 5.0, 2.0])
        mask = jnp.array([True, True, False, True])
        idx, found = SEL.lex_argmin([k1, k2], mask)
        assert int(idx) == 1 and bool(found)  # index 2 masked out; 1 before 3

    def test_sort_order_lexicographic(self):
        k1 = jnp.array([2.0, 1.0, 1.0, 3.0])
        k2 = jnp.array([0.0, 5.0, 2.0, 0.0])
        mask = jnp.array([True, True, True, False])
        order = SEL.sort_order([k1, k2], mask)
        assert list(order[:3]) == [2, 1, 0]


class TestPredicateTemplates:
    """The predicate-cache analog (plugins/predicates/cache.go:42-90):
    tasks with identical selector/toleration rows share a template id and
    one static-feasibility mask row."""

    def test_template_dedupe(self):
        ci = simple_cluster(n_nodes=2)
        job = build_job("default/j1", min_available=1)
        for i in range(3):
            job.add_task(build_task(f"same{i}", cpu="1",
                                    node_selector={"zone": "a"}))
        job.add_task(build_task("diff", cpu="1",
                                node_selector={"zone": "b"}))
        ci.add_job(job)
        snap, maps = pack(ci)
        tmpl = np.asarray(snap.tasks.template)
        ids = {maps.task_index[f"default/same{i}"] for i in range(3)}
        assert len({int(tmpl[t]) for t in ids}) == 1
        assert int(tmpl[maps.task_index["default/diff"]]) not in \
            {int(tmpl[t]) for t in ids}
        reps = np.asarray(snap.template_rep)
        n_templates = int((reps >= 0).sum())
        assert n_templates == 2

    def test_template_masks_match_per_task_feasible(self):
        import jax
        ci = simple_cluster(n_nodes=3)
        ci.nodes["n1"].labels["zone"] = "a"
        job = build_job("default/j1", min_available=1)
        job.add_task(build_task("t0", cpu="1", node_selector={"zone": "a"}))
        job.add_task(build_task("t1", cpu="1"))
        ci.add_job(job)
        snap, maps = pack(ci)
        masks = np.asarray(P.template_masks(snap.nodes, snap.tasks,
                                            snap.template_rep))
        tmpl = np.asarray(snap.tasks.template)
        for uid in ("default/t0", "default/t1"):
            ti = maps.task_index[uid]
            direct = np.asarray(P.static_feasible(
                snap.nodes, snap.tasks.selector[ti], snap.tasks.tol_hash[ti],
                snap.tasks.tol_effect[ti], snap.tasks.tol_mode[ti]))
            np.testing.assert_array_equal(masks[int(tmpl[ti])], direct)
