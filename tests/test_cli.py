"""CLI tests — mirrors the reference's vcctl e2e suite (test/e2e/vcctl/)."""

import os

import pytest

from volcano_tpu.cli import job_from_yaml
from volcano_tpu.cli.vcctl import VcctlError, main
from volcano_tpu.runtime.system import VolcanoSystem

JOB_YAML = """
apiVersion: batch.volcano.sh/v1alpha1
kind: Job
metadata:
  name: test-job
spec:
  minAvailable: 3
  schedulerName: volcano
  queue: default
  plugins:
    ssh: []
    svc: []
  policies:
    - event: PodEvicted
      action: RestartJob
  tasks:
    - replicas: 3
      name: "test"
      template:
        spec:
          containers:
            - resources:
                requests:
                  cpu: "1"
                  memory: "1Gi"
"""


@pytest.fixture
def system(tmp_path):
    sys_ = VolcanoSystem()
    for i in range(2):
        sys_.add_node(f"n{i}", cpu="4", memory="8Gi")
    return sys_


@pytest.fixture
def job_file(tmp_path):
    p = tmp_path / "job.yaml"
    p.write_text(JOB_YAML)
    return str(p)


class TestLoader:
    def test_reference_manifest_shape(self):
        job = job_from_yaml(JOB_YAML)
        assert job.name == "test-job"
        assert job.min_available == 3
        assert job.tasks[0].replicas == 3
        assert job.tasks[0].template.resreq().milli_cpu == 1000
        assert "ssh" in job.plugins
        assert job.policies[0].event.value == "PodEvicted"


class TestJobCommands:
    def test_run_list_view(self, system, job_file):
        out = main(["job", "run", "-f", job_file], system=system)
        assert "successfully" in out
        for _ in range(3):
            system.tick()
        out = main(["job", "list"], system=system)
        assert "test-job" in out and "Running" in out
        out = main(["job", "view", "-N", "test-job"], system=system)
        assert "test-job-test-0" in out
        assert "node=n" in out

    def test_suspend_resume(self, system, job_file):
        main(["job", "run", "-f", job_file], system=system)
        for _ in range(3):
            system.tick()
        main(["job", "suspend", "-N", "test-job"], system=system)
        system.reconcile()
        assert "Abort" in main(["job", "list"], system=system)
        main(["job", "resume", "-N", "test-job"], system=system)
        for _ in range(4):
            system.tick()
        assert "Running" in main(["job", "list"], system=system)

    def test_delete(self, system, job_file):
        main(["job", "run", "-f", job_file], system=system)
        system.reconcile()
        main(["job", "delete", "-N", "test-job"], system=system)
        system.reconcile()
        assert system.job("test-job") is None
        assert system.pods_of("test-job") == []

    def test_view_missing_job_errors(self, system):
        with pytest.raises(VcctlError):
            main(["job", "view", "-N", "nope"], system=system)


class TestQueueCommands:
    def test_create_list_get(self, system):
        main(["queue", "create", "-N", "q1", "-w", "3"], system=system)
        out = main(["queue", "list"], system=system)
        assert "q1" in out and "3" in out
        out = main(["queue", "get", "-N", "q1"], system=system)
        assert "Weight: 3" in out

    def test_operate_close_open(self, system):
        main(["queue", "create", "-N", "q2"], system=system)
        main(["queue", "operate", "-N", "q2", "-a", "close"], system=system)
        system.reconcile()
        assert system.api.get("queues", "q2").state.value == "Closed"
        main(["queue", "operate", "-N", "q2", "-a", "open"], system=system)
        system.reconcile()
        assert system.api.get("queues", "q2").state.value == "Open"

    def test_delete_open_queue_rejected(self, system):
        from volcano_tpu.webhooks import AdmissionError
        main(["queue", "create", "-N", "q3"], system=system)
        with pytest.raises(AdmissionError):
            main(["queue", "delete", "-N", "q3"], system=system)

    def test_invalid_operate_action(self, system):
        main(["queue", "create", "-N", "q4"], system=system)
        with pytest.raises(VcctlError):
            main(["queue", "operate", "-N", "q4", "-a", "explode"],
                 system=system)


class TestStateFile:
    def test_standalone_round_trip(self, tmp_path, job_file):
        state = str(tmp_path / "vc.pkl")
        main(["--state", state, "queue", "create", "-N", "sq"])
        out = main(["--state", state, "queue", "list"])
        assert "sq" in out
