"""Preempt/reclaim kernel vs sequential CPU oracle (VERDICT r4 #3).

The reference pins victim-choice behavior with dense action tests
(pkg/scheduler/actions/preempt/preempt_test.go:1-322 and the reclaim/drf/
proportion suites); here the pin is decision equality between
ops.preempt.make_preempt_cycle and runtime.cpu_reference.preempt_cpu on
randomized snapshots: victim sets, pipelined placements, and per-gang
outcomes must match exactly.
"""

import dataclasses

import jax
import numpy as np
import pytest

from volcano_tpu.api import (ClusterInfo, JobInfo, NodeInfo, PodGroupPhase,
                             QueueInfo, Resource, TaskInfo, TaskStatus)
from volcano_tpu.arrays import pack
from volcano_tpu.ops.allocate_scan import AllocateConfig, AllocateExtras
from volcano_tpu.ops.preempt import PreemptConfig, make_preempt_cycle
from volcano_tpu.runtime.cpu_reference import preempt_cpu

R = Resource.from_resource_list

SCORING = AllocateConfig(binpack_weight=1.0, least_allocated_weight=0.0,
                         balanced_weight=0.0, taint_prefer_weight=0.0,
                         enable_gpu=False)


def random_cluster(rng, n_nodes=12, n_low=10, n_high=4, reclaim=False):
    """Nodes mostly full of Running low-priority preemptable gangs plus
    starving high-priority gangs (config-4 shape, downscaled)."""
    ci = ClusterInfo()
    if reclaim:
        ci.add_queue(QueueInfo("qa", weight=1, reclaimable=True))
        ci.add_queue(QueueInfo("qb", weight=1, reclaimable=True))
    else:
        ci.add_queue(QueueInfo("default", weight=1))
    names = [f"n{i:02d}" for i in range(n_nodes)]
    for n in names:
        ci.add_node(NodeInfo(n, R({"cpu": "8", "memory": "16Gi"}),
                             R({"cpu": "8", "memory": "16Gi"})))
    k = 0
    for j in range(n_low):
        q = ("qa" if reclaim else "default")
        job = JobInfo(f"default/lo{j}", queue=q, min_available=1,
                      priority=int(rng.randint(0, 3)),
                      creation_timestamp=float(j),
                      pod_group_phase=PodGroupPhase.RUNNING,
                      preemptable=True)
        for t in range(int(rng.randint(2, 6))):
            cpu = ["1", "2", "3"][rng.randint(3)]
            task = TaskInfo(f"default/lo{j}-{t}", f"lo{j}-{t}",
                            resreq=R({"cpu": cpu, "memory": "1Gi"}),
                            status=TaskStatus.RUNNING,
                            priority=int(rng.randint(0, 3)),
                            preemptable=True)
            node = names[k % n_nodes]
            k += 1
            task.node_name = node
            job.add_task(task)
            try:
                ci.nodes[node].add_task(task)
            except ValueError:
                job.delete_task(task)
        job.allocated = R({})
        for t in job.tasks.values():
            job.allocated.add(t.resreq)
        ci.add_job(job)
    for j in range(n_high):
        q = ("qb" if reclaim else "default")
        ma = int(rng.randint(1, 4))
        job = JobInfo(f"default/hi{j}", queue=q, min_available=ma,
                      priority=50 + int(rng.randint(0, 3)),
                      creation_timestamp=100.0 + j,
                      pod_group_phase=PodGroupPhase.INQUEUE)
        for t in range(ma + int(rng.randint(0, 3))):
            cpu = ["2", "4"][rng.randint(2)]
            job.add_task(TaskInfo(
                f"default/hi{j}-{t}", f"hi{j}-{t}",
                resreq=R({"cpu": cpu, "memory": "2Gi"}),
                priority=50))
        ci.add_job(job)
    return ci


def run_both(ci, pcfg):
    snap, _maps = pack(ci)
    extras = AllocateExtras.neutral(snap)
    T = np.asarray(snap.tasks.status).shape[0]
    veto = np.zeros(T, bool)
    skipm = np.zeros(T, bool)
    fn = jax.jit(make_preempt_cycle(pcfg))
    dev = fn(snap, extras, veto, skipm)
    cpu = preempt_cpu(snap, extras, veto, skipm, pcfg)
    return dev, cpu


def assert_equal(dev, cpu, msg=""):
    np.testing.assert_array_equal(np.asarray(dev.evicted),
                                  cpu["evicted"], err_msg=f"victims {msg}")
    np.testing.assert_array_equal(np.asarray(dev.task_node),
                                  cpu["task_node"], err_msg=f"nodes {msg}")
    np.testing.assert_array_equal(np.asarray(dev.task_mode),
                                  cpu["task_mode"], err_msg=f"modes {msg}")
    np.testing.assert_array_equal(np.asarray(dev.job_pipelined),
                                  cpu["job_pipelined"], err_msg=msg)


#: tier-1 budget (same pattern as the K∈{2,4} batched-round rows and the
#: hdrf rescaling replays): the oracle-equality fuzz REPLAYS beyond the
#: first seed move to the `slow` tail — seed 0 stays in tier-1 (it is the
#: seed asserted to actually preempt), the full suite runs all of them
_FUZZ = pytest.mark.slow


class TestPreemptOracle:
    @pytest.mark.parametrize(
        "seed", [0] + [pytest.param(s, marks=_FUZZ) for s in (1, 2, 3, 4,
                                                              5)])
    def test_preempt_decisions_equal(self, seed):
        rng = np.random.RandomState(seed)
        ci = random_cluster(rng)
        pcfg = PreemptConfig(scoring=SCORING)
        dev, cpu = run_both(ci, pcfg)
        assert_equal(dev, cpu, f"seed={seed}")
        # the scenario actually preempts something in most seeds
        if seed == 0:
            assert np.asarray(dev.evicted).any()

    @pytest.mark.parametrize(
        "seed", [0] + [pytest.param(s, marks=_FUZZ) for s in (1, 2)])
    def test_preempt_with_drf_rule(self, seed):
        rng = np.random.RandomState(100 + seed)
        ci = random_cluster(rng)
        pcfg = PreemptConfig(
            scoring=dataclasses.replace(SCORING, drf_job_order=True),
            tiers=(("priority", "gang"), ("drf",)))
        dev, cpu = run_both(ci, pcfg)
        assert_equal(dev, cpu, f"drf seed={seed}")

    @pytest.mark.parametrize(
        "seed", [0] + [pytest.param(s, marks=_FUZZ) for s in (1, 2)])
    def test_reclaim_decisions_equal(self, seed):
        rng = np.random.RandomState(200 + seed)
        ci = random_cluster(rng, reclaim=True)
        pcfg = PreemptConfig(mode="reclaim",
                             scoring=SCORING,
                             tiers=(("gang", "proportion"),))
        snap, _maps = pack(ci)
        extras = AllocateExtras.neutral(snap)
        # finite deserved so reclaim's what-if rule actually gates
        from volcano_tpu.ops.fairshare import proportion_deserved
        extras.queue_deserved = np.asarray(proportion_deserved(
            snap.queues, snap.cluster_capacity), np.float32)
        T = np.asarray(snap.tasks.status).shape[0]
        veto = np.zeros(T, bool)
        skipm = np.zeros(T, bool)
        fn = jax.jit(make_preempt_cycle(pcfg))
        dev = fn(snap, extras, veto, skipm)
        cpu = preempt_cpu(snap, extras, veto, skipm, pcfg)
        assert_equal(dev, cpu, f"reclaim seed={seed}")

    def test_conformance_veto_respected(self):
        rng = np.random.RandomState(7)
        ci = random_cluster(rng)
        snap, _maps = pack(ci)
        extras = AllocateExtras.neutral(snap)
        T = np.asarray(snap.tasks.status).shape[0]
        veto = np.zeros(T, bool)
        veto[: T // 2] = True      # arbitrary protected half
        skipm = np.zeros(T, bool)
        pcfg = PreemptConfig(scoring=SCORING,
                             tiers=(("priority", "gang", "conformance"),))
        fn = jax.jit(make_preempt_cycle(pcfg))
        dev = fn(snap, extras, veto, skipm)
        cpu = preempt_cpu(snap, extras, veto, skipm, pcfg)
        assert_equal(dev, cpu, "veto")
        assert not np.asarray(dev.evicted)[veto].any()
