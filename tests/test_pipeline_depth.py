"""Depth-k speculative pipeline unit + edge-case tests (ISSUE 13).

Fast (non-slow) tests cover the pure host-side surfaces: conf parsing
and clamping of ``pipeline_depth``, the per-thread/per-depth occupancy
math on synthetic events, the shared :func:`step_cycle` driver helper,
the sidecar ``_TenantStream`` depth-1 compat properties, and the
``_effective_depth`` gating rules (none of these touch a JAX compile).

Slow-marked tests drive a real depth-3 Scheduler through the drain()/
wait_pending() edge cases ISSUE 13 names — double drain, drain while
degraded, drain with an empty pipeline, checkpoint mid-ring — each
riding the one compiled allocate the probe conf already pays for. The
fast behavioral gate for decision identity itself is the tier-1 ``--spec``
smoke (volcano_tpu/chaos/spec.py); these tests pin the API contracts
around it.
"""

import pytest

from volcano_tpu.framework import parse_conf
from volcano_tpu.runtime.driver import step_cycle
from volcano_tpu.runtime.fake_cluster import FakeCluster
from volcano_tpu.runtime.scheduler import Scheduler
from volcano_tpu.telemetry import spans


def _probe_conf(extra: str = ""):
    from volcano_tpu.chaos import probe
    return parse_conf(probe._PROBE_CONF + extra)


def _probe_cluster():
    from volcano_tpu.chaos import probe
    return FakeCluster(probe._small_cluster().clone())


class TestConfPipelineDepth:
    def test_default_is_one(self):
        assert _probe_conf().pipeline_depth == 1

    def test_parse_and_clamp(self):
        assert _probe_conf("pipeline_depth: 3\n").pipeline_depth == 3
        # 0 / negative / null all clamp to the depth-1 contract
        assert _probe_conf("pipeline_depth: 0\n").pipeline_depth == 1
        assert _probe_conf("pipeline_depth: -2\n").pipeline_depth == 1
        assert _probe_conf("pipeline_depth: null\n").pipeline_depth == 1


class TestOccupancyPerThread:
    def test_pack_thread_counts_while_main_blocks(self):
        """The per-tid rule: the main thread fully blocked in a drain
        must not blank the pack worker's real work (the global-merge
        analyzer reported 0 overlap here)."""
        evts = [
            # main thread: a 10 s cycle span entirely covered by wait
            {"name": "cycle", "cat": None, "tid": 1, "ts": 0.0,
             "dur": 10.0},
            {"name": "cycle.drain", "cat": "wait", "tid": 1, "ts": 0.0,
             "dur": 10.0},
            # pack worker: 6 s of genuine host work inside the window
            {"name": "pack", "cat": "pack", "tid": 2, "ts": 2.0,
             "dur": 6.0},
            {"name": "device_window", "cat": "device", "tid": 1,
             "ts": 0.0, "dur": 10.0, "args": {"depth": 3}},
            {"name": "device_window", "cat": "device", "tid": 1,
             "ts": 12.0, "dur": 2.0, "args": {"depth": 1}},
        ]
        out = spans.compute_occupancy(evts)
        assert out["windows"] == 2
        assert out["window_ms"] == 12000.0
        assert out["overlap_ms"] == 6000.0
        assert out["pipeline_overlap_fraction"] == 0.5
        # windows carry depth tags != {1}: the per-depth breakdown exists
        per_depth = out["per_depth"]
        assert set(per_depth) == {"1", "3"}
        assert per_depth["3"]["overlap_ms"] == 6000.0
        assert per_depth["3"]["pipeline_overlap_fraction"] == 0.6
        assert per_depth["1"]["overlap_ms"] == 0.0

    def test_one_threads_wait_never_blanks_another(self):
        """A wait on tid 2 must only subtract from tid 2's own work."""
        evts = [
            {"name": "work", "cat": None, "tid": 1, "ts": 0.0, "dur": 4.0},
            {"name": "w", "cat": "wait", "tid": 2, "ts": 0.0, "dur": 4.0},
            {"name": "device_window", "cat": "device", "tid": 1,
             "ts": 0.0, "dur": 4.0},
        ]
        out = spans.compute_occupancy(evts)
        assert out["overlap_ms"] == 4000.0
        # untagged windows are depth 1 — no per-depth breakdown
        assert out["per_depth"] is None

    def test_window_depth_defensive(self):
        assert spans._window_depth({}) == 1
        assert spans._window_depth({"args": None}) == 1
        assert spans._window_depth({"args": {"depth": "junk"}}) == 1
        assert spans._window_depth({"args": {"depth": 3}}) == 3

    def test_live_occupancy_backend_tag(self):
        out = spans.occupancy()
        assert "backend" in out
        assert out["backend"] is None or isinstance(out["backend"], str)


class _StubSched:
    def __init__(self, pipeline, drain_result="drained"):
        self.pipeline = pipeline
        self.calls = []
        self._drain_result = drain_result

    def run_once(self, now=None):
        self.calls.append("run_once")
        return "live"

    def drain(self, now=None):
        self.calls.append("drain")
        return self._drain_result


class TestStepCycle:
    def test_sync_returns_run_once_and_never_drains(self):
        s = _StubSched(pipeline=False)
        assert step_cycle(s, now=1.0) == "live"
        assert s.calls == ["run_once"]

    def test_pipelined_drains_after_ingest(self):
        s = _StubSched(pipeline=True)

        def ingest():
            s.calls.append("ingest")

        assert step_cycle(s, now=1.0, ingest=ingest) == "drained"
        # ingest runs between dispatch and drain — that ordering IS the
        # overlap the pipeline buys
        assert s.calls == ["run_once", "ingest", "drain"]

    def test_pipelined_empty_drain_falls_back_to_live(self):
        s = _StubSched(pipeline=True, drain_result=None)
        assert step_cycle(s, now=1.0) == "live"


class TestTenantStreamCompat:
    def test_pending_is_ring_head(self):
        from volcano_tpu.runtime.sidecar import _TenantStream
        st = _TenantStream()
        assert st.pending is None
        st.ring.append({"slot": 0})
        st.ring.append({"slot": 1})
        assert st.pending == {"slot": 0}
        st.pending = None
        assert st.ring == []
        st.pending = {"slot": 2}
        assert st.ring == [{"slot": 2}]

    def test_staged_payload_is_staged_head(self):
        from volcano_tpu.runtime.sidecar import _TenantStream
        st = _TenantStream()
        assert st.staged_payload is None
        st.staged.extend([b"old", b"new"])
        assert st.staged_payload == b"old"
        st.staged_payload = None
        assert st.staged == []
        st.staged_payload = b"x"
        assert st.staged == [b"x"]


class TestEffectiveDepthGates:
    def test_gating_rules(self):
        conf = _probe_conf("pipeline: true\npipeline_depth: 3\n")
        sched = Scheduler(_probe_cluster(), conf=conf)
        assert sched._effective_depth() == 3
        # any degradation clamps speculation to the depth-1 contract
        sched.degradation_level = 1
        assert sched._effective_depth() == 1
        sched.degradation_level = 0
        assert sched._effective_depth() == 3
        # the speculation-ladder hold clamps too
        sched._spec_disabled_until = sched.cycles + 5
        assert sched._effective_depth() == 1

    def test_requires_pipeline_incremental_unsharded(self):
        conf = _probe_conf("pipeline: true\npipeline_depth: 3\n")
        assert Scheduler(_probe_cluster(), conf=conf,
                         pipeline=False)._effective_depth() == 1
        assert Scheduler(_probe_cluster(), conf=conf,
                         incremental=False)._effective_depth() == 1
        sharded = _probe_conf(
            "pipeline: true\npipeline_depth: 3\nsharding: true\n")
        assert Scheduler(_probe_cluster(),
                         conf=sharded)._effective_depth() == 1


def _collect(digests, rec, pipeline=True):
    """spec.py's collection rule: pipelined priming cycles return the
    live (undrained) session — its decisions surface later via drain."""
    from volcano_tpu.chaos import probe
    if rec is None or (pipeline and hasattr(rec, "dispatch_allocate")):
        return
    digests.append(probe._cycle_digest(rec))


@pytest.mark.slow
class TestDrainEdgeCases:
    """Real depth-3 Scheduler edge cases (slow: one compiled allocate
    per conf; the decision-identity matrix itself is the tier-1 --spec
    smoke)."""

    def _sched(self, depth=3):
        conf = _probe_conf(f"pipeline: true\npipeline_depth: {depth}\n")
        return Scheduler(_probe_cluster(), conf=conf)

    def test_drain_empty_pipeline_is_noop(self):
        sched = self._sched()
        assert sched.drain(now=1000.0) is None
        assert sched.wait_pending() is False
        # still serves normally afterwards
        assert sched.run_once(now=1000.0) is not None

    def test_ring_fills_to_depth_then_double_drain(self):
        sched = self._sched(depth=3)
        for c in range(3):
            sched.run_once(now=1000.0 + c)
        assert len(sched._ring) == 3
        assert sched._pending is not None
        # wait_pending blocks on device work but retires nothing
        assert sched.wait_pending() is True
        assert len(sched._ring) == 3
        rec = sched.drain(now=1003.0)
        assert rec is not None and not hasattr(rec, "dispatch_allocate")
        assert sched._ring == [] and sched._pending is None
        # double drain: the second call is a no-op returning None
        assert sched.drain(now=1003.0) is None
        assert sched.wait_pending() is False

    def test_drain_while_degraded(self):
        sched = self._sched(depth=3)
        for c in range(3):
            sched.run_once(now=1000.0 + c)
        assert len(sched._ring) == 3
        sched._degrade(1)
        # drain retires the whole ring even on a degraded ladder rung
        assert sched.drain(now=1003.0) is not None
        assert sched._ring == []
        # the degraded cycle itself runs synchronously: nothing queued
        rec = sched.run_once(now=1004.0)
        assert rec is not None
        assert sched._pending is None
        assert sched.drain(now=1004.0) is None

    def test_checkpoint_mid_ring_drains_and_stays_neutral(self, tmp_path):
        """checkpoint() with cycles in flight drains oldest-first before
        cutting the snapshot — the decision stream must equal the
        uninterrupted depth-3 run's, and a fresh scheduler must restore
        from the file."""
        path = str(tmp_path / "ring.ckpt")
        legs = {}
        swallowed = None
        for label, ckpt_at in (("clean", None), ("checkpointed", 5)):
            conf = _probe_conf("pipeline: true\npipeline_depth: 3\n")
            sched = Scheduler(_probe_cluster(), conf=conf)
            digests = []
            for c in range(10):
                if ckpt_at is not None and c == ckpt_at:
                    assert sched._pending is not None  # mid-ring, really
                    # the checkpoint drains (and applies) these in-flight
                    # cycles internally; their records are not surfaced,
                    # so the collected stream skips exactly these slots
                    swallowed = [e.pending.slot for e in sched._ring]
                    sched.checkpoint(path, now=1000.0 + c)
                    # the drain-first rule: nothing left in flight
                    assert sched._pending is None
                _collect(digests, sched.run_once(now=1000.0 + c))
            while sched._ring:
                _collect(digests, sched._drain_pending(1010.0))
            legs[label] = digests
        # a full depth-3 ring went into the checkpoint
        assert swallowed is not None and len(swallowed) == 3
        # decision neutrality: the checkpoint drain retires cycles EARLY
        # but in dispatch order — the surfaced stream must equal the
        # clean leg's minus exactly the checkpoint-swallowed slots
        # (every cycle pipelines here, so slot number == cycle index)
        expected = [d for i, d in enumerate(legs["clean"])
                    if i not in swallowed]
        assert legs["checkpointed"] == expected
        # and the file restores into a fresh scheduler
        conf = _probe_conf("pipeline: true\npipeline_depth: 3\n")
        fresh = Scheduler(_probe_cluster(), conf=conf)
        assert fresh.restore(path, now=1010.0) == "restored"
        assert fresh._ring == []
        assert fresh.run_once(now=1011.0) is not None
