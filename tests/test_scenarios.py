"""Scenario engine & quality scorecard tests (ISSUE 9).

Three layers:

- exact quality math on hand-computable fixtures (nearest-rank quantiles,
  weighted water-fill incl. the zero-deserved queue, DRF share error,
  collector scorecards with pinned numbers);
- engine contracts: seed determinism (same seed -> same event sha -> same
  scorecard), observe on/off decision-sha identity (the purity contract),
  CPU-oracle drift checks covering real placements, and the
  reclaim-pressure scenario driving reclaim/reserve/elect through the
  compiled path with scorecard-visible effects;
- surfaces: volcano_quality_* gauges, the dashboard ``scenarios`` table,
  /api/scenarios, and the CLI.
"""

import json
import urllib.request

import pytest

from volcano_tpu.scenarios import quality
from volcano_tpu.scenarios.quality import (CycleSample, QualityCollector,
                                           Scorecard, nearest_rank,
                                           share_error, weighted_water_fill)


@pytest.fixture(autouse=True)
def _clean_registry():
    quality.reset_results()
    yield
    quality.reset_results()


# ------------------------------------------------------------ exact math
class TestQuantiles:
    def test_nearest_rank_exact(self):
        assert nearest_rank([3.0, 1.0, 2.0], 50) == 2.0
        assert nearest_rank([3.0, 1.0, 2.0], 1) == 1.0
        assert nearest_rank([3.0, 1.0, 2.0], 100) == 3.0
        # n=4: p50 -> rank ceil(2)=2, p95 -> rank ceil(3.8)=4
        assert nearest_rank([0.0, 1.0, 2.0, 5.0], 50) == 1.0
        assert nearest_rank([0.0, 1.0, 2.0, 5.0], 95) == 5.0
        assert nearest_rank([7.0], 99) == 7.0

    def test_empty_and_out_of_range(self):
        assert nearest_rank([], 50) is None
        with pytest.raises(ValueError):
            nearest_rank([1.0], 0)
        with pytest.raises(ValueError):
            nearest_rank([1.0], 101)


class TestWaterFill:
    def test_caps_by_demand(self):
        # b saturates at its demand 2; a absorbs the remainder
        assert weighted_water_fill(10, {"a": 1, "b": 1},
                                   {"a": 10, "b": 2}) == {"a": 8.0, "b": 2.0}

    def test_weight_proportional_when_oversubscribed(self):
        # nobody saturates: pure weight split 2:1 of capacity 6
        assert weighted_water_fill(6, {"a": 2, "b": 1},
                                   {"a": 100, "b": 100}) == {"a": 4.0,
                                                             "b": 2.0}

    def test_zero_weight_and_zero_demand_deserve_zero(self):
        d = weighted_water_fill(10, {"a": 1, "z": 0}, {"a": 5, "z": 5})
        assert d == {"a": 5.0, "z": 0.0}
        assert weighted_water_fill(10, {"a": 1}, {"a": 0}) == {"a": 0.0}


class TestShareError:
    def test_zero_deserved_queue_is_pure_error(self):
        # queue z holds the whole cluster but deserves nothing; queue a
        # deserves it all and holds nothing: |8-0|/8 + |0-8|/8 = 2.0
        assert share_error({"z": 8.0}, {"a": 8.0, "z": 0.0}, 8.0) == 2.0

    def test_perfect_and_degenerate(self):
        assert share_error({"a": 4.0}, {"a": 4.0}, 8.0) == 0.0
        assert share_error({"a": 4.0}, {}, 0.0) == 0.0


class TestCollector:
    def test_exact_scorecard(self):
        col = QualityCollector("fix", seed=7)
        col.note_arrival(0, jobs=2)
        col.note_arrival(3)
        col.note_completion(10)
        for w in (0.0, 1.0, 2.0, 5.0):
            col.note_wait(w)
        # deserved(8000, 1:1, {a:8000,b:2000}) = {a:6000,b:2000}
        col.add(CycleSample(
            cycle=0, capacity_milli_cpu=8000.0,
            allocated_milli_cpu={"a": 4000.0, "b": 2000.0},
            demand_milli_cpu={"a": 8000.0, "b": 2000.0},
            queue_weights={"a": 1.0, "b": 1.0}, evictions=3, binds=2,
            action_effects={"reclaim_evictions": 3.0,
                            "reserve_locked_total": 2.0}))
        col.add(CycleSample(
            cycle=1, capacity_milli_cpu=8000.0,
            allocated_milli_cpu={"a": 6000.0, "b": 2000.0},
            demand_milli_cpu={"a": 8000.0, "b": 2000.0},
            queue_weights={"a": 1.0, "b": 1.0}, evictions=0, binds=1,
            action_effects={"reclaim_evictions": 1.0,
                            "reserve_locked_total": 5.0}))
        card = col.scorecard(cycles=12)
        assert card.jobs_submitted == 3 and card.jobs_completed == 1
        assert card.makespan_cycles == 10
        assert card.drf_share_error == 0.125        # mean(0.25, 0.0)
        assert card.drf_share_error_max == 0.25
        assert card.node_utilization == 0.875       # mean(0.75, 1.0)
        assert card.preemption_churn_total == 3 and card.tasks_bound == 3
        assert card.wait_cycles == {"p50": 1.0, "p95": 5.0, "p99": 5.0}
        # sums for plain effects, running max for *_total effects
        assert card.action_effects == {"reclaim_evictions": 4.0,
                                       "reserve_locked_total": 5.0}
        assert card.complete()

    def test_incomplete_scorecard(self):
        card = QualityCollector("fix", seed=0).scorecard(cycles=4)
        assert card.makespan_cycles is None
        assert card.drf_share_error is None
        assert not card.complete()


# --------------------------------------------------------- engine contracts
def _run(name, **kw):
    from volcano_tpu.scenarios import get_scenario, run_scenario
    return run_scenario(get_scenario(name), **kw)


class TestEngine:
    # the multi-run scenario tests sit in the `slow` tail (tier-1 budget
    # recalibration, same pattern as PR 1/3/5/8); tier1.sh still gates the
    # engine every run via `python -m volcano_tpu.scenarios --smoke`
    @pytest.mark.slow
    def test_seed_determinism_and_drift_coverage(self):
        a = _run("trace-replay", cycles=12, observe=False,
                 drift_check_every=4)
        b = _run("trace-replay", cycles=12, observe=False,
                 drift_check_every=4)
        assert a.scorecard.event_sha == b.scorecard.event_sha
        assert a.scorecard.decisions_sha == b.scorecard.decisions_sha
        assert a.scorecard.to_dict() == b.scorecard.to_dict()
        assert a.events == b.events
        # the CPU-oracle spot-checks pass AND cover real placements
        assert a.ok and a.drift
        assert sum(d.placed for d in a.drift) > 0
        assert a.scorecard.complete()
        other = _run("trace-replay", cycles=12, observe=False,
                     drift_check_every=4, seed=99)
        assert other.scorecard.event_sha != a.scorecard.event_sha

    @pytest.mark.slow
    def test_observe_on_off_sha_identity(self):
        on = _run("trace-replay", cycles=10, observe=True,
                  drift_check_every=0)
        off = _run("trace-replay", cycles=10, observe=False,
                   drift_check_every=0)
        assert on.scorecard.decisions_sha == off.scorecard.decisions_sha
        assert on.scorecard.event_sha == off.scorecard.event_sha
        # only the observed run published to the results registry
        assert [c["scenario"] for c in quality.results()] == ["trace-replay"]

    @pytest.mark.slow
    def test_reclaim_pressure_fires_compiled_actions(self):
        r = _run("reclaim-pressure", cycles=8, observe=False,
                 drift_check_every=0)
        eff = r.scorecard.action_effects
        assert eff.get("reclaim_evictions", 0) > 0
        assert eff.get("elect_count", 0) > 0
        assert eff.get("reserve_count", 0) > 0
        assert r.scorecard.preemption_churn_total > 0

    def test_catalog(self):
        from volcano_tpu.scenarios import get_scenario, list_scenarios
        names = [s.name for s in list_scenarios()]
        assert {"trace-replay", "diurnal-churn", "hetero-pools",
                "failure-storm", "reclaim-pressure"} <= set(names)
        with pytest.raises(KeyError):
            get_scenario("no-such-scenario")


# ----------------------------------------------------------------- surfaces
def _card(**kw):
    base = dict(scenario="t", seed=1, cycles=4, jobs_completed=2,
                makespan_cycles=3, drf_share_error=0.1,
                node_utilization=0.5, preemption_churn_total=6,
                wait_cycles={"p50": 0.0, "p95": 1.0, "p99": 1.0},
                drift_checks=2, drift_failures=0, event_sha="abc123")
    base.update(kw)
    return Scorecard(**base)


class TestSurfaces:
    def test_quality_gauges(self):
        from volcano_tpu.metrics import Metrics
        reg = Metrics()
        quality.publish_quality_gauges(_card(), registry=reg)
        text = reg.exposition()
        assert 'quality_drf_share_error{scenario="t"} 0.1' in text
        assert 'quality_makespan_cycles{scenario="t"} 3' in text
        assert 'quality_queue_wait_cycles{quantile="p95",scenario="t"} 1' \
            in text or 'quantile="p95"' in text
        assert "quality_drift_failures" in text

    def test_dashboard_table_and_api(self):
        quality.record_result(_card())

        class _Api:
            def list(self, kind):
                return []

        class _Sys:
            api = _Api()

        from volcano_tpu.runtime.dashboard import Dashboard, build_page
        page = build_page(_Sys())
        tbl = page.tables["scenarios"]
        assert tbl["headers"][0] == "Scenario"
        assert all(len(r) == len(tbl["headers"]) for r in tbl["rows"])
        row = tbl["rows"][0]
        assert row[0] == "t" and row[-1] == "abc123"
        assert row[tbl["headers"].index("Drift ok")] == "2/2"
        dash = Dashboard(_Sys())
        port = dash.serve(port=0)
        try:
            body = json.loads(urllib.request.urlopen(
                f"http://127.0.0.1:{port}/api/scenarios").read())
            assert body["scorecards"][-1]["scenario"] == "t"
            assert body["scorecards"][-1]["wait_cycles"]["p95"] == 1.0
        finally:
            dash.shutdown()

    def test_cli_list(self, capsys):
        from volcano_tpu.scenarios.__main__ import main
        assert main(["--list"]) == 0
        out = capsys.readouterr().out
        assert "trace-replay" in out and "reclaim-pressure" in out
