"""Tiered victim dispatch: first-non-empty-tier-wins + intersection.

Mirrors framework/session_plugins.go:131-215 — the same cluster produces
DIFFERENT victim sets depending only on tier ordering, and the drf victim
rule recomputes shares per eviction (the event-handler analog,
drf.go:336-358 + 511-561).
"""

import numpy as np

from volcano_tpu.api import QueueInfo, TaskStatus
from volcano_tpu.framework.session import Session
from volcano_tpu.framework.conf import parse_conf

from fixtures import build_job, build_task, simple_cluster


def _tier_cluster():
    """One full 10-cpu node. Preemptor P (prio 5, needs 2 cpu) vs V1
    (prio 1, tiny drf share) and V2 (prio 10, large drf share):
    - the priority rule admits only V1 (1 < 5 < 10),
    - the drf rule admits only V2 (removing V1's only task drops its share
      to 0 < P's would-be share 0.2; V2 stays at 0.4 >= 0.2)."""
    ci = simple_cluster(n_nodes=1, node_cpu="10", node_mem="8Gi")
    v1 = build_job("default/v1", min_available=1, priority=1)
    t = build_task("v1-0", cpu="1", memory=0)
    t.status = TaskStatus.RUNNING
    v1.add_task(t)
    ci.nodes["n0"].add_task(t)
    ci.add_job(v1)
    v2 = build_job("default/v2", min_available=1, priority=10)
    for i in range(2):
        t = build_task(f"v2-{i}", cpu="4", memory=0)
        t.status = TaskStatus.RUNNING
        v2.add_task(t)
        ci.nodes["n0"].add_task(t)
    ci.add_job(v2)
    # node: 1 + 8 = 9 cpu used, 1 idle; P needs 2 -> must evict
    p = build_job("default/p", min_available=1, priority=5)
    p.add_task(build_task("p-0", cpu="2", memory=0))
    ci.add_job(p)
    return ci


def _run_preempt(ci, conf_text):
    ssn = Session(ci, parse_conf(conf_text))
    ssn.run_preempt("preempt")
    return ssn


PRIORITY_FIRST = """
actions: "preempt"
tiers:
- plugins:
  - name: priority
- plugins:
  - name: drf
"""

DRF_FIRST = """
actions: "preempt"
tiers:
- plugins:
  - name: drf
- plugins:
  - name: priority
"""


class TestTierOrdering:
    def test_priority_tier_first_picks_low_priority_victim(self):
        ssn = _run_preempt(_tier_cluster(), PRIORITY_FIRST)
        evicted = [e.task_uid for e in ssn.evictions]
        assert evicted == ["default/v1-0"], evicted

    def test_drf_tier_first_picks_high_share_victim(self):
        """Same cluster, tiers swapped -> the drf tier decides and the
        victim comes from the high-share job instead."""
        ssn = _run_preempt(_tier_cluster(), DRF_FIRST)
        evicted = [e.task_uid for e in ssn.evictions]
        assert len(evicted) == 1 and evicted[0].startswith("default/v2-"), \
            evicted

    def test_intersection_within_tier_empties_and_falls_through(self):
        """priority AND drf in ONE tier intersect to nothing here (their
        candidate sets are disjoint), so the tier yields nil and the next
        tier (conformance alone: everything) decides."""
        conf = """
actions: "preempt"
tiers:
- plugins:
  - name: priority
  - name: drf
- plugins:
  - name: conformance
"""
        ssn = _run_preempt(_tier_cluster(), conf)
        evicted = [e.task_uid for e in ssn.evictions]
        # conformance admits every Running candidate; the evict loop takes
        # the lowest task priority first until P fits
        assert len(evicted) >= 1, evicted


class TestPerEvictionDrfRecompute:
    def test_second_preemptor_task_blocked_by_updated_shares(self):
        """After the first eviction + pipeline, the preemptor's live share
        rises and the victim job's falls (drf.go:511-561); the second
        preemptor task's drf rule then rejects the remaining victims. A
        static per-cycle share snapshot would have allowed a second
        eviction."""
        ci = simple_cluster(n_nodes=1, node_cpu="3", node_mem="8Gi")
        v = build_job("default/v", min_available=1, priority=1)
        for i in range(3):
            t = build_task(f"v-{i}", cpu="1", memory=0)
            t.status = TaskStatus.RUNNING
            v.add_task(t)
            ci.nodes["n0"].add_task(t)
        ci.add_job(v)
        p = build_job("default/p", min_available=1, priority=1)
        for i in range(2):
            p.add_task(build_task(f"p-{i}", cpu="1", memory=0))
        ci.add_job(p)
        ssn = _run_preempt(ci, DRF_FIRST)
        # task p-0: ls = 1/3; v's what-if share 2/3 >= 1/3 -> evict one.
        # task p-1: ls = 2/3 (p now holds 1); v's what-if 1/3 < 2/3 - delta
        # -> no victim, no second eviction.
        assert len(ssn.evictions) == 1, [e.task_uid for e in ssn.evictions]
        assert "default/p-0" in ssn.pipelined
        assert "default/p-1" not in ssn.pipelined


class TestHDRFReclaim:
    def test_underserved_hierarchy_branch_reclaims(self):
        """dap-style reclaim: the drf hierarchy what-if rule (clone tree,
        add reclaimer, subtract candidate, compare queues — drf.go:377-449)
        lets a starving branch reclaim from an over-served one."""
        from volcano_tpu.api import QueueInfo
        from fixtures import build_node
        ci = simple_cluster(n_nodes=0)
        ci.add_node(build_node("n0", cpu="4", memory="8Gi"))
        del ci.queues["default"]
        ci.add_queue(QueueInfo("root-a", hierarchy="root/a",
                               hierarchy_weights="1/1", reclaimable=True))
        ci.add_queue(QueueInfo("root-b", hierarchy="root/b",
                               hierarchy_weights="1/1"))
        greedy = build_job("default/greedy", queue="root-a", min_available=1)
        for i in range(4):
            t = build_task(f"gr-{i}", cpu="1", memory=0)
            t.status = TaskStatus.RUNNING
            greedy.add_task(t)
            ci.nodes["n0"].add_task(t)
        ci.add_job(greedy)
        starv = build_job("default/starv", queue="root-b", min_available=1)
        starv.add_task(build_task("st-0", cpu="1", memory=0))
        ci.add_job(starv)
        conf = """
actions: "reclaim"
tiers:
- plugins:
  - name: drf
    enableHierarchy: true
"""
        ssn = Session(ci, parse_conf(conf))
        assert ssn.victim_tiers("reclaim") == (("drf_hdrf",),)
        ssn.run_preempt("reclaim")
        evicted = [e.task_uid for e in ssn.evictions]
        # root-b holds nothing, root-a holds everything: the what-if keeps
        # root-b strictly first after removing a greedy task -> reclaim
        assert len(evicted) >= 1
        assert all(uid.startswith("default/gr") for uid in evicted)
        assert "default/st-0" in ssn.pipelined


class TestIntraJobPreemption:
    def test_high_priority_task_preempts_own_jobs_low(self):
        """Phase 2 (preempt.go:145-186): a job's pending high-priority task
        evicts its own lower-priority Running task when no cross-job victim
        exists. Conf has priority WITHOUT gang in the tier (gang's
        same-job rule would empty the intersection, as in the
        reference)."""
        ci = simple_cluster(n_nodes=1, node_cpu="1", node_mem="2Gi")
        # min_available=2 with one Running + one Pending -> the job is
        # starving (underRequest), the phase-2 precondition
        j = build_job("default/j", min_available=2, priority=5)
        lo = build_task("lo-0", cpu="1", memory="1Gi", priority=1,
                        status=TaskStatus.RUNNING)
        j.add_task(lo)
        ci.nodes["n0"].add_task(lo)
        j.add_task(build_task("hi-0", cpu="1", memory="1Gi", priority=9))
        ci.add_job(j)
        conf = """
actions: "preempt"
tiers:
- plugins:
  - name: priority
"""
        ssn = _run_preempt(ci, conf)
        # phase 1 finds no cross-job victims (single job); run phase 2
        assert ssn.evictions == []
        ssn.run_preempt("preempt_intra")
        evicted = [e.task_uid for e in ssn.evictions]
        assert evicted == ["default/lo-0"], evicted
        assert "default/hi-0" in ssn.pipelined

    def test_gang_in_tier_blocks_intra_preemption(self):
        """With gang in the same tier the same-job candidates intersect to
        nothing (gang.go:83-103 equal job priority), matching the
        reference's no-op."""
        ci = simple_cluster(n_nodes=1, node_cpu="1", node_mem="2Gi")
        j = build_job("default/j", min_available=2, priority=5)
        lo = build_task("lo-0", cpu="1", memory="1Gi", priority=1,
                        status=TaskStatus.RUNNING)
        j.add_task(lo)
        ci.nodes["n0"].add_task(lo)
        j.add_task(build_task("hi-0", cpu="1", memory="1Gi", priority=9))
        ci.add_job(j)
        conf = """
actions: "preempt"
tiers:
- plugins:
  - name: priority
  - name: gang
"""
        ssn = _run_preempt(ci, conf)
        ssn.run_preempt("preempt_intra")
        assert ssn.evictions == []


class TestTdmBudgetInKernel:
    """The tdm disruption budget caps placement-path evictions per job
    (the Preemptable fn's maxVictims batching, tdm.go:219-229 + 304-340),
    enforced in-kernel via extras.job_victim_budget."""

    def _budget_cluster(self, budget_min_available):
        from volcano_tpu.api import PodGroupPhase
        # n0 carries no revocable-zone label: the tdm victim rule admits
        # preemptable Running tasks on NON-revocable nodes (tdm.go:199-218)
        ci = simple_cluster(n_nodes=1, node_cpu="8", node_mem="8Gi")
        victim = build_job("default/victim", min_available=1, priority=1,
                           preemptable=True,
                           budget_min_available=budget_min_available)
        for i in range(6):
            t = build_task(f"v-{i}", cpu="1", memory=0, preemptable=True)
            t.status = TaskStatus.RUNNING
            victim.add_task(t)
            ci.nodes["n0"].add_task(t)
        ci.add_job(victim)
        p = build_job("default/p", min_available=1, priority=50,
                      pod_group_phase=PodGroupPhase.INQUEUE)
        p.add_task(build_task("p-0", cpu="6", memory=0))
        ci.add_job(p)
        return ci

    CONF = """
actions: "preempt"
tiers:
- plugins:
  - name: priority
  - name: gang
  - name: tdm
    arguments:
      tdm.revocable-zone.rz1: 00:00-23:59
"""

    def test_budget_caps_evictions(self):
        """budget minAvailable=3 allows at most 3 evictions (6 running -
        3); the preemptor needs 4 -> it cannot fit and nothing commits
        (gang discard rolls the evictions back)."""
        ci = self._budget_cluster("3")
        ssn = _run_preempt(ci, self.CONF)
        assert len(ssn.evictions) == 0
        assert not ssn.pipelined

    def test_budget_allows_when_sufficient(self):
        """budget minAvailable=1 allows 5 evictions; the preemptor needs 4
        (2 idle + 4 freed = 6 cpu) -> it pipelines with exactly 4."""
        ci = self._budget_cluster("1")
        ssn = _run_preempt(ci, self.CONF)
        assert len(ssn.evictions) == 4
        assert "default/p-0" in ssn.pipelined

    def test_oracle_matches_budgeted_kernel(self):
        import jax
        from volcano_tpu.arrays import pack as _pack
        from volcano_tpu.ops.preempt import make_preempt_cycle
        from volcano_tpu.runtime.cpu_reference import preempt_cpu
        ci = self._budget_cluster("3")
        ssn = Session(ci, parse_conf(self.CONF))
        pcfg_kwargs = {}
        from volcano_tpu.ops.preempt import PreemptConfig
        pcfg = PreemptConfig(
            scoring=ssn.allocate_config(),
            tiers=ssn.victim_tiers("preempt"))
        extras = ssn.allocate_extras()
        T = np.asarray(ssn.snap.tasks.status).shape[0]
        veto = np.zeros(T, bool)
        skipm = np.zeros(T, bool)
        dev = jax.jit(make_preempt_cycle(pcfg))(ssn.snap, extras, veto,
                                                skipm)
        cpu = preempt_cpu(ssn.snap, extras, veto, skipm, pcfg)
        np.testing.assert_array_equal(np.asarray(dev.evicted),
                                      cpu["evicted"])
        np.testing.assert_array_equal(np.asarray(dev.task_mode),
                                      cpu["task_mode"])
