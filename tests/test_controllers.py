"""Controller tests — mirrors the reference's job state machine transitions
(pkg/controllers/job/job_state_test.go:1-1298), pod reconciliation
(job_controller_actions_test.go:1-562), queue controller
(queue_controller_test.go:1-312), and GC TTL (garbagecollector_test.go:1-385)."""

import pytest

from volcano_tpu.api.batch import (Command, Job, LifecyclePolicy, PodTemplate,
                                   TaskSpec, VolumeSpec)
from volcano_tpu.api.core import PodPhase
from volcano_tpu.api.queue_info import QueueInfo
from volcano_tpu.api.types import (BusAction, BusEvent, JobPhase,
                                   PodGroupPhase, QueueState)
from volcano_tpu.controllers.gc_controller import GarbageCollector
from volcano_tpu.runtime.system import VolcanoSystem
from volcano_tpu.webhooks import AdmissionError


def two_task_job(name="job1", replicas=(1, 2), **kw):
    return Job(name=name, tasks=[
        TaskSpec(name="ps", replicas=replicas[0],
                 template=PodTemplate(resources={"cpu": "1", "memory": "1Gi"})),
        TaskSpec(name="worker", replicas=replicas[1],
                 template=PodTemplate(resources={"cpu": "1", "memory": "1Gi"})),
    ], **kw)


def make_system(n_nodes=2):
    sys_ = VolcanoSystem()
    for i in range(n_nodes):
        sys_.add_node(f"n{i}", cpu="8", memory="16Gi")
    return sys_


class TestJobController:
    def test_sync_creates_podgroup_and_pods(self):
        sys_ = make_system()
        sys_.submit_job(two_task_job())
        sys_.reconcile()
        pg = sys_.api.podgroup_of_job("default/job1")
        assert pg is not None
        assert pg.min_member == 3  # defaulted minAvailable = total replicas
        assert pg.min_resources  # calcPGMinResources populated
        # pods are NOT created while the PodGroup is Pending (syncTask gate)
        assert sys_.pods_of("job1") == []
        # once the scheduler enqueues the group, pods appear
        pg.phase = PodGroupPhase.INQUEUE
        sys_.api.update("podgroups", pg)
        sys_.reconcile()
        assert len(sys_.pods_of("job1")) == 3

    def test_full_lifecycle_to_completed(self):
        sys_ = make_system()
        sys_.submit_job(two_task_job())
        for _ in range(3):
            sys_.tick()
        job = sys_.job("job1")
        assert job.status.state.phase == JobPhase.RUNNING
        assert job.status.running == 3
        for pod in sys_.pods_of("job1"):
            sys_.finish_pod(pod.key)
        sys_.reconcile()
        assert sys_.job("job1").status.state.phase == JobPhase.COMPLETED
        assert sys_.job("job1").status.succeeded == 3

    def test_scale_up_and_down(self):
        sys_ = make_system()
        sys_.submit_job(two_task_job())
        for _ in range(3):
            sys_.tick()
        job = sys_.job("job1")
        job.tasks[1].replicas = 4      # worker 2 -> 4
        sys_.api.update("jobs", job)
        sys_.tick()
        assert len(sys_.pods_of("job1")) == 5
        job.tasks[1].replicas = 1      # scale down
        sys_.api.update("jobs", job)
        sys_.reconcile()
        assert len(sys_.pods_of("job1")) == 2

    def test_pod_failed_policy_restart_job(self):
        sys_ = make_system()
        job = two_task_job(policies=[LifecyclePolicy(
            action=BusAction.RESTART_JOB, event=BusEvent.POD_FAILED)],
            max_retry=2)
        sys_.submit_job(job)
        for _ in range(3):
            sys_.tick()
        pod = sys_.pods_of("job1")[0]
        sys_.finish_pod(pod.key, exit_code=137)
        sys_.reconcile()
        job = sys_.job("job1")
        assert job.status.retry_count == 1
        # restarting kills pods, then next sync recreates them
        for _ in range(3):
            sys_.tick()
        assert sys_.job("job1").status.state.phase == JobPhase.RUNNING

    def test_max_retry_exhausted_fails_job(self):
        sys_ = make_system()
        job = two_task_job(policies=[LifecyclePolicy(
            action=BusAction.RESTART_JOB, event=BusEvent.POD_FAILED)],
            max_retry=1)
        sys_.submit_job(job)
        for _ in range(3):
            sys_.tick()
        for round_ in range(2):
            pods = sys_.pods_of("job1")
            running = [p for p in pods if p.phase == PodPhase.RUNNING]
            if not running:
                for _ in range(3):
                    sys_.tick()
                running = [p for p in sys_.pods_of("job1")
                           if p.phase == PodPhase.RUNNING]
            sys_.finish_pod(running[0].key, exit_code=1)
            sys_.reconcile()
        assert sys_.job("job1").status.state.phase == JobPhase.FAILED

    def test_exit_code_policy(self):
        sys_ = make_system()
        job = two_task_job(policies=[LifecyclePolicy(
            action=BusAction.ABORT_JOB, exit_code=42)])
        sys_.submit_job(job)
        for _ in range(3):
            sys_.tick()
        pod = sys_.pods_of("job1")[0]
        sys_.finish_pod(pod.key, exit_code=42)
        sys_.reconcile()
        assert sys_.job("job1").status.state.phase in (JobPhase.ABORTING,
                                                       JobPhase.ABORTED)

    def test_suspend_resume_via_commands(self):
        """vcctl suspend/resume path (SURVEY.md section 3.4 call stack)."""
        sys_ = make_system()
        sys_.submit_job(two_task_job())
        for _ in range(3):
            sys_.tick()
        sys_.suspend_job("job1")
        sys_.reconcile()
        job = sys_.job("job1")
        assert job.status.state.phase in (JobPhase.ABORTING, JobPhase.ABORTED)
        sys_.resume_job("job1")
        for _ in range(4):
            sys_.tick()
        assert sys_.job("job1").status.state.phase == JobPhase.RUNNING

    def test_job_plugins_create_artifacts(self):
        sys_ = make_system()
        job = two_task_job(plugins={"ssh": [], "svc": [], "env": []})
        sys_.submit_job(job)
        for _ in range(2):
            sys_.tick()
        assert sys_.api.get("secrets", "default/job1-ssh") is not None
        assert sys_.api.get("services", "default/job1") is not None
        cm = sys_.api.get("configmaps", "default/job1-svc")
        assert "job1-worker-1.job1" in cm.data["hosts"]
        pod = sys_.pods_of("job1")[0]
        assert pod.env.get("VC_JOB_NAME") == "job1"
        assert "VC_WORKER_HOSTS" in pod.env
        assert f"{job.name}-ssh" in pod.volumes

    def test_pvc_created_for_storage_volume(self):
        sys_ = make_system()
        job = two_task_job(volumes=[VolumeSpec(mount_path="/data",
                                               storage="1Gi")])
        sys_.submit_job(job)
        sys_.reconcile()
        assert sys_.api.get("pvcs", "default/job1-pvc-0") is not None


class TestAdmissionIntegration:
    def test_invalid_job_rejected_at_submit(self):
        sys_ = make_system()
        bad = Job(name="bad", min_available=10,
                  tasks=[TaskSpec(name="t", replicas=1)])
        with pytest.raises(AdmissionError):
            sys_.submit_job(bad)
        assert sys_.job("bad") is None

    def test_job_to_closed_queue_rejected(self):
        sys_ = make_system()
        sys_.api.create("queues", QueueInfo("closed-q", weight=1,
                                            state=QueueState.CLOSED))
        with pytest.raises(AdmissionError):
            sys_.submit_job(two_task_job(queue="closed-q"))


class TestQueueController:
    def test_close_queue_with_live_podgroups_goes_closing(self):
        sys_ = make_system()
        sys_.api.create("queues", QueueInfo("q1", weight=1))
        sys_.submit_job(two_task_job(queue="q1"))
        sys_.reconcile()
        sys_.submit_command(Command(name="close-q1", action=BusAction.CLOSE_QUEUE,
                                    target_name="q1", target_kind="Queue"))
        sys_.reconcile()
        assert sys_.api.get("queues", "q1").state == QueueState.CLOSING
        # delete the job -> podgroup gone -> queue closes
        sys_.api.delete("jobs", "default/job1")
        sys_.reconcile()
        assert sys_.api.get("queues", "q1").state == QueueState.CLOSED

    def test_reopen_queue(self):
        sys_ = make_system()
        sys_.api.create("queues", QueueInfo("q2", weight=1,
                                            state=QueueState.CLOSED))
        sys_.submit_command(Command(name="open-q2", action=BusAction.OPEN_QUEUE,
                                    target_name="q2", target_kind="Queue"))
        sys_.reconcile()
        assert sys_.api.get("queues", "q2").state == QueueState.OPEN


class TestPodGroupController:
    def test_bare_pod_adoption(self):
        from volcano_tpu.api.core import Pod
        sys_ = make_system()
        pod = Pod(name="bare", resources={"cpu": "1"})
        sys_.api.create("pods", pod)
        sys_.reconcile()
        assert pod.pod_group == "podgroup-bare"
        assert sys_.api.get("podgroups", "default/podgroup-bare") is not None

    def test_bare_pod_schedules_and_binds(self):
        from volcano_tpu.api.core import Pod
        sys_ = make_system()
        sys_.api.create("pods", Pod(name="bare", resources={"cpu": "1"}))
        for _ in range(3):
            sys_.tick()
        pod = sys_.api.get("pods", "default/bare")
        assert pod.node_name != ""
        assert pod.phase == PodPhase.RUNNING


class TestGarbageCollector:
    def test_ttl_cleanup(self):
        clock = {"now": 1000.0}
        sys_ = make_system()
        gc = next(c for c in sys_.controllers if c.name == "gc")
        gc.now = lambda: clock["now"]
        job = two_task_job(ttl_seconds_after_finished=60)
        sys_.submit_job(job)
        for _ in range(3):
            sys_.tick()
        for pod in sys_.pods_of("job1"):
            sys_.finish_pod(pod.key)
        sys_.reconcile()
        assert sys_.job("job1").status.state.phase == JobPhase.COMPLETED
        clock["now"] = sys_.job("job1").status.state.transition_time + 30
        sys_.reconcile()
        assert sys_.job("job1") is not None  # not expired yet
        clock["now"] += 31
        sys_.reconcile()
        assert sys_.job("job1") is None      # deleted
        assert sys_.pods_of("job1") == []    # foreground propagation
