"""Metrics exposition parity (VERDICT r4 #7).

The exposition must parse as Prometheus text format, expose cumulative
histogram bucket series (le labels + +Inf), and carry the reference's
queue/namespace gauge families (pkg/scheduler/metrics/queue.go:28-284,
namespace.go:28-63) wired from session close.
"""

import re

from volcano_tpu.framework import parse_conf
from volcano_tpu.metrics import METRICS
from volcano_tpu.runtime.fake_cluster import FakeCluster
from volcano_tpu.runtime.scheduler import Scheduler

from fixtures import build_job, build_task, simple_cluster

LINE_RE = re.compile(
    r'^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)'
    r'(\{(?P<labels>[^}]*)\})? (?P<value>[^ ]+)$')
LABEL_RE = re.compile(r'^[a-zA-Z_][a-zA-Z0-9_]*="[^"]*"$')


def parse_exposition(text):
    """Minimal Prometheus text parser: every line must match the format."""
    out = {}
    for line in text.strip().splitlines():
        if line.startswith("#"):
            continue
        m = LINE_RE.match(line)
        assert m, f"unparseable exposition line: {line!r}"
        labels = {}
        if m.group("labels"):
            for part in m.group("labels").split(","):
                assert LABEL_RE.match(part), f"bad label {part!r} in {line!r}"
                k, v = part.split("=", 1)
                labels[k] = v.strip('"')
        out[(m.group("name"), tuple(sorted(labels.items())))] = float(
            m.group("value"))
    return out


QUEUE_FAMILIES = [
    "volcano_queue_allocated_milli_cpu",
    "volcano_queue_allocated_memory_bytes",
    "volcano_queue_request_milli_cpu",
    "volcano_queue_request_memory_bytes",
    "volcano_queue_deserved_milli_cpu",
    "volcano_queue_deserved_memory_bytes",
    "volcano_queue_share",
    "volcano_queue_weight",
    "volcano_queue_overused",
    "volcano_queue_pod_group_inqueue_count",
    "volcano_queue_pod_group_pending_count",
    "volcano_queue_pod_group_running_count",
    "volcano_queue_pod_group_unknown_count",
]
NAMESPACE_FAMILIES = [
    "volcano_namespace_share",
    "volcano_namespace_weight",
    "volcano_namespace_weighted_share",
]


class TestMetricsParity:
    def setup_method(self):
        METRICS.reset()

    def run_cycle(self):
        ci = simple_cluster(n_nodes=4, node_cpu="8", node_mem="16Gi")
        for j in range(3):
            job = build_job(f"default/j{j}", min_available=1,
                            creation_timestamp=float(j))
            for t in range(2):
                job.add_task(build_task(f"j{j}-t{t}", cpu="1", memory="1Gi"))
            ci.add_job(job)
        sched = Scheduler(FakeCluster(ci), conf=parse_conf("""
actions: "enqueue, allocate"
tiers:
- plugins:
  - name: gang
  - name: proportion
  - name: binpack
"""))
        sched.run_once()
        return sched

    def test_exposition_parses_and_has_buckets(self):
        self.run_cycle()
        text = METRICS.exposition()
        parsed = parse_exposition(text)
        # e2e histogram: bucket series present, cumulative, +Inf == count
        buckets = {k: v for k, v in parsed.items()
                   if k[0] == "volcano_e2e_scheduling_latency_"
                   "milliseconds_bucket"}
        assert buckets, "no bucket lines in exposition"
        by_le = sorted(
            ((float("inf") if dict(k[1])["le"] == "+Inf"
              else float(dict(k[1])["le"])), v)
            for k, v in buckets.items())
        values = [v for _le, v in by_le]
        assert values == sorted(values), "bucket series not cumulative"
        count = parsed[("volcano_e2e_scheduling_latency_milliseconds_count",
                        ())]
        assert values[-1] == count
        # labeled histograms keep their labels alongside le
        action = [k for k in parsed
                  if k[0] == "volcano_action_scheduling_latency_"
                  "microseconds_bucket"]
        assert action and all(
            dict(k[1]).get("action") for k in action)
        # plugin open/close latencies recorded (framework.go:47-60)
        assert any(
            k[0] == "volcano_plugin_scheduling_latency_microseconds_count"
            and dict(k[1]).get("event") == "OnSessionOpen"
            for k in parsed)

    def test_queue_and_namespace_families(self):
        self.run_cycle()
        parsed = parse_exposition(METRICS.exposition())
        for fam in QUEUE_FAMILIES:
            keys = [k for k in parsed if k[0] == fam]
            assert keys, f"missing family {fam}"
            assert all(dict(k[1]).get("queue") == "default" for k in keys)
        for fam in NAMESPACE_FAMILIES:
            keys = [k for k in parsed if k[0] == fam]
            assert keys, f"missing family {fam}"
            assert all(dict(k[1]).get("namespace_name") for k in keys)
        # proportion deserved flows into the gauge — water-filling caps
        # deserved at the queue's request (6 tasks x 1 cpu = 6000 milli)
        assert parsed[("volcano_queue_deserved_milli_cpu",
                       (("queue", "default"),))] == 6000.0
        assert parsed[("volcano_queue_weight",
                       (("queue", "default"),))] == 1.0


class TestMetricsSatellites:
    """ISSUE 3 satellites: per-metric bucket sets, labeled counters,
    HELP/TYPE exposition metadata."""

    def setup_method(self):
        METRICS.reset()

    def test_microsecond_histograms_have_microsecond_buckets(self):
        # a 50 ms action used to land in +Inf (the shared 5..10000 series
        # read microseconds against millisecond bounds)
        METRICS.observe_action("allocate", 0.050)       # 50000 us
        METRICS.observe_plugin("gang", "OnSessionOpen", 0.2)  # 200000 us
        parsed = parse_exposition(METRICS.exposition())
        a = {dict(k[1])["le"]: v for k, v in parsed.items()
             if k[0] == "volcano_action_scheduling_latency_"
             "microseconds_bucket"}
        assert a["50000"] == 1 and a["25000"] == 0
        p = {dict(k[1])["le"]: v for k, v in parsed.items()
             if k[0] == "volcano_plugin_scheduling_latency_"
             "microseconds_bucket"}
        assert p["250000"] == 1 and p["100000"] == 0
        # millisecond histograms keep the millisecond series
        METRICS.observe_cycle(0.050)                    # 50 ms
        parsed = parse_exposition(METRICS.exposition())
        e = {dict(k[1])["le"]: v for k, v in parsed.items()
             if k[0] == "volcano_e2e_scheduling_latency_"
             "milliseconds_bucket"}
        assert e["50"] == 1

    def test_counter_labels(self):
        METRICS.inc("schedule_attempts_total", labels={"result": "scheduled"})
        METRICS.inc("schedule_attempts_total", 2,
                    labels={"result": "unschedulable"})
        METRICS.inc("unschedule_task_count", 3, labels={"reason": "job_failed"})
        METRICS.inc("plain_counter")            # bare name still works
        parsed = parse_exposition(METRICS.exposition())
        assert parsed[("volcano_schedule_attempts_total",
                       (("result", "scheduled"),))] == 1.0
        assert parsed[("volcano_schedule_attempts_total",
                       (("result", "unschedulable"),))] == 2.0
        assert parsed[("volcano_unschedule_task_count",
                       (("reason", "job_failed"),))] == 3.0
        assert parsed[("volcano_plain_counter", ())] == 1.0
        assert METRICS.counter_value("schedule_attempts_total",
                                     {"result": "scheduled"}) == 1.0

    def test_help_and_type_lines(self):
        METRICS.inc("schedule_attempts_total", labels={"result": "scheduled"})
        METRICS.set_gauge("queue_share", "default", 0.5)
        METRICS.observe_cycle(0.01)
        text = METRICS.exposition()
        lines = text.splitlines()
        typed = {}
        for line in lines:
            if line.startswith("# TYPE "):
                _h, _t, name, mtype = line.split(" ")
                typed[name] = mtype
        assert typed["volcano_schedule_attempts_total"] == "counter"
        assert typed["volcano_queue_share"] == "gauge"
        assert typed["volcano_e2e_scheduling_latency_milliseconds"] \
            == "histogram"
        # every TYPE has a HELP partner, emitted before the first sample
        for name, mtype in typed.items():
            assert any(ln.startswith(f"# HELP {name} ") for ln in lines)
            first_meta = min(i for i, ln in enumerate(lines)
                             if ln.startswith(f"# HELP {name} "))
            sample_idx = [i for i, ln in enumerate(lines)
                          if ln.startswith(name) and not ln.startswith("#")]
            assert sample_idx and first_meta < min(sample_idx)
        # sample line format unchanged (parser above already enforces it)
        parsed = parse_exposition(text)
        assert parsed[("volcano_queue_share", (("queue", "default"),))] == 0.5
