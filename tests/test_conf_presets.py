"""Shipped policy presets drive full scheduler cycles end-to-end.

Every conf/*.conf must parse and schedule a small workload through the
runtime loop — the preset-is-a-silent-no-op failure mode (round-3 finding
on the dap preset's ScaleAllocatable block) stays caught here.
"""

import glob
import os

import numpy as np
import pytest

from volcano_tpu.api import QueueInfo
from volcano_tpu.framework import parse_conf
from volcano_tpu.runtime import FakeCluster, Scheduler

from fixtures import build_job, build_task, simple_cluster

PRESETS = sorted(glob.glob(os.path.join(
    os.path.dirname(__file__), "..", "conf", "*.conf")))
# the ci preset compiles the full 5-action pipeline (~20s on one core):
# slow-marked so tier-1 keeps the other presets' e2e coverage in budget
_PRESET_PARAMS = [
    pytest.param(p, marks=pytest.mark.slow)
    if os.path.basename(p) == "volcano-scheduler-ci.conf" else p
    for p in PRESETS]


def preset_cluster():
    ci = simple_cluster(n_nodes=2, node_cpu="4")
    ci.add_queue(QueueInfo("root-sci", weight=2, hierarchy="root/sci",
                           hierarchy_weights="1/2"))
    for j, queue in enumerate(["default", "root-sci", "default"]):
        job = build_job(f"default/j{j}", queue=queue, min_available=1,
                        creation_timestamp=float(j))
        job.add_task(build_task(f"j{j}-t0", cpu="1", memory="1Gi"))
        ci.add_job(job)
    return ci


class TestPresets:
    @pytest.mark.parametrize("path", _PRESET_PARAMS,
                             ids=[os.path.basename(p) for p in PRESETS])
    def test_preset_schedules(self, path):
        with open(path) as f:
            conf = parse_conf(f.read())
        sched = Scheduler(FakeCluster(preset_cluster()), conf=conf)
        sched.run_once()
        assert len(sched.cluster.binds) >= 1, path

    def test_dap_preset_scales_allocatable_and_orders_hdrf(self):
        """The dap preset's ScaleAllocatable block must actually shrink
        capacity AND its hdrf tiers must produce hierarchy-ordered
        placement (both were silent no-ops in earlier rounds)."""
        from volcano_tpu.framework.session import Session
        with open(os.path.join(os.path.dirname(__file__), "..", "conf",
                               "volcano-scheduler-dap.conf")) as f:
            conf = parse_conf(f.read())
        ci = preset_cluster()
        ssn = Session(ci, conf)
        cfg = ssn.allocate_config()
        assert cfg.enable_hdrf
        alloc = np.asarray(ssn.snap.nodes.allocatable)
        # 4 cpu * 0.8 = 3200 millicores
        assert alloc[0, 0] == pytest.approx(3200.0)
        # the packed hierarchy tree has the sci branch materialized
        assert int(np.asarray(ssn.hierarchy.valid).sum()) >= 2
