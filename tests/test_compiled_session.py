"""The compiled session (framework/compiled_session.py): one jittable
program covering kernel + array-level plugin extras must make the same
decisions as the object Session pipeline under the same policy."""

import numpy as np
import jax
import pytest

from volcano_tpu.arrays import pack
from volcano_tpu.framework import parse_conf
from volcano_tpu.framework.compiled_session import (
    allocate_config_from_conf, make_conf_cycle)
from volcano_tpu.runtime import FakeCluster, Scheduler

from fixtures import build_job, build_task, simple_cluster
from volcano_tpu.api import QueueInfo

DEFAULT_CONF = open("conf/volcano-scheduler.conf").read()


def contended_cluster():
    """Two queues with different weights, more demand than capacity, so
    proportion's deserved and drf's shares actually matter."""
    ci = simple_cluster(n_nodes=2, node_cpu="4")
    ci.add_queue(QueueInfo("batch", weight=3))
    for j in range(4):
        queue = "default" if j % 2 == 0 else "batch"
        job = build_job(f"default/j{j}", queue=queue, min_available=2)
        for t in range(2):
            job.add_task(build_task(f"j{j}-t{t}", cpu="1", memory="1Gi"))
        ci.add_job(job)
    return ci


class TestCompiledSession:
    def test_config_derived_from_conf(self):
        cfg = allocate_config_from_conf(parse_conf(DEFAULT_CONF))
        assert cfg.enable_gang
        assert cfg.binpack_weight == 1.0          # binpack plugin default
        assert cfg.least_allocated_weight == 1.0  # nodeorder default

    def test_matches_session_pipeline(self):
        ci = contended_cluster()
        # object-session path: full Scheduler allocate under the default conf
        sched = Scheduler(FakeCluster(ci.clone()),
                          conf=parse_conf(DEFAULT_CONF))
        ssn = sched.run_once()
        session_binds = dict(sched.cluster.binds)

        # compiled path: same conf, one program
        snap, maps = pack(ci)
        result = jax.jit(make_conf_cycle(DEFAULT_CONF))(snap)
        compiled_binds = {}
        task_mode = np.asarray(result.task_mode)
        task_node = np.asarray(result.task_node)
        for uid, ti in maps.task_index.items():
            if task_mode[ti] == 1:
                compiled_binds[uid] = maps.node_names[task_node[ti]]
        assert compiled_binds == session_binds

    def test_conf_proven_batching_matches_sequential(self):
        """A batchable conf (no proportion, no drf dynamics) derives
        batch_jobs=8; its decisions must equal the sequential K=1 cycle
        on a contended snapshot."""
        import dataclasses
        batchable_conf = """
actions: "allocate"
tiers:
- plugins:
  - name: gang
  - name: binpack
"""
        cfg = allocate_config_from_conf(parse_conf(batchable_conf))
        assert cfg.batch_jobs == 8
        ci = contended_cluster()
        snap, maps = pack(ci)
        from volcano_tpu.ops.allocate_scan import (AllocateExtras,
                                                   make_allocate_cycle)
        extras = AllocateExtras.neutral(snap)
        batched = jax.jit(make_allocate_cycle(dataclasses.replace(
            cfg, use_pallas="interpret")))(snap, extras)
        seq = jax.jit(make_allocate_cycle(dataclasses.replace(
            cfg, use_pallas=False, batch_jobs=1)))(snap, extras)
        np.testing.assert_array_equal(np.asarray(batched.task_node),
                                      np.asarray(seq.task_node))
        np.testing.assert_array_equal(np.asarray(batched.task_mode),
                                      np.asarray(seq.task_mode))
        np.testing.assert_array_equal(np.asarray(batched.job_ready),
                                      np.asarray(seq.job_ready))
        # a conf with proportion carries dynamic ordering keys: it must
        # NOT take the static-keys K-section path — derive_batching routes
        # it to the in-kernel-selection path (batch_rounds) instead
        dyn_cfg = allocate_config_from_conf(parse_conf(DEFAULT_CONF))
        assert dyn_cfg.batch_rounds > 0
        assert cfg.batch_rounds == 0

    def test_hdrf_conf_compiles(self):
        conf = open("conf/volcano-scheduler-dap.conf").read()
        ci = contended_cluster()
        snap, maps = pack(ci)
        result = jax.jit(make_conf_cycle(conf))(snap)
        assert int(np.asarray(result.task_mode > 0).sum()) > 0

    def test_sidecar_serves_conf_policy(self):
        from volcano_tpu import native
        if not native.available():
            pytest.skip("native packer unavailable")
        from volcano_tpu.runtime.sidecar import SidecarClient, SidecarServer
        server = SidecarServer(conf=DEFAULT_CONF)
        server.serve_in_thread()
        try:
            client = SidecarClient(*server.address)
            out = client.schedule(contended_cluster())
            sched = Scheduler(FakeCluster(contended_cluster()),
                              conf=parse_conf(DEFAULT_CONF))
            sched.run_once()
            assert out["binds"].keys() == dict(sched.cluster.binds).keys()
            client.close()
        finally:
            server.shutdown()
