"""Elastic mesh degradation (ISSUE 20 acceptance).

- Stateful Backoff edges: next() escalation, cap, reset(), jitter bounds.
- DeviceHealthRegistry: a lone strike stays transient; N-in-a-window
  quarantines and halves the width cap; probation regrow releases and
  doubles; a probation strike re-quarantines immediately (flap) and
  escalates the interval; snapshot/restore round-trips.
- mesh_for_nodes is keyed by the live device tuple, clamped by the
  registry's shrink cap, and invalidated on quarantine/regrow.
- Session.drop_sharded_residency makes the next sharded dispatch re-fuse
  from source truth, decision-neutrally.
- Transient (anonymous) faults on the sharded Scheduler still walk
  sync-retry -> cpu-oracle WITHOUT quarantining anything, and the
  cooldown re-arms the sharded pipelined path.
- Fleet bucket keys include the current serving mesh width, so a
  health-driven width change re-buckets sharded tenants.
- Scheduler checkpoints carry the health registry state.
"""

import jax
import numpy as np
import pytest

from volcano_tpu.framework import parse_conf
from volcano_tpu.metrics import METRICS
from volcano_tpu.parallel import (HEALTH, DeviceHealthRegistry,
                                  failed_devices, invalidate_mesh_cache,
                                  mesh_for_nodes)
from volcano_tpu.runtime.backoff import Backoff

from test_delta_pipeline import PARITY_CONF, _PARITY_BODY
from test_runtime_incremental import build_cluster, churn

SHARDED_CONF = parse_conf("sharding: true\nsharding_devices: 8\n"
                          + _PARITY_BODY)


def _loss(*ids):
    exc = RuntimeError("planted device fault")
    exc.device_ids = tuple(ids)
    return exc


@pytest.fixture()
def health_defaults():
    """Pin the registry to the documented default knobs for the test and
    restore a clean env-default registry afterwards (no quarantine may
    leak into other tests' meshes)."""
    HEALTH.configure(strikes=2, window=8, probation=3, flap_window=6)
    try:
        yield HEALTH
    finally:
        HEALTH.configure()


class TestBackoffStateful:
    def test_next_escalates_and_caps(self):
        bo = Backoff(base=3.0, cap=12.0, factor=2.0, jitter=0.0, seed=0)
        assert [bo.next() for _ in range(5)] == [3.0, 6.0, 12.0, 12.0,
                                                12.0]

    def test_reset_restores_initial_interval(self):
        bo = Backoff(base=3.0, cap=48.0, factor=2.0, jitter=0.0, seed=0)
        assert (bo.next(), bo.next()) == (3.0, 6.0)
        bo.reset()
        assert bo.next() == 3.0

    def test_peek_does_not_consume(self):
        bo = Backoff(base=2.0, cap=32.0, factor=2.0, jitter=0.5, seed=7)
        assert bo.peek() == bo.peek() == 2.0     # undithered, stateless
        bo.next()
        assert bo.peek() == 4.0

    def test_jitter_within_declared_bounds(self):
        bo = Backoff(base=1.0, cap=1000.0, factor=2.0, jitter=0.25,
                     seed=11)
        for attempt in range(8):
            undithered = min(bo.cap, bo.base * bo.factor ** attempt)
            d = bo.delay(attempt)
            assert 0.75 * undithered <= d <= 1.25 * undithered, (attempt,
                                                                 d)

    def test_cap_respected_after_many_steps(self):
        bo = Backoff(base=0.5, cap=8.0, factor=2.0, jitter=0.25, seed=3)
        for _ in range(50):
            assert bo.next() <= 8.0 * 1.25
        assert bo.peek() == 8.0


class TestFailedDevices:
    def test_attribution_walks_cause_chain(self):
        inner = _loss(3, 5)
        try:
            raise RuntimeError("wrapper") from inner
        except RuntimeError as outer:
            assert failed_devices(outer) == (3, 5)

    def test_anonymous_exception_names_nothing(self):
        assert failed_devices(RuntimeError("transient blip")) == ()


class TestHealthRegistry:
    def test_single_strike_stays_transient(self, health_defaults):
        reg = health_defaults
        assert reg.note_failure(_loss(2), cycle=1, serving_width=8) == ()
        assert not reg.quarantined and reg.width_cap is None

    def test_strikes_in_window_quarantine_and_halve(self, health_defaults):
        reg = health_defaults
        reg.note_failure(_loss(2), cycle=1, serving_width=8)
        assert reg.note_failure(_loss(2), cycle=2, serving_width=8) == (2,)
        assert 2 in reg.quarantined
        assert reg.width_cap == 4            # halved, not pow2-of-healthy
        assert reg.generation == 1

    def test_strike_outside_window_ages_out(self, health_defaults):
        reg = health_defaults
        reg.note_failure(_loss(2), cycle=0, serving_width=8)
        assert reg.note_failure(_loss(2), cycle=20, serving_width=8) == ()
        assert not reg.quarantined

    def test_repeated_loss_keeps_descending(self, health_defaults):
        reg = health_defaults
        for c in (1, 2):
            reg.note_failure(_loss(2), cycle=c, serving_width=8)
        for c in (3, 4):
            reg.note_failure(_loss(5), cycle=c, serving_width=4)
        assert reg.width_cap == 2            # 8 -> 4 -> 2, never stuck

    def test_regrow_releases_on_probation_and_doubles(self,
                                                      health_defaults):
        reg = health_defaults
        for c in (1, 2):
            reg.note_failure(_loss(2), cycle=c, serving_width=8)
        gen = reg.generation
        assert reg.tick(3) is None           # interval = probation = 3
        step = reg.tick(5)                   # quarantined at 2, regrow 2+3
        assert step is not None and step["released"] == [2]
        assert reg.width_cap is None         # 4*2 >= 8 devices: cap off
        assert not reg.quarantined
        assert reg.generation == gen + 1

    def test_flap_requarantines_immediately_and_escalates(
            self, health_defaults):
        reg = health_defaults
        for c in (1, 2):
            reg.note_failure(_loss(2), cycle=c, serving_width=8)
        assert reg.probation_interval == 3
        reg.tick(5)                          # released on probation
        # ONE strike inside the flap window re-quarantines
        assert reg.note_failure(_loss(2), cycle=6, serving_width=8) == (2,)
        assert reg.quarantined[2]["reason"] == "flap"
        assert reg.probation_interval == 6   # backoff escalated, no reset

    def test_probation_survivor_rearms_strike_budget(self,
                                                     health_defaults):
        reg = health_defaults
        for c in (1, 2):
            reg.note_failure(_loss(2), cycle=c, serving_width=8)
        reg.tick(5)
        reg.tick(20)                         # probation window long past
        assert reg.note_failure(_loss(2), cycle=21,
                                serving_width=8) == ()  # transient again

    def test_snapshot_restore_roundtrip(self, health_defaults):
        reg = health_defaults
        for c in (1, 2):
            reg.note_failure(_loss(2), cycle=c, serving_width=8)
        snap = reg.snapshot()
        other = DeviceHealthRegistry()
        other.configure(strikes=2, window=8, probation=3, flap_window=6)
        other.restore(snap)
        assert other.snapshot() == snap
        assert other.width_cap == 4 and 2 in other.quarantined

    def test_healthy_devices_filters_quarantined(self, health_defaults):
        reg = health_defaults
        victim = jax.devices()[0].id
        for c in (1, 2):
            reg.note_failure(_loss(victim), cycle=c, serving_width=8)
        assert victim not in {d.id for d in reg.healthy_devices()}
        assert len(reg.healthy_devices()) == len(jax.devices()) - 1


class TestMeshHealthIntegration:
    def test_mesh_cache_keyed_by_device_tuple(self, health_defaults):
        m1 = mesh_for_nodes(128, 2)
        assert mesh_for_nodes(128, 2) is m1          # cache hit
        victim = jax.devices()[0].id
        for c in (1, 2):
            HEALTH.note_failure(_loss(victim), cycle=c, serving_width=8)
        m2 = mesh_for_nodes(128, 2)
        assert m2 is not m1
        assert victim not in {d.id for d in m2.devices.flat}

    def test_width_cap_clamps_mesh(self, health_defaults):
        for c in (1, 2):
            HEALTH.note_failure(_loss(jax.devices()[7].id), cycle=c,
                                serving_width=8)
        assert HEALTH.width_cap == 4
        assert int(mesh_for_nodes(128, 8).devices.size) == 4

    def test_invalidate_drops_and_rebuilds_entries(self):
        from volcano_tpu.parallel.sharding import _MESH_CACHE
        m1 = mesh_for_nodes(128, 2)
        assert _MESH_CACHE
        invalidate_mesh_cache()
        assert not _MESH_CACHE
        m2 = mesh_for_nodes(128, 2)     # same healthy set: same devices
        assert [d.id for d in m2.devices.flat] == \
               [d.id for d in m1.devices.flat]
        assert len(_MESH_CACHE) == 1

    def test_fleet_bucket_key_tracks_mesh_width(self, health_defaults):
        from volcano_tpu.arrays import pack
        from volcano_tpu.fleet import bucket_key
        from volcano_tpu.ops.allocate_scan import (AllocateConfig,
                                                   AllocateExtras,
                                                   derive_batching)
        snap, _maps = pack(build_cluster(n_nodes=4, n_jobs=4))
        tree = (snap, AllocateExtras.neutral(snap))
        cfg = derive_batching(AllocateConfig(binpack_weight=1.0,
                                             enable_gpu=False),
                              has_proportion=False)
        key_full = bucket_key(cfg, tree, sharding=True)
        for c in (1, 2):
            HEALTH.note_failure(_loss(jax.devices()[0].id), cycle=c,
                                serving_width=4)
        key_shrunk = bucket_key(cfg, tree, sharding=True)
        assert key_full != key_shrunk        # width change re-buckets
        w_full = dict([key_full[-1]])["mesh_width"]
        w_shrunk = dict([key_shrunk[-1]])["mesh_width"]
        assert w_shrunk < w_full
        assert w_shrunk == HEALTH.width_cap  # clamped by the registry
        # unsharded tenants never key on the mesh
        assert bucket_key(cfg, tree) == bucket_key(cfg, tree)


class TestSessionRemesh:
    def test_drop_sharded_residency_refuses_decision_neutral(self):
        from volcano_tpu.framework.session import Session
        HEALTH.configure()
        try:
            ci = build_cluster(n_nodes=8, n_jobs=10)
            ssn = Session(ci.clone(), SHARDED_CONF)
            ref = ssn.run_allocate()
            ref_binds = sorted((b.task_uid, b.node_name)
                               for b in ssn.binds)
            assert ssn._sharded_ids           # residency was sharded
            dropped = ssn.drop_sharded_residency()
            assert dropped >= 1 and not ssn._sharded_ids
            ssn._reset_cycle_state()
            again = ssn.run_allocate()        # cold re-fuse from truth
            assert sorted((b.task_uid, b.node_name)
                          for b in ssn.binds) == ref_binds
            np.testing.assert_array_equal(np.asarray(again.task_node),
                                          np.asarray(ref.task_node))
        finally:
            HEALTH.configure()


class TestSchedulerTransientLadder:
    def test_anonymous_faults_walk_oracle_without_quarantine(self):
        """Satellite acceptance: repeated backend_loss (transient, no
        device attribution) on the SHARDED Scheduler walks sync-retry ->
        cpu-oracle exactly as before the elastic-mesh rung landed — no
        quarantine, no shrink — and the cooldown re-arms the sharded
        pipelined path."""
        import contextlib

        from volcano_tpu.chaos import FaultInjector, FaultPlan, chaos
        from volcano_tpu.runtime.fake_cluster import FakeCluster
        from volcano_tpu.runtime.scheduler import Scheduler
        from test_delta_pipeline import decisions_sha, digest

        def run(plan, cycles=8):
            HEALTH.configure()
            cluster = FakeCluster(build_cluster(n_nodes=8, n_jobs=10))
            sched = Scheduler(cluster, conf=SHARDED_CONF, pipeline=True)
            inj = FaultInjector(plan) if plan else None
            ctx = chaos(inj) if inj else contextlib.nullcontext()
            digests = []
            with ctx:
                for c in range(cycles):
                    out = sched.run_once(now=1000.0 + c)
                    rec = sched.drain(now=1000.0 + c) or out
                    digests.append(digest(rec))
                    churn(cluster, c, arrivals=True)
            return decisions_sha(digests), sched, inj

        try:
            clean_sha, _, _ = run(None)
            # both faults at cycle 1: dispatch fails AND the sync retry
            # fails; with no device attribution the mesh rung must pass
            plan = FaultPlan(seed=2, cycles=2, kinds=("backend_loss",),
                             per_kind=2)
            assert [f.cycle for f in plan.faults] == [1, 1]
            shrinks0 = METRICS.counter_total("mesh_shrink_total")
            sha, sched, inj = run(plan)
            assert len(inj.fired) == 2
            assert sha == clean_sha
            flights = sched.flight.snapshots()
            degr = [e.get("degradation", 0) for e in flights]
            assert 3 in degr                  # oracle rung reached
            assert not HEALTH.quarantined     # nothing quarantined
            assert METRICS.counter_total("mesh_shrink_total") == shrinks0
            # cooldown re-armed the sharded path: the tail cycles serve
            # on the full mesh at degradation 0 again
            assert degr[-1] == 0
            assert flights[-1].get("mesh_devices") == 8
        finally:
            HEALTH.configure()


class TestCheckpointHealth:
    def test_checkpoint_carries_health_state(self, tmp_path):
        from volcano_tpu.runtime.fake_cluster import FakeCluster
        from volcano_tpu.runtime.scheduler import Scheduler
        try:
            HEALTH.configure(strikes=2, window=8, probation=3,
                             flap_window=6)
            for c in (1, 2):
                HEALTH.note_failure(_loss(6), cycle=c, serving_width=8)
            want = HEALTH.snapshot()
            sched = Scheduler(FakeCluster(build_cluster(4, 4)),
                              conf=SHARDED_CONF, pipeline=False)
            path = str(tmp_path / "sched.ckpt")
            sched.checkpoint(path)

            HEALTH.configure(strikes=2, window=8, probation=3,
                             flap_window=6)           # wipe live state
            assert not HEALTH.quarantined
            sched2 = Scheduler(FakeCluster(build_cluster(4, 4)),
                               conf=SHARDED_CONF, pipeline=False)
            assert sched2.restore(path) == "restored"
            got = HEALTH.snapshot()
            # generation restarts per process; everything durable matches
            assert {k: v for k, v in got.items() if k != "generation"} \
                == {k: v for k, v in want.items() if k != "generation"}
            assert 6 in HEALTH.quarantined and HEALTH.width_cap == 4
        finally:
            HEALTH.configure()


@pytest.mark.slow
class TestMeshlossProbe:
    """The full probe (clean + fault runs, three GSPMD widths) rides the
    slow tail; tier-1 covers it via ``chaos --smoke --meshloss``."""

    def test_loss_leg_green(self):
        from volcano_tpu.chaos.meshloss import (check_loss_leg,
                                                run_meshloss_probe)
        report = run_meshloss_probe()
        assert check_loss_leg(report) == [], report

    def test_flap_leg_green(self):
        from volcano_tpu.chaos.meshloss import (check_flap_leg,
                                                run_meshloss_probe)
        report = run_meshloss_probe(flap=True)
        assert check_flap_leg(report) == [], report
