"""Webhook manager HTTP surface: AdmissionReview round trips + registration.

Reference: cmd/webhook-manager/app/server.go:72-150 (HTTP serving of every
admission path) and its self-registration of webhook configurations.
"""

import yaml

from volcano_tpu.webhooks.server import (WebhookManager, apply_patch,
                                         submit_review)

JOB_MANIFEST = yaml.safe_load("""
apiVersion: batch.volcano.sh/v1alpha1
kind: Job
metadata:
  name: mpi-e2e
  namespace: default
spec:
  minAvailable: 0
  tasks:
    - replicas: 2
      template:
        spec:
          containers:
            - name: worker
              resources:
                requests:
                  cpu: "1"
""")


class TestWebhookHTTP:
    def setup_method(self):
        self.mgr = WebhookManager()
        self.mgr.serve_in_thread()

    def teardown_method(self):
        self.mgr.shutdown()

    def test_job_submission_through_http(self):
        """The full admission flow a kube-apiserver performs: mutate (apply
        the returned JSONPatch), then validate the patched object."""
        out = submit_review(self.mgr.url("/jobs/mutate"), "CREATE",
                            JOB_MANIFEST)
        assert out["response"]["allowed"]
        patched = apply_patch(JOB_MANIFEST, out)
        # mutate_job defaults (mutate_job.go:49-200)
        assert patched["spec"]["queue"] == "default"
        assert patched["spec"]["schedulerName"] == "volcano"
        assert patched["spec"]["maxRetry"] == 3
        assert patched["spec"]["minAvailable"] == 2
        assert patched["spec"]["tasks"][0]["name"] == "default0"
        out = submit_review(self.mgr.url("/jobs/validate"), "CREATE", patched)
        assert out["response"]["allowed"], out

    def test_invalid_job_denied_with_message(self):
        bad = dict(JOB_MANIFEST, spec=dict(JOB_MANIFEST["spec"],
                                           minAvailable=5))
        out = submit_review(self.mgr.url("/jobs/validate"), "CREATE", bad)
        assert not out["response"]["allowed"]
        assert "minAvailable" in out["response"]["status"]["message"]

    def test_job_update_immutability(self):
        old = apply_patch(JOB_MANIFEST,
                          submit_review(self.mgr.url("/jobs/mutate"),
                                        "CREATE", JOB_MANIFEST))
        new = apply_patch(old, {"response": {}})
        new["spec"]["queue"] = "other"
        out = submit_review(self.mgr.url("/jobs/validate-update"), "UPDATE",
                            new, old=old)
        assert not out["response"]["allowed"]
        assert "queue" in out["response"]["status"]["message"]

    def test_queue_mutate_and_delete_protection(self):
        queue = {"apiVersion": "scheduling.volcano.sh/v1beta1",
                 "kind": "Queue",
                 "metadata": {"name": "q1"},
                 "spec": {}}
        out = submit_review(self.mgr.url("/queues/mutate"), "CREATE", queue)
        patched = apply_patch(queue, out)
        assert patched["spec"]["weight"] == 1
        assert patched["status"]["state"] == "Open"
        # default queue can never be deleted (validate_queue.go delete path)
        default_q = {"metadata": {"name": "default"}, "spec": {"weight": 1}}
        out = submit_review(self.mgr.url("/queues/validate-delete"),
                            "DELETE", None, old=default_q)
        assert not out["response"]["allowed"]

    def test_malformed_object_denied_not_crash(self):
        out = submit_review(self.mgr.url("/jobs/validate"), "CREATE",
                            {"spec": {"tasks": "not-a-list"}})
        assert not out["response"]["allowed"]
        # and the server keeps serving
        out = submit_review(self.mgr.url("/jobs/mutate"), "CREATE",
                            JOB_MANIFEST)
        assert out["response"]["allowed"]

    def test_unknown_path_denied(self):
        out = submit_review(self.mgr.url("/nope"), "CREATE", {})
        assert not out["response"]["allowed"]

    def test_self_registration_records(self):
        class Store:
            store = {}
        api = Store()
        regs = self.mgr.register_webhooks()
        self.mgr.apiserver = api
        self.mgr.register_webhooks()
        kinds = {r["kind"] for r in regs}
        assert kinds == {"MutatingWebhookConfiguration",
                         "ValidatingWebhookConfiguration"}
        paths = {r["webhooks"][0]["clientConfig"]["url"].split(
            str(self.mgr.address[1]))[-1] for r in regs}
        assert "/jobs/validate" in paths and "/jobs/mutate" in paths
        assert len(api.store["webhookconfigurations"]) == len(regs)


class TestSystemIntegration:
    def test_system_serves_webhooks_and_registers(self):
        """The assembled control plane exposes the webhook-manager surface
        and writes its registration records to the store."""
        from volcano_tpu.runtime.system import VolcanoSystem
        sys_ = VolcanoSystem()
        mgr = sys_.start_webhook_manager()
        try:
            out = submit_review(mgr.url("/jobs/mutate"), "CREATE",
                                JOB_MANIFEST)
            assert out["response"]["allowed"]
            regs = sys_.api.stores.get("webhookconfigurations", {})
            assert len(regs) >= 8
            # idempotent: starting again reuses the same manager
            assert sys_.start_webhook_manager() is mgr
        finally:
            mgr.shutdown()

    def test_system_with_webhooks_stays_picklable(self):
        import pickle
        from volcano_tpu.runtime.system import VolcanoSystem
        sys_ = VolcanoSystem()
        mgr = sys_.start_webhook_manager()
        try:
            blob = pickle.dumps(sys_)
        finally:
            mgr.shutdown()
        restored = pickle.loads(blob)
        assert restored._webhook_manager is None

    def test_rebind_conflict_raises(self):
        import pytest
        from volcano_tpu.runtime.system import VolcanoSystem
        sys_ = VolcanoSystem()
        mgr = sys_.start_webhook_manager()
        try:
            with pytest.raises(RuntimeError):
                sys_.start_webhook_manager("0.0.0.0", 8443)
        finally:
            mgr.shutdown()
