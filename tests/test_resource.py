"""Resource algebra tests — mirrors the assertions of the reference's
pkg/scheduler/api/resource_info_test.go:1-632."""

import pytest

from volcano_tpu.api import Resource
from volcano_tpu.api.resource import parse_quantity


def R(cpu=0, memory=0, **s):
    rl = {}
    if cpu:
        rl["cpu"] = cpu
    if memory:
        rl["memory"] = memory
    rl.update(s)
    return Resource.from_resource_list(rl)


class TestParseQuantity:
    def test_cpu_millicores(self):
        assert parse_quantity("100m", is_cpu=True) == 100
        assert parse_quantity("2", is_cpu=True) == 2000
        assert parse_quantity("1.5", is_cpu=True) == 1500

    def test_memory_suffixes(self):
        assert parse_quantity("1Ki") == 1024
        assert parse_quantity("2Gi") == 2 * 2**30
        assert parse_quantity("1G") == 1e9
        assert parse_quantity("42") == 42


class TestArithmetic:
    def test_add(self):
        a = R(cpu="1", memory="1Gi").add(R(cpu="2", memory="1Gi"))
        assert a == R(cpu="3", memory="2Gi")

    def test_sub(self):
        a = R(cpu="3", memory="3Gi").sub(R(cpu="1", memory="1Gi"))
        assert a == R(cpu="2", memory="2Gi")

    def test_sub_underflow_raises(self):
        with pytest.raises(ValueError):
            R(cpu="1").sub(R(cpu="2"))

    def test_multi(self):
        assert R(cpu="1", memory="2Gi").multi(2) == R(cpu="2", memory="4Gi")

    def test_set_max(self):
        a = R(cpu="1", memory="4Gi").set_max_resource(R(cpu="2", memory="1Gi"))
        assert a == R(cpu="2", memory="4Gi")

    def test_min_dimension(self):
        a = R(cpu="3", memory="1Gi").min_dimension_resource(R(cpu="1", memory="4Gi"))
        assert a == R(cpu="1", memory="1Gi")

    def test_scalar_resources(self):
        a = R(cpu="1", **{"nvidia.com/gpu": "2"})
        b = R(**{"nvidia.com/gpu": "1"})
        assert a.clone().add(b).get("nvidia.com/gpu") == 3
        assert b.less_equal(a)
        assert not a.less_equal(b)


class TestComparisons:
    def test_less_equal_zero_semantics(self):
        # missing dims on the left count as zero -> always <=
        assert R().less_equal(R(cpu="1"))
        assert R(cpu="1").less_equal(R(cpu="1"))
        assert not R(cpu="2").less_equal(R(cpu="1"))
        # scalar present on left only: right treated as zero
        assert not R(**{"gpu": "1"}).less_equal(R(cpu="4"))

    def test_less_equal_strict(self):
        assert R(cpu="1").less_equal_strict(R(cpu="2", memory="1Gi"))
        assert not R(**{"gpu": "0"}).less_equal_strict(R(cpu="4"))

    def test_less_all_dims(self):
        assert R(cpu="1", memory="1Gi").less(R(cpu="2", memory="2Gi"))
        assert not R(cpu="1", memory="2Gi").less(R(cpu="2", memory="2Gi"))

    def test_less_partly(self):
        assert R(cpu="1", memory="2Gi").less_partly(R(cpu="2", memory="1Gi"))
        assert not R(cpu="2", memory="2Gi").less_partly(R(cpu="1", memory="1Gi"))

    def test_diff(self):
        inc, dec = R(cpu="3", memory="1Gi").diff(R(cpu="1", memory="2Gi"))
        assert inc == R(cpu="2")
        assert dec == R(memory="1Gi")

    def test_is_empty(self):
        assert R().is_empty()
        assert Resource({"cpu": 0.05}).is_empty()
        assert not R(cpu="1").is_empty()

    def test_fit_delta(self):
        a = R(cpu="1").fit_delta(R(cpu="1"))
        assert a.milli_cpu > 2000  # epsilon added


class TestMaxTaskNum:
    def test_pods_becomes_max_task_num(self):
        r = Resource.from_resource_list({"cpu": "4", "pods": "110"})
        assert r.max_task_num == 110
        assert "pods" not in r.quantities
