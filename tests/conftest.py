"""Test configuration: force an 8-device virtual CPU mesh before JAX inits.

Mirrors the reference's test strategy (SURVEY.md section 4): unit tests run
against fake backends with no real cluster; here, additionally, no real TPU —
sharding tests use 8 virtual CPU devices.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
