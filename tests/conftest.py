"""Test configuration: force an 8-device virtual CPU mesh before JAX inits.

Mirrors the reference's test strategy (SURVEY.md section 4): unit tests run
against fake backends with no real cluster; here, additionally, no real TPU —
sharding tests use 8 virtual CPU devices.

Note: the environment's axon site hook sets jax_platforms=axon,cpu, which
overrides the JAX_PLATFORMS env var — the config must be updated via the API
before any backend initializes.
"""

import os
import sys

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def pytest_configure(config):
    # tier-1 (scripts/tier1.sh) runs `-m 'not slow'`; the slow tail
    # (sharded 8-device identity, full hdrf outcome sweeps, sidecar e2e)
    # runs in the full suite only
    config.addinivalue_line(
        "markers", "slow: long-running test excluded from tier-1")
    # dtype/API drift must not accumulate silently (graphcheck satellite):
    # a JAX/NumPy deprecation in the cycle is tomorrow's behavior change,
    # so the suite fails the moment one appears
    config.addinivalue_line("filterwarnings", "error::DeprecationWarning")
    config.addinivalue_line(
        "filterwarnings", "error::PendingDeprecationWarning")
    config.addinivalue_line("filterwarnings", "error::FutureWarning")
