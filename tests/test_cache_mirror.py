"""SchedulerCache's incremental mirror vs its full projection.

The mirror (live_view + watch handlers) is the event_handlers.go analog:
after ANY sequence of store events it must describe the same world as a
from-scratch snapshot() projection — compared here at the packed-array
level, which is what the kernels actually consume. Also drives the full
scheduler loop over the cache and checks decisions match a fresh-snapshot
scheduler cycle for cycle.
"""

import jax
import numpy as np

from volcano_tpu.api.core import (POD_GROUP_ANNOTATION, Pod, PodGroup,
                                  PodPhase)
from volcano_tpu.api.node_info import NodeInfo
from volcano_tpu.api.queue_info import QueueInfo
from volcano_tpu.api.resource import Resource
from volcano_tpu.api.types import PodGroupPhase
from volcano_tpu.arrays.pack import pack
from volcano_tpu.framework import parse_conf
from volcano_tpu.framework.session import BindIntent, EvictIntent
from volcano_tpu.runtime.apiserver import APIServer
from volcano_tpu.runtime.cache import SchedulerCache

CONF = parse_conf("""
actions: "enqueue, allocate, backfill"
tiers:
- plugins:
  - name: priority
  - name: gang
- plugins:
  - name: drf
  - name: predicates
  - name: proportion
  - name: binpack
""")


def make_node(name, cpu="8", mem="16Gi"):
    return NodeInfo(name, allocatable=Resource.from_resource_list(
        {"cpu": cpu, "memory": mem}))


def make_pod(name, group, cpu="1", mem="1Gi", phase=PodPhase.PENDING,
             node=""):
    p = Pod(name=name, annotations={POD_GROUP_ANNOTATION: group},
            resources={"cpu": cpu, "memory": mem}, creation_timestamp=1.0)
    p.phase = phase
    p.node_name = node
    return p


def assert_mirror_matches(cache):
    got, _ = pack(cache.live_view())
    want, _ = pack(cache.snapshot())
    for g, w in zip(jax.tree.leaves(got), jax.tree.leaves(want)):
        np.testing.assert_array_equal(np.asarray(g), np.asarray(w))


def seed(api):
    for i in range(4):
        api.create("nodes", make_node(f"n{i}"))
    api.create("queues", QueueInfo("q1", weight=2))
    for g in range(3):
        api.create("podgroups", PodGroup(
            name=f"g{g}", min_member=2, queue="q1" if g % 2 else "",
            creation_timestamp=float(g)))
        for t in range(3):
            api.create("pods", make_pod(f"g{g}-t{t}", f"g{g}"))


class TestCacheMirror:
    def test_event_sequences_match_projection(self):
        api = APIServer()
        cache = SchedulerCache(api)
        assert_mirror_matches(cache)        # empty world
        seed(api)
        assert_mirror_matches(cache)        # rebuild path

        # bind writes (the scheduler's own write-back)
        cache.bind(BindIntent("default/g0-t0", "default/g0", "n0"))
        assert_mirror_matches(cache)
        # kubelet: pod starts running
        pod = api.get("pods", "default/g0-t0")
        pod.phase = PodPhase.RUNNING
        api.update("pods", pod)
        assert_mirror_matches(cache)
        # pod completes
        pod.phase = PodPhase.SUCCEEDED
        api.update("pods", pod)
        assert_mirror_matches(cache)
        # eviction deletes the pod
        cache.bind(BindIntent("default/g1-t0", "default/g1", "n1"))
        cache.evict(EvictIntent("default/g1-t0", "default/g1"))
        assert_mirror_matches(cache)
        # controller re-creates it pending
        api.create("pods", make_pod("g1-t0", "g1"))
        assert_mirror_matches(cache)
        # podgroup phase flip + spec change
        cache.update_podgroup_phases({"default/g2": PodGroupPhase.RUNNING})
        assert_mirror_matches(cache)
        pg = api.get("podgroups", "default/g2")
        pg.min_member = 1
        api.update("podgroups", pg)
        assert_mirror_matches(cache)
        # queue weight edit + new queue
        q = api.get("queues", "q1")
        q.weight = 5
        api.update("queues", q)
        assert_mirror_matches(cache)
        api.create("queues", QueueInfo("q2", weight=3))
        assert_mirror_matches(cache)
        # node appears / disappears
        api.create("nodes", make_node("n9"))
        assert_mirror_matches(cache)
        api.delete("nodes", "n9")
        assert_mirror_matches(cache)
        # pod deleted outright
        api.delete("pods", "default/g2-t2")
        assert_mirror_matches(cache)

    def test_node_overcommit_gates_out_and_back(self):
        """Forced ingestion past allocatable flags the node OutOfSync: it
        must leave the mirror's node set exactly like the projection drops
        it, and return once the pressure clears."""
        api = APIServer()
        cache = SchedulerCache(api)
        api.create("nodes", make_node("n0", cpu="2", mem="4Gi"))
        api.create("nodes", make_node("n1"))
        api.create("podgroups", PodGroup(name="g", min_member=1))
        api.create("pods", make_pod("big", "g", cpu="4", mem="2Gi",
                                    phase=PodPhase.RUNNING, node="n0"))
        cache.live_view()
        assert "n0" not in cache.live_view().nodes      # gated out
        assert_mirror_matches(cache)
        pod = api.get("pods", "default/big")
        pod.phase = PodPhase.SUCCEEDED                  # frees the node
        api.update("pods", pod)
        assert "n0" in cache.live_view().nodes
        assert_mirror_matches(cache)

    def test_scheduler_loop_over_cache_matches_fresh(self):
        """Full loop: persistent-session scheduler over the cache equals a
        fresh-snapshot scheduler, cycle for cycle, under store churn."""
        from volcano_tpu.runtime.scheduler import Scheduler

        def build():
            api = APIServer()
            cache = SchedulerCache(api)
            seed(api)
            return api, cache

        api_a, cache_a = build()
        api_b, cache_b = build()
        sa = Scheduler(cache_a, conf=CONF, incremental=True)
        sb = Scheduler(cache_b, conf=CONF, incremental=False)
        assert sa.incremental and not sb.incremental
        for c in range(4):
            ssn_a = sa.run_once(now=100.0 + c)
            ssn_b = sb.run_once(now=100.0 + c)
            da = sorted((b.task_uid, b.node_name) for b in ssn_a.binds)
            db = sorted((b.task_uid, b.node_name) for b in ssn_b.binds)
            assert da == db, f"cycle {c}"
            assert sorted(ssn_a.pipelined) == sorted(ssn_b.pipelined)
            for api in (api_a, api_b):
                # kubelet: bound pods run; one runner completes each cycle
                done = False
                for pod in sorted(api.stores["pods"].values(),
                                  key=lambda p: p.key):
                    if pod.node_name and pod.phase == PodPhase.PENDING:
                        pod.phase = PodPhase.RUNNING
                        api.update("pods", pod)
                    elif pod.phase == PodPhase.RUNNING and not done:
                        pod.phase = PodPhase.SUCCEEDED
                        api.update("pods", pod)
                        done = True
            assert_mirror_matches(cache_a)
        assert cache_a.binds == cache_b.binds

    def test_pod_regroup_and_scheduler_flip(self):
        """A pod whose group annotation moves to another podgroup, or whose
        schedulerName stops being ours, must re-project — the old job may
        not keep a stale twin (the _task_owner guard)."""
        from volcano_tpu.api.core import POD_GROUP_ANNOTATION
        api = APIServer()
        cache = SchedulerCache(api)
        seed(api)
        cache.live_view()
        pod = api.get("pods", "default/g0-t0")
        pod.annotations[POD_GROUP_ANNOTATION] = "g1"
        api.update("pods", pod)
        assert_mirror_matches(cache)
        mirror = cache.live_view()
        assert "default/g0-t0" in mirror.jobs["default/g1"].tasks
        assert "default/g0-t0" not in mirror.jobs["default/g0"].tasks
        pod2 = api.get("pods", "default/g1-t1")
        pod2.scheduler_name = "other-scheduler"
        api.update("pods", pod2)
        assert_mirror_matches(cache)
        assert "default/g1-t1" not in cache.live_view().jobs[
            "default/g1"].tasks
