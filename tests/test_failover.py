"""Warm-standby HA: checkpoint streaming, lease-fenced failover (ISSUE 11).

- Delta records are bit-exact (NaN payloads, -0.0) and reconstruct the
  full mirror; unchanged mirrors ship nothing.
- The standby verifies every record's integrity digest before adopting
  it, applies envelopes atomically, and answers gap/invalid so the
  sender repairs with one full resync; a LOST envelope needs no repair.
- The fencing token: writes stamped with a superseded lease generation
  are rejected structurally — no split-brain double-bind — and the
  promotion announces the new fence before the first write.
- A follower scheduler refuses to dispatch; the promotion ladder lands
  warm/cold/fallback; the failover probe proves decision identity at
  every kill phase (slow tail — tier1.sh runs the same probe as the
  failover smoke on every tier-1 invocation).
"""

import dataclasses

import numpy as np
import pytest

from volcano_tpu.chaos import FaultInjector, FaultPlan, chaos
from volcano_tpu.chaos.plan import Fault
from volcano_tpu.metrics import METRICS
from volcano_tpu.ops.fused_io import host_digest
from volcano_tpu.runtime import checkpoint as ckpt
from volcano_tpu.runtime.fake_cluster import FakeCluster
from volcano_tpu.runtime.leader import DEFAULT_LEASE_DURATION, LeaderElector
from volcano_tpu.runtime.replication import (REPL_KIND, WarmStandby,
                                             apply_delta, delta_record,
                                             replica_pair)
from volcano_tpu.runtime.scheduler import Scheduler
from volcano_tpu.runtime.system import VolcanoSystem
from volcano_tpu.framework.session import BindIntent

from test_delta_pipeline import PARITY_CONF
from test_runtime_incremental import build_cluster, churn


class FakeClock:
    def __init__(self, now: float = 100.0):
        self.now = now

    def __call__(self) -> float:
        return self.now


def _mirror(*vals, dtype=np.float32):
    return (np.array(vals, dtype=dtype),)


# --------------------------------------------------------- delta records
class TestDeltaRecords:
    def test_full_copy_without_base_then_delta(self):
        cur = _mirror(1.0, 2.0, 3.0)
        rec = delta_record(("k",), None, cur, [1, 2, 3])
        assert rec["mirror"] is not None and rec["delta"] is None
        nxt = _mirror(1.0, 9.0, 3.0)
        rec2 = delta_record(("k",), cur, nxt, [4, 5, 6])
        assert rec2["mirror"] is None
        (idx, vals), = rec2["delta"]
        assert idx.tolist() == [1]
        out = apply_delta(cur, rec2["delta"])
        np.testing.assert_array_equal(out[0], nxt[0])

    def test_unchanged_mirror_ships_nothing(self):
        cur = _mirror(1.0, 2.0)
        assert delta_record(("k",), cur, _mirror(1.0, 2.0), [0, 0, 0]) \
            is None

    def test_nan_payloads_and_negative_zero_roundtrip_bitexact(self):
        """The diff/apply path works on u32 views: a NaN position is
        neither eternally re-sent (NaN != NaN would re-flag it) nor
        flattened to a canonical NaN; -0.0 survives its sign."""
        base = _mirror(0.0, 1.0, 2.0)
        nan_payload = np.array([np.float32(np.nan)], np.float32)
        nan_payload.view(np.uint32)[0] |= 0x1234        # non-canonical NaN
        cur = (np.array([-0.0, nan_payload[0], 2.0], np.float32),)
        rec = delta_record(("k",), base, cur, [0, 0, 0])
        out = apply_delta(base, rec["delta"])
        np.testing.assert_array_equal(out[0].view(np.uint32),
                                      cur[0].view(np.uint32))
        # the NaN position is now identical bits: no further edits
        assert delta_record(("k",), out, cur, [0, 0, 0]) is None

    def test_shape_change_falls_back_to_full_copy(self):
        rec = delta_record(("k",), _mirror(1.0, 2.0),
                           _mirror(1.0, 2.0, 3.0), [0, 0, 0])
        assert rec["mirror"] is not None and rec["delta"] is None


# ------------------------------------------------------- standby apply
def _envelope(mirror, seq=1, since=0, digest=None, state=None):
    return {"kind": REPL_KIND, "seq": seq, "since": since,
            "state": state or {"cycles": 1},
            "mirrors": [{"key": ("k",), "mirror": mirror, "delta": None,
                         "digest": (digest if digest is not None else
                                    [int(x) for x in host_digest(mirror)])}],
            "digest_words": [0, 0, 0]}


class TestWarmStandbyApply:
    def test_wrong_kind_is_invalid(self):
        assert WarmStandby().apply({"kind": "nope"}) == "invalid"

    def test_since_mismatch_is_gap(self):
        sb = WarmStandby()
        assert sb.apply(_envelope(_mirror(1.0), seq=5, since=4)) == "gap"
        assert sb.applied_seq == 0

    def test_tampered_digest_refused_atomically(self):
        sb = WarmStandby()
        assert sb.apply(_envelope(_mirror(1.0, 2.0))) == "applied"
        before = METRICS.counter_value("replication_mirror_invalid_total")
        bad = _envelope(_mirror(9.0, 9.0), seq=2, since=1,
                        digest=[1, 2, 3])
        assert sb.apply(bad) == "invalid"
        assert METRICS.counter_value(
            "replication_mirror_invalid_total") == before + 1
        # nothing adopted: position and mirrors unchanged
        assert sb.applied_seq == 1
        np.testing.assert_array_equal(
            sb.mirrors[ckpt._freeze_key(("k",))][0],
            np.array([1.0, 2.0], np.float32))

    def test_full_resync_replaces_world(self):
        sb = WarmStandby()
        sb.apply(_envelope(_mirror(1.0)))
        stale_key = ckpt._freeze_key(("k",))
        env = _envelope(_mirror(5.0), seq=7, since=0)
        env["mirrors"][0]["key"] = ("k2",)
        env["mirrors"][0]["digest"] = [
            int(x) for x in host_digest(_mirror(5.0))]
        assert sb.apply(env) == "applied"
        assert stale_key not in sb.mirrors          # no lingering keys
        assert sb.applied_seq == 7


# ------------------------------------------------- streaming over a run
def _ha_sched(cycles=0, pipeline=True):
    cluster = FakeCluster(build_cluster(n_nodes=8, n_jobs=10))
    clock = FakeClock()
    api = VolcanoSystem().api
    elector = LeaderElector(api, identity="leader-0", clock=clock)
    elector.tick()
    sched = Scheduler(cluster, conf=PARITY_CONF, pipeline=pipeline,
                      elector=elector)
    sender, standby = replica_pair(sched)
    for c in range(cycles):
        clock.now += 1.0
        sched.run_once(now=1000.0 + c)
        if pipeline:
            sched.drain(now=1000.0 + c)
        assert sender.stream() == "applied"
        churn(cluster, c, arrivals=True)
    return cluster, clock, api, sched, sender, standby


class TestStreamRepair:
    def test_steady_stream_applies_and_tracks_seq(self):
        _, _, _, sched, sender, standby = _ha_sched(cycles=3)
        assert standby.applied_seq == sender.seq == 3
        assert standby.state["cycles"] == sched.cycles
        assert standby.mirrors                      # mirrors replicated

    def test_lost_envelope_needs_no_repair(self):
        cluster, clock, _, sched, sender, standby = _ha_sched(cycles=2)
        plan = FaultPlan(seed=1, cycles=8, kinds=())
        plan.faults = (Fault(kind="replication_partition", cycle=2,
                             param=0),)
        inj = FaultInjector(plan)
        with chaos(inj):
            inj.begin_cycle(2)
            clock.now += 1.0
            sched.run_once(now=1002.0)
            sched.drain(now=1002.0)
            assert sender.stream() == "lost"        # dropped at the seam
            churn(cluster, 2, arrivals=True)
            inj.begin_cycle(3)
            clock.now += 1.0
            sched.run_once(now=1003.0)
            sched.drain(now=1003.0)
            # the un-advanced ack base keeps the next delta applicable
            assert sender.stream() == "applied"
        assert standby.applied_seq == sender.seq
        assert [k for _, k, _s in inj.fired] == ["replication_partition"]

    def test_desynced_standby_repaired_with_full_resync(self):
        _, clock, _, sched, sender, standby = _ha_sched(cycles=2)
        standby.applied_seq = 99                    # restarted standby
        clock.now += 1.0
        sched.run_once(now=1002.0)
        sched.drain(now=1002.0)
        assert sender.stream() == "applied"         # gap -> full resend
        assert standby.applied_seq == sender.seq


# ----------------------------------------------------------- the fence
class TestFencing:
    def _intent(self, cluster):
        job = next(iter(cluster.ci.jobs.values()))
        task = next(t for t in job.tasks.values())
        node = next(iter(cluster.ci.nodes.values()))
        return BindIntent(task_uid=task.uid, job_uid=job.uid,
                          node_name=node.name)

    def test_stale_token_rejected_before_any_validity_check(self):
        cluster = FakeCluster(build_cluster(n_nodes=4, n_jobs=4))
        cluster.advance_fence(3)
        assert cluster.fence_admits(3) and not cluster.fence_admits(2)
        before = METRICS.counter_total("fenced_writes_rejected_total")
        intent = self._intent(cluster)
        assert not cluster.bind(intent, fence=2)
        assert cluster.fenced_rejections[-1][0] == "bind"
        assert cluster.fenced_rejections[-1][2:] == (2, 3)
        assert METRICS.counter_total(
            "fenced_writes_rejected_total") == before + 1
        assert not cluster.binds                    # nothing applied
        # a rejection is permanent for that token; unfenced callers and
        # the current token still pass the fence
        assert cluster._check_fence("bind", "t", None)
        assert cluster._check_fence("bind", "t", 3)

    def test_admission_ratchets_the_fence(self):
        cluster = FakeCluster(build_cluster(n_nodes=4, n_jobs=4))
        intent = self._intent(cluster)
        assert cluster.bind(intent, fence=5)        # admits + ratchets
        assert cluster.fence_generation == 5
        from volcano_tpu.framework.session import EvictIntent
        ev = EvictIntent(task_uid=intent.task_uid, job_uid=intent.job_uid)
        assert not cluster.evict(ev, fence=4)       # older token: fenced
        assert cluster.evict(ev, fence=6)


# --------------------------------------------- follower + promotion
class TestFollowerAndPromotion:
    def test_follower_refuses_to_dispatch(self):
        cluster, clock, api, sched, sender, standby = _ha_sched(cycles=1)
        rival = LeaderElector(api, identity="rival", clock=clock)
        clock.now += DEFAULT_LEASE_DURATION + 1.0
        assert rival.tick()                         # steals the lease
        fol0 = METRICS.counter_value("leader_transitions_total",
                                     {"to": "follower"})
        assert sched.run_once(now=1001.0) is None   # follower: no cycle
        assert not sched.elector.is_leader
        assert METRICS.counter_value(
            "leader_transitions_total", {"to": "follower"}) == fol0 + 1
        assert METRICS.gauges.get(("is_leader", "")) == 0

    def test_promote_warm_first_cycle_is_delta(self):
        cluster, clock, api, sched, sender, standby = _ha_sched(cycles=3)
        clock.now += DEFAULT_LEASE_DURATION + 1.0
        el = LeaderElector(api, identity="standby-1", clock=clock)
        warm0 = METRICS.counter_value("failover_promotions_total",
                                      {"outcome": "warm"})
        sched2 = standby.promote(cluster, conf=sched.conf, pipeline=True,
                                 now=1003.0, elector=el)
        assert standby.last_outcome == "warm"
        assert METRICS.counter_value("failover_promotions_total",
                                     {"outcome": "warm"}) == warm0 + 1
        assert sched2.cycles == sched.cycles        # counters carried over
        assert el.generation == 2
        assert cluster.fence_generation == 2        # fence pre-announced
        sched2.run_once(now=1003.0)
        sched2.drain(now=1003.0)
        snaps = sched2.flight.snapshots()
        assert snaps and snaps[0]["cycle_kind"] == "delta"

    def test_promote_cold_and_fallback_rungs(self):
        cluster = FakeCluster(build_cluster(n_nodes=4, n_jobs=4))
        empty = WarmStandby(conf=PARITY_CONF)
        empty.promote(cluster, pipeline=False, now=1000.0)
        assert empty.last_outcome == "cold"
        _, _, _, sched, sender, standby = _ha_sched(cycles=1)
        from volcano_tpu.chaos.probe import _PROBE_CONF
        from volcano_tpu.framework import parse_conf
        other = parse_conf(_PROBE_CONF)
        standby.promote(cluster, conf=other, pipeline=False, now=1001.0)
        assert standby.last_outcome == "fallback"

    def test_deposed_leader_split_brain_writes_fenced(self):
        """The planted split-brain: the deposed leader survives promotion
        and replays a write with its stale token — rejected, zero
        duplicate binds."""
        cluster, clock, api, sched, sender, standby = _ha_sched(cycles=2)
        deposed = sched
        clock.now += DEFAULT_LEASE_DURATION + 1.0
        el = LeaderElector(api, identity="standby-1", clock=clock)
        standby.promote(cluster, conf=sched.conf, pipeline=True,
                        now=1002.0, elector=el)
        binds0 = list(cluster.binds)
        task_uid, node = binds0[0]
        job_uid = next(j.uid for j in cluster.ci.jobs.values()
                       if task_uid in j.tasks)
        replay = BindIntent(task_uid=task_uid, job_uid=job_uid,
                            node_name=node)
        assert not cluster.bind(replay, fence=deposed.elector.generation)
        assert cluster.binds == binds0              # no duplicate bind
        assert cluster.fenced_rejections[-1][1] == task_uid


# ------------------------------------------------- the probe (slow tail)
class TestFailoverProbe:
    # slow tail (tier-1 budget): tier1.sh runs this EXACT probe with the
    # same acceptance checks as the failover smoke on every invocation
    @pytest.mark.slow
    def test_kill_every_phase_decision_identical(self):
        from volcano_tpu.chaos import run_failover_probe
        rpt = run_failover_probe(seed=7, cycles=8)
        assert rpt["calm_equal_clean"]              # replication invisible
        assert rpt["decisions_equal_clean"]
        assert {p for _, p in rpt["kills"]} == {"pre_dispatch",
                                                "in_flight", "post_drain"}
        assert rpt["warm_promotions"] == 3
        assert rpt["cycles_lost"] <= 1
        assert rpt["cycles_to_steady"] == 0
        sb = rpt["split_brain"]
        assert sb["decisions_equal_clean"]
        assert sb["fenced_writes_rejected"] >= 1
        assert sb["applied_by_deposed"] == 0
        assert sb["duplicate_binds"] == 0
        assert sb["replays_rejected"]
        assert rpt["partition"]["decisions_equal_clean"]
        assert rpt["partition"]["envelopes_dropped"] >= 1

    @pytest.mark.slow
    def test_pallas_interpret_path_identical(self):
        from volcano_tpu.chaos import run_failover_probe
        rpt = run_failover_probe(seed=7, cycles=8, use_pallas="interpret",
                                 partition_leg=False)
        assert rpt["calm_equal_clean"]
        assert rpt["decisions_equal_clean"]
        assert rpt["split_brain"]["decisions_equal_clean"]
        assert rpt["cycles_to_steady"] == 0


# ---------------------------------------------- failover-storm scenario
class TestFailoverStormScenario:
    # slow tail (tier-1 budget): two scenario engine runs; the failover
    # path itself is gated every tier-1 run by the failover smoke
    @pytest.mark.slow
    def test_failover_storm_decision_identical_to_calm_run(self):
        from volcano_tpu.scenarios import get_scenario, run_scenario
        spec = get_scenario("failover-storm")
        storm = run_scenario(spec, cycles=18, observe=False)
        calm = run_scenario(dataclasses.replace(spec, failover_every=0),
                            cycles=18, observe=False)
        fo = [e for e in storm.events if e["kind"] == "failover"]
        assert [e["outcome"] for e in fo] == ["warm"] * 2
        assert storm.scorecard.decisions_sha == calm.scorecard.decisions_sha
