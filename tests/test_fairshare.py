"""Fair-share solver tests — mirrors the reference's proportion_test.go and
drf/hdrf_test.go outcome assertions."""

import numpy as np
import jax
import jax.numpy as jnp

from volcano_tpu.api import QueueInfo, Resource, TaskStatus
from volcano_tpu.arrays import pack
from volcano_tpu.ops.enqueue import EnqueueConfig, make_enqueue_pass
from volcano_tpu.ops.backfill import make_backfill_pass
from volcano_tpu.ops.fairshare import (dominant_share, drf_job_shares,
                                       hdrf_level_keys, namespace_shares,
                                       proportion_deserved)

from fixtures import build_job, build_task, res, simple_cluster


def packed(ci):
    return pack(ci)


def make_queue_snapshot(total_cpu, specs):
    """specs: list of (name, weight, request_cpu_millis, capability_cpu or None)."""
    ci = simple_cluster(n_nodes=0)
    from fixtures import build_node
    ci.add_node(build_node("n0", cpu=str(total_cpu), memory="64Gi"))
    del ci.queues["default"]
    for name, weight, req, cap in specs:
        q = QueueInfo(name, weight=weight)
        if cap is not None:
            q.capability = res(cpu=str(cap))
        ci.add_queue(q)
        if req:
            job = build_job(f"default/{name}-job", queue=name,
                            min_available=1)
            job.add_task(build_task(f"{name}-t", cpu=f"{req}m", memory=0))
            ci.add_job(job)
    return pack(ci)


class TestProportion:
    def test_water_filling_two_queues(self):
        # total 10 cpu; q1 w1 requests 8, q2 w1 requests 2
        snap, maps = make_queue_snapshot(10, [("q1", 1, 8000, None),
                                              ("q2", 1, 2000, None)])
        deserved = proportion_deserved(jax.tree.map(jnp.asarray, snap.queues),
                                       jnp.asarray(snap.cluster_capacity))
        d = np.array(deserved)
        assert abs(d[maps.queue_index["q1"]][0] - 8000) < 1
        assert abs(d[maps.queue_index["q2"]][0] - 2000) < 1

    def test_weights_split_contention(self):
        # total 9 cpu; q1 w2 requests 9, q2 w1 requests 9 -> 6 / 3
        snap, maps = make_queue_snapshot(9, [("q1", 2, 9000, None),
                                             ("q2", 1, 9000, None)])
        d = np.array(proportion_deserved(
            jax.tree.map(jnp.asarray, snap.queues),
            jnp.asarray(snap.cluster_capacity)))
        assert abs(d[maps.queue_index["q1"]][0] - 6000) < 1
        assert abs(d[maps.queue_index["q2"]][0] - 3000) < 1

    def test_capability_clamps(self):
        # q1 w1 requests 8 but capability 2 -> gets 2; q2 absorbs the rest
        snap, maps = make_queue_snapshot(10, [("q1", 1, 8000, 2),
                                              ("q2", 1, 8000, None)])
        d = np.array(proportion_deserved(
            jax.tree.map(jnp.asarray, snap.queues),
            jnp.asarray(snap.cluster_capacity)))
        assert abs(d[maps.queue_index["q1"]][0] - 2000) < 1
        assert abs(d[maps.queue_index["q2"]][0] - 8000) < 1

    def test_deserved_never_exceeds_request(self):
        snap, maps = make_queue_snapshot(100, [("q1", 1, 1000, None),
                                               ("q2", 1, 500, None)])
        d = np.array(proportion_deserved(
            jax.tree.map(jnp.asarray, snap.queues),
            jnp.asarray(snap.cluster_capacity)))
        assert d[maps.queue_index["q1"]][0] <= 1000 + 1
        assert d[maps.queue_index["q2"]][0] <= 500 + 1


class TestDRF:
    def test_dominant_share(self):
        total = jnp.array([10000.0, 100.0])
        alloc = jnp.array([[1000.0, 50.0], [5000.0, 10.0]])
        s = np.array(dominant_share(alloc, total))
        assert abs(s[0] - 0.5) < 1e-6   # memory dominant
        assert abs(s[1] - 0.5) < 1e-6   # cpu dominant

    def test_job_shares_order_jobs(self):
        total = jnp.array([10000.0])
        alloc = jnp.array([[2000.0], [8000.0], [0.0]])
        valid = jnp.array([True, True, False])
        s = np.array(drf_job_shares(alloc, total, valid))
        assert s[0] < s[1]
        assert np.isinf(s[2])

    def test_namespace_shares_weighted(self):
        total = jnp.array([10000.0])
        job_alloc = jnp.array([[4000.0], [4000.0]])
        job_ns = jnp.array([0, 1])
        valid = jnp.array([True, True])
        w = jnp.array([4.0, 1.0])
        s = np.array(namespace_shares(job_alloc, job_ns, valid, w, total))
        assert s[0] < s[1]  # same usage, higher weight -> lower share


class TestHDRF:
    def test_weighted_level_keys_favor_heavier_queue(self):
        from volcano_tpu.arrays.hierarchy import build_hierarchy
        ci = simple_cluster(n_nodes=1, node_cpu="10")
        del ci.queues["default"]
        ci.add_queue(QueueInfo("root.a", hierarchy="root/a",
                               hierarchy_weights="1/1"))
        ci.add_queue(QueueInfo("root.b", hierarchy="root/b",
                               hierarchy_weights="1/3"))
        for qname, cpu in [("root.a", "4"), ("root.b", "4")]:
            job = build_job(f"default/{qname}", queue=qname)
            t = build_task(f"{qname}-t", cpu=cpu, memory=0)
            t.status = TaskStatus.RUNNING
            job.add_task(t)
            ci.add_job(job)
        snap, maps = pack(ci)
        Q = np.asarray(snap.queues.weight).shape[0]
        J = np.asarray(snap.jobs.valid).shape[0]
        hier = build_hierarchy(ci, maps, Q, J)
        keys = np.asarray(hdrf_level_keys(
            hier, jnp.asarray(snap.jobs.allocated),
            jnp.asarray(snap.jobs.total_request),
            jnp.asarray(snap.jobs.valid),
            jnp.asarray(snap.cluster_capacity)))
        ia, ib = maps.queue_index["root.a"], maps.queue_index["root.b"]
        # same usage; b has 3x hierarchy weight -> lower weighted share at
        # its level -> sorts first (compareQueues, drf.go:208-215)
        assert tuple(keys[ib]) < tuple(keys[ia])


class TestEnqueue:
    def test_proportion_gate_respects_queue_capability(self):
        """Permit iff minReq + allocated + inqueue <= capability; the running
        inqueue tally makes admission sequential (proportion.go:254-280)."""
        from volcano_tpu.api import PodGroupPhase
        ci = simple_cluster(n_nodes=1, node_cpu="8")
        del ci.queues["default"]
        q = QueueInfo("default", weight=1)
        q.capability = res(cpu="4")
        ci.add_queue(q)
        j1 = build_job("default/j1", min_available=1,
                       pod_group_phase=PodGroupPhase.PENDING,
                       min_resources=res(cpu="2"))
        j1.add_task(build_task("p1", cpu="2", memory=0))
        j2 = build_job("default/j2", min_available=1,
                       pod_group_phase=PodGroupPhase.PENDING,
                       min_resources=res(cpu="3"))
        j2.add_task(build_task("p2", cpu="3", memory=0))
        ci.add_job(j1)
        ci.add_job(j2)
        snap, maps = pack(ci)
        fn = jax.jit(make_enqueue_pass(EnqueueConfig()))
        admitted = np.array(fn(snap, np.zeros(snap.jobs.valid.shape[0], bool)))
        # j1 (2 cpu) fits the 4-cpu capability; j2 (2+3=5) does not
        assert admitted[maps.job_index["default/j1"]]
        assert not admitted[maps.job_index["default/j2"]]

    def test_no_capability_always_admits(self):
        from volcano_tpu.api import PodGroupPhase
        ci = simple_cluster(n_nodes=1, node_cpu="1")
        j = build_job("default/j1", min_available=1,
                      pod_group_phase=PodGroupPhase.PENDING,
                      min_resources=res(cpu="500"))
        j.add_task(build_task("p1", cpu="500", memory=0))
        ci.add_job(j)
        snap, maps = pack(ci)
        fn = jax.jit(make_enqueue_pass(EnqueueConfig()))
        assert np.array(fn(snap, np.zeros(snap.jobs.valid.shape[0], bool)))[0]

    def test_sla_overrides_gate(self):
        from volcano_tpu.api import PodGroupPhase
        ci = simple_cluster(n_nodes=1, node_cpu="1")
        del ci.queues["default"]
        q = QueueInfo("default", weight=1)
        q.capability = res(cpu="1")
        ci.add_queue(q)
        j = build_job("default/j1", min_available=1,
                      pod_group_phase=PodGroupPhase.PENDING,
                      min_resources=res(cpu="5"))
        j.add_task(build_task("p1", cpu="5", memory=0))
        ci.add_job(j)
        snap, maps = pack(ci)
        fn = jax.jit(make_enqueue_pass(EnqueueConfig()))
        sla = np.zeros(snap.jobs.valid.shape[0], bool)
        assert not np.array(fn(snap, sla))[0]
        sla[maps.job_index["default/j1"]] = True
        assert np.array(fn(snap, sla))[0]


class TestBackfill:
    def test_places_best_effort_tasks(self):
        ci = simple_cluster(n_nodes=2)
        job = build_job("default/j1", min_available=0)
        job.add_task(build_task("be-0", cpu=0, memory=0))
        job.add_task(build_task("be-1", cpu=0, memory=0))
        ci.add_job(job)
        snap, maps = pack(ci)
        t_node, placed = jax.jit(make_backfill_pass())(snap)
        for uid in ("default/be-0", "default/be-1"):
            ti = maps.task_index[uid]
            assert bool(placed[ti])
            assert int(t_node[ti]) >= 0

    def test_respects_pod_capacity(self):
        ci = simple_cluster(n_nodes=1)
        ci.nodes["n0"].max_pods = 1
        job = build_job("default/j1", min_available=0)
        job.add_task(build_task("be-0", cpu=0, memory=0))
        job.add_task(build_task("be-1", cpu=0, memory=0))
        ci.add_job(job)
        snap, maps = pack(ci)
        t_node, placed = jax.jit(make_backfill_pass())(snap)
        assert int(np.array(placed).sum()) == 1
