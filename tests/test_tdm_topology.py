"""Session-level tests for the tdm and task-topology plugins.

Reference behaviors: revocable-zone windows gate non-preemptable placement
and sweep preemptable victims outside the window (tdm.go:295-340); task
topology steers bucket-mates onto the same node (topology.go:344)."""

import datetime
import time

import numpy as np
import pytest

from volcano_tpu.api import TaskStatus
from volcano_tpu.framework import parse_conf
from volcano_tpu.plugins.tdm import REVOCABLE_ZONE_LABEL
from volcano_tpu.runtime import FakeCluster, Scheduler

from fixtures import build_job, build_node, build_task, simple_cluster


def window(offset_start_min: int, offset_end_min: int) -> str:
    """A daily window positioned relative to now."""
    t = datetime.datetime.fromtimestamp(time.time())
    lo = (t.hour * 60 + t.minute + offset_start_min) % 1440
    hi = (t.hour * 60 + t.minute + offset_end_min) % 1440
    return f"{lo // 60:02d}:{lo % 60:02d}-{hi // 60:02d}:{hi % 60:02d}"


def tdm_conf(win: str) -> str:
    return f"""
actions: "enqueue, allocate, backfill"
tiers:
- plugins:
  - name: gang
  - name: binpack
  - name: tdm
    arguments:
      tdm.revocable-zone.z1: "{win}"
"""


class TestTDM:
    def _cluster(self):
        ci = simple_cluster(n_nodes=1, node_cpu="4")
        revocable = build_node("rev0", cpu="4", memory="8Gi",
                               labels={REVOCABLE_ZONE_LABEL: "z1"})
        ci.add_node(revocable)
        return ci

    def test_revocable_node_blocks_nonpreemptable(self):
        """During an active window, a revocable node only admits preemptable
        tasks (tdm.go:295): the non-preemptable job must land on n0 even
        when rev0 is emptier."""
        ci = self._cluster()
        job = build_job("default/plain", min_available=1)
        job.add_task(build_task("p0", cpu="1"))
        ci.add_job(job)
        sched = Scheduler(FakeCluster(ci),
                          conf=parse_conf(tdm_conf(window(-60, 60))))
        sched.run_once()
        binds = dict(sched.cluster.binds)
        assert binds["default/p0"] == "n0"

    def test_preemptable_task_admitted_on_revocable_node(self):
        ci = self._cluster()
        # fill the normal node so the preemptable task must use rev0
        filler = build_job("default/filler", min_available=1)
        filler.add_task(build_task("f0", cpu="4"))
        ci.add_job(filler)
        job = build_job("default/cheap", min_available=1, preemptable=True)
        t = build_task("c0", cpu="1", preemptable=True)
        job.add_task(t)
        ci.add_job(job)
        sched = Scheduler(FakeCluster(ci),
                          conf=parse_conf(tdm_conf(window(-60, 60))))
        sched.run_once()
        binds = dict(sched.cluster.binds)
        assert binds["default/c0"] == "rev0"

    def test_victims_swept_outside_window(self):
        """Preemptable tasks on revocable nodes are eviction victims once
        the window closes (tdm victimsFn, tdm.go:298-340)."""
        ci = self._cluster()
        job = build_job("default/cheap", min_available=1, preemptable=True)
        t = build_task("c0", cpu="1", preemptable=True,
                       status=TaskStatus.RUNNING)
        job.add_task(t)
        ci.add_job(job)
        ci.nodes["rev0"].add_task(t)
        conf = tdm_conf(window(120, 180)).replace(
            'actions: "enqueue, allocate, backfill"',
            'actions: "enqueue, allocate, backfill, preempt"')
        sched = Scheduler(FakeCluster(ci), conf=parse_conf(conf))
        ssn = sched.run_once()
        assert "default/c0" in sched.cluster.evictions


class TestTaskTopology:
    def test_bucket_mate_prefers_same_node(self):
        """A pending worker whose affine ps-mate already runs on a node gets
        steered there (topology.go:344 node-order bonus)."""
        conf = """
actions: "enqueue, allocate, backfill"
tiers:
- plugins:
  - name: gang
  - name: task-topology
    arguments:
      task-topology.affinity: "ps,worker"
"""
        ci = simple_cluster(n_nodes=3, node_cpu="8")
        job = build_job("default/tf", min_available=1)
        ps = build_task("ps-0", cpu="1", role="ps", status=TaskStatus.RUNNING,
                        node_name="n2")
        job.add_task(ps)
        worker = build_task("worker-0", cpu="1", role="worker")
        job.add_task(worker)
        ci.add_job(job)
        ci.nodes["n2"].add_task(ps)
        sched = Scheduler(FakeCluster(ci), conf=parse_conf(conf))
        sched.run_once()
        binds = dict(sched.cluster.binds)
        assert binds["default/worker-0"] == "n2"


class TestReservation:
    def test_elect_reserve_protects_target(self):
        """elect picks the starving high-priority job; reserve locks the
        emptiest node each cycle; other jobs cannot take locked nodes, so
        the target eventually fits (elect.go:29-50, reserve.go:43-77)."""
        conf = parse_conf("""
actions: "enqueue, elect, reserve, allocate, backfill"
tiers:
- plugins:
  - name: gang
  - name: binpack
  - name: reservation
""")
        ci = simple_cluster(n_nodes=2, node_cpu="4")
        # the target needs a whole empty node's worth of cpu
        target = build_job("default/big", min_available=1, priority=10,
                           creation_timestamp=1.0)
        target.add_task(build_task("b0", cpu="4"))
        ci.add_job(target)
        # a stream of small jobs would otherwise nibble every node
        for i in range(2):
            small = build_job(f"default/s{i}", min_available=1, priority=0,
                              creation_timestamp=2.0 + i)
            small.add_task(build_task(f"s{i}-0", cpu="3"))
            ci.add_job(small)
        sched = Scheduler(FakeCluster(ci), conf=conf)
        for _ in range(3):
            sched.run_once()
        binds = dict(sched.cluster.binds)
        # the target got a node; the two small jobs could not both squeeze
        # in (one node was locked for the target)
        assert binds["default/b0"] in ("n0", "n1")
        placed_small = [k for k in binds if k.startswith("default/s")]
        assert len(placed_small) <= 1


class TestTDMFidelity:
    """tdm_test.go case families: inactive-window total block, node-order
    bonus, budget-capped victim batching, evict-period rate limiting."""

    def _cluster(self):
        ci = simple_cluster(n_nodes=1, node_cpu="4")
        revocable = build_node("rev0", cpu="4", memory="8Gi",
                               labels={REVOCABLE_ZONE_LABEL: "z1"})
        ci.add_node(revocable)
        return ci

    def test_inactive_window_blocks_even_preemptable(self):
        """Outside the window a revocable node admits NOTHING new —
        including preemptable tasks (tdm.go:149-156 predicate error)."""
        ci = self._cluster()
        filler = build_job("default/filler", min_available=1)
        filler.add_task(build_task("f0", cpu="4"))
        ci.add_job(filler)
        job = build_job("default/cheap", min_available=1, preemptable=True)
        job.add_task(build_task("c0", cpu="1", preemptable=True))
        ci.add_job(job)
        sched = Scheduler(FakeCluster(ci),
                          conf=parse_conf(tdm_conf(window(120, 180))))
        sched.run_once()
        binds = dict(sched.cluster.binds)
        assert "default/c0" not in binds   # rev0 closed, n0 full

    def test_active_window_bonus_steers_revocable_task(self):
        """A revocable task lands on the active revocable node even when a
        plain node has room (MaxNodeScore bonus, tdm.go:170-191)."""
        ci = self._cluster()
        job = build_job("default/cheap", min_available=1, preemptable=True)
        job.add_task(build_task("c0", cpu="1", preemptable=True))
        ci.add_job(job)
        sched = Scheduler(FakeCluster(ci),
                          conf=parse_conf(tdm_conf(window(-60, 60))))
        sched.run_once()
        binds = dict(sched.cluster.binds)
        assert binds["default/c0"] == "rev0"

    def _sweep_cluster(self, n_tasks=4, **job_kw):
        ci = self._cluster()
        job = build_job("default/cheap", min_available=1, preemptable=True,
                        **job_kw)
        for i in range(n_tasks):
            t = build_task(f"c{i}", cpu="1", preemptable=True,
                           status=TaskStatus.RUNNING)
            job.add_task(t)
            ci.nodes["rev0"].add_task(t)
        ci.add_job(job)
        return ci

    def _sweep_conf(self, win, extra_args=""):
        return f"""
actions: "enqueue, allocate, backfill, preempt"
tiers:
- plugins:
  - name: gang
  - name: tdm
    arguments:
      tdm.revocable-zone.z1: "{win}"{extra_args}
"""

    @pytest.mark.slow
    def test_sweep_caps_victims_at_default_budget(self):
        """Without a budget annotation at most defaultPodEvictNum=1 task
        per job is swept per run (tdm.go:330-340)."""
        ci = self._sweep_cluster(n_tasks=4)
        sched = Scheduler(FakeCluster(ci),
                          conf=parse_conf(self._sweep_conf(window(120, 180))))
        sched.run_once()
        assert len(sched.cluster.evictions) == 1

    # full-suite (`pytest -m slow`): the budget variant of the sweep;
    # test_victims_swept_outside_window keeps the sweep path itself in
    # tier-1 — budget calibration
    @pytest.mark.slow
    def test_sweep_respects_max_unavailable_budget(self):
        """volcano.sh/max-unavailable bounds the batch (tdm.go:318-330)."""
        ci = self._sweep_cluster(n_tasks=4, budget_max_unavailable="50%")
        sched = Scheduler(FakeCluster(ci),
                          conf=parse_conf(self._sweep_conf(window(120, 180))))
        sched.run_once()
        assert len(sched.cluster.evictions) == 2   # ceil(50% of 4)

    def test_sweep_respects_min_available_budget(self):
        """volcano.sh/min-available keeps that many running (tdm.go:331-336)."""
        ci = self._sweep_cluster(n_tasks=4, budget_min_available="3")
        sched = Scheduler(FakeCluster(ci),
                          conf=parse_conf(self._sweep_conf(window(120, 180))))
        sched.run_once()
        assert len(sched.cluster.evictions) == 1   # 4 running - 3 min

    def test_sweep_rate_limited_by_evict_period(self):
        """The sweep fires at most once per tdm.evict-period
        (tdm.go:233-236); the next period releases another batch."""
        ci = self._sweep_cluster(n_tasks=4)
        conf = self._sweep_conf(
            window(120, 180), '\n      tdm.evict-period: "1m"')
        sched = Scheduler(FakeCluster(ci), conf=parse_conf(conf))
        t0 = time.time()
        sched.run_once(now=t0)
        assert len(sched.cluster.evictions) == 1
        sched.run_once(now=t0 + 10)     # within the period: no new sweep
        assert len(sched.cluster.evictions) == 1
        sched.run_once(now=t0 + 61)     # period elapsed: next batch
        assert len(sched.cluster.evictions) == 2

    def test_preemptable_job_never_preempts(self):
        """tdm JobStarvingFn: a preemptable job cannot be a preemptor
        (tdm.go:292-298)."""
        ci = simple_cluster(n_nodes=1, node_cpu="1", node_mem="2Gi")
        lo = build_job("default/lo", min_available=1, priority=1)
        t = build_task("lo-0", cpu="1", memory="1Gi",
                       status=TaskStatus.RUNNING)
        lo.add_task(t)
        ci.nodes["n0"].add_task(t)
        ci.add_job(lo)
        hi = build_job("default/hi", min_available=1, priority=10,
                       preemptable=True)
        hi.add_task(build_task("hi-0", cpu="1", memory="1Gi",
                               preemptable=True))
        ci.add_job(hi)
        conf = """
actions: "preempt"
tiers:
- plugins:
  - name: priority
  - name: gang
  - name: tdm
"""
        sched = Scheduler(FakeCluster(ci), conf=parse_conf(conf))
        sched.run_once()
        assert sched.cluster.evictions == []


class TestJobManagerBuckets:
    """JobManager fidelity (manager.go:111-318): bucket construction from
    the pairwise matrices, anti-affinity splits, placed-node seeding, and
    the TaskOrderFn-driven pending-table reordering."""

    def _jm(self, aff="", anti="", order="", tasks=()):
        from volcano_tpu.plugins.task_topology import (JobManager,
                                                       _parse_groups)
        jm = JobManager("default/j")
        jm.apply_topology(_parse_groups(aff), _parse_groups(anti),
                          [r for r in order.split(",") if r])
        jm.construct_buckets(list(tasks))
        return jm

    def test_affine_roles_share_bucket(self):
        tasks = [build_task(f"t{i}", cpu="1", role=r)
                 for i, r in enumerate(["ps", "worker", "worker"])]
        jm = self._jm(aff="ps,worker", tasks=tasks)
        idx = {t.uid: jm.pod_in_bucket[t.uid] for t in tasks}
        assert len(set(idx.values())) == 1       # one bucket holds all

    def test_anti_affine_roles_split_buckets(self):
        tasks = [build_task(f"t{i}", cpu="1", role=r)
                 for i, r in enumerate(["a", "b", "a", "b"])]
        jm = self._jm(anti="a,b", tasks=tasks)
        buckets = {jm.pod_in_bucket[t.uid] for t in tasks}
        # a and b never share a bucket
        for b in buckets:
            roles = set(jm.buckets[b].task_name_set)
            assert roles in ({"a"}, {"b"})

    def test_self_anti_affinity_one_per_bucket(self):
        tasks = [build_task(f"t{i}", cpu="1", role="solo") for i in range(3)]
        jm = self._jm(anti="solo", tasks=tasks)
        assert len({jm.pod_in_bucket[t.uid] for t in tasks}) == 3

    def test_unmanaged_roles_out_of_bucket(self):
        from volcano_tpu.plugins.task_topology import OUT_OF_BUCKET
        tasks = [build_task("t0", cpu="1", role="ps"),
                 build_task("t1", cpu="1", role="other")]
        jm = self._jm(aff="ps,worker", tasks=tasks)
        assert jm.pod_in_bucket[tasks[1].uid] == OUT_OF_BUCKET

    def test_placed_tasks_seed_node_buckets(self):
        placed = build_task("p0", cpu="1", role="ps", node_name="n1",
                            status=TaskStatus.RUNNING)
        pend = build_task("t0", cpu="1", role="worker")
        jm = self._jm(aff="ps,worker", tasks=[placed, pend])
        b = jm.get_bucket(pend.uid)
        assert b is not None and b.node == {"n1": 1}

    def test_task_order_annotation_wins(self):
        from volcano_tpu.plugins.task_topology import JobManager
        jm = JobManager("j")
        jm.apply_topology([], [], ["worker", "ps"])
        assert jm.task_affinity_order("worker", "ps") == 1
        assert jm.task_affinity_order("ps", "worker") == -1

    def test_session_reorders_pending_table(self):
        """Bucketed tasks schedule before out-of-bucket ones regardless of
        packed insertion order (TaskOrderFn, topology.go:61-131)."""
        import numpy as np
        from volcano_tpu.framework.session import Session
        ci = simple_cluster(n_nodes=2, node_cpu="8")
        job = build_job("default/j", min_available=0)
        # insertion order: loner first, then the affine pair
        job.add_task(build_task("j-loner-0", cpu="1", role="loner"))
        job.add_task(build_task("j-ps-0", cpu="1", role="ps"))
        job.add_task(build_task("j-worker-0", cpu="1", role="worker"))
        job.annotations["volcano.sh/task-topology-affinity"] = "ps,worker"
        ci.add_job(job)
        ssn = Session(ci, parse_conf("""
actions: "allocate"
tiers:
- plugins:
  - name: gang
  - name: task-topology
"""))
        ji = ssn.maps.job_index["default/j"]
        row = np.asarray(ssn.snap.jobs.task_table)[ji]
        uids = [ssn.maps.task_uids[t] for t in row if t >= 0]
        assert uids[-1] == "default/j-loner-0"   # out-of-bucket last
        assert set(uids[:2]) == {"default/j-ps-0", "default/j-worker-0"}

    def test_bucket_steers_to_dominant_node(self):
        """A pending bucket task is steered to the node already holding
        most of its bucket (calcBucketScore base, topology.go:150-163)."""
        ci = simple_cluster(n_nodes=3, node_cpu="8")
        job = build_job("default/j", min_available=0)
        for i, node in enumerate(["n1", "n1", "n2"]):
            t = build_task(f"j-ps-{i}", cpu="1", role="ps",
                           status=TaskStatus.RUNNING, node_name=node)
            job.add_task(t)
            ci.nodes[node].add_task(t)
        job.add_task(build_task("j-worker-0", cpu="1", role="worker"))
        job.annotations["volcano.sh/task-topology-affinity"] = "ps,worker"
        ci.add_job(job)
        sched = Scheduler(FakeCluster(ci), conf=parse_conf("""
actions: "enqueue, allocate, backfill"
tiers:
- plugins:
  - name: gang
  - name: task-topology
"""))
        sched.run_once()
        assert dict(sched.cluster.binds)["default/j-worker-0"] == "n1"
