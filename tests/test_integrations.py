"""Distributed-framework integration e2e tests through the full stack.

The analog of the reference's kind-cluster e2e suites for real MPI and
TensorFlow jobs (test/e2e/jobseq/mpi.go, test/e2e/jobseq/tensorflow.go):
an MPI-shaped gang (master + workers, ssh/svc/env plugins, CompleteJob on
TaskCompleted) and a TF-shaped gang (ps + workers, svc plugin) submitted to
the assembled control plane (runtime/system.VolcanoSystem), asserting gang
placement, plugin artifacts, and lifecycle-policy-driven completion.
"""

from volcano_tpu.api.batch import Job, LifecyclePolicy, PodTemplate, TaskSpec
from volcano_tpu.api.core import PodPhase
from volcano_tpu.api.types import BusAction, BusEvent, JobPhase
from volcano_tpu.runtime.system import VolcanoSystem


def make_system(n_nodes=3, cpu="8", memory="16Gi"):
    sys_ = VolcanoSystem()
    for i in range(n_nodes):
        sys_.add_node(f"n{i}", cpu=cpu, memory=memory)
    return sys_


def mpi_job(name="mpi", workers=2):
    """The e2e MPI job shape (mpi.go:40-100): 1 master + N workers, gang of
    all, ssh/svc/env plugins, CompleteJob when the master task completes."""
    return Job(
        name=name,
        min_available=1 + workers,
        plugins={"ssh": [], "svc": [], "env": []},
        policies=[LifecyclePolicy(action=BusAction.COMPLETE_JOB,
                                  event=BusEvent.TASK_COMPLETED)],
        tasks=[
            TaskSpec(name="mpimaster", replicas=1,
                     template=PodTemplate(resources={"cpu": "1",
                                                     "memory": "1Gi"})),
            TaskSpec(name="mpiworker", replicas=workers,
                     template=PodTemplate(resources={"cpu": "1",
                                                     "memory": "1Gi"})),
        ])


def tf_job(name="tensorflow-dist-mnist", workers=2):
    """The e2e TF job shape (tensorflow.go:40-120): 1 ps + N workers, svc
    plugin for host files, CompleteJob when the worker task completes."""
    return Job(
        name=name,
        min_available=1 + workers,
        plugins={"svc": [], "env": []},
        tasks=[
            TaskSpec(name="ps", replicas=1,
                     template=PodTemplate(resources={"cpu": "1",
                                                     "memory": "1Gi"})),
            TaskSpec(name="worker", replicas=workers,
                     policies=[LifecyclePolicy(
                         action=BusAction.COMPLETE_JOB,
                         event=BusEvent.TASK_COMPLETED)],
                     template=PodTemplate(resources={"cpu": "1",
                                                     "memory": "1Gi"})),
        ])


class TestMPIIntegration:
    def test_runs_and_completes(self):
        sys_ = make_system()
        sys_.submit_job(mpi_job())
        for _ in range(3):
            sys_.tick()

        # gang placed atomically: all 3 pods running
        pods = sys_.pods_of("mpi")
        assert len(pods) == 3
        assert all(p.phase == PodPhase.RUNNING for p in pods)
        assert sys_.job("mpi").status.state.phase == JobPhase.RUNNING

        # ssh plugin: keypair secret mounted into every pod (ssh.go:64-238)
        secret = sys_.api.get("secrets", "default/mpi-ssh")
        assert secret is not None
        assert "id_rsa" in secret.data and "authorized_keys" in secret.data
        assert all("mpi-ssh" in p.volumes for p in pods)

        # svc plugin: the hostfile mpiexec reads (mpi.go command uses
        # /etc/volcano/mpiworker.host)
        cm = sys_.api.get("configmaps", "default/mpi-svc")
        assert cm.data["mpiworker.host"].splitlines() == [
            "mpi-mpiworker-0.mpi", "mpi-mpiworker-1.mpi"]
        assert "mpi-mpimaster-0.mpi" in cm.data["hosts"]

        # env plugin: indices for rank assignment
        by_name = {p.name: p for p in pods}
        assert by_name["mpi-mpiworker-1"].env["VC_TASK_INDEX"] == "1"

        # master finishes -> TaskCompleted -> CompleteJob policy: remaining
        # workers are cleaned up and the job completes (mpi.go:44-49)
        sys_.finish_pod("default/mpi-mpimaster-0", exit_code=0)
        for _ in range(4):
            sys_.tick()
        assert sys_.job("mpi").status.state.phase == JobPhase.COMPLETED

    def test_gang_blocks_partial_mpi(self):
        """Workers alone can't start: gang needs master + all workers."""
        sys_ = make_system(n_nodes=1, cpu="2")   # room for 2 of 3 pods
        sys_.submit_job(mpi_job())
        for _ in range(3):
            sys_.tick()
        pods = sys_.pods_of("mpi")
        assert all(p.phase == PodPhase.PENDING for p in pods)
        # scale up -> whole gang schedules
        sys_.add_node("n-late", cpu="8", memory="16Gi")
        for _ in range(3):
            sys_.tick()
        assert all(p.phase == PodPhase.RUNNING for p in sys_.pods_of("mpi"))


class TestTensorFlowIntegration:
    def test_runs_and_completes(self):
        sys_ = make_system()
        sys_.submit_job(tf_job())
        for _ in range(3):
            sys_.tick()

        pods = sys_.pods_of("tensorflow-dist-mnist")
        assert len(pods) == 3
        assert all(p.phase == PodPhase.RUNNING for p in pods)

        # host files for TF_CONFIG construction (tensorflow.go commands read
        # /etc/volcano/ps.host and worker.host)
        cm = sys_.api.get("configmaps", "default/tensorflow-dist-mnist-svc")
        assert cm.data["ps.host"] == "tensorflow-dist-mnist-ps-0.tensorflow-dist-mnist"
        assert len(cm.data["worker.host"].splitlines()) == 2

        # VC_<TASK>_HOSTS env lets pods build cluster specs without mounts
        ps_pod = next(p for p in pods if "ps" in p.name)
        assert "tensorflow-dist-mnist-worker-1.tensorflow-dist-mnist" in \
            ps_pod.env["VC_WORKER_HOSTS"]
        assert ps_pod.env["VK_TASK_INDEX"] == "0"

        # all workers complete -> TaskCompleted on the worker task ->
        # CompleteJob (task-level policy beats job default)
        sys_.finish_pod("default/tensorflow-dist-mnist-worker-0", 0)
        sys_.tick()
        assert sys_.job("tensorflow-dist-mnist").status.state.phase == \
            JobPhase.RUNNING   # only 1 of 2 workers done: not yet complete
        sys_.finish_pod("default/tensorflow-dist-mnist-worker-1", 0)
        for _ in range(4):
            sys_.tick()
        assert sys_.job("tensorflow-dist-mnist").status.state.phase == \
            JobPhase.COMPLETED


class TestMXNetShape:
    def test_ps_gang_places_and_publishes_hosts(self):
        import importlib.util as iu
        import os
        spec = iu.spec_from_file_location(
            "mxnet_example", os.path.join(
                os.path.dirname(__file__), "..", "examples", "integrations",
                "mxnet.py"))
        mod = iu.module_from_spec(spec)
        spec.loader.exec_module(mod)
        sys_ = make_system(n_nodes=3)
        sys_.submit_job(mod.mxnet_job(workers=2, servers=2))
        for _ in range(3):
            sys_.tick()
        pods = sys_.pods_of("mxnet-job")
        assert len([p for p in pods if p.node_name]) == 5   # full gang
        cm = sys_.api.get("configmaps", "default/mxnet-job-svc")
        assert "scheduler.host" in cm.data


class TestPaddleShape:
    def test_pserver_trainer_gang(self):
        import importlib.util as iu
        import os
        spec = iu.spec_from_file_location(
            "paddle_example", os.path.join(
                os.path.dirname(__file__), "..", "examples", "integrations",
                "paddle.py"))
        mod = iu.module_from_spec(spec)
        spec.loader.exec_module(mod)
        sys_ = make_system(n_nodes=2)
        sys_.submit_job(mod.paddle_job())
        for _ in range(3):
            sys_.tick()
        assert len([p for p in sys_.pods_of("ctr-volcano")
                    if p.node_name]) == 4


class TestMindSporeShape:
    def test_elastic_gang_starts_at_quorum(self):
        import importlib.util as iu
        import os
        spec = iu.spec_from_file_location(
            "ms_example", os.path.join(
                os.path.dirname(__file__), "..", "examples", "integrations",
                "mindspore.py"))
        mod = iu.module_from_spec(spec)
        spec.loader.exec_module(mod)
        sys_ = make_system(n_nodes=3, cpu="2", memory="8Gi")
        sys_.submit_job(mod.mindspore_job())
        for _ in range(3):
            sys_.tick()
        placed = [p for p in sys_.pods_of("mindspore-cpu") if p.node_name]
        # elastic: at least the quorum (5) places on 6 slots, not all 8
        assert 5 <= len(placed) <= 6


class TestArgoWorkflow:
    def test_dag_completion_order(self):
        import importlib.util as iu
        import os
        spec = iu.spec_from_file_location(
            "argo_example", os.path.join(
                os.path.dirname(__file__), "..", "examples", "integrations",
                "argo.py"))
        mod = iu.module_from_spec(spec)
        spec.loader.exec_module(mod)
        sys_ = make_system(n_nodes=1)
        order = mod.run_workflow(sys_, mod.DAG)
        assert order[0] == "a"
        assert order[-1] == "d"
        assert set(order) == {"a", "b", "c", "d"}
