"""Shared-GPU scheduling tests.

Reference semantics under test: GPUDevice accounting (pkg/scheduler/api/
device_info.go:24-62, node_info.go:171-195,365-415) and the GPU-sharing
predicate — a task requesting `volcano.sh/gpu-memory` must fit on ONE card,
not in the node's aggregate GPU memory (pkg/scheduler/plugins/predicates/
gpu.go:27-56).
"""

import numpy as np
import jax

from volcano_tpu.api import (GPU_MEMORY_RESOURCE, GPU_NUMBER_RESOURCE,
                             QueueInfo, TaskStatus)
from volcano_tpu.arrays import pack
from volcano_tpu.ops import AllocateConfig, MODE_ALLOCATED, make_allocate_cycle
from volcano_tpu.ops import predicates as P
from volcano_tpu.ops.allocate_scan import AllocateExtras
from volcano_tpu.runtime.cpu_reference import allocate_cpu

from fixtures import build_job, build_node, build_task, simple_cluster


def gpu_node(name, cards=2, mem_per_card=8, cpu="16", memory="64Gi"):
    return build_node(name, cpu=cpu, memory=memory,
                      scalars={GPU_MEMORY_RESOURCE: cards * mem_per_card,
                               GPU_NUMBER_RESOURCE: cards})


def gpu_task(name, gpu_mem, cpu="1", memory="1Gi"):
    return build_task(name, cpu=cpu, memory=memory,
                      scalars={GPU_MEMORY_RESOURCE: gpu_mem})


class TestGPUDeviceModel:
    def test_devices_built_from_capacity(self):
        """setNodeGPUInfo splits total memory evenly across cards
        (node_info.go:171-195)."""
        n = gpu_node("g0", cards=4, mem_per_card=8)
        assert len(n.gpu_devices) == 4
        assert all(d.memory == 8 for d in n.gpu_devices)

    def test_add_remove_task_charges_card(self):
        n = gpu_node("g0", cards=2, mem_per_card=8)
        t = gpu_task("t0", gpu_mem=6)
        t.gpu_index = 1
        t.status = TaskStatus.RUNNING
        n.add_task(t)
        assert n.gpu_devices[1].used_memory() == 6
        assert n.idle_gpu_memory() == [8, 2]
        n.remove_task(t)
        assert n.idle_gpu_memory() == [8, 8]

    def test_predicate_gpu_picks_lowest_fitting_card(self):
        n = gpu_node("g0", cards=2, mem_per_card=8)
        filler = gpu_task("f", gpu_mem=5)
        filler.gpu_index = 0
        filler.status = TaskStatus.RUNNING
        n.add_task(filler)
        assert n.predicate_gpu(gpu_task("a", gpu_mem=3)) == 0   # still fits 0
        assert n.predicate_gpu(gpu_task("b", gpu_mem=4)) == 1   # spills to 1
        assert n.predicate_gpu(gpu_task("c", gpu_mem=9)) == -1  # fits nowhere


class TestGPUFitKernel:
    def test_single_card_constraint(self):
        """Aggregate GPU memory fits but no single card does -> infeasible
        (the whole point of gpu.go:41-56)."""
        ci = simple_cluster(n_nodes=0)
        ci.add_node(gpu_node("g0", cards=2, mem_per_card=8))
        job = build_job("default/j1")
        job.add_task(gpu_task("t0", gpu_mem=10))  # 16 total, 8 per card
        ci.add_job(job)
        snap, maps = pack(ci)
        mask = P.gpu_fit(snap.tasks.gpu_request[0], snap.nodes)
        assert not bool(np.asarray(mask)[0])

    def test_non_gpu_task_unaffected(self):
        ci = simple_cluster(n_nodes=0)
        ci.add_node(gpu_node("g0"))
        job = build_job("default/j1")
        job.add_task(build_task("t0", cpu="1"))
        ci.add_job(job)
        snap, _ = pack(ci)
        mask = P.gpu_fit(snap.tasks.gpu_request[0], snap.nodes)
        assert bool(np.asarray(mask)[0])

    def test_pick_gpu_lowest_first(self):
        ci = simple_cluster(n_nodes=0)
        node = gpu_node("g0", cards=2, mem_per_card=8)
        filler = gpu_task("f", gpu_mem=5)
        filler.gpu_index = 0
        filler.status = TaskStatus.RUNNING
        node.add_task(filler)
        ci.add_node(node)
        job = build_job("default/j1")
        job.add_task(gpu_task("t0", gpu_mem=4))
        ci.add_job(job)
        snap, _ = pack(ci)
        card = P.pick_gpu(snap.tasks.gpu_request[0], snap.nodes)
        assert int(np.asarray(card)[0]) == 1  # card 0 only has 3 left


class TestGPUAllocate:
    def _run(self, ci, cfg=AllocateConfig()):
        snap, maps = pack(ci)
        extras = AllocateExtras.neutral(snap)
        tpu = jax.jit(make_allocate_cycle(cfg))(snap, extras)
        cpu = allocate_cpu(snap, extras, cfg)
        return snap, maps, tpu, cpu

    def test_two_tasks_spread_across_cards(self):
        """Two 6GB tasks on one node with 2x8GB cards: first fills card 0,
        second must take card 1 (in-cycle device accounting)."""
        ci = simple_cluster(n_nodes=0)
        ci.add_node(gpu_node("g0", cards=2, mem_per_card=8))
        job = build_job("default/j1", min_available=2)
        job.add_task(gpu_task("t0", gpu_mem=6))
        job.add_task(gpu_task("t1", gpu_mem=6))
        ci.add_job(job)
        snap, maps, tpu, cpu = self._run(ci)
        gpus = sorted(int(g) for g in np.asarray(tpu.task_gpu)[:2])
        assert gpus == [0, 1]
        assert np.asarray(tpu.task_mode)[:2].tolist() == [MODE_ALLOCATED] * 2

    def test_gang_discard_frees_gpu(self):
        """A 2-task gang whose second GPU task cannot fit discards, leaving
        the card free for a following job (statement Discard semantics)."""
        ci = simple_cluster(n_nodes=0)
        ci.add_node(gpu_node("g0", cards=1, mem_per_card=8))
        big = build_job("default/big", min_available=2)
        big.add_task(gpu_task("b0", gpu_mem=6))
        big.add_task(gpu_task("b1", gpu_mem=6))   # won't fit after b0
        ci.add_job(big)
        small = build_job("default/small", min_available=1)
        small.add_task(gpu_task("s0", gpu_mem=8))
        ci.add_job(small)
        snap, maps, tpu, cpu = self._run(ci)
        task_mode = np.asarray(tpu.task_mode)
        s0 = maps.task_index["default/s0"]
        b0 = maps.task_index["default/b0"]
        assert int(task_mode[s0]) == MODE_ALLOCATED  # got the whole card
        assert int(task_mode[b0]) == 0               # gang discarded

    def test_cpu_tpu_equivalence_with_gpus(self):
        rng = np.random.RandomState(7)
        ci = simple_cluster(n_nodes=0)
        for i in range(4):
            ci.add_node(gpu_node(f"g{i}", cards=2, mem_per_card=8))
        ci.add_queue(QueueInfo("default", weight=1))
        for j in range(6):
            job = build_job(f"default/j{j}", min_available=2)
            for t in range(2):
                job.add_task(gpu_task(f"j{j}-t{t}",
                                      gpu_mem=int(rng.randint(1, 9))))
            ci.add_job(job)
        snap, maps, tpu, cpu = self._run(ci)
        np.testing.assert_array_equal(np.asarray(tpu.task_node),
                                      cpu["task_node"])
        np.testing.assert_array_equal(np.asarray(tpu.task_mode),
                                      cpu["task_mode"])
        np.testing.assert_array_equal(np.asarray(tpu.task_gpu),
                                      cpu["task_gpu"])


class TestGPUWireFormat:
    def test_native_pack_carries_gpu_arrays(self):
        from volcano_tpu.native import available, pack_native
        if not available():
            import pytest
            pytest.skip("native packer unavailable")
        ci = simple_cluster(n_nodes=1)
        node = gpu_node("g0", cards=2, mem_per_card=8)
        filler = gpu_task("f", gpu_mem=5)
        filler.gpu_index = 1
        filler.status = TaskStatus.RUNNING
        node.add_task(filler)
        ci.add_node(node)
        job = build_job("default/j1")
        job.add_task(gpu_task("t0", gpu_mem=4))
        ci.add_job(job)
        py_snap, _ = pack(ci)
        nat_snap, _ = pack_native(ci)
        np.testing.assert_allclose(np.asarray(py_snap.nodes.gpu_memory),
                                   np.asarray(nat_snap.nodes.gpu_memory))
        np.testing.assert_allclose(np.asarray(py_snap.nodes.gpu_used),
                                   np.asarray(nat_snap.nodes.gpu_used))
        np.testing.assert_allclose(np.asarray(py_snap.tasks.gpu_request),
                                   np.asarray(nat_snap.tasks.gpu_request))


class TestNumatopology:
    def test_crd_stored_in_apiserver(self):
        """Numatopology is a cluster-scoped object per node
        (numatopo_types.go:70-88); types-only parity with the reference."""
        from volcano_tpu.api import CPUInfo, Numatopology, NumatopoSpec
        from volcano_tpu.runtime.apiserver import APIServer
        api = APIServer()
        topo = Numatopology("n0", NumatopoSpec(
            policies={"CPUManagerPolicy": "static"},
            cpu_detail={"0": CPUInfo(numa_node_id=0, socket_id=0, core_id=0)}))
        api.create("numatopologies", topo)
        assert api.get("numatopologies", "n0").spec.policies[
            "CPUManagerPolicy"] == "static"
