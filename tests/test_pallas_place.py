"""Pallas fused-round placer vs the lax.scan path: bit-identical decisions.

Runs the kernel through the pallas interpreter (tests force a CPU mesh), so
this validates the kernel logic; the TPU lowering is exercised by bench.py
and the driver's real-chip runs.
"""

import dataclasses

import numpy as np
import jax
import pytest

from volcano_tpu.api import (GPU_MEMORY_RESOURCE, GPU_NUMBER_RESOURCE,
                             QueueInfo, Taint, Toleration)
from volcano_tpu.arrays import pack
from volcano_tpu.ops import AllocateConfig, make_allocate_cycle
from volcano_tpu.ops.allocate_scan import AllocateExtras

from fixtures import build_job, build_node, build_task, simple_cluster


def run_both_paths(ci, cfg=AllocateConfig(), extras_fn=None):
    snap, maps = pack(ci)
    extras = AllocateExtras.neutral(snap)
    if extras_fn:
        extras = extras_fn(snap, maps, extras)
    scan_cfg = dataclasses.replace(cfg, use_pallas=False)
    pallas_cfg = dataclasses.replace(cfg, use_pallas="interpret")
    scan = jax.jit(make_allocate_cycle(scan_cfg))(snap, extras)
    pls = jax.jit(make_allocate_cycle(pallas_cfg))(snap, extras)
    return snap, maps, scan, pls


def assert_equal(scan, pls):
    np.testing.assert_array_equal(np.asarray(scan.task_node),
                                  np.asarray(pls.task_node))
    np.testing.assert_array_equal(np.asarray(scan.task_mode),
                                  np.asarray(pls.task_mode))
    np.testing.assert_array_equal(np.asarray(scan.task_gpu),
                                  np.asarray(pls.task_gpu))
    np.testing.assert_array_equal(np.asarray(scan.job_ready),
                                  np.asarray(pls.job_ready))
    np.testing.assert_allclose(np.asarray(scan.idle), np.asarray(pls.idle),
                               atol=1e-5)


def random_cluster(seed, n_nodes=6, n_jobs=5, gpus=False, taints=False):
    rng = np.random.RandomState(seed)
    ci = simple_cluster(n_nodes=0)
    for i in range(n_nodes):
        scalars = {}
        if gpus and i % 2 == 0:
            scalars = {GPU_MEMORY_RESOURCE: 16, GPU_NUMBER_RESOURCE: 2}
        node = build_node(f"n{i}", cpu=str(2 + int(rng.randint(4))),
                          memory="8Gi", scalars=scalars)
        if taints and i % 3 == 0:
            node.taints.append(Taint("dedicated", "batch", "PreferNoSchedule"))
        ci.add_node(node)
    ci.add_queue(QueueInfo("batch", weight=2))
    for j in range(n_jobs):
        queue = "default" if j % 2 == 0 else "batch"
        n_tasks = 1 + int(rng.randint(3))
        job = build_job(f"default/j{j}", queue=queue,
                        min_available=max(1, n_tasks - 1),
                        priority=int(rng.randint(3)))
        for t in range(n_tasks):
            scalars = {}
            if gpus and rng.rand() < 0.5:
                scalars = {GPU_MEMORY_RESOURCE: int(rng.randint(1, 10))}
            task = build_task(f"j{j}-t{t}",
                              cpu=f"{int(rng.randint(1, 4)) * 500}m",
                              memory="1Gi", priority=int(rng.randint(2)),
                              scalars=scalars)
            if taints and rng.rand() < 0.3:
                task.tolerations.append(Toleration(
                    key="dedicated", operator="Equal", value="batch",
                    effect=""))
            job.add_task(task)
        ci.add_job(job)
    return ci


class TestPallasEquivalence:
    @pytest.mark.parametrize("seed", [0, 1, 2])
    def test_random_snapshots(self, seed):
        ci = random_cluster(seed)
        _, _, scan, pls = run_both_paths(ci)
        assert_equal(scan, pls)

    @pytest.mark.parametrize("seed", [3, 4])
    def test_with_gpus(self, seed):
        ci = random_cluster(seed, gpus=True)
        _, _, scan, pls = run_both_paths(ci)
        assert_equal(scan, pls)

    def test_with_taint_scoring_and_all_weights(self):
        ci = random_cluster(7, taints=True)
        cfg = AllocateConfig(binpack_weight=1.0, least_allocated_weight=1.0,
                             most_allocated_weight=0.5, balanced_weight=1.0,
                             taint_prefer_weight=1.0)
        _, _, scan, pls = run_both_paths(ci, cfg)
        assert_equal(scan, pls)

    def test_gang_discard(self):
        ci = simple_cluster(n_nodes=1, node_cpu="2")
        big = build_job("default/big", min_available=3)
        for t in range(3):
            big.add_task(build_task(f"b{t}", cpu="1"))
        ci.add_job(big)
        small = build_job("default/small", min_available=1)
        small.add_task(build_task("s0", cpu="2"))
        ci.add_job(small)
        _, maps, scan, pls = run_both_paths(ci)
        assert_equal(scan, pls)
        assert bool(np.asarray(pls.job_ready)[maps.job_index["default/small"]])

    def test_matches_cpu_oracle(self):
        from volcano_tpu.runtime.cpu_reference import allocate_cpu
        ci = random_cluster(11, gpus=True)
        snap, maps = pack(ci)
        extras = AllocateExtras.neutral(snap)
        cfg = AllocateConfig(binpack_weight=1.0, use_pallas="interpret")
        pls = jax.jit(make_allocate_cycle(cfg))(snap, extras)
        cpu = allocate_cpu(snap, extras, cfg)
        np.testing.assert_array_equal(np.asarray(pls.task_node),
                                      cpu["task_node"])
        np.testing.assert_array_equal(np.asarray(pls.task_mode),
                                      cpu["task_mode"])
        np.testing.assert_array_equal(np.asarray(pls.task_gpu),
                                      cpu["task_gpu"])

    @pytest.mark.parametrize("seed", [0, 5, 6])
    # tier-1 runs the production batch size (derive_batching lands on
    # K=8); the smaller-K rows replay the same scenarios and run in the
    # full suite (`pytest -m slow`) — tier-1 budget calibration
    @pytest.mark.parametrize("batch", [
        pytest.param(2, marks=pytest.mark.slow),
        pytest.param(4, marks=pytest.mark.slow), 8])
    def test_batched_rounds_match_sequential(self, seed, batch):
        """K-job batched rounds (AllocateConfig.batch_jobs) are bit-exact
        with the sequential pop order when the ordering keys are static
        over commits (neutral deserved, no drf dynamics) — the safety
        argument the session relies on when auto-enabling K=8."""
        ci = random_cluster(seed, n_nodes=7, n_jobs=9, gpus=(seed % 2 == 0),
                            taints=True)
        cfg = AllocateConfig(binpack_weight=0.7, taint_prefer_weight=1.0)
        _, _, scan, _ = run_both_paths(ci, cfg)
        snap, maps = pack(ci)
        extras = AllocateExtras.neutral(snap)
        bcfg = dataclasses.replace(cfg, use_pallas="interpret",
                                   batch_jobs=batch)
        pls = jax.jit(make_allocate_cycle(bcfg))(snap, extras)
        assert_equal(scan, pls)

    @pytest.mark.parametrize("batch", [1, 4])
    def test_zero_deserved_queue_blocks_batching(self, batch):
        """A finite deserved of 0 must disqualify pop fusion AND K-job
        batching: the first commit flips the queue overused, which the
        sequential order re-checks before every subsequent pop
        (proportion.go:240-253). Scan, batched pallas, and the CPU oracle
        must all agree."""
        from volcano_tpu.runtime.cpu_reference import allocate_cpu
        ci = simple_cluster(n_nodes=4, node_cpu="8")
        for j in range(3):
            job = build_job(f"default/z{j}", min_available=1)
            job.add_task(build_task(f"z{j}-t0", cpu="1"))
            job.add_task(build_task(f"z{j}-t1", cpu="1"))
            ci.add_job(job)
        snap, maps = pack(ci)
        extras = AllocateExtras.neutral(snap)
        deserved = np.asarray(extras.queue_deserved).copy()
        deserved[maps.queue_index["default"]] = 0.0   # zero quota
        extras.queue_deserved = deserved
        cfg = AllocateConfig(binpack_weight=1.0)
        scan = jax.jit(make_allocate_cycle(
            dataclasses.replace(cfg, use_pallas=False)))(snap, extras)
        pls = jax.jit(make_allocate_cycle(dataclasses.replace(
            cfg, use_pallas="interpret", batch_jobs=batch)))(snap, extras)
        assert_equal(scan, pls)
        cpu = allocate_cpu(snap, extras, cfg)
        np.testing.assert_array_equal(np.asarray(scan.task_node),
                                      cpu["task_node"])
        np.testing.assert_array_equal(np.asarray(scan.task_mode),
                                      cpu["task_mode"])

    def test_gpu_elision_neutral(self):
        """enable_gpu=False on a GPU-free snapshot is decision-neutral
        (a zero gpu_request never charges a card, gpu.go:41-56)."""
        ci = random_cluster(8, gpus=False, taints=True)
        snap, maps = pack(ci)
        extras = AllocateExtras.neutral(snap)
        base = AllocateConfig(binpack_weight=1.0, taint_prefer_weight=1.0)
        scan = jax.jit(make_allocate_cycle(
            dataclasses.replace(base, use_pallas=False)))(snap, extras)
        nog = jax.jit(make_allocate_cycle(dataclasses.replace(
            base, use_pallas="interpret", enable_gpu=False,
            batch_jobs=4)))(snap, extras)
        assert_equal(scan, nog)


class TestPallasPipelining:
    def test_pipelined_placement_on_releasing_capacity(self):
        """A node whose idle is exhausted but whose releasing capacity covers
        the request: the scan path pipelines the task (MODE_PIPELINED on
        FutureIdle); the kernel's do_pipe branch must match exactly."""
        from volcano_tpu.api import TaskStatus
        from volcano_tpu.ops import MODE_PIPELINED
        ci = simple_cluster(n_nodes=1, node_cpu="4")
        # a releasing task occupies the whole node -> idle 0, releasing 4
        holder = build_job("default/holder", min_available=1)
        t = build_task("h0", cpu="4", status=TaskStatus.RELEASING)
        holder.add_task(t)
        ci.add_job(holder)
        ci.nodes["n0"].add_task(t)
        waiter = build_job("default/waiter", min_available=1)
        waiter.add_task(build_task("w0", cpu="2"))
        ci.add_job(waiter)
        _, maps, scan, pls = run_both_paths(ci)
        assert_equal(scan, pls)
        wi = maps.task_index["default/w0"]
        assert int(np.asarray(pls.task_mode)[wi]) == MODE_PIPELINED

    def test_pipelined_gpu_charge_on_releasing_capacity(self):
        """Same, with a GPU request: the pipelined placement must charge the
        card chosen for the in-flight cycle state identically in both paths."""
        from volcano_tpu.api import (GPU_MEMORY_RESOURCE, GPU_NUMBER_RESOURCE,
                                     TaskStatus)
        from volcano_tpu.ops import MODE_PIPELINED
        ci = simple_cluster(n_nodes=0)
        node = build_node("g0", cpu="4", memory="8Gi",
                          scalars={GPU_MEMORY_RESOURCE: 16,
                                   GPU_NUMBER_RESOURCE: 2})
        holder = build_job("default/holder", min_available=1)
        t = build_task("h0", cpu="4", status=TaskStatus.RELEASING)
        holder.add_task(t)
        ci.add_job(holder)
        node.add_task(t)
        ci.add_node(node)
        waiter = build_job("default/waiter", min_available=2)
        for i in range(2):
            waiter.add_task(build_task(f"w{i}", cpu="2",
                                       scalars={GPU_MEMORY_RESOURCE: 6}))
        ci.add_job(waiter)
        _, maps, scan, pls = run_both_paths(ci)
        assert_equal(scan, pls)
        modes = np.asarray(pls.task_mode)
        gpus = sorted(int(np.asarray(pls.task_gpu)[maps.task_index[f"default/w{i}"]])
                      for i in range(2))
        assert all(int(modes[maps.task_index[f"default/w{i}"]]) ==
                   MODE_PIPELINED for i in range(2))
        assert gpus == [0, 1]   # in-cycle card accounting on pipelined tasks


def run_dyn_paths(ci, cfg, extras_fn=None, batch=(4, 12), check_cpu=True):
    """Scan path vs the dynamic-key batched kernel (batch_rounds > 0:
    in-kernel job selection + fairness-key recompute), plus the CPU oracle.
    Returns (snap, maps, scan, dyn)."""
    from volcano_tpu.runtime.cpu_reference import allocate_cpu
    snap, maps = pack(ci)
    extras = AllocateExtras.neutral(snap)
    if extras_fn:
        extras = extras_fn(snap, maps, extras)
    scan = jax.jit(make_allocate_cycle(
        dataclasses.replace(cfg, use_pallas=False)))(snap, extras)
    dyn = jax.jit(make_allocate_cycle(dataclasses.replace(
        cfg, use_pallas="interpret", batch_jobs=batch[0],
        batch_rounds=batch[1])))(snap, extras)
    assert_equal(scan, dyn)
    np.testing.assert_array_equal(np.asarray(scan.job_pipelined),
                                  np.asarray(dyn.job_pipelined))
    if check_cpu:
        cpu = allocate_cpu(snap, extras, cfg)
        np.testing.assert_array_equal(np.asarray(scan.task_node),
                                      cpu["task_node"])
        np.testing.assert_array_equal(np.asarray(scan.task_mode),
                                      cpu["task_mode"])
    return snap, maps, scan, dyn


def dyn_cluster(seed, n_nodes=5, n_jobs=8, node_cpu="3", gpus=False,
                ns=False):
    """Capacity-scarce multi-queue cluster: the dominant-share ordering
    decides who places, so a key-recompute bug changes decisions."""
    rng = np.random.RandomState(seed)
    ci = simple_cluster(n_nodes=0)
    for i in range(n_nodes):
        scalars = {}
        if gpus and i % 2 == 0:
            scalars = {GPU_MEMORY_RESOURCE: 16, GPU_NUMBER_RESOURCE: 2}
        ci.add_node(build_node(f"n{i}", cpu=node_cpu, memory="8Gi",
                               scalars=scalars))
    ci.add_queue(QueueInfo("batch", weight=2))
    for j in range(n_jobs):
        queue = "default" if j % 2 == 0 else "batch"
        nspace = ("default" if (not ns or j % 3 == 0) else "team-a")
        n_tasks = 1 + int(rng.randint(4))
        job = build_job(f"{nspace}/j{j}", queue=queue,
                        min_available=max(1, n_tasks - 1),
                        priority=int(rng.randint(2)))
        for t in range(n_tasks):
            scalars = {}
            if gpus and rng.rand() < 0.4:
                scalars = {GPU_MEMORY_RESOURCE: int(rng.randint(1, 10))}
            job.add_task(build_task(
                f"j{j}-t{t}", cpu=f"{int(rng.randint(1, 4)) * 500}m",
                memory="1Gi", scalars=scalars))
        ci.add_job(job)
    return ci


class TestDynamicKeyRounds:
    """The dynamic-key batched kernel (in-kernel job selection +
    fairness-key recompute, ops/pallas_place._dyn_kernel) must replay the
    sequential pop order bit-identically for every dynamic-ordering
    config: drf job/namespace shares, finite proportion deserved, hdrf
    frozen-column guard, and combinations with GPU + affinity state."""

    @pytest.mark.parametrize("seed", [0, 3])
    def test_drf_job_order(self, seed):
        run_dyn_paths(dyn_cluster(seed),
                      AllocateConfig(binpack_weight=1.0, drf_job_order=True,
                                     enable_gpu=False))

    def test_drf_ns_and_job_order(self):
        run_dyn_paths(dyn_cluster(2, ns=True),
                      AllocateConfig(binpack_weight=1.0, drf_job_order=True,
                                     drf_ns_order=True, enable_gpu=False))

    def test_proportion_finite_deserved(self):
        def des_fn(snap, maps, extras):
            d = np.asarray(extras.queue_deserved).copy()
            d[maps.queue_index["default"]] = 2.5
            d[maps.queue_index["batch"]] = 4.0
            extras.queue_deserved = d
            return extras
        run_dyn_paths(dyn_cluster(1),
                      AllocateConfig(binpack_weight=1.0, enable_gpu=False),
                      extras_fn=des_fn)

    def test_zero_deserved_overused_flip(self):
        """A zero-quota queue flips overused on the FIRST commit; the
        in-kernel eligibility recompute must stop popping its jobs exactly
        like the sequential order does."""
        def zero_fn(snap, maps, extras):
            d = np.asarray(extras.queue_deserved).copy()
            d[maps.queue_index["default"]] = 0.0
            extras.queue_deserved = d
            return extras
        run_dyn_paths(dyn_cluster(7),
                      AllocateConfig(binpack_weight=1.0, enable_gpu=False),
                      extras_fn=zero_fn)

    def test_gpu_with_drf(self):
        run_dyn_paths(dyn_cluster(0, gpus=True),
                      AllocateConfig(binpack_weight=1.0, drf_job_order=True))

    # full-suite (`pytest -m slow`): the frozen-columns guard replays a
    # whole dynamic-key round matrix; the non-slow dynamic-key tests
    # keep the per-round semantics in tier-1 — budget calibration
    @pytest.mark.slow
    def test_hdrf_frozen_columns_guard(self):
        """hdrf level keys are frozen per launch and guarded (a pop after
        any commit proceeds only while the eligible set spans one queue):
        the hdrf_test.go rescaling scenario must still come out
        bit-identical through the dynamic-key kernel."""
        from test_hdrf import _hdrf_cluster
        from volcano_tpu.arrays.hierarchy import build_hierarchy
        from volcano_tpu.runtime.cpu_reference import allocate_cpu
        ci = _hdrf_cluster(
            "10", str(10 * 2 ** 30),
            [("root-sci", "root/sci", "100/50"),
             ("root-eng-dev", "root/eng/dev", "100/50/50"),
             ("root-eng-prod", "root/eng/prod", "100/50/50")],
            [("pg1", "root-sci", 10, "1", 2 ** 30),
             ("pg21", "root-eng-dev", 10, "1", 0),
             ("pg22", "root-eng-prod", 10, "0", 2 ** 30)])
        snap, maps = pack(ci)
        Q = np.asarray(snap.queues.weight).shape[0]
        J = np.asarray(snap.jobs.valid).shape[0]
        extras = AllocateExtras.neutral(snap)
        extras.hierarchy = build_hierarchy(ci, maps, Q, J)
        cfg = AllocateConfig(enable_gang=False, enable_hdrf=True,
                             drf_job_order=True)
        scan = jax.jit(make_allocate_cycle(
            dataclasses.replace(cfg, use_pallas=False)))(snap, extras)
        dyn = jax.jit(make_allocate_cycle(dataclasses.replace(
            cfg, use_pallas="interpret", batch_jobs=4,
            batch_rounds=12)))(snap, extras)
        assert_equal(scan, dyn)
        cpu = allocate_cpu(snap, extras, cfg)
        np.testing.assert_array_equal(np.asarray(scan.task_node),
                                      cpu["task_node"])

    def test_derive_batching_is_single_authority(self):
        """The one-place precondition: static-key confs get batch_jobs
        only; any dynamic-ordering evidence routes to batch_rounds; manual
        settings are respected; and the kernel builder refuses the
        illegal static-K + dynamic-keys combination outright."""
        from volcano_tpu.ops.allocate_scan import (DEFAULT_BATCH_JOBS,
                                                   DEFAULT_BATCH_ROUNDS,
                                                   derive_batching)
        neutral = np.full((2, 3), np.inf, np.float32)
        finite = neutral.copy()
        finite[1, 0] = 4.0
        static = derive_batching(AllocateConfig(), neutral)
        assert static.batch_jobs == DEFAULT_BATCH_JOBS
        assert static.batch_rounds == 0
        for dyn_cfg in (AllocateConfig(drf_job_order=True),
                        AllocateConfig(drf_ns_order=True),
                        AllocateConfig(enable_hdrf=True)):
            got = derive_batching(dyn_cfg, neutral)
            assert got.batch_rounds == DEFAULT_BATCH_ROUNDS
            assert got.batch_jobs == DEFAULT_BATCH_JOBS
        prop = derive_batching(AllocateConfig(), finite)
        assert prop.batch_rounds == DEFAULT_BATCH_ROUNDS
        zero = neutral.copy()
        zero[0, 1] = 0.0    # a zero quota counts as finite deserved
        assert derive_batching(AllocateConfig(), zero).batch_rounds > 0
        manual = derive_batching(
            AllocateConfig(drf_job_order=True, batch_jobs=2), neutral)
        assert manual.batch_jobs == 2 and manual.batch_rounds == 0
        with pytest.raises(ValueError, match="static-keys path"):
            make_allocate_cycle(AllocateConfig(
                drf_job_order=True, batch_jobs=4, use_pallas="interpret"))(
                *_tiny_snapshot())


def _tiny_snapshot():
    ci = simple_cluster(n_nodes=1)
    job = build_job("default/j", min_available=1)
    job.add_task(build_task("t", cpu="1"))
    ci.add_job(job)
    snap, _ = pack(ci)
    return snap, AllocateExtras.neutral(snap)
