"""Pallas fused-round placer vs the lax.scan path: bit-identical decisions.

Runs the kernel through the pallas interpreter (tests force a CPU mesh), so
this validates the kernel logic; the TPU lowering is exercised by bench.py
and the driver's real-chip runs.
"""

import dataclasses

import numpy as np
import jax
import pytest

from volcano_tpu.api import (GPU_MEMORY_RESOURCE, GPU_NUMBER_RESOURCE,
                             QueueInfo, Taint, Toleration)
from volcano_tpu.arrays import pack
from volcano_tpu.ops import AllocateConfig, make_allocate_cycle
from volcano_tpu.ops.allocate_scan import AllocateExtras

from fixtures import build_job, build_node, build_task, simple_cluster


def run_both_paths(ci, cfg=AllocateConfig(), extras_fn=None):
    snap, maps = pack(ci)
    extras = AllocateExtras.neutral(snap)
    if extras_fn:
        extras = extras_fn(snap, maps, extras)
    scan_cfg = dataclasses.replace(cfg, use_pallas=False)
    pallas_cfg = dataclasses.replace(cfg, use_pallas="interpret")
    scan = jax.jit(make_allocate_cycle(scan_cfg))(snap, extras)
    pls = jax.jit(make_allocate_cycle(pallas_cfg))(snap, extras)
    return snap, maps, scan, pls


def assert_equal(scan, pls):
    np.testing.assert_array_equal(np.asarray(scan.task_node),
                                  np.asarray(pls.task_node))
    np.testing.assert_array_equal(np.asarray(scan.task_mode),
                                  np.asarray(pls.task_mode))
    np.testing.assert_array_equal(np.asarray(scan.task_gpu),
                                  np.asarray(pls.task_gpu))
    np.testing.assert_array_equal(np.asarray(scan.job_ready),
                                  np.asarray(pls.job_ready))
    np.testing.assert_allclose(np.asarray(scan.idle), np.asarray(pls.idle),
                               atol=1e-5)


def random_cluster(seed, n_nodes=6, n_jobs=5, gpus=False, taints=False):
    rng = np.random.RandomState(seed)
    ci = simple_cluster(n_nodes=0)
    for i in range(n_nodes):
        scalars = {}
        if gpus and i % 2 == 0:
            scalars = {GPU_MEMORY_RESOURCE: 16, GPU_NUMBER_RESOURCE: 2}
        node = build_node(f"n{i}", cpu=str(2 + int(rng.randint(4))),
                          memory="8Gi", scalars=scalars)
        if taints and i % 3 == 0:
            node.taints.append(Taint("dedicated", "batch", "PreferNoSchedule"))
        ci.add_node(node)
    ci.add_queue(QueueInfo("batch", weight=2))
    for j in range(n_jobs):
        queue = "default" if j % 2 == 0 else "batch"
        n_tasks = 1 + int(rng.randint(3))
        job = build_job(f"default/j{j}", queue=queue,
                        min_available=max(1, n_tasks - 1),
                        priority=int(rng.randint(3)))
        for t in range(n_tasks):
            scalars = {}
            if gpus and rng.rand() < 0.5:
                scalars = {GPU_MEMORY_RESOURCE: int(rng.randint(1, 10))}
            task = build_task(f"j{j}-t{t}",
                              cpu=f"{int(rng.randint(1, 4)) * 500}m",
                              memory="1Gi", priority=int(rng.randint(2)),
                              scalars=scalars)
            if taints and rng.rand() < 0.3:
                task.tolerations.append(Toleration(
                    key="dedicated", operator="Equal", value="batch",
                    effect=""))
            job.add_task(task)
        ci.add_job(job)
    return ci


class TestPallasEquivalence:
    @pytest.mark.parametrize("seed", [0, 1, 2])
    def test_random_snapshots(self, seed):
        ci = random_cluster(seed)
        _, _, scan, pls = run_both_paths(ci)
        assert_equal(scan, pls)

    @pytest.mark.parametrize("seed", [3, 4])
    def test_with_gpus(self, seed):
        ci = random_cluster(seed, gpus=True)
        _, _, scan, pls = run_both_paths(ci)
        assert_equal(scan, pls)

    def test_with_taint_scoring_and_all_weights(self):
        ci = random_cluster(7, taints=True)
        cfg = AllocateConfig(binpack_weight=1.0, least_allocated_weight=1.0,
                             most_allocated_weight=0.5, balanced_weight=1.0,
                             taint_prefer_weight=1.0)
        _, _, scan, pls = run_both_paths(ci, cfg)
        assert_equal(scan, pls)

    def test_gang_discard(self):
        ci = simple_cluster(n_nodes=1, node_cpu="2")
        big = build_job("default/big", min_available=3)
        for t in range(3):
            big.add_task(build_task(f"b{t}", cpu="1"))
        ci.add_job(big)
        small = build_job("default/small", min_available=1)
        small.add_task(build_task("s0", cpu="2"))
        ci.add_job(small)
        _, maps, scan, pls = run_both_paths(ci)
        assert_equal(scan, pls)
        assert bool(np.asarray(pls.job_ready)[maps.job_index["default/small"]])

    def test_matches_cpu_oracle(self):
        from volcano_tpu.runtime.cpu_reference import allocate_cpu
        ci = random_cluster(11, gpus=True)
        snap, maps = pack(ci)
        extras = AllocateExtras.neutral(snap)
        cfg = AllocateConfig(binpack_weight=1.0, use_pallas="interpret")
        pls = jax.jit(make_allocate_cycle(cfg))(snap, extras)
        cpu = allocate_cpu(snap, extras, cfg)
        np.testing.assert_array_equal(np.asarray(pls.task_node),
                                      cpu["task_node"])
        np.testing.assert_array_equal(np.asarray(pls.task_mode),
                                      cpu["task_mode"])
        np.testing.assert_array_equal(np.asarray(pls.task_gpu),
                                      cpu["task_gpu"])

    @pytest.mark.parametrize("seed", [0, 5, 6])
    @pytest.mark.parametrize("batch", [2, 4, 8])
    def test_batched_rounds_match_sequential(self, seed, batch):
        """K-job batched rounds (AllocateConfig.batch_jobs) are bit-exact
        with the sequential pop order when the ordering keys are static
        over commits (neutral deserved, no drf dynamics) — the safety
        argument the session relies on when auto-enabling K=8."""
        ci = random_cluster(seed, n_nodes=7, n_jobs=9, gpus=(seed % 2 == 0),
                            taints=True)
        cfg = AllocateConfig(binpack_weight=0.7, taint_prefer_weight=1.0)
        _, _, scan, _ = run_both_paths(ci, cfg)
        snap, maps = pack(ci)
        extras = AllocateExtras.neutral(snap)
        bcfg = dataclasses.replace(cfg, use_pallas="interpret",
                                   batch_jobs=batch)
        pls = jax.jit(make_allocate_cycle(bcfg))(snap, extras)
        assert_equal(scan, pls)

    @pytest.mark.parametrize("batch", [1, 4])
    def test_zero_deserved_queue_blocks_batching(self, batch):
        """A finite deserved of 0 must disqualify pop fusion AND K-job
        batching: the first commit flips the queue overused, which the
        sequential order re-checks before every subsequent pop
        (proportion.go:240-253). Scan, batched pallas, and the CPU oracle
        must all agree."""
        from volcano_tpu.runtime.cpu_reference import allocate_cpu
        ci = simple_cluster(n_nodes=4, node_cpu="8")
        for j in range(3):
            job = build_job(f"default/z{j}", min_available=1)
            job.add_task(build_task(f"z{j}-t0", cpu="1"))
            job.add_task(build_task(f"z{j}-t1", cpu="1"))
            ci.add_job(job)
        snap, maps = pack(ci)
        extras = AllocateExtras.neutral(snap)
        deserved = np.asarray(extras.queue_deserved).copy()
        deserved[maps.queue_index["default"]] = 0.0   # zero quota
        extras.queue_deserved = deserved
        cfg = AllocateConfig(binpack_weight=1.0)
        scan = jax.jit(make_allocate_cycle(
            dataclasses.replace(cfg, use_pallas=False)))(snap, extras)
        pls = jax.jit(make_allocate_cycle(dataclasses.replace(
            cfg, use_pallas="interpret", batch_jobs=batch)))(snap, extras)
        assert_equal(scan, pls)
        cpu = allocate_cpu(snap, extras, cfg)
        np.testing.assert_array_equal(np.asarray(scan.task_node),
                                      cpu["task_node"])
        np.testing.assert_array_equal(np.asarray(scan.task_mode),
                                      cpu["task_mode"])

    def test_gpu_elision_neutral(self):
        """enable_gpu=False on a GPU-free snapshot is decision-neutral
        (a zero gpu_request never charges a card, gpu.go:41-56)."""
        ci = random_cluster(8, gpus=False, taints=True)
        snap, maps = pack(ci)
        extras = AllocateExtras.neutral(snap)
        base = AllocateConfig(binpack_weight=1.0, taint_prefer_weight=1.0)
        scan = jax.jit(make_allocate_cycle(
            dataclasses.replace(base, use_pallas=False)))(snap, extras)
        nog = jax.jit(make_allocate_cycle(dataclasses.replace(
            base, use_pallas="interpret", enable_gpu=False,
            batch_jobs=4)))(snap, extras)
        assert_equal(scan, nog)


class TestPallasPipelining:
    def test_pipelined_placement_on_releasing_capacity(self):
        """A node whose idle is exhausted but whose releasing capacity covers
        the request: the scan path pipelines the task (MODE_PIPELINED on
        FutureIdle); the kernel's do_pipe branch must match exactly."""
        from volcano_tpu.api import TaskStatus
        from volcano_tpu.ops import MODE_PIPELINED
        ci = simple_cluster(n_nodes=1, node_cpu="4")
        # a releasing task occupies the whole node -> idle 0, releasing 4
        holder = build_job("default/holder", min_available=1)
        t = build_task("h0", cpu="4", status=TaskStatus.RELEASING)
        holder.add_task(t)
        ci.add_job(holder)
        ci.nodes["n0"].add_task(t)
        waiter = build_job("default/waiter", min_available=1)
        waiter.add_task(build_task("w0", cpu="2"))
        ci.add_job(waiter)
        _, maps, scan, pls = run_both_paths(ci)
        assert_equal(scan, pls)
        wi = maps.task_index["default/w0"]
        assert int(np.asarray(pls.task_mode)[wi]) == MODE_PIPELINED

    def test_pipelined_gpu_charge_on_releasing_capacity(self):
        """Same, with a GPU request: the pipelined placement must charge the
        card chosen for the in-flight cycle state identically in both paths."""
        from volcano_tpu.api import (GPU_MEMORY_RESOURCE, GPU_NUMBER_RESOURCE,
                                     TaskStatus)
        from volcano_tpu.ops import MODE_PIPELINED
        ci = simple_cluster(n_nodes=0)
        node = build_node("g0", cpu="4", memory="8Gi",
                          scalars={GPU_MEMORY_RESOURCE: 16,
                                   GPU_NUMBER_RESOURCE: 2})
        holder = build_job("default/holder", min_available=1)
        t = build_task("h0", cpu="4", status=TaskStatus.RELEASING)
        holder.add_task(t)
        ci.add_job(holder)
        node.add_task(t)
        ci.add_node(node)
        waiter = build_job("default/waiter", min_available=2)
        for i in range(2):
            waiter.add_task(build_task(f"w{i}", cpu="2",
                                       scalars={GPU_MEMORY_RESOURCE: 6}))
        ci.add_job(waiter)
        _, maps, scan, pls = run_both_paths(ci)
        assert_equal(scan, pls)
        modes = np.asarray(pls.task_mode)
        gpus = sorted(int(np.asarray(pls.task_gpu)[maps.task_index[f"default/w{i}"]])
                      for i in range(2))
        assert all(int(modes[maps.task_index[f"default/w{i}"]]) ==
                   MODE_PIPELINED for i in range(2))
        assert gpus == [0, 1]   # in-cycle card accounting on pipelined tasks
