"""Allocate-pass tests: behavior fixtures mirroring the reference's
TestAllocate (pkg/scheduler/actions/allocate/allocate_test.go:43-279) plus
TPU-vs-CPU decision-equivalence on randomized snapshots (SURVEY.md section 4)."""

import numpy as np
import jax
import pytest

from volcano_tpu.api import QueueInfo, TaskStatus
from volcano_tpu.arrays import pack
from volcano_tpu.ops import (MODE_ALLOCATED, MODE_PIPELINED, AllocateConfig,
                             make_allocate_cycle)
from volcano_tpu.ops.allocate_scan import AllocateExtras
from volcano_tpu.runtime.cpu_reference import allocate_cpu

from fixtures import build_job, build_node, build_task, simple_cluster


def run_both(ci, cfg=AllocateConfig(), extras_fn=None):
    snap, maps = pack(ci)
    extras = AllocateExtras.neutral(snap)
    if extras_fn:
        extras = extras_fn(snap, maps, extras)
    fn = jax.jit(make_allocate_cycle(cfg))
    tpu = fn(snap, extras)
    cpu = allocate_cpu(snap, extras, cfg)
    return snap, maps, tpu, cpu


def binds(maps, task_node, task_mode):
    out = {}
    for uid, ti in maps.task_index.items():
        if int(task_mode[ti]) == MODE_ALLOCATED:
            out[uid] = maps.node_names[int(task_node[ti])]
    return out


class TestAllocateBehavior:
    def test_single_job_fits(self):
        """One gang job, two tasks, two nodes — both must bind
        (allocate_test.go case 'one Job with two Pods on one node')."""
        ci = simple_cluster(n_nodes=2, node_cpu="2", node_mem="4Gi")
        job = build_job("default/j1", min_available=2)
        job.add_task(build_task("p1", cpu="1", memory="1Gi"))
        job.add_task(build_task("p2", cpu="1", memory="1Gi"))
        ci.add_job(job)
        _, maps, tpu, cpu = run_both(ci)
        b = binds(maps, tpu.task_node, tpu.task_mode)
        assert len(b) == 2
        assert bool(tpu.job_ready[maps.job_index["default/j1"]])

    def test_gang_all_or_nothing(self):
        """minAvailable=3 but capacity for 2 -> nothing binds
        (gang discard, statement.go:352-374)."""
        ci = simple_cluster(n_nodes=1, node_cpu="2", node_mem="4Gi")
        job = build_job("default/j1", min_available=3)
        for i in range(3):
            job.add_task(build_task(f"p{i}", cpu="1", memory="1Gi"))
        ci.add_job(job)
        _, maps, tpu, cpu = run_both(ci)
        assert binds(maps, tpu.task_node, tpu.task_mode) == {}
        assert not bool(tpu.job_ready[0])
        np.testing.assert_allclose(np.array(tpu.idle)[0, 0],
                                   ci.nodes["n0"].idle.get("cpu"), atol=1e-3)

    def test_partial_gang_discard_frees_capacity_for_next_job(self):
        """Discarded gang's capacity goes to the next job in order."""
        ci = simple_cluster(n_nodes=1, node_cpu="2", node_mem="4Gi")
        big = build_job("default/big", min_available=3, priority=10)
        for i in range(3):
            big.add_task(build_task(f"b{i}", cpu="1", memory="1Gi"))
        small = build_job("default/small", min_available=1, priority=1)
        small.add_task(build_task("s0", cpu="1", memory="1Gi"))
        ci.add_job(big)
        ci.add_job(small)
        _, maps, tpu, cpu = run_both(ci)
        b = binds(maps, tpu.task_node, tpu.task_mode)
        assert b == {"default/s0": "n0"}

    def test_priority_order(self):
        """Higher-priority job wins scarce capacity (priority plugin
        JobOrderFn, priority.go:83)."""
        ci = simple_cluster(n_nodes=1, node_cpu="1", node_mem="2Gi")
        lo = build_job("default/lo", min_available=1, priority=1)
        lo.add_task(build_task("lo-0", cpu="1", memory="1Gi"))
        hi = build_job("default/hi", min_available=1, priority=5)
        hi.add_task(build_task("hi-0", cpu="1", memory="1Gi"))
        ci.add_job(lo)
        ci.add_job(hi)
        _, maps, tpu, cpu = run_both(ci)
        b = binds(maps, tpu.task_node, tpu.task_mode)
        assert b == {"default/hi-0": "n0"}

    def test_pipelining_on_releasing(self):
        """Task that fits only future idle gets Pipelined, not Allocated
        (allocate.go:200-240 Idle/FutureIdle candidate split)."""
        ci = simple_cluster(n_nodes=1, node_cpu="2", node_mem="4Gi")
        # a releasing task occupies the whole node
        rel_job = build_job("default/old", min_available=1)
        rel = build_task("old-0", cpu="2", memory="4Gi")
        rel.status = TaskStatus.RELEASING
        rel_job.add_task(rel)
        ci.nodes["n0"].add_task(rel)
        ci.add_job(rel_job)
        new = build_job("default/new", min_available=1)
        new.add_task(build_task("new-0", cpu="2", memory="4Gi"))
        ci.add_job(new)
        _, maps, tpu, cpu = run_both(ci)
        ti = maps.task_index["default/new-0"]
        assert int(tpu.task_mode[ti]) == MODE_PIPELINED
        assert bool(tpu.job_pipelined[maps.job_index["default/new"]])
        assert binds(maps, tpu.task_node, tpu.task_mode) == {}

    def test_closed_queue_skipped(self):
        from volcano_tpu.api import QueueState
        ci = simple_cluster(n_nodes=1)
        ci.add_queue(QueueInfo("closed", state=QueueState.CLOSED))
        job = build_job("default/j1", queue="closed", min_available=1)
        job.add_task(build_task("p0"))
        ci.add_job(job)
        _, maps, tpu, cpu = run_both(ci)
        assert binds(maps, tpu.task_node, tpu.task_mode) == {}

    def test_best_effort_skipped_in_allocate(self):
        """Zero-request tasks are backfill's business (backfill.go:40-93)."""
        ci = simple_cluster(n_nodes=1)
        job = build_job("default/j1", min_available=0)
        job.add_task(build_task("be", cpu=0, memory=0))
        ci.add_job(job)
        _, maps, tpu, cpu = run_both(ci)
        assert binds(maps, tpu.task_node, tpu.task_mode) == {}

    def test_node_selector_constrains_placement(self):
        ci = simple_cluster(n_nodes=3)
        ci.nodes["n2"].labels = {"disk": "ssd"}
        job = build_job("default/j1", min_available=1)
        job.add_task(build_task("p0", node_selector={"disk": "ssd"}))
        ci.add_job(job)
        _, maps, tpu, cpu = run_both(ci)
        assert binds(maps, tpu.task_node, tpu.task_mode) == {"default/p0": "n2"}

    def test_overused_queue_skipped(self):
        """A queue already allocated beyond its deserved share is skipped
        entirely (proportion Overused, proportion.go:240-253)."""
        ci = simple_cluster(n_nodes=2, node_cpu="2")
        ci.add_queue(QueueInfo("qa", weight=1))
        ci.add_queue(QueueInfo("qb", weight=1))
        ja = build_job("default/ja", queue="qa", min_available=1)
        running = build_task("a-run", cpu="2", memory=0)
        running.status = TaskStatus.RUNNING
        ja.add_task(running)
        ci.nodes["n1"].add_task(running)
        ja.add_task(build_task("a0", cpu="1", memory=0))
        jb = build_job("default/jb", queue="qb", min_available=1)
        jb.add_task(build_task("b0", cpu="1", memory=0))
        ci.add_job(ja)
        ci.add_job(jb)
        snap, maps = pack(ci)
        extras = AllocateExtras.neutral(snap)
        # qa deserved only 1 cpu but has 2 allocated -> overused -> skipped
        deserved = np.array(extras.queue_deserved)
        deserved[maps.queue_index["qa"]] = 1000.0
        extras.queue_deserved = deserved
        fn = jax.jit(make_allocate_cycle(AllocateConfig()))
        tpu = fn(snap, extras)
        b = binds(maps, tpu.task_node, tpu.task_mode)
        assert "default/a0" not in b
        assert b.get("default/b0") is not None


NODE_CPUS = ["1", "2", "4", "8"]


class TestDecisionEquivalence:
    @pytest.mark.parametrize("seed", range(6))
    def test_random_snapshots_match_cpu(self, seed):
        rng = np.random.RandomState(seed)
        ci = simple_cluster(n_nodes=0)
        for i in range(rng.randint(2, 6)):
            ci.add_node(build_node(
                f"n{i}", cpu=NODE_CPUS[rng.randint(len(NODE_CPUS))],
                memory="8Gi",
                labels={"zone": f"z{rng.randint(2)}"}))
        ci.add_queue(QueueInfo("default", weight=1))
        ci.add_queue(QueueInfo("q2", weight=2))
        for j in range(rng.randint(1, 5)):
            queue = "default" if rng.rand() < 0.5 else "q2"
            n_tasks = rng.randint(1, 4)
            job = build_job(f"default/j{j}", queue=queue,
                            min_available=rng.randint(1, n_tasks + 1),
                            priority=int(rng.randint(3)))
            for t in range(n_tasks):
                kw = {}
                if rng.rand() < 0.3:
                    kw["node_selector"] = {"zone": f"z{rng.randint(2)}"}
                job.add_task(build_task(f"j{j}-t{t}",
                                        cpu=str(rng.randint(1, 3)),
                                        memory="1Gi", **kw))
            ci.add_job(job)
        cfg = AllocateConfig(binpack_weight=float(rng.rand() < 0.5))
        snap, maps, tpu, cpu = run_both(ci, cfg=cfg)
        np.testing.assert_array_equal(np.array(tpu.task_node), cpu["task_node"])
        np.testing.assert_array_equal(np.array(tpu.task_mode), cpu["task_mode"])
        np.testing.assert_array_equal(np.array(tpu.job_ready), cpu["job_ready"])
        np.testing.assert_allclose(np.array(tpu.idle), cpu["idle"], atol=1e-2)
