"""Job admission validation matrix — mirrors the case families of the
reference's admit_job_test.go:1-1351 (policy event/action allowlists,
duplicates, exit codes, the AnyEvent-exclusivity rule, update immutability)."""

import pytest

from volcano_tpu.api.batch import (Job, LifecyclePolicy, PodTemplate,
                                   TaskSpec, VolumeSpec)
from volcano_tpu.api.types import BusAction, BusEvent
from volcano_tpu.webhooks import AdmissionError
from volcano_tpu.webhooks.jobs import (mutate_job, validate_job_create,
                                       validate_job_update)


def job(policies=None, tasks=None, **kw):
    return Job(name="j", tasks=tasks or [
        TaskSpec(name="w", replicas=2,
                 template=PodTemplate(resources={"cpu": "1"}))],
        policies=policies or [], **kw)


def ok(j):
    validate_job_create(j)


def bad(j, fragment):
    with pytest.raises(AdmissionError) as e:
        validate_job_create(j)
    assert fragment in str(e.value)


class TestPolicyMatrix:
    def test_valid_external_events(self):
        for ev in (BusEvent.POD_FAILED, BusEvent.POD_EVICTED,
                   BusEvent.TASK_COMPLETED, BusEvent.JOB_UNKNOWN):
            ok(job(policies=[LifecyclePolicy(action=BusAction.RESTART_JOB,
                                             event=ev)]))

    def test_internal_events_rejected(self):
        for ev in (BusEvent.OUT_OF_SYNC, BusEvent.COMMAND_ISSUED):
            bad(job(policies=[LifecyclePolicy(action=BusAction.RESTART_JOB,
                                              event=ev)]),
                "invalid policy event")

    def test_internal_actions_rejected(self):
        for act in (BusAction.SYNC_JOB, BusAction.ENQUEUE_JOB):
            bad(job(policies=[LifecyclePolicy(action=act,
                                              event=BusEvent.POD_FAILED)]),
                "invalid policy action")

    def test_event_and_exit_code_mutually_exclusive(self):
        bad(job(policies=[LifecyclePolicy(action=BusAction.ABORT_JOB,
                                          event=BusEvent.POD_FAILED,
                                          exit_code=1)]),
            "simultaneously")

    def test_neither_event_nor_exit_code(self):
        bad(job(policies=[LifecyclePolicy(action=BusAction.ABORT_JOB)]),
            "either event or exitCode")

    def test_zero_exit_code(self):
        bad(job(policies=[LifecyclePolicy(action=BusAction.ABORT_JOB,
                                          exit_code=0)]),
            "0 is not a valid error code")

    def test_duplicate_exit_code(self):
        bad(job(policies=[
            LifecyclePolicy(action=BusAction.ABORT_JOB, exit_code=3),
            LifecyclePolicy(action=BusAction.RESTART_JOB, exit_code=3)]),
            "duplicate exitCode")

    def test_duplicate_event_across_policies(self):
        bad(job(policies=[
            LifecyclePolicy(action=BusAction.ABORT_JOB,
                            event=BusEvent.POD_FAILED),
            LifecyclePolicy(action=BusAction.RESTART_JOB,
                            event=BusEvent.POD_FAILED)]),
            "duplicate event")

    def test_any_event_must_be_alone(self):
        bad(job(policies=[
            LifecyclePolicy(action=BusAction.ABORT_JOB, event=BusEvent.ANY),
            LifecyclePolicy(action=BusAction.RESTART_JOB,
                            event=BusEvent.POD_EVICTED)]),
            "no other policy")

    def test_task_level_policies_validated(self):
        t = TaskSpec(name="w", replicas=1,
                     policies=[LifecyclePolicy(action=BusAction.SYNC_JOB,
                                               event=BusEvent.POD_FAILED)],
                     template=PodTemplate(resources={"cpu": "1"}))
        bad(job(tasks=[t]), "invalid policy action")


class TestSpecRules:
    def test_min_available_exceeds_replicas(self):
        bad(job(min_available=5), "minAvailable")

    def test_duplicate_task_names(self):
        tasks = [TaskSpec(name="w", replicas=1,
                          template=PodTemplate(resources={"cpu": "1"})),
                 TaskSpec(name="w", replicas=1,
                          template=PodTemplate(resources={"cpu": "1"}))]
        bad(job(tasks=tasks), "duplicated task name")

    def test_bad_dns_name(self):
        tasks = [TaskSpec(name="Not_DNS", replicas=1,
                          template=PodTemplate(resources={"cpu": "1"}))]
        bad(job(tasks=tasks), "DNS-1123")

    def test_duplicate_mount_path(self):
        j = job()
        j.volumes = [VolumeSpec(mount_path="/data", storage="1Gi"),
                     VolumeSpec(mount_path="/data", storage="1Gi")]
        bad(j, "duplicated mountPath")

    def test_update_immutability(self):
        old = mutate_job(job())
        new = mutate_job(job())
        new.queue = "other"
        with pytest.raises(AdmissionError):
            validate_job_update(old, new)

    def test_update_replicas_allowed(self):
        old = mutate_job(job())
        new = mutate_job(job())
        new.tasks[0].replicas = 4
        new.min_available = 4
        for t in new.tasks:
            t.min_available = None
        validate_job_update(old, new)
