"""Cluster-fixture builders for tests.

The Python analog of the reference's util.BuildPod/BuildNode/BuildResourceList
(pkg/scheduler/util/test_utils.go:30-93): construct ClusterInfo snapshots by
hand, feed them to sessions/actions, and assert on the resulting bind maps.
"""

from __future__ import annotations

from typing import Dict, Optional

from volcano_tpu.api import (ClusterInfo, JobInfo, NodeInfo, PodGroupPhase,
                             QueueInfo, Resource, TaskInfo, TaskStatus)


def res(cpu=0, memory=0, **scalars) -> Resource:
    rl = {}
    if cpu:
        rl["cpu"] = cpu
    if memory:
        rl["memory"] = memory
    rl.update(scalars)
    return Resource.from_resource_list(rl)


def build_node(name: str, cpu="4", memory="8Gi", labels: Optional[Dict] = None,
               max_pods: int = 110, **kw) -> NodeInfo:
    allocatable = res(cpu=cpu, memory=memory,
                      **kw.pop("scalars", {}))
    return NodeInfo(name, allocatable=allocatable, labels=labels or {},
                    max_pods=max_pods, **kw)


def build_task(name: str, cpu="1", memory="1Gi", namespace="default",
               status=TaskStatus.PENDING, node_name="", priority=0,
               role="", **kw) -> TaskInfo:
    t = TaskInfo(uid=f"{namespace}/{name}", name=name, namespace=namespace,
                 resreq=res(cpu=cpu, memory=memory, **kw.pop("scalars", {})),
                 status=status, priority=priority, task_role=role, **kw)
    t.node_name = node_name
    return t


def build_job(uid: str, queue="default", min_available=1, priority=0,
              namespace="default", **kw) -> JobInfo:
    # Fixtures build already-admitted gangs (phase Inqueue) so action tests can
    # run allocate directly, the way the reference's allocate_test.go builds
    # PodGroups already past the enqueue gate.
    kw.setdefault("pod_group_phase", PodGroupPhase.INQUEUE)
    name = uid.split("/")[-1]
    return JobInfo(uid=uid, name=name, namespace=namespace, queue=queue,
                   priority=priority, min_available=min_available, **kw)


def simple_cluster(n_nodes=2, node_cpu="4", node_mem="8Gi") -> ClusterInfo:
    ci = ClusterInfo()
    for i in range(n_nodes):
        ci.add_node(build_node(f"n{i}", cpu=node_cpu, memory=node_mem))
    ci.add_queue(QueueInfo("default", weight=1))
    return ci


def place_running(ci: ClusterInfo, job: JobInfo, task: TaskInfo,
                  node: str) -> None:
    """Attach a Running task to a job and account it on a node."""
    task.status = TaskStatus.RUNNING
    job.add_task(task)
    ci.nodes[node].add_task(task)
