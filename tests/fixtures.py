"""Cluster-fixture builders for tests.

The Python analog of the reference's util.BuildPod/BuildNode/BuildResourceList
(pkg/scheduler/util/test_utils.go:30-93): construct ClusterInfo snapshots by
hand, feed them to sessions/actions, and assert on the resulting bind maps.
"""

from __future__ import annotations

from typing import Dict, Optional

from volcano_tpu.api import (ClusterInfo, JobInfo, NodeInfo, PodGroupPhase,
                             QueueInfo, Resource, TaskInfo, TaskStatus)


def res(cpu=0, memory=0, **scalars) -> Resource:
    rl = {}
    if cpu:
        rl["cpu"] = cpu
    if memory:
        rl["memory"] = memory
    rl.update(scalars)
    return Resource.from_resource_list(rl)


def build_node(name: str, cpu="4", memory="8Gi", labels: Optional[Dict] = None,
               max_pods: int = 110, **kw) -> NodeInfo:
    allocatable = res(cpu=cpu, memory=memory,
                      **kw.pop("scalars", {}))
    return NodeInfo(name, allocatable=allocatable, labels=labels or {},
                    max_pods=max_pods, **kw)


def build_task(name: str, cpu="1", memory="1Gi", namespace="default",
               status=TaskStatus.PENDING, node_name="", priority=0,
               role="", **kw) -> TaskInfo:
    t = TaskInfo(uid=f"{namespace}/{name}", name=name, namespace=namespace,
                 resreq=res(cpu=cpu, memory=memory, **kw.pop("scalars", {})),
                 status=status, priority=priority, task_role=role, **kw)
    t.node_name = node_name
    return t


def build_job(uid: str, queue="default", min_available=1, priority=0,
              namespace="default", **kw) -> JobInfo:
    # Fixtures build already-admitted gangs (phase Inqueue) so action tests can
    # run allocate directly, the way the reference's allocate_test.go builds
    # PodGroups already past the enqueue gate.
    kw.setdefault("pod_group_phase", PodGroupPhase.INQUEUE)
    name = uid.split("/")[-1]
    return JobInfo(uid=uid, name=name, namespace=namespace, queue=queue,
                   priority=priority, min_available=min_available, **kw)


def simple_cluster(n_nodes=2, node_cpu="4", node_mem="8Gi") -> ClusterInfo:
    ci = ClusterInfo()
    for i in range(n_nodes):
        ci.add_node(build_node(f"n{i}", cpu=node_cpu, memory=node_mem))
    ci.add_queue(QueueInfo("default", weight=1))
    return ci


def place_running(ci: ClusterInfo, job: JobInfo, task: TaskInfo,
                  node: str) -> None:
    """Attach a Running task to a job and account it on a node."""
    task.status = TaskStatus.RUNNING
    job.add_task(task)
    ci.nodes[node].add_task(task)


def make_cluster() -> ClusterInfo:
    """A deliberately messy cluster exercising every packed encoding:
    labels, taints, tolerations, selectors, affinity, hierarchy queues,
    namespace weights, mixed statuses, unknown queues, scalar resources."""
    from volcano_tpu.api.job_info import Taint, Toleration
    from volcano_tpu.api.cluster_info import NamespaceInfo
    from volcano_tpu.api import QueueState

    ci = ClusterInfo()
    ci.add_node(build_node("n0", cpu="8", memory="16Gi",
                           labels={"zone": "a", "disk": "ssd"}))
    n1 = build_node("n1", cpu="4", memory="8Gi", labels={"zone": "b"},
                    scalars={"nvidia.com/gpu": "2"})
    n1.taints = [Taint(key="dedicated", value="batch", effect="NoSchedule"),
                 Taint(key="flaky", value="", effect="PreferNoSchedule")]
    ci.add_node(n1)
    n2 = build_node("n2", cpu="2", memory="4Gi", max_pods=3)
    n2.unschedulable = True
    ci.add_node(n2)
    n3 = build_node("n3", cpu="16", memory="32Gi")
    n3.ready = False
    ci.add_node(n3)

    ci.add_queue(QueueInfo("default", weight=1))
    ci.add_queue(QueueInfo("root", weight=1, hierarchy="/root",
                           hierarchy_weights="1"))
    ci.add_queue(QueueInfo("sci", weight=2, hierarchy="/root/sci",
                           hierarchy_weights="1/2",
                           capability=res(cpu=6, memory="12Gi")))
    ci.add_queue(QueueInfo("closed", weight=3, state=QueueState.CLOSED))

    j0 = build_job("default/j0", queue="default", min_available=2, priority=5,
                   creation_timestamp=10.0)
    j0.add_task(build_task("j0-a", cpu="1", memory="1Gi", priority=2))
    t = build_task("j0-b", cpu="2", memory="2Gi", priority=7)
    t.node_selector = {"zone": "a"}
    t.tolerations = [Toleration(key="dedicated", operator="Equal",
                                value="batch", effect="NoSchedule"),
                     Toleration(key="flaky", operator="Exists"),
                     Toleration(key="", operator="Exists")]
    j0.add_task(t)
    run = build_task("j0-c", cpu="1", memory="1Gi",
                     status=TaskStatus.RUNNING, node_name="n0")
    j0.add_task(run)
    ci.nodes["n0"].add_task(run)
    ci.add_job(j0)

    j1 = build_job("team/j1", queue="sci", min_available=1,
                   namespace="team", creation_timestamp=3.0)
    t = build_task("j1-a", cpu="500m", memory="512Mi", namespace="team")
    t.affinity_required = [{"disk": "ssd"}]
    j1.add_task(t)
    j1.add_task(build_task("j1-gpu", cpu="1", memory="1Gi", namespace="team",
                           scalars={"nvidia.com/gpu": "1"}))
    ci.add_job(j1)

    # best-effort task, job in an unknown queue, and a gang-invalid job
    j2 = build_job("default/j2", queue="ghost", min_available=1,
                   creation_timestamp=3.0)
    j2.add_task(build_task("j2-a", cpu=0, memory=0))
    ci.add_job(j2)
    j3 = build_job("default/j3", queue="default", min_available=5,
                   creation_timestamp=1.0)  # 5 > 1 task: gang-invalid
    j3.add_task(build_task("j3-a", cpu="1", memory="1Gi"))
    ci.add_job(j3)
    j4 = build_job("default/j4", queue="closed", min_available=1,
                   pod_group_phase=PodGroupPhase.PENDING,
                   creation_timestamp=2.0, preemptable=True)
    t = build_task("j4-a", cpu="1", memory="1Gi", preemptable=True,
                   status=TaskStatus.BOUND, node_name="n1")
    j4.add_task(t)
    j4.add_task(build_task("j4-b", cpu="1", memory="1Gi", preemptable=True))
    ci.add_job(j4)

    ci.namespaces["team"] = NamespaceInfo("team", weight=4)
    return ci
