"""Native (C++) packer equivalence: wire -> vc_pack arrays == arrays/pack.py.

The pure-Python packer is the oracle; every field of every array must match
bit-for-bit on clusters exercising labels, taints, tolerations, selectors,
hierarchy queues, mixed task statuses, unknown queues, and empty corners.
"""

import dataclasses

import numpy as np
import pytest

from volcano_tpu import native
from volcano_tpu.arrays.pack import pack
from volcano_tpu.native.wire import serialize

from fixtures import make_cluster  # noqa: E402

pytestmark = pytest.mark.skipif(
    not native.available(),
    reason=f"native packer unavailable: {native.build_error()}")


def assert_snapshots_equal(a, b):
    flat_a = dataclasses.asdict(a)
    flat_b = dataclasses.asdict(b)

    def walk(pa, pb, path):
        if isinstance(pa, dict):
            assert set(pa) == set(pb), path
            for k in pa:
                walk(pa[k], pb[k], f"{path}.{k}")
            return
        pa, pb = np.asarray(pa), np.asarray(pb)
        assert pa.shape == pb.shape, f"{path}: {pa.shape} vs {pb.shape}"
        assert pa.dtype == pb.dtype, f"{path}: {pa.dtype} vs {pb.dtype}"
        np.testing.assert_array_equal(pa, pb, err_msg=path)

    walk(flat_a, flat_b, "snap")


def assert_maps_equal(ma, mb):
    assert ma.node_names == mb.node_names
    assert ma.task_uids == mb.task_uids
    assert ma.job_uids == mb.job_uids
    assert ma.queue_names == mb.queue_names
    assert ma.namespace_names == mb.namespace_names
    assert ma.resource_names == mb.resource_names
    assert ma.node_index == mb.node_index
    assert ma.task_index == mb.task_index


def test_native_matches_python_on_rich_cluster():
    ci = make_cluster()
    snap_py, maps_py = pack(ci)
    snap_cc, maps_cc = native.pack_native(ci)
    assert_snapshots_equal(snap_py, snap_cc)
    assert_maps_equal(maps_py, maps_cc)


def test_native_matches_python_on_synthetic_scale():
    from __graft_entry__ import _synthetic_cluster
    ci = _synthetic_cluster(n_nodes=64, n_jobs=24, tasks_per_job=5)
    snap_py, _ = pack(ci)
    snap_cc, _ = native.pack_native(ci)
    assert_snapshots_equal(snap_py, snap_cc)


def test_native_matches_python_on_empty_cluster():
    from volcano_tpu.api import ClusterInfo
    ci = ClusterInfo()
    snap_py, _ = pack(ci)
    snap_cc, _ = native.pack_native(ci)
    assert_snapshots_equal(snap_py, snap_cc)


def test_wire_rejects_garbage():
    with pytest.raises(ValueError):
        native.pack_wire(b"\x00" * 64)
    with pytest.raises(ValueError):
        # valid magic, truncated body
        buf, _ = serialize(make_cluster())
        native.pack_wire(buf[: len(buf) // 2])


def test_wire_rejects_crafted_huge_counts():
    # valid magic + counts far beyond the buffer must raise, not abort the
    # process with bad_alloc / heap corruption
    import struct
    hdr = struct.pack("<7I", 0x31534356, 1, 0, 0, 0, 0, 0xFFFFFFFF)
    with pytest.raises(ValueError):
        native.pack_wire(hdr + b"\x00" * 256)
    hdr = struct.pack("<7I", 0x31534356, 1024, 2**31, 0, 2**31, 0, 2**31)
    with pytest.raises(ValueError):
        native.pack_wire(hdr + b"\x00" * 1024)


def test_pack_best_effort_runs():
    ci = make_cluster()
    snap, maps = native.pack_best_effort(ci)
    assert snap.nodes.idle.ndim == 2
    assert maps.resource_names[0] == "cpu"


# ---------------------------------------------------------------- pywire
# The pure-Python VCS1 parser (native/pywire.py) is the sidecar's fallback
# when g++ is unavailable; it must match the C++ packer bit-for-bit.

def test_pywire_matches_native_on_rich_cluster():
    from volcano_tpu.native.pywire import pack_wire_py
    buf, _ = serialize(make_cluster())
    assert_snapshots_equal(native.pack_wire(buf), pack_wire_py(buf))


def test_pywire_matches_native_on_synthetic_scale():
    from __graft_entry__ import _synthetic_cluster
    from volcano_tpu.native.pywire import pack_wire_py
    ci = _synthetic_cluster(n_nodes=64, n_jobs=24, tasks_per_job=5)
    buf, _ = serialize(ci)
    assert_snapshots_equal(native.pack_wire(buf), pack_wire_py(buf))


def test_pywire_matches_native_on_empty_cluster():
    from volcano_tpu.api import ClusterInfo
    from volcano_tpu.native.pywire import pack_wire_py
    buf, _ = serialize(ClusterInfo())
    assert_snapshots_equal(native.pack_wire(buf), pack_wire_py(buf))


def test_pywire_rejects_garbage():
    from volcano_tpu.native.pywire import pack_wire_py
    with pytest.raises(ValueError):
        pack_wire_py(b"\x00" * 64)
    with pytest.raises(ValueError):
        buf, _ = serialize(make_cluster())
        pack_wire_py(buf[: len(buf) // 2])


class TestIncrementalWire:
    """IncrementalWire must produce byte-identical buffers to a fresh
    serialize() across steady-state churn, falling back to the full path
    on entity-set or task-set changes."""

    def _cluster(self):
        from fixtures import build_job, build_task, simple_cluster
        ci = simple_cluster(n_nodes=6, node_cpu="8", node_mem="16Gi")
        for j in range(8):
            job = build_job(f"default/j{j}", min_available=2,
                            creation_timestamp=float(j))
            for t in range(4):
                job.add_task(build_task(f"j{j}-t{t}", cpu="1",
                                        memory="1Gi"))
            ci.add_job(job)
        return ci

    def test_patched_buffer_equals_fresh(self):
        from volcano_tpu.api import TaskStatus
        from volcano_tpu.native.wire import IncrementalWire, serialize
        ci = self._cluster()
        inc = IncrementalWire()
        buf0, maps0 = inc.serialize(ci)
        assert buf0 == serialize(ci)[0]
        # churn: bind a gang, start it running, complete another
        uids = list(ci.jobs)
        dirty_jobs, dirty_nodes = set(), set()
        names = sorted(ci.nodes)
        for k, task in enumerate(ci.jobs[uids[0]].tasks.values()):
            node = ci.nodes[names[k % len(names)]]
            task.status = TaskStatus.BOUND
            ci.jobs[uids[0]].update_task_status(task, TaskStatus.RUNNING)
            node.add_task(task)
            dirty_nodes.add(node.name)
        dirty_jobs.add(uids[0])
        for task in ci.jobs[uids[1]].tasks.values():
            ci.jobs[uids[1]].update_task_status(task, TaskStatus.SUCCEEDED)
        dirty_jobs.add(uids[1])
        buf1, _ = inc.serialize(ci, dirty_jobs=dirty_jobs,
                                dirty_nodes=dirty_nodes)
        assert inc.incremental_serializes == 1
        assert buf1 == serialize(ci)[0]
        # queue spec edit needs no dirty mark (records rebuild wholesale)
        ci.queues["default"].weight = 7
        buf2, _ = inc.serialize(ci)
        assert inc.incremental_serializes == 2
        assert buf2 == serialize(ci)[0]

    def test_structural_changes_fall_back(self):
        from fixtures import build_job, build_task
        from volcano_tpu.native.wire import IncrementalWire, serialize
        ci = self._cluster()
        inc = IncrementalWire()
        inc.serialize(ci)
        job = build_job("default/new", min_available=1,
                        creation_timestamp=99.0)
        job.add_task(build_task("new-t0", cpu="1", memory="1Gi"))
        ci.add_job(job)
        buf, _ = inc.serialize(ci, dirty_jobs={"default/new"})
        assert inc.full_serializes == 2 and inc.incremental_serializes == 0
        assert buf == serialize(ci)[0]

    def test_task_set_change_falls_back(self):
        from fixtures import build_task
        from volcano_tpu.native.wire import IncrementalWire, serialize
        ci = self._cluster()
        inc = IncrementalWire()
        inc.serialize(ci)
        uid = list(ci.jobs)[2]
        ci.jobs[uid].add_task(build_task("j2-extra", cpu="1", memory="1Gi"))
        buf, _ = inc.serialize(ci, dirty_jobs={uid})
        assert inc.full_serializes == 2
        assert buf == serialize(ci)[0]
