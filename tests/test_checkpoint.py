"""Crash-consistent checkpoint/restore (ISSUE 10 acceptance).

- Checkpoint file edge cases: truncation, a flipped byte (sha mismatch),
  a future schema version, foreign magic, kind mismatch — every one
  degrades to a fallback/cold outcome, never an exception.
- The restart matrix: process_kill at pre-dispatch / in-flight /
  post-drain, scheduler restored from the checkpoint each time, applied
  decisions sha-identical to the uninterrupted run — including the
  corrupt-checkpoint leg landing on the ``fallback`` rung and STILL
  finishing identical.
- A checkpoint taken mid-pipelined-cycle drains the pending cycle first
  (depth-1 makes the early drain decision-neutral), so a restore never
  replays a half-applied bind.
- Warm restart on the pallas-interpret DeltaKernel path: a mirror
  checkpointed mid-run, digest-verified and re-adopted, continues the
  decision stream bit-identically; a tampered mirror is dropped to a
  cold re-fuse instead.
- ResyncQueue.redrive gives dead letters a second life after restore.
- The sidecar resumes its replay cache / epoch set / staged decisions
  across checkpoint+restore, and a client whose server restarted
  WITHOUT state re-primes via the structured ERR_EPOCH_RESTORED code
  instead of a timeout.
- CrashLoopSupervisor restarts a crashing serve loop with capped
  backoff and eventually surfaces the error.
"""

import hashlib
import os
import struct

import numpy as np
import pytest

from volcano_tpu.metrics import METRICS
from volcano_tpu.runtime import checkpoint as ckpt
from volcano_tpu.runtime.fake_cluster import FakeCluster
from volcano_tpu.runtime.scheduler import ResyncQueue, Scheduler

from fixtures import build_job, build_task, simple_cluster
from test_delta_pipeline import PARITY_CONF
from test_runtime_incremental import build_cluster


# ------------------------------------------------------- file edge cases
class TestCheckpointFile:
    def _write(self, tmp_path, state=None):
        path = str(tmp_path / "t.vckp")
        ckpt.write_checkpoint(path, "scheduler", state or {"x": 1})
        return path

    def test_roundtrip(self, tmp_path):
        path = self._write(tmp_path, {"cycles": 7})
        env, reason = ckpt.load_checkpoint(path, "scheduler")
        assert reason == "ok"
        assert env["state"]["cycles"] == 7
        assert env["kind"] == "scheduler"

    def test_missing(self, tmp_path):
        env, reason = ckpt.load_checkpoint(str(tmp_path / "nope"),
                                           "scheduler")
        assert env is None and reason == "missing"

    def test_truncated(self, tmp_path):
        path = self._write(tmp_path)
        with open(path, "rb") as f:
            raw = f.read()
        # torn mid-body: header intact, body cut short -> sha mismatch
        with open(path, "wb") as f:
            f.write(raw[:len(raw) - 10])
        env, reason = ckpt.load_checkpoint(path, "scheduler")
        assert env is None and reason == "sha_mismatch"
        # torn mid-header: shorter than the fixed header
        with open(path, "wb") as f:
            f.write(raw[:8])
        env, reason = ckpt.load_checkpoint(path, "scheduler")
        assert env is None and reason == "truncated"

    def test_flipped_byte(self, tmp_path):
        path = self._write(tmp_path)
        with open(path, "r+b") as f:
            f.seek(-1, os.SEEK_END)
            b = f.read(1)
            f.seek(-1, os.SEEK_END)
            f.write(bytes([b[0] ^ 0xFF]))
        env, reason = ckpt.load_checkpoint(path, "scheduler")
        assert env is None and reason == "sha_mismatch"

    def test_future_schema_version(self, tmp_path):
        path = self._write(tmp_path)
        with open(path, "r+b") as f:
            f.seek(4)
            f.write(struct.pack("<I", ckpt.SCHEMA_VERSION + 1))
        env, reason = ckpt.load_checkpoint(path, "scheduler")
        assert env is None and reason == "version_skew"

    def test_foreign_magic(self, tmp_path):
        path = str(tmp_path / "foreign")
        with open(path, "wb") as f:
            f.write(b"\x89PNG" + b"\x00" * 64)
        env, reason = ckpt.load_checkpoint(path, "scheduler")
        assert env is None and reason == "bad_magic"

    def test_kind_mismatch(self, tmp_path):
        path = self._write(tmp_path)
        env, reason = ckpt.load_checkpoint(path, "sidecar")
        assert env is None and reason == "kind_mismatch"

    def test_atomic_replace_keeps_previous_on_overwrite(self, tmp_path):
        path = self._write(tmp_path, {"gen": 1})
        ckpt.write_checkpoint(path, "scheduler", {"gen": 2})
        env, reason = ckpt.load_checkpoint(path, "scheduler")
        assert reason == "ok" and env["state"]["gen"] == 2
        # no stray tmp files left behind
        assert [p for p in os.listdir(tmp_path)
                if p.startswith(".vckp.")] == []


# ------------------------------------------------------ the restart matrix
class TestRestartMatrix:
    # slow tail (tier-1 budget recalibration, PR 1/3/5/8/9 pattern): the
    # tier1.sh restart smoke runs this EXACT probe with the same
    # acceptance checks on every tier-1 invocation, so the pytest copy
    # rides with the full suite
    @pytest.mark.slow
    def test_kill_every_phase_decision_identical(self):
        """The tentpole claim: process_kill at all three phases, each
        restore warm, the applied-decision log identical to the clean
        run — and the corrupt-checkpoint leg lands on the fallback rung
        while STILL finishing identical (cold re-fuse from external truth
        is decision-correct)."""
        from volcano_tpu.chaos import run_restart_probe
        rpt = run_restart_probe(seed=7, cycles=8)
        assert rpt["decisions_equal_clean"], \
            (rpt["clean_sha"], rpt["decisions_sha"])
        assert rpt["restore_outcomes"] == {"restored": 3}
        assert {p for _, p in rpt["kills"]} == {"pre_dispatch", "in_flight",
                                                "post_drain"}
        assert [k for _, k, _pt in rpt["fault_log"]] == ["process_kill"] * 3
        assert rpt["warm_refuses"] >= 1          # mirrors adopted warm
        assert rpt["cycles_to_steady"] == 0      # first cycle back: delta
        corrupt = rpt["corrupt"]
        assert corrupt["decisions_equal_clean"]
        assert corrupt["restore_outcomes"] == {"fallback": 3}
        assert corrupt["fallbacks_visible"] >= 3

    def test_sync_path_restart_identical(self):
        """The same identity on the synchronous (non-pipelined) loop:
        pre-dispatch and post-drain kills (in_flight needs a pipeline)."""
        from volcano_tpu.chaos import run_restart_probe
        rpt = run_restart_probe(
            seed=11, cycles=6, pipeline=False,
            kills=((2, "pre_dispatch"), (4, "post_drain")),
            corrupt_leg=False)
        assert rpt["decisions_equal_clean"]
        assert rpt["restore_outcomes"] == {"restored": 2}

    def test_checkpoint_mid_pipelined_cycle_drains_first(self, tmp_path):
        """A checkpoint taken with a cycle in flight drains it (depth-1
        makes that decision-neutral) so the restored process can never
        replay a half-applied bind."""
        cluster = FakeCluster(build_cluster(n_nodes=6, n_jobs=8))
        sched = Scheduler(cluster, conf=PARITY_CONF, pipeline=True)
        sched.run_once(now=1000.0)
        assert sched._pending is not None        # a cycle is in flight
        path = str(tmp_path / "mid.vckp")
        sched.checkpoint(path, now=1000.0)
        assert sched._pending is None            # drained, applied once
        applied = list(cluster.binds)
        assert applied                            # the cycle really bound
        # the "restarted" scheduler re-runs over already-updated truth:
        # a no-op, never a double-dispatch
        sched2 = Scheduler(cluster, conf=PARITY_CONF, pipeline=True)
        assert sched2.restore(path, now=1001.0) == "restored"
        sched2.run_once(now=1001.0)
        sched2.drain(now=1001.0)
        uids = [u for u, _ in cluster.binds]
        assert len(uids) == len(set(uids)), "a bind was double-applied"
        assert cluster.binds[:len(applied)] == applied

    def test_conf_mismatch_falls_back(self, tmp_path):
        cluster = FakeCluster(build_cluster(n_nodes=4, n_jobs=4))
        sched = Scheduler(cluster, conf=PARITY_CONF, pipeline=False)
        sched.run_once(now=1000.0)
        path = str(tmp_path / "conf.vckp")
        sched.checkpoint(path, now=1000.0)
        from volcano_tpu.chaos.probe import _PROBE_CONF
        sched2 = Scheduler(cluster, conf=_PROBE_CONF, pipeline=False)
        assert ckpt.conf_fingerprint(sched2.conf) \
            != ckpt.conf_fingerprint(sched.conf)
        assert sched2.restore(path, now=1001.0) == "fallback"

    def test_missing_checkpoint_is_cold_start(self, tmp_path):
        cluster = FakeCluster(build_cluster(n_nodes=4, n_jobs=4))
        sched = Scheduler(cluster, conf=PARITY_CONF, pipeline=False)
        before = METRICS.counter_value("checkpoint_restore_total",
                                       {"outcome": "cold"})
        assert sched.restore(str(tmp_path / "never"), now=1000.0) == "cold"
        assert METRICS.counter_value("checkpoint_restore_total",
                                     {"outcome": "cold"}) == before + 1


# ------------------------------------------- warm restart, pallas path
class TestWarmMirrorRestore:
    def _kernel(self):
        from volcano_tpu.arrays import pack
        from volcano_tpu.ops import AllocateConfig, make_allocate_cycle
        from volcano_tpu.ops.allocate_scan import AllocateExtras
        from volcano_tpu.ops.fused_io import DeltaKernel
        ci = simple_cluster(n_nodes=4, node_cpu="8", node_mem="16Gi")
        for j in range(4):
            job = build_job(f"default/j{j}", min_available=2)
            for t in range(2):
                job.add_task(build_task(f"j{j}-t{t}", cpu="2",
                                        memory="2Gi"))
            ci.add_job(job)
        snap, _maps = pack(ci)
        extras = AllocateExtras.neutral(snap)
        cfg = AllocateConfig(binpack_weight=1.0, use_pallas="interpret",
                             enable_gpu=False)
        kern = DeltaKernel(make_allocate_cycle(cfg), (snap, extras))
        return kern, snap, extras

    def _drive(self, kern, state, snap, extras, prio, cycles):
        decs = []
        for c in cycles:
            packed = np.asarray(kern.run(state, (snap, extras)))
            dec, _dig = kern.split_digest(packed)
            decs.append(dec.tobytes())
            prio[c % prio.size] += 1            # steady churn
        return decs

    def test_pallas_interpret_checkpoint_restore_identical(self, tmp_path):
        """Kill after cycle 1 on the pallas-interpret delta path: the
        checkpointed mirror is digest-verified, adopted warm, and cycles
        2-3 produce bit-identical decisions to the uninterrupted run."""
        from volcano_tpu.ops.fused_io import ResidentState
        kern, snap, extras = self._kernel()
        prio = np.asarray(snap.tasks.priority)
        base = np.array(prio, copy=True)

        clean_state = ResidentState()
        clean = self._drive(kern, clean_state, snap, extras, prio,
                            range(4))
        prio[:] = base                           # rewind the shared snap

        state = ResidentState()
        first = self._drive(kern, state, snap, extras, prio, range(2))
        assert first == clean[:2]
        path = str(tmp_path / "pallas.vckp")
        mirrors = ckpt.mirror_records({("shape",): kern},
                                      {id(kern): state})
        assert len(mirrors) == 1
        ckpt.write_checkpoint(path, "sidecar", {"t": 1}, mirrors=mirrors)

        env, reason = ckpt.load_checkpoint(path, "sidecar")
        assert reason == "ok"
        warm0 = METRICS.counter_value("checkpoint_warm_refuse_total")
        restored = ckpt.verify_mirrors(env["mirrors"])
        state2 = ResidentState()                 # the fresh process
        ckpt.adopt_mirror(state2, restored[("shape",)])
        assert METRICS.counter_value(
            "checkpoint_warm_refuse_total") == warm0 + 1
        rest = self._drive(kern, state2, snap, extras, prio, range(2, 4))
        prio[:] = base
        assert rest == clean[2:], "warm-restored decisions diverged"

    def test_tampered_mirror_dropped_to_cold_refuse(self, tmp_path):
        from volcano_tpu.ops.fused_io import ResidentState
        kern, snap, extras = self._kernel()
        prio = np.asarray(snap.tasks.priority)
        base = np.array(prio, copy=True)
        state = ResidentState()
        self._drive(kern, state, snap, extras, prio, range(2))
        prio[:] = base
        records = ckpt.mirror_records({("k",): kern}, {id(kern): state})
        # bit-rot between checkpoint and restore: flip one element
        buf = next(b for b in records[0]["mirror"] if b.size)
        if buf.dtype == np.bool_:
            buf[0] = not buf[0]
        else:
            buf.view(np.uint32)[0] ^= np.uint32(0x5A5A5A5A)
        invalid0 = METRICS.counter_value("checkpoint_mirror_invalid_total")
        restored = ckpt.verify_mirrors(records)
        assert restored == {}                    # dropped, not adopted
        assert METRICS.counter_value(
            "checkpoint_mirror_invalid_total") == invalid0 + 1

    def test_digest_fold_order_independent(self):
        recs = [{"digest": [1, 2, 3]}, {"digest": [7, 11, 13]},
                {"digest": [100, 200, 300]}]
        assert ckpt.fold_digest(recs) == ckpt.fold_digest(recs[::-1])


# --------------------------------------------------------- resync redrive
class TestResyncRedrive:
    class _AlwaysFails:
        def bind(self, intent):
            return False

        def evict(self, intent):
            return False

        def resync_task(self, uid):
            pass

    def test_dead_letters_get_second_life(self):
        from volcano_tpu.framework.session import BindIntent
        q = ResyncQueue(base_delay=0.001, max_delay=0.001, max_attempts=2)
        cluster = self._AlwaysFails()
        q.add(BindIntent("default/t0", "default/j0", "n0"), "bind", now=0.0)
        now = 0.0
        for _ in range(4):
            now += 1.0
            q.process(cluster, now)
        assert len(q.dead_letter()) == 1 and len(q) == 0
        before = METRICS.counter_value("resync_redrive_total")
        assert q.redrive(now) == 1
        assert q.dead_letter() == [] and len(q) == 1   # pending again
        assert q.entries[0]["attempts"] == 1           # attempts reset
        assert METRICS.counter_value("resync_redrive_total") == before + 1
        assert q.redrive(now) == 0                     # idempotent


# -------------------------------------------------- crash-loop supervisor
class TestCrashLoopSupervisor:
    def _backoff(self):
        from volcano_tpu.runtime.backoff import Backoff
        return Backoff(base=0.01, cap=0.02, attempts=10, jitter=0.0,
                       seed=0)

    def test_restarts_until_clean_return(self):
        calls = {"n": 0}
        slept = []

        def flaky():
            calls["n"] += 1
            if calls["n"] <= 2:
                raise RuntimeError(f"crash {calls['n']}")
            return "served"

        before = METRICS.counter_value("crash_loop_restarts_total")
        sup = ckpt.CrashLoopSupervisor(flaky, max_restarts=5,
                                       backoff=self._backoff(),
                                       sleep=slept.append)
        assert sup.run() == "served"
        assert sup.restarts == 2 and len(slept) == 2
        assert METRICS.counter_value(
            "crash_loop_restarts_total") == before + 2

    def test_crash_loop_eventually_surfaces(self):
        def hopeless():
            raise RuntimeError("wedged")

        sup = ckpt.CrashLoopSupervisor(hopeless, max_restarts=2,
                                       backoff=self._backoff(),
                                       sleep=lambda _s: None)
        with pytest.raises(RuntimeError, match="wedged"):
            sup.run()
        assert sup.restarts == 3                 # initial + 2 restarts

    def test_clean_shutdown_is_not_a_crash(self):
        def interrupted():
            raise KeyboardInterrupt

        sup = ckpt.CrashLoopSupervisor(interrupted, max_restarts=5,
                                       backoff=self._backoff(),
                                       sleep=lambda _s: None)
        with pytest.raises(KeyboardInterrupt):
            sup.run()
        assert sup.restarts == 0


# -------------------------------------------------------- sidecar restarts
from volcano_tpu import native  # noqa: E402


@pytest.mark.skipif(not native.available(),
                    reason=f"native packer unavailable: "
                           f"{native.build_error()}")
class TestSidecarRestart:
    def _cluster(self, k: int):
        from volcano_tpu.api import TaskStatus
        ci = simple_cluster(n_nodes=3)
        for j in range(3):
            job = build_job(f"default/j{j}", min_available=2)
            for t in range(2):
                job.add_task(build_task(f"j{j}-t{t}", cpu="1",
                                        memory="1Gi"))
            ci.add_job(job)
        names = sorted(ci.nodes)
        bound = 0
        for job in ci.jobs.values():
            for task in job.tasks.values():
                if bound >= k:
                    break
                job.update_task_status(task, TaskStatus.RUNNING)
                task.node_name = names[bound % len(names)]
                ci.nodes[task.node_name].add_task(task)
                bound += 1
        return ci

    def _fast_backoff(self):
        from volcano_tpu.runtime.backoff import Backoff
        return Backoff(base=0.01, cap=0.05, attempts=5, jitter=0.0, seed=0)

    # slow tail (tier-1 budget): multi-round server runs dominated by
    # compile time; the corrupt-fallback row below stays in tier-1
    @pytest.mark.slow
    def test_checkpoint_restore_resumes_stream_identically(self, tmp_path):
        """Kill the sidecar between rounds 2 and 3: the restored process
        serves rounds 3..N byte-identically to an uninterrupted sidecar —
        replay cache, known epochs, staged pending decisions, and warm
        mirrors all resume."""
        from volcano_tpu.native.wire import serialize
        from volcano_tpu.ops.allocate_scan import AllocateConfig
        from volcano_tpu.runtime.sidecar import SchedulerSidecar
        cfg = AllocateConfig(binpack_weight=1.0)
        bufs = [serialize(self._cluster(k))[0] for k in range(5)]

        clean = SchedulerSidecar(cfg)
        clean_out = [clean.schedule_buffer_seq(9, s + 1, b)
                     for s, b in enumerate(bufs)]

        side = SchedulerSidecar(cfg)
        out = [side.schedule_buffer_seq(9, s + 1, bufs[s])
               for s in range(2)]
        assert out == clean_out[:2]
        path = str(tmp_path / "side.vckp")
        side.checkpoint(path)

        side2 = SchedulerSidecar(cfg)            # the fresh process
        assert side2.restore(path) == "restored"
        # the reconnect contract across death: a REPLAY of the last
        # round served before the crash comes from the restored cache
        replays0 = METRICS.counter_value("sidecar_replayed_rounds_total")
        assert side2.schedule_buffer_seq(9, 2, bufs[1]) == clean_out[1]
        assert METRICS.counter_value(
            "sidecar_replayed_rounds_total") == replays0 + 1
        # and the stream continues byte-identically to the clean run
        out2 = [side2.schedule_buffer_seq(9, s + 1, bufs[s])
                for s in range(2, 5)]
        assert out2 == clean_out[2:]

    def test_corrupt_sidecar_checkpoint_is_cold_start(self, tmp_path):
        from volcano_tpu.ops.allocate_scan import AllocateConfig
        from volcano_tpu.runtime.sidecar import SchedulerSidecar
        cfg = AllocateConfig(binpack_weight=1.0)
        side = SchedulerSidecar(cfg)
        path = str(tmp_path / "bad.vckp")
        side.checkpoint(path)
        with open(path, "r+b") as f:
            f.seek(-1, os.SEEK_END)
            b = f.read(1)
            f.seek(-1, os.SEEK_END)
            f.write(bytes([b[0] ^ 0xFF]))
        fb0 = METRICS.counter_value("checkpoint_restore_total",
                                    {"outcome": "fallback"})
        side2 = SchedulerSidecar(cfg)
        assert side2.restore(path) == "fallback"
        assert METRICS.counter_value("checkpoint_restore_total",
                                     {"outcome": "fallback"}) == fb0 + 1
        assert side2._known_epochs == set()      # genuinely cold

    @pytest.mark.slow      # tier-1 budget: two live servers + compile
    def test_server_restart_client_reprimes_via_epoch_restored(self):
        """A client mid-stream against a server that restarted WITHOUT
        checkpoint state gets the structured ERR_EPOCH_RESTORED answer,
        adopts a fresh epoch, and re-primes in one extra roundtrip — no
        timeout, no error surfaced to the caller."""
        from volcano_tpu.runtime.sidecar import SidecarClient, SidecarServer
        cis = [self._cluster(k) for k in range(4)]
        server = SidecarServer()
        host, port = server.address
        server.serve_in_thread()
        client = None
        try:
            client = SidecarClient(host, port,
                                   backoff=self._fast_backoff(),
                                   call_timeout=10.0)
            assert client.schedule_pipelined(cis[0]) is None    # prime
            assert client.schedule_pipelined(cis[1]) is not None
            # SIGKILL the server; a fresh one binds the same address with
            # no state (the no-checkpoint worst case). shutdown() stops
            # the accept loop but not live handler threads, so sever the
            # established connection too — that's what the kill does
            server.shutdown()
            server.server_close()
            client.sock.close()
            server = SidecarServer(host=host, port=port)
            server.serve_in_thread()
            srv0 = METRICS.counter_value("sidecar_epoch_restored_total",
                                         {"side": "server"})
            cli0 = METRICS.counter_value("sidecar_epoch_restored_total",
                                         {"side": "client"})
            epoch_before = client._epoch
            # mid-stream round: reconnects, gets ERR_EPOCH_RESTORED,
            # re-primes with a fresh epoch — the round returns None
            assert client.schedule_pipelined(cis[2]) is None
            assert client._epoch != epoch_before
            assert METRICS.counter_value(
                "sidecar_epoch_restored_total",
                {"side": "server"}) == srv0 + 1
            assert METRICS.counter_value(
                "sidecar_epoch_restored_total",
                {"side": "client"}) == cli0 + 1
            # the re-primed stream serves decisions again
            out = client.schedule_pipelined(cis[3])
            assert out is not None
            tail = client.drain_pipelined()
            assert tail is not None
        finally:
            if client is not None:
                client.close()
            server.shutdown()
            server.server_close()


# ------------------------------------------- sharded scenario identity
@pytest.mark.slow
class TestShardedScenarioIdentity:
    def test_trace_replay_sharded_equals_unsharded(self):
        """`--sharded` purity: the node-axis sharded backend decides the
        trace-replay scenario bit-identically to the unsharded run (the
        conftest 8-device virtual CPU mesh covers the >= 2-device mesh
        the flag needs)."""
        import jax
        if len(jax.devices()) < 2:
            pytest.skip("needs >= 2 devices")
        from volcano_tpu.scenarios import get_scenario, run_scenario
        spec = get_scenario("trace-replay")
        a = run_scenario(spec, cycles=12, observe=False)
        b = run_scenario(spec, cycles=12, observe=False, sharded=True)
        assert a.scorecard.decisions_sha == b.scorecard.decisions_sha


# ------------------------------------------------ restart-storm scenario
class TestRestartStormScenario:
    # slow tail (tier-1 budget): two full 18-cycle scenario engine runs;
    # the restart path itself is gated every tier-1 run by the restart
    # smoke in scripts/tier1.sh
    @pytest.mark.slow
    def test_restart_storm_decision_identical_to_calm_run(self):
        import dataclasses
        from volcano_tpu.scenarios import get_scenario, run_scenario
        spec = get_scenario("restart-storm")
        storm = run_scenario(spec, cycles=18, observe=False)
        calm = run_scenario(dataclasses.replace(spec, restart_every=0),
                            cycles=18, observe=False)
        restarts = [e for e in storm.events if e["kind"] == "restart"]
        assert [e["outcome"] for e in restarts] == ["restored"] * 2
        assert storm.scorecard.decisions_sha == calm.scorecard.decisions_sha
