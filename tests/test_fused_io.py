"""Fused 3-buffer snapshot transfer (ops/fused_io): the rebuilt tree and
cycle decisions must be identical to the per-leaf path."""

import numpy as np
import jax

from volcano_tpu.arrays import pack
from volcano_tpu.ops import AllocateConfig, make_allocate_cycle
from volcano_tpu.ops.allocate_scan import AllocateExtras
from volcano_tpu.ops.fused_io import fuse, fuse_spec, make_fused_cycle, make_unfuse

from fixtures import build_job, build_task, simple_cluster


def snapshot():
    ci = simple_cluster(n_nodes=3)
    for j in range(3):
        job = build_job(f"default/j{j}", min_available=2)
        for t in range(2):
            job.add_task(build_task(f"j{j}-t{t}", cpu="1", memory="1Gi"))
        ci.add_job(job)
    snap, _ = pack(ci)
    return snap, AllocateExtras.neutral(snap)


class TestFusedIO:
    def test_round_trip_tree(self):
        tree = snapshot()
        treedef, spec = fuse_spec(tree)
        rebuilt = make_unfuse(treedef, spec)(*map(jax.numpy.asarray,
                                                  fuse(tree)))
        for a, b in zip(jax.tree.leaves(tree), jax.tree.leaves(rebuilt)):
            a = np.asarray(a)
            b = np.asarray(b)
            assert a.shape == b.shape and a.dtype == b.dtype
            np.testing.assert_array_equal(a, b)

    def test_cycle_decisions_identical(self):
        snap, extras = snapshot()
        cycle = make_allocate_cycle(AllocateConfig(binpack_weight=1.0))
        plain = np.asarray(jax.jit(
            lambda s, e: cycle(s, e).packed_decisions())(snap, extras))
        fn, fz = make_fused_cycle(cycle, (snap, extras))
        fused = np.asarray(fn(*fz((snap, extras))))
        np.testing.assert_array_equal(plain, fused)
