"""Fused 3-buffer snapshot transfer (ops/fused_io): the rebuilt tree and
cycle decisions must be identical to the per-leaf path — on the full-upload
path AND the device-resident delta path (ISSUE 4)."""

import numpy as np
import jax
import pytest

from volcano_tpu.arrays import pack
from volcano_tpu.ops import AllocateConfig, make_allocate_cycle
from volcano_tpu.ops.allocate_scan import AllocateExtras
from volcano_tpu.ops.fused_io import (DeltaKernel, ResidentState,
                                      delta_bucket, delta_cycle_cached,
                                      fuse, fuse_spec, fused_cycle_cached,
                                      group_sizes, make_fused_cycle,
                                      make_unfuse)

from fixtures import build_job, build_task, simple_cluster


def snapshot():
    ci = simple_cluster(n_nodes=3)
    for j in range(3):
        job = build_job(f"default/j{j}", min_available=2)
        for t in range(2):
            job.add_task(build_task(f"j{j}-t{t}", cpu="1", memory="1Gi"))
        ci.add_job(job)
    snap, _ = pack(ci)
    return snap, AllocateExtras.neutral(snap)


class TestFusedIO:
    def test_round_trip_tree(self):
        tree = snapshot()
        treedef, spec = fuse_spec(tree)
        rebuilt = make_unfuse(treedef, spec)(*map(jax.numpy.asarray,
                                                  fuse(tree)))
        for a, b in zip(jax.tree.leaves(tree), jax.tree.leaves(rebuilt)):
            a = np.asarray(a)
            b = np.asarray(b)
            assert a.shape == b.shape and a.dtype == b.dtype
            np.testing.assert_array_equal(a, b)

    def test_cycle_decisions_identical(self):
        snap, extras = snapshot()
        cycle = make_allocate_cycle(AllocateConfig(binpack_weight=1.0))
        plain = np.asarray(jax.jit(
            lambda s, e: cycle(s, e).packed_decisions())(snap, extras))
        fn, fz = make_fused_cycle(cycle, (snap, extras))
        fused = np.asarray(fn(*fz((snap, extras))))
        np.testing.assert_array_equal(plain, fused)

    def test_unsupported_dtype_raises(self):
        tree = {"bad": np.zeros(3, np.complex64)}
        with pytest.raises(TypeError, match="unsupported dtype"):
            fuse_spec(tree)
        with pytest.raises(TypeError, match="unsupported dtype"):
            fuse(tree)

    def test_empty_dtype_groups_round_trip(self):
        # a tree with ONLY float leaves: the i32 and bool group buffers
        # are empty and the round trip must still be exact
        tree = {"a": np.arange(6, dtype=np.float32).reshape(2, 3),
                "b": np.float32(7.5)}
        treedef, spec = fuse_spec(tree)
        sizes = group_sizes(spec)
        assert sizes[1] == 0 and sizes[2] == 0
        bufs = fuse(tree)
        assert bufs[1].size == 0 and bufs[2].size == 0
        rebuilt = make_unfuse(treedef, spec)(*map(jax.numpy.asarray, bufs))
        for a, b in zip(jax.tree.leaves(tree), jax.tree.leaves(rebuilt)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_fuse_matches_spec_layout(self):
        # fuse() fills slices from the same spec make_unfuse reads — every
        # leaf must land at its spec offset with the group target dtype
        tree = snapshot()
        _td, spec = fuse_spec(tree)
        bufs = dict(zip("fib", fuse(tree)))
        for leaf, (g, off, shape, _dt) in zip(jax.tree.leaves(tree), spec):
            arr = np.asarray(leaf)
            np.testing.assert_array_equal(
                bufs[g][off:off + arr.size],
                arr.ravel().astype(bufs[g].dtype, copy=False))

    def test_fused_cycle_cached_key_isolation(self):
        snap, extras = snapshot()
        cycle = make_allocate_cycle(AllocateConfig(binpack_weight=1.0))
        cache = {}
        a1 = fused_cycle_cached(cycle, (snap, extras), cache, key_extra="a")
        b1 = fused_cycle_cached(cycle, (snap, extras), cache, key_extra="b")
        a2 = fused_cycle_cached(cycle, (snap, extras), cache, key_extra="a")
        assert a1 is a2                 # same key: cache hit
        assert a1 is not b1             # differing key_extra: isolated
        assert len(cache) == 2
        dcache = {}
        ka = delta_cycle_cached(cycle, (snap, extras), dcache, key_extra="a")
        kb = delta_cycle_cached(cycle, (snap, extras), dcache, key_extra="b")
        assert ka is not kb and len(dcache) == 2
        assert ka is delta_cycle_cached(cycle, (snap, extras), dcache,
                                        key_extra="a")


class TestDeltaPath:
    def test_bucket_shape(self):
        assert delta_bucket(0) == 0
        assert delta_bucket(1) == 256
        assert delta_bucket(256) == 256
        assert delta_bucket(257) == 512

    def test_delta_cycles_byte_identical_to_full(self):
        """full -> delta -> idle-delta -> huge-delta(full fallback): every
        cycle's packed decisions equal the full-upload reference on the
        same mutated snapshot."""
        snap, extras = snapshot()
        cycle = make_allocate_cycle(AllocateConfig(binpack_weight=1.0))
        fn, fz = make_fused_cycle(cycle, (snap, extras))
        kern = DeltaKernel(cycle, (snap, extras))
        state = ResidentState()

        def check(expect_kind):
            ref = np.asarray(fn(*fz((snap, extras))))
            # the delta entry's packed readback carries the ISSUE 5
            # integrity-digest tail past the decisions; strip it for the
            # byte-parity compare and verify it against the host mirror
            dec, dev_digest = kern.split_digest(
                np.asarray(kern.run(state, (snap, extras))))
            np.testing.assert_array_equal(dec, ref)
            np.testing.assert_array_equal(dev_digest,
                                          kern.mirror_digest(state))
            assert state.last_kind == expect_kind

        check("full")                       # cold: resident buffers land
        prio = np.asarray(snap.tasks.priority)
        prio[0] += 1
        check("delta")                      # one changed element
        assert state.last_upload_bytes < state.full_upload_bytes
        check("delta")                      # idle cycle: empty delta
        assert state.last_upload_bytes == 0
        # status/placement churn across several rows stays a delta
        idle = np.asarray(snap.nodes.idle)
        idle[0] = idle[0] * np.float32(0.5)
        check("delta")
        # structural change: the caller forces a full re-fuse — still
        # byte-identical, residency re-established
        ref = np.asarray(fn(*fz((snap, extras))))
        got, _dig = kern.split_digest(np.asarray(
            kern.run(state, (snap, extras), force_full=True)))
        np.testing.assert_array_equal(got, ref)
        assert state.last_kind == "full"
        assert state.full_cycles == 2 and state.delta_cycles == 3

    def test_huge_delta_falls_back_to_full_upload(self):
        # when the diff covers most of the buffers, shipping idx+vals
        # would move MORE bytes than the buffers themselves: the size
        # heuristic must take the full path (decisions identical anyway)
        class _Stub:
            def __init__(self, tree):
                self._x = tree["a"]

            def packed_decisions(self):
                return (self._x * 2).astype(jax.numpy.int32)

        tree = {"a": np.arange(1024, dtype=np.float32)}
        kern = DeltaKernel(lambda t: _Stub(t), (tree,))
        state = ResidentState()
        kern.run(state, (tree,))
        assert state.last_kind == "full"
        tree["a"] = tree["a"] + np.float32(1.0)      # every element changed
        out, _dig = kern.split_digest(np.asarray(kern.run(state, (tree,))))
        assert state.last_kind == "full"
        np.testing.assert_array_equal(
            out, ((tree["a"]) * 2).astype(np.int32))

    def test_consumed_residents_fail_fast_on_reread(self):
        """The invalidation deadline: a resident handle consumed by cycle
        k is dead no later than cycle k+1's dispatch (immediately where
        the backend honored the donation)."""
        snap, extras = snapshot()
        cycle = make_allocate_cycle(AllocateConfig(binpack_weight=1.0))
        kern = DeltaKernel(cycle, (snap, extras))
        state = ResidentState()
        kern.run(state, (snap, extras))
        old = state.device
        np.asarray(snap.tasks.priority)[1] += 1
        packed = kern.run(state, (snap, extras))    # consumes `old`
        np.asarray(packed)              # the OUTPUT stays readable
        kern.run(state, (snap, extras))             # next dispatch
        for h in old:                   # ...retires the consumed inputs
            with pytest.raises(RuntimeError):
                np.asarray(h)

    def test_donation_matches_backend_contract(self):
        from volcano_tpu.ops.fused_io import donation_for_backend
        assert donation_for_backend("cpu") == ()
        assert donation_for_backend("tpu") == (0, 1, 2)
        snap, extras = snapshot()
        kern = DeltaKernel(
            make_allocate_cycle(AllocateConfig(binpack_weight=1.0)),
            (snap, extras))
        assert tuple(kern.donate_argnums) == donation_for_backend()
