"""Leader-election edge cases (ISSUE 11 satellite).

The happy-path election dance lives in test_runtime_aux.py; this suite
pins the edges the HA failover machinery leans on:

- a lease stolen mid-renew forces an immediate step-down and the deposed
  replica KEEPS its old fencing token (the fence must reject it),
- clock skew past renew_deadline steps the leader down even with no
  rival (the silent-renewal-stall rule),
- lease transitions and the fencing generation are strictly monotonic
  across steals and never move on renewals,
- a replica re-acquires after its rival's lease expires, with a fresh
  (higher) generation.
"""

from volcano_tpu.runtime.leader import (DEFAULT_LEASE_DURATION,
                                        DEFAULT_RENEW_DEADLINE,
                                        LeaderElector)
from volcano_tpu.runtime.system import VolcanoSystem

LEASE_KEY = "volcano-system/vc-scheduler"


class FakeClock:
    def __init__(self, now: float = 100.0):
        self.now = now

    def __call__(self) -> float:
        return self.now


def _pair(events=None):
    api = VolcanoSystem().api
    clock = FakeClock()
    ev = events if events is not None else []
    a = LeaderElector(api, identity="a", clock=clock,
                      on_started_leading=lambda: ev.append("a+"),
                      on_stopped_leading=lambda: ev.append("a-"))
    b = LeaderElector(api, identity="b", clock=clock,
                      on_started_leading=lambda: ev.append("b+"),
                      on_stopped_leading=lambda: ev.append("b-"))
    return api, clock, a, b


class TestLeaseStolenMidRenew:
    def test_steps_down_and_keeps_old_fencing_token(self):
        events = []
        api, clock, a, b = _pair(events)
        assert a.tick() and a.generation == 1
        # a rival rewrites the lease out from under the live leader (an
        # operator force-steal / a partitioned store healing the wrong
        # way): holder flips while a still believes it leads
        lease = api.get("leases", LEASE_KEY)
        lease.holder = "b"
        lease.renew_time = clock.now
        lease.transitions += 1
        lease.generation += 1
        api.update("leases", lease)
        clock.now += 1.0
        assert not a.tick() and not a.is_leader     # immediate step-down
        # the deposed replica presents its OLD token — that is the whole
        # point of keeping it: the fence rejects generation 1 < 2
        assert a.generation == 1
        assert api.get("leases", LEASE_KEY).generation == 2
        assert events == ["a+", "a-"]

    def test_stolen_lease_blocks_until_expiry(self):
        api, clock, a, b = _pair()
        assert a.tick()
        assert not b.tick()                          # live lease blocks b
        clock.now += DEFAULT_LEASE_DURATION - 1.0
        assert not b.tick()                          # still not expired
        clock.now += 1.1
        assert b.tick() and b.is_leader
        assert b.generation == 2 > a.generation


class TestRenewDeadlineSkew:
    def test_clock_jump_past_renew_deadline_steps_down(self):
        """A leader whose renewals stalled longer than renew_deadline
        must step down even though nobody else took the lock — the
        client-go rule that bounds how stale a leader's view can be."""
        api, clock, a, _ = _pair()
        assert a.tick()
        clock.now += DEFAULT_RENEW_DEADLINE + 0.1    # < lease_duration
        assert not a.tick() and not a.is_leader
        # the lease is still ours and unexpired: the NEXT tick re-renews
        # and resumes leadership — same holder, so no generation bump
        assert a.tick() and a.is_leader
        assert a.generation == 1

    def test_skew_past_lease_duration_lets_rival_win(self):
        api, clock, a, b = _pair()
        assert a.tick()
        clock.now += DEFAULT_LEASE_DURATION + 0.1
        assert b.tick() and b.is_leader              # expired: b takes it
        assert not a.tick() and not a.is_leader      # a observes the loss
        assert b.generation == 2 and a.generation == 1


class TestMonotonicity:
    def test_transitions_and_generation_strictly_increase(self):
        api, clock, a, b = _pair()
        seen_gen, seen_tr = [], []
        holders = (a, b, a, b)
        for el in holders:
            clock.now += DEFAULT_LEASE_DURATION + 1.0
            assert el.tick() and el.is_leader
            lease = api.get("leases", LEASE_KEY)
            seen_gen.append(lease.generation)
            seen_tr.append(lease.transitions)
        assert seen_gen == sorted(set(seen_gen))     # strictly increasing
        assert seen_tr == sorted(set(seen_tr))
        assert seen_gen[-1] == len(holders)          # one bump per steal

    def test_renew_never_bumps_generation_or_transitions(self):
        api, clock, a, _ = _pair()
        assert a.tick()
        for _ in range(5):
            clock.now += 1.0
            assert a.tick()                          # renewals
        lease = api.get("leases", LEASE_KEY)
        assert lease.generation == 1 and lease.transitions == 0


class TestReacquireAfterRivalExpiry:
    def test_original_leader_wins_back_with_fresh_token(self):
        events = []
        api, clock, a, b = _pair(events)
        assert a.tick()
        clock.now += DEFAULT_LEASE_DURATION + 1.0
        assert b.tick()                              # b steals (gen 2)
        clock.now += 1.0
        assert not a.tick()                          # a steps down
        # b dies (never renews); its lease expires and a wins it back
        clock.now += DEFAULT_LEASE_DURATION + 1.0
        assert a.tick() and a.is_leader
        assert a.generation == 3                     # fresh fencing token
        lease = api.get("leases", LEASE_KEY)
        assert lease.holder == "a" and lease.transitions == 2
        assert events == ["a+", "b+", "a-", "a+"]
