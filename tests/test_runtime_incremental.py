"""Persistent-session scheduler loop vs fresh-Session-per-cycle oracle.

VERDICT r4 #1: the production loop must run on the incremental path —
`Scheduler.run_once` holds one Session over the cluster's live view and
re-opens it each cycle via refresh_snapshot from the cluster's dirty marks.
These tests drive many cycles of realistic churn (binds landing, tasks
starting, jobs completing, gangs re-arriving, new jobs appearing) through
two schedulers over identical clusters — one incremental, one rebuilding a
fresh Session per cycle (the reference semantics: a clean Snapshot each
runOnce, scheduler.go:91) — and require bit-identical decisions every
cycle plus identical final cluster state.
"""

import numpy as np
import pytest

from volcano_tpu.api import TaskStatus
from volcano_tpu.arrays.pack import pack
from volcano_tpu.framework import parse_conf
from volcano_tpu.runtime.fake_cluster import FakeCluster
from volcano_tpu.runtime.scheduler import Scheduler

from fixtures import build_job, build_task, simple_cluster

CONF = parse_conf("""
actions: "enqueue, allocate, backfill"
tiers:
- plugins:
  - name: priority
  - name: gang
- plugins:
  - name: drf
  - name: predicates
  - name: proportion
  - name: binpack
""")

PREEMPT_CONF = parse_conf("""
actions: "enqueue, allocate, preempt"
tiers:
- plugins:
  - name: priority
  - name: gang
  - name: conformance
- plugins:
  - name: drf
  - name: predicates
  - name: binpack
""")


def build_cluster(n_nodes=8, n_jobs=10, tasks_per_job=4):
    ci = simple_cluster(n_nodes=n_nodes, node_cpu="8", node_mem="16Gi")
    for j in range(n_jobs):
        job = build_job(f"default/j{j}", min_available=2,
                        priority=j % 3, creation_timestamp=float(j))
        for t in range(tasks_per_job):
            job.add_task(build_task(f"j{j}-t{t}", cpu="2", memory="2Gi",
                                    priority=t % 2))
        ci.add_job(job)
    return ci


def cycle_digest(ssn):
    return (sorted((b.task_uid, b.node_name, b.gpu_index) for b in ssn.binds),
            sorted(e.task_uid for e in ssn.evictions),
            sorted(ssn.pipelined.items()),
            sorted((u, str(p)) for u, p in ssn.phase_updates.items()))


def churn(cluster: FakeCluster, cycle: int, arrivals: bool) -> None:
    """Deterministic between-cycle churn, applied via the cluster API so
    dirty marks are recorded (direct edits use mark_dirty)."""
    ci = cluster.ci
    # kubelet: every Bound task starts Running
    bound = [t.uid for job in ci.jobs.values()
             for t in job.tasks.values() if t.status == TaskStatus.BOUND]
    for uid in sorted(bound):
        cluster.run_task(uid)
    # one fully-Running job completes and its gang re-arrives as Pending
    # (completed-and-replaced: the steady-state churn shape)
    for uid in sorted(ci.jobs):
        job = ci.jobs[uid]
        tasks = list(job.tasks.values())
        if tasks and all(t.status == TaskStatus.RUNNING for t in tasks) \
                and (hash(uid) + cycle) % 3 == 0:
            for t in tasks:
                node = ci.nodes.get(t.node_name)
                if node is not None and t.uid in node.tasks:
                    node.remove_task(t)
                    cluster.mark_dirty(node_name=node.name)
                job.update_task_status(t, TaskStatus.PENDING)
                t.node_name = ""
            job.allocated = type(job.allocated)({})
            cluster.mark_dirty(job_uid=uid)
            break
    if arrivals and cycle % 2 == 0:
        # a new job appears (entity-set change: the repack fallback path)
        job = build_job(f"default/new{cycle}", min_available=1,
                        creation_timestamp=100.0 + cycle)
        job.add_task(build_task(f"new{cycle}-t0", cpu="1", memory="1Gi"))
        ci.add_job(job)
        cluster.mark_dirty(job_uid=job.uid, structural=False)


def run_pair(conf, cycles, arrivals, n_nodes=8, n_jobs=10):
    ci = build_cluster(n_nodes=n_nodes, n_jobs=n_jobs)
    ca = FakeCluster(ci.clone())
    cb = FakeCluster(ci.clone())
    sa = Scheduler(ca, conf=conf, incremental=True)
    sb = Scheduler(cb, conf=conf, incremental=False)
    assert sa.incremental and not sb.incremental
    for c in range(cycles):
        ssn_a = sa.run_once(now=1000.0 + c)
        ssn_b = sb.run_once(now=1000.0 + c)
        assert cycle_digest(ssn_a) == cycle_digest(ssn_b), f"cycle {c}"
        churn(ca, c, arrivals)
        churn(cb, c, arrivals)
    snap_a, _ = pack(ca.ci)
    snap_b, _ = pack(cb.ci)
    import jax
    for ga, gb in zip(jax.tree.leaves(snap_a), jax.tree.leaves(snap_b)):
        np.testing.assert_array_equal(np.asarray(ga), np.asarray(gb))
    return sa, sb


class TestIncrementalLoop:
    def test_steady_churn_identical_and_incremental(self):
        """Pure status/placement churn: every cycle after the first must be
        served by the incremental patch, with decisions identical to the
        fresh-session oracle."""
        sa, _ = run_pair(CONF, cycles=6, arrivals=False)
        assert sa.full_packs == 1
        assert sa.incremental_cycles == 5
        assert sa._session is not None

    def test_arrivals_force_repack_but_stay_identical(self):
        """Entity-set changes take refresh_snapshot's repack fallback inside
        the SAME persistent session — still bit-identical."""
        sa, _ = run_pair(CONF, cycles=6, arrivals=True)
        assert sa.full_packs > 1           # arrival cycles re-packed
        assert sa.incremental_cycles >= 1  # churn-only cycles did not

    # full-suite (`pytest -m slow`): multi-cycle eviction round-trip;
    # the preempt oracle + victim-tier tests keep eviction bookkeeping
    # in tier-1 — budget calibration
    @pytest.mark.slow
    def test_preempt_loop_identity(self):
        """Preempt evictions + re-placements across cycles: the persistent
        session's eviction bookkeeping must round-trip exactly."""
        ci = build_cluster(n_nodes=4, n_jobs=6, tasks_per_job=4)
        # fill the nodes with low-priority preemptable running gangs, then
        # starve a high-priority job
        ca = FakeCluster(ci.clone())
        cb = FakeCluster(ci.clone())
        sa = Scheduler(ca, conf=PREEMPT_CONF, incremental=True)
        sb = Scheduler(cb, conf=PREEMPT_CONF, incremental=False)
        for c in range(3):
            ssn_a = sa.run_once(now=2000.0 + c)
            ssn_b = sb.run_once(now=2000.0 + c)
            assert cycle_digest(ssn_a) == cycle_digest(ssn_b), f"cycle {c}"
            for cl in (ca, cb):
                for uid in sorted(u for job in cl.ci.jobs.values()
                                  for u, t in job.tasks.items()
                                  if t.status == TaskStatus.BOUND):
                    cl.run_task(uid)
                if c == 0:
                    hi = build_job("default/hi", min_available=4,
                                   priority=100, creation_timestamp=50.0,
                                   preemptable=False)
                    for t in range(4):
                        hi.add_task(build_task(f"hi-t{t}", cpu="6",
                                               memory="12Gi", priority=100))
                    cl.ci.add_job(hi)
                    cl.mark_dirty(job_uid=hi.uid)

    def test_resync_holds_round_trip(self):
        """A failed bind dispatch leaves the task Binding-held; the
        incremental next cycle must see the same world as a fresh pack."""
        ci = build_cluster(n_nodes=4, n_jobs=4, tasks_per_job=2)
        ca = FakeCluster(ci.clone())
        cb = FakeCluster(ci.clone())
        # same injected transient failure on both sides: first task of j0
        for cl in (ca, cb):
            cl.bind_failures["j0-t0"] = 2   # fails twice, then succeeds
        sa = Scheduler(ca, conf=CONF, incremental=True)
        sb = Scheduler(cb, conf=CONF, incremental=False)
        for c in range(4):
            ssn_a = sa.run_once(now=3000.0 + c)
            ssn_b = sb.run_once(now=3000.0 + c)
            assert cycle_digest(ssn_a) == cycle_digest(ssn_b), f"cycle {c}"
        assert ("j0-t0", "n0") not in ca.binds or True
        snap_a, _ = pack(ca.ci)
        snap_b, _ = pack(cb.ci)
        import jax
        for ga, gb in zip(jax.tree.leaves(snap_a), jax.tree.leaves(snap_b)):
            np.testing.assert_array_equal(np.asarray(ga), np.asarray(gb))
