"""Graphcheck: each check family fires on a planted violation AND stays
green on the repo's real entry points (ISSUE 2 acceptance).

The planted tests build tiny synthetic jaxprs/fixtures per family; the
real-entry test runs the whole pass in fast mode (pruned entry set — the
full set runs in the CLI / the slow-marked test below).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import textwrap

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from volcano_tpu.analysis import apply_allowlist, report_sha, run_graphcheck
from volcano_tpu.analysis.entrypoints import EntryTrace
from volcano_tpu.analysis.jaxpr_audit import (check_dtype, check_gather,
                                              check_purity, check_wavefront)

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _trace(fn, *args, x64=False, dims=None, cfg=None):
    if x64:
        with jax.experimental.enable_x64():
            closed = jax.make_jaxpr(fn)(*args)
    else:
        closed = jax.make_jaxpr(fn)(*args)
    return EntryTrace("planted", closed,
                      dims or {"N": 7, "task_dims": {5}}, cfg)


class TestPlantedViolations:
    def test_purity_fires_on_callback_in_hot_path(self):
        def hot(x):
            jax.debug.callback(lambda v: None, x[0])
            return x * 2.0

        findings = check_purity(_trace(hot, np.ones(4, np.float32)))
        assert findings and findings[0].family == "purity"
        assert "debug_callback" in findings[0].what

    def test_purity_clean_on_pure_fn(self):
        findings = check_purity(_trace(lambda x: x * 2.0,
                                       np.ones(4, np.float32)))
        assert findings == []

    def test_dtype_fires_on_float64_leak(self):
        def leaky(x):
            # the classic leak: a weak float literal paired with a bool
            return jnp.where(x > 0, 1.0, 0.0)

        findings = check_dtype(_trace(leaky, np.ones(4, np.float32),
                                      x64=True))
        assert any("float64" in f.what for f in findings)

    def test_dtype_clean_on_pinned_fn(self):
        def pinned(x):
            return jnp.where(x > 0, jnp.float32(1.0), jnp.float32(0.0))

        assert check_dtype(_trace(pinned, np.ones(4, np.float32),
                                  x64=True)) == []

    def test_gather_fires_on_mn_materialization(self):
        T, N = 5, 7

        def regress(req, cap):
            # the PR 1 regression class: a [T, N] fit product
            return jnp.sum(req[:, None] <= cap[None, :], axis=1)

        findings = check_gather(_trace(
            regress, np.ones(T, np.float32), np.ones(N, np.float32),
            dims={"N": N, "task_dims": {T}}))
        assert findings and str((T, N)) in findings[0].what

    def test_gather_clean_on_node_resident_form(self):
        T, N = 5, 7

        def ok(req, cap):
            return jnp.sum(cap) + jnp.sum(req)

        assert check_gather(_trace(
            ok, np.ones(T, np.float32), np.ones(N, np.float32),
            dims={"N": N, "task_dims": {T}})) == []

    def test_recompile_fires_on_size_dependent_shapes(self):
        from volcano_tpu.analysis.recompile import check_recompile

        # one nominal problem size whose two packs produce different
        # shapes — the value-dependent-padding hazard: 2 traces where the
        # shape-bucket contract promises 1
        probes = [("planted", lambda: (lambda x: x * 2.0),
                   {"a": [(np.ones(4, np.float32),),
                          (np.ones(5, np.float32),)]})]
        findings = check_recompile(probes=probes)
        assert findings and "traced 2x" in findings[0].what

    def test_recompile_clean_on_stable_shapes(self):
        from volcano_tpu.analysis.recompile import check_recompile
        probes = [("stable", lambda: (lambda x: x * 2.0),
                   {"a": (np.ones(4, np.float32),),
                    "b": (np.ones(5, np.float32),)})]
        assert check_recompile(probes=probes) == []

    def test_vmem_fires_on_over_budget_blockspec(self, graph_traces):
        from volcano_tpu.analysis.vmem import check_vmem
        findings = check_vmem(graph_traces, budget_bytes=1024)
        assert any("per-core budget" in f.what for f in findings)

    def test_obligation_fires_on_hand_set_batch_rounds(self, tmp_path):
        from volcano_tpu.analysis.obligations import scan_file
        mod = tmp_path / "rogue.py"
        mod.write_text(textwrap.dedent("""\
            from volcano_tpu.ops.allocate_scan import AllocateConfig
            CFG = AllocateConfig(drf_job_order=True, batch_rounds=32)
        """))
        findings = scan_file(str(mod), "rogue.py")
        assert findings and "batch_rounds" in findings[0].key

    def test_obligation_accepts_derive_batching_route(self, tmp_path):
        from volcano_tpu.analysis.obligations import scan_file
        mod = tmp_path / "lawful.py"
        mod.write_text(textwrap.dedent("""\
            from volcano_tpu.ops.allocate_scan import (AllocateConfig,
                                                       derive_batching)
            CFG = derive_batching(AllocateConfig(drf_job_order=True),
                                  has_proportion=False)
        """))
        assert scan_file(str(mod), "lawful.py") == []

    def test_obligation_fires_on_splatted_dict(self, tmp_path):
        from volcano_tpu.analysis.obligations import scan_file
        mod = tmp_path / "splat.py"
        mod.write_text(textwrap.dedent("""\
            from volcano_tpu.ops.allocate_scan import AllocateConfig
            KW = {"binpack_weight": 1.0, "batch_jobs": 8}
            CFG = AllocateConfig(**KW)
        """))
        findings = scan_file(str(mod), "splat.py")
        assert findings and "dict" in findings[0].key


class TestWavefrontFamily:
    """Family 4 (ISSUE 16): the (W, N) sweep discipline of wave entries.
    A planted (W, task, N) re-materialization must fire; the proper
    gathered-rows sweep and non-wave entries must not. The real wave
    entries stay green via the fast_report fixture (allocate/wave4 is in
    the fast trace set; wave16 in the full CLI set)."""

    def _wave_cfg(self, w):
        from volcano_tpu.ops.allocate_scan import AllocateConfig
        return AllocateConfig(wave_width=w)

    def test_fires_on_planted_wtn_materialization(self):
        W, T, N = 4, 5, 7

        def regress(req, cap):
            # the violation class: every wave slot re-broadcasts the FULL
            # task table against the node axis instead of gathering its
            # own W candidate rows first
            fit = req[None, :, None] <= cap[None, None, :]
            return jnp.sum(jnp.broadcast_to(fit, (W, T, N)), axis=(1, 2))

        findings = check_wavefront(_trace(
            regress, np.ones(T, np.float32), np.ones(N, np.float32),
            dims={"N": N, "task_dims": {T}}, cfg=self._wave_cfg(W)))
        assert findings and str((W, T, N)) in findings[0].what

    def test_clean_on_gathered_wn_sweep(self):
        W, T, N = 4, 5, 7

        def ok(req, cap):
            rows = req[:W]                      # gather the wave's rows
            return jnp.sum(rows[:, None] <= cap[None, :], axis=1)

        assert check_wavefront(_trace(
            ok, np.ones(T, np.float32), np.ones(N, np.float32),
            dims={"N": N, "task_dims": {T}}, cfg=self._wave_cfg(W))) == []

    def test_skips_non_wave_entries(self):
        # the identical planted violation with wave_width=1 (or no cfg at
        # all) is the plain gather family's business, not this one's
        W, T, N = 4, 5, 7

        def regress(req, cap):
            fit = req[None, :, None] <= cap[None, None, :]
            return jnp.sum(jnp.broadcast_to(fit, (W, T, N)), axis=(1, 2))

        args = (np.ones(T, np.float32), np.ones(N, np.float32))
        dims = {"N": N, "task_dims": {T}}
        assert check_wavefront(_trace(regress, *args, dims=dims,
                                      cfg=self._wave_cfg(1))) == []
        assert check_wavefront(_trace(regress, *args, dims=dims)) == []

    def test_wave_entry_in_trace_set(self, graph_traces):
        names = [t.name for t in graph_traces]
        assert "allocate/wave4" in names
        tr = next(t for t in graph_traces if t.name == "allocate/wave4")
        assert tr.cfg is not None and tr.cfg.wave_width == 4

    def test_family_registered(self):
        from volcano_tpu.analysis import FAMILIES
        assert "wavefront" in FAMILIES


class TestTelemetryFamily:
    """Family 7 (ISSUE 3): fires on a planted 64-bit telemetry leak and
    the off-build DCE contract; stays green on the real code (covered by
    the fast_report fixture below, which runs all seven families)."""

    def test_fires_on_planted_f64_leak(self, monkeypatch):
        from volcano_tpu.analysis.telemetry import check_telemetry
        from volcano_tpu.telemetry import cycle as tel_cycle
        # the classic accumulator leak: a counter leaf born float64 — under
        # the x64 trace every accumulation step goes wide
        monkeypatch.setattr(tel_cycle, "_F32", jnp.float64)
        findings = check_telemetry(fast=True)
        assert any(f.family == "telemetry" and "float64" in f.what
                   for f in findings), [f.what for f in findings]

    def test_family_registered(self):
        from volcano_tpu.analysis import FAMILIES
        assert "telemetry" in FAMILIES


class TestDonationFamily:
    """Family 8 (ISSUE 4): the device-resident delta path's contract.
    Planted violations — a host callback in the delta update entry and a
    re-read of a donated buffer — must provably fire; the real code stays
    green (covered by the fast_report fixture, which runs all families)."""

    def test_fires_on_planted_callback_in_delta_entry(self, monkeypatch):
        from volcano_tpu.analysis.donation import check_donation
        from volcano_tpu.ops import fused_io as fio
        real_unfuse = fio.make_unfuse

        def planted(treedef, spec):
            unfuse = real_unfuse(treedef, spec)

            def wrapped(fbuf, ibuf, bbuf):
                # the violation class: a host round-trip smuggled into the
                # scatter+cycle entry
                jax.debug.callback(lambda v: None, fbuf[0])
                return unfuse(fbuf, ibuf, bbuf)

            return wrapped

        monkeypatch.setattr(fio, "make_unfuse", planted)
        findings = check_donation(fast=True)
        assert any(f.family == "donation" and "callback" in f.key
                   for f in findings), [f.what for f in findings]

    def test_fires_on_planted_reread_of_donated_buffer(self, monkeypatch):
        import volcano_tpu.telemetry as tel
        from volcano_tpu.analysis.donation import check_donation
        from volcano_tpu.ops import fused_io as fio
        # the double failure that leaves resident handles readable: the
        # entry silently compiles WITHOUT donation (a wrapper dropping
        # jit kwargs would do it) AND the fail-fast invalidation is lost.
        # Either layer alone keeps the contract (the runtime deletes
        # donated inputs itself); losing both is the re-read hazard the
        # family exists to catch.
        real_cj = tel.counted_jit

        def undonated_jit(fn, entry, **kwargs):
            kwargs.pop("donate_argnums", None)
            return real_cj(fn, entry, **kwargs)

        monkeypatch.setattr(tel, "counted_jit", undonated_jit)
        monkeypatch.setattr(fio.DeltaKernel, "_invalidate",
                            lambda self, handles: None)
        findings = check_donation(fast=True)
        assert any(f.family == "donation" and "re-read" in f.key
                   for f in findings), [f.what for f in findings]

    def test_clean_on_real_delta_path(self):
        from volcano_tpu.analysis.donation import check_donation
        assert check_donation(fast=True) == []

    def test_family_registered(self):
        from volcano_tpu.analysis import FAMILIES
        assert "donation" in FAMILIES

    def test_delta_entry_in_trace_set(self, graph_traces):
        assert "fused_io/delta_update" in [t.name for t in graph_traces]


class TestShardingFamily:
    """Family 9 (ISSUE 7): collective discipline of the node-axis sharded
    cycle. A planted O(nodes) all-gather — a node-sharded tensor forced
    to a replicated output — must provably fire; the real compiled entry
    stays green (also covered by the fast_report fixture)."""

    def test_fires_on_planted_allgather(self):
        from volcano_tpu.analysis.sharding import (_collective_findings,
                                                   planted_allgather_hlo)
        hlo = planted_allgather_hlo(n_devices=2, n_nodes=128, cols=4)
        findings = _collective_findings(hlo, 128, "planted")
        assert any(f.family == "sharding" and "allgather" in f.key
                   for f in findings), hlo

    def test_column_gather_is_priced_in(self):
        """A single node-axis COLUMN all-gather (the scan-carry sync, the
        collective analog of SelectBestNode) stays below the 2*N
        threshold and must NOT fire."""
        from volcano_tpu.analysis.sharding import (_collective_findings,
                                                   planted_allgather_hlo)
        hlo = planted_allgather_hlo(n_devices=2, n_nodes=128, cols=1)
        assert _collective_findings(hlo, 128, "column") == []

    def test_clean_on_real_sharded_entry(self):
        from volcano_tpu.analysis.sharding import check_sharding
        assert check_sharding(fast=True) == []

    def test_fires_on_planted_gather_feeding_pallas(self):
        """ISSUE 14: a shard that all-gathers the full node axis and
        feeds it to a pallas launch must trip the shard-local block
        check."""
        from volcano_tpu.analysis.sharding import (_pallas_findings,
                                                   planted_gather_pallas)
        closed, rows_per = planted_gather_pallas(n_devices=2, n_nodes=32)
        findings = _pallas_findings(closed, 32, rows_per, "planted")
        assert any(f.family == "sharding" and "pallas-block" in f.key
                   for f in findings), closed

    def test_shard_local_launch_does_not_fire(self):
        """The REAL sharded+pallas entry's launches are shard-local —
        _pallas_findings on its trace must be empty (the compiled-entry
        sweep in test_clean_on_real_sharded_entry covers the HLO side)."""
        import jax as _jax
        from volcano_tpu.analysis.sharding import (_audit_kernel,
                                                   _pallas_findings)
        from volcano_tpu.parallel import mesh_for_nodes
        kernel = _audit_kernel(mesh_for_nodes(128, 2),
                               "fused_cycle_shardaudit_test_pl",
                               use_pallas="interpret")
        closed = _jax.make_jaxpr(kernel.traceable)(
            *kernel.example_delta_args(256))
        # the launch is really in the trace (the check is not vacuous)
        from volcano_tpu.analysis.jaxpr_audit import iter_eqns
        assert any(e.primitive.name == "pallas_call"
                   for e in iter_eqns(closed.jaxpr))
        assert _pallas_findings(closed, kernel.n_nodes, kernel.rows_per,
                                "real") == []

    def test_family_registered(self):
        from volcano_tpu.analysis import FAMILIES
        assert "sharding" in FAMILIES


class TestCostFamily:
    """Family `cost` (ISSUE 17): the whole-cycle static cost model. The
    FLOP table and liveness sweep are pinned to hand-computable fixtures;
    a planted O(N^2) node x node broadcast must trip the north-star HBM
    projection gate and a planted full-node-axis all_gather the
    collective gate; the real entries stay green (fast_report) with the
    projection numbers in the report meta."""

    def test_matmul_flops_match_textbook(self):
        from volcano_tpu.analysis.costmodel import jaxpr_cost
        A = 64
        closed = jax.make_jaxpr(lambda a, b: a @ b)(
            np.ones((A, A), np.float32), np.ones((A, A), np.float32))
        # 2 * M * N * K — exactly what XLA's cost_analysis reports
        assert jaxpr_cost(closed.jaxpr).flops == 2 * A ** 3

    def test_liveness_fixture_hand_computed(self):
        """(a + b) * 2.0 over 1000-element f32 vectors: with caller-owned
        inputs the peak is inputs (8000) + tmp (4000) + out (4000) =
        16000 bytes at the multiply; donating both inputs lets them die
        at the add, so the multiply holds tmp + out over the still-live
        donated sum = 12000."""
        from volcano_tpu.analysis.costmodel import peak_live_bytes
        a = np.ones(1000, np.float32)
        closed = jax.make_jaxpr(lambda a, b: (a + b) * 2.0)(a, a)
        assert peak_live_bytes(closed) == 16000
        assert peak_live_bytes(closed, donated=(0, 1)) == 12000

    def test_scan_cost_is_trip_aware(self):
        from volcano_tpu.analysis.costmodel import jaxpr_cost

        def loop(c):
            def body(carry, _):
                carry = carry + 1.0         # 1 flop / iteration
                return carry * 2.0, None    # 1 flop / iteration
            out, _ = jax.lax.scan(body, c, None, length=10)
            return out

        closed = jax.make_jaxpr(loop)(np.float32(0.0))
        assert jaxpr_cost(closed.jaxpr).flops == 20

    def test_planted_quadratic_trips_northstar_gate(self):
        """The violation class the gate exists for: an intermediate
        holding the full node x node product. At the audit sizes it is
        tiny (256^2 f32 = 256 KiB) — only the fitted projection to the
        100k-node north star exposes it (~52 TiB >> 16 GiB)."""
        from volcano_tpu.analysis.costmodel import (_projection_findings,
                                                    peak_live_bytes)

        def quad(x):
            return jnp.sum(x[:, None] * x[None, :])

        pts = [(n, peak_live_bytes(jax.make_jaxpr(quad)(
            np.ones(n, np.float32)))) for n in (128, 256)]
        findings = _projection_findings("planted/quad", pts, 16 * 2 ** 30)
        assert findings and "cost:northstar:planted/quad" in findings[0].key
        from volcano_tpu.analysis.costmodel import fit_power
        exponent, _ = fit_power(pts)
        assert exponent > 1.8

    def test_linear_entry_clears_northstar_gate(self):
        from volcano_tpu.analysis.costmodel import (_projection_findings,
                                                    peak_live_bytes)
        pts = [(n, peak_live_bytes(jax.make_jaxpr(lambda x: x * 2.0)(
            np.ones(n, np.float32)))) for n in (128, 256)]
        assert _projection_findings("planted/linear", pts,
                                    16 * 2 ** 30) == []

    @pytest.mark.skipif(jax.device_count() < 2,
                        reason="needs >= 2 devices for a mesh axis")
    def test_planted_full_node_allgather_trips_collective_gate(self):
        """A shard that re-gathers the FULL node block every scan
        iteration: the all_gather output carries 2x the node axis, and
        the trip-aware walk scales its per-cycle bytes by the scan
        length."""
        from jax.experimental.shard_map import shard_map
        from jax.sharding import Mesh, PartitionSpec as P
        from volcano_tpu.analysis.costmodel import (_site_findings,
                                                    jaxpr_cost)
        N, C, T = 32, 4, 5
        mesh = Mesh(np.array(jax.devices()[:2]), ("nodes",))

        def local(x):
            def body(carry, _):
                full = jax.lax.all_gather(x, "nodes", axis=0, tiled=True)
                return carry + jnp.sum(full), None
            s, _ = jax.lax.scan(body, jnp.float32(0.0), None, length=T)
            return x + s

        fn = shard_map(local, mesh=mesh, in_specs=P("nodes", None),
                       out_specs=P("nodes", None), check_rep=False)
        closed = jax.make_jaxpr(fn)(
            jax.ShapeDtypeStruct((N, C), jnp.float32))
        cost = jaxpr_cost(closed.jaxpr)
        findings = _site_findings(cost.sites, N, "planted")
        assert findings and "cost:allgather:planted" in findings[0].key
        # ring total per invocation: out_bytes * (D-1) = N*C*4 * 1,
        # trip-scaled by the scan length
        site = next(s for s in cost.sites if s.prim == "all_gather")
        assert site.out_elems == N * C
        assert site.bytes_per_cycle == N * C * 4 * (2 - 1) * T

    @pytest.mark.skipif(jax.device_count() < 2,
                        reason="needs >= 2 devices for a mesh axis")
    def test_column_allgather_is_priced_in(self):
        """A single node-axis COLUMN gather (the scan-carry sync the
        design prices in) stays under the 2*N threshold."""
        from jax.experimental.shard_map import shard_map
        from jax.sharding import Mesh, PartitionSpec as P
        from volcano_tpu.analysis.costmodel import (_site_findings,
                                                    jaxpr_cost)
        N = 32
        mesh = Mesh(np.array(jax.devices()[:2]), ("nodes",))

        def local(x):
            col = jax.lax.all_gather(x[:, 0], "nodes", axis=0, tiled=True)
            return x + jnp.sum(col)

        fn = shard_map(local, mesh=mesh, in_specs=P("nodes", None),
                       out_specs=P("nodes", None), check_rep=False)
        closed = jax.make_jaxpr(fn)(
            jax.ShapeDtypeStruct((N, 4), jnp.float32))
        cost = jaxpr_cost(closed.jaxpr)
        assert any(s.prim == "all_gather" for s in cost.sites)
        assert _site_findings(cost.sites, N, "column") == []

    def test_hlo_collective_bytes_counts_planted_allgather(self):
        from volcano_tpu.analysis.costmodel import hlo_collective_bytes
        from volcano_tpu.analysis.sharding import planted_allgather_hlo
        hlo = planted_allgather_hlo(n_devices=2, n_nodes=128, cols=4)
        # the partitioner must insert a full [128, 4] f32 gather; the
        # ring total is out_bytes * (D-1) — at least that much traffic
        assert hlo_collective_bytes(hlo, 2) >= 128 * 4 * 4 * (2 - 1)

    def test_real_entry_projection_in_report(self, fast_report):
        cost = fast_report["meta"]["cost"]
        assert "allocate/scan" in cost["entries"]
        ec = cost["entries"]["allocate/scan"]
        assert ec["flops"] > 0 and ec["peak_live_bytes"] > 0
        proj = cost["projection"]["allocate/scan"]
        # the cycle's resident state is O(N): the fitted exponent must
        # say so, and the north-star watermark must clear the budget
        assert 0.5 < proj["peak_live_exponent"] < 1.3
        assert proj["within_budget"]
        ns = cost["northstar"]
        assert ns["nodes"] == 100_000 and ns["tasks"] == 1_000_000

    @pytest.mark.skipif(jax.device_count() < 2,
                        reason="collective audit needs a mesh")
    def test_real_collective_audit_in_report(self, fast_report):
        coll = fast_report["meta"]["cost"]["collectives"]
        assert coll["audited"]
        # cross-shard bytes scale with devices/wave width, not the node
        # axis: the fitted node exponent stays ~linear-or-below
        assert coll["node_exponent"] < 1.3
        assert coll["within_budget"]
        assert coll["per_cycle_bytes"] > 0

    def test_family_stats_in_report(self, fast_report):
        from volcano_tpu.analysis import FAMILIES
        stats = fast_report["meta"]["family_stats"]
        assert set(stats) == set(FAMILIES)
        assert all("elapsed_s" in s and "findings" in s
                   for s in stats.values())

    def test_bench_cost_meta_flattens_report(self, fast_report):
        from volcano_tpu.analysis.costmodel import bench_cost_meta
        block = bench_cost_meta(fast_report["meta"])
        assert block["peak_live_bytes"] > 0
        assert block["scan_flops"] > 0
        assert block["northstar"]["peak_live_bytes"] > 0
        assert block["northstar"]["within_budget"] is True
        # fail-soft contract: no meta, no block — never a raise
        assert bench_cost_meta(None) is None
        assert bench_cost_meta({}) is None

    def test_family_registered(self):
        from volcano_tpu.analysis import FAMILIES
        assert "cost" in FAMILIES


class TestHygieneFamily:
    """Family `hygiene` (ISSUE 17 satellite): every statically-named
    metric emission has an explicit _HELP entry and the exposition keeps
    the # HELP / # TYPE pair ahead of every sample family."""

    def test_fires_on_planted_unhelped_gauge(self):
        from volcano_tpu.analysis.hygiene import _coverage_findings
        from volcano_tpu.metrics.metrics import _HELP
        findings = _coverage_findings(
            {"my_planted_gauge": "planted.py:1"}, _HELP)
        assert findings and \
            "hygiene:help-missing:my_planted_gauge" in findings[0].key

    def test_fires_when_help_entry_removed(self, monkeypatch):
        from volcano_tpu.analysis.hygiene import check_hygiene
        from volcano_tpu.metrics import metrics as m
        monkeypatch.delitem(m._HELP, "queue_share")
        findings = check_hygiene()
        assert any("hygiene:help-missing:queue_share" in f.key
                   for f in findings)

    def test_exposition_pair_check_fires_on_bare_sample(self):
        from volcano_tpu.analysis.hygiene import _exposition_findings

        class Stub:
            def exposition(self):
                return ("# HELP volcano_ok_total fine\n"
                        "# TYPE volcano_ok_total counter\n"
                        "volcano_ok_total 1\n"
                        "volcano_rogue_total 1\n")

        findings = _exposition_findings(Stub())
        assert [f.key for f in findings] == \
            ["hygiene:pair-missing:rogue_total"]

    def test_discovers_alias_and_direct_emissions(self):
        from volcano_tpu.analysis.hygiene import discovered_metric_names
        names = discovered_metric_names()
        # direct self.inc(...) site
        assert "schedule_attempts_total" in names
        # the g = self.set_gauge local-alias idiom (update_queue_family)
        assert "queue_share" in names

    def test_clean_on_live_repo(self):
        from volcano_tpu.analysis.hygiene import check_hygiene
        assert check_hygiene() == []

    def test_family_registered(self):
        from volcano_tpu.analysis import FAMILIES
        assert "hygiene" in FAMILIES


class TestDeriveBatchingErrorPaths:
    """Satellite: the documented error paths of the batching authority."""

    def test_illegal_static_k_dynamic_keys_raises(self):
        from volcano_tpu.analysis.entrypoints import _ALT_SIZE, _snap_extras
        from volcano_tpu.ops.allocate_scan import (AllocateConfig,
                                                   make_allocate_cycle)
        snap, extras = _snap_extras(_ALT_SIZE)
        for bad in (AllocateConfig(batch_jobs=8, drf_job_order=True),
                    AllocateConfig(batch_jobs=8, drf_ns_order=True),
                    AllocateConfig(batch_jobs=8, enable_hdrf=True)):
            with pytest.raises(ValueError,
                               match="static-keys path requires static "
                                     "ordering keys"):
                jax.eval_shape(make_allocate_cycle(bad), snap, extras)

    def test_batching_rule_verifies_clean(self):
        from volcano_tpu.analysis.obligations import verify_batching_rule
        assert verify_batching_rule() == []

    def test_deserved_evidence_paths(self):
        from volcano_tpu.ops.allocate_scan import (AllocateConfig,
                                                   derive_batching)
        neutral = np.full((3, 2), np.inf, np.float32)
        got = derive_batching(AllocateConfig(), queue_deserved=neutral)
        assert got.batch_jobs > 1 and got.batch_rounds == 0
        finite = neutral.copy()
        finite[0, 0] = 0.0      # zero-quota queue counts as dynamic
        got = derive_batching(AllocateConfig(), queue_deserved=finite)
        assert got.batch_rounds > 0

    def test_manual_settings_pass_through(self):
        from volcano_tpu.ops.allocate_scan import (AllocateConfig,
                                                   derive_batching)
        manual = AllocateConfig(batch_jobs=4)
        assert derive_batching(manual, has_proportion=True) == manual


@pytest.fixture(scope="module")
def graph_traces():
    from volcano_tpu.analysis.entrypoints import build_traces
    return build_traces(fast=True)


@pytest.fixture(scope="module")
def fast_report(graph_traces):
    # run_graphcheck re-traces internally; the fixture order just keeps
    # the heavyweight jax state warm within the module
    return run_graphcheck(fast=True)


class TestRealEntryPoints:
    def test_repo_is_clean(self, fast_report):
        blocking = [f for f in fast_report["findings"]
                    if not f["allowlisted"]]
        assert fast_report["clean"], (
            "graphcheck found violations on the real entry points:\n"
            + "\n".join(f"  {f['family']}: {f['what']}" for f in blocking))

    def test_all_families_ran(self, fast_report):
        assert all(fast_report["families"].values())
        assert fast_report["meta"]["traced_entry_points"]

    def test_pallas_kernels_in_trace_set(self, graph_traces):
        from volcano_tpu.analysis.vmem import _pallas_bytes
        names = [t.name for t in graph_traces
                 if t.cfg is not None and t.cfg.use_pallas
                 and _pallas_bytes(t.closed)]
        assert "allocate/pallas_static" in names
        assert "allocate/pallas_dyn" in names

    def test_report_sha_ignores_timing(self, fast_report):
        clone = dict(fast_report)
        clone["elapsed_s"] = 9999.0
        assert report_sha(clone) == fast_report["report_sha256"]


class TestAllowlistPlumbing:
    def test_allowlist_marks_matching_findings(self, monkeypatch):
        from volcano_tpu.analysis import Finding
        from volcano_tpu.analysis import allowlist as al
        monkeypatch.setattr(
            al, "ALLOWLIST",
            (al.Allow("dtype", "known-site", "intentional for the test"),))
        fs = apply_allowlist([
            Finding("dtype", "dtype:known-site:f64", "x", "leak"),
            Finding("dtype", "dtype:other:f64", "y", "leak")])
        assert fs[0].allowlisted and fs[0].reason
        assert not fs[1].allowlisted


@pytest.mark.slow
def test_cost_flops_cross_check_xla_cost_analysis():
    """Fidelity: the cost table's dot_general count matches XLA's own
    Compiled.cost_analysis() exactly on a plain matmul, and stays
    within an order of magnitude on a dot+transcendental composite
    (our 10-flops/element transcendental convention vs XLA's)."""
    from volcano_tpu.analysis.costmodel import jaxpr_cost

    def _xla_flops(compiled):
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        return float((ca or {}).get("flops", 0.0))

    A = 64
    args = (np.ones((A, A), np.float32), np.ones((A, A), np.float32))
    xla = _xla_flops(jax.jit(lambda a, b: a @ b).lower(*args).compile())
    ours = jaxpr_cost(jax.make_jaxpr(lambda a, b: a @ b)(*args).jaxpr).flops
    if xla:                         # backend may not report the counter
        assert ours == int(xla)

    comp = lambda a, b: jnp.sum(jnp.tanh(a @ b))        # noqa: E731
    xla = _xla_flops(jax.jit(comp).lower(*args).compile())
    ours = jaxpr_cost(jax.make_jaxpr(comp)(*args).jaxpr).flops
    assert ours > 0
    if xla:
        assert xla / 10 <= ours <= xla * 10


@pytest.mark.slow
def test_full_graphcheck_cli_exits_zero(tmp_path):
    """Acceptance: `python -m volcano_tpu.analysis` exits 0 on the repo
    with every registered family enabled (full entry set, CLI
    surface)."""
    rpt = tmp_path / "graphcheck.json"
    proc = subprocess.run(
        [sys.executable, "-m", "volcano_tpu.analysis", "--json", str(rpt)],
        capture_output=True, text=True, cwd=_REPO,
        env={**os.environ, "JAX_PLATFORMS": "cpu"}, timeout=600)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    report = json.loads(rpt.read_text())
    assert report["clean"] and all(report["families"].values())
