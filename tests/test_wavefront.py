"""Wavefront task placement (ISSUE 16 acceptance).

The tentpole claim is strict decision equivalence: with ``wave_width`` W,
each scan iteration evaluates the next W eligible tasks against the SAME
capacity snapshot in one batched (W, N) sweep, then commits in-graph in
strict task order — the first conflicting task truncates the wave and
replays — so the committed decisions are bit-identical to the W=1
sequential sweep at EVERY width, on every execution path:

- plain scan (fast, with the CPU oracle reproducing the wave telemetry
  counters exactly),
- the fused pallas paths (W clamps to 1 — byte-identical program),
- the 2-device node-sharded pallas-interpret path (slow),
- the depth-k speculative pipeline with mid-flight arrivals (slow),
- the fleet-batched multi-tenant dispatch (slow).

Plus the non-vacuity leg: a planted same-node-contention fixture where
W=16 provably truncates and replays (W=8 stays conflict-free — the
candidate depth covers the contention), so the commit rule is exercised,
not just traced.
"""

import dataclasses

import jax
import numpy as np
import pytest

from volcano_tpu.arrays import pack
from volcano_tpu.ops import AllocateConfig, make_allocate_cycle
from volcano_tpu.ops.allocate_scan import (AllocateExtras, normalize_wave,
                                           wave_candidate_depth)
from volcano_tpu.runtime.cpu_reference import allocate_cpu
from volcano_tpu.telemetry.cycle import unpack_cycle_telemetry

from fixtures import build_job, build_task, make_cluster, simple_cluster

WIDTHS = (4, 16)


def _decisions(r):
    return (np.asarray(r.task_node).tolist(),
            np.asarray(r.task_mode).tolist(),
            np.asarray(r.task_gpu).tolist(),
            np.asarray(r.job_ready).tolist(),
            np.asarray(r.job_pipelined).tolist())


def _kernel_tel(r, snap):
    """Unpack the CycleTelemetry block from the packed readback."""
    T = np.asarray(snap.tasks.resreq).shape[0]
    J = np.asarray(snap.jobs.task_table).shape[0]
    R = np.asarray(snap.nodes.idle).shape[1]
    return unpack_cycle_telemetry(
        np.asarray(r.packed_decisions())[3 * T + 3 * J:], R)


def _run_widths(ci, base, widths=WIDTHS):
    """Run W=1 and each wave width on one snapshot; assert decisions
    equal W=1 and kernel telemetry == CPU-oracle telemetry at each W.
    Returns {W: kernel telemetry dict} for width-specific claims."""
    snap, _ = pack(ci)
    extras = AllocateExtras.neutral(snap)
    ref = _decisions(jax.jit(make_allocate_cycle(base))(snap, extras))
    tels = {}
    for w in widths:
        cfg = dataclasses.replace(base, wave_width=w)
        rw = jax.jit(make_allocate_cycle(cfg))(snap, extras)
        assert _decisions(rw) == ref, f"W={w} diverged from sequential"
        cpu = allocate_cpu(snap, extras, cfg, collect_telemetry=True)
        assert np.array_equal(cpu["task_node"], ref[0]), f"W={w} oracle"
        assert np.array_equal(cpu["task_mode"], ref[1]), f"W={w} oracle"
        ktel = _kernel_tel(rw, snap)
        assert ktel == cpu["telemetry"], (
            f"W={w} counter drift: "
            + str({k: (v, cpu['telemetry'].get(k))
                   for k, v in ktel.items()
                   if v != cpu['telemetry'].get(k)}))
        tels[w] = ktel
    return tels


class TestScanShaIdentity:
    """Fast legs: the plain-scan path, oracle-checked at every width."""

    def test_mixed_cluster_identity_and_oracle(self):
        tels = _run_widths(make_cluster(),
                           AllocateConfig(telemetry=True))
        for w in WIDTHS:
            assert tels[w]["waves"] > 0
            assert tels[w]["wave_commits"] == sum(
                i * n for i, n in enumerate(tels[w]["wave_hist"]))

    def test_pallas_fused_clamps_to_sequential(self):
        """The fused pallas paths force W=1 (normalize happens inside the
        cycle builder): wave_width on a pallas conf is decision-inert."""
        snap, _ = pack(make_cluster())
        extras = AllocateExtras.neutral(snap)
        base = AllocateConfig(use_pallas="interpret")
        ref = _decisions(jax.jit(make_allocate_cycle(base))(snap, extras))
        wide = dataclasses.replace(base, wave_width=4)
        assert _decisions(
            jax.jit(make_allocate_cycle(wide))(snap, extras)) == ref

    def test_normalize_wave_authority(self):
        assert normalize_wave(AllocateConfig(wave_width=0)).wave_width == 1
        assert normalize_wave(AllocateConfig(wave_width=8)).wave_width == 8
        # the serialized-predicate paths opt out: pod affinity and host
        # ports both consume per-commit state the window sweep can't see
        assert normalize_wave(AllocateConfig(
            wave_width=8, enable_pod_affinity=True)).wave_width == 1
        assert normalize_wave(AllocateConfig(
            wave_width=8, enable_host_ports=True)).wave_width == 1
        assert wave_candidate_depth(1) == 1
        assert wave_candidate_depth(4) == 4
        assert wave_candidate_depth(16) == 8      # clamps at 8


class TestPlantedContention:
    """Non-vacuity: same-node contention must actually truncate/replay."""

    def _contended(self):
        # 16 identical nodes, one 16-task gang of identical tasks, spread
        # scoring: every wave slot's top candidate list is the SAME node
        # ordering, so at W=16 (candidate depth 8) the tail slots exhaust
        # their lists once 8+ nodes are touched — truncation + replay
        ci = simple_cluster(n_nodes=16, node_cpu="8", node_mem="16Gi")
        job = build_job("default/big", min_available=16)
        for i in range(16):
            job.add_task(build_task(f"p{i}", cpu="2", memory="2Gi"))
        ci.add_job(job)
        return ci

    def test_truncation_and_replay_fire_at_w16(self):
        base = AllocateConfig(telemetry=True, least_allocated_weight=1.0)
        tels = _run_widths(self._contended(), base, widths=(8, 16))
        # W=8: candidate depth == W covers the contention — clean sweep
        assert tels[8]["wave_truncations"] == 0
        # W=16: depth clamps at 8 < W, the commit rule must fire
        assert tels[16]["wave_truncations"] > 0, "vacuous planted fixture"
        assert tels[16]["wave_replays"] > 0
        assert tels[16]["wave_commits"] == tels[8]["wave_commits"]

    def test_pipelined_decisions_survive_waving(self):
        """Future-capacity (MODE_PIPELINED) commits ride the same wave
        commit rule: scarce now-capacity + releasing nodes."""
        from volcano_tpu.api import TaskStatus
        ci = simple_cluster(n_nodes=4, node_cpu="4", node_mem="8Gi")
        jobr = build_job("default/running", min_available=1)
        for i in range(4):
            t = build_task(f"r{i}", cpu="3", memory="6Gi",
                           status=TaskStatus.RELEASING)
            t.node_name = f"n{i}"
            jobr.add_task(t)
            ci.nodes[f"n{i}"].add_task(t)
        ci.add_job(jobr)
        jobp = build_job("default/pend", min_available=2)
        for i in range(6):
            jobp.add_task(build_task(f"q{i}", cpu="2", memory="2Gi"))
        ci.add_job(jobp)
        base = AllocateConfig(telemetry=True, enable_pipelining=True,
                              enable_gang=True, least_allocated_weight=1.0)
        tels = _run_widths(ci, base)
        for w in WIDTHS:
            assert tels[w]["placed_future"] > 0, "no pipelined commits"


@pytest.mark.slow
class TestShardedShaIdentity:
    """The shard-local pallas-interpret path on a 2-device mesh: wave
    decisions bitwise equal to the unsharded W=1 scan, oracle-checked."""

    @pytest.mark.parametrize("width", [4, 16])
    def test_sharded_wave_equals_unsharded_scan(self, width):
        import jax.numpy as jnp
        from test_sharded import _random_cluster
        from volcano_tpu.parallel import make_sharded_allocate, scheduler_mesh
        if len(jax.devices()) < 2:
            pytest.skip("needs >= 2 devices")
        mesh = scheduler_mesh(2)
        ci = _random_cluster(5, n_nodes=64, n_jobs=16)
        snap, _ = pack(ci)
        extras = AllocateExtras.neutral(snap)
        base = AllocateConfig(least_allocated_weight=1.0,
                              balanced_weight=1.0,
                              use_pallas="interpret", telemetry=True)
        single = jax.jit(make_allocate_cycle(
            dataclasses.replace(base, use_pallas=False)))(
                jax.tree.map(jnp.asarray, snap), extras)
        cfg = dataclasses.replace(base, wave_width=width)
        fn = make_sharded_allocate(cfg, mesh, snap)
        with mesh:
            sh = fn(snap, extras)
            sh.task_node.block_until_ready()
        assert _decisions(sh) == _decisions(single)
        cpu = allocate_cpu(snap, extras, cfg, collect_telemetry=True)
        assert _kernel_tel(sh, snap) == cpu["telemetry"]


@pytest.mark.slow
class TestPipelinedDepthK:
    """The depth-k speculative pipeline with barriers and mid-flight
    arrivals: the wave run's dispatch-ordered decision stream must sha
    exactly as the W=1 run's (chaos/spec.py's matrix harness)."""

    def test_depthk_stream_sha_identical(self):
        from volcano_tpu.chaos import spec
        ref = spec._drive(depth=3, pipeline=True, cycles=28,
                          arrivals=True)
        wav = spec._drive(depth=3, pipeline=True, cycles=28,
                          arrivals=True, conf_extra="wave_width: 4\n")
        assert wav["records"] == ref["records"]
        assert wav["sha"] == ref["sha"]


@pytest.mark.slow
class TestFleetShaIdentity:
    """The multi-tenant batched dispatch with per-tenant wave_width: the
    fleet's digests must equal N independent W=1 solo references (wave
    neutrality AND batch transparency in one matrix)."""

    def test_fleet_wave_equals_solo_sequential(self):
        from test_fleet import _PROBE_CONF, SPECS, _bases, run_fleet, run_solo
        specs = {n: SPECS[n] for n in ("tenant-a", "tenant-c")}
        bases = _bases(specs)
        fleet_d, _ = run_fleet(bases, cycles=3, specs=specs,
                               conf_text=_PROBE_CONF + "wave_width: 4\n")
        solo_d = run_solo(bases, cycles=3, specs=specs)
        for n in specs:
            assert fleet_d[n] == solo_d[n], n
