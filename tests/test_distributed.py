"""Multi-host mesh groundwork (ISSUE 14, parallel/distributed):
initialize_distributed's single-process no-op contract, the host shard
partition, and per-host delta routing — the union of every host's masked
(D, B) upload must apply exactly the full routed delta through the real
sharded scatter, with foreign rows inert."""

import dataclasses

import jax
import numpy as np
import pytest

from volcano_tpu.framework.conf import parse_conf
from volcano_tpu.parallel import (host_shard_range, initialize_distributed,
                                  mask_foreign_shards)


class TestInitializeDistributed:
    def test_default_is_noop(self, monkeypatch):
        monkeypatch.delenv("VOLCANO_MESH_HOSTS", raising=False)
        monkeypatch.delenv("VOLCANO_COORDINATOR", raising=False)
        monkeypatch.delenv("VOLCANO_PROCESS_ID", raising=False)
        out = initialize_distributed()
        assert out["initialized"] is False
        assert out["n_hosts"] == 1 and out["process_id"] == 0
        assert "single-process" in out["reason"]

    def test_conf_mesh_hosts_one_is_noop(self, monkeypatch):
        # conf wins over env, and 1 host is explicitly single-process
        monkeypatch.setenv("VOLCANO_MESH_HOSTS", "4")
        conf = parse_conf("mesh_hosts: 1\n")
        out = initialize_distributed(conf)
        assert out["initialized"] is False and out["n_hosts"] == 1

    def test_multi_host_without_coordinator_stays_single(self, monkeypatch):
        """mesh_hosts > 1 with no coordinator env must NOT raise and must
        NOT touch jax.distributed — fail-soft into single-process."""
        monkeypatch.delenv("VOLCANO_COORDINATOR", raising=False)
        monkeypatch.delenv("VOLCANO_PROCESS_ID", raising=False)
        out = initialize_distributed(parse_conf("mesh_hosts: 2\n"))
        assert out["initialized"] is False
        assert out["n_hosts"] == 2
        assert "VOLCANO_COORDINATOR" in out["reason"]

    def test_env_mesh_hosts_without_conf(self, monkeypatch):
        monkeypatch.setenv("VOLCANO_MESH_HOSTS", "2")
        monkeypatch.delenv("VOLCANO_COORDINATOR", raising=False)
        monkeypatch.delenv("VOLCANO_PROCESS_ID", raising=False)
        out = initialize_distributed()
        assert out["initialized"] is False and out["n_hosts"] == 2

    def test_conf_parse_roundtrip(self):
        assert parse_conf("mesh_hosts: 2\n").mesh_hosts == 2
        assert parse_conf().mesh_hosts is None


class TestHostShardRange:
    @pytest.mark.parametrize("n_shards,n_hosts",
                             [(8, 1), (8, 2), (8, 3), (8, 8),
                              (2, 2), (30, 4), (5, 3)])
    def test_partition_is_disjoint_and_complete(self, n_shards, n_hosts):
        seen = []
        for h in range(n_hosts):
            lo, hi = host_shard_range(n_shards, n_hosts, h)
            assert 0 <= lo <= hi <= n_shards
            seen.extend(range(lo, hi))
        assert seen == list(range(n_shards))

    def test_even_split_when_divisible(self):
        assert host_shard_range(8, 2, 0) == (0, 4)
        assert host_shard_range(8, 2, 1) == (4, 8)

    def test_bad_host_id_raises(self):
        with pytest.raises(ValueError):
            host_shard_range(8, 2, 2)
        with pytest.raises(ValueError):
            host_shard_range(8, 2, -1)


class TestMaskForeignShards:
    def test_own_rows_untouched_foreign_rows_drop_encoded(self):
        D, B, rows_per, C = 4, 3, 5, 7
        rng = np.random.default_rng(0)
        pidx = rng.integers(0, D * rows_per * C, (D, B)).astype(np.int32)
        pvals = rng.standard_normal((D, B)).astype(np.float32)
        lo, hi = 1, 3
        mi, mv = mask_foreign_shards(pidx, pvals, rows_per, C, lo, hi)
        np.testing.assert_array_equal(mi[lo:hi], pidx[lo:hi])
        np.testing.assert_array_equal(mv[lo:hi], pvals[lo:hi])
        for s in (0, 3):
            assert (mi[s] == (s + 1) * rows_per * C).all()
            assert (mv[s] == 0).all()
        # inputs not mutated
        assert mi is not pidx and mv is not pvals

    def test_full_range_is_identity(self):
        pidx = np.arange(6, dtype=np.int32).reshape(2, 3)
        pvals = np.ones((2, 3), np.float32)
        mi, mv = mask_foreign_shards(pidx, pvals, 4, 2, 0, 2)
        np.testing.assert_array_equal(mi, pidx)
        np.testing.assert_array_equal(mv, pvals)

    def test_empty_bucket_passthrough(self):
        pidx = np.zeros((3, 0), np.int32)
        pvals = np.zeros((3, 0), np.float32)
        mi, mv = mask_foreign_shards(pidx, pvals, 4, 2, 0, 1)
        assert mi.shape == (3, 0) and mv.shape == (3, 0)


@pytest.mark.skipif(len(jax.devices()) < 2,
                    reason="needs a >=2-device mesh "
                           "(XLA_FLAGS=--xla_force_host_platform_device_count)")
class TestPerHostRoutingEquivalence:
    def _kernel(self):
        from volcano_tpu.analysis.entrypoints import _snap_extras
        from volcano_tpu.ops.allocate_scan import (AllocateConfig,
                                                   derive_batching,
                                                   make_allocate_cycle)
        from volcano_tpu.ops.fused_io import ShardedDeltaKernel
        from volcano_tpu.parallel import mesh_for_nodes, node_leaf_mask
        snap, extras = _snap_extras((30, 6, 2))
        cfg = dataclasses.replace(
            derive_batching(AllocateConfig(binpack_weight=1.0,
                                           enable_gpu=False),
                            has_proportion=False), use_pallas=False)
        tree = (snap, extras)
        mesh = mesh_for_nodes(int(np.asarray(snap.nodes.valid).shape[0]), 2)
        kernel = ShardedDeltaKernel(make_allocate_cycle(cfg), tree, mesh,
                                    node_leaf_mask(tree),
                                    entry="fused_cycle_dist_test")
        return kernel, tree

    def test_union_of_host_uploads_equals_full_routing(self):
        """Apply the full routed (D, B) delta in one scatter vs. one
        masked scatter per host: the resident node buffer must end up
        bit-identical — the per-host upload contract."""
        kernel, tree = self._kernel()
        C = kernel.node_cols["f"]
        nb0 = kernel._fuse_sharded(tree)[0]           # f node buffer (N, C)
        scatter = kernel._make_node_scatter("f")
        rng = np.random.default_rng(7)
        # unique flat indices spread over both shards (set semantics make
        # duplicate indices order-dependent; uniqueness keeps the oracle
        # exact)
        idx = rng.choice(kernel.n_nodes * C, size=11,
                         replace=False).astype(np.int32)
        vals = rng.standard_normal(11).astype(nb0.dtype)
        pidx, pvals = kernel._route(idx, vals, "f")
        full, _ = scatter(nb0.copy(), pidx, pvals)
        D = kernel.n_shards
        for n_hosts in (1, 2):
            nb = nb0.copy()
            for h in range(n_hosts):
                lo, hi = host_shard_range(D, n_hosts, h)
                mi, mv = mask_foreign_shards(pidx, pvals, kernel.rows_per,
                                             C, lo, hi)
                nb, _ = scatter(np.asarray(nb), mi, mv)
            np.testing.assert_array_equal(np.asarray(nb), np.asarray(full),
                                          err_msg=f"n_hosts={n_hosts}")

    def test_single_host_upload_leaves_foreign_shards_untouched(self):
        """Host 0's masked upload must not materialize host 1's delta
        content: foreign shard rows of the resident stay at their prior
        bytes."""
        kernel, tree = self._kernel()
        C = kernel.node_cols["f"]
        nb0 = kernel._fuse_sharded(tree)[0]
        scatter = kernel._make_node_scatter("f")
        rows_per, D = kernel.rows_per, kernel.n_shards
        # one real update per shard
        idx = np.array([0, rows_per * C], np.int32)
        vals = np.array([123.0, 456.0], nb0.dtype)
        pidx, pvals = kernel._route(idx, vals, "f")
        lo, hi = host_shard_range(D, 2, 0)
        mi, mv = mask_foreign_shards(pidx, pvals, rows_per, C, lo, hi)
        nb, _ = scatter(nb0.copy(), mi, mv)
        nb = np.asarray(nb)
        assert nb[0, 0] == np.asarray(vals[0])        # own shard applied
        np.testing.assert_array_equal(nb[rows_per:], nb0[rows_per:])

    def test_empty_delta_routes_and_masks_cleanly(self):
        kernel, _tree = self._kernel()
        pidx, pvals = kernel._route(np.zeros(0, np.int32),
                                    np.zeros(0, np.float32), "f")
        assert pidx.shape == (kernel.n_shards, 0)
        mi, mv = mask_foreign_shards(pidx, pvals, kernel.rows_per,
                                     kernel.node_cols["f"], 0, 1)
        assert mi.shape == pidx.shape and mv.shape == pvals.shape
