"""End-to-end scheduler-loop tests through the runtime seam — the unit-level
analog of the reference's e2e suites (test/e2e/schedulingbase/
job_scheduling.go, schedulingaction/{preempt,reclaim}.go), run against the
FakeCluster the way reference action tests run against FakeBinder."""

import numpy as np

from volcano_tpu.api import (ClusterInfo, PodGroupPhase, QueueInfo, Resource,
                             TaskStatus)
from volcano_tpu.framework import parse_conf
from volcano_tpu.runtime import FakeCluster, Scheduler

from fixtures import build_job, build_node, build_task, res, simple_cluster


def make_scheduler(ci, conf_text=None):
    return Scheduler(FakeCluster(ci),
                     conf=parse_conf(conf_text) if conf_text else None)


class TestFullCycle:
    def test_enqueue_allocate_bind(self):
        """Pending PodGroup -> Inqueue -> allocated -> bound, one cycle."""
        ci = simple_cluster(n_nodes=2)
        job = build_job("default/j1", min_available=2,
                        pod_group_phase=PodGroupPhase.PENDING,
                        min_resources=res(cpu="2", memory="2Gi"))
        job.add_task(build_task("p0", cpu="1", memory="1Gi"))
        job.add_task(build_task("p1", cpu="1", memory="1Gi"))
        ci.add_job(job)
        sched = make_scheduler(ci)
        ssn = sched.run_once()
        assert ssn.stats.get("enqueued") == 1
        assert len(sched.cluster.binds) == 2
        stored = sched.cluster.ci.jobs["default/j1"]
        # enqueued this cycle, then allocated -> gang ready -> Running
        assert stored.pod_group_phase == PodGroupPhase.RUNNING
        assert all(t.status == TaskStatus.BOUND for t in stored.tasks.values())
        # nodes actually account the bound tasks
        used = sum(n.used.milli_cpu for n in sched.cluster.ci.nodes.values())
        assert used == 2000

    def test_gang_blocks_until_capacity(self):
        """A 3-task gang on a 2-slot cluster binds nothing, then binds all
        after a node is added (scale-up recovery)."""
        ci = simple_cluster(n_nodes=1, node_cpu="2", node_mem="4Gi")
        job = build_job("default/gang", min_available=3)
        for i in range(3):
            job.add_task(build_task(f"g{i}", cpu="1", memory="1Gi"))
        ci.add_job(job)
        sched = make_scheduler(ci)
        sched.run_once()
        assert sched.cluster.binds == []
        sched.cluster.ci.add_node(build_node("n-new", cpu="2", memory="4Gi"))
        sched.run_once()
        assert len(sched.cluster.binds) == 3

    def test_multi_cycle_progress(self):
        """Bound tasks keep their placement across cycles; new jobs fill
        remaining capacity."""
        ci = simple_cluster(n_nodes=1, node_cpu="4", node_mem="8Gi")
        j1 = build_job("default/j1", min_available=1)
        j1.add_task(build_task("a0", cpu="2", memory="1Gi"))
        ci.add_job(j1)
        sched = make_scheduler(ci)
        sched.run_once()
        assert len(sched.cluster.binds) == 1
        j2 = build_job("default/j2", min_available=1)
        j2.add_task(build_task("b0", cpu="2", memory="1Gi"))
        sched.cluster.ci.add_job(j2)
        sched.run_once()
        assert len(sched.cluster.binds) == 2
        assert sched.cluster.ci.nodes["n0"].idle.milli_cpu == 0

    def test_backfill_places_best_effort(self):
        ci = simple_cluster(n_nodes=1)
        job = build_job("default/be", min_available=1)
        job.add_task(build_task("be0", cpu=0, memory=0))
        ci.add_job(job)
        sched = make_scheduler(ci)
        ssn = sched.run_once()
        assert ssn.stats.get("backfilled") == 1
        assert len(sched.cluster.binds) == 1


class TestPreemptE2E:
    def conf(self):
        return """
actions: "enqueue, allocate, preempt, backfill"
tiers:
- plugins:
  - name: priority
  - name: gang
  - name: conformance
- plugins:
  - name: drf
  - name: predicates
  - name: proportion
  - name: nodeorder
"""

    def test_high_priority_preempts_low(self):
        """Starving high-priority gang evicts a low-priority job's surplus
        tasks in the same queue (preempt.go:42-291)."""
        ci = simple_cluster(n_nodes=1, node_cpu="2", node_mem="4Gi")
        lo = build_job("default/lo", min_available=1, priority=1)
        for i in range(2):
            t = build_task(f"lo-{i}", cpu="1", memory="1Gi")
            t.status = TaskStatus.RUNNING
            lo.add_task(t)
            ci.nodes["n0"].add_task(t)
        ci.add_job(lo)
        hi = build_job("default/hi", min_available=1, priority=10)
        hi.add_task(build_task("hi-0", cpu="1", memory="1Gi"))
        ci.add_job(hi)
        sched = make_scheduler(ci, self.conf())
        ssn = sched.run_once()
        assert ssn.stats.get("preempt_evictions", 0) >= 1
        assert len(sched.cluster.evictions) >= 1
        # the victim is a lo task, and hi-0 is pipelined onto the node
        assert all(uid.startswith("default/lo") for uid in sched.cluster.evictions)
        assert "default/hi-0" in ssn.pipelined

    def test_gang_protects_min_available(self):
        """Victims stop once the low-priority gang hits its minAvailable
        (gang.go:83-107 veto)."""
        ci = simple_cluster(n_nodes=1, node_cpu="3", node_mem="6Gi")
        lo = build_job("default/lo", min_available=2, priority=1)
        for i in range(3):
            t = build_task(f"lo-{i}", cpu="1", memory="1Gi")
            t.status = TaskStatus.RUNNING
            lo.add_task(t)
            ci.nodes["n0"].add_task(t)
        ci.add_job(lo)
        hi = build_job("default/hi", min_available=2, priority=10)
        for i in range(2):
            hi.add_task(build_task(f"hi-{i}", cpu="1", memory="1Gi"))
        ci.add_job(hi)
        sched = make_scheduler(ci, self.conf())
        sched.run_once()
        # only 1 surplus task may be evicted (3 running - minAvailable 2);
        # hi needs 2 slots -> cannot be satisfied -> gang discard, no evictions
        assert len(sched.cluster.evictions) == 0

    def test_no_preemption_across_equal_priority(self):
        ci = simple_cluster(n_nodes=1, node_cpu="1", node_mem="2Gi")
        a = build_job("default/a", min_available=1, priority=5)
        t = build_task("a-0", cpu="1", memory="1Gi")
        t.status = TaskStatus.RUNNING
        a.add_task(t)
        ci.nodes["n0"].add_task(t)
        ci.add_job(a)
        b = build_job("default/b", min_available=1, priority=5)
        b.add_task(build_task("b-0", cpu="1", memory="1Gi"))
        ci.add_job(b)
        sched = make_scheduler(ci, self.conf())
        sched.run_once()
        assert sched.cluster.evictions == []


class TestReclaimE2E:
    def conf(self):
        return """
actions: "enqueue, reclaim, allocate, backfill"
tiers:
- plugins:
  - name: priority
  - name: gang
  - name: conformance
- plugins:
  - name: proportion
  - name: predicates
  - name: nodeorder
"""

    def test_underserved_queue_reclaims(self):
        """q2's starving job reclaims capacity from q1 which is over its
        deserved share (reclaim.go:40-191)."""
        ci = simple_cluster(n_nodes=1, node_cpu="4", node_mem="8Gi")
        ci.add_queue(QueueInfo("q1", weight=1, reclaimable=True))
        ci.add_queue(QueueInfo("q2", weight=1))
        greedy = build_job("default/greedy", queue="q1", min_available=1,
                           priority=1)
        for i in range(4):
            t = build_task(f"gr-{i}", cpu="1", memory="1Gi")
            t.status = TaskStatus.RUNNING
            greedy.add_task(t)
            ci.nodes["n0"].add_task(t)
        ci.add_job(greedy)
        starv = build_job("default/starv", queue="q2", min_available=1,
                          priority=1)
        starv.add_task(build_task("st-0", cpu="1", memory="1Gi"))
        ci.add_job(starv)
        sched = make_scheduler(ci, self.conf())
        ssn = sched.run_once()
        assert ssn.stats.get("reclaim_evictions", 0) >= 1
        assert any(uid.startswith("default/gr") for uid in sched.cluster.evictions)

    def test_non_reclaimable_queue_protected(self):
        ci = simple_cluster(n_nodes=1, node_cpu="4", node_mem="8Gi")
        ci.add_queue(QueueInfo("q1", weight=1, reclaimable=False))
        ci.add_queue(QueueInfo("q2", weight=1))
        greedy = build_job("default/greedy", queue="q1", min_available=1)
        for i in range(4):
            t = build_task(f"gr-{i}", cpu="1", memory="1Gi")
            t.status = TaskStatus.RUNNING
            greedy.add_task(t)
            ci.nodes["n0"].add_task(t)
        ci.add_job(greedy)
        starv = build_job("default/starv", queue="q2", min_available=1)
        starv.add_task(build_task("st-0", cpu="1", memory="1Gi"))
        ci.add_job(starv)
        sched = make_scheduler(ci, self.conf())
        sched.run_once()
        assert sched.cluster.evictions == []


class TestConfSystem:
    def test_default_conf_parses(self):
        conf = parse_conf()
        assert conf.actions == ["enqueue", "allocate", "backfill"]
        assert conf.enabled("gang") and conf.enabled("proportion")

    def test_hdrf_proportion_conflict(self):
        import pytest
        bad = """
actions: "allocate"
tiers:
- plugins:
  - name: drf
    enableHierarchy: true
  - name: proportion
"""
        with pytest.raises(ValueError):
            parse_conf(bad)

    def test_metrics_exposition(self):
        from volcano_tpu.metrics import METRICS
        ci = simple_cluster(n_nodes=1)
        sched = make_scheduler(ci)
        sched.run_once()
        text = METRICS.exposition()
        assert "volcano_schedule_attempts" in text
        assert "e2e_scheduling_latency_milliseconds" in text


class TestBindSeamTolerance:
    """ADVICE r1 (medium): the device cycle admits with float32 1e-5 slack;
    the host Resource algebra checks float64 1e-9. A boundary exact-fit that
    passes on-device but fails host-side must degrade to a recorded bind
    error (reference: dispatch returns the AddTask error and continues,
    session.go:330-355), never crash apply_allocate mid-way."""

    def test_session_bind_overflow_reverts_to_pending(self):
        from volcano_tpu.framework import Session
        ci = simple_cluster(n_nodes=1, node_cpu="1", node_mem="1Gi")
        job = build_job("default/j1", min_available=1)
        job.add_task(build_task("t0", cpu="1", memory="1Gi"))
        ci.add_job(job)
        ssn = Session(ci)
        task = next(iter(ci.jobs["default/j1"].tasks.values()))
        # host-side view: make the node too small AFTER packing, so the
        # bind seam sees a fit failure the kernel did not
        node = ci.nodes["n0"]
        node.idle.sub_floored(res(cpu="500m"))
        ssn._bind_task(task.uid, "n0")
        assert ssn.binds == []
        assert len(ssn.bind_errors) == 1
        assert task.status == TaskStatus.PENDING
        assert task.gpu_index == -1

    def test_fake_cluster_bind_overflow_returns_false(self):
        ci = simple_cluster(n_nodes=1, node_cpu="1", node_mem="1Gi")
        job = build_job("default/j1", min_available=1)
        job.add_task(build_task("t0", cpu="1", memory="1Gi"))
        ci.add_job(job)
        cluster = FakeCluster(ci)
        from volcano_tpu.framework.session import BindIntent
        task = next(iter(cluster.ci.jobs["default/j1"].tasks.values()))
        cluster.ci.nodes["n0"].idle.sub_floored(res(cpu="500m"))
        ok = cluster.bind(BindIntent(task.uid, "default/j1", "n0", -1))
        assert not ok
        assert cluster.binds == []
        assert task.status == TaskStatus.PENDING
