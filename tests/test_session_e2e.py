"""End-to-end scheduler-loop tests through the runtime seam — the unit-level
analog of the reference's e2e suites (test/e2e/schedulingbase/
job_scheduling.go, schedulingaction/{preempt,reclaim}.go), run against the
FakeCluster the way reference action tests run against FakeBinder."""

import numpy as np

from volcano_tpu.api import (ClusterInfo, PodGroupPhase, QueueInfo, Resource,
                             TaskStatus)
from volcano_tpu.framework import parse_conf
from volcano_tpu.runtime import FakeCluster, Scheduler

from fixtures import build_job, build_node, build_task, res, simple_cluster


def make_scheduler(ci, conf_text=None):
    return Scheduler(FakeCluster(ci),
                     conf=parse_conf(conf_text) if conf_text else None)


class TestFullCycle:
    def test_enqueue_allocate_bind(self):
        """Pending PodGroup -> Inqueue -> allocated -> bound, one cycle."""
        ci = simple_cluster(n_nodes=2)
        job = build_job("default/j1", min_available=2,
                        pod_group_phase=PodGroupPhase.PENDING,
                        min_resources=res(cpu="2", memory="2Gi"))
        job.add_task(build_task("p0", cpu="1", memory="1Gi"))
        job.add_task(build_task("p1", cpu="1", memory="1Gi"))
        ci.add_job(job)
        sched = make_scheduler(ci)
        ssn = sched.run_once()
        assert ssn.stats.get("enqueued") == 1
        assert len(sched.cluster.binds) == 2
        stored = sched.cluster.ci.jobs["default/j1"]
        # enqueued this cycle, then allocated -> gang ready -> Running
        assert stored.pod_group_phase == PodGroupPhase.RUNNING
        assert all(t.status == TaskStatus.BOUND for t in stored.tasks.values())
        # nodes actually account the bound tasks
        used = sum(n.used.milli_cpu for n in sched.cluster.ci.nodes.values())
        assert used == 2000

    def test_gang_blocks_until_capacity(self):
        """A 3-task gang on a 2-slot cluster binds nothing, then binds all
        after a node is added (scale-up recovery)."""
        ci = simple_cluster(n_nodes=1, node_cpu="2", node_mem="4Gi")
        job = build_job("default/gang", min_available=3)
        for i in range(3):
            job.add_task(build_task(f"g{i}", cpu="1", memory="1Gi"))
        ci.add_job(job)
        sched = make_scheduler(ci)
        sched.run_once()
        assert sched.cluster.binds == []
        sched.cluster.ci.add_node(build_node("n-new", cpu="2", memory="4Gi"))
        sched.run_once()
        assert len(sched.cluster.binds) == 3

    def test_multi_cycle_progress(self):
        """Bound tasks keep their placement across cycles; new jobs fill
        remaining capacity."""
        ci = simple_cluster(n_nodes=1, node_cpu="4", node_mem="8Gi")
        j1 = build_job("default/j1", min_available=1)
        j1.add_task(build_task("a0", cpu="2", memory="1Gi"))
        ci.add_job(j1)
        sched = make_scheduler(ci)
        sched.run_once()
        assert len(sched.cluster.binds) == 1
        j2 = build_job("default/j2", min_available=1)
        j2.add_task(build_task("b0", cpu="2", memory="1Gi"))
        sched.cluster.ci.add_job(j2)
        sched.run_once()
        assert len(sched.cluster.binds) == 2
        assert sched.cluster.ci.nodes["n0"].idle.milli_cpu == 0

    def test_backfill_places_best_effort(self):
        ci = simple_cluster(n_nodes=1)
        job = build_job("default/be", min_available=1)
        job.add_task(build_task("be0", cpu=0, memory=0))
        ci.add_job(job)
        sched = make_scheduler(ci)
        ssn = sched.run_once()
        assert ssn.stats.get("backfilled") == 1
        assert len(sched.cluster.binds) == 1


class TestPreemptE2E:
    def conf(self):
        return """
actions: "enqueue, allocate, preempt, backfill"
tiers:
- plugins:
  - name: priority
  - name: gang
  - name: conformance
- plugins:
  - name: drf
  - name: predicates
  - name: proportion
  - name: nodeorder
"""

    def test_high_priority_preempts_low(self):
        """Starving high-priority gang evicts a low-priority job's surplus
        tasks in the same queue (preempt.go:42-291)."""
        ci = simple_cluster(n_nodes=1, node_cpu="2", node_mem="4Gi")
        lo = build_job("default/lo", min_available=1, priority=1)
        for i in range(2):
            t = build_task(f"lo-{i}", cpu="1", memory="1Gi")
            t.status = TaskStatus.RUNNING
            lo.add_task(t)
            ci.nodes["n0"].add_task(t)
        ci.add_job(lo)
        hi = build_job("default/hi", min_available=1, priority=10)
        hi.add_task(build_task("hi-0", cpu="1", memory="1Gi"))
        ci.add_job(hi)
        sched = make_scheduler(ci, self.conf())
        ssn = sched.run_once()
        assert ssn.stats.get("preempt_evictions", 0) >= 1
        assert len(sched.cluster.evictions) >= 1
        # the victim is a lo task, and hi-0 is pipelined onto the node
        assert all(uid.startswith("default/lo") for uid in sched.cluster.evictions)
        assert "default/hi-0" in ssn.pipelined

    def test_priority_victims_cross_gang_min_available(self):
        """This fork's gang preemptableFn is a plain job-priority rule
        (gang.go:83-103) — it does NOT stop victims at the low gang's
        minAvailable, so a higher-priority gang takes what it needs."""
        ci = simple_cluster(n_nodes=1, node_cpu="3", node_mem="6Gi")
        lo = build_job("default/lo", min_available=2, priority=1)
        for i in range(3):
            t = build_task(f"lo-{i}", cpu="1", memory="1Gi")
            t.status = TaskStatus.RUNNING
            lo.add_task(t)
            ci.nodes["n0"].add_task(t)
        ci.add_job(lo)
        hi = build_job("default/hi", min_available=2, priority=10)
        for i in range(2):
            hi.add_task(build_task(f"hi-{i}", cpu="1", memory="1Gi"))
        ci.add_job(hi)
        sched = make_scheduler(ci, self.conf())
        ssn = sched.run_once()
        # hi needs 2 slots on the full node -> 2 lo victims (even though lo
        # then falls below its minAvailable), and hi holds the capacity
        assert len(sched.cluster.evictions) == 2
        assert all(uid.startswith("default/lo")
                   for uid in sched.cluster.evictions)
        assert {"default/hi-0", "default/hi-1"} <= set(ssn.pipelined)

    def test_no_preemption_across_equal_priority(self):
        ci = simple_cluster(n_nodes=1, node_cpu="1", node_mem="2Gi")
        a = build_job("default/a", min_available=1, priority=5)
        t = build_task("a-0", cpu="1", memory="1Gi")
        t.status = TaskStatus.RUNNING
        a.add_task(t)
        ci.nodes["n0"].add_task(t)
        ci.add_job(a)
        b = build_job("default/b", min_available=1, priority=5)
        b.add_task(build_task("b-0", cpu="1", memory="1Gi"))
        ci.add_job(b)
        sched = make_scheduler(ci, self.conf())
        sched.run_once()
        assert sched.cluster.evictions == []


class TestReclaimE2E:
    def conf(self):
        return """
actions: "enqueue, reclaim, allocate, backfill"
tiers:
- plugins:
  - name: priority
  - name: gang
  - name: conformance
- plugins:
  - name: proportion
  - name: predicates
  - name: nodeorder
"""

    def test_underserved_queue_reclaims(self):
        """q2's starving job reclaims capacity from q1 which is over its
        deserved share (reclaim.go:40-191). Tasks request cpu only: the
        proportion victim rule is a per-dim what-if — the donor queue must
        stay at-or-above deserved on EVERY dim after the eviction
        (proportion.go:217-236), so an uncontended-memory queue whose
        deserved memory equals its full request would never donate."""
        ci = simple_cluster(n_nodes=1, node_cpu="4", node_mem="8Gi")
        ci.add_queue(QueueInfo("q1", weight=1, reclaimable=True))
        ci.add_queue(QueueInfo("q2", weight=1))
        greedy = build_job("default/greedy", queue="q1", min_available=1,
                           priority=1)
        for i in range(4):
            t = build_task(f"gr-{i}", cpu="1", memory=0)
            t.status = TaskStatus.RUNNING
            greedy.add_task(t)
            ci.nodes["n0"].add_task(t)
        ci.add_job(greedy)
        starv = build_job("default/starv", queue="q2", min_available=1,
                          priority=1)
        starv.add_task(build_task("st-0", cpu="1", memory=0))
        ci.add_job(starv)
        sched = make_scheduler(ci, self.conf())
        ssn = sched.run_once()
        assert ssn.stats.get("reclaim_evictions", 0) >= 1
        assert any(uid.startswith("default/gr") for uid in sched.cluster.evictions)

    def test_non_reclaimable_queue_protected(self):
        ci = simple_cluster(n_nodes=1, node_cpu="4", node_mem="8Gi")
        ci.add_queue(QueueInfo("q1", weight=1, reclaimable=False))
        ci.add_queue(QueueInfo("q2", weight=1))
        greedy = build_job("default/greedy", queue="q1", min_available=1)
        for i in range(4):
            t = build_task(f"gr-{i}", cpu="1", memory="1Gi")
            t.status = TaskStatus.RUNNING
            greedy.add_task(t)
            ci.nodes["n0"].add_task(t)
        ci.add_job(greedy)
        starv = build_job("default/starv", queue="q2", min_available=1)
        starv.add_task(build_task("st-0", cpu="1", memory="1Gi"))
        ci.add_job(starv)
        sched = make_scheduler(ci, self.conf())
        sched.run_once()
        assert sched.cluster.evictions == []


class TestConfSystem:
    def test_default_conf_parses(self):
        conf = parse_conf()
        assert conf.actions == ["enqueue", "allocate", "backfill"]
        assert conf.enabled("gang") and conf.enabled("proportion")

    def test_hdrf_proportion_conflict(self):
        import pytest
        bad = """
actions: "allocate"
tiers:
- plugins:
  - name: drf
    enableHierarchy: true
  - name: proportion
"""
        with pytest.raises(ValueError):
            parse_conf(bad)

    def test_metrics_exposition(self):
        from volcano_tpu.metrics import METRICS
        ci = simple_cluster(n_nodes=1)
        sched = make_scheduler(ci)
        sched.run_once()
        text = METRICS.exposition()
        assert "volcano_schedule_attempts" in text
        assert "e2e_scheduling_latency_milliseconds" in text


class TestBindSeamTolerance:
    """ADVICE r1 (medium): the device cycle admits with float32 1e-5 slack;
    the host Resource algebra checks float64 1e-9. A boundary exact-fit that
    passes on-device but fails host-side must degrade to a recorded bind
    error (reference: dispatch returns the AddTask error and continues,
    session.go:330-355), never crash apply_allocate mid-way."""

    def test_session_bind_overflow_reverts_to_pending(self):
        from volcano_tpu.framework import Session
        ci = simple_cluster(n_nodes=1, node_cpu="1", node_mem="1Gi")
        job = build_job("default/j1", min_available=1)
        job.add_task(build_task("t0", cpu="1", memory="1Gi"))
        ci.add_job(job)
        ssn = Session(ci)
        task = next(iter(ci.jobs["default/j1"].tasks.values()))
        # host-side view: make the node too small AFTER packing, so the
        # bind seam sees a fit failure the kernel did not
        node = ci.nodes["n0"]
        node.idle.sub_floored(res(cpu="500m"))
        ssn._bind_task(task.uid, "n0")
        assert ssn.binds == []
        assert len(ssn.bind_errors) == 1
        assert task.status == TaskStatus.PENDING
        assert task.gpu_index == -1

    def test_fake_cluster_bind_overflow_returns_false(self):
        ci = simple_cluster(n_nodes=1, node_cpu="1", node_mem="1Gi")
        job = build_job("default/j1", min_available=1)
        job.add_task(build_task("t0", cpu="1", memory="1Gi"))
        ci.add_job(job)
        cluster = FakeCluster(ci)
        from volcano_tpu.framework.session import BindIntent
        task = next(iter(cluster.ci.jobs["default/j1"].tasks.values()))
        cluster.ci.nodes["n0"].idle.sub_floored(res(cpu="500m"))
        ok = cluster.bind(BindIntent(task.uid, "default/j1", "n0", -1))
        assert not ok
        assert cluster.binds == []
        assert task.status == TaskStatus.PENDING


class TestScaleAllocatables:
    """ScaleAllocatable configurations shrink node allocatable + idle at
    session open (framework.go:33 -> session.go:448-468)."""

    CONF = """
actions: "allocate"
configurations:
  - name: ScaleAllocatable
    arguments:
      millicpu: 0.5
tiers:
- plugins:
  - name: nodeorder
"""

    def test_scaling_changes_placement(self):
        import numpy as np
        from volcano_tpu.framework.conf import parse_conf
        from volcano_tpu.framework.session import Session
        ci = simple_cluster(n_nodes=1, node_cpu="4", node_mem="8Gi")
        job = build_job("default/j", min_available=0)
        for i in range(4):
            job.add_task(build_task(f"t-{i}", cpu="1", memory="1Gi"))
        ci.add_job(job)
        # unscaled: all 4 tasks fit
        plain = Session(ci, parse_conf("""
actions: "allocate"
tiers:
- plugins:
  - name: nodeorder
"""))
        plain.run_allocate()
        assert len(plain.binds) == 4
        # scaled to 2 cpu: only 2 place
        ci2 = simple_cluster(n_nodes=1, node_cpu="4", node_mem="8Gi")
        job2 = build_job("default/j", min_available=0)
        for i in range(4):
            job2.add_task(build_task(f"t-{i}", cpu="1", memory="1Gi"))
        ci2.add_job(job2)
        ssn = Session(ci2, parse_conf(self.CONF))
        alloc = np.asarray(ssn.snap.nodes.allocatable)
        assert alloc[0, 0] == 2000.0      # 4 cpu * 0.5
        assert np.asarray(ssn.snap.nodes.idle)[0, 0] == 2000.0
        assert np.asarray(ssn.snap.cluster_capacity)[0] == 2000.0
        ssn.run_allocate()
        assert len(ssn.binds) == 2

    def test_scaling_below_used_zeroes_idle(self):
        """When the removed allocatable exceeds idle, idle cpu+memory zero
        out instead of going negative (session.go:459-463)."""
        import numpy as np
        from volcano_tpu.framework.conf import parse_conf
        from volcano_tpu.framework.session import Session
        ci = simple_cluster(n_nodes=1, node_cpu="4", node_mem="8Gi")
        job = build_job("default/j", min_available=1)
        t = build_task("r-0", cpu="3", memory="1Gi")
        t.status = TaskStatus.RUNNING
        job.add_task(t)
        ci.nodes["n0"].add_task(t)
        ci.add_job(job)
        # scale to 2 cpu: unavailable (2 cpu) > idle (1 cpu) -> idle zeroed
        ssn = Session(ci, parse_conf(self.CONF))
        idle = np.asarray(ssn.snap.nodes.idle)
        assert idle[0, 0] == 0.0
        assert idle[0, 1] == 0.0


class TestResyncRetry:
    """Failed bind/evict dispatches retry from the rate-limited resync
    queue (cache.go:687-709) without a fresh allocate decision."""

    def test_failed_bind_retries_and_binds_later(self):
        ci = simple_cluster(n_nodes=1)
        job = build_job("default/j", min_available=1)
        job.add_task(build_task("t-0", cpu="1", memory="1Gi"))
        ci.add_job(job)
        sched = make_scheduler(ci)
        # first bind attempt fails once, then the backend accepts
        sched.cluster.bind_failures["default/t-0"] = 1
        sched.run_once(now=100.0)
        assert sched.cluster.binds == []
        # the task holds Binding on its decided node so later cycles do not
        # re-decide it
        held = sched.cluster.ci.jobs["default/j"].tasks["default/t-0"]
        assert held.status == TaskStatus.BINDING
        assert len(sched.resync) == 1
        # next cycle: the retry (not a fresh decision) lands the bind
        ssn = sched.run_once(now=101.0)
        assert sched.cluster.binds == [("default/t-0", "n0")]
        assert ssn.binds == []   # the session itself decided nothing new
        assert held.status == TaskStatus.BOUND

    def test_exhausted_retries_resync_then_fresh_decision(self):
        from volcano_tpu.metrics import METRICS
        ci = simple_cluster(n_nodes=1)
        job = build_job("default/j", min_available=1)
        job.add_task(build_task("t-0", cpu="1", memory="1Gi"))
        ci.add_job(job)
        sched = make_scheduler(ci)
        sched.resync.max_attempts = 3
        sched.cluster.bind_failures["default/t-0"] = "node gone"   # forever
        dropped0 = METRICS.counter_value("resync_dropped")
        sched.run_once(now=100.0)
        task = sched.cluster.ci.jobs["default/j"].tasks["default/t-0"]
        assert task.status == TaskStatus.BINDING
        for i in range(3):
            sched.run_once(now=200.0 + 100.0 * i)
        # retries exhausted -> the drop resyncs the task to Pending (the
        # syncTask give-up, cache.go:690-709) and the SAME cycle's fresh
        # session re-decides it, restarting the retry ladder at attempt 1
        assert METRICS.counter_value("resync_dropped") == dropped0 + 1
        assert len(sched.resync) == 1
        assert sched.resync.entries[0]["attempts"] == 1
        # once the backend recovers, the retry path completes the bind
        del sched.cluster.bind_failures["default/t-0"]
        sched.run_once(now=1000.0)
        assert task.status == TaskStatus.BOUND
        assert len(sched.resync) == 0

    def test_backoff_rate_limits_retries(self):
        from volcano_tpu.runtime.scheduler import ResyncQueue
        from volcano_tpu.framework.session import BindIntent
        q = ResyncQueue(base_delay=1.0, max_delay=8.0, max_attempts=5)

        class Never:
            def __init__(self):
                self.calls = 0

            def bind(self, intent):
                self.calls += 1
                return False

            def resync_task(self, uid):
                pass

        c = Never()
        q.add(BindIntent("t", "j", "n"), "bind", now=0.0)
        assert q.process(c, now=0.5) == dict(retried=0, succeeded=0,
                                             dropped=0, dead_lettered=0,
                                             fenced=0)
        assert q.process(c, now=1.0)["retried"] == 1      # after base delay
        # second attempt backs off exponentially (2s, not 1s)
        assert q.process(c, now=2.0)["retried"] == 0
        assert q.process(c, now=3.5)["retried"] == 1


class TestConformanceMatrix:
    """conformance.go:45-63 evictableFn skip rules: kube-system namespace,
    system-cluster-critical, system-node-critical are never victims."""

    def _run(self, namespace="default", priority_class=""):
        ci = ClusterInfo()
        ci.add_node(build_node("n0", cpu="1", memory="2Gi"))
        ci.add_queue(QueueInfo("default", weight=1))
        lo = build_job(f"{namespace}/lo", min_available=1, priority=1,
                       namespace=namespace)
        t = build_task("lo-0", cpu="1", memory="1Gi", namespace=namespace,
                       status=TaskStatus.RUNNING)
        t.priority_class = priority_class
        lo.add_task(t)
        ci.nodes["n0"].add_task(t)
        ci.add_job(lo)
        hi = build_job("default/hi", min_available=1, priority=10)
        hi.add_task(build_task("hi-0", cpu="1", memory="1Gi"))
        ci.add_job(hi)
        conf = """
actions: "preempt"
tiers:
- plugins:
  - name: priority
  - name: conformance
"""
        sched = make_scheduler(ci, conf)
        sched.run_once()
        return sched.cluster.evictions

    def test_normal_pod_evictable(self):
        assert len(self._run()) == 1

    def test_kube_system_protected(self):
        assert self._run(namespace="kube-system") == []

    def test_cluster_critical_protected(self):
        assert self._run(priority_class="system-cluster-critical") == []

    def test_node_critical_protected(self):
        assert self._run(priority_class="system-node-critical") == []

    def test_other_priority_class_evictable(self):
        assert len(self._run(priority_class="high-priority")) == 1


class TestSLAMatrix:
    """sla.go behavior matrix: per-job annotation overrides the global
    argument (readJobWaitingTime :57-66), the enqueue gate permits overdue
    jobs (:133-145), and job order runs earliest-deadline-first
    (:104-131)."""

    def _conf(self, global_jwt=None):
        args = (f"\n    arguments:\n      sla-waiting-time: {global_jwt}"
                if global_jwt else "")
        return f"""
actions: "enqueue, allocate, backfill"
tiers:
- plugins:
  - name: gang
  - name: sla{args}
  - name: proportion
"""

    def test_job_annotation_overrides_global(self):
        """Global SLA 1h would not admit yet, but the job's own 10s
        annotation does."""
        from volcano_tpu.api import PodGroupPhase
        now = 1_000_000.0
        ci = simple_cluster(n_nodes=1, node_cpu="1")
        q = ci.queues["default"]
        q.capability = res(cpu="1")
        # the queue is full, so only an SLA override admits the job
        running = build_job("default/holder", min_available=1)
        t = build_task("h-0", cpu="1", memory=0, status=TaskStatus.RUNNING)
        running.add_task(t)
        ci.nodes["n0"].add_task(t)
        ci.add_job(running)
        j = build_job("default/slow", min_available=1,
                      pod_group_phase=PodGroupPhase.PENDING,
                      min_resources=res(cpu="1"),
                      creation_timestamp=now - 60)
        j.add_task(build_task("s-0", cpu="1", memory=0))
        j.sla_waiting_time = "10s"
        ci.add_job(j)
        sched = make_scheduler(ci, self._conf(global_jwt="1h"))
        ssn = sched.run_once(now=now)
        assert ssn.stats.get("enqueued") == 1

    def test_global_only_not_yet_due(self):
        from volcano_tpu.api import PodGroupPhase
        now = 1_000_000.0
        ci = simple_cluster(n_nodes=1, node_cpu="1")
        ci.queues["default"].capability = res(cpu="1")
        running = build_job("default/holder", min_available=1)
        t = build_task("h-0", cpu="1", memory=0, status=TaskStatus.RUNNING)
        running.add_task(t)
        ci.nodes["n0"].add_task(t)
        ci.add_job(running)
        j = build_job("default/slow", min_available=1,
                      pod_group_phase=PodGroupPhase.PENDING,
                      min_resources=res(cpu="1"),
                      creation_timestamp=now - 60)
        j.add_task(build_task("s-0", cpu="1", memory=0))
        ci.add_job(j)
        sched = make_scheduler(ci, self._conf(global_jwt="1h"))
        ssn = sched.run_once(now=now)
        assert ssn.stats.get("enqueued") == 0

    def test_deadline_orders_jobs(self):
        """Two jobs, one slot: the one with the EARLIER creation+jwt
        deadline places first even though the other is older."""
        now = 1_000_000.0
        ci = simple_cluster(n_nodes=1, node_cpu="1")
        old = build_job("default/old", min_available=1,
                        creation_timestamp=now - 100)
        old.add_task(build_task("o-0", cpu="1", memory=0))
        old.sla_waiting_time = "1h"        # deadline now+3500
        ci.add_job(old)
        urgent = build_job("default/urgent", min_available=1,
                           creation_timestamp=now - 10)
        urgent.add_task(build_task("u-0", cpu="1", memory=0))
        urgent.sla_waiting_time = "30s"    # deadline now+20
        ci.add_job(urgent)
        sched = make_scheduler(ci, self._conf())
        sched.run_once(now=now)
        binds = dict(sched.cluster.binds)
        assert binds.get("default/u-0") == "n0"
        assert "default/o-0" not in binds

    def test_no_sla_jobs_sort_last(self):
        now = 1_000_000.0
        ci = simple_cluster(n_nodes=1, node_cpu="1")
        plain = build_job("default/plain", min_available=1,
                          creation_timestamp=now - 1000)
        plain.add_task(build_task("p-0", cpu="1", memory=0))
        ci.add_job(plain)
        sla = build_job("default/sla", min_available=1,
                        creation_timestamp=now - 10)
        sla.add_task(build_task("s-0", cpu="1", memory=0))
        sla.sla_waiting_time = "1h"
        ci.add_job(sla)
        sched = make_scheduler(ci, self._conf())
        sched.run_once(now=now)
        binds = dict(sched.cluster.binds)
        assert binds.get("default/s-0") == "n0"
