"""Delta uploads + pipelined cycle loop vs the full-upload synchronous
oracle (ISSUE 4 acceptance).

Multi-cycle scheduler runs over identical clusters must produce
sha256-identical decision sequences across all four loop variants:

- fresh Session per cycle (incremental=False) — the reference oracle,
- persistent session, full uploads every cycle (delta_uploads: false),
- persistent session, device-resident delta uploads (the default),
- persistent session, delta uploads + one-deep pipelined readback.

Churn covers binds landing, evictions (preempt), job completion/rearrival,
and node add/remove (entity-set change -> the structural full-re-fuse
fallback).
"""

import hashlib

import numpy as np
import pytest

from volcano_tpu.api import NodeInfo, Resource, TaskStatus
from volcano_tpu.framework import parse_conf
from volcano_tpu.runtime.fake_cluster import FakeCluster
from volcano_tpu.runtime.scheduler import Scheduler

from fixtures import build_job, build_task, simple_cluster
from test_runtime_incremental import PREEMPT_CONF, build_cluster, churn

#: allocate-terminal variant of the incremental-suite conf (same plugins;
#: backfill only places best-effort tasks, which this cluster has none of)
#: so the pipelined loop can defer the allocate readback
_PARITY_BODY = """
actions: "enqueue, allocate"
tiers:
- plugins:
  - name: priority
  - name: gang
- plugins:
  - name: drf
  - name: predicates
  - name: proportion
  - name: binpack
"""
PARITY_CONF = parse_conf(_PARITY_BODY)
NODELTA_CONF = parse_conf("delta_uploads: false\n" + _PARITY_BODY)

ALLOC_CONF = parse_conf("""
actions: "allocate"
tiers:
- plugins:
  - name: gang
  - name: binpack
""")


def digest(ssn) -> tuple:
    return (sorted((b.task_uid, b.node_name, b.gpu_index)
                   for b in ssn.binds),
            sorted(e.task_uid for e in ssn.evictions),
            sorted(ssn.pipelined.items()),
            sorted((u, str(p)) for u, p in ssn.phase_updates.items()))


def decisions_sha(digests) -> str:
    return hashlib.sha256(repr(digests).encode()).hexdigest()[:16]


def node_churn(cluster: FakeCluster, cycle: int) -> None:
    """Entity-set churn: a node arrives, later an empty node drains —
    both structural (refresh_snapshot repacks, the scheduler opens a
    fresh Session, the delta path re-fuses in full)."""
    ci = cluster.ci
    if cycle == 2:
        ci.add_node(NodeInfo(
            f"late{cycle}", allocatable=Resource.from_resource_list(
                {"cpu": "8", "memory": "16Gi", "pods": "110"})))
        cluster.mark_dirty(structural=True)
    if cycle == 4:
        empty = [n for n, node in ci.nodes.items() if not node.tasks]
        if empty:
            del ci.nodes[empty[-1]]
            cluster.mark_dirty(structural=True)


class TestDeltaLoopParity:
    def run_variants(self, conf_pairs, cycles, with_node_churn=False):
        """Drive every (label, conf, pipeline) variant over clones of one
        cluster with identical churn; return {label: sha}."""
        base = build_cluster(n_nodes=8, n_jobs=10)
        shas = {}
        for label, conf, incremental, pipeline in conf_pairs:
            cluster = FakeCluster(base.clone())
            sched = Scheduler(cluster, conf=conf, incremental=incremental,
                              pipeline=pipeline)
            digests = []
            for c in range(cycles):
                out = sched.run_once(now=1000.0 + c)
                rec = (sched.drain(now=1000.0 + c) or out) if pipeline \
                    else out
                digests.append(digest(rec))
                churn(cluster, c, arrivals=True)
                if with_node_churn:
                    node_churn(cluster, c)
            shas[label] = decisions_sha(digests)
        assert len(set(shas.values())) == 1, shas
        return shas

    def test_delta_and_pipeline_match_oracle_sha(self):
        """Binds + completions + arrivals: all variants sha-identical to
        the fresh-session oracle across 6 cycles."""
        self.run_variants([
            ("oracle_fresh", PARITY_CONF, False, False),
            ("sync_full_upload", NODELTA_CONF, True, False),
            ("sync_delta", PARITY_CONF, True, False),
            ("pipelined_delta", PARITY_CONF, True, True),
        ], cycles=6)

    def test_node_add_remove_structural_fallback(self):
        """Node arrival/drain mid-run: the delta path must take the full
        re-fuse fallback and stay sha-identical."""
        self.run_variants([
            ("oracle_fresh", PARITY_CONF, False, False),
            ("sync_delta", PARITY_CONF, True, False),
            ("pipelined_delta", PARITY_CONF, True, True),
        ], cycles=6, with_node_churn=True)

    @pytest.mark.slow
    def test_evictions_preempt_loop(self):
        """Preempt evictions through the delta+pipelined loop: eviction
        bookkeeping must round-trip exactly. The preempt conf does not end
        with allocate, so the pipelined scheduler transparently falls back
        to the synchronous path — decisions must be unaffected either
        way. Slow-marked for the tier-1 budget (the 20 s preempt-conf
        compile dominates); the sha-matrix coverage of the delta+pipelined
        loop itself stays tier-1 in the two tests above."""
        base = build_cluster(n_nodes=4, n_jobs=6, tasks_per_job=4)
        shas = {}
        for label, incremental, pipeline in (
                ("oracle", False, False), ("delta", True, False),
                ("pipelined_flag", True, True)):
            cluster = FakeCluster(base.clone())
            sched = Scheduler(cluster, conf=PREEMPT_CONF,
                              incremental=incremental, pipeline=pipeline)
            digests = []
            for c in range(3):
                ssn = sched.run_once(now=2000.0 + c)
                assert sched._pending is None   # fallback: nothing queued
                digests.append(digest(ssn))
                for uid in sorted(u for job in cluster.ci.jobs.values()
                                  for u, t in job.tasks.items()
                                  if t.status == TaskStatus.BOUND):
                    cluster.run_task(uid)
                if c == 0:
                    hi = build_job("default/hi", min_available=4,
                                   priority=100, creation_timestamp=50.0,
                                   preemptable=False)
                    for t in range(4):
                        hi.add_task(build_task(f"hi-t{t}", cpu="6",
                                               memory="12Gi", priority=100))
                    cluster.ci.add_job(hi)
                    cluster.mark_dirty(job_uid=hi.uid)
            shas[label] = decisions_sha(digests)
        assert len(set(shas.values())) == 1, shas


class TestFreeRunningPipeline:
    def test_free_run_matches_sync_cycle_for_cycle(self):
        """Free-running pipeline (no per-cycle drain): with churn that
        does not read the in-flight cycle's outcome, the completed-cycle
        records must equal the synchronous scheduler's cycles one for
        one — the deferred readback shifts timing, never decisions."""
        ci = simple_cluster(n_nodes=6, node_cpu="8", node_mem="16Gi")
        for j in range(8):
            job = build_job(f"default/j{j}", min_available=2,
                            creation_timestamp=float(j))
            for t in range(3):
                job.add_task(build_task(f"j{j}-t{t}", cpu="2",
                                        memory="2Gi"))
            ci.add_job(job)
        ca, cb = FakeCluster(ci.clone()), FakeCluster(ci.clone())
        sync = Scheduler(ca, conf=ALLOC_CONF)
        pipe = Scheduler(cb, conf=ALLOC_CONF, pipeline=True)

        def arrive(cluster, c):
            job = build_job(f"default/new{c}", min_available=1,
                            creation_timestamp=100.0 + c)
            job.add_task(build_task(f"new{c}-t0", cpu="1", memory="1Gi"))
            cluster.ci.add_job(job)
            cluster.mark_dirty(job_uid=job.uid)

        sync_digests, pipe_digests = [], []
        for c in range(5):
            sync_digests.append(digest(sync.run_once(now=1000.0 + c)))
            out = pipe.run_once(now=1000.0 + c)
            if c > 0:
                pipe_digests.append(digest(out))
            arrive(ca, c)
            arrive(cb, c)
        pipe_digests.append(digest(pipe.drain(now=1005.0)))
        assert sync_digests == pipe_digests
        assert pipe.cycles == sync.cycles == 5
        # the steady cycles actually took the delta path
        kinds = [e.get("cycle_kind") for e in pipe.flight.snapshots()]
        assert kinds.count("delta") >= 2, kinds
        # flight records carry the upload accounting bench consumes
        deltas = [e for e in pipe.flight.snapshots()
                  if e.get("cycle_kind") == "delta"]
        assert all(e["upload_bytes"] < e["upload_bytes_full"]
                   for e in deltas)

    def test_structural_epochs_counted(self):
        ci = build_cluster(n_nodes=4, n_jobs=4)
        cluster = FakeCluster(ci)
        assert cluster.structural_epochs == 0
        cluster.mark_dirty(structural=True)
        cluster.mark_dirty(structural=True)   # same pending epoch
        assert cluster.structural_epochs == 1
        cluster.drain_dirty()
        cluster.mark_dirty(structural=True)
        assert cluster.structural_epochs == 2


class TestConfFlags:
    def test_parse_conf_flags(self):
        sc = parse_conf("delta_uploads: false\npipeline: true\n"
                        "compilation_cache_dir: /tmp/vc_cache\n"
                        + """
actions: "allocate"
tiers:
- plugins:
  - name: binpack
""")
        assert sc.delta_uploads is False
        assert sc.pipeline is True
        assert sc.compilation_cache_dir == "/tmp/vc_cache"
        default = parse_conf()
        assert default.delta_uploads is True
        assert default.pipeline is False
        assert default.compilation_cache_dir is None

    def test_scheduler_picks_up_conf_pipeline(self):
        conf = parse_conf("pipeline: true\n" + """
actions: "allocate"
tiers:
- plugins:
  - name: binpack
""")
        sched = Scheduler(FakeCluster(build_cluster(4, 2)), conf=conf)
        assert sched.pipeline
        sched = Scheduler(FakeCluster(build_cluster(4, 2)), conf=conf,
                          pipeline=False)
        assert not sched.pipeline


class TestWarmup:
    def test_scheduler_warmup_compiles_without_cycle(self):
        from volcano_tpu.telemetry import tracecount
        cluster = FakeCluster(build_cluster(n_nodes=4, n_jobs=4))
        sched = Scheduler(cluster, conf=ALLOC_CONF)
        before = tracecount.counts().get("fused_cycle_delta",
                                         {"traces": 0})["traces"]
        sched.warmup(now=999.0)
        after = tracecount.counts().get("fused_cycle_delta",
                                        {"traces": 0})["traces"]
        assert after == before + 1          # AOT-traced, nothing executed
        assert sched.cycles == 0
        ssn = sched.run_once(now=1000.0)    # and the real cycle still runs
        assert ssn.binds
