"""Node-affinity matchExpressions: full k8s operator semantics.

VERDICT r4 #4: In / NotIn / Exists / DoesNotExist / Gt / Lt for required and
preferred node affinity. The semantics vectors mirror the k8s
nodeaffinity.GetRequiredNodeAffinity cases the reference inherits through
its wrapped NodeAffinity plugin (predicates.go:186-190 filter,
nodeorder.go:255-266 preferred scorer). Expression terms ride the per-task
OR-group masks (Session._node_affinity_extras), so the kernel path, the CPU
oracle, and the sidecar wire all see identical feasibility.
"""

import numpy as np

from volcano_tpu.api import (ClusterInfo, JobInfo, NodeInfo, NodeSelectorTerm,
                             QueueInfo, Resource, TaskInfo)
from volcano_tpu.framework import parse_conf
from volcano_tpu.framework.session import Session

R = Resource.from_resource_list

CONF = parse_conf("""
actions: "allocate"
tiers:
- plugins:
  - name: gang
  - name: predicates
  - name: nodeorder
    arguments:
      nodeaffinity.weight: 1
  - name: binpack
""")


def term(expressions=None, labels=None):
    return NodeSelectorTerm(match_labels=labels or {},
                            match_expressions=[
                                (k, op, tuple(v)) for k, op, v
                                in (expressions or [])])


class TestOperatorSemantics:
    """Ported k8s nodeaffinity requirement vectors."""

    LABELS = {"zone": "us-east1-a", "gpu": "true", "cores": "8"}

    def check(self, t, want):
        assert t.matches(self.LABELS) is want

    def test_in_present(self):
        self.check(term([("zone", "In", ["us-east1-a", "us-east1-b"])]), True)

    def test_in_wrong_value(self):
        self.check(term([("zone", "In", ["us-west1-a"])]), False)

    def test_in_missing_key(self):
        self.check(term([("disk", "In", ["ssd"])]), False)

    def test_not_in_hit(self):
        self.check(term([("zone", "NotIn", ["us-east1-a"])]), False)

    def test_not_in_other_value(self):
        self.check(term([("zone", "NotIn", ["us-west1-a"])]), True)

    def test_not_in_missing_key_matches(self):
        self.check(term([("disk", "NotIn", ["ssd"])]), True)

    def test_exists(self):
        self.check(term([("gpu", "Exists", [])]), True)

    def test_exists_missing(self):
        self.check(term([("disk", "Exists", [])]), False)

    def test_does_not_exist(self):
        self.check(term([("disk", "DoesNotExist", [])]), True)

    def test_does_not_exist_present(self):
        self.check(term([("gpu", "DoesNotExist", [])]), False)

    def test_gt(self):
        self.check(term([("cores", "Gt", ["4"])]), True)
        self.check(term([("cores", "Gt", ["8"])]), False)

    def test_lt(self):
        self.check(term([("cores", "Lt", ["16"])]), True)
        self.check(term([("cores", "Lt", ["8"])]), False)

    def test_gt_non_numeric_label(self):
        self.check(term([("zone", "Gt", ["4"])]), False)

    def test_gt_missing_key(self):
        self.check(term([("disk", "Gt", ["4"])]), False)

    def test_gt_multiple_values_invalid(self):
        self.check(term([("cores", "Gt", ["4", "5"])]), False)

    def test_expressions_and_within_term(self):
        self.check(term([("zone", "In", ["us-east1-a"]),
                         ("cores", "Gt", ["4"])]), True)
        self.check(term([("zone", "In", ["us-east1-a"]),
                         ("cores", "Gt", ["100"])]), False)

    def test_empty_term_matches_nothing(self):
        self.check(term(), False)

    def test_labels_and_expressions(self):
        self.check(term([("cores", "Gt", ["4"])],
                        labels={"gpu": "true"}), True)
        self.check(term([("cores", "Gt", ["4"])],
                        labels={"gpu": "false"}), False)


def build_cluster():
    """6 nodes with varied labels for end-to-end placement checks."""
    ci = ClusterInfo()
    ci.add_queue(QueueInfo("default", weight=1))
    specs = [
        ("n0", {"zone": "a", "tier": "web", "cores": "4"}),
        ("n1", {"zone": "a", "tier": "db", "cores": "8"}),
        ("n2", {"zone": "b", "tier": "web", "cores": "16"}),
        ("n3", {"zone": "b", "cores": "32"}),
        ("n4", {"zone": "c", "tier": "db", "cores": "2"}),
        ("n5", {"zone": "c", "gpu": "true", "cores": "64"}),
    ]
    for name, labels in specs:
        n = NodeInfo(name, R({"cpu": "8", "memory": "16Gi"}),
                     R({"cpu": "8", "memory": "16Gi"}))
        n.labels.update(labels)
        ci.add_node(n)
    return ci


def place(ci, *tasks):
    from volcano_tpu.api import PodGroupPhase
    job = JobInfo("default/j", queue="default", min_available=0,
                  creation_timestamp=1.0,
                  pod_group_phase=PodGroupPhase.INQUEUE)
    for t in tasks:
        job.add_task(t)
    ci.add_job(job)
    ssn = Session(ci, CONF)
    ssn.run_allocate()
    return {b.task_uid: b.node_name for b in ssn.binds}


def mk_task(name, required=None, preferred=None):
    t = TaskInfo(f"default/{name}", name,
                 resreq=R({"cpu": "1", "memory": "1Gi"}))
    t.affinity_required = required or []
    t.affinity_preferred = preferred or []
    return t


class TestEndToEndRequired:
    def test_single_expression_term(self):
        """cores > 8 excludes n0/n1/n4; Exists(gpu) narrows to n5."""
        binds = place(build_cluster(),
                      mk_task("a", required=[term([("cores", "Gt", ["8"])])]),
                      mk_task("b", required=[term([("gpu", "Exists", [])])]))
        assert binds["default/a"] in ("n2", "n3", "n5")
        assert binds["default/b"] == "n5"

    def test_not_in_and_does_not_exist(self):
        """NotIn zone {a,b} + DoesNotExist(gpu) -> only n4."""
        binds = place(build_cluster(), mk_task("a", required=[
            term([("zone", "NotIn", ["a", "b"]),
                  ("gpu", "DoesNotExist", [])])]))
        assert binds["default/a"] == "n4"

    def test_or_of_terms_with_expressions(self):
        """tier=web OR cores < 4 -> n0, n2 (web) or n4 (cores 2)."""
        binds = place(build_cluster(), mk_task("a", required=[
            term([("tier", "In", ["web"])]),
            term([("cores", "Lt", ["4"])])]))
        assert binds["default/a"] in ("n0", "n2", "n4")

    def test_unsatisfiable_expression_blocks(self):
        binds = place(build_cluster(), mk_task("a", required=[
            term([("cores", "Gt", ["100"])])]))
        assert "default/a" not in binds

    def test_mixed_labels_and_expression(self):
        """zone=b labels AND cores < 20 -> n2 only."""
        binds = place(build_cluster(), mk_task("a", required=[
            term([("cores", "Lt", ["20"])], labels={"zone": "b"})]))
        assert binds["default/a"] == "n2"


class TestEndToEndPreferred:
    def test_preferred_expression_steers(self):
        """All nodes feasible; preference Gt(cores, 30) steers to n3/n5,
        and the heavier weight on gpu Exists wins n5."""
        binds = place(build_cluster(), mk_task("a", preferred=[
            (term([("cores", "Gt", ["30"])]), 1.0),
            (term([("gpu", "Exists", [])]), 10.0)]))
        assert binds["default/a"] == "n5"

    def test_preferred_not_in_repels(self):
        binds = place(build_cluster(), mk_task("a", preferred=[
            (term([("zone", "NotIn", ["a", "b"])]), 5.0)]))
        assert binds["default/a"] in ("n4", "n5")


class TestOracleEquality:
    def test_session_kernel_matches_cpu_oracle_with_expressions(self):
        """Randomized expression workloads: kernel decisions equal the
        sequential CPU reference through the same extras."""
        import dataclasses
        from volcano_tpu.runtime.cpu_reference import allocate_cpu
        rng = np.random.RandomState(7)
        ci = ClusterInfo()
        ci.add_queue(QueueInfo("default", weight=1))
        zones = ["a", "b", "c"]
        for i in range(24):
            n = NodeInfo(f"n{i:02d}", R({"cpu": "8", "memory": "16Gi"}),
                         R({"cpu": "8", "memory": "16Gi"}))
            n.labels["zone"] = zones[i % 3]
            n.labels["cores"] = str(2 ** (i % 6))
            if i % 4 == 0:
                n.labels["gpu"] = "true"
            ci.add_node(n)
        pool = [
            [term([("cores", "Gt", ["4"])])],
            [term([("zone", "In", ["a", "c"])])],
            [term([("gpu", "Exists", [])]), term([("cores", "Lt", ["3"])])],
            [term([("zone", "NotIn", ["b"]), ("gpu", "DoesNotExist", [])])],
            [],
        ]
        from volcano_tpu.api import PodGroupPhase
        for j in range(12):
            job = JobInfo(f"default/j{j}", queue="default", min_available=1,
                          creation_timestamp=float(j),
                          pod_group_phase=PodGroupPhase.INQUEUE)
            req = pool[rng.randint(len(pool))]
            for k in range(3):
                t = TaskInfo(f"default/j{j}-t{k}", f"j{j}-t{k}",
                             resreq=R({"cpu": "1", "memory": "1Gi"}))
                t.affinity_required = req
                if rng.rand() < 0.5:
                    t.affinity_preferred = [
                        (term([("cores", "Gt", ["8"])]), 2.0)]
                job.add_task(t)
            ci.add_job(job)
        ssn = Session(ci, CONF)
        cfg = ssn.allocate_config()
        extras = ssn.allocate_extras()
        cpu = allocate_cpu(ssn.snap, extras, cfg)
        ssn.run_allocate()
        res = ssn.last_allocate
        np.testing.assert_array_equal(np.asarray(res.task_node),
                                      cpu["task_node"])
        np.testing.assert_array_equal(np.asarray(res.task_mode),
                                      cpu["task_mode"])
        # and at least one expression group actually constrained a task
        assert (np.asarray(extras.task_or_group) >= 0).any()
