"""Session.refresh_snapshot: incremental snapshot patching vs full repack.

The steady-state cycle path (the event-handler analog of the reference's
incrementally maintained cache, event_handlers.go:43-740): after binds,
evictions, and status churn on an unchanged entity set, the patched arrays
must equal a from-scratch pack of the mutated cluster bit for bit.
"""

import numpy as np
import jax

from volcano_tpu.api import TaskStatus
from volcano_tpu.arrays.pack import pack
from volcano_tpu.framework import parse_conf
from volcano_tpu.framework.session import Session

from fixtures import build_job, build_task, simple_cluster

CONF = parse_conf("""
actions: "allocate"
tiers:
- plugins:
  - name: gang
  - name: binpack
""")


def build_cluster(n_nodes=6, n_jobs=8, tasks_per_job=4):
    ci = simple_cluster(n_nodes=n_nodes, node_cpu="8", node_mem="16Gi")
    for j in range(n_jobs):
        job = build_job(f"default/j{j}", min_available=2,
                        priority=j % 3, creation_timestamp=float(j))
        for t in range(tasks_per_job):
            job.add_task(build_task(f"j{j}-t{t}", cpu="1", memory="1Gi",
                                    priority=t % 2))
        ci.add_job(job)
    return ci


def assert_snap_equal(got, want):
    gl = jax.tree.leaves(got)
    wl = jax.tree.leaves(want)
    assert len(gl) == len(wl)
    for g, w in zip(gl, wl):
        np.testing.assert_array_equal(np.asarray(g), np.asarray(w))


class TestRefreshSnapshot:
    def test_apply_churn_matches_full_pack(self):
        ci = build_cluster()
        ssn = Session(ci, CONF)
        ssn.run_allocate()      # binds mutate the cluster + record dirties
        assert ssn.binds
        ok = ssn.refresh_snapshot()
        assert ok
        want, _ = pack(ci)
        assert_snap_equal(ssn.snap, want)

    def test_status_churn_and_eviction(self):
        ci = build_cluster()
        ssn = Session(ci, CONF)
        ssn.run_allocate()
        # promote some bound tasks to Running, complete one job, evict one
        uids = list(ci.jobs)
        run_job = ci.jobs[uids[0]]
        for task in run_job.tasks.values():
            if task.status == TaskStatus.BINDING:
                run_job.update_task_status(task, TaskStatus.RUNNING)
        ssn.mark_dirty(job_uid=run_job.uid)
        done_job = ci.jobs[uids[1]]
        for task in done_job.tasks.values():
            node = ci.nodes.get(task.node_name)
            if node is not None and task.uid in node.tasks:
                node.remove_task(task)
                ssn.mark_dirty(node_name=node.name)
            done_job.update_task_status(task, TaskStatus.SUCCEEDED)
            task.node_name = ""
        ssn.mark_dirty(job_uid=done_job.uid)
        ssn.evict_task(next(iter(ci.jobs[uids[2]].tasks)))
        ok = ssn.refresh_snapshot()
        assert ok
        want, _ = pack(ci)
        assert_snap_equal(ssn.snap, want)

    def test_reset_to_pending_round_trips(self):
        """The steady-cycle churn shape: a bound gang resets to pending
        (completed-and-replaced arrival) and the next cycle re-places it."""
        ci = build_cluster()
        ssn = Session(ci, CONF)
        ssn.run_allocate()
        uid = list(ci.jobs)[3]
        job = ci.jobs[uid]
        for task in list(job.tasks.values()):
            node = ci.nodes.get(task.node_name)
            if node is not None and task.uid in node.tasks:
                node.remove_task(task)
                ssn.mark_dirty(node_name=node.name)
            job.update_task_status(task, TaskStatus.PENDING)
            task.node_name = ""
        job.allocated = type(job.allocated)({})
        ssn.mark_dirty(job_uid=uid)
        assert ssn.refresh_snapshot()
        want, _ = pack(ci)
        assert_snap_equal(ssn.snap, want)
        # and the next cycle places the churned gang again
        before = len(ssn.binds)
        ssn.run_allocate()
        placed_again = [b for b in ssn.binds[before:] if b.job_uid == uid]
        assert len(placed_again) == len(job.tasks)

    def test_queue_close_and_capacity_change(self):
        """Queue open-state flips re-derive member jobs' schedulable; a
        node allocatable change re-derives cluster_capacity (both feed
        the kernel's ordering/eligibility directly)."""
        from volcano_tpu.api import QueueState, Resource
        ci = build_cluster()
        ssn = Session(ci, CONF)
        ssn.run_allocate()
        ssn.refresh_snapshot()      # absorb the bind churn
        ci.queues["default"].state = QueueState.CLOSED
        node = ci.nodes["n0"]
        node.allocatable = Resource.from_resource_list(
            {"cpu": "16", "memory": "32Gi"})
        node.capability = Resource.from_resource_list(
            {"cpu": "16", "memory": "32Gi"})
        ssn.mark_dirty(node_name="n0")
        assert ssn.refresh_snapshot()
        want, _ = pack(ci)
        assert_snap_equal(ssn.snap, want)
        assert not np.asarray(ssn.snap.jobs.schedulable).any()

    def test_namespace_weight_change(self):
        ci = build_cluster()
        ssn = Session(ci, CONF)
        ssn.run_allocate()
        ssn.refresh_snapshot()
        ci.namespaces["default"].weight = 7
        assert ssn.refresh_snapshot()
        want, _ = pack(ci)
        assert_snap_equal(ssn.snap, want)

    def test_entity_set_change_falls_back(self):
        ci = build_cluster()
        ssn = Session(ci, CONF)
        ssn.run_allocate()
        newjob = build_job("default/late", min_available=1)
        newjob.add_task(build_task("late-t0", cpu="1", memory="1Gi"))
        ci.add_job(newjob)
        ssn.mark_dirty(job_uid="default/late")
        ok = ssn.refresh_snapshot()
        assert not ok                       # full repack path
        want, maps = pack(ci)
        assert_snap_equal(ssn.snap, want)
        assert "default/late" in ssn.maps.job_index
