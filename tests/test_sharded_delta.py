"""Node-axis sharded cycle execution (ISSUE 7): ShardedDeltaKernel and
the conf-driven ``sharding: true`` scheduler path.

Tier-1 (fast) coverage on the 2-device mesh (and the degenerate 1-device
mesh) — the 8-device sweeps live in test_sharded.py's slow tier:

- scheduler-level decision identity: ``sharding: true`` runs must be
  sha-identical to the unsharded loop, sync and pipelined, with zero
  resharding copies recorded on every steady delta cycle,
- the routed delta scatter: after a cross-shard mutation the resident
  node buffers on device are bit-identical to a fresh host fuse,
- per-shard digest discipline: a corrupted mirror block flips EXACTLY
  its shard's digest word, and ``recover`` restores both the digest and
  decision identity.
"""

import dataclasses

import numpy as np
import jax
import pytest

from volcano_tpu.framework import parse_conf
from volcano_tpu.ops.allocate_scan import (AllocateConfig, derive_batching,
                                           make_allocate_cycle)
from volcano_tpu.ops.fused_io import (DeltaKernel, ResidentState,
                                      ShardedDeltaKernel)
from volcano_tpu.parallel import mesh_for_nodes, node_leaf_mask
from volcano_tpu.runtime.fake_cluster import FakeCluster
from volcano_tpu.runtime.scheduler import Scheduler

from test_delta_pipeline import decisions_sha, digest
from test_runtime_incremental import build_cluster, churn

_BODY = """
actions: "allocate"
tiers:
- plugins:
  - name: gang
  - name: binpack
"""
PLAIN_CONF = parse_conf(_BODY)
SHARD1_CONF = parse_conf("sharding: true\nsharding_devices: 1\n" + _BODY)
SHARD2_CONF = parse_conf("sharding: true\nsharding_devices: 2\n" + _BODY)


def _pl_conf(devices: int):
    """Sharded + shard-local pallas candidate launch (ISSUE 14), in
    interpret mode so the matrix runs on the CPU test mesh."""
    return parse_conf(f"sharding: true\nsharding_devices: {devices}\n"
                      "use_pallas: interpret\n" + _BODY)

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 2, reason="needs the multi-device virtual mesh")


def _run_loop(conf, pipeline, cycles=4):
    cluster = FakeCluster(build_cluster(n_nodes=8, n_jobs=6).clone())
    sched = Scheduler(cluster, conf=conf, incremental=True,
                      pipeline=pipeline)
    digests = []
    for c in range(cycles):
        out = sched.run_once(now=1000.0 + c)
        rec = (sched.drain(now=1000.0 + c) or out) if pipeline else out
        digests.append(digest(rec))
        churn(cluster, c, arrivals=True)
    return decisions_sha(digests), sched


class TestShardedSchedulerIdentity:
    # full-suite (`pytest -m slow`): the multi-loop sha sweep; tier-1's
    # chaos --sharded smoke proves per-shard decision identity every
    # run — budget calibration
    @pytest.mark.slow
    def test_sharded_loops_match_unsharded_sha(self):
        """2-device and 1-device sharded loops, sync and pipelined, all
        sha-identical to the unsharded scheduler on identical churn."""
        shas = {
            "plain_sync": _run_loop(PLAIN_CONF, False)[0],
            "shard1_sync": _run_loop(SHARD1_CONF, False)[0],
            "shard2_sync": _run_loop(SHARD2_CONF, False)[0],
            "shard2_pipe": _run_loop(SHARD2_CONF, True)[0],
        }
        assert len(set(shas.values())) == 1, shas

    def test_sharded_pallas_loops_match_unsharded_sha(self):
        """ISSUE 14 fast rows: the sharded cycle honoring ``use_pallas``
        (shard-local candidate launch + cross-shard argmax combine) is
        sha-identical to the unsharded scheduler on 1- and 2-device
        meshes, with the steady delta cycles still paying zero
        resharding copies."""
        plain = _run_loop(PLAIN_CONF, False)[0]
        shas = {
            "shard1_pl_sync": _run_loop(_pl_conf(1), False)[0],
        }
        sha2, sched2 = _run_loop(_pl_conf(2), False)
        shas["shard2_pl_sync"] = sha2
        assert set(shas.values()) == {plain}, (plain, shas)
        deltas = [e for e in sched2.flight.snapshots()
                  if e.get("cycle_kind") == "delta"]
        assert deltas and all(e["resharding_copies"] == 0 for e in deltas)

    @pytest.mark.slow
    @pytest.mark.skipif(len(jax.devices()) < 8,
                        reason="needs the 8-device virtual mesh")
    def test_sharded_pallas_wide_mesh_matches_sha(self):
        """ISSUE 14 slow tail: the 8-device shard-local launch and the
        pipelined 2-device row stay in the same sha class."""
        shas = {
            "plain_sync": _run_loop(PLAIN_CONF, False)[0],
            "shard8_pl_sync": _run_loop(_pl_conf(8), False)[0],
            "shard2_pl_pipe": _run_loop(_pl_conf(2), True)[0],
        }
        assert len(set(shas.values())) == 1, shas

    def test_steady_cycles_record_zero_resharding_copies(self):
        """Every steady delta cycle runs on the declared mesh with the
        live transfer probe reading zero — the out==in zero-copy
        contract, recorded in the flight ring bench consumes."""
        _sha, sched = _run_loop(SHARD2_CONF, False)
        flight = sched.flight.snapshots()
        deltas = [e for e in flight if e.get("cycle_kind") == "delta"]
        assert deltas, [e.get("cycle_kind") for e in flight]
        assert all(e["mesh_devices"] == 2 for e in deltas), flight
        assert all(e["resharding_copies"] == 0 for e in deltas), flight

    def test_sharding_requires_delta_uploads(self):
        """``sharding: true`` with delta uploads off is documented as
        ignored — the loop must still run (unsharded) and match."""
        conf = parse_conf("sharding: true\ndelta_uploads: false\n" + _BODY)
        sha, sched = _run_loop(conf, False)
        assert sha == _run_loop(PLAIN_CONF, False)[0]
        assert all(e.get("mesh_devices") is None
                   for e in sched.flight.snapshots())


def _kernel_pair():
    """A 2-device ShardedDeltaKernel + unsharded DeltaKernel oracle over
    the same small real snapshot."""
    from volcano_tpu.analysis.entrypoints import _snap_extras
    snap, extras = _snap_extras((30, 6, 2))
    cfg = dataclasses.replace(
        derive_batching(AllocateConfig(binpack_weight=1.0, enable_gpu=False),
                        has_proportion=False), use_pallas=False)
    cycle = make_allocate_cycle(cfg)
    tree = (snap, extras)
    mesh = mesh_for_nodes(
        int(np.asarray(snap.nodes.valid).shape[0]), 2)
    sharded = ShardedDeltaKernel(cycle, tree, mesh, node_leaf_mask(tree),
                                 entry="fused_cycle_sharded_test")
    return sharded, DeltaKernel(cycle, tree), tree, snap


class TestShardedDeltaScatter:
    def test_cross_shard_scatter_reproduces_full_fuse(self):
        """Mutations landing in BOTH shards (plus a replicated rest
        leaf): the routed scatter must leave the device node buffers
        bit-identical to a fresh host fuse of the mutated tree."""
        kernel, oracle, tree, snap = _kernel_pair()
        state = ResidentState()
        kernel.run(state, tree)                       # cold full upload
        idle = np.asarray(snap.nodes.idle)
        half = kernel.rows_per
        idle[0] = idle[0] * 0.5                       # shard 0
        idle[half] = idle[half] * 0.25                # shard 1
        idle[-1] = idle[-1] + 1.0                     # last row, shard 1
        prio = np.asarray(snap.tasks.priority)        # rest (replicated)
        prio[3] = prio[3] + 2
        packed = np.asarray(kernel.run(state, tree))
        assert state.last_kind == "delta"
        fresh = kernel._fuse_sharded(tree)
        for i, (dev, want) in enumerate(zip(state.device, fresh)):
            np.testing.assert_array_equal(np.asarray(dev), want,
                                          err_msg=f"resident {i}")
        # and the decisions equal the unsharded kernel on the same tree
        ref = np.asarray(oracle.run(ResidentState(), tree))
        dec, _ = kernel.split_digest(packed)
        ref_dec, _ = oracle.split_digest(ref)
        np.testing.assert_array_equal(dec, ref_dec)
        idle[0] = idle[0] * 2.0
        idle[half] = idle[half] * 4.0
        idle[-1] = idle[-1] - 1.0
        prio[3] = prio[3] - 2                         # restore shared snap

    def test_empty_shard_padding_is_decision_neutral(self):
        """A delta touching only ONE shard: the other shard receives pure
        padding rows, which must scatter to nothing."""
        kernel, _oracle, tree, snap = _kernel_pair()
        state = ResidentState()
        kernel.run(state, tree)
        idle = np.asarray(snap.nodes.idle)
        idle[1] = idle[1] * 0.5                       # shard 0 only
        kernel.run(state, tree)
        assert state.last_kind == "delta"
        fresh = kernel._fuse_sharded(tree)
        for dev, want in zip(state.device, fresh):
            np.testing.assert_array_equal(np.asarray(dev), want)
        idle[1] = idle[1] * 2.0


class TestPerShardDigestRecovery:
    def test_corrupt_shard_flips_exactly_its_digest_word(self):
        """Corrupt one row of the f32 node mirror inside shard 1: only
        that shard's f-group digest word may change — the per-shard
        digest localizes corruption without any gather."""
        kernel, _oracle, tree, _snap = _kernel_pair()
        state = ResidentState()
        packed = np.asarray(kernel.run(state, tree))
        _dec, device_tail = kernel.split_digest(packed)
        before = kernel.mirror_digest(state)
        np.testing.assert_array_equal(before, device_tail)
        # post-dispatch corruption: the mirror drifts from device truth
        state.mirror[0][kernel.rows_per + 1, 0] += 3.0
        after = kernel.mirror_digest(state)
        diff = np.nonzero(before != after)[0]
        np.testing.assert_array_equal(diff, [1])      # f-group, shard 1
        assert not np.array_equal(after, device_tail)

    def test_recover_restores_digest_and_decisions(self):
        kernel, oracle, tree, _snap = _kernel_pair()
        state = ResidentState()
        packed0 = np.asarray(kernel.run(state, tree))
        state.mirror[1][0, 0] += 7                    # i-group, shard 0
        _dec0, tail0 = kernel.split_digest(packed0)
        assert not np.array_equal(kernel.mirror_digest(state), tail0)
        packed = np.asarray(kernel.recover(state, tree))
        assert state.last_kind == "recovery"
        dec, tail = kernel.split_digest(packed)
        np.testing.assert_array_equal(kernel.mirror_digest(state), tail)
        ref_dec, _ = oracle.split_digest(
            np.asarray(oracle.run(ResidentState(), tree)))
        np.testing.assert_array_equal(dec, ref_dec)
