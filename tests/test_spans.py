"""Cycle timeline profiler (ISSUE 8): host span tracing, pipeline
occupancy, the Chrome trace export, and the structured event log.

The load-bearing contract first: spans are HOST-ONLY, so scheduler
decisions must be bit-identical with tracing on and off — pinned here on
the sync, pipelined, and sharded 2-device loops. Then the observability
surfaces themselves: occupancy math on synthetic spans with known
overlap (including the wait-subtraction that keeps a blocked readback
from masquerading as useful overlap), trace-event JSON schema, latency
ring quantiles, and event-log emission on a planted digest trip.
"""

from __future__ import annotations

import json

import jax
import pytest

from volcano_tpu.framework import parse_conf
from volcano_tpu.runtime.fake_cluster import FakeCluster
from volcano_tpu.runtime.scheduler import Scheduler
from volcano_tpu.telemetry import spans

from test_delta_pipeline import decisions_sha, digest
from test_runtime_incremental import build_cluster, churn

_BODY = """
actions: "allocate"
tiers:
- plugins:
  - name: gang
  - name: binpack
"""
PLAIN_CONF = parse_conf(_BODY)
SHARD2_CONF = parse_conf("sharding: true\nsharding_devices: 2\n" + _BODY)


@pytest.fixture(autouse=True)
def _clean_spans():
    spans.reset()
    spans.set_enabled(True)
    yield
    spans.set_enabled(True)
    spans.reset()


def _run_loop(conf, pipeline, cycles=4):
    cluster = FakeCluster(build_cluster(n_nodes=8, n_jobs=6).clone())
    sched = Scheduler(cluster, conf=conf, incremental=True,
                      pipeline=pipeline)
    digests = []
    for c in range(cycles):
        out = sched.run_once(now=1000.0 + c)
        rec = (sched.drain(now=1000.0 + c) or out) if pipeline else out
        digests.append(digest(rec))
        churn(cluster, c, arrivals=True)
    return decisions_sha(digests), sched


class TestDecisionIdentity:
    """Tracing on vs off: the decision sha must not move — spans wrap
    host code only, never a traced function."""

    def test_sync_loop_identical_on_off(self):
        on, _ = _run_loop(PLAIN_CONF, pipeline=False)
        spans.reset()
        prev = spans.set_enabled(False)
        try:
            off, _ = _run_loop(PLAIN_CONF, pipeline=False)
        finally:
            spans.set_enabled(prev)
        assert on == off

    def test_pipelined_loop_identical_on_off(self):
        on, _ = _run_loop(PLAIN_CONF, pipeline=True)
        spans.reset()
        prev = spans.set_enabled(False)
        try:
            off, _ = _run_loop(PLAIN_CONF, pipeline=True)
        finally:
            spans.set_enabled(prev)
        assert on == off

    @pytest.mark.slow  # GSPMD compile dominates; tier-1 budget (PR 1/3/5
    # pattern) — the sync + pipelined identity rows above stay tier-1
    @pytest.mark.skipif(len(jax.devices()) < 2,
                        reason="needs the multi-device virtual mesh")
    def test_sharded_pipelined_loop_identical_on_off(self):
        on, _ = _run_loop(SHARD2_CONF, pipeline=True)
        spans.reset()
        prev = spans.set_enabled(False)
        try:
            off, _ = _run_loop(SHARD2_CONF, pipeline=True)
        finally:
            spans.set_enabled(prev)
        assert on == off

    def test_disabled_records_nothing(self):
        prev = spans.set_enabled(False)
        try:
            with spans.span("x"):
                pass
            spans.device_window(0.0, 1.0)
            spans.log_event("digest_trip")
        finally:
            spans.set_enabled(prev)
        assert spans.phase_stats() == {}
        assert spans.events() == []


class TestOccupancyMath:
    """compute_occupancy on synthetic spans with hand-checked overlap."""

    @staticmethod
    def _ev(name, cat, ts, dur, **kw):
        return dict(name=name, cat=cat, ts=ts, dur=dur, tid=1, **kw)

    def test_known_overlap(self):
        # window [0, 10); host work [2, 5) and [8, 12) -> 3 + 2 = 5s in
        evts = [
            self._ev("device_window", "device", 0.0, 10.0, shards=1),
            self._ev("ingest", "ingest", 2.0, 3.0),
            self._ev("open", "host", 8.0, 4.0),
        ]
        occ = spans.compute_occupancy(evts)
        assert occ["windows"] == 1
        assert occ["window_ms"] == 10000.0
        assert occ["overlap_ms"] == 5000.0
        assert occ["bubble_ms"] == 5000.0
        assert occ["pipeline_overlap_fraction"] == 0.5

    def test_wait_subtraction_and_nesting(self):
        # an OUTER host span covering the whole window would naively give
        # overlap 1.0; the inner wait (blocked readback) must be carved
        # out, and the nested inner host span must not double-count
        evts = [
            self._ev("device_window", "device", 0.0, 10.0),
            self._ev("cycle", "host", 0.0, 10.0),     # outer
            self._ev("apply", "host", 1.0, 2.0),      # nested in outer
            self._ev("readback", "wait", 4.0, 6.0),   # blocked 4..10
        ]
        occ = spans.compute_occupancy(evts)
        assert occ["overlap_ms"] == 4000.0            # [0,4) only
        assert occ["pipeline_overlap_fraction"] == 0.4

    def test_all_wait_window_is_zero(self):
        # the synchronous loop: window interior fully blocked -> ~0
        evts = [
            self._ev("device_window", "device", 0.0, 5.0),
            self._ev("cycle", "host", 0.0, 5.0),
            self._ev("readback", "wait", 0.0, 5.0),
        ]
        occ = spans.compute_occupancy(evts)
        assert occ["overlap_ms"] == 0.0
        assert occ["pipeline_overlap_fraction"] == 0.0

    def test_per_shard_views(self):
        # one common GSPMD window over 2 shards plus a shard-1-only
        # window: shard 0 sees 1 window, shard 1 sees 2
        evts = [
            self._ev("device_window", "device", 0.0, 4.0,
                     shard=None, shards=2),
            self._ev("device_window", "device", 6.0, 2.0,
                     shard=1, shards=2),
            self._ev("ingest", "ingest", 0.0, 2.0),
            self._ev("ingest", "ingest", 6.0, 1.0),
        ]
        occ = spans.compute_occupancy(evts)
        per = occ["per_shard"]
        assert set(per) == {"1"}  # explicit shard ids win
        assert per["1"]["windows"] == 2
        assert per["1"]["overlap_ms"] == 3000.0
        # shards=2 with no explicit ids -> synthesized per-shard views
        occ2 = spans.compute_occupancy(evts[:1] + evts[2:3])
        assert set(occ2["per_shard"]) == {"0", "1"}
        assert occ2["per_shard"]["0"] == occ2["per_shard"]["1"]

    def test_live_rings_feed_occupancy(self):
        with spans.span("work"):
            pass
        spans.device_window(0.0, spans.now() + 1.0)
        occ = spans.occupancy()
        assert occ["windows"] == 1
        assert occ["pipeline_overlap_fraction"] is not None


class TestTraceExport:
    def test_chrome_trace_schema(self, tmp_path):
        with spans.span("outer"):
            with spans.span("inner", cat="dispatch", detail=7):
                pass
        spans.device_window(0.0, 0.001)
        spans.log_event("digest_trip", source="test")
        path = tmp_path / "trace.json"
        trace = spans.export_chrome_trace(str(path))
        on_disk = json.loads(path.read_text())
        assert on_disk["displayTimeUnit"] == "ms"
        evts = on_disk["traceEvents"]
        assert evts == trace["traceEvents"]
        complete = [e for e in evts if e["ph"] == "X"]
        assert {e["name"] for e in complete} >= {"outer", "inner",
                                                "device_window"}
        for e in complete:
            assert {"name", "cat", "ph", "ts", "dur", "pid",
                    "tid"} <= set(e)
            assert e["ts"] >= 0 and e["dur"] >= 0
        inner = next(e for e in complete if e["name"] == "inner")
        assert inner["args"] == {"detail": 7}
        # metadata names for host threads AND the device track
        meta = [e for e in evts if e["ph"] == "M"
                and e["name"] == "thread_name"]
        assert any(m["args"]["name"] == "device" for m in meta)
        # the planted event rides along as an instant on track 0
        assert any(e["ph"] == "i" and e["name"] == "digest_trip"
                   for e in evts)

    def test_merge_appends_foreign_events(self):
        with spans.span("mine"):
            pass
        foreign = {"traceEvents": [{"name": "theirs", "ph": "X", "ts": 0,
                                    "dur": 1, "pid": 9, "tid": 9,
                                    "cat": "device"}]}
        trace = spans.export_chrome_trace(merge=foreign)
        names = {e["name"] for e in trace["traceEvents"]}
        assert {"mine", "theirs"} <= names

    def test_phase_stats_quantiles(self):
        for _ in range(10):
            with spans.span("pack"):
                pass
        st = spans.phase_stats()["pack"]
        assert st["count"] == 10
        assert 0 <= st["p50"] <= st["p95"] <= st["p99"]
        assert st["total_ms"] >= st["last"] >= 0

    def test_cycle_summary_drains(self):
        with spans.span("pack"):
            pass
        acc = spans.drain_cycle_summary()
        assert acc is not None and "pack" in acc
        assert spans.drain_cycle_summary() is None  # drained


class TestEventLog:
    @pytest.mark.slow  # full chaos probe (~6 s compile); tier1.sh's chaos
    # smoke exercises the same storm with the event log live
    def test_digest_trip_emits_event(self):
        """The chaos probe's planted resident-state corruption must land
        a digest_trip (and a recovery) in the structured event log."""
        from volcano_tpu.chaos import run_chaos_probe
        rpt = run_chaos_probe(seed=7, cycles=6)
        assert rpt["digest_mismatches"] >= 1  # the probe planted one
        kinds = [e["kind"] for e in spans.events()]
        assert "digest_trip" in kinds
        assert "recovery" in kinds
        trip = next(e for e in spans.events()
                    if e["kind"] == "digest_trip")
        assert trip["source"] in ("session", "sidecar")
        assert trip["ts_ms"] >= 0 and trip["wall_ts"] > 0

    def test_event_log_jsonl_export(self, tmp_path):
        spans.log_event("degradation", level_from=0, level_to=1)
        spans.log_event("recovery", mode="refuse")
        path = tmp_path / "events.jsonl"
        n = spans.export_event_log(str(path))
        lines = [json.loads(ln) for ln in
                 path.read_text().splitlines() if ln]
        assert n == len(lines) == 2
        assert lines[0]["kind"] == "degradation"
        assert lines[0]["level_to"] == 1

    def test_write_through_env(self, tmp_path, monkeypatch):
        path = tmp_path / "wt.jsonl"
        monkeypatch.setenv("VOLCANO_EVENT_LOG", str(path))
        spans.log_event("digest_trip", source="test")
        entry = json.loads(path.read_text().splitlines()[0])
        assert entry["kind"] == "digest_trip"


class TestSchedulerWiring:
    def test_flight_entries_carry_span_summary(self):
        _sha, sched = _run_loop(PLAIN_CONF, pipeline=True)
        entries = sched.flight.snapshots()
        summed = [e for e in entries if e.get("spans")]
        assert summed, entries
        assert any("session.dispatch" in e["spans"] for e in summed)
        json.dumps(entries)  # JSON-clean with the summary attached

    def test_metrics_gauges_published(self):
        from volcano_tpu.metrics import METRICS
        METRICS.reset()
        _run_loop(PLAIN_CONF, pipeline=False)
        text = METRICS.exposition()
        assert "volcano_span_phase_ms{" in text
        assert 'phase="session.dispatch"' in text

    def test_dashboard_tables_and_trace_route(self):
        import urllib.request
        _sha, sched = _run_loop(PLAIN_CONF, pipeline=True)

        class _Api:          # empty stores: only the telemetry/latency
            def list(self, kind):  # tables matter to this test
                return []

        class _Sys:
            scheduler = sched
            api = _Api()
        from volcano_tpu.runtime.dashboard import Dashboard, build_page
        page = build_page(_Sys())
        assert "latency" in page.tables
        assert page.tables["latency"]["rows"]
        tel = page.tables["telemetry"]
        assert tel["headers"][-3:] == ["Mesh", "Reshard", "Degr"]
        assert all(len(r) == len(tel["headers"]) for r in tel["rows"])
        dash = Dashboard(_Sys())
        port = dash.serve(port=0)
        try:
            body = json.loads(urllib.request.urlopen(
                f"http://127.0.0.1:{port}/api/trace").read())
            assert body["traceEvents"]
        finally:
            dash.shutdown()
