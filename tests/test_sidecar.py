"""Sidecar service tests: snapshot-in/decisions-out over a real socket
(SURVEY.md section 5.8 distributed backbone), decisions identical to an
in-process cycle."""

import numpy as np
import jax
import pytest

from volcano_tpu import native
from volcano_tpu.arrays import pack
from volcano_tpu.ops import AllocateConfig, make_allocate_cycle
from volcano_tpu.ops.allocate_scan import AllocateExtras
from volcano_tpu.runtime.sidecar import SidecarClient, SidecarServer

from fixtures import build_job, build_task, simple_cluster

pytestmark = pytest.mark.skipif(
    not native.available(),
    reason=f"native packer unavailable: {native.build_error()}")


def cluster():
    ci = simple_cluster(n_nodes=3)
    for j in range(3):
        job = build_job(f"default/j{j}", min_available=2)
        for t in range(2):
            job.add_task(build_task(f"j{j}-t{t}", cpu="1", memory="1Gi"))
        ci.add_job(job)
    return ci


class TestSidecar:
    def test_round_trip_matches_local(self):
        server = SidecarServer()
        server.serve_in_thread()
        try:
            client = SidecarClient(*server.address)
            ci = cluster()
            out = client.schedule(ci)
            # local oracle on the same snapshot
            snap, maps = pack(ci)
            local = jax.jit(make_allocate_cycle(
                AllocateConfig(binpack_weight=1.0)))(
                    snap, AllocateExtras.neutral(snap))
            np.testing.assert_array_equal(out["task_node"],
                                          np.asarray(local.task_node))
            np.testing.assert_array_equal(out["task_mode"],
                                          np.asarray(local.task_mode))
            assert len(out["binds"]) == 6
            assert all(node.startswith("n") for node, _ in
                       out["binds"].values())
            client.close()
        finally:
            server.shutdown()

    def test_multiple_cycles_one_connection(self):
        server = SidecarServer()
        server.serve_in_thread()
        try:
            client = SidecarClient(*server.address)
            first = client.schedule(cluster())
            second = client.schedule(cluster())
            np.testing.assert_array_equal(first["task_node"],
                                          second["task_node"])
            client.close()
        finally:
            server.shutdown()

    def test_pipelined_rounds_match_sync_shifted_by_one(self):
        """VCRP serving: response k carries round k-1's decisions and the
        stream (prime, rounds, drain) reproduces the synchronous responses
        exactly, one round late. Exercises the device-resident delta path
        under evolving snapshots."""
        server = SidecarServer()
        server.serve_in_thread()
        try:
            sync_client = SidecarClient(*server.address)
            pipe_client = SidecarClient(*server.address)
            # evolving snapshots: each round binds one more task up front
            cis = []
            for k in range(3):
                ci = cluster()
                names = sorted(ci.nodes)
                bound = 0
                for job in ci.jobs.values():
                    for task in job.tasks.values():
                        if bound >= k:
                            break
                        from volcano_tpu.api import TaskStatus
                        job.update_task_status(task, TaskStatus.RUNNING)
                        task.node_name = names[bound % len(names)]
                        ci.nodes[task.node_name].add_task(task)
                        bound += 1
                cis.append(ci)
            sync_outs = [sync_client.schedule(ci) for ci in cis]
            assert pipe_client.schedule_pipelined(cis[0]) is None  # prime
            pipe_outs = [pipe_client.schedule_pipelined(ci)
                         for ci in cis[1:]]
            pipe_outs.append(pipe_client.drain_pipelined())
            for k, (s, p) in enumerate(zip(sync_outs, pipe_outs)):
                np.testing.assert_array_equal(s["task_node"],
                                              p["task_node"], f"round {k}")
                np.testing.assert_array_equal(s["task_mode"],
                                              p["task_mode"], f"round {k}")
                assert s["binds"] == p["binds"], f"round {k}"
            assert pipe_client.drain_pipelined() is None
            sync_client.close()
            pipe_client.close()
        finally:
            server.shutdown()

    def test_error_reply_keeps_connection(self):
        import socket, struct
        server = SidecarServer()
        server.serve_in_thread()
        try:
            sock = socket.create_connection(server.address, timeout=30)
            garbage = b"nonsense"
            sock.sendall(struct.pack("<II", len(garbage), 0) + garbage)
            status = struct.unpack("<I", sock.recv(4))[0]
            assert status == 1
            n = struct.unpack("<I", sock.recv(4))[0]
            sock.recv(n)
            # connection still usable for a real request
            client = SidecarClient(*server.address)
            out = client.schedule(cluster())
            assert len(out["binds"]) == 6
            client.close()
            sock.close()
        finally:
            server.shutdown()


class TestSidecarShardedPallas:
    """ISSUE 14: a conf-mode sidecar serving ``sharding: true`` +
    ``use_pallas: interpret`` — the served sharded cycle runs the
    shard-local candidate launch and must stay decision-identical to
    the plain served conf, across the cold fuse AND a warm delta
    cycle."""

    _BODY = """
actions: "enqueue, allocate"
tiers:
- plugins:
  - name: gang
  - name: binpack
"""

    @pytest.mark.skipif(len(jax.devices()) < 2,
                        reason="needs the multi-device virtual mesh")
    def test_sharded_pallas_conf_matches_plain_served(self):
        plain_srv = SidecarServer(conf=self._BODY)
        shard_srv = SidecarServer(
            conf="sharding: true\nsharding_devices: 2\n"
                 "use_pallas: interpret\n" + self._BODY)
        plain_srv.serve_in_thread()
        shard_srv.serve_in_thread()
        try:
            plain = SidecarClient(*plain_srv.address)
            shard = SidecarClient(*shard_srv.address)
            assert shard_srv.sidecar.sharding
            for k in range(2):      # cycle 0 cold-fuses, cycle 1 deltas
                ci_a, ci_b = cluster(), cluster()
                out_p = plain.schedule(ci_a)
                out_s = shard.schedule(ci_b)
                np.testing.assert_array_equal(
                    out_p["task_node"], out_s["task_node"], f"cycle {k}")
                np.testing.assert_array_equal(
                    out_p["task_mode"], out_s["task_mode"], f"cycle {k}")
                assert out_p["binds"] == out_s["binds"], f"cycle {k}"
            plain.close()
            shard.close()
        finally:
            plain_srv.shutdown()
            shard_srv.shutdown()


@pytest.mark.slow
class TestSidecarHDRF:
    def test_wire_carries_hierarchy_tree(self):
        """A conf-mode sidecar serving an hdrf policy rebuilds the exact
        hierarchy tree from the VCS3 queue annotations and reproduces the
        reference's rescaling split (drf/hdrf_test.go:68-118) over the
        wire."""
        import numpy as np
        from test_hdrf import _hdrf_cluster
        from volcano_tpu.runtime.sidecar import SidecarClient, SidecarServer
        ci = _hdrf_cluster(
            "10", str(10 * 2 ** 30),
            [("root-sci", "root/sci", "100/50"),
             ("root-eng-dev", "root/eng/dev", "100/50/50"),
             ("root-eng-prod", "root/eng/prod", "100/50/50")],
            [("pg1", "root-sci", 10, "1", 2 ** 30),
             ("pg21", "root-eng-dev", 10, "1", 0),
             ("pg22", "root-eng-prod", 10, "0", 2 ** 30)])
        conf = """
actions: "allocate"
tiers:
- plugins:
  - name: drf
    enableHierarchy: true
"""
        server = SidecarServer(conf=conf)
        server.serve_in_thread()
        try:
            client = SidecarClient(*server.address)
            out = client.schedule(ci)
            client.close()
        finally:
            server.shutdown()
        placed = {}
        maps = out["maps"]
        for uid, ti in maps.task_index.items():
            job = uid.split("/")[-1].rsplit("-", 1)[0]
            if out["task_mode"][ti] != 0:
                placed[job] = placed.get(job, 0) + 1
        assert placed == {"pg1": 5, "pg21": 5, "pg22": 5}, placed


class TestWireFidelity:
    """VERDICT r4 #5: the served path must make bit-identical decisions to
    the in-process Session on workloads whose semantics ride host-computed
    extras — multi-term OR node affinity, matchExpressions, preferred
    terms, host ports, and volume pins — shipped in the VCX1 frame."""

    CONF = """
actions: "allocate"
tiers:
- plugins:
  - name: gang
  - name: predicates
  - name: nodeorder
    arguments:
      nodeaffinity.weight: 2
  - name: binpack
"""

    def fidelity_cluster(self):
        from volcano_tpu.api import NodeSelectorTerm, PodGroupPhase
        from volcano_tpu.api.cluster_info import PersistentVolumeClaim
        ci = simple_cluster(n_nodes=6, node_cpu="8", node_mem="16Gi")
        zones = ["a", "a", "b", "b", "c", "c"]
        for i, name in enumerate(sorted(ci.nodes)):
            ci.nodes[name].labels["zone"] = zones[i]
            ci.nodes[name].labels["cores"] = str(2 ** i)
        expr = lambda k, op, v: NodeSelectorTerm(  # noqa: E731
            match_expressions=[(k, op, tuple(v))])
        ci.pvcs["claim-a"] = PersistentVolumeClaim(
            "claim-a", bindable=True, node_name=sorted(ci.nodes)[3])
        ci.pvcs["claim-bad"] = PersistentVolumeClaim(
            "claim-bad", bindable=False)
        shapes = [
            dict(required=[expr("cores", "Gt", ["4"])]),
            dict(required=[expr("zone", "In", ["a"]),
                           expr("zone", "In", ["c"])]),   # OR of terms
            dict(required=[expr("zone", "NotIn", ["a", "b"])]),
            dict(preferred=[(expr("cores", "Gt", ["8"]), 3.0)]),
            dict(ports=[8080]),
            dict(pvcs=["claim-a"]),
            dict(pvcs=["claim-bad"]),
            dict(),
        ]
        for j, shape in enumerate(shapes):
            job = build_job(f"default/w{j}", min_available=1,
                            creation_timestamp=float(j))
            job.pod_group_phase = PodGroupPhase.INQUEUE
            for t in range(2):
                task = build_task(f"w{j}-t{t}", cpu="1", memory="1Gi")
                task.affinity_required = list(shape.get("required", []))
                task.affinity_preferred = list(shape.get("preferred", []))
                task.host_ports = list(shape.get("ports", []))
                task.pvcs = list(shape.get("pvcs", []))
                job.add_task(task)
            ci.add_job(job)
        return ci

    def test_sidecar_matches_session_on_extras_workload(self):
        from volcano_tpu.framework import parse_conf
        from volcano_tpu.framework.session import Session
        ci = self.fidelity_cluster()
        ssn = Session(ci.clone(), parse_conf(self.CONF))
        ssn.run_allocate()
        want_binds = {b.task_uid: (b.node_name, b.gpu_index)
                      for b in ssn.binds}
        want_pipelined = dict(ssn.pipelined)
        # claim-bad blocks its job everywhere; claim-a pins to node 3
        assert all(not u.startswith("default/w6")
                   for u in list(want_binds) + list(want_pipelined))
        assert any(u.startswith("default/w5") for u in want_binds)

        server = SidecarServer(conf=self.CONF)
        server.serve_in_thread()
        try:
            client = SidecarClient(*server.address, conf=self.CONF)
            out = client.schedule(ci.clone())
            got_binds = {u: (n, g) for u, (n, g) in out["binds"].items()}
            assert got_binds == want_binds
            client.close()
        finally:
            server.shutdown()

    def test_confless_client_is_permissive_no_more(self):
        """A client WITHOUT the conf ships no extras — document that the
        fidelity contract requires the conf on both ends: with it, the
        expression-constrained job lands only on matching nodes."""
        from volcano_tpu.framework import parse_conf
        from volcano_tpu.framework.session import Session
        ci = self.fidelity_cluster()
        ssn = Session(ci.clone(), parse_conf(self.CONF))
        ssn.run_allocate()
        constrained = {u: n for u, (n, _g) in
                       {b.task_uid: (b.node_name, b.gpu_index)
                        for b in ssn.binds}.items()
                       if u.startswith("default/w2")}
        # zone NotIn {a,b} -> only the two zone-c nodes are legal
        names = sorted(ci.nodes)
        legal = {names[4], names[5]}
        assert constrained and set(constrained.values()) <= legal
