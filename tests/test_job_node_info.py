"""JobInfo/NodeInfo accounting tests — mirrors pkg/scheduler/api/job_info_test.go
and node_info_test.go assertions (status index, gang readiness, node buckets)."""

from volcano_tpu.api import JobInfo, NodeInfo, TaskStatus

from fixtures import build_job, build_node, build_task, res


class TestJobInfo:
    def test_status_index_and_ready(self):
        job = build_job("default/j1", min_available=2)
        t1 = build_task("p1", status=TaskStatus.RUNNING)
        t2 = build_task("p2", status=TaskStatus.PENDING)
        job.add_task(t1)
        job.add_task(t2)
        assert job.ready_task_num() == 1
        assert not job.is_ready()
        job.update_task_status(t2, TaskStatus.ALLOCATED)
        assert job.ready_task_num() == 2
        assert job.is_ready()

    def test_allocated_tracking(self):
        job = build_job("default/j1")
        t = build_task("p1", cpu="2", status=TaskStatus.PENDING)
        job.add_task(t)
        assert job.allocated.milli_cpu == 0
        job.update_task_status(t, TaskStatus.ALLOCATED)
        assert job.allocated.milli_cpu == 2000
        job.update_task_status(t, TaskStatus.PENDING)
        assert job.allocated.milli_cpu == 0

    def test_pipelined_and_starving(self):
        job = build_job("default/j1", min_available=2)
        t1 = build_task("p1", status=TaskStatus.RUNNING)
        t2 = build_task("p2", status=TaskStatus.PENDING)
        job.add_task(t1)
        job.add_task(t2)
        assert job.is_starving()
        job.update_task_status(t2, TaskStatus.PIPELINED)
        assert job.is_pipelined()
        assert not job.is_starving()

    def test_valid_min_available(self):
        job = build_job("default/j1", min_available=3)
        for i in range(2):
            job.add_task(build_task(f"p{i}"))
        ok, reason = job.is_valid()
        assert not ok and "minAvailable" in reason

    def test_task_min_available_per_role(self):
        job = build_job("default/j1", min_available=2,
                        task_min_available={"ps": 1, "worker": 2})
        job.add_task(build_task("ps-0", role="ps"))
        job.add_task(build_task("w-0", role="worker"))
        assert not job.check_task_min_available()
        job.add_task(build_task("w-1", role="worker"))
        assert job.check_task_min_available()

    def test_clone_is_deep(self):
        job = build_job("default/j1")
        t = build_task("p1")
        job.add_task(t)
        c = job.clone()
        c.update_task_status(c.tasks[t.uid], TaskStatus.ALLOCATED)
        assert job.tasks[t.uid].status == TaskStatus.PENDING


class TestNodeInfo:
    def test_add_remove_task(self):
        node = build_node("n1", cpu="4", memory="8Gi")
        t = build_task("p1", cpu="1", memory="1Gi", status=TaskStatus.RUNNING)
        node.add_task(t)
        assert node.idle.milli_cpu == 3000
        assert node.used.milli_cpu == 1000
        node.remove_task(t)
        assert node.idle.milli_cpu == 4000
        assert node.used.milli_cpu == 0

    def test_releasing_and_future_idle(self):
        node = build_node("n1", cpu="4", memory="8Gi")
        releasing = build_task("p1", cpu="2", status=TaskStatus.RELEASING)
        pipelined = build_task("p2", cpu="1", status=TaskStatus.PIPELINED)
        node.add_task(releasing)
        node.add_task(pipelined)
        # idle = 2, releasing = 2, pipelined = 1 -> future idle = 3
        assert node.idle.milli_cpu == 2000
        assert node.future_idle().milli_cpu == 3000

    def test_pipelined_does_not_consume_idle(self):
        node = build_node("n1", cpu="4")
        node.add_task(build_task("p1", cpu="4", status=TaskStatus.PIPELINED))
        assert node.idle.milli_cpu == 4000
