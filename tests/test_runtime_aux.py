"""Leader election, dashboard, and standalone v* CLI binaries.

Reference seams: client-go leaderelection (cmd/scheduler/app/server.go:
100-148), cmd/dashboard/app/server.go:59-233, cmd/cli/v* entrypoints.
"""

import json
import urllib.request

from volcano_tpu.cli import vbin
from volcano_tpu.runtime.dashboard import Dashboard, build_page, render_html
from volcano_tpu.runtime.leader import Lease, LeaderElector
from volcano_tpu.runtime.system import VolcanoSystem


def _system_with_job(tmp_path):
    system = VolcanoSystem()
    system.add_node("n0", cpu="8", memory="16Gi")
    manifest = tmp_path / "job.yaml"
    manifest.write_text("""
apiVersion: batch.volcano.sh/v1alpha1
kind: Job
metadata: {name: demo, namespace: default}
spec:
  minAvailable: 2
  tasks:
    - replicas: 2
      name: worker
      template:
        spec:
          containers:
            - name: c
              resources: {requests: {cpu: "1", memory: 1Gi}}
""")
    return system, manifest


# ------------------------------------------------------------ leader election
class FakeClock:
    def __init__(self):
        self.now = 100.0

    def __call__(self):
        return self.now


def test_leader_election_single_winner_and_failover():
    api = VolcanoSystem().api
    clock = FakeClock()
    events = []
    a = LeaderElector(api, identity="a", clock=clock,
                      on_started_leading=lambda: events.append("a+"),
                      on_stopped_leading=lambda: events.append("a-"))
    b = LeaderElector(api, identity="b", clock=clock,
                      on_started_leading=lambda: events.append("b+"))
    assert a.tick() and a.is_leader
    assert not b.tick() and not b.is_leader   # live lease blocks b
    clock.now += 5
    assert a.tick()                            # renew
    # a dies; lease expires after lease_duration since last renew
    clock.now += a.lease_duration + 0.1
    assert b.tick() and b.is_leader            # failover
    lease = api.get("leases", "volcano-system/vc-scheduler")
    assert lease.holder == "b" and lease.transitions == 1
    # a comes back, sees b's live lease, steps down
    clock.now += 1
    assert not a.tick() and not a.is_leader
    assert events == ["a+", "b+", "a-"]


def test_leader_release_hands_over_immediately():
    api = VolcanoSystem().api
    clock = FakeClock()
    a = LeaderElector(api, identity="a", clock=clock)
    b = LeaderElector(api, identity="b", clock=clock)
    assert a.tick()
    a.release()
    assert not a.is_leader
    assert b.tick() and b.is_leader            # no wait for expiry


def test_lease_expiry_math():
    lease = Lease(name="x", holder="a", renew_time=100.0, lease_duration=15.0)
    assert not lease.expired(110.0)
    assert lease.expired(115.0)


# ----------------------------------------------------------------- dashboard
def test_build_page_tables(tmp_path):
    system, manifest = _system_with_job(tmp_path)
    assert vbin.vsub(["-f", str(manifest)], system=system) == 0
    system.tick()
    page = build_page(system)
    assert [r[1] for r in page.tables["jobs"]["rows"]] == ["demo"]
    assert page.tables["jobs"]["headers"][0] == "Namespace"
    assert len(page.tables["pods"]["rows"]) == 2
    assert len(page.tables["nodes"]["rows"]) == 1
    assert page.tables["podgroups"]["rows"][0][4] == 2  # MinMember
    html = render_html(page)
    assert "demo" in html and "<table>" in html


def test_dashboard_page_cache_ttl(tmp_path):
    system, manifest = _system_with_job(tmp_path)
    dash = Dashboard(system, refresh_seconds=10)
    p1 = dash.page(now=1000.0)
    vbin.vsub(["-f", str(manifest)], system=system)
    assert dash.page(now=1005.0) is p1          # cached
    p2 = dash.page(now=1010.0)                  # TTL expired -> rebuilt
    assert p2 is not p1
    assert len(p2.tables["jobs"]["rows"]) == 1


def test_dashboard_http_endpoints(tmp_path):
    system, manifest = _system_with_job(tmp_path)
    vbin.vsub(["-f", str(manifest)], system=system)
    system.tick()
    dash = Dashboard(system)
    port = dash.serve(port=0)
    try:
        base = f"http://127.0.0.1:{port}"
        assert urllib.request.urlopen(f"{base}/healthz").read() == b"ok"
        page = json.loads(urllib.request.urlopen(f"{base}/api/page").read())
        assert page["tables"]["jobs"]["rows"][0][1] == "demo"
        html = urllib.request.urlopen(f"{base}/").read().decode()
        assert "volcano_tpu" in html
        metrics = urllib.request.urlopen(f"{base}/metrics").read().decode()
        assert "volcano" in metrics
        assert urllib.request.urlopen(f"{base}/nope").status == 404
    except urllib.error.HTTPError as e:
        assert e.code == 404
    finally:
        dash.shutdown()


# -------------------------------------------------------------- v* binaries
def test_v_binaries_full_flow(tmp_path, capsys):
    system, manifest = _system_with_job(tmp_path)
    assert vbin.vsub(["-f", str(manifest)], system=system) == 0
    system.tick()
    assert vbin.vjobs([], system=system) == 0
    out = capsys.readouterr().out
    assert "demo" in out and "Running" in out
    assert vbin.vqueues([], system=system) == 0
    assert "default" in capsys.readouterr().out
    assert vbin.vsuspend(["-N", "demo"], system=system) == 0
    system.reconcile()
    job = system.job("demo")
    assert job.status.state.phase.value in ("Aborting", "Aborted")
    system.reconcile()
    assert vbin.vresume(["-N", "demo"], system=system) == 0
    system.reconcile()
    assert vbin.vcancel(["-N", "demo"], system=system) == 0
    assert system.job("demo") is None
    assert vbin.vcancel(["-N", "demo"], system=system) == 1  # already gone


def test_v_binaries_state_file_mode(tmp_path):
    state = tmp_path / "vc.pkl"
    # First call creates the system; no nodes yet, so just reconcile.
    _, manifest = _system_with_job(tmp_path)
    assert vbin.vsub(["--state", str(state), "-f", str(manifest)]) == 0
    assert state.exists()
    assert vbin.vjobs(["--state", str(state)]) == 0


class TestSnapshotNodeGating:
    """Snapshot node filters (cache.go:712-750): NotReady/OutOfSync nodes,
    nodes with in-flight binding tasks, and the dedicated-node label gate."""

    def _system(self, n=2):
        from volcano_tpu.runtime.system import VolcanoSystem
        sys_ = VolcanoSystem()
        for i in range(n):
            sys_.add_node(f"n{i}", cpu="4", memory="8Gi")
        return sys_

    def test_binding_node_skipped(self):
        sys_ = self._system()
        sys_.api.get("nodes", "n0").add_binding_task("default/in-flight")
        ci = sys_.cache.snapshot()
        assert "n0" not in ci.nodes and "n1" in ci.nodes
        sys_.api.get("nodes", "n0").remove_binding_task("default/in-flight")
        assert "n0" in sys_.cache.snapshot().nodes

    def test_out_of_sync_node_skipped(self):
        """A node whose declared allocatable shrinks below its accounted
        pods goes OutOfSync and leaves the pool (setNodeState,
        node_info.go:143-149)."""
        from volcano_tpu.api.core import Pod
        from volcano_tpu.api.resource import Resource
        sys_ = self._system()
        from volcano_tpu.api.core import POD_GROUP_ANNOTATION
        pod = Pod(name="big", resources={"cpu": "4", "memory": "1Gi"},
                  node_name="n0", phase="Running",
                  annotations={POD_GROUP_ANNOTATION: "pg-big"})
        sys_.api.create("pods", pod)
        from volcano_tpu.api.core import PodGroup
        sys_.api.create("podgroups", PodGroup(name="pg-big", min_member=1))
        node = sys_.api.get("nodes", "n0")
        node.allocatable = Resource.from_resource_list(
            {"cpu": "2", "memory": "8Gi"})   # shrank below the running pod
        ci = sys_.cache.snapshot()
        assert "n0" not in ci.nodes and "n1" in ci.nodes

    def test_dedicated_label_gates_pool(self):
        from volcano_tpu.runtime.cache import DEDICATED_NODE_LABEL
        sys_ = self._system(3)
        sys_.api.get("nodes", "n1").labels[DEDICATED_NODE_LABEL] = "true"
        ci = sys_.cache.snapshot()
        assert set(ci.nodes) == {"n1"}

    def test_gpu_index_round_trips_through_store(self):
        """A bound GPU pod's card assignment survives into later snapshots
        (the GPUIndex patch, pod_info.go:154-160)."""
        from volcano_tpu.api import (GPU_MEMORY_RESOURCE, GPU_NUMBER_RESOURCE,
                                     PodGroupPhase)
        from volcano_tpu.api.batch import Job, PodTemplate, TaskSpec
        from volcano_tpu.runtime.system import VolcanoSystem
        sys_ = VolcanoSystem()
        from volcano_tpu.api.node_info import NodeInfo
        from volcano_tpu.api.resource import Resource
        sys_.api.create("nodes", NodeInfo(
            "g0", allocatable=Resource.from_resource_list(
                {"cpu": "8", "memory": "16Gi",
                 GPU_MEMORY_RESOURCE: 16, GPU_NUMBER_RESOURCE: 2})))
        job = Job(name="trainer", min_available=1, tasks=[
            TaskSpec(name="t", replicas=1, template=PodTemplate(
                resources={"cpu": "1", "memory": "1Gi",
                           GPU_MEMORY_RESOURCE: 6}))])
        sys_.submit_job(job)
        for _ in range(2):
            sys_.tick()
        pod = sys_.pods_of("trainer")[0]
        assert pod.gpu_index == 0
        ci = sys_.cache.snapshot()
        node = ci.nodes["g0"]
        assert node.gpu_devices[0].used_memory() == 6
