#!/usr/bin/env python
"""Benchmark: compiled TPU scheduling cycle vs the sequential CPU reference.

Measures the allocate pass (predicates + binpack scoring + gang commit) at
the BASELINE.json north-star scale (10k nodes / 100k pending tasks) and
reports ONE JSON line:

    {"metric": ..., "value": <tpu cycle ms>, "unit": "ms", "vs_baseline": <speedup>}

vs_baseline is the speedup over the CPU path on the same snapshot. The
reference publishes no numbers (BASELINE.md) and no Go toolchain exists in
this image, so the CPU baseline is runtime/cpu_reference.py — the same
sequential predicate->score->argmax loop the Go scheduler runs per task
(allocate.go:43-281), vectorized over the node axis with numpy (at least as
fast as the Go loop's per-node work).  The full-scale CPU run takes ~6.6
minutes, so it was measured once and recorded in BENCH_BASELINE.json (with
TPU decisions verified bit-identical at full scale at measurement time);
every bench run still measures the CPU path live AND re-verifies decision
equality at a 1k-node/10k-task sub-scale, reported in the stderr extras.

Fail-soft contract (VERDICT round 1, item 1): this script exits 0 with one
valid JSON line in EVERY outcome.  The TPU backend is probed in a
subprocess with a hard timeout first (a dead axon tunnel can make backend
init hang, not just raise) and RETRIED with capped backoff (VERDICT r5
item 1 — a transient tunnel blip must not blind a whole round's record);
if the chip stays unreachable the whole measurement re-runs on the CPU
backend at a reduced scale and the record carries "tpu_unavailable": true.
A mid-run TPU failure re-execs into the CPU path in a clean process.

Degrade, never skip (VERDICT r5 item 1): under the CPU fallback the
drf / preempt / affinity configs still run, at sub-scale on the CPU
backend, labeled with an explicit ``*_backend: "cpu_subscale"`` column —
no BENCH record ships all-null config columns because the chip was away.

Env knobs: BENCH_NODES, BENCH_JOBS, BENCH_TASKS_PER_JOB, BENCH_REPS,
BENCH_LIVE_CPU=1 (measure the CPU baseline at full scale instead of using
BENCH_BASELINE.json), BENCH_SKIP_CHECK=1 (skip the sub-scale equality
check), BENCH_FORCE_CPU=1 (skip the TPU probe, run the degraded CPU path),
BENCH_PROBE_TIMEOUT (seconds, default 150), BENCH_PROBE_RETRIES (default
3, backoff 5s doubling capped at 60s), BENCH_SKIP_MULTICHIP=1 (skip the
node-axis sharded-cycle comparison subprocess), BENCH_SKIP_SCENARIOS=1
(skip the scheduling-quality scenario block; BENCH_SCENARIO_CYCLES sets
its horizon, default 16), BENCH_SKIP_RESTART=1 (skip the crash-consistent
checkpoint/restore restart block), BENCH_SKIP_FAILOVER=1 (skip the
warm-standby HA failover block), BENCH_SKIP_MESHLOSS=1 (skip the
elastic-mesh device-loss shrink/regrow block; BENCH_MESHLOSS_TIMEOUT sets
its subprocess cap, default 900s), BENCH_SKIP_FLEET=1 (skip the
multi-tenant fleet serving block; BENCH_FLEET_TENANTS / BENCH_FLEET_CYCLES
size it), BENCH_SKIP_WAVEFRONT=1 (skip the wavefront width sweep;
BENCH_WAVE_NODES / BENCH_WAVE_JOBS size its churn workload).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

_BASELINE_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                              "BENCH_BASELINE.json")


def _tpu_alive(timeout_s: float) -> bool:
    """Probe TPU backend init in a subprocess with a hard timeout.

    A dead axon tunnel makes jax backend init HANG in-process (observed:
    >120s with no exception), so the probe must be a killable child. The
    child runs one tiny computation end-to-end so a half-up backend that
    inits but cannot execute also counts as dead.
    """
    code = ("import jax, jax.numpy as jnp; "
            "print(int(jnp.ones((8, 8)).sum()))")
    try:
        proc = subprocess.run(
            [sys.executable, "-c", code], timeout=timeout_s,
            capture_output=True, text=True)
        out = proc.stdout.strip().splitlines()
        return proc.returncode == 0 and bool(out) and out[-1] == "64"
    except (subprocess.TimeoutExpired, OSError):
        return False


def _reexec_cpu(reason: str) -> "NoReturn":
    """Re-exec this script with the CPU backend forced, in a clean process.

    After a failed axon init, backend state in this process is poisoned;
    a fresh interpreter with jax_platforms=cpu (set before any backend
    initializes, mirroring tests/conftest.py) is the only reliable reset.
    """
    env = dict(os.environ)
    env["BENCH_FORCE_CPU"] = "1"
    env["BENCH_CPU_REASON"] = reason
    # Scale pinned for a TPU run must not carry into the degraded CPU run:
    # the XLA scan at full 10k/100k scale on CPU is unboundedly slow, which
    # would defeat the fail-soft contract. The reduced CPU defaults apply;
    # re-pin explicitly with BENCH_FORCE_CPU=1 to override.
    for k in ("BENCH_NODES", "BENCH_JOBS", "BENCH_TASKS_PER_JOB"):
        env.pop(k, None)
    os.execve(sys.executable, [sys.executable, os.path.abspath(__file__)], env)


def _bench_cfg(cfg_kwargs):
    """The bench's kernel config, routed through derive_batching — the
    single authority for the batching preconditions (graphcheck
    obligations). No drf/hdrf ordering and no proportion plugin here, so
    the derivation lands on the static-keys K-batch path."""
    from volcano_tpu.ops.allocate_scan import AllocateConfig, derive_batching
    return derive_batching(AllocateConfig(**cfg_kwargs),
                           has_proportion=False)


def _build(n_nodes, n_jobs, tasks_per_job, cfg_kwargs):
    from __graft_entry__ import _synthetic_cluster
    from volcano_tpu.arrays import pack
    from volcano_tpu.ops.allocate_scan import AllocateExtras

    ci = _synthetic_cluster(n_nodes=n_nodes, n_jobs=n_jobs,
                            tasks_per_job=tasks_per_job)
    snap, _maps = pack(ci)
    extras = AllocateExtras.neutral(snap)
    return snap, extras, _bench_cfg(cfg_kwargs)


def _decisions_equal(result, cpu) -> bool:
    """Kernel vs CPU-oracle decision equality (task->node and mode)."""
    return bool(
        np.array_equal(np.asarray(result.task_node), cpu["task_node"])
        and np.array_equal(np.asarray(result.task_mode), cpu["task_mode"]))


def _drain(result):
    """Force true completion: fetch the decision outputs to host.

    On the axon TPU platform block_until_ready() can return before the
    computation finishes (observed: 0.5 ms "latency" for a 350 ms cycle), so
    timing must include a host readback of the arrays the scheduler runtime
    actually consumes — which is also exactly what a real cycle pays.
    """
    import jax
    jax.block_until_ready(result)
    for leaf in (result.task_node, result.task_mode, result.task_gpu,
                 result.job_ready, result.job_pipelined):
        np.asarray(leaf)


def _time_device(cycle_fn, snap, extras, reps):
    """Times snapshot-in -> decisions-on-host-out, the full cycle a real
    scheduler pays: host fuse + 3-buffer upload (ops/fused_io; the tunnel
    charges per transfer), compute, ONE packed readback
    (AllocateResult.packed_decisions)."""
    from volcano_tpu.ops.fused_io import make_fused_cycle
    inner = getattr(cycle_fn, "__wrapped__", cycle_fn)
    fn, fuse = make_fused_cycle(inner, (snap, extras))
    t0 = time.time()
    np.asarray(fn(*fuse((snap, extras))))
    compile_s = time.time() - t0
    times = []
    for _ in range(reps):
        t0 = time.time()
        packed = np.asarray(fn(*fuse((snap, extras))))
        times.append(time.time() - t0)
    # full result (for equality checks), outside the timed region
    result = cycle_fn(snap, extras)
    _drain(result)
    return result, min(times) * 1000, compile_s


def _emit_child_stderr(tag, text):
    """Re-emit a child process's captured stderr on the bench's stderr,
    dropping XLA/absl host-backend boilerplate (the CPU-features warning
    class) so the captured bench tail — the parsed-extra JSON line — stays
    machine-readable while real child diagnostics still surface."""
    drop = ("cpu_feature_guard", "oneDNN", "TfrtCpuClient",
            "absl::InitializeLog", "computation_placer",
            "CPU Frequency:", "external/local_xla")
    for line in (text or "").splitlines():
        s = line.strip()
        if s and not any(m in s for m in drop):
            print("bench[%s]: %s" % (tag, s), file=sys.stderr)


def _regression_guard(force_cpu, steady_loop_ms, sub_tpu_ms, quality=None,
                      steady_total_ms=None):
    """Compare this run's steady-loop and sub-scale kernel timings — and,
    when available, the scheduling-quality scorecard numbers (DRF share
    error, node utilization) — against the most recent BENCH_r*.json
    recorded on the SAME backend label (tpu vs cpu — cross-backend ratios
    are meaningless). Returns a fail-soft block with per-metric
    baseline/ratio and a ``regression`` flag (ratio above
    BENCH_REGRESSION_THRESHOLD, default 1.5×), or None when no comparable
    baseline exists. Every ratio is oriented so >1 means WORSE
    (utilization, where lower is worse, is inverted).
    ``steady_cycle_total_p50_ms`` carries its own STRICT limit (ISSUE 13
    acceptance: the depth-k loop must beat the most recent same-backend
    baseline, ratio < 1.0; BENCH_TOTAL_THRESHOLD overrides). Never
    raises, never exits nonzero — the guard annotates the record, the
    trajectory tooling decides what to do about it."""
    import glob
    threshold = float(os.environ.get("BENCH_REGRESSION_THRESHOLD", 1.5))
    total_limit = float(os.environ.get("BENCH_TOTAL_THRESHOLD", 1.0))
    here = os.path.dirname(os.path.abspath(__file__))
    my_label = "cpu" if force_cpu else "tpu"
    quality = quality or {}
    for path in sorted(glob.glob(os.path.join(here, "BENCH_r*.json")),
                       reverse=True):
        try:
            with open(path) as f:
                parsed = (json.load(f) or {}).get("parsed") or {}
        except Exception:
            continue
        label = ("cpu" if parsed.get("tpu_unavailable")
                 or "cpu" in str(parsed.get("device", "")).lower()
                 else "tpu")
        if label != my_label:
            continue
        block = {"baseline": os.path.basename(path), "backend": my_label,
                 "threshold": threshold, "total_threshold": total_limit,
                 "regression": False}
        found = False
        for key, cur, invert, limit in (
                ("steady_loop_ms", steady_loop_ms, False, None),
                ("sub_tpu_ms", sub_tpu_ms, False, None),
                # strict: ratio must land BELOW the limit, not at it
                ("steady_cycle_total_p50_ms", steady_total_ms, False,
                 total_limit),
                ("scenario_drf_share_error",
                 quality.get("scenario_drf_share_error"), False, None),
                ("scenario_node_utilization",
                 quality.get("scenario_node_utilization"), True, None),
                ("failover_promote_ms_p50",
                 quality.get("failover_promote_ms_p50"), False, None),
                ("fleet_cycle_ms_p99",
                 quality.get("fleet_cycle_ms_p99"), False, None),
                ("fleet_tenants_per_s",
                 quality.get("fleet_tenants_per_s"), True, None),
                # wavefront win at the best width: higher is better, so
                # the ratio is inverted (a future change that erodes the
                # batched-sweep speedup trips the guard)
                ("wavefront_speedup",
                 quality.get("wavefront_speedup"), True, None),
                # static cost model: peak-live HBM and per-cycle
                # collective bytes must not creep (>1 = footprint grew)
                ("cost_peak_live_bytes",
                 quality.get("cost_peak_live_bytes"), False, None),
                ("cost_collective_bytes",
                 quality.get("cost_collective_bytes"), False, None),
                # elastic-mesh recovery: quarantine->serving-again latency
                # and the shrunk mesh's steady cycle must not creep
                ("remesh_ms_p50",
                 quality.get("remesh_ms_p50"), False, None),
                ("post_shrink_steady_ms_p50",
                 quality.get("post_shrink_steady_ms_p50"), False, None)):
            base = parsed.get(key)
            if cur is None or not base or (invert and not cur):
                continue
            ratio = round(float(base) / float(cur) if invert
                          else float(cur) / float(base), 2)
            block[key + "_baseline"] = base
            block[key + "_ratio"] = ratio
            if (ratio >= limit) if limit is not None \
                    else (ratio > threshold):
                block["regression"] = True
            found = True
        return block if found else None
    return None


def _run(force_cpu: bool):
    if force_cpu:
        # Degraded mode: the jitted cycle runs on the CPU backend. The
        # XLA-compiled scan at full 10k/100k scale is too slow for a
        # bounded bench run, so scale down (overridable via env).
        n_nodes = int(os.environ.get("BENCH_NODES", 2048))
        n_jobs = int(os.environ.get("BENCH_JOBS", 1280))
    else:
        n_nodes = int(os.environ.get("BENCH_NODES", 10000))
        n_jobs = int(os.environ.get("BENCH_JOBS", 6250))
    tasks_per_job = int(os.environ.get("BENCH_TASKS_PER_JOB", 16))
    reps = int(os.environ.get("BENCH_REPS", 3))
    # batching comes from derive_batching (_bench_cfg): exact K-batching
    # here because there is no drf/hdrf ordering and neutral (infinite)
    # proportion deserved; the snapshot carries no GPU requests
    cfg_kwargs = dict(binpack_weight=1.0, least_allocated_weight=0.0,
                      balanced_weight=0.0, taint_prefer_weight=0.0,
                      enable_gpu=False)

    import jax
    if force_cpu:
        # Same mechanism as tests/conftest.py: the config API overrides
        # the axon site hook's jax_platforms=axon, as long as it runs
        # before any backend initializes.
        jax.config.update("jax_platforms", "cpu")
    # persistent compile cache: the cycle compiles once per shape bucket and
    # every later bench/driver run reuses it (the same knob the scheduler
    # and sidecar expose via conf/env — framework/compile_cache)
    from volcano_tpu.framework.compile_cache import enable_compilation_cache
    enable_compilation_cache(os.environ.get("JAX_COMPILATION_CACHE_DIR",
                                            "/tmp/volcano_tpu_jax_cache"))
    from volcano_tpu.ops.allocate_scan import (AllocateExtras,
                                               make_allocate_cycle)
    from volcano_tpu.runtime.cpu_reference import allocate_cpu

    snap, extras, cfg = _build(n_nodes, n_jobs, tasks_per_job, cfg_kwargs)
    fn = jax.jit(make_allocate_cycle(cfg))
    result, dev_ms, compile_s = _time_device(fn, snap, extras, reps)
    n_tasks = n_jobs * tasks_per_job
    placed = int(np.asarray(result.task_mode > 0).sum())
    # decision fingerprint: detects when kernel changes invalidate the
    # RECORDED full-scale equality/cpu_ms without paying the live CPU run
    # (the round-3 staleness finding)
    import hashlib
    decisions_sha = hashlib.sha256(
        np.asarray(result.task_node).tobytes()
        + np.asarray(result.task_mode).tobytes()).hexdigest()[:16]

    # ---- CPU baseline ----------------------------------------------------
    recorded = None
    if os.path.exists(_BASELINE_PATH):
        with open(_BASELINE_PATH) as f:
            recorded = json.load(f)
    matches_recorded = bool(
        recorded
        and recorded["config"] == {"nodes": n_nodes, "jobs": n_jobs,
                                   "tasks_per_job": tasks_per_job,
                                   "binpack_weight": 1.0})
    if os.environ.get("BENCH_LIVE_CPU") or not matches_recorded:
        t0 = time.time()
        cpu = allocate_cpu(snap, extras, cfg)
        cpu_ms = (time.time() - t0) * 1000
        equal_full = _decisions_equal(result, cpu)
        cpu_source = "measured"
    else:
        cpu_ms = float(recorded["cpu_ms"])
        rec_sha = recorded.get("decisions_sha256")
        if rec_sha is not None and rec_sha == decisions_sha:
            # decisions byte-identical to the verified record
            equal_full = True
        else:
            equal_full = None
        cpu_source = f"recorded {recorded['measured']} (BENCH_BASELINE.json)"
        if rec_sha is not None and rec_sha != decisions_sha:
            cpu_source += " [STALE: decisions changed since record]"

    # ---- full-session wall time (open -> allocate -> apply -> close) -----
    # The reference's cycle budget is the 1s schedule period
    # (cmd/scheduler/app/options/options.go:86); the kernel alone is not the
    # whole story — this measures snapshot pack, extras, kernel, and the
    # host-side bind readout through the real Session object path.
    full_session_ms = None
    steady_ms = steady_binds = None
    steady_p50 = steady_p95 = steady_total_p50 = None
    steady_delta_fraction = None
    steady_upload_full = steady_upload_delta = None
    steady_readback_delta = steady_readback_full = None
    loop_incremental = None
    bench_depth = None
    latency_depth_occ = None
    latency_phases = latency_occ = None
    if not os.environ.get("BENCH_SKIP_SESSION"):
        from __graft_entry__ import _synthetic_cluster
        from volcano_tpu.framework import parse_conf
        from volcano_tpu.framework.session import Session
        _sess_body = """
actions: "allocate"
tiers:
- plugins:
  - name: gang
  - name: binpack
"""
        sess_conf = parse_conf(_sess_body)
        ci = _synthetic_cluster(n_nodes=n_nodes, n_jobs=n_jobs,
                                tasks_per_job=tasks_per_job)
        # warm the jit cache for this shape bucket outside the timed region
        warm = Session(ci, sess_conf)
        warm.run_allocate()
        warm.close()
        ci = _synthetic_cluster(n_nodes=n_nodes, n_jobs=n_jobs,
                                tasks_per_job=tasks_per_job)
        t0 = time.time()
        ssn = Session(ci, sess_conf)
        ssn.run_allocate()
        ssn.close()
        full_session_ms = (time.time() - t0) * 1000
        session_binds = len(ssn.binds)

        # ---- steady-state SCHEDULER LOOP cycle (the production path) ----
        # The recurring cycle a long-running scheduler pays every schedule
        # period, measured through Scheduler.run_once itself: most of the
        # cluster is unchanged, ~5% of gangs completed and were replaced by
        # new arrivals. run_once holds ONE session over the cluster's live
        # view and re-opens it via refresh_snapshot from the cluster's
        # dirty marks (the event-handler analog); the kernel re-places only
        # the churned tasks; the timed region includes intent dispatch back
        # into the cluster — everything a real cycle pays.
        # ISSUE 4: the production loop now runs device-resident delta
        # uploads (O(dirty) transfer) with the one-deep pipelined readback
        # — run_once drains cycle N-1's decisions, refreshes, packs the
        # delta, dispatches cycle N, and returns while the device
        # computes; decisions are sha-identical to the synchronous
        # full-upload loop (tests/test_delta_pipeline.py).
        from volcano_tpu.api import TaskStatus as _TS
        from volcano_tpu.runtime.fake_cluster import FakeCluster
        from volcano_tpu.runtime.scheduler import Scheduler
        ci = _synthetic_cluster(n_nodes=n_nodes, n_jobs=n_jobs,
                                tasks_per_job=tasks_per_job)
        cluster = FakeCluster(ci)
        # ISSUE 13: the headline loop runs at the production default
        # (pipeline_depth: 1) — on this churn workload every cycle binds,
        # so a depth-k speculation is always invalidated and replayed;
        # the depth-1 contract already gets the PR's wins (the delta
        # pack rides the async worker thread during ingest, the drain
        # reads back only changed decision rows). A separate depth-k leg
        # below records the per-depth overlap observability.
        sched = Scheduler(cluster, conf=sess_conf, pipeline=True)
        sched.run_once()        # cold cycle: full pack + full placement

        def loop_churn(off, cl=cluster):
            # a ROTATING ~5% of gangs completes and re-arrives: the slice
            # rotates so each cycle churns gangs whose previous binds have
            # already been applied (under the one-deep pipeline the newest
            # cycle's binds land at the top of the next run_once, so a
            # fixed slice would alternately churn not-yet-bound tasks)
            for uid in list(cl.ci.jobs)[off % 20::20]:
                job = cl.ci.jobs[uid]
                for task in list(job.tasks.values()):
                    node = cl.ci.nodes.get(task.node_name)
                    if node is not None and task.uid in node.tasks:
                        node.remove_task(task)
                        cl.mark_dirty(node_name=node.name)
                    job.update_task_status(task, _TS.PENDING)
                    task.node_name = ""
                job.allocated = type(job.allocated)({})
                cl.mark_dirty(job_uid=uid)

        # warm rounds: absorb the residual full-cycle compile AND the
        # delta-bucket compiles for the churn's steady delta sizes
        for w in range(3):
            loop_churn(w)
            sched.run_once()
        # span rings restart here so the latency_breakdown block reports
        # STEADY phase stats, not compile-tainted warmup durations
        from volcano_tpu.telemetry import spans as _spans
        _spans.reset()
        times_steady = []
        times_total = []
        steady_reps = int(os.environ.get("BENCH_STEADY_REPS", 5))
        for r in range(max(steady_reps, 1)):
            t_all = time.time()
            # the churn IS the host's inter-cycle ingest work: spanning it
            # lets the occupancy analyzer credit it against the in-flight
            # device window (the overlap the pipeline buys)
            with _spans.span("loop.ingest", cat="ingest"):
                loop_churn(3 + r)
            # in production the 1 s schedule period lets the in-flight
            # cycle's device compute finish during event ingestion; the
            # bench's churn is faster than a real period, so wait here —
            # run_once's latency then measures the LOOP (drain + refresh
            # + delta pack + dispatch), which is the recurring cost the
            # pipeline leaves on the critical path. times_total keeps the
            # compute-inclusive wall time for comparison.
            sched.wait_pending()
            t0 = time.time()
            sched.run_once()
            now = time.time()
            times_steady.append((now - t0) * 1000)
            times_total.append((now - t_all) * 1000)
        sched.drain()           # retire the final in-flight cycle
        # snapshot the steady loop's span rings BEFORE later blocks (the
        # sidecar and chaos probe run their own cycles on the same rings)
        latency_phases = _spans.phase_stats()
        latency_occ = _spans.occupancy()
        ts = sorted(times_steady)
        steady_p50 = ts[len(ts) // 2]
        steady_p95 = ts[min(len(ts) - 1, int(round(0.95 * (len(ts) - 1))))]
        steady_ms = steady_p50
        steady_total_p50 = sorted(times_total)[len(times_total) // 2]
        flight = sched.flight.snapshots()
        steady_binds = flight[-1]["binds"] if flight else None
        kinds = [e.get("cycle_kind") for e in flight
                 if e.get("cycle_kind")]
        steady_delta_fraction = (round(kinds.count("delta") / len(kinds), 3)
                                 if kinds else None)
        deltas = [e for e in flight if e.get("cycle_kind") == "delta"]
        if deltas:
            steady_upload_delta = deltas[-1]["upload_bytes"]
            steady_upload_full = deltas[-1]["upload_bytes_full"]
        # changed-decisions-only readback: the last steady cycle that
        # took the delta tail records what the drain actually moved vs
        # what a full decision readback would have (the O(churn) claim)
        rb = [e["stats"] for e in flight
              if (e.get("stats") or {}).get("drain_readback_rows")
              is not None
              and e["stats"].get("drain_readback_bytes_full") is not None]
        if rb:
            steady_readback_delta = rb[-1]["drain_readback_bytes"]
            steady_readback_full = rb[-1]["drain_readback_bytes_full"]
        loop_incremental = sched.incremental_cycles >= 2 \
            and sched.full_packs == 1

        # ---- depth-k overlap leg (ISSUE 13 observability) ----------------
        # The same churned loop at pipeline_depth k on a fresh cluster:
        # speculative cycles ride the ring while the host ingests, and on
        # this always-binding workload each one is invalidated and
        # replayed — the leg records what that costs/buys (per-depth
        # overlap fraction + replay count), NOT the headline timing.
        # BENCH_PIPELINE_DEPTH sets k (1 disables the leg).
        bench_depth = max(1, int(os.environ.get("BENCH_PIPELINE_DEPTH",
                                                "3")))
        if bench_depth > 1:
            from volcano_tpu.metrics import METRICS as _METRICS
            loop_conf = parse_conf(f"pipeline_depth: {bench_depth}\n"
                                   + _sess_body)
            cluster_k = FakeCluster(_synthetic_cluster(
                n_nodes=n_nodes, n_jobs=n_jobs,
                tasks_per_job=tasks_per_job))
            sched_k = Scheduler(cluster_k, conf=loop_conf, pipeline=True)
            sched_k.run_once()
            for w in range(3):  # warm: speculative-dispatch variants too
                loop_churn(w, cluster_k)
                sched_k.run_once()
            sched_k.drain()
            _spans.reset()
            replays0 = _METRICS.counter_total("cycle_replays_total")
            for r in range(max(steady_reps, 1)):
                with _spans.span("loop.ingest", cat="ingest"):
                    loop_churn(3 + r, cluster_k)
                sched_k.run_once()
            sched_k.drain()
            depth_occ = _spans.occupancy()
            latency_depth_occ = {
                "depth": bench_depth,
                "replays": int(_METRICS.counter_total(
                    "cycle_replays_total") - replays0),
                "per_depth": {
                    d: a.get("pipeline_overlap_fraction")
                    for d, a in (depth_occ.get("per_depth") or {
                        "1": depth_occ}).items()},
            }

    # ---- sidecar serving cycle (SURVEY section 5.8 production path) ------
    # The API-layer process ships a VCS3 wire snapshot; the sidecar packs it
    # with the C++ packer and runs the compiled cycle. This measures
    # buffer-in -> decisions-out, the recurring cost of the served cycle
    # (client-side serialization happens in the API-layer process).
    sidecar_ms = None
    sidecar_steady_ms = None
    sidecar_steady_kind = sidecar_upload_delta = None
    if not os.environ.get("BENCH_SKIP_SIDECAR"):
        from volcano_tpu.native import available as _native_ok
        from volcano_tpu.native.wire import IncrementalWire
        from volcano_tpu.native.wire import serialize as _wire_ser
        from volcano_tpu.runtime.sidecar import SchedulerSidecar
        if _native_ok():
            from __graft_entry__ import _synthetic_cluster as _synth
            sci0 = _synth(n_nodes=n_nodes, n_jobs=n_jobs,
                          tasks_per_job=tasks_per_job)
            wire_buf, _wm = _wire_ser(sci0)
            car = SchedulerSidecar(cfg=_bench_cfg(cfg_kwargs))
            car.schedule_buffer(wire_buf)        # warm the jit cache
            times = []
            for _ in range(min(reps, 3)):
                t0 = time.time()
                car.schedule_buffer(wire_buf)
                times.append(time.time() - t0)
            sidecar_ms = min(times) * 1000

            # steady-state SERVED cycle: the API layer applies each
            # round's binds, churns a rotating ~5% of gangs, patches only
            # the dirty entities into the retained wire buffer
            # (IncrementalWire, the refresh_snapshot analog at the wire
            # boundary) and serves rounds through the ONE-DEEP PIPELINED
            # protocol (VCRP, ISSUE 4): each request dispatches its
            # snapshot's cycle against the device-resident delta buffers
            # and returns the previous round's decisions — the serving
            # path the timed rounds measure excludes raw device compute,
            # which overlaps the API layer's apply/churn/serialize work
            # (wait_idle stands in for the schedule period's slack).
            from volcano_tpu.api import TaskStatus as _TS2
            inc = IncrementalWire()
            buf0, wmaps = inc.serialize(sci0)
            import struct as _st
            names2 = wmaps.node_names

            def apply_binds(out_bytes):
                """Bind every allocated task (the API-layer role).
                Returns the dirty sets for the next incremental patch."""
                Tn, _Jn = _st.unpack("<II", out_bytes[4:12])
                if Tn == 0:
                    return set(), set()
                tnode = np.frombuffer(out_bytes, "<i4", Tn, 12)
                tmode = np.frombuffer(out_bytes, "<i4", Tn, 12 + 4 * Tn)
                dirty_j, dirty_n = set(), set()
                for job in sci0.jobs.values():
                    for uid, task in job.tasks.items():
                        ti = wmaps.task_index[uid]
                        if tmode[ti] == 1 and task.status == _TS2.PENDING:
                            node = sci0.nodes[names2[tnode[ti]]]
                            job.update_task_status(task, _TS2.BOUND)
                            task.node_name = node.name
                            try:
                                node.add_task(task)
                            except ValueError:
                                job.update_task_status(task, _TS2.PENDING)
                                task.node_name = ""
                                continue
                            dirty_j.add(job.uid)
                            dirty_n.add(node.name)
                return dirty_j, dirty_n

            def wire_churn(off=0):
                dj, dn = set(), set()
                for uid in list(sci0.jobs)[off::20]:     # ~5% of gangs
                    job = sci0.jobs[uid]
                    for task in list(job.tasks.values()):
                        node = sci0.nodes.get(task.node_name)
                        if node is not None and task.uid in node.tasks:
                            node.remove_task(task)
                            dn.add(node.name)
                        job.update_task_status(task, _TS2.PENDING)
                        task.node_name = ""
                    job.allocated = type(job.allocated)({})
                    dj.add(uid)
                return dj, dn

            out0 = car.schedule_buffer(buf0)
            dirty_j, dirty_n = apply_binds(out0)

            def round_trip(off, timed=False):
                """One steady round: churn + apply-dirty -> incremental
                patch -> pipelined serve; the previous round's decisions
                come back and are applied. Returns (elapsed_ms|None)."""
                nonlocal dirty_j, dirty_n
                dj, dn = wire_churn(off)
                dj |= dirty_j
                dn |= dirty_n
                car.wait_idle()      # the schedule period's slack
                t0 = time.time()
                bufN, _ = inc.serialize(sci0, dirty_jobs=dj, dirty_nodes=dn)
                out = car.schedule_buffer_pipelined(bufN)
                elapsed = (time.time() - t0) * 1000
                dirty_j, dirty_n = apply_binds(out)
                return elapsed

            # warm rounds prime the pipeline and the churn-sized delta
            # buckets (the sidecar holds the fused buffers device-resident
            # and ships only the diff); min over the timed rounds filters
            # a round that lands in a fresh delta bucket (compile)
            for w in (1, 2, 3):
                round_trip(w)
            sc_times = [round_trip(r, timed=True) for r in (4, 5, 6)]
            sidecar_steady_ms = min(sc_times)
            drained = car.drain_pending()
            if drained is not None:
                apply_binds(drained)
            assert inc.incremental_serializes >= 6
            sc_flight = [e for e in car.flight.snapshots()
                         if e.get("cycle_kind")]
            sidecar_steady_kind = (sc_flight[-1].get("cycle_kind")
                                   if sc_flight else None)
            sidecar_upload_delta = (sc_flight[-1].get("upload_bytes")
                                    if sc_flight else None)

    # ---- DRF multi-queue fair share (BASELINE.json config 3) -------------
    # 8 weighted queues, 50k tasks over 1k nodes (capacity-scarce so the
    # dominant-resource ordering decides who places), drf JobOrderFn with
    # live share recomputation per pop (drf.go:454-472 + 511-536).
    drf_ms = drf_placed = drf_equal_sub = None
    drf_equal_full = drf_sha = None
    drf_backend = None
    # initialized BEFORE the drf section: the preempt block's init used to
    # re-None this after the drf section had already set it
    drf_record_stale = None
    if not os.environ.get("BENCH_SKIP_DRF"):
        from __graft_entry__ import _synthetic_cluster as _synth
        from volcano_tpu.api import QueueInfo
        from volcano_tpu.ops.allocate_scan import (AllocateConfig as _AC,
                                                   derive_batching)

        def _drf_cluster(n_nodes, n_jobs, tasks_per_job):
            c = _synth(n_nodes=n_nodes, n_jobs=n_jobs,
                       tasks_per_job=tasks_per_job)
            for q in range(8):
                c.add_queue(QueueInfo(f"q{q}", weight=1 + q % 4))
            for j, job in enumerate(c.jobs.values()):
                job.queue = f"q{j % 8}"
            return c

        if force_cpu:
            # degrade, never skip: sub-scale on the CPU backend, labeled
            drf_backend = "cpu_subscale"
            dci = _drf_cluster(
                int(os.environ.get("BENCH_DRF_NODES", 256)),
                int(os.environ.get("BENCH_DRF_JOBS", 384)), 8)
        else:
            drf_backend = "tpu"
            dci = _drf_cluster(
                int(os.environ.get("BENCH_DRF_NODES", 1024)),
                int(os.environ.get("BENCH_DRF_JOBS", 3125)), 16)
        from volcano_tpu import native as _nat
        dsnap, _dm = _nat.pack_best_effort(dci)
        dextras = AllocateExtras.neutral(dsnap)
        # derive_batching routes the dynamic-key (drf) ordering through
        # the fused in-kernel-selection path on TPU (batch_rounds); on the
        # CPU backend the auto probe falls back to the XLA scan
        dcfg = derive_batching(
            _AC(binpack_weight=1.0, least_allocated_weight=0.0,
                balanced_weight=0.0, taint_prefer_weight=0.0,
                drf_job_order=True, enable_gpu=False),
            has_proportion=False)
        dfn = jax.jit(make_allocate_cycle(dcfg))
        dresult, drf_ms, _ = _time_device(dfn, dsnap, dextras, min(reps, 2))
        drf_placed = int(np.asarray(dresult.task_mode > 0).sum())
        if not force_cpu:
            # full-scale equality record (scripts/drf_record.py runs the
            # live CPU oracle once at this scale), fingerprint-guarded
            # thereafter; meaningless at the degraded sub-scale
            import hashlib as _hl2
            drf_sha = _hl2.sha256(
                np.asarray(dresult.task_node).tobytes()
                + np.asarray(dresult.task_mode).tobytes()).hexdigest()[:16]
            rec_dsha = (recorded or {}).get("drf_sha256")
            drf_equal_full = (True if (rec_dsha is not None
                                       and rec_dsha == drf_sha
                                       and (recorded or {}).get(
                                           "drf_equal_full_scale_verified"))
                              else None)
            if rec_dsha is not None:
                drf_record_stale = rec_dsha != drf_sha
        # sub-scale decision equality for the dynamic-drf ordering path
        sci = _drf_cluster(192, 192, 8)
        ssnap2, _sm2 = _nat.pack_best_effort(sci)
        sextras2 = AllocateExtras.neutral(ssnap2)
        sres2 = dfn(ssnap2, sextras2)     # same jit object, new shape bucket
        scpu2 = allocate_cpu(ssnap2, sextras2, dcfg)
        drf_equal_sub = _decisions_equal(sres2, scpu2)

    # ---- gang + preempt at scale (BASELINE.json config 4) ----------------
    # 10k nodes ~75% full of Running preemptable low-priority tasks plus
    # starving high-priority gangs; the preempt kernel picks victims via
    # the tiered dispatch and pipelines the preemptors. Verified against
    # the sequential CPU oracle (runtime/cpu_reference.preempt_cpu):
    # live at a subscale config EVERY run, at full config-4 scale once
    # with the fingerprint guard (BENCH_LIVE_PREEMPT_CPU=1 re-records).
    preempt_ms = preempt_victims = preempt_pipelined = None
    preempt_invariants_ok = None
    preempt_equal_sub = preempt_equal_full = None
    preempt_sha = None
    preempt_record_stale = None
    preempt_adv_record_stale = None
    preempt_adv_ms = preempt_adv_victims = preempt_adv_pipelined = None
    preempt_adv_equal = None
    preempt_backend = None
    if not os.environ.get("BENCH_SKIP_PREEMPT"):
        from __graft_entry__ import _synthetic_cluster as _synth
        from volcano_tpu.api import (JobInfo, PodGroupPhase, Resource,
                                     TaskInfo, TaskStatus)
        from volcano_tpu.ops.preempt import PreemptConfig, make_preempt_cycle
        from volcano_tpu.ops.allocate_scan import AllocateConfig as _AC
        from volcano_tpu.runtime.cpu_reference import preempt_cpu
        from volcano_tpu.ops.allocate_scan import MODE_PIPELINED as _MP
        from volcano_tpu import native as _nat2

        # single scenario builder shared with the recorded-oracle scripts
        # (scripts/preempt_profile.py) so fingerprints stay comparable
        from scripts.preempt_profile import scenario as _pp_scenario

        def _preempt_scenario(n_nodes, n_jobs, n_gangs, gang_tasks=16,
                              min_avail=8):
            return _pp_scenario(n_nodes=n_nodes, n_jobs=n_jobs,
                                n_gangs=n_gangs, gang_tasks=gang_tasks,
                                min_avail=min_avail)

        pcfg = PreemptConfig(scoring=_AC(
            binpack_weight=1.0, least_allocated_weight=0.0,
            balanced_weight=0.0, taint_prefer_weight=0.0, enable_gpu=False))
        pfn = jax.jit(make_preempt_cycle(pcfg))

        def _run_preempt(pci, reps_n):
            psnap, _pm = _nat2.pack_best_effort(pci)
            pextras = AllocateExtras.neutral(psnap)
            pT = psnap.tasks.status.shape[0]
            pveto = np.zeros(pT, bool)
            pskip = np.zeros(pT, bool)
            pres = pfn(psnap, pextras, pveto, pskip)   # compile + warm
            np.asarray(pres.evicted)
            times = []
            for _ in range(reps_n):
                t0 = time.time()
                pres = pfn(psnap, pextras, pveto, pskip)
                pev = np.asarray(pres.evicted)
                ptm = np.asarray(pres.task_mode)
                times.append(time.time() - t0)
            return psnap, pextras, pveto, pskip, pres, pev, ptm, \
                min(times) * 1000

        # subscale oracle equality, every run
        sci = _preempt_scenario(1000, 600, 8)
        ssnap, sextras, sveto, sskip, sres, _sev, _stm, sub_pre_ms = \
            _run_preempt(sci, 1)
        scpu = preempt_cpu(ssnap, sextras, sveto, sskip, pcfg)
        preempt_equal_sub = bool(
            np.array_equal(np.asarray(sres.evicted), scpu["evicted"])
            and np.array_equal(np.asarray(sres.task_node),
                               scpu["task_node"])
            and np.array_equal(np.asarray(sres.task_mode),
                               scpu["task_mode"]))

        import hashlib as _hl
        if force_cpu:
            # degrade, never skip: the oracle-checked sub-scale scenario
            # IS the measured config on the CPU backend, labeled
            preempt_backend = "cpu_subscale"
            psnap, pres, pev, ptm = ssnap, sres, np.asarray(sres.evicted), \
                np.asarray(sres.task_mode)
            preempt_ms = sub_pre_ms
        else:
            # config 4 at full scale
            preempt_backend = "tpu"
            pci = _preempt_scenario(
                int(os.environ.get("BENCH_PRE_NODES", 10000)),
                int(os.environ.get("BENCH_PRE_JOBS", 6000)),
                int(os.environ.get("BENCH_PRE_GANGS", 64)))
            psnap, pextras, pveto, pskip, pres, pev, ptm, preempt_ms = \
                _run_preempt(pci, min(reps, 2))
        preempt_victims = int(pev.sum())
        preempt_pipelined = int((ptm == _MP).sum())
        if not force_cpu:
            preempt_sha = _hl.sha256(
                np.asarray(pres.task_node).tobytes()
                + np.asarray(pres.task_mode).tobytes()
                + pev.tobytes()).hexdigest()[:16]
            rec_psha = (recorded or {}).get("preempt_sha256")
            if os.environ.get("BENCH_LIVE_PREEMPT_CPU"):
                pcpu = preempt_cpu(psnap, pextras, pveto, pskip, pcfg)
                preempt_equal_full = bool(
                    np.array_equal(pev, pcpu["evicted"])
                    and np.array_equal(np.asarray(pres.task_node),
                                       pcpu["task_node"])
                    and np.array_equal(np.asarray(pres.task_mode),
                                       pcpu["task_mode"]))
            elif rec_psha is not None:
                # mismatch = the verified record no longer describes these
                # decisions: surface the staleness, do not silently skip
                preempt_equal_full = True if rec_psha == preempt_sha \
                    else None
                preempt_record_stale = rec_psha != preempt_sha

        # invariants (cross-checking the oracle): victims only from
        # lower-priority jobs; every pipelined-flag gang reached
        # minAvailable with its pipelined tasks
        ptjob = np.asarray(psnap.tasks.job)
        pprio = np.asarray(psnap.jobs.priority)
        pjp = np.asarray(pres.job_pipelined)
        pminav = np.asarray(psnap.jobs.min_available)
        pipe_jobs = ptjob[ptm == _MP]
        pipe_per_job = np.bincount(np.maximum(pipe_jobs, 0),
                                   minlength=pprio.shape[0])
        preempt_invariants_ok = bool(
            (ptjob[pev] >= 0).all() and (pipe_jobs >= 0).all()
            and (pprio[ptjob[pev]] < 100).all()
            and (pipe_per_job[pjp] >= pminav[pjp]).all())

        # adversarial scale (VERDICT r4 #2): >=300 starving gangs, ~28k
        # pending preemptor tasks over the same 10k-node cluster
        # (cpu_subscale: same gang density at 1/10 the cluster)
        if not os.environ.get("BENCH_SKIP_PREEMPT_ADV"):
            if force_cpu:
                aci = _preempt_scenario(1000, 600, 31, gang_tasks=90,
                                        min_avail=90)
            else:
                aci = _preempt_scenario(10000, 6000, 312, gang_tasks=90,
                                        min_avail=90)
            (_a1, _a2, _a3, _a4, ares, aev, atm,
             preempt_adv_ms) = _run_preempt(aci, 1)
            preempt_adv_victims = int(aev.sum())
            preempt_adv_pipelined = int((atm == _MP).sum())
            # full-scale equality record (PREEMPT_ADV_RECORD.json, written
            # by scripts/preempt_adv_oracle.py: CPU oracle 1001.8s vs TPU
            # 7.8s, decisions bit-identical) — fingerprint-guarded
            arec_path = os.path.join(
                os.path.dirname(os.path.abspath(__file__)),
                "PREEMPT_ADV_RECORD.json")
            if force_cpu:
                pass    # sub-scale decisions can't match the full record
            elif os.path.exists(arec_path):
                with open(arec_path) as f:
                    arec = json.load(f)
                asha = _hl.sha256(
                    np.asarray(ares.task_node).tobytes() + atm.tobytes()
                    + aev.tobytes()).hexdigest()[:16]
                preempt_adv_equal = (
                    True if (arec.get("decisions_equal")
                             and arec.get("preempt_adv_sha256") == asha)
                    else None)
                preempt_adv_record_stale = (
                    arec.get("preempt_adv_sha256") != asha)
            else:
                preempt_adv_equal = None

    # ---- topology-aware binpack with affinity (BASELINE.json config 5) ---
    # 10k nodes with zone/rack labels, required + preferred inter-pod
    # (anti-)affinity terms. The fused round placer now carries the live
    # affinity counts in VMEM (ops/pallas_place v3), so the auto path uses
    # it on TPU; oracle equality is checked live at 1k-node sub-scale
    # every run, and the full-scale record is fingerprint-guarded like the
    # north-star config's (affinity_sha256 in BENCH_BASELINE.json).
    affinity_ms = affinity_placed = None
    affinity_equal_sub = affinity_equal_full = affinity_sha = None
    affinity_record_stale = None
    affinity_backend = None
    if not os.environ.get("BENCH_SKIP_AFFINITY"):
        import dataclasses as _dc
        # same scenario + extras builders as the recorded-oracle script so
        # fingerprints stay comparable (scripts/affinity_record.py)
        from scripts.affinity_record import build as _aff_pack
        from scripts.affinity_record import scenario as _aff_cluster

        if force_cpu:
            affinity_backend = "cpu_subscale"
            aci = _aff_cluster(int(os.environ.get("BENCH_AFF_NODES", 512)),
                               int(os.environ.get("BENCH_AFF_JOBS", 192)))
        else:
            affinity_backend = "tpu"
            aci = _aff_cluster(
                int(os.environ.get("BENCH_AFF_NODES", 10000)),
                int(os.environ.get("BENCH_AFF_JOBS", 2500)))
        asnap, aextras = _aff_pack(aci)
        acfg = _dc.replace(cfg, enable_pod_affinity=True)
        afn = jax.jit(make_allocate_cycle(acfg))
        aresult, affinity_ms, _ = _time_device(afn, asnap, aextras,
                                               min(reps, 2))
        affinity_placed = int(np.asarray(aresult.task_mode > 0).sum())
        if not force_cpu:
            import hashlib as _hl3
            affinity_sha = _hl3.sha256(
                np.asarray(aresult.task_node).tobytes()
                + np.asarray(aresult.task_mode).tobytes()).hexdigest()[:16]
            rec_asha = (recorded or {}).get("affinity_sha256")
            affinity_equal_full = (
                True if (rec_asha is not None and rec_asha == affinity_sha
                         and (recorded or {}).get(
                             "affinity_equal_full_scale_verified"))
                else None)
            if rec_asha is not None:
                affinity_record_stale = rec_asha != affinity_sha
        # live 1k-node oracle equality, every run (VERDICT r5 item 3)
        saci = _aff_cluster(1024, 320, seed=1)
        sasnap, saextras = _aff_pack(saci)
        sares = afn(sasnap, saextras)
        sacpu = allocate_cpu(sasnap, saextras, acfg)
        affinity_equal_sub = _decisions_equal(sares, sacpu)

    # ---- live sub-scale decision-equality + speedup check ----------------
    equal_sub = sub_speedup = stpu_ms = scpu_ms = None
    if not os.environ.get("BENCH_SKIP_CHECK"):
        ssnap, sextras, scfg = _build(1024, 640, 16, cfg_kwargs)
        sfn = jax.jit(make_allocate_cycle(scfg))
        sresult, stpu_ms, _ = _time_device(sfn, ssnap, sextras, 3)
        t0 = time.time()
        scpu = allocate_cpu(ssnap, sextras, scfg)
        scpu_ms = (time.time() - t0) * 1000
        equal_sub = _decisions_equal(sresult, scpu)
        sub_speedup = round(scpu_ms / stpu_ms, 1)

    # ---- in-graph cycle telemetry block (volcano_tpu/telemetry) ----------
    # Every BENCH record carries the telemetry=True cycle's counter block
    # at the oracle-checked sub-scale: rejection totals, rounds/pops, the
    # unplaced-reason histogram, and the live jit retrace counters. Fail
    # soft: a telemetry failure (or BENCH_SKIP_TELEMETRY=1) records null,
    # never kills the bench.
    telemetry_block = None
    if not os.environ.get("BENCH_SKIP_TELEMETRY"):
        try:
            import dataclasses as _dct
            from volcano_tpu.telemetry import unpack_cycle_telemetry
            from volcano_tpu.telemetry import tracecount as _tc
            tsnap, textras, tcfg = _build(512, 320, 8, cfg_kwargs)
            tfn = jax.jit(make_allocate_cycle(
                _dct.replace(tcfg, telemetry=True)))
            tres = tfn(tsnap, textras)
            tR = int(np.asarray(tsnap.nodes.idle).shape[1])
            tel = unpack_cycle_telemetry(
                np.asarray(tres.telemetry.packed()), tR)
            telemetry_block = {
                "rejections_total": sum(tel["pred_reject"].values()),
                "pred_reject": tel["pred_reject"],
                "unplaced": tel["unplaced"],
                "rounds": tel["rounds"],
                "pops": tel["pops"],
                "placed_now": tel["placed_now"],
                "placed_future": tel["placed_future"],
                "argmax_ties": tel["argmax_ties"],
                "dyn_launches": tel["dyn_launches"],
                "dyn_early_stops": tel["dyn_early_stops"],
                "jit_retraces": {e: c["traces"]
                                 for e, c in _tc.counts().items()},
            }
        except Exception as e:  # noqa: BLE001 — fail-soft contract
            print("bench: telemetry block failed: %s: %s"
                  % (type(e).__name__, e), file=sys.stderr)
            telemetry_block = None

    # ---- fault-tolerance robustness block (volcano_tpu/chaos) ------------
    # Every BENCH record carries a fail-soft chaos probe: a seeded fault
    # storm (every recoverable kind) over a small multi-cycle pipelined
    # scheduler run, verified against the identical no-fault run. The
    # block records how many cycles recovered, how fast, how far down the
    # degradation ladder the loop went, and — the actual claim — whether
    # the post-recovery decision sha still equals the clean run's.
    # BENCH_SKIP_CHAOS=1 skips; a probe failure records null, never kills
    # the bench.
    robustness_block = None
    if not os.environ.get("BENCH_SKIP_CHAOS"):
        try:
            from volcano_tpu.chaos import run_chaos_probe
            rpt = run_chaos_probe(seed=int(os.environ.get("BENCH_CHAOS_SEED",
                                                          7)),
                                  cycles=6)
            robustness_block = {
                "decisions_equal_clean": rpt["decisions_equal_clean"],
                "faults_fired": rpt["faults_fired"],
                "fault_schedule_sha": rpt["fault_schedule_sha"],
                "recovered_cycles": rpt["recovered_cycles"],
                "recovery_ms_p50": rpt["recovery_ms_p50"],
                "degradation_max": rpt["degradation_max"],
                "digest_mismatches": rpt["digest_mismatches"],
                "resync_dead_letter": rpt["resync_dead_letter"],
            }
        except Exception as e:  # noqa: BLE001 — fail-soft contract
            print("bench: robustness block failed: %s: %s"
                  % (type(e).__name__, e), file=sys.stderr)
            robustness_block = None

    # ---- elastic-mesh degradation block (volcano_tpu/chaos/meshloss) -----
    # The ISSUE 20 probe: persistent device_loss faults quarantine devices
    # and shrink the sharded serving mesh 8->4->2, probation regrows it to
    # full width, decisions stay sha-identical to the clean run, and the
    # flap leg proves the probation backoff bounds re-mesh churn. Runs as
    # a subprocess on the CPU backend with 8 forced virtual devices (like
    # the multichip block) so a GSPMD failure can't take the record down.
    # remesh_ms_p50 (quarantine -> serving again, dominated by the shrunk
    # mesh's GSPMD compile) and the post-shrink steady-cycle p50 feed the
    # regression guard. BENCH_SKIP_MESHLOSS=1 skips; failure records null.
    if not os.environ.get("BENCH_SKIP_MESHLOSS"):
        try:
            menv = dict(os.environ, JAX_PLATFORMS="cpu",
                        XLA_FLAGS=os.environ.get(
                            "XLA_FLAGS",
                            "--xla_force_host_platform_device_count=8"))
            proc = subprocess.run(
                [sys.executable, "-m", "volcano_tpu.chaos",
                 "--smoke", "--meshloss"],
                capture_output=True, text=True,
                cwd=os.path.dirname(os.path.abspath(__file__)),
                timeout=float(os.environ.get("BENCH_MESHLOSS_TIMEOUT",
                                             900)), env=menv)
            _emit_child_stderr("meshloss", proc.stderr)
            if proc.returncode in (0, 1):
                mrpt = json.loads(proc.stdout)
                legs = mrpt.get("legs") or {}
                loss = legs.get("loss_scan") or {}
                flap = legs.get("flap_scan") or {}
                robustness_block = dict(robustness_block or {})
                robustness_block["meshloss"] = {
                    "ok": mrpt.get("ok"),
                    "failures": mrpt.get("failures"),
                    "width_sequence": loss.get("width_sequence"),
                    "decisions_equal_clean":
                        loss.get("decisions_equal_clean"),
                    "mesh_shrinks": loss.get("mesh_shrinks"),
                    "mesh_regrows": loss.get("mesh_regrows"),
                    "post_shrink_resharding_copies":
                        loss.get("post_shrink_resharding_copies"),
                    "remesh_ms_p50": loss.get("remesh_ms_p50"),
                    "post_shrink_steady_ms_p50":
                        loss.get("post_shrink_steady_ms_p50"),
                    "flap_remesh_events": flap.get("remesh_events"),
                    "flap_probation_interval":
                        flap.get("probation_interval_after"),
                }
        except Exception as e:  # noqa: BLE001 — fail-soft contract
            print("bench: meshloss block failed: %s: %s"
                  % (type(e).__name__, e), file=sys.stderr)

    # ---- crash-consistent restart block (volcano_tpu/chaos/restart) ------
    # The restart probe: process_kill at all three phases (pre-dispatch /
    # in-flight / post-drain), each restored from the crash-consistent
    # checkpoint (runtime/checkpoint.py), verified decision-identical to
    # the uninterrupted run — plus a corrupt-checkpoint leg that must land
    # on the fallback ladder rung and still finish identical. The record
    # carries restore latency and warm-restart quality (cycles until the
    # upload path is a delta again). BENCH_SKIP_RESTART=1 skips; a probe
    # failure records null, never kills the bench.
    restart_block = None
    if not os.environ.get("BENCH_SKIP_RESTART"):
        try:
            from volcano_tpu.chaos import run_restart_probe
            rrpt = run_restart_probe(
                seed=int(os.environ.get("BENCH_CHAOS_SEED", 7)), cycles=8)
            restart_block = {
                "decisions_equal_clean": rrpt["decisions_equal_clean"],
                "kills": rrpt["kills"],
                "kill_schedule_sha": rrpt["kill_schedule_sha"],
                "restore_outcomes": rrpt["restore_outcomes"],
                "restore_ms_p50": rrpt["restore_ms_p50"],
                "cycles_to_steady": rrpt["cycles_to_steady"],
                "warm_refuses": rrpt["warm_refuses"],
                "corrupt_decisions_equal_clean":
                    rrpt["corrupt"]["decisions_equal_clean"],
                "corrupt_fallbacks_visible":
                    rrpt["corrupt"]["fallbacks_visible"],
            }
        except Exception as e:  # noqa: BLE001 — fail-soft contract
            print("bench: restart block failed: %s: %s"
                  % (type(e).__name__, e), file=sys.stderr)
            restart_block = None

    # ---- warm-standby failover block (volcano_tpu/chaos/failover) --------
    # The HA probe: leader_kill at all three phases, each promoting the
    # warm standby fed by checkpoint streaming (runtime/replication.py)
    # behind a fresh lease-generation fence, verified decision-identical
    # to the uninterrupted run at a cost of at most one cycle — plus the
    # split-brain leg whose deposed-leader writes must be fence-rejected
    # and the partition leg that promotes from stale replicated state and
    # must still converge. BENCH_SKIP_FAILOVER=1 skips; a probe failure
    # records null, never kills the bench.
    failover_block = None
    if not os.environ.get("BENCH_SKIP_FAILOVER"):
        try:
            from volcano_tpu.chaos import run_failover_probe
            frpt = run_failover_probe(
                seed=int(os.environ.get("BENCH_CHAOS_SEED", 7)), cycles=8)
            fsb = frpt.get("split_brain") or {}
            failover_block = {
                "decisions_equal_clean": frpt["decisions_equal_clean"],
                "calm_equal_clean": frpt["calm_equal_clean"],
                "kills": frpt["kills"],
                "kill_schedule_sha": frpt["kill_schedule_sha"],
                "promote_ms_p50": frpt["promote_ms_p50"],
                "warm_promotions": frpt["warm_promotions"],
                "cycles_lost": frpt["cycles_lost"],
                "cycles_to_steady": frpt["cycles_to_steady"],
                "split_brain_decisions_equal_clean":
                    fsb.get("decisions_equal_clean"),
                "fenced_writes_rejected":
                    fsb.get("fenced_writes_rejected"),
                "duplicate_binds": fsb.get("duplicate_binds"),
                "partition_decisions_equal_clean":
                    (frpt.get("partition") or {}).get(
                        "decisions_equal_clean"),
            }
        except Exception as e:  # noqa: BLE001 — fail-soft contract
            print("bench: failover block failed: %s: %s"
                  % (type(e).__name__, e), file=sys.stderr)
            failover_block = None

    # ---- multichip sharded-cycle block (volcano_tpu/parallel) ------------
    # The node-axis sharded execution mode (ISSUE 7) measured per device
    # count against the unsharded oracle on identical churned workloads:
    # steady-cycle p50, decision-sha equality, and the live
    # resharding-copy counter (the zero-copy out==in contract). Runs in a
    # subprocess on the CPU backend with 8 forced virtual devices so a
    # GSPMD compile failure (or a poisoned TPU parent) can't take the
    # record down; BENCH_SKIP_MULTICHIP=1 skips, failure records null.
    multichip_block = None
    if not os.environ.get("BENCH_SKIP_MULTICHIP"):
        try:
            menv = dict(os.environ, JAX_PLATFORMS="cpu",
                        XLA_FLAGS=os.environ.get(
                            "XLA_FLAGS",
                            "--xla_force_host_platform_device_count=8"))
            proc = subprocess.run(
                [sys.executable, "-m", "volcano_tpu.parallel", "--bench"],
                capture_output=True, text=True,
                cwd=os.path.dirname(os.path.abspath(__file__)),
                timeout=float(os.environ.get("BENCH_MULTICHIP_TIMEOUT",
                                             600)), env=menv)
            _emit_child_stderr("multichip", proc.stderr)
            if proc.returncode in (0, 1):
                multichip_block = json.loads(proc.stdout)
                multichip_block["clean"] = proc.returncode == 0
        except Exception as e:  # noqa: BLE001 — fail-soft contract
            print("bench: multichip block failed: %s: %s"
                  % (type(e).__name__, e), file=sys.stderr)
            multichip_block = None

    # ---- graphcheck static-analysis status (volcano_tpu/analysis) --------
    # The perf trajectory carries the static-analysis state alongside the
    # decision fingerprints: a record with graphcheck_clean=false (or
    # null = the pass itself failed) flags that these numbers were
    # measured on a cycle violating a framework invariant. Subprocess on
    # the CPU backend so a TPU-poisoned parent process can't block it;
    # fail-soft like everything else in this script.
    graphcheck_clean = graphcheck_sha = grpt = None
    if not os.environ.get("BENCH_SKIP_GRAPHCHECK"):
        import tempfile
        rpt = os.path.join(tempfile.gettempdir(), "graphcheck_bench.json")
        try:
            genv = dict(os.environ, JAX_PLATFORMS="cpu")
            proc = subprocess.run(
                [sys.executable, "-m", "volcano_tpu.analysis",
                 "--json", rpt],
                capture_output=True, text=True,
                cwd=os.path.dirname(os.path.abspath(__file__)),
                timeout=float(os.environ.get("BENCH_GRAPHCHECK_TIMEOUT",
                                             300)), env=genv)
            _emit_child_stderr("graphcheck", proc.stderr)
            if proc.returncode in (0, 1):
                with open(rpt) as f:
                    grpt = json.load(f)
                graphcheck_clean = bool(grpt["clean"])
                graphcheck_sha = grpt["report_sha256"]
        except Exception:  # noqa: BLE001 — the record ships regardless
            grpt = None

    # ---- static cost model (graphcheck `cost` family, ISSUE 17) ----------
    # The north-star scoreboard: static peak-live HBM, per-cycle collective
    # bytes, and their 100k-node / 1M-task projections travel with every
    # bench record so a perf PR that regresses the static footprint is
    # visible even when wall-clock numbers hold. Reuses the graphcheck
    # subprocess's report when it ran (the full pass includes `cost`);
    # otherwise runs the family alone. Fail-soft: BENCH_SKIP_COST=1 (or
    # any failure) records null.
    cost_block = None
    if not os.environ.get("BENCH_SKIP_COST"):
        import tempfile
        try:
            from volcano_tpu.analysis.costmodel import bench_cost_meta
            cost_block = bench_cost_meta((grpt or {}).get("meta"))
            if cost_block is None:
                crpt = os.path.join(tempfile.gettempdir(),
                                    "graphcheck_cost_bench.json")
                genv = dict(os.environ, JAX_PLATFORMS="cpu")
                proc = subprocess.run(
                    [sys.executable, "-m", "volcano_tpu.analysis",
                     "--fast", "--families", "cost", "--json", crpt],
                    capture_output=True, text=True,
                    cwd=os.path.dirname(os.path.abspath(__file__)),
                    timeout=float(os.environ.get("BENCH_COST_TIMEOUT",
                                                 300)), env=genv)
                _emit_child_stderr("cost", proc.stderr)
                if proc.returncode in (0, 1):
                    with open(crpt) as f:
                        cost_block = bench_cost_meta(
                            (json.load(f) or {}).get("meta"))
        except Exception as e:  # noqa: BLE001 — fail-soft contract
            print("bench: cost block failed: %s: %s"
                  % (type(e).__name__, e), file=sys.stderr)
            cost_block = None

    # ---- cycle latency breakdown (volcano_tpu/telemetry/spans) -----------
    # The steady loop's per-phase span rings + pipeline occupancy, and the
    # headline host_overhead_ratio = steady_cycle_total_p50 / sub_tpu_ms —
    # the number the deep-async-pipeline item must drive toward ~1.2.
    # Fail-soft: BENCH_SKIP_LATENCY=1 (or any failure) records null.
    latency_block = None
    if not os.environ.get("BENCH_SKIP_LATENCY"):
        try:
            if latency_phases:
                latency_block = {
                    "phases": {ph: {q: st[q] for q in
                                    ("count", "p50", "p95", "p99")}
                               for ph, st in latency_phases.items()},
                }
                if latency_occ is not None:
                    latency_block["pipeline_overlap_fraction"] = \
                        latency_occ.get("pipeline_overlap_fraction")
                    latency_block["bubble_ms"] = latency_occ.get("bubble_ms")
                    latency_block["device_windows"] = \
                        latency_occ.get("windows")
                    # ISSUE 13: the occupancy backend tag and overlap per
                    # dispatch depth — the headline loop's depth-1
                    # windows plus the depth-k leg's (the pack-thread
                    # overlap shows up here: host work inside in-flight
                    # windows even while the main thread blocks)
                    latency_block["backend"] = latency_occ.get("backend")
                    per_depth = {"1": latency_occ.get(
                        "pipeline_overlap_fraction")}
                    if latency_depth_occ is not None:
                        per_depth.update(latency_depth_occ["per_depth"])
                    latency_block["per_depth_overlap"] = per_depth
                latency_block["pipeline_depth"] = bench_depth
                latency_block["depth_leg"] = latency_depth_occ
                # changed-rows drain vs the full decision readback — the
                # O(churn) evidence (delta must sit well under full)
                latency_block["drain_readback_bytes"] = \
                    steady_readback_delta
                latency_block["drain_readback_bytes_full"] = \
                    steady_readback_full
                if steady_total_p50 is not None and sub_speedup is not None \
                        and stpu_ms:
                    latency_block["host_overhead_ratio"] = round(
                        steady_total_p50 / stpu_ms, 2)
        except Exception as e:  # noqa: BLE001 — fail-soft contract
            print("bench: latency block failed: %s: %s"
                  % (type(e).__name__, e), file=sys.stderr)
            latency_block = None

    # ---- scheduling-quality scenario block (volcano_tpu/scenarios) -------
    # A short seeded trace-replay scenario scored end to end: the record
    # carries WHAT the scheduler decided (DRF share error, utilization,
    # makespan, wait quantiles) next to how fast it decided it, so a perf
    # win that quietly worsens placement quality shows up in the same
    # trajectory. Drift spot-checks pin the compiled path to the CPU
    # oracle inside the bench too. BENCH_SKIP_SCENARIOS=1 skips; failure
    # records null, never kills the bench.
    scenario_block = None
    if not os.environ.get("BENCH_SKIP_SCENARIOS"):
        try:
            from volcano_tpu.scenarios import get_scenario, run_scenario
            sres = run_scenario(
                get_scenario("trace-replay"),
                cycles=int(os.environ.get("BENCH_SCENARIO_CYCLES", 16)),
                observe=False, drift_check_every=4)
            scard = sres.scorecard
            scenario_block = {
                "scenario": scard.scenario,
                "seed": scard.seed,
                "cycles": scard.cycles,
                "jobs_completed": scard.jobs_completed,
                "makespan_cycles": scard.makespan_cycles,
                "drf_share_error": scard.drf_share_error,
                "node_utilization": scard.node_utilization,
                "preemption_churn_total": scard.preemption_churn_total,
                "wait_cycles": scard.wait_cycles,
                "event_sha": scard.event_sha,
                "decisions_sha": scard.decisions_sha,
                "drift_checks": scard.drift_checks,
                "drift_failures": scard.drift_failures,
            }
        except Exception as e:  # noqa: BLE001 — fail-soft contract
            print("bench: scenarios block failed: %s: %s"
                  % (type(e).__name__, e), file=sys.stderr)
            scenario_block = None

    # ---- multi-tenant fleet serving block (volcano_tpu/fleet) ------------
    # The fleet throughput claim measured end to end: N same-shape tenants
    # served through ONE batched vmapped dispatch per cycle (fleet/pool
    # shape buckets), warmed past the compile, then timed over a churned
    # multi-cycle run. The record carries per-cycle p50/p99 wall latency
    # and the headline tenants-served-per-second at the p99 cycle — the
    # number the batching transparency layer exists to move.
    # BENCH_SKIP_FLEET=1 skips; a failure records null, never kills the
    # bench.
    fleet_block = None
    if not os.environ.get("BENCH_SKIP_FLEET"):
        try:
            from volcano_tpu.chaos.probe import _PROBE_CONF as _fconf
            from volcano_tpu.chaos.probe import _churn as _fchurn
            from volcano_tpu.chaos.probe import _small_cluster as _fsmall
            from volcano_tpu.fleet import FleetScheduler
            from volcano_tpu.framework import parse_conf as _fparse
            from volcano_tpu.runtime.fake_cluster import FakeCluster as _FCl
            f_tenants = int(os.environ.get("BENCH_FLEET_TENANTS", 4))
            f_cycles = int(os.environ.get("BENCH_FLEET_CYCLES", 8))
            flt = FleetScheduler(conf=_fparse(_fconf))
            fcls = {}
            for i in range(f_tenants):
                name = f"bench-t{i}"
                fcls[name] = _FCl(_fsmall(n_nodes=6, n_jobs=8,
                                          tasks_per_job=3))
                flt.admit(name, fcls[name], conf=_fparse(_fconf))
            for w in range(2):              # warm: compile + first deltas
                flt.run_once(now=1000.0 + w)
                for n in flt.tenants:
                    _fchurn(fcls[n], w)
            f_times = []
            for c in range(f_cycles):
                t0 = time.time()
                flt.run_once(now=1002.0 + c)
                f_times.append(time.time() - t0)
                for n in flt.tenants:
                    _fchurn(fcls[n], 2 + c)
            f_times.sort()
            f_p50 = f_times[len(f_times) // 2]
            f_p99 = f_times[min(len(f_times) - 1,
                                int(len(f_times) * 0.99))]
            fleet_block = {
                "tenants": f_tenants,
                "cycles": f_cycles,
                "buckets": len(flt.pool.buckets),
                "cycle_ms_p50": round(f_p50 * 1000, 1),
                "cycle_ms_p99": round(f_p99 * 1000, 1),
                "tenants_per_s_at_p99": round(f_tenants / f_p99, 1),
                "degraded_tenants": sum(
                    1 for t in flt.tenants.values()
                    if t.degradation_level),
            }
        except Exception as e:  # noqa: BLE001 — fail-soft contract
            print("bench: fleet block failed: %s: %s"
                  % (type(e).__name__, e), file=sys.stderr)
            fleet_block = None

    # ---- wavefront placement block (ISSUE 16) ----------------------------
    # W tasks per device sweep with the order-preserving in-graph conflict
    # commit: steady cycle time at W in {1, 4, 8, 16} on the churn
    # workload under spread scoring (least_allocated + balanced — binpack
    # funnels every slot onto one node and collapses the wave), with
    # decision-sha equality vs the W=1 sequential sweep at EVERY width
    # (the tentpole claim, re-proved where it is priced), the telemetry
    # wave_commit_ratio at the winning width, and the winning width's
    # speedup fed into the regression guard below so a change that erodes
    # the batched-sweep win shows in the trajectory.
    # BENCH_SKIP_WAVEFRONT=1 skips; a failure records null.
    wavefront_block = None
    if not os.environ.get("BENCH_SKIP_WAVEFRONT"):
        try:
            import dataclasses as _dcw
            import hashlib as _hlw
            from volcano_tpu.ops.fused_io import make_fused_cycle as _mfcw
            wsnap, wextras, wcfg0 = _build(
                int(os.environ.get("BENCH_WAVE_NODES", 2048)),
                int(os.environ.get("BENCH_WAVE_JOBS", 1280)),
                tasks_per_job,
                dict(cfg_kwargs, binpack_weight=0.0,
                     least_allocated_weight=1.0, balanced_weight=1.0))
            widths = {}
            ref_sha = None
            sha_equal = True
            for ww in (1, 4, 8, 16):
                wcyc = make_allocate_cycle(
                    _dcw.replace(wcfg0, wave_width=ww))
                wfnp, wfuse = _mfcw(wcyc, (wsnap, wextras))
                wpd = np.asarray(wfnp(*wfuse((wsnap, wextras))))  # compile
                wts = []
                for _ in range(max(3, min(reps, 5))):
                    t0 = time.time()
                    np.asarray(wfnp(*wfuse((wsnap, wextras))))
                    wts.append((time.time() - t0) * 1000)
                wts.sort()
                # the packed readback IS the decision block (telemetry
                # off), so its bytes are the decision fingerprint
                wsha = _hlw.sha256(wpd.tobytes()).hexdigest()[:16]
                if ww == 1:
                    ref_sha = wsha
                elif wsha != ref_sha:
                    sha_equal = False
                widths[ww] = {"cycle_ms": round(wts[0], 1),
                              "cycle_ms_p50": round(
                                  wts[len(wts) // 2], 1),
                              "decisions_sha256": wsha}
            best_w = min((w for w in widths if w != 1),
                         key=lambda w: widths[w]["cycle_ms"])
            wave_speedup = round(
                widths[1]["cycle_ms"] / widths[best_w]["cycle_ms"], 2)
            # commit ratio at the winning width from a telemetry build on
            # the same snapshot (counters are oracle-pinned at sub-scale
            # by tests/test_wavefront.py; here they price the workload)
            from volcano_tpu.telemetry import (
                unpack_cycle_telemetry as _uctw)
            wtres = jax.jit(make_allocate_cycle(_dcw.replace(
                wcfg0, wave_width=best_w, telemetry=True)))(wsnap, wextras)
            wtel = _uctw(np.asarray(wtres.telemetry.packed()),
                         int(np.asarray(wsnap.nodes.idle).shape[1]))
            wcommits = int(wtel["wave_commits"])
            wreplays = int(wtel["wave_replays"])
            wavefront_block = {
                "widths": {str(k): v for k, v in widths.items()},
                "best_width": best_w,
                "speedup_vs_sequential": wave_speedup,
                "decisions_sha_equal_all_widths": sha_equal,
                "wave_commit_ratio": round(
                    wcommits / max(wcommits + wreplays, 1), 4),
                "wave_truncations": int(wtel["wave_truncations"]),
                "wave_replays": wreplays,
                "waves": int(wtel["waves"]),
            }
        except Exception as e:  # noqa: BLE001 — fail-soft contract
            print("bench: wavefront block failed: %s: %s"
                  % (type(e).__name__, e), file=sys.stderr)
            wavefront_block = None

    # ---- perf regression guard vs the last same-backend BENCH record -----
    regression_block = None
    if not os.environ.get("BENCH_SKIP_REGRESSION"):
        try:
            regression_block = _regression_guard(
                force_cpu, steady_ms,
                stpu_ms if sub_speedup is not None else None,
                steady_total_ms=steady_total_p50,
                quality={
                    "scenario_drf_share_error":
                        (scenario_block or {}).get("drf_share_error"),
                    "scenario_node_utilization":
                        (scenario_block or {}).get("node_utilization"),
                    "failover_promote_ms_p50":
                        (failover_block or {}).get("promote_ms_p50"),
                    "fleet_cycle_ms_p99":
                        (fleet_block or {}).get("cycle_ms_p99"),
                    "fleet_tenants_per_s":
                        (fleet_block or {}).get("tenants_per_s_at_p99"),
                    "wavefront_speedup":
                        (wavefront_block or {}).get("speedup_vs_sequential"),
                    "cost_peak_live_bytes":
                        (cost_block or {}).get("peak_live_bytes"),
                    "cost_collective_bytes":
                        (cost_block or {}).get(
                            "collective_bytes_per_cycle"),
                    "remesh_ms_p50":
                        ((robustness_block or {}).get("meshloss")
                         or {}).get("remesh_ms_p50"),
                    "post_shrink_steady_ms_p50":
                        ((robustness_block or {}).get("meshloss")
                         or {}).get("post_shrink_steady_ms_p50"),
                })
        except Exception as e:  # noqa: BLE001 — fail-soft contract
            print("bench: regression guard failed: %s: %s"
                  % (type(e).__name__, e), file=sys.stderr)
            regression_block = None

    out = {
        "metric": f"schedule_cycle_ms_{n_nodes}nodes_{n_tasks}tasks",
        "value": round(dev_ms, 3),
        "unit": "ms",
        "vs_baseline": round(cpu_ms / dev_ms, 2),
        "graphcheck_clean": graphcheck_clean,
        "graphcheck_sha256": graphcheck_sha,
        "telemetry": telemetry_block,
        "robustness": robustness_block,
        "restart": restart_block,
        "failover": failover_block,
        "multichip": multichip_block,
        "latency_breakdown": latency_block,
        "scenarios": scenario_block,
        "fleet": fleet_block,
        "wavefront": wavefront_block,
        "cost": cost_block,
        "regression": regression_block,
    }
    if force_cpu:
        out["tpu_unavailable"] = True
        out["note"] = ("TPU backend unreachable (%s); compiled-cycle timing "
                       "on the CPU backend at reduced scale" %
                       os.environ.get("BENCH_CPU_REASON", "probe failed"))
    extra = {
        # degraded-run visibility for trajectory tooling: the flag used to
        # survive only in the stdout line / tail text, so the parsed block
        # could not distinguish a TPU run from a CPU-fallback run
        "tpu_unavailable": bool(force_cpu),
        "cpu_ms": round(cpu_ms, 1),
        "cpu_source": cpu_source,
        "compile_s": round(compile_s, 1),
        "placed_tasks": placed,
        "full_session_ms": (round(full_session_ms, 1)
                            if full_session_ms is not None else None),
        "session_binds": (session_binds
                          if full_session_ms is not None else None),
        "sidecar_cycle_ms": (round(sidecar_ms, 1)
                             if sidecar_ms is not None else None),
        "sidecar_steady_ms": (round(sidecar_steady_ms, 1)
                              if sidecar_steady_ms is not None else None),
        "sidecar_steady_kind": sidecar_steady_kind,
        "sidecar_upload_bytes_delta": sidecar_upload_delta,
        "steady_loop_ms": (round(steady_ms, 1)
                           if steady_ms is not None else None),
        "steady_loop_p50_ms": (round(steady_p50, 1)
                               if steady_p50 is not None else None),
        "steady_loop_p95_ms": (round(steady_p95, 1)
                               if steady_p95 is not None else None),
        "steady_cycle_total_p50_ms": (round(steady_total_p50, 1)
                                      if steady_total_p50 is not None
                                      else None),
        "steady_delta_cycle_fraction": steady_delta_fraction,
        "steady_upload_bytes_full": steady_upload_full,
        "steady_upload_bytes_delta": steady_upload_delta,
        # depth-k loop observability: the dispatch depth the steady loop
        # ran at and the changed-rows drain vs full-readback bytes
        "steady_pipeline_depth": bench_depth,
        "steady_readback_bytes_delta": steady_readback_delta,
        "steady_readback_bytes_full": steady_readback_full,
        "steady_loop_binds": steady_binds,
        "steady_loop_incremental": loop_incremental,
        "drf_cycle_ms": (round(drf_ms, 1) if drf_ms is not None else None),
        "drf_backend": drf_backend,
        "drf_placed": drf_placed,
        "drf_decisions_equal_cpu_subscale": drf_equal_sub,
        "drf_decisions_equal_cpu_full_scale": drf_equal_full,
        "drf_sha256": drf_sha,
        "preempt_cycle_ms": (round(preempt_ms, 1)
                             if preempt_ms is not None else None),
        "preempt_backend": preempt_backend,
        "preempt_victims": preempt_victims,
        "preempt_pipelined": preempt_pipelined,
        "preempt_invariants_ok": preempt_invariants_ok,
        "preempt_decisions_equal_cpu_subscale": preempt_equal_sub,
        "preempt_decisions_equal_cpu_full_scale": preempt_equal_full,
        "preempt_sha256": preempt_sha,
        "preempt_record_stale": preempt_record_stale,
        "preempt_adv_record_stale": preempt_adv_record_stale,
        "drf_record_stale": drf_record_stale,
        "preempt_adversarial_ms": (round(preempt_adv_ms, 1)
                                   if preempt_adv_ms is not None else None),
        "preempt_adversarial_victims": preempt_adv_victims,
        "preempt_adversarial_pipelined": preempt_adv_pipelined,
        "preempt_adversarial_equal_cpu_full_scale": preempt_adv_equal,
        "affinity_cycle_ms": (round(affinity_ms, 1)
                              if affinity_ms is not None else None),
        "affinity_backend": affinity_backend,
        "affinity_placed": affinity_placed,
        "affinity_decisions_equal_cpu_1024n": affinity_equal_sub,
        "affinity_decisions_equal_cpu_full_scale": affinity_equal_full,
        "affinity_sha256": affinity_sha,
        "affinity_record_stale": affinity_record_stale,
        "decisions_equal_cpu_full_scale": equal_full,
        "decisions_sha256": decisions_sha,
        "decisions_equal_cpu_1024n_10240t": equal_sub,
        "speedup_1024n_10240t": sub_speedup,
        "sub_tpu_ms": round(stpu_ms, 3) if sub_speedup is not None else None,
        "sub_cpu_ms": round(scpu_ms, 1) if sub_speedup is not None else None,
        # scenario quality numbers in the parsed block so future runs'
        # regression guard has a same-backend quality baseline to ratio
        # against (see _regression_guard)
        "scenario_drf_share_error":
            (scenario_block or {}).get("drf_share_error"),
        "scenario_node_utilization":
            (scenario_block or {}).get("node_utilization"),
        "scenario_event_sha": (scenario_block or {}).get("event_sha"),
        # restart-quality numbers in the parsed block: restore latency and
        # warm-restart health over the bench trajectory
        "restart_restore_ms_p50":
            (restart_block or {}).get("restore_ms_p50"),
        "restart_decisions_equal_clean":
            (restart_block or {}).get("decisions_equal_clean"),
        "restart_cycles_to_steady":
            (restart_block or {}).get("cycles_to_steady"),
        # failover-quality numbers in the parsed block: promotion latency
        # and handoff cost over the bench trajectory, baselines for the
        # regression guard
        "failover_promote_ms_p50":
            (failover_block or {}).get("promote_ms_p50"),
        "failover_cycles_lost":
            (failover_block or {}).get("cycles_lost"),
        "failover_decisions_equal_clean":
            (failover_block or {}).get("decisions_equal_clean"),
        "failover_fenced_writes_rejected":
            (failover_block or {}).get("fenced_writes_rejected"),
        # fleet-serving numbers in the parsed block: batched-cycle
        # latency and tenants/sec, baselines for the regression guard
        "fleet_cycle_ms_p99": (fleet_block or {}).get("cycle_ms_p99"),
        "fleet_tenants_per_s":
            (fleet_block or {}).get("tenants_per_s_at_p99"),
        "fleet_buckets": (fleet_block or {}).get("buckets"),
        # wavefront numbers in the parsed block: the winning width's
        # speedup is the regression-guard baseline for future runs
        "wavefront_speedup":
            (wavefront_block or {}).get("speedup_vs_sequential"),
        "wavefront_best_width": (wavefront_block or {}).get("best_width"),
        "wavefront_sha_equal_all_widths":
            (wavefront_block or {}).get("decisions_sha_equal_all_widths"),
        "wave_commit_ratio":
            (wavefront_block or {}).get("wave_commit_ratio"),
        # elastic-mesh numbers in the parsed block: remesh latency and
        # post-shrink steady cycle, baselines for the regression guard
        "remesh_ms_p50":
            ((robustness_block or {}).get("meshloss")
             or {}).get("remesh_ms_p50"),
        "post_shrink_steady_ms_p50":
            ((robustness_block or {}).get("meshloss")
             or {}).get("post_shrink_steady_ms_p50"),
        "meshloss_decisions_equal_clean":
            ((robustness_block or {}).get("meshloss")
             or {}).get("decisions_equal_clean"),
        "meshloss_flap_remesh_events":
            ((robustness_block or {}).get("meshloss")
             or {}).get("flap_remesh_events"),
        # static cost-model numbers in the parsed block: the regression
        # guard ratios future runs against these same-backend baselines
        "cost_peak_live_bytes": (cost_block or {}).get("peak_live_bytes"),
        "cost_collective_bytes":
            (cost_block or {}).get("collective_bytes_per_cycle"),
        "cost_peak_live_northstar_bytes":
            ((cost_block or {}).get("northstar") or {}).get(
                "peak_live_bytes"),
        "cost_collective_northstar_bytes":
            ((cost_block or {}).get("northstar") or {}).get(
                "collective_bytes"),
        "cost_northstar_within_budget":
            ((cost_block or {}).get("northstar") or {}).get(
                "within_budget"),
        "device": str(jax.devices()[0]),
    }
    print(json.dumps(out))
    print(json.dumps(extra), file=sys.stderr)


def main():
    force_cpu = bool(os.environ.get("BENCH_FORCE_CPU"))
    if not force_cpu:
        timeout_s = float(os.environ.get("BENCH_PROBE_TIMEOUT", 150))
        retries = int(os.environ.get("BENCH_PROBE_RETRIES", 3))
        alive, backoff = False, 5.0
        for attempt in range(max(1, retries)):
            if _tpu_alive(timeout_s):
                alive = True
                break
            if attempt + 1 < retries:
                # capped backoff: a transient tunnel blip must not blind
                # the whole record (VERDICT r5 item 1)
                print("bench: TPU probe attempt %d/%d failed; retrying "
                      "in %gs" % (attempt + 1, retries, backoff),
                      file=sys.stderr)
                time.sleep(backoff)
                backoff = min(backoff * 3, 60.0)
        if not alive:
            _reexec_cpu("backend probe failed/timed out after %d attempts "
                        "x %gs" % (retries, timeout_s))
    try:
        _run(force_cpu)
    except Exception as e:  # noqa: BLE001 — fail-soft contract
        if not force_cpu:
            # a mid-run TPU failure (flaky tunnel): clean-process retry on CPU
            _reexec_cpu("mid-run failure: %s: %s" % (type(e).__name__, e))
        # even the CPU path failed — emit a degraded-but-valid record
        print(json.dumps({
            "metric": "schedule_cycle_ms_error",
            "value": -1,
            "unit": "ms",
            "vs_baseline": 0,
            "tpu_unavailable": True,
            "note": "bench failed on both TPU and CPU paths: %s: %s"
                    % (type(e).__name__, e),
        }))
        import traceback
        traceback.print_exc(file=sys.stderr)


if __name__ == "__main__":
    main()
