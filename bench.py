#!/usr/bin/env python
"""Benchmark: compiled TPU scheduling cycle vs the sequential CPU reference.

Measures the allocate pass (predicates + binpack/spread scoring + gang
commit) on a synthetic snapshot shaped like BASELINE.md config #2
(1k nodes / 10k tasks), and reports ONE JSON line:

    {"metric": ..., "value": <tpu cycle ms>, "unit": "ms", "vs_baseline": <speedup>}

vs_baseline is the speedup over the CPU path on the same snapshot with
verified-identical bind decisions. The reference publishes no numbers
(BASELINE.md) and no Go toolchain exists in this image, so the CPU baseline
is runtime/cpu_reference.py — the same sequential predicate->score->argmax
loop the Go scheduler runs per task (allocate.go:43-281), in vectorized
numpy (one vector op over the node axis per predicate/score term, i.e. at
least as fast as the Go loop's per-node work).

Env knobs: BENCH_NODES, BENCH_JOBS, BENCH_TASKS_PER_JOB, BENCH_REPS,
BENCH_SKIP_CPU=1 (report cached baseline ratio instead of measuring).
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))


def main():
    n_nodes = int(os.environ.get("BENCH_NODES", 1024))
    n_jobs = int(os.environ.get("BENCH_JOBS", 640))
    tasks_per_job = int(os.environ.get("BENCH_TASKS_PER_JOB", 16))
    reps = int(os.environ.get("BENCH_REPS", 3))

    import jax
    # persistent compile cache: the cycle compiles once per shape bucket and
    # every later bench/driver run reuses it
    cache_dir = os.environ.get("JAX_COMPILATION_CACHE_DIR",
                               "/tmp/volcano_tpu_jax_cache")
    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:
        pass
    from __graft_entry__ import _synthetic_cluster
    from volcano_tpu.arrays import pack
    from volcano_tpu.ops.allocate_scan import (AllocateConfig, AllocateExtras,
                                               make_allocate_cycle)
    from volcano_tpu.runtime.cpu_reference import allocate_cpu

    ci = _synthetic_cluster(n_nodes=n_nodes, n_jobs=n_jobs,
                            tasks_per_job=tasks_per_job)
    snap, _maps = pack(ci)
    extras = AllocateExtras.neutral(snap)
    cfg = AllocateConfig(binpack_weight=1.0, least_allocated_weight=0.0,
                         balanced_weight=0.0, taint_prefer_weight=0.0)

    fn = jax.jit(make_allocate_cycle(cfg))
    t0 = time.time()
    result = fn(snap, extras)
    result.task_node.block_until_ready()
    compile_s = time.time() - t0

    times = []
    for _ in range(reps):
        t0 = time.time()
        result = fn(snap, extras)
        result.task_node.block_until_ready()
        times.append(time.time() - t0)
    tpu_ms = min(times) * 1000

    n_tasks = n_jobs * tasks_per_job
    placed = int(np.asarray(result.task_mode > 0).sum())

    if os.environ.get("BENCH_SKIP_CPU"):
        cpu_ms = float(os.environ.get("BENCH_CPU_MS", 0)) or tpu_ms
        equal = None
    else:
        t0 = time.time()
        cpu = allocate_cpu(snap, extras, cfg)
        cpu_ms = (time.time() - t0) * 1000
        equal = bool(
            np.array_equal(np.asarray(result.task_node), cpu["task_node"])
            and np.array_equal(np.asarray(result.task_mode), cpu["task_mode"]))

    out = {
        "metric": f"schedule_cycle_ms_{n_nodes}nodes_{n_tasks}tasks",
        "value": round(tpu_ms, 3),
        "unit": "ms",
        "vs_baseline": round(cpu_ms / tpu_ms, 2),
    }
    extra = {
        "cpu_ms": round(cpu_ms, 1),
        "compile_s": round(compile_s, 1),
        "placed_tasks": placed,
        "decisions_equal_cpu": equal,
        "device": str(jax.devices()[0]),
    }
    print(json.dumps(out))
    print(json.dumps(extra), file=sys.stderr)


if __name__ == "__main__":
    main()
