"""Session: one scheduling cycle's world state and compiled-pass composer.

Reference: pkg/scheduler/framework/session.go:38-468 (per-cycle snapshot +
plugin instances + Allocate/Pipeline/Evict ops) and framework.go:29-63
(OpenSession/CloseSession). Re-designed so the session's job is to
- pack the ClusterInfo snapshot into device arrays,
- query each plugin's kernel contributions (score weights, fairness arrays,
  gates, vetoes) and bake them into AllocateConfig/AllocateExtras,
- run the actions' compiled passes,
- and translate decision arrays back into bind/pipeline/evict intents
  (the Statement commit boundary, statement.go:377-395 — here the kernels
  already did commit/discard internally, so apply is a pure readout).
"""

from __future__ import annotations

import dataclasses
import time
from functools import lru_cache
from typing import Dict, List, Optional

import jax
import numpy as np

from ..api import ClusterInfo, TaskStatus
from ..arrays import pack
from ..ops.allocate_scan import (MODE_ALLOCATED, MODE_PIPELINED,
                                 AllocateConfig, AllocateExtras,
                                 AllocateResult, make_allocate_cycle)
from ..ops.backfill import make_backfill_pass
from ..ops.enqueue import EnqueueConfig, make_enqueue_pass
from ..telemetry import spans as _spans
from .conf import SchedulerConfiguration, parse_conf


@dataclasses.dataclass
class BindIntent:
    """A decided placement to flush to the cluster (cache.Bind seam,
    pkg/scheduler/cache/cache.go:549)."""

    task_uid: str
    job_uid: str
    node_name: str
    gpu_index: int = -1  # shared-GPU card (AddGPUIndexPatch, pod_info.go:154)


@dataclasses.dataclass
class EvictIntent:
    """A decided eviction (cache.Evict seam, cache.go:496)."""

    task_uid: str
    job_uid: str
    reason: str = ""


def _trace_span(name: str):
    """Host-side profiler span around a cycle entry point
    (jax.profiler.TraceAnnotation) — shows up in a collected device/host
    trace; a no-op context when the profiler is unavailable."""
    try:
        return jax.profiler.TraceAnnotation(name)
    except Exception:
        import contextlib
        return contextlib.nullcontext()


@lru_cache(maxsize=64)
def _allocate_fn(cfg: AllocateConfig):
    from ..telemetry import counted_jit
    return counted_jit(make_allocate_cycle(cfg), "allocate_cycle")


#: (cfg, input-shape signature) -> (jitted fused fn, fuse) — the 3-buffer
#: upload + one packed readback path (ops/fused_io); per-leaf uploads cost
#: ~tens of ms EACH over the axon tunnel, which dominated the full-session
#: time at scale
_FUSED_CACHE: Dict[tuple, tuple] = {}

#: same key -> DeltaKernel — the device-resident delta-upload path
#: (conf delta_uploads, default on). Kernels are stateless programs and
#: shared across sessions; the device residency itself (ResidentState)
#: lives on each Session so concurrent sessions never fight over buffers.
_DELTA_CACHE: Dict[tuple, object] = {}


def _fused_allocate(cfg: AllocateConfig, snap, extras):
    from ..ops.fused_io import fused_cycle_cached
    return fused_cycle_cached(make_allocate_cycle(cfg), (snap, extras),
                              _FUSED_CACHE, key_extra=cfg)


def _delta_allocate(cfg: AllocateConfig, snap, extras):
    from ..ops.fused_io import delta_cycle_cached
    return delta_cycle_cached(make_allocate_cycle(cfg), (snap, extras),
                              _DELTA_CACHE, key_extra=cfg)


#: same key + mesh identity -> ShardedDeltaKernel (conf ``sharding: true``):
#: node-axis residents over a device mesh, deltas routed to owning shards
#: (ops/fused_io.ShardedDeltaKernel via parallel/sharding)
_SHARDED_DELTA_CACHE: Dict[tuple, object] = {}


def _sharded_delta_allocate(cfg: AllocateConfig, snap, extras, mesh):
    from ..parallel.sharding import sharded_delta_allocate_cached
    return sharded_delta_allocate_cached(cfg, (snap, extras), mesh,
                                         _SHARDED_DELTA_CACHE)


@dataclasses.dataclass
class PendingAllocate:
    """An in-flight dispatched allocate cycle: the device handle of the
    packed decisions plus everything complete_allocate needs to decode and
    apply them. The one-deep pipeline (runtime/scheduler.py) holds exactly
    one of these across a run_once boundary."""

    packed: object              # device array (readback deferred)
    cfg: AllocateConfig
    T: int
    J: int
    R: int
    dispatch_ms: float = 0.0
    #: recovery context (delta path only): the DeltaKernel + ResidentState
    #: that dispatched this cycle and the exact argument tree it consumed —
    #: complete_allocate verifies the in-graph integrity digest against the
    #: mirror and, on mismatch or a failed readback, re-fuses from ``tree``
    #: (or falls to the CPU oracle when the accelerator is gone)
    kernel: object = None
    state: object = None
    tree: object = None
    #: span-clock time (telemetry.spans.now) when the dispatch returned —
    #: the in-flight device window opens here; the drain's readback closes
    #: it (telemetry.spans.device_window, the occupancy analyzer's input)
    dispatched_at: float = 0.0
    #: mesh width of the dispatch (1 when unsharded) — per-shard occupancy
    shards: int = 1
    #: device handle of the changed-rows readback tail (delta path with
    #: ``kernel.rb_cap``); None keeps the full-readback drain
    tail: object = None
    #: the HOST group buffers this dispatch packed (the mirror capture at
    #: dispatch time). Speculative cycles recover from these via
    #: ``kernel.host_tree`` — their ``tree`` may be refreshed in place by
    #: the time they drain — and the digest verify falls back to
    #: ``mirror_digest`` below when a newer dispatch advanced the live
    #: mirror past this capture.
    bufs: object = None
    #: host digest of ``bufs`` frozen at dispatch (speculative dispatches
    #: only; None = compare against the live state mirror as depth-1 does)
    mirror_digest: object = None
    #: the session's pack epoch when this cycle dispatched — a structural
    #: repack while the cycle was in flight reindexes the maps, and the
    #: drain must then apply with the capture below instead of live maps
    epoch: int = 0
    #: ring slot (monotonic dispatch sequence number) — per-slot device
    #: windows in the occupancy trace
    slot: int = 0
    #: effective pipeline depth the ring owner dispatched this cycle under
    #: (occupancy windows group per depth so a degenerate depth-1 overlap
    #: is distinguishable from a real depth-k one)
    depth: int = 1
    #: True when this cycle dispatched against the last-drained snapshot
    #: with predecessors still in flight (depth-k speculation)
    speculative: bool = False
    #: apply capture: (maps, task->job row copy) frozen at dispatch, so an
    #: epoch-stale but otherwise valid cycle still applies its decisions
    #: with the indexing it was computed under
    apply_ctx: object = None
    #: dispatch-time stats snapshot (extras_ms, upload bytes, ...) merged
    #: back at drain — at depth k the session's cycle state has been reset
    #: by later reopens before this cycle drains
    stats: object = None
    #: in-flight async dispatch handle (_AsyncDispatch); resolve() fills
    #: packed/tail/bufs/dispatch_ms before any readback
    future: object = None
    #: set by the ring owner: force the full-readback drain path (the
    #: decisions mirror chain was broken by a replay/recovery upstream)
    rb_full: bool = False
    #: ResidentState.dec_epoch at dispatch — the decisions-chain lineage.
    #: A mismatch at drain means an out-of-band dispatch (recovery,
    #: replay) rewired the device diff base after this cycle went out:
    #: drain full, and do NOT advance dec_mirror (the entry dispatched
    #: under the new lineage reseeds it from its own full readback)
    dec_epoch: int = 0


class _AsyncDispatch:
    """Minimal single-shot future for the double-buffered pack thread: one
    daemon thread runs the dispatch closure (diff/pack + device submit)
    while the main thread returns to event ingestion. Deliberately not a
    ThreadPoolExecutor — no pool state to leak across Scheduler restarts,
    and the one-behind ring resolves every handle before the next submit,
    so at most one worker is ever alive per scheduler."""

    __slots__ = ("_done", "_result", "_exc")

    def __init__(self, fn):
        import threading
        self._done = threading.Event()
        self._result = None
        self._exc = None

        def _run():
            try:
                self._result = fn()
            except BaseException as e:  # resurfaced on the main thread
                self._exc = e
            finally:
                self._done.set()

        threading.Thread(target=_run, name="volcano-pack",
                         daemon=True).start()

    def result(self):
        self._done.wait()
        if self._exc is not None:
            raise self._exc
        return self._result


@lru_cache(maxsize=64)
def _enqueue_fn(cfg: EnqueueConfig):
    from ..telemetry import counted_jit
    return counted_jit(make_enqueue_pass(cfg), "enqueue_pass")


@lru_cache(maxsize=2)
def _backfill_fn(telemetry: bool = False):
    from ..telemetry import counted_jit
    return counted_jit(make_backfill_pass(telemetry=telemetry),
                       "backfill_pass")


@lru_cache(maxsize=64)
def _preempt_fn(cfg):
    from ..ops.preempt import make_preempt_cycle
    from ..telemetry import counted_jit
    return counted_jit(make_preempt_cycle(cfg), "preempt_cycle")


class Session:
    def __init__(self, cluster: ClusterInfo,
                 conf: Optional[SchedulerConfiguration] = None,
                 now: Optional[float] = None,
                 plugin_overrides: Optional[Dict[str, object]] = None):
        self.cluster = cluster
        self.conf = conf or parse_conf()
        self.now = now if now is not None else time.time()
        self._build_plugins(plugin_overrides or {})

        # device residency for the delta-upload path: DeltaKernel ->
        # ResidentState. Survives reopen (that's the point: the fused
        # buffers stay on-device across cycles); a fresh Session starts
        # cold and pays one full upload.
        self._resident: Dict[object, object] = {}
        #: id(kernel) of residents that live on a device mesh — the set
        #: drop_sharded_residency() clears on an elastic mesh change
        self._sharded_ids: set = set()
        self._reset_cycle_state()
        self.repack()
        self._open_plugins()

    def _build_plugins(self, overrides: Dict[str, object]) -> None:
        from ..plugins.factory import build_plugin
        self.plugins = []
        for tier in self.conf.tiers:
            for opt in tier.plugins:
                if opt.name in overrides:
                    self.plugins.append(overrides[opt.name])
                else:
                    self.plugins.append(build_plugin(opt))

    def _open_plugins(self) -> None:
        from ..metrics import METRICS
        for p in self.plugins:
            t0 = time.time()
            p.on_session_open(self)
            METRICS.observe_plugin(p.name, "OnSessionOpen",
                                   time.time() - t0)

    def _reset_cycle_state(self) -> None:
        self.binds: List[BindIntent] = []
        self.evictions: List[EvictIntent] = []
        self.bind_errors: List[tuple] = []      # (task uid, node, error)
        self.pipelined: Dict[str, str] = {}     # task uid -> node name
        self.conditions: Dict[str, str] = {}    # job uid -> condition type
        self.phase_updates: Dict[str, object] = {}  # job uid -> new PG phase
        #: the subset of phase_updates that actually CHANGES the job's
        #: current PodGroup phase — the depth-k ring's invalidation
        #: predicate needs effective transitions, not the steady-state
        #: re-assertion of RUNNING every cycle
        self.phase_changes: Dict[str, object] = {}
        #: set by the ring owner after a drain applied intents; a second
        #: drain without an intervening reopen resets first so each
        #: completed cycle's record holds only its own intents
        self._cycle_state_dirty = False
        self.last_allocate: Optional[AllocateResult] = None
        self._last_queue_deserved = None
        self.stats: Dict[str, float] = {}
        #: per-pass in-graph telemetry of this cycle (conf telemetry: true):
        #: {"allocate": CycleTelemetry dict, "backfill": {...},
        #:  "preempt": [per-mode dicts]} — empty when telemetry is off
        self.last_telemetry: Dict[str, object] = {}
        # dirty sets feeding refresh_snapshot (the event-handler analog of
        # the reference's incrementally maintained cache,
        # event_handlers.go): apply/evict record their touches; external
        # mutators call mark_dirty. Preserved across _reset_cycle_state so
        # a reopened session refreshes what the previous cycle touched.
        if not hasattr(self, "_dirty_jobs"):
            self._dirty_jobs: set = set()
            self._dirty_nodes: set = set()

    def reopen(self, now: Optional[float] = None,
               conf: Optional[SchedulerConfiguration] = None,
               plugin_overrides: Optional[Dict[str, object]] = None) -> bool:
        """Start the next scheduling cycle on this session without a full
        re-pack: drop the previous cycle's intents, incrementally refresh
        the packed snapshot from the recorded dirty entities, and re-open
        the plugins. This is the production steady-state path — the
        reference never re-builds its cache between cycles either; informer
        event handlers patch it in place and runOnce snapshots the result
        (event_handlers.go:43-740 feeding scheduler.go:91).

        Returns True when the incremental patch sufficed (False = one of
        refresh_snapshot's documented fallbacks forced a full repack)."""
        if conf is not None:
            self.conf = conf
        self.now = now if now is not None else time.time()
        self._reset_cycle_state()
        refreshed = self.refresh_snapshot()
        self._build_plugins(plugin_overrides or {})
        self._open_plugins()
        return refreshed

    # ------------------------------------------------------------- packing
    def repack(self) -> None:
        """Re-flatten the cluster into device arrays (cache.Snapshot seam).

        Uses the native (C++) packer when the library is buildable — the
        host-side hot path at scale — and the pure-Python packer otherwise
        (they are equivalence-tested in tests/test_native_pack.py).  Set
        VOLCANO_TPU_NO_NATIVE=1 to force the Python path.
        """
        import os
        t0 = time.time()
        if os.environ.get("VOLCANO_TPU_NO_NATIVE"):
            self.snap, self.maps = pack(self.cluster)
        else:
            from .. import native
            self.snap, self.maps = native.pack_best_effort(self.cluster)
        # pack epoch: every repack reindexes the maps, so an in-flight
        # cycle dispatched under an older epoch must apply with its own
        # captured maps (PendingAllocate.apply_ctx), never the live ones
        self.pack_epoch = getattr(self, "pack_epoch", 0) + 1
        self.stats["pack_ms"] = (time.time() - t0) * 1000
        # inter-pod affinity encoding rides the snapshot (the predicates
        # plugin's InterPodAffinity state, predicates.go:116-160)
        from ..arrays.affinity import build_affinity
        N = np.asarray(self.snap.nodes.pod_count).shape[0]
        T = np.asarray(self.snap.tasks.status).shape[0]
        self.affinity = build_affinity(self.cluster, self.maps, N, T)
        # uid -> (job, task) readout index: built lazily on first use (one
        # O(T) pass; skipping it when nothing reads back by uid saved
        # ~150 ms at 100k tasks)
        self._task_lookup_cache = None
        # packed-order (job, task) object pairs for _bulk_bind: built (and
        # alignment-verified) once per pack, then kept valid by the dirty
        # machinery — refresh_snapshot repacks on any task-set change and
        # patches entries for replaced objects, so per-cycle re-validation
        # collapses to a length check
        self._packed_objs_cache = None
        # hdrf tree topology (the drf plugin's hierarchicalRoot,
        # drf.go:128-147) — static per snapshot, consumed in-kernel
        from ..arrays.hierarchy import build_hierarchy
        Q = np.asarray(self.snap.queues.weight).shape[0]
        J = np.asarray(self.snap.jobs.valid).shape[0]
        self.hierarchy = build_hierarchy(self.cluster, self.maps, Q, J)
        # queue-known membership mask for refresh_snapshot's aggregate
        # recompute (pack keeps unknown-queue jobs out of the sums)
        qk = np.zeros(J, bool)
        for ji, uid in enumerate(self.maps.job_uids):
            job = self.cluster.jobs.get(uid)
            qk[ji] = (job is not None
                      and job.queue in self.maps.queue_index)
        self._queue_known = qk
        self._dirty_jobs = set()
        self._dirty_nodes = set()
        self._scale_allocatables()

    def _scale_allocatables(self) -> None:
        """Apply the conf's ScaleAllocatable factors to the packed node
        allocatable/idle (OpenSession -> ScaleAllocatables,
        framework.go:33 + session.go:448-468). Session-scoped: operates on
        the snapshot arrays only, never the ClusterInfo.

        Per node: allocatable scales in place (ScaleResource keys
        millicpu/memory/maxtasknum, resource_info.go:55-75); the removed
        amount comes out of idle when idle covers it, otherwise idle's
        cpu+memory zero out (session.go:455-464)."""
        import dataclasses as _dc
        for c in self.conf.configurations:
            if c.name.lower() != "scaleallocatable":
                continue
            dims = self.maps.resource_names
            alloc = np.asarray(self.snap.nodes.allocatable).copy()
            idle = np.asarray(self.snap.nodes.idle).copy()
            max_pods = np.asarray(self.snap.nodes.max_pods).copy()
            old_alloc = alloc.copy()
            key_to_dim = {"millicpu": "cpu", "memory": "memory"}
            for key, factor in c.arguments.items():
                try:
                    f = float(factor)
                except (TypeError, ValueError):
                    continue
                if key.lower() == "maxtasknum":
                    max_pods = (max_pods * f).astype(max_pods.dtype)
                    continue
                dim = key_to_dim.get(key.lower())
                if dim in dims:
                    alloc[:, dims.index(dim)] *= f
            unavailable = old_alloc - alloc
            covered = np.all(unavailable <= idle + 1e-9, axis=-1)
            new_idle = np.where(covered[:, None], idle - unavailable, idle)
            if "cpu" in dims:
                new_idle[:, dims.index("cpu")] *= covered
            if "memory" in dims:
                new_idle[:, dims.index("memory")] *= covered
            valid = np.asarray(self.snap.nodes.valid)
            self.snap = _dc.replace(
                self.snap, nodes=_dc.replace(
                    self.snap.nodes,
                    allocatable=alloc.astype(np.float32),
                    idle=new_idle.astype(np.float32),
                    max_pods=max_pods),
                # plugins sum allocatable AFTER scaling (framework.go:33
                # runs before OnSessionOpen)
                cluster_capacity=np.where(valid[:, None], alloc, 0.0)
                .sum(axis=0).astype(np.float32))

    def plugin(self, name: str):
        for p in self.plugins:
            if p.name == name:
                return p
        return None

    # -------------------------------------------------- incremental refresh
    def mark_dirty(self, job_uid: Optional[str] = None,
                   node_name: Optional[str] = None) -> None:
        """Record an out-of-session mutation for refresh_snapshot."""
        if job_uid is not None:
            self._dirty_jobs.add(job_uid)
        if node_name is not None:
            self._dirty_nodes.add(node_name)

    def refresh_snapshot(self) -> bool:
        """Patch the packed snapshot in place for the recorded dirty
        entities instead of re-packing the whole cluster — the steady-state
        cycle path (the reference maintains its cache incrementally through
        informer event handlers, event_handlers.go:43-740, and only
        deep-copies at Snapshot; here the patch IS the snapshot update).

        Exact only for status/placement/accounting churn on an unchanged
        entity set: same nodes, same jobs, same per-job task uids, and no
        task spec changes (selector/toleration/affinity rows are immutable
        per the job-update webhook, webhooks/jobs.py). Anything else —
        including any inter-pod affinity terms, whose live counts depend on
        placements — falls back to a full repack. Returns True when the
        incremental patch was applied.
        """
        import numpy as np
        dirty_jobs = self._dirty_jobs
        dirty_nodes = self._dirty_nodes
        self._dirty_jobs = set()
        self._dirty_nodes = set()
        maps = self.maps
        scaled = any(c.name.lower() == "scaleallocatable"
                     for c in self.conf.configurations)
        if (self.affinity.has_terms
                or scaled                       # node rows carry scaled
                #                                 allocatable (session.go:448)
                or len(self.cluster.jobs) != len(maps.job_uids)
                or len(self.cluster.nodes) != len(maps.node_names)
                or len(self.cluster.queues) != len(maps.queue_names)
                or any(q not in maps.queue_index
                       for q in self.cluster.queues)
                or sorted(self.cluster.namespaces or {"default": None})
                != maps.namespace_names
                or any(u not in maps.job_index for u in dirty_jobs)
                or any(n not in maps.node_index for n in dirty_nodes)):
            self.repack()
            return False
        snap = self.snap
        dims = maps.resource_names
        tjob = np.asarray(snap.tasks.job)
        tasks_a = snap.tasks
        jobs_a = snap.jobs
        nodes_arr = snap.nodes
        M = jobs_a.task_table.shape[1]
        from ..api import (PodGroupPhase, QueueState, TaskStatus,
                           gpu_request_of, is_allocated_status)
        from ..arrays.pack import queue_capability_row, queue_parent_depth

        def vec(res):
            q = res.quantities
            return [q.get(d, 0.0) for d in dims]

        # ---- queue + namespace static rows (Q/S are small: re-encode) ----
        # covers queue open/closed flips, weight edits, hierarchy
        # annotation changes, and namespace weight changes without
        # per-entity dirty tracking
        queues_a = snap.queues
        q_changed = False
        open_flipped = []
        parents, depths = queue_parent_depth(self.cluster, maps.queue_names)
        for qi, name in enumerate(maps.queue_names):
            q = self.cluster.queues[name]
            hw = q.hierarchy_weight_values()
            row = (np.float32(max(q.weight, 0)),
                   queue_capability_row(q, dims),
                   bool(q.reclaimable),
                   q.state == QueueState.OPEN,
                   np.int32(parents[qi]), np.int32(depths[qi]),
                   np.float32(hw[-1] if hw else 1.0))
            old = (queues_a.weight[qi], queues_a.capability[qi],
                   bool(queues_a.reclaimable[qi]), bool(queues_a.open[qi]),
                   queues_a.parent[qi], queues_a.depth[qi],
                   queues_a.hier_weight[qi])
            if (old[0] != row[0] or not np.array_equal(old[1], row[1])
                    or old[2] != row[2] or old[3] != row[3]
                    or old[4] != row[4] or old[5] != row[5]
                    or old[6] != row[6]):
                q_changed = True
                if old[3] != row[3]:
                    open_flipped.append(qi)
                queues_a.weight[qi] = row[0]
                queues_a.capability[qi] = row[1]
                queues_a.reclaimable[qi] = row[2]
                queues_a.open[qi] = row[3]
                queues_a.parent[qi] = row[4]
                queues_a.depth[qi] = row[5]
                queues_a.hier_weight[qi] = row[6]
        for si, name in enumerate(maps.namespace_names):
            ns = self.cluster.namespaces.get(name)
            snap.namespace_weight[si] = max(ns.weight if ns else 1, 1)
        if q_changed:
            # the hdrf tree rides the queue annotations
            from ..arrays.hierarchy import build_hierarchy
            Q = np.asarray(queues_a.weight).shape[0]
            J = np.asarray(jobs_a.valid).shape[0]
            self.hierarchy = build_hierarchy(self.cluster, maps, Q, J)
        ns_index = {n: i for i, n in enumerate(maps.namespace_names)}
        if open_flipped:
            # member jobs' schedulable depends on queue_open (pack j_sched):
            # re-encode them like dirty jobs
            jq = np.asarray(jobs_a.queue)
            jvalid = np.asarray(jobs_a.valid)
            for qi in open_flipped:
                for ji in np.flatnonzero((jq == qi) & jvalid):
                    dirty_jobs.add(maps.job_uids[int(ji)])

        # ---- dirty task/job rows -----------------------------------------
        for uid in dirty_jobs:
            ji = maps.job_index[uid]
            job = self.cluster.jobs.get(uid)
            if job is None:
                self.repack()
                return False
            tis = np.flatnonzero(tjob == ji)
            if ([maps.task_uids[ti] for ti in tis]
                    != list(job.tasks.keys())):
                self.repack()       # task set changed: full rebuild
                return False
            # a watch-driven store may have replaced the TaskInfo objects
            # behind unchanged uids: re-point the positional/uid caches so
            # later binds mutate the live objects, not stale ones
            if self._packed_objs_cache is not None:
                for ti, task in zip(tis.tolist(), job.tasks.values()):
                    self._packed_objs_cache[ti] = (job, task)
            if self._task_lookup_cache is not None:
                for task in job.tasks.values():
                    self._task_lookup_cache[task.uid] = (job, task)
            pending: list = []
            req_sum = np.zeros(len(dims), np.float32)
            for ti, task in zip(tis.tolist(), job.tasks.values()):
                tasks_a.resreq[ti] = vec(task.resreq)
                tasks_a.status[ti] = int(task.status)
                tasks_a.priority[ti] = task.priority
                tasks_a.node[ti] = maps.node_index.get(task.node_name, -1)
                tasks_a.best_effort[ti] = task.best_effort
                tasks_a.gpu_request[ti] = gpu_request_of(task.resreq)
                tasks_a.preemptable[ti] = task.preemptable
                if task.status == TaskStatus.PENDING:
                    pending.append(ti)
                if (task.status == TaskStatus.PENDING
                        or is_allocated_status(TaskStatus(task.status))):
                    req_sum += np.asarray(tasks_a.resreq[ti])
            pending.sort(key=lambda ti: (-int(tasks_a.priority[ti]), ti))
            if len(pending) > M:
                self.repack()       # pending row outgrew the M bucket
                return False
            ready_num = job.ready_task_num()
            jobs_a.min_available[ji] = job.min_available
            jobs_a.queue[ji] = maps.queue_index.get(job.queue, 0)
            self._queue_known[ji] = job.queue in maps.queue_index
            jobs_a.namespace[ji] = ns_index.get(job.namespace, 0)
            jobs_a.priority[ji] = job.priority
            jobs_a.ready_num[ji] = ready_num
            jobs_a.allocated[ji] = vec(job.allocated)
            jobs_a.total_request[ji] = req_sum
            jobs_a.min_resources[ji] = vec(job.min_resources)
            jobs_a.task_table[ji] = -1
            jobs_a.task_table[ji, :len(pending)] = pending
            jobs_a.n_pending[ji] = len(pending)
            gang_valid, _ = job.is_valid()
            qi = maps.queue_index.get(job.queue)
            queue_open = qi is not None and bool(snap.queues.open[qi])
            pending_phase = job.pod_group_phase == PodGroupPhase.PENDING
            jobs_a.pending_phase[ji] = pending_phase
            jobs_a.inqueue[ji] = not pending_phase
            jobs_a.schedulable[ji] = (gang_valid and queue_open
                                      and not pending_phase)
            jobs_a.preemptable[ji] = job.preemptable

        # ---- dirty node rows ---------------------------------------------
        for name in dirty_nodes:
            ni = maps.node_index[name]
            node = self.cluster.nodes.get(name)
            if node is None:
                self.repack()
                return False
            nodes_arr.idle[ni] = vec(node.idle)
            nodes_arr.used[ni] = vec(node.used)
            nodes_arr.releasing[ni] = vec(node.releasing)
            nodes_arr.pipelined[ni] = vec(node.pipelined)
            nodes_arr.allocatable[ni] = vec(node.allocatable)
            nodes_arr.capability[ni] = vec(node.capability)
            nodes_arr.pod_count[ni] = node.pod_count()
            nodes_arr.max_pods[ni] = node.max_pods
            nodes_arr.schedulable[ni] = (node.ready
                                         and not node.unschedulable)
            # always zero first (a node whose device set emptied must not
            # keep stale rows — pack zeros them); a device set that outgrew
            # the packed G bucket or a dev.id past it needs the wider bucket
            # only a repack can size
            nodes_arr.gpu_memory[ni] = 0.0
            nodes_arr.gpu_used[ni] = 0.0
            G = nodes_arr.gpu_memory.shape[1]
            if node.gpu_devices:
                if (len(node.gpu_devices) > G
                        or any(dev.id >= G for dev in node.gpu_devices)):
                    self.repack()
                    return False
                for dev in node.gpu_devices:
                    nodes_arr.gpu_memory[ni, dev.id] = dev.memory
                    nodes_arr.gpu_used[ni, dev.id] = dev.used_memory()

        # ---- cluster capacity (pack.py cluster_capacity formula) ---------
        if dirty_nodes:
            nn = len(maps.node_names)
            snap.cluster_capacity[:] = (
                nodes_arr.allocatable[:nn].sum(axis=0) if nn
                else np.zeros(len(dims), np.float32))

        # ---- queue aggregates (proportion.OnSessionOpen sums) ------------
        if dirty_jobs or q_changed:
            jq = np.asarray(jobs_a.queue)
            # pack excludes valid jobs whose queue is unknown from the
            # aggregates (their j_queue defaults to 0); mirror via the
            # queue-known mask recorded at repack
            member = np.asarray(jobs_a.valid) & self._queue_known
            alloc = np.where(member[:, None], jobs_a.allocated, 0.0)
            req = np.where(member[:, None], jobs_a.total_request, 0.0)
            inq = np.where((member & np.asarray(jobs_a.inqueue))[:, None],
                           jobs_a.min_resources, 0.0)
            for arr, src in ((snap.queues.allocated, alloc),
                             (snap.queues.request, req),
                             (snap.queues.inqueue_minres, inq)):
                arr[:] = 0.0
                np.add.at(arr, jq, src)
        return True

    # ------------------------------------------------- kernel composition
    def allocate_config(self) -> AllocateConfig:
        weights: Dict[str, float] = dict(
            binpack_weight=0.0, least_allocated_weight=0.0,
            most_allocated_weight=0.0, balanced_weight=0.0,
            taint_prefer_weight=0.0, pod_affinity_weight=0.0)
        provided = set()
        any_scorer = False
        for p in self.plugins:
            w = p.score_weights(self)
            if w:
                any_scorer = True
                for k, v in w.items():
                    weights[k] = weights.get(k, 0.0) + v
                    provided.add(k)
        if not any_scorer:
            # no scoring plugin: fall back to spread defaults like the
            # reference's nodeorder defaults
            weights.update(least_allocated_weight=1.0, balanced_weight=1.0)
        # InterPodAffinity is part of the predicates plugin's filter set
        # (predicates.go:196-200); compiled in only when terms exist so the
        # affinity-free hot path keeps its fused-placer shape.
        enable_aff = (self.affinity.has_terms
                      and self.plugin("predicates") is not None)
        # NodePorts likewise (predicates.go:191): only when a pending task
        # actually declares hostPorts
        enable_ports = (self.plugin("predicates") is not None
                        and any(t.host_ports
                                for job in self.cluster.jobs.values()
                                for t in job.tasks.values()))
        # Default the scoring weight to 1.0 only when no nodeorder plugin
        # supplied a value; an explicit ``podaffinity.weight: 0`` stays 0
        # (nodeorder.go:104-140 priorityWeight defaults).
        if enable_aff and "pod_affinity_weight" not in provided:
            weights["pod_affinity_weight"] = 1.0
        drf = self.plugin("drf")
        tdm = self.plugin("tdm")
        return AllocateConfig(telemetry=bool(getattr(self.conf, "telemetry",
                                                     False)),
                              use_pallas=getattr(self.conf, "use_pallas",
                                                 None),
                              wave_width=int(getattr(self.conf,
                                                     "wave_width", 1)),
                              enable_gang=self.plugin("gang") is not None,
                              enable_pod_affinity=enable_aff,
                              enable_host_ports=enable_ports,
                              enable_hdrf=(drf is not None
                                           and drf.option.enabled_hierarchy),
                              drf_job_order=(drf is not None
                                             and drf.option.enabled_job_order),
                              drf_ns_order=(drf is not None
                                            and drf.option.enabled_namespace_order),
                              tdm_job_order=(tdm is not None
                                             and tdm.option.enabled_job_order),
                              sla_job_order=(self.plugin("sla") is not None
                                             and self.plugin("sla")
                                             .option.enabled_job_order),
                              **weights)

    def _port_volume_extras(self, extras: AllocateExtras) -> None:
        """Host-side NodePorts + volume-binding inputs (the predicates
        plugin's nodePortFilter, predicates.go:191, and the
        defaultVolumeBinder seam, cache.go:240-272). The walk itself lives
        in framework/host_extras.py, shared with the VCS4 wire client."""
        from .host_extras import apply_port_volume_sections, \
            port_volume_sections
        sec = port_volume_sections(self.cluster, self.maps.node_index,
                                   self.maps.task_index)
        apply_port_volume_sections(extras, sec, self.snap)

    def _node_affinity_extras(self, extras: AllocateExtras) -> None:
        """f32[P, N] NodeAffinity preferred-terms score per predicate
        template: sum of matched term weights x nodeaffinity.weight
        (nodeorder.go:255-266 wrapping the k8s NodeAffinity scorer,
        un-normalized like the reference's TODO notes)."""
        from .host_extras import (apply_affinity_sections,
                                  node_affinity_sections)
        no = self.plugin("nodeorder")
        w = no.arg_float("nodeaffinity.weight", 1.0) if no is not None else 0.0
        do_required = self.plugin("predicates") is not None
        if not (bool(w) or do_required):
            return
        # the walk + grouping (full matchExpressions semantics,
        # api.NodeSelectorTerm) is shared with the VCS4 wire client so the
        # served sidecar sees bit-identical masks
        sec = node_affinity_sections(self.cluster, self.maps.node_names,
                                     self.maps.task_index, w, do_required)
        apply_affinity_sections(extras, sec, self.snap,
                                len(self.maps.node_names))

    def allocate_extras(self) -> AllocateExtras:
        extras = AllocateExtras.neutral(self.snap)
        extras.affinity = self.affinity
        extras.hierarchy = self.hierarchy
        if self.plugin("predicates") is not None:
            self._port_volume_extras(extras)
        if (self.plugin("nodeorder") is not None
                or self.plugin("predicates") is not None):
            self._node_affinity_extras(extras)
        for p in self.plugins:
            deserved = p.queue_deserved(self)
            if deserved is not None:
                extras.queue_deserved = np.asarray(deserved, np.float32)
                # reused by the metric families at close (no re-dispatch)
                self._last_queue_deserved = extras.queue_deserved
            share = p.job_order_share(self)
            if share is not None and p.option.enabled_job_order:
                extras.job_share = np.asarray(share, np.float32)
            ns = p.namespace_share(self)
            if ns is not None:
                extras.ns_share = np.asarray(ns, np.float32)
            if hasattr(p, "job_deadline"):
                extras.job_deadline = np.asarray(p.job_deadline(self),
                                                 np.float32)
            if hasattr(p, "block_nonrevocable"):
                extras.block_nonrevocable = np.asarray(
                    p.block_nonrevocable(self))
                extras.block_all = np.asarray(p.block_all_mask(self))
                extras.task_revocable = np.asarray(
                    p.task_revocable_mask(self))
                extras.tdm_bonus = np.asarray(p.tdm_bonus_mask(self))
            if hasattr(p, "revocable_node_mask"):
                extras.revocable_node = np.asarray(p.revocable_node_mask(self))
            if hasattr(p, "job_victim_budget"):
                extras.job_victim_budget = np.asarray(
                    p.job_victim_budget(self), np.int32)
            if hasattr(p, "task_pref_node"):
                extras.task_pref_node = np.asarray(
                    p.task_pref_node(self), np.int32)
            if hasattr(p, "node_locked_mask"):
                extras.node_locked = np.asarray(p.node_locked_mask(self))
                extras.target_job = np.int32(p.target_job_index(self))
        return extras

    def enqueue_config(self) -> EnqueueConfig:
        gates: Dict[str, object] = {}
        for p in self.plugins:
            gates.update(p.enqueue_gates(self))
        return EnqueueConfig(
            enable_proportion_gate=bool(gates.get("enable_proportion_gate",
                                                  False)),
            enable_overcommit_gate=bool(gates.get("enable_overcommit_gate",
                                                  False)),
            overcommit_factor=float(gates.get("overcommit_factor", 1.2)))

    def sla_waiting_flags(self) -> np.ndarray:
        J = np.asarray(self.snap.jobs.valid).shape[0]
        flags = np.zeros(J, bool)
        for p in self.plugins:
            f = p.sla_waiting(self)
            if f is not None:
                flags |= np.asarray(f, bool)
        return flags

    # --------------------------------------------------------- pass runners
    def run_enqueue(self) -> int:
        """Run the enqueue pass; promote admitted jobs Pending -> Inqueue.
        Returns the number admitted."""
        with _trace_span("volcano/session/enqueue"):
            return self._run_enqueue()

    def _run_enqueue(self) -> int:
        fn = _enqueue_fn(self.enqueue_config())
        admitted = np.asarray(fn(self.snap, self.sla_waiting_flags()))
        count = 0
        from ..api import PodGroupPhase
        for uid, ji in self.maps.job_index.items():
            if admitted[ji]:
                self.cluster.jobs[uid].pod_group_phase = PodGroupPhase.INQUEUE
                self.phase_updates[uid] = PodGroupPhase.INQUEUE
                count += 1
        if count:
            self.repack()
        return count

    def run_allocate(self):
        with _trace_span("volcano/session/allocate"):
            return self._run_allocate()

    def _run_allocate(self):
        return self.complete_allocate(self.dispatch_allocate())

    def run_allocate_oracle(self):
        """Graceful-degradation rung: the whole allocate pass on the
        pure-host CPU reference (no jax dispatch at all), applied to the
        session exactly like the compiled result. Decisions are
        bit-identical to the compiled cycle (the oracle IS the equality
        reference of the kernel test suites), so a scheduler that lost its
        accelerator keeps serving the same placements, just slower."""
        cfg, extras = self._derived_allocate_inputs()
        from ..runtime.cpu_reference import allocate_cpu
        # collect_telemetry=True enables the oracle's kernel-mirroring
        # give-up short-circuit (see _oracle_packed) — required for exact
        # job_ready/phase-update parity, not for the counters
        out = allocate_cpu(self.snap, extras, cfg, collect_telemetry=True)
        import types
        task_node = np.asarray(out["task_node"], np.int32)
        task_mode = np.asarray(out["task_mode"], np.int32)
        task_gpu = np.asarray(out["task_gpu"], np.int32)
        job_ready = np.asarray(out["job_ready"], bool)
        job_pipelined = np.asarray(out["job_pipelined"], bool)
        result = types.SimpleNamespace(
            task_node=task_node, task_mode=task_mode, task_gpu=task_gpu,
            job_ready=job_ready, job_pipelined=job_pipelined,
            job_attempted=np.asarray(out["job_attempted"], bool))
        self.last_allocate = result
        self.stats["cpu_oracle"] = 1.0
        self.apply_allocate(result, host=(task_node, task_mode, task_gpu,
                                          job_ready, job_pipelined))
        return result

    def allocate_inputs(self):
        """Public (cfg, extras) exactly as :meth:`dispatch_allocate`
        derives them — the fleet runtime (volcano_tpu/fleet) derives its
        shape-bucket keys and batched argument trees from this, so the
        batched cycle consumes bit-identical inputs to the single-tenant
        dispatch."""
        return self._derived_allocate_inputs()

    def _derived_allocate_inputs(self):
        """(cfg, extras) exactly as the dispatched cycle consumes them.

        Batched pallas rounds: ops/allocate_scan.derive_batching is the
        single authority for the exactness preconditions — static-key
        configs get K pre-selected sections (batch_jobs), dynamic-key
        configs (drf/hdrf ordering or any finite proportion deserved,
        including 0: zero-quota queues flip overused on the first
        commit) get the in-kernel-selection path (batch_rounds)."""
        cfg = self.allocate_config()
        extras = self.allocate_extras()
        from ..ops.allocate_scan import derive_batching
        cfg = derive_batching(cfg, extras.queue_deserved)
        # GPU-free snapshots skip the per-card kernel state
        # (decision-neutral: zero requests never charge a card)
        if not np.any(np.asarray(self.snap.tasks.gpu_request) > 0):
            cfg = dataclasses.replace(cfg, enable_gpu=False)
        return cfg, extras

    def _sharding_mesh(self):
        """The device mesh the allocate cycle runs on, or None when the
        conf leaves sharding off (or the delta path — the only residency
        the sharded kernel supports — is disabled). Sized per the CURRENT
        snapshot's node bucket (parallel/sharding.mesh_for_nodes), so a
        shape-bucket change re-picks a dividing mesh."""
        if not bool(getattr(self.conf, "sharding", False)):
            return None
        if not bool(getattr(self.conf, "delta_uploads", True)):
            return None
        from ..parallel.sharding import mesh_for_nodes
        n_nodes = int(np.asarray(self.snap.nodes.valid).shape[0])
        return mesh_for_nodes(n_nodes,
                              getattr(self.conf, "sharding_devices", None))

    def drop_sharded_residency(self) -> int:
        """Forget every mesh-bound resident — the elastic-mesh hook
        (ISSUE 20). After a quarantine or probation regrow the serving
        mesh changed, so the old residents' device buffers (and their
        kernel, whose cache key includes the mesh's device ids) are
        unreachable history; the next dispatch_allocate resolves a fresh
        kernel on the new mesh and cold-fuses its residency from source
        truth — the same re-fuse-from-truth primitive integrity recovery
        uses, which is why a mesh change needs no new Session and is
        decision-neutral. Returns how many residents were dropped."""
        dropped = 0
        for kid in list(self._sharded_ids):
            if self._resident.pop(kid, None) is not None:
                dropped += 1
        self._sharded_ids.clear()
        return dropped

    def warm_allocate(self) -> None:
        """AOT-compile the allocate entry for the current shape bucket
        WITHOUT executing a cycle — the cold-start hook (pair with
        framework/compile_cache: a restarted scheduler stops paying
        ``compile_s`` on its first real cycle)."""
        with _spans.span("session.warm"):
            cfg, extras = self._derived_allocate_inputs()
            mesh = self._sharding_mesh()
            if mesh is not None:
                _sharded_delta_allocate(cfg, self.snap, extras, mesh).warm()
            elif bool(getattr(self.conf, "delta_uploads", True)):
                _delta_allocate(cfg, self.snap, extras).warm()
            else:
                from ..ops.fused_io import _TARGETS, fuse_spec, group_sizes
                fn, _fuse = _fused_allocate(cfg, self.snap, extras)
                _td, spec = fuse_spec((self.snap, extras))
                import jax
                avals = tuple(jax.ShapeDtypeStruct((n,), _TARGETS[g])
                              for g, n in zip(("f", "i", "b"),
                                              group_sizes(spec)))
                fn.lower(*avals).compile()

    def dispatch_allocate(self, speculative: bool = False,
                          async_pack: bool = False) -> PendingAllocate:
        """Upload (full or delta) + dispatch the compiled allocate cycle
        WITHOUT reading the decisions back. Returns the pending handle;
        :meth:`complete_allocate` drains it. The synchronous path is
        ``complete_allocate(dispatch_allocate())``; the pipelined scheduler
        loop holds the pending across one run_once boundary so device
        compute overlaps host event ingestion.

        ``speculative`` marks a depth-k dispatch with predecessors still in
        flight: the kernel packs into a fresh scratch (keep_scratch — the
        in-flight mirror buffers stay referenced by their pendings) and the
        pending freezes its own mirror digest + host buffers, since the
        live residency will have moved on by the time it drains.

        ``async_pack`` moves the diff/pack + device submit onto a worker
        thread (the double-buffered pack thread): the returned pending
        carries a ``future``; :meth:`resolve_pending` joins it. Everything
        epoch-sensitive (extras derivation, chaos seam, kernel/state
        lookup, apply capture) stays on the calling thread."""
        t0 = time.time()
        with _spans.span("session.extras"):
            cfg, extras = self._derived_allocate_inputs()
        extras_ms = (time.time() - t0) * 1000
        self.stats["extras_ms"] = extras_ms
        # fault-injection seam (chaos backend-loss / slow-dispatch faults
        # fire here, before any resident state is touched, exactly where a
        # real accelerator loss surfaces) — main thread, so the deadline
        # watchdog still sees an injected slow dispatch
        from ..chaos.inject import seam
        seam("session.dispatch", session=self)
        kernel = state = mesh = None
        if bool(getattr(self.conf, "delta_uploads", True)):
            # device-resident buffers + packed delta scatter: steady-state
            # upload is O(changed elements); full re-fuse only on the
            # first cycle of a shape bucket or when the diff is huge. With
            # conf ``sharding: true`` the residents split along the node
            # axis over a device mesh (ShardedDeltaKernel): deltas route
            # to the owning shard, the digest verifies per shard, and
            # out_shardings == in_shardings keeps the steady loop free of
            # resharding copies (probe-counted below).
            mesh = self._sharding_mesh()
            if mesh is not None:
                kernel = _sharded_delta_allocate(cfg, self.snap, extras,
                                                 mesh)
            else:
                kernel = _delta_allocate(cfg, self.snap, extras)
            if mesh is not None:
                # remember which residents are mesh-bound so an elastic
                # mesh shrink/regrow can drop exactly them (the scalar
                # replicated residents never reference dead devices)
                self._sharded_ids.add(id(kernel))
            state = self._resident.get(id(kernel))
            if state is None:
                from ..ops.fused_io import ResidentState
                state = self._resident[id(kernel)] = ResidentState()
                warm = getattr(self, "_warm_mirrors", None)
                if warm and mesh is None:
                    # warm restart (runtime/checkpoint): a digest-
                    # verified pre-crash mirror for this shape bucket
                    # becomes the residency, so this first run ships
                    # a delta instead of the cold full upload.
                    # Sharded residents always cold-fuse (mesh-
                    # dependent placement is not checkpointed).
                    from ..ops.fused_io import _shape_key
                    mir = warm.pop(
                        _shape_key((self.snap, extras), cfg), None)
                    if mir is not None:
                        from ..runtime.checkpoint import adopt_mirror
                        adopt_mirror(state, mir)
        snap = self.snap
        T = int(np.asarray(snap.tasks.status).shape[0])
        J = int(np.asarray(snap.jobs.valid).shape[0])
        R = int(np.asarray(snap.nodes.idle).shape[1])
        pending = PendingAllocate(
            packed=None, cfg=cfg, T=T, J=J, R=R, kernel=kernel, state=state,
            tree=(snap, extras),
            shards=(int(mesh.devices.size) if mesh is not None else 1),
            epoch=int(getattr(self, "pack_epoch", 0)),
            dec_epoch=int(getattr(state, "dec_epoch", 0) or 0)
            if state is not None else 0,
            speculative=bool(speculative),
            apply_ctx=(self.maps, np.asarray(snap.tasks.job)),
            stats={"extras_ms": extras_ms})
        k_run, k_mesh = kernel, mesh

        def _dispatch():
            t1 = time.time()
            dstats = {}
            with _spans.span("session.dispatch", cat="dispatch"):
                tail = bufs = mdig = None
                if k_run is not None:
                    packed = k_run.run(state, (snap, extras),
                                       keep_scratch=speculative)
                    dstats["upload_bytes"] = float(state.last_upload_bytes)
                    dstats["upload_bytes_full"] = float(
                        state.full_upload_bytes)
                    dstats["delta_cycle"] = float(
                        state.last_kind == "delta")
                    if k_mesh is not None:
                        dstats["mesh_devices"] = float(k_mesh.devices.size)
                        dstats["resharding_copies"] = float(
                            state.resharding_copies)
                    from ..metrics import METRICS
                    METRICS.inc("cycle_upload_bytes",
                                state.last_upload_bytes,
                                labels={"kind": state.last_kind})
                    tail = getattr(state, "last_tail", None)
                    bufs = state.mirror
                    if speculative:
                        # freeze THIS dispatch's integrity digest: by its
                        # drain the live mirror belongs to a newer dispatch
                        mdig = k_run.mirror_digest(state)
                else:
                    # fused 3-buffer full upload + single packed readback
                    # (the per-leaf transfer cost over the axon tunnel
                    # dominated at scale; conf delta_uploads: false)
                    fn, fuse = _fused_allocate(cfg, snap, extras)
                    packed = fn(*fuse((snap, extras)))
            dstats["dispatch_ms"] = (time.time() - t1) * 1000
            return packed, tail, bufs, mdig, dstats, _spans.now()

        if async_pack:
            pending.future = _AsyncDispatch(_dispatch)
        else:
            self._adopt_dispatch(pending, _dispatch())
            self.stats.update(pending.stats)
        return pending

    def _adopt_dispatch(self, pending: PendingAllocate, out) -> None:
        packed, tail, bufs, mdig, dstats, at = out
        pending.packed = packed
        pending.tail = tail
        pending.bufs = bufs
        pending.mirror_digest = mdig
        pending.stats.update(dstats)
        pending.dispatch_ms = dstats.get("dispatch_ms", 0.0)
        pending.dispatched_at = at
        pending.future = None

    def resolve_pending(self, pending: PendingAllocate) -> None:
        """Join an async pack/dispatch (no-op for sync dispatches). Worker
        exceptions resurface HERE, on the calling thread — the ring owner
        maps them onto the degradation ladder like a dispatch fault."""
        fut = pending.future
        if fut is not None:
            with _spans.span("session.pack_wait", cat="wait"):
                out = fut.result()
            self._adopt_dispatch(pending, out)

    def _oracle_packed(self, pending: PendingAllocate,
                       tree=None) -> np.ndarray:
        """Last rung of the degradation ladder: decisions from the
        pure-host CPU reference (runtime/cpu_reference.allocate_cpu — the
        decision-equality oracle of the kernel test suites), packed into
        the same 3T+3J layout so the drain path is shared. Used when the
        compiled re-dispatch itself fails, i.e. the accelerator is gone."""
        from ..runtime.cpu_reference import allocate_cpu
        snap, extras = tree if tree is not None else pending.tree
        # collect_telemetry=True is NOT about telemetry here: it enables
        # the oracle's kernel-mirroring capacity-give-up short-circuit,
        # without which an already-ready gang evaluated after a stalled
        # round flips job_ready where the kernel skipped it — task
        # decisions match either way, but phase updates would not
        out = allocate_cpu(snap, extras, pending.cfg,
                           collect_telemetry=True)
        return np.concatenate([
            np.asarray(out["task_node"], np.int32),
            np.asarray(out["task_mode"], np.int32),
            np.asarray(out["task_gpu"], np.int32),
            np.asarray(out["job_ready"], np.int32),
            np.asarray(out["job_pipelined"], np.int32),
            np.asarray(out["job_attempted"], np.int32)])

    def _readback_packed(self, pending: PendingAllocate) -> np.ndarray:
        """Read a dispatched cycle's packed decisions back, verifying the
        in-graph integrity digest against the host mirror's. On a failed
        readback (handle dead, backend error) or a digest mismatch the
        cycle is recovered in place: full re-fuse from the pending tree +
        recompute (decision-neutral), falling to the CPU oracle if the
        compiled dispatch is gone too. Recovery is visible in METRICS
        (``resident_digest_mismatch_total``, ``cycle_recoveries_total``),
        ``last_telemetry["integrity"]`` and the flight-recorder ring."""
        from ..chaos.inject import seam
        from ..metrics import METRICS
        kernel, state = pending.kernel, pending.state
        reason = None
        packed = None
        window_closed = False
        seam_fired = False
        digest_checked = False

        def _close_window():
            nonlocal window_closed
            if pending.dispatched_at and not window_closed:
                # close this cycle's in-flight device window for the
                # pipeline-occupancy analyzer (per-slot at depth k)
                _spans.device_window(pending.dispatched_at, _spans.now(),
                                     shards=pending.shards,
                                     slot=pending.slot,
                                     depth=int(pending.depth or 1))
                window_closed = True

        def _host_digest():
            # the k-slot identity rule: a speculative pending froze its
            # mirror digest at dispatch (the live mirror has moved on by
            # its drain); a depth-1 pending still owns the live mirror,
            # so the live digest keeps chaos mirror-drift trip semantics
            if pending.mirror_digest is not None:
                return pending.mirror_digest
            return kernel.mirror_digest(state)

        rb_cap = int(getattr(kernel, "rb_cap", 0) or 0) \
            if kernel is not None else 0
        dec_len = int(getattr(kernel, "dec_len", 0) or 0)
        mirror = getattr(state, "dec_mirror", None) \
            if state is not None else None
        same_lineage = (state is not None and pending.dec_epoch
                        == int(getattr(state, "dec_epoch", 0) or 0))
        use_tail = (rb_cap > 0 and pending.tail is not None
                    and not pending.rb_full and same_lineage
                    and mirror is not None
                    and mirror.shape[0] == dec_len)
        if use_tail:
            # O(churn) drain: read only the changed-rows tail
            # [digest | count | idx[cap] | vals[cap]] and patch the host
            # mirror of the last drained decisions
            try:
                with _spans.span("session.readback", cat="wait"):
                    tail = np.asarray(pending.tail)
                _close_window()
                # chaos mirror-drift faults fire here: after the dispatch,
                # before the compare — the point where a real desync sits
                seam("session.complete", state=state)
                seam_fired = True
                with _spans.span("session.digest"):
                    dev_digest, cnt, idx, vals = kernel.split_tail(tail)
                    host_digest = _host_digest()
                if host_digest is not None and not np.array_equal(
                        dev_digest, host_digest):
                    reason = "digest"
                    METRICS.inc("resident_digest_mismatch_total")
                    _spans.log_event("digest_trip", source="session")
                elif cnt <= rb_cap:
                    digest_checked = True
                    packed = mirror.copy()
                    packed[idx] = vals
                    self.stats["drain_readback_bytes"] = float(tail.nbytes)
                    self.stats["drain_readback_rows"] = float(cnt)
                else:
                    # churn burst overflowed the tail capacity — not a
                    # fault; the digest already verified, fall through to
                    # the full readback below
                    digest_checked = True
            except Exception as e:
                if pending.tree is None and pending.bufs is None:
                    raise
                reason = f"readback:{type(e).__name__}"
        if packed is None and reason is None:
            try:
                with _spans.span("session.readback", cat="wait"):
                    packed = np.asarray(pending.packed)
                _close_window()
            except Exception as e:
                if kernel is None or (pending.tree is None
                                      and pending.bufs is None):
                    raise
                reason = f"readback:{type(e).__name__}"
            if packed is not None and kernel is not None \
                    and kernel.digest_words:
                if not seam_fired:
                    seam("session.complete", state=state)
                    seam_fired = True
                with _spans.span("session.digest"):
                    packed, dev_digest = kernel.split_digest(packed)
                    host_digest = None if digest_checked \
                        else _host_digest()
                if host_digest is not None and not np.array_equal(
                        dev_digest, host_digest):
                    reason = "digest"
                    METRICS.inc("resident_digest_mismatch_total")
                    _spans.log_event("digest_trip", source="session")
                    packed = None
                elif not digest_checked:
                    digest_checked = True
            if packed is not None:
                self.stats["drain_readback_bytes"] = float(packed.nbytes)
        if rb_cap > 0:
            self.stats["drain_readback_bytes_full"] = float(
                (dec_len + kernel.digest_words) * 4)
        if reason is None:
            if rb_cap > 0 and same_lineage and packed is not None \
                    and packed.shape[0] == dec_len:
                # the next drain's delta base — the tail path scattered
                # into a fresh array already; the full path's slice view
                # copies out of the readback buffer
                state.dec_mirror = packed if packed.flags.owndata \
                    else np.array(packed, np.int32)
            return packed
        src_tree = pending.tree
        if pending.speculative and pending.bufs is not None \
                and hasattr(kernel, "host_tree"):
            # a speculative pending's ``tree`` may have been refreshed in
            # place since its dispatch — recover from the host buffers the
            # dispatch actually packed
            src_tree = kernel.host_tree(pending.bufs)
        t0 = time.time()
        with _spans.span("session.recovery", cat="recovery"):
            try:
                packed = np.asarray(kernel.recover(state, src_tree))
                packed, _dig = kernel.split_digest(packed)
                mode = "refuse"
            except Exception:
                packed = self._oracle_packed(pending, tree=src_tree)
                mode = "cpu_oracle"
        if state is not None:
            # the decisions-mirror chain is broken either way; force the
            # next drain onto the full readback
            state.dec_mirror = None
        ms = (time.time() - t0) * 1000
        METRICS.inc("cycle_recoveries_total",
                    labels={"reason": reason.split(":")[0], "mode": mode})
        self.stats["recovery_ms"] = ms
        self.last_telemetry["integrity"] = dict(
            reason=reason, mode=mode, recovery_ms=round(ms, 3))
        _spans.log_event("recovery", source="session", reason=reason,
                         mode=mode, recovery_ms=round(ms, 3))
        return packed

    def complete_allocate(self, pending: PendingAllocate):
        """Drain a dispatched cycle: read the packed decisions back
        (verifying the resident-buffer integrity digest and recovering in
        place if it trips), decode the telemetry tail, and apply
        binds/pipelines to the session."""
        self.resolve_pending(pending)
        if pending.stats:
            # dispatch-time stats snapshot: at depth k this session's
            # cycle state has been reset by later reopens since the
            # dispatch — re-merge so the drained cycle's record is whole
            self.stats.update(pending.stats)
        t0 = time.time()
        cfg, T, J = pending.cfg, pending.T, pending.J
        packed = self._readback_packed(pending)
        self.stats["kernel_ms"] = (pending.dispatch_ms
                                   + (time.time() - t0) * 1000)
        if cfg.telemetry and packed.shape[0] > 3 * T + 3 * J:
            # the CycleTelemetry block rode the same packed readback as
            # the decisions — decode its i32 tail and bridge it into the
            # METRICS registry (unschedule_task_count{reason=...} etc.)
            from ..telemetry import (publish_cycle_telemetry,
                                     unpack_cycle_telemetry)
            tel = unpack_cycle_telemetry(packed[3 * T + 3 * J:], pending.R)
            self.last_telemetry["allocate"] = tel
            publish_cycle_telemetry(tel)
        ctx = None
        if pending.epoch != int(getattr(self, "pack_epoch", 0)):
            ctx = pending.apply_ctx
        return self.apply_packed(packed, T, J, ctx=ctx)

    def apply_packed(self, packed: np.ndarray, T: int, J: int, ctx=None):
        """Decode a packed decision vector (integrity digest already
        stripped) and apply it to this session — the shared tail of
        :meth:`complete_allocate`, also the entry the fleet runtime
        (volcano_tpu/fleet) uses after its batched readback handed each
        tenant its own row of decisions. ``ctx`` carries a stale pack
        epoch's (maps, task->job) capture for depth-k applies."""
        from ..ops.allocate_scan import unpack_decisions
        with _spans.span("session.unpack"):
            (task_node, task_mode, task_gpu, job_ready, job_pipelined,
             job_attempted) = unpack_decisions(packed, T, J)
        import types
        result = types.SimpleNamespace(
            task_node=task_node, task_mode=task_mode, task_gpu=task_gpu,
            job_ready=job_ready, job_pipelined=job_pipelined,
            job_attempted=job_attempted)
        self.last_allocate = result
        t0 = time.time()
        with _spans.span("session.apply"):
            self.apply_allocate(
                result, host=(task_node, task_mode, task_gpu, job_ready,
                              job_pipelined), ctx=ctx)
        self.stats["apply_ms"] = (time.time() - t0) * 1000
        return result

    def run_backfill(self) -> int:
        with _trace_span("volcano/session/backfill"):
            return self._run_backfill()

    def _run_backfill(self) -> int:
        extras = self.allocate_extras()
        telem = bool(getattr(self.conf, "telemetry", False))
        out = _backfill_fn(telem)(self.snap, extras.task_or_group,
                                  extras.or_feasible)
        if telem:
            t_node, placed, tel = out
            self.last_telemetry["backfill"] = tel.to_host()
        else:
            t_node, placed = out
        t_node, placed = np.asarray(t_node), np.asarray(placed)
        count = 0
        uids = self.maps.task_uids
        for ti in np.nonzero(placed)[0]:
            self._bind_task(uids[ti], self.maps.node_names[int(t_node[ti])])
            count += 1
        return count

    def victim_veto_mask(self) -> np.ndarray:
        """Host-computed conformance veto consumed by the kernel's tiered
        victim dispatch as the "conformance" rule (conformance.go:45-63);
        unioned across host plugins that veto."""
        T = np.asarray(self.snap.tasks.status).shape[0]
        veto = np.zeros(T, bool)
        for p in self.plugins:
            v = p.victim_veto(self)
            if v is not None:
                veto |= np.asarray(v, bool)
        return veto

    def victim_tasks_mask(self) -> np.ndarray:
        """Union of plugin victimsFn sweeps (tdm.go:298-340)."""
        T = np.asarray(self.snap.tasks.status).shape[0]
        victims = np.zeros(T, bool)
        for p in self.plugins:
            if hasattr(p, "victim_tasks"):
                victims |= np.asarray(p.victim_tasks(self), bool)
        return victims

    #: plugins registering a victim fn per mode, mirroring the reference's
    #: AddPreemptableFn / AddReclaimableFn call sites (tdm.go:297,
    #: priority.go:114, gang.go:106-107, drf.go:360+450,
    #: conformance.go:64-65, proportion.go:213)
    _VICTIM_REGISTRANTS = {
        "preempt": ("tdm", "priority", "gang", "drf", "conformance"),
        "reclaim": ("gang", "proportion", "drf", "conformance"),
    }

    def victim_tiers(self, mode: str):
        """Conf tiers -> per-tier victim-rule names for the kernel's tiered
        intersection dispatch (session_plugins.go:131-215)."""
        tiers = []
        for tier in self.conf.tiers:
            names = []
            for opt in tier.plugins:
                if opt.name not in self._VICTIM_REGISTRANTS[mode]:
                    continue
                enabled = (opt.enabled_preemptable if mode == "preempt"
                           else opt.enabled_reclaimable)
                if not enabled:
                    continue
                if opt.name == "drf" and mode == "reclaim":
                    # drf registers a Reclaimable fn only under hierarchy
                    # (drf.go:362-450)
                    if opt.enabled_hierarchy:
                        names.append("drf_hdrf")
                    continue
                names.append(opt.name)
            tiers.append(tuple(names))
        return tuple(tiers)

    def run_preempt(self, mode: str = "preempt"):
        with _trace_span(f"volcano/session/{mode}"):
            return self._run_preempt(mode)

    def _run_preempt(self, mode: str = "preempt"):
        from ..ops.preempt import PreemptConfig
        tdm = self.plugin("tdm")
        drf = self.plugin("drf")
        dispatch = "preempt" if mode == "preempt_intra" else mode
        cfg = PreemptConfig(
            mode=mode,
            telemetry=bool(getattr(self.conf, "telemetry", False)),
            scoring=self.allocate_config(),
            tiers=self.victim_tiers(dispatch),
            tdm_starving=(dispatch == "preempt" and tdm is not None
                          and tdm.option.enabled_job_starving),
            enable_hdrf=(drf is not None and drf.option.enabled_hierarchy
                         and drf.option.enabled_queue_order))
        # phase-2 preemptors exclude tasks phase 1 already pipelined
        # (their status left Pending in the reference session)
        T = np.asarray(self.snap.tasks.status).shape[0]
        skip = np.zeros(T, bool)
        if mode == "preempt_intra":
            for uid in self.pipelined:
                ti = self.maps.task_index.get(uid)
                if ti is not None:
                    skip[ti] = True
        result = _preempt_fn(cfg)(self.snap, self.allocate_extras(),
                                  self.victim_veto_mask(), skip)
        if cfg.telemetry and result.telemetry is not None:
            entry = dict(result.telemetry.to_host(), mode=mode)
            self.last_telemetry.setdefault("preempt", []).append(entry)
        self.apply_preempt(result, mode)
        return result

    def apply_preempt(self, result, mode: str) -> None:
        evicted = np.asarray(result.evicted)
        task_node = np.asarray(result.task_node)
        task_mode = np.asarray(result.task_mode)
        uids = self.maps.task_uids
        for ti in np.nonzero(evicted)[0]:
            self.evict_task(uids[ti], reason=f"{mode} victim")
        for ti in np.nonzero(task_mode == MODE_PIPELINED)[0]:
            self.pipelined[uids[ti]] = \
                self.maps.node_names[int(task_node[ti])]

    def evict_task(self, task_uid: str, reason: str = "") -> None:
        """Session evict (session.go:357 -> cache.Evict, cache.go:496):
        mark Releasing, keep node accounting in the releasing bucket, queue
        the evict intent."""
        job, task = self._find_task(task_uid)
        if task is None:
            return
        node = self.cluster.nodes.get(task.node_name)
        if node is not None and task.uid in node.tasks:
            node.remove_task(task)
            job.update_task_status(task, TaskStatus.RELEASING)
            node.add_task(task)
            self._dirty_nodes.add(node.name)
        else:
            job.update_task_status(task, TaskStatus.RELEASING)
        self._dirty_jobs.add(job.uid)
        self.evictions.append(EvictIntent(task_uid, job.uid, reason))

    # -------------------------------------------------------- apply/readout
    @property
    def _task_lookup(self):
        if self._task_lookup_cache is None:
            self._task_lookup_cache = {
                uid: (job, task)
                for job in self.cluster.jobs.values()
                for uid, task in job.tasks.items()}
        return self._task_lookup_cache

    def _find_task(self, uid: str):
        """O(1) via the lazily built uid index (the TaskStatusIndex
        analog); the old per-call job scan was O(J) and dominated
        apply_allocate at 100k tasks."""
        return self._task_lookup.get(uid, (None, None))

    def _bind_task(self, task_uid: str, node_name: str,
                   gpu_index: int = -1) -> None:
        """Session dispatch: mark Binding, account on the node, queue the
        bind intent (session.go:264-355 Allocate -> dispatch -> cache.Bind)."""
        job, task = self._find_task(task_uid)
        if task is None:
            return
        job.update_task_status(task, TaskStatus.BINDING)
        task.gpu_index = gpu_index
        node = self.cluster.nodes.get(node_name)
        if node is not None and task.uid not in node.tasks:
            try:
                node.add_task(task)
            except ValueError as e:
                # The device cycle admits with float32 1e-5 slack while the
                # host Resource algebra checks float64 1e-9, so a boundary
                # exact-fit can pass on-device and fail here. The reference
                # returns the AddTask error from dispatch and continues
                # (session.go:330-355); mirror that: revert to pending and
                # record the fit error instead of crashing apply_allocate.
                job.update_task_status(task, TaskStatus.PENDING)
                task.gpu_index = -1
                self.bind_errors.append((task_uid, node_name, str(e)))
                self._dirty_jobs.add(job.uid)
                return
            self._dirty_nodes.add(node_name)
        self._dirty_jobs.add(job.uid)
        self.binds.append(BindIntent(task_uid, job.uid, node_name, gpu_index))

    def _bulk_bind(self, bind_idx, task_node, task_gpu) -> None:
        """Vectorized dispatch of many binds in one pass.

        Per-task work shrinks to dict/status bookkeeping; the per-node and
        per-job Resource arithmetic batches into one numpy segment-sum per
        axis (the apply half of VERDICT round 3's 1 s cycle budget). The
        per-task float64 exact-fit recheck that _bind_task performs moves
        to the cache bind seam, where a boundary misfit fails the bind
        into the resync path — the same place a rejected API bind lands.
        """
        from ..api import TaskStatus, gpu_request_of
        from ..api.resource import Resource
        resreq = np.asarray(self.snap.tasks.resreq, np.float64)
        dims = self.maps.resource_names
        uids = self.maps.task_uids
        node_names = self.maps.node_names
        N = len(node_names)
        J = len(self.maps.job_uids)
        tjob = np.asarray(self.snap.tasks.job)
        node_sum = np.zeros((N, resreq.shape[1]))
        job_sum = np.zeros((J, resreq.shape[1]))
        np.add.at(node_sum, task_node[bind_idx], resreq[bind_idx])
        np.add.at(job_sum, tjob[bind_idx], resreq[bind_idx])
        touched_nodes = np.unique(task_node[bind_idx])
        touched_jobs = np.unique(tjob[bind_idx])
        # plain-python views: .tolist() python ints beat per-element numpy
        # scalar casts ~10x in this loop
        idx_l = bind_idx.tolist()
        node_l = task_node[bind_idx].tolist()
        gpu_l = task_gpu[bind_idx].tolist()
        # packed-order (job, task) object list: one append pass in the
        # packer's task order beats building + probing the uid dict. Built
        # (and uid-alignment-verified — count alone cannot catch a
        # count-preserving swap) once per pack, then reused: refresh
        # repacks on any task-set change and patches replaced objects, so
        # the O(T) verification does not recur every cycle
        packed_objs = self._packed_objs_cache
        if packed_objs is None:
            packed_objs = []
            extend = packed_objs.extend
            for juid in self.maps.job_uids:
                jb = self.cluster.jobs.get(juid)
                if jb is not None:
                    extend((jb, t) for t in jb.tasks.values())
            if (len(packed_objs) != len(uids)
                    or not all(p[1].uid == u
                               for p, u in zip(packed_objs, uids))):
                # packing order no longer matches the live cluster: fall
                # back to the uid index
                packed_objs = None
            else:
                self._packed_objs_cache = packed_objs
        elif len(packed_objs) != len(uids):
            packed_objs = self._packed_objs_cache = None
        if packed_objs is None:
            lookup_get = self._task_lookup.get
        node_objs = self.cluster.nodes
        binds_append = self.binds.append
        binding = TaskStatus.BINDING
        # status-index moves batched per job: bind indices are packed in
        # job order, so the from/to buckets of job.task_status_index are
        # fetched once per job instead of per task (the _unindex/_index
        # pair was ~40% of the bind loop at 100k binds); empty source
        # buckets are dropped at the job boundary, matching _unindex
        prev_job = None
        tsi = None
        buckets: Dict = {}

        def _flush_empties():
            if prev_job is not None:
                for s, b in buckets.items():
                    if b is not None and not b and s in tsi:
                        del tsi[s]

        for k, ti in enumerate(idx_l):
            if packed_objs is not None:
                job, task = packed_objs[ti]
            else:
                job, task = lookup_get(uids[ti], (None, None))
            if task is None:
                continue
            if job is not prev_job:
                _flush_empties()
                prev_job = job
                tsi = job.task_status_index
                buckets = {}
            s = task.status
            if s not in buckets:
                buckets[s] = tsi.get(s)
            src = buckets[s]
            if src is not None:
                src.pop(task.uid, None)
            if buckets.get(binding) is None:
                buckets[binding] = tsi.setdefault(binding, {})
            task.status = binding
            buckets[binding][task.uid] = task
            gi = gpu_l[k]
            task.gpu_index = gi
            nname = node_names[node_l[k]]
            node = node_objs.get(nname)
            if node is not None and task.uid not in node.tasks:
                node.tasks[task.uid] = task
                task.node_name = nname
                if gi >= 0 and gpu_request_of(task.resreq) > 0:
                    node.add_gpu_resource(task)
            binds_append(BindIntent(task.uid, job.uid, nname, gi))
        _flush_empties()
        for ni in touched_nodes:
            node = self.cluster.nodes.get(node_names[int(ni)])
            if node is None:
                continue
            delta = Resource({d: float(node_sum[ni, k])
                              for k, d in enumerate(dims)
                              if node_sum[ni, k] > 0})
            node.used.add(delta)
            node.idle.sub_floored(delta)
            self._dirty_nodes.add(node.name)
        job_uids = self.maps.job_uids
        for ji in touched_jobs:
            job = self.cluster.jobs.get(job_uids[int(ji)])
            if job is None:
                continue
            job.allocated.add(Resource({d: float(job_sum[ji, k])
                                        for k, d in enumerate(dims)
                                        if job_sum[ji, k] > 0}))
            self._dirty_jobs.add(job.uid)

    def apply_allocate(self, result: AllocateResult, host=None,
                       ctx=None) -> None:
        if host is not None:
            task_node, task_mode, task_gpu, job_ready, _ = host
        else:
            task_node = np.asarray(result.task_node)
            task_mode = np.asarray(result.task_mode)
            task_gpu = np.asarray(result.task_gpu)
            job_ready = np.asarray(result.job_ready)
        if ctx is not None:
            # epoch-stale apply (depth-k ring): this cycle dispatched under
            # an older pack epoch, so its decision rows index THAT epoch's
            # maps — apply with the captured (maps, task->job) instead of
            # the live ones. Binds/evictions key by uid, so cluster truth
            # stays consistent regardless of the repack in between.
            maps, task_job = ctx
        else:
            maps, task_job = self.maps, np.asarray(self.snap.tasks.job)
        from ..api import PodGroupPhase
        # touch only the decided tasks (numpy picks them; at 100k tasks the
        # all-uids python sweep was the apply bottleneck)
        uids = maps.task_uids
        bind_mask = (task_mode == MODE_ALLOCATED) & job_ready[task_job]
        bind_idx = np.nonzero(bind_mask)[0]
        if ctx is None and len(bind_idx) >= 512:
            # _bulk_bind reads the CURRENT pack's object caches — only
            # valid for same-epoch applies; stale applies take the per-task
            # path (uid-keyed, epoch-independent)
            self._bulk_bind(bind_idx, task_node, task_gpu)
        else:
            for ti in bind_idx:
                self._bind_task(uids[ti],
                                maps.node_names[int(task_node[ti])],
                                int(task_gpu[ti]))
        for ti in np.nonzero((task_mode != 0) & ~bind_mask)[0]:
            # held in-session only (pipelined or allocated-but-unready):
            # no cache flush, like an uncommitted Statement
            self.pipelined[uids[ti]] = \
                maps.node_names[int(task_node[ti])]
        # ready gangs' PodGroups move to Running (scheduler status updater,
        # session.go:173 jobStatus) — AFTER the bind loop so a job whose
        # bind degraded to a recorded error is not marked Running with
        # fewer bound tasks than minAvailable
        failed_jobs = set()
        for task_uid, _node, _err in self.bind_errors:
            _job, _task = self._find_task(task_uid)
            if _job is not None:
                failed_jobs.add(_job.uid)
        for uid, ji in maps.job_index.items():
            if bool(job_ready[ji]) and uid not in failed_jobs:
                self.phase_updates[uid] = PodGroupPhase.RUNNING
                job = self.cluster.jobs.get(uid)
                if job is not None and job.pod_group_phase \
                        != PodGroupPhase.RUNNING:
                    # effective transition only — the ring's invalidation
                    # predicate; steady re-assertion of RUNNING every
                    # cycle must not poison speculation
                    self.phase_changes[uid] = PodGroupPhase.RUNNING

    # --------------------------------------------------------------- close
    def close(self) -> None:
        from ..metrics import METRICS
        for p in self.plugins:
            t0 = time.time()
            p.on_session_close(self)
            METRICS.observe_plugin(p.name, "OnSessionClose",
                                   time.time() - t0)
        self._flush_metric_families()

    def _flush_metric_families(self) -> None:
        """Queue + namespace gauge families at session close (the
        proportion plugin's metrics updates, queue.go:28-284, and
        namespace.go:28-63 — here from the packed aggregates, so every
        conf exposes them)."""
        from ..metrics import METRICS
        snap, maps = self.snap, self.maps
        dims = maps.resource_names
        ci_cpu = dims.index("cpu") if "cpu" in dims else -1
        ci_mem = dims.index("memory") if "memory" in dims else -1

        def dim(row, i):
            # Resource stores cpu in millicores and memory in bytes
            # already — the gauge units (queue.go:28-60) need no scaling
            return float(row[i]) if i >= 0 else 0.0

        q_alloc = np.asarray(snap.queues.allocated)
        q_req = np.asarray(snap.queues.request)
        q_weight = np.asarray(snap.queues.weight)
        # the deserved shares this cycle's allocate already computed (no
        # second water-filling dispatch at close)
        deserved = self._last_queue_deserved
        from ..api import PodGroupPhase
        pg_counts: Dict[str, list] = {}
        for job in self.cluster.jobs.values():
            c = pg_counts.setdefault(job.queue, [0, 0, 0, 0])
            ph = job.pod_group_phase
            if ph == PodGroupPhase.INQUEUE:
                c[0] += 1
            elif ph == PodGroupPhase.PENDING:
                c[1] += 1
            elif ph == PodGroupPhase.RUNNING:
                c[2] += 1
            else:
                c[3] += 1
        for qi, name in enumerate(maps.queue_names):
            des_row = (deserved[qi] if deserved is not None
                       else np.full(len(dims), np.inf))
            finite = np.isfinite(des_row) & (des_row > 0)
            share = float(np.max(np.where(
                finite, q_alloc[qi] / np.maximum(des_row, 1e-9), 0.0)))
            overused = bool(np.any(q_alloc[qi] > des_row + 1e-6))
            pg = pg_counts.get(name, [0, 0, 0, 0])
            METRICS.update_queue_family(
                name,
                allocated_milli_cpu=dim(q_alloc[qi], ci_cpu),
                allocated_memory_bytes=dim(q_alloc[qi], ci_mem),
                request_milli_cpu=dim(q_req[qi], ci_cpu),
                request_memory_bytes=dim(q_req[qi], ci_mem),
                deserved_milli_cpu=(dim(des_row, ci_cpu)
                                    if deserved is not None
                                    and np.isfinite(des_row).all() else 0.0),
                deserved_memory_bytes=(dim(des_row, ci_mem)
                                       if deserved is not None
                                       and np.isfinite(des_row).all()
                                       else 0.0),
                share=share, weight=float(q_weight[qi]),
                overused=overused,
                pg_inqueue=pg[0], pg_pending=pg[1],
                pg_running=pg[2], pg_unknown=pg[3])
        # namespace share/weight (namespace.go:28-63): weighted dominant
        # share of member jobs' allocations — plain numpy (no device
        # dispatch on the session-close path)
        jns = np.asarray(snap.jobs.namespace)
        jvalid = np.asarray(snap.jobs.valid)
        nsw = np.asarray(snap.namespace_weight)
        j_alloc = np.where(jvalid[:, None],
                           np.asarray(snap.jobs.allocated), 0.0)
        S = nsw.shape[0]
        ns_alloc = np.zeros((S, j_alloc.shape[1]))
        np.add.at(ns_alloc, np.clip(jns, 0, S - 1), j_alloc)
        total = np.asarray(snap.cluster_capacity)
        frac = np.where(total > 0, ns_alloc / np.maximum(total, 1e-6), 0.0)
        share_raw = frac.max(axis=-1)
        for si, name in enumerate(maps.namespace_names):
            METRICS.update_namespace_family(
                name, float(share_raw[si]), float(nsw[si]))
