"""Host-computed session extras, shared by the in-process Session and the
VCS4 wire client.

These are the allocate inputs that come from walking the object model
rather than the packed arrays: node-affinity OR-group / preferred-score
masks (full matchExpressions semantics via api.NodeSelectorTerm) and the
NodePorts / volume-binding seams. Session._node_affinity_extras and
_port_volume_extras consume them directly; native/wire.serialize_extras
ships the same sections to the scheduling sidecar so the served path and
the in-process path make bit-identical decisions (VERDICT r4 #5 — the
reference has one full-fidelity production path, cache.go:712-811).

Everything here is sized to the REAL entity counts (nt tasks, nn nodes);
padding to device buckets happens at the consumer.
"""

from __future__ import annotations

from typing import Dict, List, Tuple

import numpy as np

from ..api import as_node_term


def node_affinity_sections(cluster, node_names: List[str],
                           task_index: Dict[str, int],
                           na_weight: float,
                           do_required: bool) -> Dict[str, np.ndarray]:
    """Node-affinity host sections.

    Returns dict with:
      task_or_group i32[nt]  (-1 = unconstrained) and or_masks bool[G, nn]
        — required OR-of-terms / expression-term feasibility, grouped by
        distinct term-set signature (predicates.go:186-190 semantics);
      task_na_group i32[nt] (-1 = no preferred terms) and na_rows
        f32[G2, nn] — preferred-term score rows, already scaled by
        ``na_weight`` (nodeorder.go:255-266), grouped by signature.
        Accumulation order follows the first-seen task's term list so every
        consumer reproduces the same f32 association.
    """
    nt = len(task_index)
    nn = len(node_names)
    node_labels = [cluster.nodes[n].labels for n in node_names]

    def term_mask(term) -> np.ndarray:
        t = as_node_term(term)
        return np.fromiter((t.matches(labels) for labels in node_labels),
                           bool, count=nn)

    task_or_group = np.full(nt, -1, np.int32)
    or_masks: List[np.ndarray] = []
    or_group_of: Dict[tuple, int] = {}
    task_na_group = np.full(nt, -1, np.int32)
    na_rows: List[np.ndarray] = []
    na_group_of: Dict[tuple, int] = {}
    do_score = bool(na_weight)

    for job in cluster.jobs.values():
        for uid, task in job.tasks.items():
            ti = task_index.get(uid)
            if ti is None or ti >= nt:
                continue
            if do_required and task.affinity_required:
                terms = [as_node_term(m) for m in task.affinity_required]
                if not (len(terms) == 1 and terms[0].is_pure_labels()):
                    # a lone pure-labels term folds into the packed hash
                    # row (arrays/pack.py); everything else rides the mask
                    key = tuple(sorted(t.signature() for t in terms))
                    g = or_group_of.get(key)
                    if g is None:
                        g = len(or_masks)
                        or_group_of[key] = g
                        ok = np.zeros(nn, bool)
                        for t in terms:
                            ok |= term_mask(t)
                        or_masks.append(ok)
                    task_or_group[ti] = g
            if do_score and task.affinity_preferred:
                key = tuple(sorted(
                    (as_node_term(m).signature(), w)
                    for m, w in task.affinity_preferred))
                g = na_group_of.get(key)
                if g is None:
                    g = len(na_rows)
                    na_group_of[key] = g
                    row = np.zeros(nn, np.float32)
                    for match, weight in task.affinity_preferred:
                        row += (np.float32(na_weight * weight)
                                * term_mask(match))
                    na_rows.append(row.astype(np.float32))
                task_na_group[ti] = g
    return dict(
        task_or_group=task_or_group,
        or_masks=(np.stack(or_masks) if or_masks
                  else np.zeros((0, nn), bool)),
        task_na_group=task_na_group,
        na_rows=(np.stack(na_rows) if na_rows
                 else np.zeros((0, nn), np.float32)),
    )


def port_volume_sections(cluster, node_index: Dict[str, int],
                         task_index: Dict[str, int]) -> Dict[str, object]:
    """NodePorts + volume-binding host sections (predicates.go:191 and the
    defaultVolumeBinder seam, cache.go:240-272).

    Returns dict with:
      task_ports: {ti: sorted list} pending tasks' host ports;
      node_ports: {ni: sorted list} ports already used on nodes;
      n_pending_ports: total pending port count (sizes the in-cycle
        placement buffer);
      vol_ok bool[nt], vol_node i32[nt].
    """
    nt = len(task_index)
    task_ports: Dict[int, list] = {}
    node_ports: Dict[int, set] = {}
    vol_ok = np.ones(nt, bool)
    vol_node = np.full(nt, -1, np.int32)
    n_pending_ports = 0
    for job in cluster.jobs.values():
        for uid, task in job.tasks.items():
            ti = task_index.get(uid)
            if ti is None or ti >= nt:
                continue
            if task.host_ports:
                if task.node_name in node_index:
                    node_ports.setdefault(
                        node_index[task.node_name],
                        set()).update(task.host_ports)
                else:
                    task_ports[ti] = list(task.host_ports)
                    n_pending_ports += len(task.host_ports)
            for claim in task.pvcs:
                pvc = cluster.pvcs.get(claim)
                if pvc is None or not pvc.bindable:
                    vol_ok[ti] = False
                elif pvc.node_name:
                    ni = node_index.get(pvc.node_name, -1)
                    if ni < 0:
                        vol_ok[ti] = False
                    elif vol_node[ti] >= 0 and vol_node[ti] != ni:
                        vol_ok[ti] = False   # claims pin to two nodes
                    else:
                        vol_node[ti] = ni
    return dict(task_ports={ti: sorted(p) for ti, p in task_ports.items()},
                node_ports={ni: sorted(p) for ni, p in node_ports.items()},
                n_pending_ports=n_pending_ports,
                vol_ok=vol_ok, vol_node=vol_node)


def apply_port_volume_sections(extras, sec: Dict[str, object], snap) -> None:
    """Pad the port/volume sections to the snapshot's device buckets and
    install them on an AllocateExtras (same layout Session always used)."""
    from ..arrays.schema import bucket
    N = np.asarray(snap.nodes.pod_count).shape[0]
    T = np.asarray(snap.tasks.status).shape[0]
    task_ports: Dict[int, list] = sec["task_ports"]
    node_ports: Dict[int, list] = sec["node_ports"]
    HP = bucket(max((len(p) for p in task_ports.values()), default=1), 1)
    PS = bucket(max((len(p) for p in node_ports.values()), default=1), 1)
    tp = np.zeros((T, HP), np.int32)
    for ti, ports in task_ports.items():
        tp[ti, :len(ports)] = ports[:HP]
    npo = np.zeros((N, PS), np.int32)
    for ni, ports in node_ports.items():
        npo[ni, :len(ports)] = ports[:PS]
    PE = bucket(max(int(sec["n_pending_ports"]), 1), 8)
    vol_ok = np.ones(T, bool)
    vol_ok[:len(sec["vol_ok"])] = sec["vol_ok"]
    vol_node = np.full(T, -1, np.int32)
    vol_node[:len(sec["vol_node"])] = sec["vol_node"]
    extras.task_ports = tp
    extras.node_ports = npo
    extras.pe_node0 = np.full(PE, -1, np.int32)
    extras.pe_port0 = np.zeros(PE, np.int32)
    extras.task_volume_ok = vol_ok
    extras.task_volume_node = vol_node


def apply_affinity_sections(extras, sec: Dict[str, np.ndarray], snap,
                            n_nodes: int) -> None:
    """Pad the node-affinity sections to device buckets and install them:
    per-task OR-group masks plus per-template preferred score rows (the
    template gather the kernel performs; templates split by preferred-term
    signature, so a template's representative decides its row exactly)."""
    from ..arrays.schema import bucket
    T = np.asarray(snap.tasks.status).shape[0]
    task_or = sec["task_or_group"]
    or_masks = sec["or_masks"]
    if or_masks.shape[0]:
        Nfull = np.asarray(extras.or_feasible).shape[1]
        GR = bucket(or_masks.shape[0], 1)
        feas = np.ones((GR, Nfull), bool)
        feas[:or_masks.shape[0], :n_nodes] = or_masks
        feas[:or_masks.shape[0], n_nodes:] = False  # padded nodes never match
        tg = np.full(T, -1, np.int32)
        tg[:len(task_or)] = task_or
        extras.task_or_group = tg
        extras.or_feasible = feas
    na_rows = sec["na_rows"]
    if na_rows.shape[0]:
        task_na = sec["task_na_group"]
        rep = np.asarray(snap.template_rep)
        score = np.asarray(extras.template_na_score).copy()
        for p, ti in enumerate(rep.tolist()):
            if ti < 0 or ti >= len(task_na):
                continue
            g = int(task_na[ti])
            if g >= 0:
                score[p, :n_nodes] += na_rows[g]
        extras.template_na_score = score.astype(np.float32)


def conf_na_weight(conf) -> Tuple[float, bool]:
    """(nodeaffinity.weight if the nodeorder plugin is enabled else 0,
    predicates enabled?) from a SchedulerConfiguration — the two knobs the
    affinity sections depend on, needed identically on both wire ends."""
    no = conf.plugin_option("nodeorder") if conf is not None else None
    pred = (conf.plugin_option("predicates") is not None
            if conf is not None else False)
    w = 0.0
    if no is not None:
        v = no.get_argument("nodeaffinity.weight")
        try:
            w = float(v) if v is not None else 1.0
        except (TypeError, ValueError):
            w = 1.0
    return w, pred
