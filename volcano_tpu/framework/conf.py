"""Scheduler policy configuration: actions list + plugin tiers.

Reference: pkg/scheduler/conf/scheduler_conf.go:20-82 (SchedulerConfiguration,
Tier, PluginOption with Enabled* switches) and pkg/scheduler/util.go:31-92
(defaultSchedulerConf, unmarshalSchedulerConf incl. the hdrf+proportion
conflict check). Same YAML shape as the reference so existing conf files port
unchanged.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import yaml

DEFAULT_SCHEDULER_CONF = """
actions: "enqueue, allocate, backfill"
tiers:
- plugins:
  - name: priority
  - name: gang
- plugins:
  - name: drf
  - name: predicates
  - name: proportion
  - name: nodeorder
"""


@dataclass
class PluginOption:
    """One plugin entry in a tier (scheduler_conf.go:44-82)."""

    name: str
    arguments: Dict[str, str] = field(default_factory=dict)
    # Enabled* switches default to on, like the reference's nil-means-true
    # pointers (plugins.ApplyPluginConfDefaults).
    enabled_job_order: bool = True
    enabled_namespace_order: bool = True
    enabled_hierarchy: bool = False       # drf-only: hdrf
    enabled_job_ready: bool = True
    enabled_job_pipelined: bool = True
    enabled_task_order: bool = True
    enabled_preemptable: bool = True
    enabled_reclaimable: bool = True
    enabled_queue_order: bool = True
    enabled_predicate: bool = True
    enabled_best_node: bool = True
    enabled_node_order: bool = True
    enabled_target_job: bool = True
    enabled_reserved_nodes: bool = True
    enabled_job_enqueued: bool = True
    enabled_victim: bool = True
    enabled_job_starving: bool = True

    def get_argument(self, key: str, default=None):
        return self.arguments.get(key, default)


@dataclass
class Tier:
    plugins: List[PluginOption] = field(default_factory=list)


@dataclass
class Configuration:
    """Per-action arguments block (scheduler_conf.go:30-42, used by the
    fork's ScaleAllocatable / dap conf)."""

    name: str
    arguments: Dict[str, Any] = field(default_factory=dict)


@dataclass
class SchedulerConfiguration:
    actions: List[str] = field(default_factory=lambda: ["enqueue", "allocate",
                                                        "backfill"])
    tiers: List[Tier] = field(default_factory=list)
    configurations: List[Configuration] = field(default_factory=list)
    #: in-graph cycle telemetry (ISSUE 3): compiles the CycleTelemetry /
    #: PreemptTelemetry / BackfillTelemetry counter blocks into the cycle
    #: programs. Default off — decisions are bit-identical either way, and
    #: the off-build's jaxprs carry zero telemetry equations. YAML:
    #: top-level ``telemetry: true``.
    telemetry: bool = False
    #: device-resident snapshot buffers with packed delta uploads
    #: (ops/fused_io.DeltaKernel): steady-state cycles ship O(changed
    #: elements) instead of re-uploading the full fused buffers. Decisions
    #: are bit-identical either way (the delta is a value-level diff
    #: against the mirror of device truth); ``delta_uploads: false``
    #: restores the full-upload path. YAML: top-level key.
    delta_uploads: bool = True
    #: one-deep pipelined scheduler loop (runtime/scheduler.py): dispatch
    #: the compiled cycle, defer the packed readback, and drain it at the
    #: top of the next run_once, overlapping device compute with host
    #: event ingestion. Default off — the synchronous loop is the
    #: reference semantics; see docs/architecture.md "Steady-state
    #: pipeline" for the exact apply-ordering contract. YAML: top-level
    #: ``pipeline: true``.
    pipeline: bool = False
    #: pipelined in-flight depth (runtime/scheduler.py pending ring): 1
    #: (default) keeps the depth-1 contract above unchanged; k > 1 lets
    #: up to k cycles be in flight, where cycles dispatched behind an
    #: undrained predecessor are SPECULATIVE — replayed decision-
    #: neutrally at drain if a predecessor applied decisions. Only
    #: meaningful with ``pipeline: true``. YAML: top-level
    #: ``pipeline_depth: 3``.
    pipeline_depth: int = 1
    #: opt-in persistent XLA compilation cache directory
    #: (framework/compile_cache.enable_compilation_cache); also settable
    #: via $VOLCANO_JAX_CACHE_DIR. None = disabled.
    compilation_cache_dir: Optional[str] = None
    #: per-cycle watchdog deadline for the dispatch/drain halves of the
    #: scheduler loop, in milliseconds (ISSUE 5). A cycle that blows it is
    #: retired synchronously (decisions unaffected) and the loop drops out
    #: of pipelining for the fault-cooldown window. None = no watchdog —
    #: the default, because a sane deadline is deployment-specific (it
    #: must exceed the cold-compile cycle). YAML: top-level
    #: ``cycle_deadline_ms: 500``.
    cycle_deadline_ms: Optional[float] = None
    #: node-axis sharded execution over a device mesh (ISSUE 7,
    #: parallel/sharding + ops/fused_io.ShardedDeltaKernel): the resident
    #: snapshot buffers split along the node axis, deltas route to the
    #: owning shard, and the cycle runs under GSPMD with
    #: out_shardings == in_shardings across iterations. Decisions are
    #: bit-identical to the unsharded path. Composes with ``use_pallas``:
    #: the placer runs as a shard-local pallas candidate launch with an
    #: in-graph cross-shard argmax combine (ops/allocate_scan, ISSUE 14)
    #: rather than being forced back to the scan. Requires the delta path
    #: (``delta_uploads: true``, the default) — with delta uploads off the
    #: knob is ignored. YAML: top-level ``sharding: true``.
    sharding: bool = False
    #: device-count cap for the sharded mesh (None = all local devices);
    #: the effective mesh is the largest power of two <= this that divides
    #: the packed node axis (parallel/sharding.mesh_for_nodes). YAML:
    #: top-level ``sharding_devices: 8``.
    sharding_devices: Optional[int] = None
    #: multi-host groundwork (parallel/distributed): number of host
    #: processes the mesh spans. None/1 (default) = single-process —
    #: initialize_distributed is a strict no-op. > 1 plus the
    #: $VOLCANO_COORDINATOR / $VOLCANO_PROCESS_ID env contract calls
    #: jax.distributed.initialize before mesh construction. YAML:
    #: top-level ``mesh_hosts: 2``.
    mesh_hosts: Optional[int] = None
    #: kernel-path override threaded into AllocateConfig.use_pallas:
    #: ``true`` compiles the allocate sweep as the pallas kernel,
    #: ``"interpret"`` runs the same kernel in interpreter mode (any N,
    #: CPU-friendly — what the chaos/failover probe's second leg uses),
    #: None (default) keeps the pure-XLA scan. YAML: top-level
    #: ``use_pallas: interpret``.
    use_pallas: Optional[object] = None
    #: wavefront task placement width (ISSUE 16), threaded into
    #: AllocateConfig.wave_width: each inner iteration evaluates the next
    #: W eligible tasks of the popped job against the same capacity
    #: snapshot in one batched sweep, then commits in strict task order
    #: with an in-graph conflict rule — the committed decision sequence
    #: is identical to W=1 at every width. 1 (default) keeps the per-task
    #: sweep byte-for-byte unchanged; normalize_wave clamps illegal
    #: combinations (pod affinity / host ports force 1). YAML: top-level
    #: ``wave_width: 8``.
    wave_width: int = 1
    #: fleet runtime (volcano_tpu/fleet): max tenants served per fleet
    #: cycle. None (default) serves every admitted tenant each cycle; a
    #: finite value makes the cross-tenant fairness pass (the proportion
    #: plugin's weighted water-fill lifted one level up) pick the
    #: highest-deficit tenants under load. YAML: top-level
    #: ``fleet_slots: 8``.
    fleet_slots: Optional[int] = None
    #: fleet per-tenant checkpoint directory (one PR 10 envelope per
    #: tenant, ``tenant-<name>.vckp`` — a corrupt file cold-fuses only its
    #: own tenant). None = checkpointing only via explicit
    #: FleetScheduler.checkpoint(dir) calls. YAML: top-level
    #: ``fleet_checkpoint_dir: /var/run/volcano``.
    fleet_checkpoint_dir: Optional[str] = None

    def plugin_option(self, name: str) -> Optional[PluginOption]:
        for tier in self.tiers:
            for opt in tier.plugins:
                if opt.name == name:
                    return opt
        return None

    def enabled(self, name: str) -> bool:
        return self.plugin_option(name) is not None

    def action_arguments(self, action: str) -> Dict[str, Any]:
        for c in self.configurations:
            if c.name == action:
                return c.arguments
        return {}


_BOOL_KEYS = {
    "enableJobOrder": "enabled_job_order",
    "enableNamespaceOrder": "enabled_namespace_order",
    "enableHierarchy": "enabled_hierarchy",
    "enableJobReady": "enabled_job_ready",
    "enableJobPipelined": "enabled_job_pipelined",
    "enableTaskOrder": "enabled_task_order",
    "enablePreemptable": "enabled_preemptable",
    "enableReclaimable": "enabled_reclaimable",
    "enableQueueOrder": "enabled_queue_order",
    "enablePredicate": "enabled_predicate",
    "enableBestNode": "enabled_best_node",
    "enableNodeOrder": "enabled_node_order",
    "enableTargetJob": "enabled_target_job",
    "enableReservedNodes": "enabled_reserved_nodes",
    "enableJobEnqueued": "enabled_job_enqueued",
    "enableVictim": "enabled_victim",
    "enableJobStarving": "enabled_job_starving",
}


def parse_conf(text: Optional[str] = None) -> SchedulerConfiguration:
    """Parse reference-shaped YAML; raises ValueError on the hdrf+proportion
    conflict exactly like unmarshalSchedulerConf (util.go:60-71)."""
    data = yaml.safe_load(text or DEFAULT_SCHEDULER_CONF) or {}
    sc = SchedulerConfiguration()
    sc.telemetry = bool(data.get("telemetry", False))
    sc.delta_uploads = bool(data.get("delta_uploads", True))
    sc.pipeline = bool(data.get("pipeline", False))
    sc.pipeline_depth = max(1, int(data.get("pipeline_depth", 1) or 1))
    cache_dir = data.get("compilation_cache_dir")
    sc.compilation_cache_dir = str(cache_dir) if cache_dir else None
    ddl = data.get("cycle_deadline_ms")
    sc.cycle_deadline_ms = float(ddl) if ddl else None
    sc.sharding = bool(data.get("sharding", False))
    sd = data.get("sharding_devices")
    sc.sharding_devices = int(sd) if sd is not None else None
    mh = data.get("mesh_hosts")
    sc.mesh_hosts = int(mh) if mh is not None else None
    sc.use_pallas = data.get("use_pallas")
    sc.wave_width = max(1, int(data.get("wave_width", 1) or 1))
    fs = data.get("fleet_slots")
    sc.fleet_slots = int(fs) if fs is not None else None
    fcd = data.get("fleet_checkpoint_dir")
    sc.fleet_checkpoint_dir = str(fcd) if fcd else None
    raw_actions = data.get("actions", "enqueue, allocate, backfill")
    if isinstance(raw_actions, str):
        sc.actions = [a.strip() for a in raw_actions.split(",") if a.strip()]
    else:
        sc.actions = list(raw_actions)

    hdrf = proportion = False
    for tier_data in data.get("tiers", []) or []:
        tier = Tier()
        for p in tier_data.get("plugins", []) or []:
            opt = PluginOption(name=p["name"],
                               arguments=dict(p.get("arguments") or {}))
            for yaml_key, attr in _BOOL_KEYS.items():
                if yaml_key in p:
                    setattr(opt, attr, bool(p[yaml_key]))
            if opt.name == "drf" and opt.enabled_hierarchy:
                hdrf = True
            if opt.name == "proportion":
                proportion = True
            tier.plugins.append(opt)
        sc.tiers.append(tier)
    if hdrf and proportion:
        raise ValueError("proportion and drf with hierarchy enabled conflicts")

    for c in data.get("configurations", []) or []:
        sc.configurations.append(
            Configuration(name=c["name"], arguments=dict(c.get("arguments") or {})))
    if not sc.tiers:
        sc.tiers = parse_conf(DEFAULT_SCHEDULER_CONF).tiers
    return sc
