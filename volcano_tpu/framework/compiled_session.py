"""The whole session policy as ONE jittable program: snapshot -> decisions.

``make_conf_cycle(conf)`` composes the allocate kernel AND the array-level
plugin contributions (proportion's deserved water-filling, drf's job/
namespace shares, hdrf's hierarchical keys) into a single function of the
snapshot, so a TPU process needs nothing but arrays — this is what the
scheduling sidecar serves, and what the reference does across
OpenSession -> plugin OnSessionOpen -> action Execute
(framework.go:29-54, proportion.go:95-197, drf.go:104-360) in Go callbacks.

Plugins that need object-level inputs (tdm's revocable-zone windows,
task-topology's bucket assignments, reservation's elect state) stay on the
session path: their contributions arrive via AllocateExtras, and the
in-process Session remains the full-fidelity driver. The compiled path
covers the shipped conf presets (conf/*.conf), none of which enable those
three.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from ..arrays.schema import SnapshotArrays
from ..ops.allocate_scan import (AllocateConfig, AllocateExtras,
                                 derive_batching, make_allocate_cycle)
from ..ops.fairshare import proportion_deserved
from .conf import SchedulerConfiguration, parse_conf


def _plugin_options(sc: SchedulerConfiguration):
    return [opt for tier in sc.tiers for opt in tier.plugins]


def allocate_config_from_conf(sc: SchedulerConfiguration) -> AllocateConfig:
    """Derive the kernel-composition config from a policy file alone —
    mirrors Session.allocate_config (score_weights read only plugin args)."""
    from ..plugins.factory import build_plugin
    weights = dict(binpack_weight=0.0, least_allocated_weight=0.0,
                   most_allocated_weight=0.0, balanced_weight=0.0,
                   taint_prefer_weight=0.0)
    any_scorer = False
    has_gang = False
    has_proportion = False
    drf_opt = None
    for opt in _plugin_options(sc):
        if opt.name == "gang":
            has_gang = True
        if opt.name == "proportion":
            has_proportion = True
        if opt.name == "drf":
            drf_opt = opt
        plugin = build_plugin(opt)
        w = plugin.score_weights(None)
        if w:
            any_scorer = True
            for k, v in w.items():
                weights[k] = weights.get(k, 0.0) + v
    if not any_scorer:
        weights.update(least_allocated_weight=1.0, balanced_weight=1.0)
    enable_hdrf = drf_opt is not None and drf_opt.enabled_hierarchy
    drf_job_order = drf_opt is not None and drf_opt.enabled_job_order
    drf_ns_order = drf_opt is not None and drf_opt.enabled_namespace_order
    # Batching is derivable from the conf alone: no proportion plugin
    # means deserved stays neutral (infinite) for the whole cycle.
    # derive_batching (ops/allocate_scan.py) owns the rule — static-key
    # confs batch K pre-selected sections, dynamic-key confs (drf/hdrf
    # ordering or proportion) get the in-kernel-selection batch_rounds
    # path.
    return derive_batching(AllocateConfig(
        enable_gang=has_gang,
        enable_hdrf=enable_hdrf,
        drf_job_order=drf_job_order,
        drf_ns_order=drf_ns_order,
        # in-graph telemetry rides the conf (top-level ``telemetry: true``)
        # so a served sidecar cycle carries the same counter block an
        # in-process Session would
        telemetry=bool(getattr(sc, "telemetry", False)),
        # kernel-path override (``use_pallas: true|false|interpret``) —
        # same threading Session._allocate_config does, so a served conf
        # selects the same kernel an in-process Session would
        use_pallas=getattr(sc, "use_pallas", None),
        # wavefront width (top-level ``wave_width: 8``) — decision-
        # neutral by the order-preserving commit rule, validated/clamped
        # by derive_batching's normalize_wave pass
        wave_width=int(getattr(sc, "wave_width", 1)),
        **weights), has_proportion=has_proportion)


def make_conf_cycle(conf: Optional[object] = None, hierarchy=None,
                    cfg_overrides: Optional[dict] = None, mesh=None):
    """conf (SchedulerConfiguration | YAML text | None) -> jittable
    cycle(snap, hierarchy=None, base_extras=None) -> AllocateResult with
    in-graph plugin extras.

    ``mesh``: the 1-D node mesh when the caller runs this cycle sharded
    (the sidecar's per-bucket meshes). Passed through to
    make_allocate_cycle, which then honors ``use_pallas`` via the
    shard-local candidate launch instead of disabling it — see
    parallel/sharding.py.

    ``hierarchy`` (arrays/hierarchy.HierarchyArrays) supplies the hdrf tree
    topology when the conf enables drf hierarchy — either baked here or
    passed per call (the sidecar rebuilds it from the VCS4 wire's queue
    annotations via native/pywire.decode_hierarchy). An hdrf conf with no
    tree warns and degrades to a root-only tree (neutral queue keys).

    ``base_extras`` (AllocateExtras) replaces the neutral starting point —
    the sidecar passes the host extras decoded from the VCX1 wire frame
    (node-affinity masks, ports, volumes) so the served cycle starts from
    the same inputs an in-process Session would; the conf-derived pieces
    (hierarchy, proportion deserved) are still applied here on top."""
    if conf is None or isinstance(conf, str):
        sc = parse_conf(conf)
    else:
        sc = conf
    options = {opt.name: opt for opt in _plugin_options(sc)}
    cfg = allocate_config_from_conf(sc)
    if cfg_overrides:
        import dataclasses as _dc
        cfg = _dc.replace(cfg, **cfg_overrides)
    allocate = make_allocate_cycle(cfg, mesh=mesh)
    proportion_on = "proportion" in options
    baked_hierarchy = hierarchy

    def cycle(snap: SnapshotArrays, hierarchy=None, base_extras=None):
        snap = jax.tree.map(jnp.asarray, snap)
        extras = jax.tree.map(
            jnp.asarray,
            base_extras if base_extras is not None
            else AllocateExtras.neutral(snap))
        tree = hierarchy if hierarchy is not None else baked_hierarchy
        if tree is not None:
            extras.hierarchy = jax.tree.map(jnp.asarray, tree)
        elif cfg.enable_hdrf:
            import warnings
            warnings.warn(
                "conf enables drf hierarchy but no HierarchyArrays were "
                "supplied; hdrf queue ordering degrades to neutral keys",
                stacklevel=2)
        total = snap.cluster_capacity
        if proportion_on:
            extras.queue_deserved = proportion_deserved(snap.queues, total)
        # drf job/namespace shares and the hdrf queue keys are computed
        # in-kernel from the live allocations (cfg.drf_job_order /
        # drf_ns_order / enable_hdrf), matching the reference's
        # event-updated attrs rather than a per-cycle snapshot
        return allocate(snap, extras)

    return cycle
