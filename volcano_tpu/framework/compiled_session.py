"""The whole session policy as ONE jittable program: snapshot -> decisions.

``make_conf_cycle(conf)`` composes the allocate kernel AND the array-level
plugin contributions (proportion's deserved water-filling, drf's job/
namespace shares, hdrf's hierarchical keys) into a single function of the
snapshot, so a TPU process needs nothing but arrays — this is what the
scheduling sidecar serves, and what the reference does across
OpenSession -> plugin OnSessionOpen -> action Execute
(framework.go:29-54, proportion.go:95-197, drf.go:104-360) in Go callbacks.

Plugins that need object-level inputs (tdm's revocable-zone windows,
task-topology's bucket assignments, reservation's elect state) stay on the
session path: their contributions arrive via AllocateExtras, and the
in-process Session remains the full-fidelity driver. The compiled path
covers the shipped conf presets (conf/*.conf), none of which enable those
three.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from ..arrays.schema import SnapshotArrays
from ..ops.allocate_scan import (AllocateConfig, AllocateExtras,
                                 make_allocate_cycle)
from ..ops.fairshare import (drf_job_shares, hierarchical_shares,
                             namespace_shares, proportion_deserved)
from .conf import SchedulerConfiguration, parse_conf


def _plugin_options(sc: SchedulerConfiguration):
    return [opt for tier in sc.tiers for opt in tier.plugins]


def allocate_config_from_conf(sc: SchedulerConfiguration) -> AllocateConfig:
    """Derive the kernel-composition config from a policy file alone —
    mirrors Session.allocate_config (score_weights read only plugin args)."""
    from ..plugins.factory import build_plugin
    weights = dict(binpack_weight=0.0, least_allocated_weight=0.0,
                   most_allocated_weight=0.0, balanced_weight=0.0,
                   taint_prefer_weight=0.0)
    any_scorer = False
    has_gang = False
    for opt in _plugin_options(sc):
        if opt.name == "gang":
            has_gang = True
        plugin = build_plugin(opt)
        w = plugin.score_weights(None)
        if w:
            any_scorer = True
            for k, v in w.items():
                weights[k] = weights.get(k, 0.0) + v
    if not any_scorer:
        weights.update(least_allocated_weight=1.0, balanced_weight=1.0)
    return AllocateConfig(enable_gang=has_gang, **weights)


def make_conf_cycle(conf: Optional[object] = None):
    """conf (SchedulerConfiguration | YAML text | None) -> jittable
    cycle(snap) -> AllocateResult with in-graph plugin extras."""
    if conf is None or isinstance(conf, str):
        sc = parse_conf(conf)
    else:
        sc = conf
    options = {opt.name: opt for opt in _plugin_options(sc)}
    cfg = allocate_config_from_conf(sc)
    allocate = make_allocate_cycle(cfg)
    proportion_on = "proportion" in options
    drf_opt = options.get("drf")
    drf_job_order = drf_opt is not None and drf_opt.enabled_job_order
    drf_ns_order = drf_opt is not None and drf_opt.enabled_namespace_order
    hdrf_on = drf_opt is not None and drf_opt.enabled_hierarchy

    def cycle(snap: SnapshotArrays):
        snap = jax.tree.map(jnp.asarray, snap)
        extras = jax.tree.map(jnp.asarray, AllocateExtras.neutral(snap))
        total = snap.cluster_capacity
        if proportion_on:
            extras.queue_deserved = proportion_deserved(snap.queues, total)
        if drf_job_order:
            # drf JobOrderFn share (drf.go:454-472)
            extras.job_share = drf_job_shares(
                snap.jobs.allocated, total, snap.jobs.valid)
        if drf_ns_order:
            extras.ns_share = namespace_shares(
                snap.jobs.allocated, snap.jobs.namespace, snap.jobs.valid,
                snap.namespace_weight, total)
        if hdrf_on:
            extras.queue_share_extra = hierarchical_shares(
                snap.queues, total, snap.queues.hier_weight)
        return allocate(snap, extras)

    return cycle
