"""Session framework (reference: pkg/scheduler/framework)."""

from .conf import (DEFAULT_SCHEDULER_CONF, Configuration, PluginOption,
                   SchedulerConfiguration, Tier, parse_conf)
from .session import BindIntent, EvictIntent, Session

__all__ = [
    "DEFAULT_SCHEDULER_CONF", "Configuration", "PluginOption",
    "SchedulerConfiguration", "Tier", "parse_conf", "BindIntent",
    "EvictIntent", "Session",
]
