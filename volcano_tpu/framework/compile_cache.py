"""Opt-in persistent XLA compilation cache for the cycle programs.

Every scheduler/sidecar restart used to re-pay the full trace+compile of
the fused cycle (BENCH records ``compile_s`` ~= 4.0 on this machine's CPU
backend; a driver-TPU mosaic lowering costs more). jax ships a persistent
compilation cache keyed by the serialized HLO — enabling it turns the
restart cost into a disk read for every shape/delta bucket the process has
ever compiled.

Opt-in only (the cache directory is a deployment decision):

- conf: top-level ``compilation_cache_dir: /path`` (framework/conf.py)
- env:  ``VOLCANO_JAX_CACHE_DIR=/path`` (wins over nothing, loses to an
  explicit argument)

Pair with the AOT warmup hooks (``Scheduler.warmup`` /
``SchedulerSidecar.warmup``) to move even the first cycle's compile off
the serving path.
"""

from __future__ import annotations

import os
from typing import Optional


def enable_compilation_cache(path: Optional[str] = None) -> Optional[str]:
    """Point jax's persistent compilation cache at ``path`` (or
    ``$VOLCANO_JAX_CACHE_DIR``). Returns the directory in effect, or None
    when disabled/unavailable. Safe to call repeatedly and before or after
    backend init; failures are swallowed (an old jax without the knob must
    not take the scheduler down)."""
    path = path or os.environ.get("VOLCANO_JAX_CACHE_DIR")
    if not path:
        return None
    import jax
    try:
        jax.config.update("jax_compilation_cache_dir", path)
        jax.config.update(
            "jax_persistent_cache_min_compile_time_secs",
            float(os.environ.get("VOLCANO_JAX_CACHE_MIN_S", 1.0)))
    except Exception:
        return None
    return path
