"""Graphcheck: trace-time static analysis of the compiled scheduling cycle.

The paper's north star is the whole cycle (predicate x score x argmax,
fairness pops, preempt) as ONE compiled TPU program — which means whole
failure classes live in the traced graph, not in any single Python line:
a host callback smuggled into the hot path, a float64/weak-type promotion
that doubles VMEM traffic (or breaks mosaic, which has no 64-bit types),
an O(M*N) jobs-x-nodes re-materialization (the regression class the PR 1
affinity rounds fixed), a Python-value-dependent shape that recompiles
per cycle, or a Pallas kernel whose VMEM footprint outgrows the core.
Every one of those is visible at TRACE time on a plain CPU — graphcheck
walks the closed jaxprs of the real entry points (framework session +
compiled_session conf presets, the ops/ cycle functions, both Pallas
kernel builders) and turns each class into a CI failure instead of a
driver-TPU surprise.

Check families (all run by default; the authoritative list is the
``FAMILIES`` tuple below — the CLI derives its help text from it):

- ``purity``       — no pure_callback/io_callback/debug_callback
                     primitives anywhere in a compiled cycle.
- ``dtype``        — no 64-bit (float64/int64) intermediates when the
                     cycle is traced under enable_x64 with 32-bit inputs:
                     any 64-bit value is a weak-type/default-dtype
                     promotion leak that production silently truncates
                     only because x64 is globally off.
- ``gather``       — no intermediate carrying BOTH a task-axis dim and
                     the node-axis dim (the [M, N] gather
                     re-materialization class; shapes are made
                     distinguishable by construction, see entrypoints).
- ``wavefront``    — wave entries (``wave_width`` > 1, ISSUE 16) sweep
                     their W candidate tasks as (W, N) intermediates:
                     no rank-3 intermediate may combine the wave axis,
                     a task axis, AND the node axis — the O(W*T*N)
                     re-materialization that would erase the batched
                     sweep's arithmetic-intensity win.
- ``recompile``    — each jitted entry point compiles exactly once per
                     problem-size bucket: re-invoking with fresh
                     same-shaped inputs must not retrace.
- ``vmem``         — the static VMEM footprint of every Pallas kernel
                     input/output (whole-array BlockSpecs) stays under
                     the per-core budget, the ``vmem_estimate_bytes``
                     gate never understates the traced truth, and the
                     north-star-scale projection clears the budget.
- ``cost``         — the whole-cycle static cost model (costmodel.py):
                     per-entry FLOPs / unfused HBM bytes / arithmetic
                     intensity from a trip-count-aware per-primitive
                     table, a donation-aware liveness sweep yielding the
                     static peak-live HBM watermark per entry (gated
                     against a per-chip budget, default 16 GiB), a
                     collective-bytes audit of the sharded cycle (jaxpr
                     collectives + GSPMD-inserted HLO collectives, with
                     the cross-shard bytes' node-axis growth exponent
                     gated), and a north-star projection: each entry
                     traced at 2-3 problem sizes, power-law growth
                     fitted, peak HBM + collective bytes projected to
                     100k nodes / 1M tasks against the budget.
- ``obligations``  — ``derive_batching`` stays the single authority for
                     the static-segment batching rule: the rule itself is
                     re-derived and re-verified, the illegal static-K +
                     dynamic-keys combination still raises, and an AST
                     scan proves no construction site in the package
                     hand-sets ``batch_jobs``/``batch_rounds``.
- ``telemetry``    — the in-graph cycle-telemetry contract
                     (volcano_tpu/telemetry): counter outputs are pure
                     i32/f32, the telemetry=True build introduces no
                     callbacks / 64-bit leaks / per-cycle retraces, and
                     with telemetry=False (default) the counters are
                     dead-code-eliminated — nothing telemetry-shaped in
                     the outputs, jaxpr equation-count-identical to a
                     telemetry-free build.
- ``donation``     — the device-resident delta-upload contract
                     (ops/fused_io.DeltaKernel): the update+cycle entry's
                     donation matches the platform contract (resident
                     buffers donated on accelerators, none on CPU where
                     donation forces inline execution), every consumed
                     handle is invalidated within one dispatch (a host
                     re-read fails fast instead of silently reading
                     aliased post-scatter memory on TPU), the delta
                     scatter stays device-pure, and delta-ingested
                     decisions are byte-identical to a full upload.
- ``sharding``     — the node-axis sharded execution mode
                     (ops/fused_io.ShardedDeltaKernel): the compiled
                     GSPMD module contains no all-gather whose output
                     re-materializes O(nodes) state (mesh-sized digest
                     gathers and single node-axis column syncs are
                     priced in), the packed decisions leave the entry
                     fully replicated, and every resident output keeps
                     its declared input sharding (out == in: the
                     zero-resharding steady-state contract). Reports
                     nothing when fewer than two devices are visible.
- ``fleet``        — the multi-tenant batched cycle
                     (fleet/pool.FleetDeltaKernel): the vmapped entry
                     stays callback-free, every decision output carries
                     the leading tenant axis at the bucket width, and a
                     value-level probe proves NO cross-tenant data flow —
                     perturbing one tenant's stacked inputs leaves every
                     other tenant's packed decisions (digest included)
                     bit-identical.
- ``hygiene``      — metrics exposition hygiene: an AST scan over the
                     package finds every statically-named metric
                     emission and requires an explicit ``_HELP`` entry
                     (no generated filler text on /metrics), and a live
                     exposition is checked for the ``# HELP``/``# TYPE``
                     pair ahead of every sample family.

Run ``python -m volcano_tpu.analysis`` (wrapped by scripts/graphcheck.sh)
for the CLI; tier-1 runs the same pass via tests/test_graphcheck.py.
Intentional findings are registered in :mod:`.allowlist` with a one-line
justification.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import time
from typing import List, Optional, Sequence

FAMILIES = ("purity", "dtype", "gather", "wavefront", "recompile", "vmem",
            "cost", "obligations", "telemetry", "donation", "sharding",
            "fleet", "hygiene")


@dataclasses.dataclass
class Finding:
    """One violation of a framework invariant.

    ``key`` is the stable identity string the allowlist matches on
    (family:location:detail); ``what`` is the human-readable sentence.
    """

    family: str
    key: str
    where: str
    what: str
    allowlisted: bool = False
    reason: Optional[str] = None

    def to_dict(self):
        return dataclasses.asdict(self)


def apply_allowlist(findings: Sequence[Finding]) -> List[Finding]:
    from .allowlist import ALLOWLIST
    for f in findings:
        for entry in ALLOWLIST:
            if entry.family == f.family and entry.match in f.key:
                f.allowlisted = True
                f.reason = entry.reason
                break
    return list(findings)


def run_graphcheck(families: Optional[Sequence[str]] = None,
                   fast: bool = False,
                   vmem_budget_bytes: Optional[int] = None,
                   cost_hbm_budget_bytes: Optional[int] = None,
                   repo_root: Optional[str] = None) -> dict:
    """Run the requested check families and assemble the report dict.

    ``fast`` prunes the traced-entry set to a representative subset (one
    entry per graph shape) so the tier-1 test stays cheap; the CLI runs
    the full set. The report is machine-readable (see schema below) and
    carries a content sha so bench records can fingerprint the
    static-analysis state alongside the decision fingerprints.
    ``meta["family_stats"]`` records per-family wall time and finding
    counts so a creeping CI budget is attributable to one family.
    """
    families = list(families) if families else list(FAMILIES)
    unknown = [f for f in families if f not in FAMILIES]
    if unknown:
        raise ValueError(f"unknown graphcheck families: {unknown}; "
                         f"known: {list(FAMILIES)}")
    t0 = time.time()
    findings: List[Finding] = []
    fam_meta = {}
    fam_stats = {f: {"elapsed_s": 0.0, "findings": 0}
                 for f in FAMILIES if f in families}

    def _timed(fam, check, *args, **kwargs):
        ts = time.time()
        out = check(*args, **kwargs)
        fam_stats[fam]["elapsed_s"] += time.time() - ts
        fam_stats[fam]["findings"] += len(out)
        return out

    need_traces = bool({"purity", "dtype", "gather", "wavefront", "vmem",
                        "cost"} & set(families))
    traces = []
    if need_traces:
        from .entrypoints import build_traces
        ts = time.time()
        traces = build_traces(fast=fast)
        fam_meta["traced_entry_points"] = [t.name for t in traces]
        fam_meta["trace_build_s"] = round(time.time() - ts, 2)

    jaxpr_fams = {"purity", "dtype", "gather", "wavefront"} & set(families)
    if jaxpr_fams:
        from .jaxpr_audit import (check_dtype, check_gather, check_purity,
                                  check_wavefront)
        for tr in traces:
            if "purity" in families:
                findings += _timed("purity", check_purity, tr)
            if "dtype" in families:
                findings += _timed("dtype", check_dtype, tr)
            if "gather" in families:
                findings += _timed("gather", check_gather, tr)
            if "wavefront" in families:
                findings += _timed("wavefront", check_wavefront, tr)

    if "vmem" in families:
        from .vmem import check_vmem
        findings += _timed("vmem", check_vmem, traces,
                           budget_bytes=vmem_budget_bytes)

    if "cost" in families:
        from .costmodel import check_cost
        cost_meta = fam_meta.setdefault("cost", {})
        findings += _timed("cost", check_cost, traces, fast=fast,
                           hbm_budget_bytes=cost_hbm_budget_bytes,
                           meta=cost_meta)

    if "recompile" in families:
        from .recompile import check_recompile
        findings += _timed("recompile", check_recompile, fast=fast)

    if "obligations" in families:
        from .obligations import check_obligations
        findings += _timed("obligations", check_obligations,
                           repo_root=repo_root)

    if "telemetry" in families:
        from .telemetry import check_telemetry
        findings += _timed("telemetry", check_telemetry, fast=fast)

    if "donation" in families:
        from .donation import check_donation
        findings += _timed("donation", check_donation, fast=fast)

    if "sharding" in families:
        from .sharding import check_sharding
        findings += _timed("sharding", check_sharding, fast=fast)

    if "fleet" in families:
        from .fleet import check_fleet
        findings += _timed("fleet", check_fleet, fast=fast)

    if "hygiene" in families:
        from .hygiene import check_hygiene
        findings += _timed("hygiene", check_hygiene, repo_root=repo_root)

    for st in fam_stats.values():
        st["elapsed_s"] = round(st["elapsed_s"], 2)
    fam_meta["family_stats"] = fam_stats

    findings = apply_allowlist(findings)
    blocking = [f for f in findings if not f.allowlisted]
    report = {
        "graphcheck_version": 1,
        "clean": not blocking,
        "families": {f: f in families for f in FAMILIES},
        "finding_count": len(findings),
        "blocking_count": len(blocking),
        "findings": [f.to_dict() for f in findings],
        "meta": fam_meta,
        "elapsed_s": round(time.time() - t0, 2),
    }
    report["report_sha256"] = report_sha(report)
    return report


def report_sha(report: dict) -> str:
    """Content fingerprint over everything decision-relevant in the report
    (NOT elapsed time), for the bench record's graphcheck column."""
    core = {k: report[k] for k in
            ("graphcheck_version", "clean", "families", "findings")}
    return hashlib.sha256(
        json.dumps(core, sort_keys=True).encode()).hexdigest()[:16]
