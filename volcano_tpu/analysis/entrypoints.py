"""Traced entry-point registry for graphcheck.

Builds small REAL snapshots (through the same ``arrays.pack`` path every
production cycle uses) and traces the real entry points with abstract
values: the XLA scan cycle, both Pallas kernel builders (static-keys
K-batch and dynamic-keys in-kernel-selection, traced in interpret mode so
the ``pallas_call`` primitive and its kernel jaxpr appear on CPU), the
conf-preset compiled cycles (framework/compiled_session), the in-process
Session's derived config (framework/session), and the enqueue / backfill /
preempt passes.

Shape discipline for the gather audit: the synthetic sizes are chosen so
the PADDED axes are distinguishable — the node axis buckets to a size no
task-ish axis (T, J*M, K*M) shares, so "an intermediate carrying both a
task dim and the node dim" is decidable by exact dim match. See
``_AUDIT_SIZE`` below; changing it requires re-checking the bucket table
in arrays/schema.bucket.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Optional

#: (n_nodes, n_jobs, tasks_per_job) for the audited traces. Buckets to
#: N=128, T=32, J=16, M=4 — so N collides with NO task-ish axis
#: (T=32, J*M=64, K*M=32 for K=8) and the gather audit can key on exact
#: dims. Changing this requires re-checking arrays/schema.bucket.
_AUDIT_SIZE = (100, 10, 3)
#: second size for the recompile lint (distinct buckets: N=64, T=32)
_ALT_SIZE = (48, 10, 3)


def _mini_cluster(n_nodes: int, n_jobs: int, tasks_per_job: int,
                  seed: int = 0, affinity: bool = False):
    """Small ClusterInfo in the same shape family as the bench/driver
    synthetic cluster (two queues, Inqueue gangs, mixed cpu requests) —
    local so the analysis package has no repo-root import."""
    import numpy as np
    from ..api import (ClusterInfo, JobInfo, NodeInfo, PodGroupPhase,
                       QueueInfo, Resource, TaskInfo)
    rng = np.random.RandomState(seed)
    ci = ClusterInfo()
    for i in range(n_nodes):
        node = NodeInfo(
            f"n{i:05d}",
            allocatable=Resource.from_resource_list(
                {"cpu": "16", "memory": "64Gi", "pods": "110"}))
        if affinity:
            node.labels["zone"] = f"z{i % 4}"
        ci.add_node(node)
    ci.add_queue(QueueInfo("default", weight=1))
    ci.add_queue(QueueInfo("batch", weight=2))
    for j in range(n_jobs):
        job = JobInfo(f"default/job-{j:05d}",
                      queue="default" if j % 2 == 0 else "batch",
                      min_available=max(1, tasks_per_job // 2),
                      priority=int(rng.randint(3)),
                      creation_timestamp=float(j),
                      pod_group_phase=PodGroupPhase.INQUEUE)
        for t in range(tasks_per_job):
            task = TaskInfo(
                uid=f"default/job-{j:05d}-{t}", name=f"job-{j:05d}-{t}",
                resreq=Resource.from_resource_list(
                    {"cpu": f"{rng.randint(1, 4) * 500}m", "memory": "1Gi"}))
            if affinity:
                from ..api import PodAffinityTerm
                task.labels["app"] = f"app{j % 4}"
                if j % 3 == 0:
                    task.pod_anti_affinity = [PodAffinityTerm(
                        topology_key="zone",
                        match_labels={"app": f"app{j % 4}"})]
                elif j % 3 == 1:
                    task.pod_affinity_preferred = [PodAffinityTerm(
                        topology_key="zone",
                        match_labels={"app": f"app{j % 4}"}, weight=10)]
            job.add_task(task)
        ci.add_job(job)
    return ci


def _snap_extras(size=_AUDIT_SIZE, affinity: bool = False):
    import dataclasses as dc
    from ..arrays import pack
    from ..ops.allocate_scan import AllocateExtras
    ci = _mini_cluster(*size, affinity=affinity)
    snap, maps = pack(ci)
    extras = AllocateExtras.neutral(snap)
    if affinity:
        from ..arrays.affinity import build_affinity
        N = snap.nodes.idle.shape[0]
        T = snap.tasks.resreq.shape[0]
        extras = dc.replace(extras,
                            affinity=build_affinity(ci, maps, N, T))
    return snap, extras


def _dims(snap, cfg=None, extras=None) -> Dict[str, object]:
    """Semantic axis sizes of a packed snapshot, for the gather audit and
    the VMEM estimator cross-check."""
    N, R = snap.nodes.idle.shape
    J, M = snap.jobs.task_table.shape
    T = snap.tasks.resreq.shape[0]
    d = dict(N=N, R=R, J=J, M=M, T=T,
             G=snap.nodes.gpu_memory.shape[1],
             P=snap.template_rep.shape[0],
             Q=snap.queues.allocated.shape[0],
             S=snap.namespace_weight.shape[0],
             GR=extras.or_feasible.shape[0] if extras is not None else 1,
             SK=(extras.affinity.sk_domain.shape[0]
                 if extras is not None else 0),
             ETA=(extras.affinity.eta_domain.shape[0]
                  if extras is not None else 0),
             SEL=(extras.affinity.task_match.shape[0]
                  if extras is not None else 0))
    task_dims = {T, J * M}
    if cfg is not None and cfg.batch_jobs > 1:
        task_dims.add(cfg.batch_jobs * M)
    d["task_dims"] = task_dims
    return d


@dataclasses.dataclass
class EntryTrace:
    """One traced entry point: its closed jaxpr (traced under enable_x64
    with 32-bit inputs) plus the dim map the audits key on."""

    name: str
    closed: object                  # jax.core.ClosedJaxpr
    dims: Dict[str, object]
    cfg: Optional[object] = None    # AllocateConfig when applicable
    #: entry invar indices the jitted wrapper donates (the cost family's
    #: liveness sweep frees those at last use; empty on CPU, where
    #: ops/fused_io.donation_for_backend declines donation)
    donated: tuple = ()


def _allocate_cfgs(fast: bool):
    import dataclasses as dc
    from ..ops.allocate_scan import AllocateConfig, derive_batching
    base = AllocateConfig(binpack_weight=1.0, enable_gpu=False)
    cfgs = [
        ("allocate/scan", dc.replace(
            derive_batching(base, has_proportion=False), use_pallas=False)),
        ("allocate/pallas_static", dc.replace(
            derive_batching(base, has_proportion=False),
            use_pallas="interpret")),
        ("allocate/pallas_dyn", dc.replace(
            derive_batching(dc.replace(base, drf_job_order=True),
                            has_proportion=False),
            use_pallas="interpret")),
        # wavefront placement (ISSUE 16): the W>1 while_loop body under
        # every jaxpr family, plus the wavefront-specific (W, task, N)
        # materialization check. W=4 collides with NO audit dim that
        # matters (task_dims={T=32, J*M=64}; N=128), so the wave axis is
        # distinguishable by construction like everything else here.
        ("allocate/wave4", dc.replace(
            derive_batching(dc.replace(base, wave_width=4),
                            has_proportion=False),
            use_pallas=False)),
    ]
    if not fast:
        cfgs.append(("allocate/pallas_affinity", dc.replace(
            derive_batching(dc.replace(base, enable_pod_affinity=True),
                            has_proportion=False),
            use_pallas="interpret")))
        # the widest supported wave (candidate depth clamps at 8 < W, so
        # the truncation/replay arm of the commit rule is in the trace)
        cfgs.append(("allocate/wave16", dc.replace(
            derive_batching(dc.replace(base, wave_width=16),
                            has_proportion=False),
            use_pallas=False)))
    return cfgs


def _conf_presets(fast: bool):
    """(name, conf text) for every parseable tiered policy in conf/."""
    import os
    root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from ..framework.conf import parse_conf
    out = []
    conf_dir = os.path.join(root, "conf")
    names = sorted(os.listdir(conf_dir)) if os.path.isdir(conf_dir) else []
    for fname in names:
        if not fname.endswith(".conf"):
            continue
        with open(os.path.join(conf_dir, fname)) as f:
            text = f.read()
        try:
            sc = parse_conf(text)
        except Exception:
            continue
        if not sc.tiers:
            continue    # hierarchy-weights files are not scheduler policies
        out.append((f"conf/{fname[:-len('.conf')]}", text))
        if fast:
            break
    return out


def build_traces(fast: bool = False) -> List[EntryTrace]:
    """Trace every entry point under enable_x64 (inputs stay 32-bit, so
    any 64-bit intermediate is a promotion leak) and return the closed
    jaxprs for the purity/dtype/gather/vmem walks."""
    import jax
    from ..ops.allocate_scan import make_allocate_cycle
    traces: List[EntryTrace] = []

    snap, extras = _snap_extras()
    with jax.experimental.enable_x64():
        for name, cfg in _allocate_cfgs(fast):
            if "affinity" in name:
                asnap, aextras = _snap_extras(affinity=True)
                closed = jax.make_jaxpr(make_allocate_cycle(cfg))(
                    asnap, aextras)
                traces.append(EntryTrace(
                    name, closed, _dims(asnap, cfg, aextras), cfg))
            else:
                closed = jax.make_jaxpr(make_allocate_cycle(cfg))(
                    snap, extras)
                traces.append(EntryTrace(
                    name, closed, _dims(snap, cfg, extras), cfg))

        # the delta-update + cycle entry (ops/fused_io.DeltaKernel): the
        # steady-state production program — in-graph scatter of the packed
        # deltas onto the device-resident buffers, then the cycle over the
        # rebuilt tree. Traced with a representative non-empty delta
        # bucket so the scatter path itself is walked.
        from ..ops.fused_io import DeltaKernel
        _scan_cfg = _allocate_cfgs(fast=True)[0][1]
        dk = DeltaKernel(make_allocate_cycle(_scan_cfg), (snap, extras))
        closed = jax.make_jaxpr(dk.traceable)(*dk.example_delta_args())
        traces.append(EntryTrace("fused_io/delta_update", closed,
                                 _dims(snap, _scan_cfg, extras), _scan_cfg,
                                 donated=tuple(dk.donate_argnums)))

        # compiled_session conf presets (in-graph plugin extras included)
        from ..framework.compiled_session import make_conf_cycle
        for name, text in _conf_presets(fast):
            cycle = make_conf_cycle(text)
            closed = jax.make_jaxpr(lambda s: cycle(s))(snap)
            traces.append(EntryTrace(name, closed, _dims(snap)))

        # the in-process Session's derived config (framework/session.py)
        if not fast:
            from ..framework.session import Session
            ssn = Session(_mini_cluster(*_AUDIT_SIZE))
            scfg = ssn.allocate_config()
            sextras = ssn.allocate_extras()
            closed = jax.make_jaxpr(make_allocate_cycle(scfg))(
                ssn.snap, sextras)
            traces.append(EntryTrace("framework/session", closed,
                                     _dims(ssn.snap, scfg, sextras), scfg))
            ssn.close()

        # enqueue / backfill / preempt cycle functions
        import numpy as np
        from ..ops.enqueue import EnqueueConfig, make_enqueue_pass
        J = snap.jobs.min_available.shape[0]
        closed = jax.make_jaxpr(make_enqueue_pass(EnqueueConfig()))(
            snap, np.zeros(J, bool))
        traces.append(EntryTrace("ops/enqueue", closed, _dims(snap)))

        from ..ops.backfill import make_backfill_pass
        closed = jax.make_jaxpr(make_backfill_pass())(snap)
        traces.append(EntryTrace("ops/backfill", closed, _dims(snap)))

        from ..ops.allocate_scan import AllocateConfig
        from ..ops.preempt import PreemptConfig, make_preempt_cycle
        T = snap.tasks.resreq.shape[0]
        pcfg = PreemptConfig(scoring=AllocateConfig(binpack_weight=1.0,
                                                    enable_gpu=False))
        closed = jax.make_jaxpr(make_preempt_cycle(pcfg))(
            snap, extras, np.zeros(T, bool), np.zeros(T, bool))
        traces.append(EntryTrace("ops/preempt", closed, _dims(snap)))

    return traces


def cost_projection_traces(fast: bool = False) -> List[tuple]:
    """(entry_name, [(padded_N, closed_jaxpr, donated), ...]) traced at
    the cost family's projection sizes (costmodel.PROJECTION_SIZES_*) —
    the raw material of the north-star growth-exponent fit.

    Traced WITHOUT enable_x64: the cost model prices the production
    32-bit byte widths (the dtype family separately proves no 64-bit
    intermediate exists, so the x64 trace would carry the same shapes).
    Tracing stays abstract — no compile, no real arrays — so the 512-node
    point costs the same as the 128-node one.
    """
    import jax
    from ..ops.allocate_scan import make_allocate_cycle
    from .costmodel import PROJECTION_SIZES_FAST, PROJECTION_SIZES_FULL

    sizes = PROJECTION_SIZES_FAST if fast else PROJECTION_SIZES_FULL
    packed = [_snap_extras(s) for s in sizes]
    cfgs = dict(_allocate_cfgs(fast=True))
    names = (("allocate/scan",) if fast
             else ("allocate/scan", "allocate/wave4"))
    out: List[tuple] = []
    for name in names:
        cycle = make_allocate_cycle(cfgs[name])
        pts = []
        for snap, extras in packed:
            closed = jax.make_jaxpr(cycle)(snap, extras)
            pts.append((snap.nodes.idle.shape[0], closed, ()))
        out.append((name, pts))
    if not fast:
        # the steady-state delta entry: donation-aware, one kernel per
        # size (the scatter+cycle program the production loop runs)
        from ..ops.fused_io import DeltaKernel
        cycle = make_allocate_cycle(cfgs["allocate/scan"])
        pts = []
        for snap, extras in packed:
            dk = DeltaKernel(cycle, (snap, extras))
            closed = jax.make_jaxpr(dk.traceable)(
                *dk.example_delta_args())
            pts.append((snap.nodes.idle.shape[0], closed,
                        tuple(dk.donate_argnums)))
        out.append(("fused_io/delta_update", pts))
    return out


def recompile_probes(fast: bool = False) -> List[tuple]:
    """(name, build_fn, args_for_size) triples for the recompile lint.

    ``build_fn()`` returns the raw (unjitted) callable; the lint wraps it
    with a trace counter + jax.jit and calls it twice per size. Sizes
    bucket to different shapes, so the expected trace count equals the
    number of sizes — any extra trace is a Python-value-dependent shape
    or control-flow hazard.
    """
    import numpy as np
    from ..ops.allocate_scan import make_allocate_cycle

    sizes = (_AUDIT_SIZE, _ALT_SIZE)
    packed = {s: _snap_extras(s) for s in sizes}

    probes: List[tuple] = []
    for name, cfg in _allocate_cfgs(fast=True):
        if fast and name != "allocate/scan":
            continue
        probes.append((name, lambda cfg=cfg: make_allocate_cycle(cfg),
                       {s: packed[s] for s in sizes}))

    from ..ops.enqueue import EnqueueConfig, make_enqueue_pass

    def enq_args(s):
        snap, _ = packed[s]
        return (snap, np.zeros(snap.jobs.min_available.shape[0], bool))

    probes.append(("ops/enqueue",
                   lambda: make_enqueue_pass(EnqueueConfig()),
                   {s: enq_args(s) for s in sizes}))

    if not fast:
        from ..ops.backfill import make_backfill_pass
        probes.append(("ops/backfill", make_backfill_pass,
                       {s: (packed[s][0],) for s in sizes}))

        from ..ops.allocate_scan import AllocateConfig
        from ..ops.preempt import PreemptConfig, make_preempt_cycle
        pcfg = PreemptConfig(scoring=AllocateConfig(binpack_weight=1.0,
                                                    enable_gpu=False))

        def pre_args(s):
            snap, extras = packed[s]
            T = snap.tasks.resreq.shape[0]
            return (snap, extras, np.zeros(T, bool), np.zeros(T, bool))

        probes.append(("ops/preempt",
                       lambda: make_preempt_cycle(pcfg),
                       {s: pre_args(s) for s in sizes}))

        from ..framework.compiled_session import make_conf_cycle
        presets = _conf_presets(fast=True)
        if presets:
            name, text = presets[0]
            probes.append((name, lambda text=text: make_conf_cycle(text),
                           {s: (packed[s][0],) for s in sizes}))

        # delta-update entry: the "problem sizes" are delta BUCKETS — the
        # steady loop must compile once per bucket, never per delta size
        from ..ops.fused_io import DeltaKernel
        dcfg = _allocate_cfgs(fast=True)[0][1]
        dk = DeltaKernel(make_allocate_cycle(dcfg), packed[_AUDIT_SIZE])
        probes.append(("fused_io/delta_update", lambda dk=dk: dk.traceable,
                       {b: dk.example_delta_args(b) for b in (256, 512)}))
    return probes
