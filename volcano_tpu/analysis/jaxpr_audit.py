"""Jaxpr walkers: hot-path purity, dtype discipline, gather-shape audit.

All three checks share one recursive walk over a closed jaxpr (descending
into while/scan/cond bodies, pjit sub-jaxprs, and the Pallas kernel jaxpr
carried in the ``pallas_call`` params), so one trace per entry point
serves every family.
"""

from __future__ import annotations

from typing import Iterator, List

from . import Finding

#: host-callback primitives that must never appear in a compiled cycle:
#: each one pins the program to a host round-trip per invocation, which
#: destroys the one-launch-per-cycle budget and breaks sharded execution
CALLBACK_PRIMITIVES = frozenset(
    {"pure_callback", "io_callback", "debug_callback", "host_callback_call",
     "outside_call"})

#: 64-bit dtypes that cannot exist on the production path: mosaic has no
#: 64-bit types, and under the production x64-off config these silently
#: truncate — so their appearance under an x64 trace is always a
#: weak-type/default-dtype promotion leak
WIDE_DTYPES = frozenset({"float64", "int64", "uint64", "complex128"})


def iter_eqns(jaxpr) -> Iterator:
    """Yield every eqn in ``jaxpr`` and, recursively, in every sub-jaxpr
    found in eqn params (while/scan/cond bodies, pjit, pallas_call)."""
    from jax.core import ClosedJaxpr, Jaxpr
    for eqn in jaxpr.eqns:
        yield eqn
        for v in eqn.params.values():
            if isinstance(v, ClosedJaxpr):
                yield from iter_eqns(v.jaxpr)
            elif isinstance(v, Jaxpr):
                yield from iter_eqns(v)
            elif isinstance(v, (list, tuple)):
                for x in v:
                    if isinstance(x, ClosedJaxpr):
                        yield from iter_eqns(x.jaxpr)
                    elif isinstance(x, Jaxpr):
                        yield from iter_eqns(x)


def _loc(eqn) -> str:
    """Best-effort user-code location of an eqn ("file.py:line (fn)").

    Caveat: jnp composites are trace-cached, so a sub-jaxpr first traced
    by another entry point can carry that entry's frame — locations are a
    debugging aid, not an identity (the finding key includes them, but a
    clean repo has zero findings so staleness cannot hide anything).
    """
    try:
        from jax._src import source_info_util
        s = source_info_util.summarize(eqn.source_info)
        # keep paths repo-relative so finding keys are machine-stable
        for marker in ("/volcano_tpu/", "/tests/", "/scripts/"):
            i = s.find(marker)
            if i >= 0:
                return s[i + 1:]
        return s
    except Exception:
        return "unknown"


def check_purity(trace) -> List[Finding]:
    """No host-callback primitive anywhere in the compiled cycle."""
    out = []
    seen = set()
    for eqn in iter_eqns(trace.closed.jaxpr):
        name = eqn.primitive.name
        if name in CALLBACK_PRIMITIVES:
            loc = _loc(eqn)
            key = f"purity:{trace.name}:{name}:{loc}"
            if key in seen:
                continue
            seen.add(key)
            out.append(Finding(
                family="purity", key=key, where=f"{trace.name} @ {loc}",
                what=(f"host callback primitive '{name}' inside the "
                      f"compiled cycle '{trace.name}' — the hot path must "
                      "stay device-pure (one launch per cycle)")))
    return out


def check_dtype(trace) -> List[Finding]:
    """No 64-bit intermediates when traced under enable_x64 with 32-bit
    inputs (see entrypoints.build_traces)."""
    out = []
    seen = set()
    for eqn in iter_eqns(trace.closed.jaxpr):
        for v in eqn.outvars:
            aval = getattr(v, "aval", None)
            dt = getattr(aval, "dtype", None)
            if dt is None or str(dt) not in WIDE_DTYPES:
                continue
            loc = _loc(eqn)
            key = f"dtype:{trace.name}:{loc}:{eqn.primitive.name}:{dt}"
            dedup = (loc, str(dt))
            if dedup in seen:
                continue
            seen.add(dedup)
            out.append(Finding(
                family="dtype", key=key, where=f"{trace.name} @ {loc}",
                what=(f"{dt} intermediate ({eqn.primitive.name}) in "
                      f"'{trace.name}': a weak-type/default-dtype "
                      "promotion that only the global x64-off config "
                      "truncates — pin the dtype at the source")))
    return out


def check_wavefront(trace) -> List[Finding]:
    """Wave entries (``wave_width`` > 1) must sweep as (W, N) — never a
    (W, task-axis, N) rank-3 re-materialization.

    The wavefront sweep's whole bargain (ISSUE 16) is that widening the
    per-iteration front from 1 task to W costs O(W*N), not O(W*T*N): the
    W candidate rows are gathered to (W, R)/(W, N) operands and swept
    against the node axis directly. An intermediate carrying the wave
    axis AND a task axis AND the node axis on three distinct axes means
    some per-slot computation re-materialized the full task table per
    node — the O(M*N) gather class with an extra W multiplier on top.
    Applies only to traces whose cfg has ``wave_width`` > 1; the audit
    fixture sizes keep W numerically distinct from every task dim and N.
    """
    cfg = trace.cfg
    W = int(getattr(cfg, "wave_width", 1) or 1) if cfg is not None else 1
    if W <= 1:
        return []
    N = trace.dims["N"]
    task_dims = set(trace.dims["task_dims"]) - {N, W}
    out = []
    seen = set()
    for eqn in iter_eqns(trace.closed.jaxpr):
        for v in eqn.outvars:
            aval = getattr(v, "aval", None)
            shape = getattr(aval, "shape", None)
            if not shape or len(shape) < 3:
                continue
            dims = list(shape)
            if W in dims and N in dims \
                    and any(d in task_dims for d in dims):
                loc = _loc(eqn)
                key = (f"wavefront:{trace.name}:{loc}:"
                       f"{eqn.primitive.name}:{tuple(shape)}")
                dedup = (loc, tuple(shape))
                if dedup in seen:
                    continue
                seen.add(dedup)
                out.append(Finding(
                    family="wavefront", key=key,
                    where=f"{trace.name} @ {loc}",
                    what=(f"O(W*T*N) intermediate of shape {tuple(shape)} "
                          f"({eqn.primitive.name}) in '{trace.name}': the "
                          f"wave sweep must stay (W={W}, N) — gather the "
                          "W candidate rows first, never broadcast the "
                          "full task axis against the node axis")))
    return out


def check_gather(trace) -> List[Finding]:
    """No intermediate carrying BOTH a task-axis dim and the node-axis
    dim — the O(M*N) jobs-x-nodes re-materialization class the PR 1
    affinity rounds eliminated (per-round [M, N] gather outputs serialized
    on TPU and dominated the cycle)."""
    N = trace.dims["N"]
    task_dims = set(trace.dims["task_dims"]) - {N}
    out = []
    seen = set()
    for eqn in iter_eqns(trace.closed.jaxpr):
        for v in eqn.outvars:
            aval = getattr(v, "aval", None)
            shape = getattr(aval, "shape", None)
            if not shape or len(shape) < 2:
                continue
            dims = list(shape)
            # a task dim and the node dim on distinct axes (task_dims
            # excludes N above, so two different axes must match)
            if N in dims and any(d in task_dims for d in dims):
                loc = _loc(eqn)
                key = (f"gather:{trace.name}:{loc}:"
                       f"{eqn.primitive.name}:{tuple(shape)}")
                dedup = (loc, tuple(shape))
                if dedup in seen:
                    continue
                seen.add(dedup)
                out.append(Finding(
                    family="gather", key=key,
                    where=f"{trace.name} @ {loc}",
                    what=(f"O(M*N) intermediate of shape {tuple(shape)} "
                          f"({eqn.primitive.name}) in '{trace.name}': a "
                          "task-axis x node-axis materialization — ship "
                          "O(M) scalars + node-resident maps instead "
                          "(the PR 1 affinity regression class)")))
    return out
