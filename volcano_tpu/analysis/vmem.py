"""Pallas VMEM budget estimator + fidelity check.

Both placement kernels run with whole-array BlockSpecs (no grid), so the
kernel's static VMEM footprint is exactly the byte sum of the
``pallas_call`` equation's input and output avals. The runtime auto-gate
(allocate_scan: ``use_pallas is None``) admits the kernel only when
``vmem_estimate_bytes`` stays under budget — which means a lowering
surprise on the driver's TPU can only come from the ESTIMATOR drifting
below the truth. This check closes that gap on CPU:

1. per traced kernel, the jaxpr-derived footprint must stay under the
   per-core budget;
2. ``vmem_estimate_bytes`` (fed the same dims the auto-gate feeds it)
   must not understate the jaxpr-derived truth;
3. the north-star-scale projection (10240 nodes, M=16 task slots, the
   bench's bucketed J/Q) must clear the budget, so the full-scale cycle
   keeps lowering long before a TPU sees it.
"""

from __future__ import annotations

from typing import List, Optional

from . import Finding

#: per-core VMEM budget the auto-gate enforces (allocate_scan keeps 4 MiB
#: of the ~16 MiB core for mosaic's own scratch/padding headroom)
DEFAULT_BUDGET_BYTES = 12 * 2 ** 20

#: estimator must cover at least this fraction of the traced footprint
FIDELITY = 1.0

#: north-star problem size (BASELINE.json config 1, bucketed)
_NS_NODES = 10240
_NS_M = 16
_NS_JOBS = 6250


def _pallas_bytes(closed) -> List[int]:
    """Byte totals (inputs + outputs) of every pallas_call in the trace."""
    import numpy as np

    from .jaxpr_audit import iter_eqns
    totals = []
    for eqn in iter_eqns(closed.jaxpr):
        if eqn.primitive.name != "pallas_call":
            continue
        tot = 0
        for v in list(eqn.invars) + list(eqn.outvars):
            aval = getattr(v, "aval", None)
            if aval is None or not hasattr(aval, "shape"):
                continue
            tot += int(np.prod(aval.shape, dtype=np.int64)
                       ) * aval.dtype.itemsize
        totals.append(tot)
    return totals


def _estimate(dims, cfg, N=None, M=None, J=None, Q=None) -> int:
    """vmem_estimate_bytes with the SAME dim wiring the auto-gate uses."""
    from ..ops.pallas_place import vmem_estimate_bytes
    K = max(1, int(cfg.batch_jobs))
    KP = max(0, int(cfg.batch_rounds))
    aff = ((dims["SK"], dims["ETA"], dims["SEL"])
           if cfg.enable_pod_affinity else (0, 0, 0))
    return vmem_estimate_bytes(
        K, M if M is not None else dims["M"],
        N if N is not None else dims["N"],
        dims["R"], dims["G"], dims["P"], dims["GR"], *aff,
        J=(J if J is not None else dims["J"]) if KP else 0,
        Q=(Q if Q is not None else dims["Q"]) if KP else 0)


def check_vmem(traces, budget_bytes: Optional[int] = None) -> List[Finding]:
    from ..arrays.schema import bucket
    budget = budget_bytes or DEFAULT_BUDGET_BYTES
    out: List[Finding] = []
    checked = 0
    for tr in traces:
        cfg = tr.cfg
        if cfg is None or not getattr(cfg, "use_pallas", None):
            continue
        totals = _pallas_bytes(tr.closed)
        if not totals:
            continue
        checked += 1
        traced = max(totals)
        if traced > budget:
            out.append(Finding(
                family="vmem",
                key=f"vmem:{tr.name}:traced={traced}:budget={budget}",
                where=tr.name,
                what=(f"pallas kernel in '{tr.name}' holds {traced} bytes "
                      f"of VMEM-resident inputs/outputs, over the "
                      f"{budget}-byte per-core budget")))
        est = _estimate(tr.dims, cfg)
        if est < FIDELITY * traced:
            out.append(Finding(
                family="vmem",
                key=f"vmem:{tr.name}:estimator={est}:traced={traced}",
                where=tr.name,
                what=(f"vmem_estimate_bytes returns {est} for the dims of "
                      f"'{tr.name}' but the traced kernel holds {traced} "
                      "bytes — the runtime auto-gate is understating the "
                      "footprint (keep the estimator in sync with "
                      "_read_*_env)")))
        # north-star projection through the SAME estimator the gate uses
        est_ns = _estimate(tr.dims, cfg, N=_NS_NODES, M=_NS_M,
                           J=bucket(_NS_JOBS), Q=tr.dims["Q"])
        if est_ns > budget:
            out.append(Finding(
                family="vmem",
                key=f"vmem:{tr.name}:northstar={est_ns}:budget={budget}",
                where=tr.name,
                what=(f"north-star-scale ({_NS_NODES} nodes, M={_NS_M}) "
                      f"VMEM estimate for '{tr.name}' is {est_ns} bytes, "
                      f"over the {budget}-byte budget — the full-scale "
                      "cycle would fall off the fused-kernel path")))
    if checked == 0:
        out.append(Finding(
            family="vmem", key="vmem:no-pallas-entry-traced",
            where="analysis/entrypoints",
            what=("no pallas_call found in any traced entry point — the "
                  "vmem family has nothing to certify (entrypoints "
                  "registry out of sync with ops/pallas_place)")))
    return out
