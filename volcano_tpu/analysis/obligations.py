"""Proof-obligation checker for the static-segment batching rule.

``derive_batching`` (ops/allocate_scan.py) is the single authority for
when K-job batching is bit-exact with the sequential pop order: static
ordering keys batch as K pre-selected sections, dynamic keys (drf/hdrf
ordering or finite proportion deserved) must take the in-kernel-selection
``batch_rounds`` path. This module enforces the obligation from both
sides:

- ``verify_batching_rule`` RE-DERIVES the rule over every flag
  combination and checks derive_batching's output against it, checks the
  deserved-array evidence path (any finite entry, including 0, counts as
  dynamic), checks manual settings pass through untouched, and probes
  that the illegal static-K + dynamic-keys combination still raises with
  the documented message.
- ``scan_sources`` walks the package AST and flags every construction
  site that hand-sets ``batch_jobs``/``batch_rounds`` without routing
  through derive_batching — including ``dataclasses.replace`` and dict
  literals later splatted into AllocateConfig(**kwargs). tests/ are
  exempt (kernel tests own the preconditions they set).
"""

from __future__ import annotations

import ast
import os
from typing import List, Optional

from . import Finding

BATCH_KEYS = frozenset({"batch_jobs", "batch_rounds"})

#: the one file allowed to set batch fields directly: derive_batching's
#: home (the authority itself) and the kernel's one-place config assert
_HOME = os.path.join("volcano_tpu", "ops", "allocate_scan.py")

#: documented error of the illegal combination (allocate_scan one-place
#: config assert) — verified verbatim so the message stays documented
_ILLEGAL_MSG = "static-keys path requires static ordering keys"


def _call_name(func) -> Optional[str]:
    if isinstance(func, ast.Name):
        return func.id
    if isinstance(func, ast.Attribute):
        return func.attr
    return None


def _parent_map(tree) -> dict:
    parents = {}
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            parents[child] = node
    return parents


def _routed_through_derive(node, parents) -> bool:
    """True when ``node`` sits (at any depth) inside the arguments of a
    derive_batching(...) call — the compliant construction pattern."""
    cur = parents.get(node)
    while cur is not None:
        if isinstance(cur, ast.Call) and \
                _call_name(cur.func) == "derive_batching":
            return True
        cur = parents.get(cur)
    return False


def scan_file(path: str, rel: str) -> List[Finding]:
    """AST-scan one file for hand-set batch_jobs/batch_rounds sites."""
    out: List[Finding] = []
    with open(path) as f:
        src = f.read()
    try:
        tree = ast.parse(src, filename=rel)
    except SyntaxError as e:
        out.append(Finding(
            family="obligations", key=f"obligations:{rel}:syntax",
            where=rel, what=f"unparseable source: {e}"))
        return out
    parents = _parent_map(tree)
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            kw = {k.arg for k in node.keywords if k.arg}
            hit = kw & BATCH_KEYS
            if hit and not _routed_through_derive(node, parents):
                fname = _call_name(node.func) or "<call>"
                out.append(Finding(
                    family="obligations",
                    key=(f"obligations:{rel}:{node.lineno}:"
                         f"{fname}:{'/'.join(sorted(hit))}"),
                    where=f"{rel}:{node.lineno}",
                    what=(f"{fname}(...) hand-sets {sorted(hit)} without "
                          "routing through derive_batching — the "
                          "static-segment exactness precondition lives "
                          "in ONE place (ops/allocate_scan."
                          "derive_batching); wrap the config there or "
                          "drop the manual setting")))
        elif isinstance(node, ast.Dict):
            for k in node.keys:
                if isinstance(k, ast.Constant) and k.value in BATCH_KEYS:
                    out.append(Finding(
                        family="obligations",
                        key=f"obligations:{rel}:{node.lineno}:dict:{k.value}",
                        where=f"{rel}:{node.lineno}",
                        what=(f"dict literal carries '{k.value}' (splatted "
                              "into a config constructor) — route the "
                              "constructed AllocateConfig through "
                              "derive_batching instead")))
    return out


def scan_sources(repo_root: Optional[str] = None) -> List[Finding]:
    root = repo_root or os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    out: List[Finding] = []
    for base, dirs, files in os.walk(root):
        rel_base = os.path.relpath(base, root)
        parts = rel_base.split(os.sep)
        if any(p.startswith(".") for p in parts if p != "."):
            continue
        if parts[0] in ("tests", "examples", "deploy", "related"):
            continue
        # the checker itself constructs manual configs as rule probes
        if rel_base.startswith(os.path.join("volcano_tpu", "analysis")):
            continue
        for fname in sorted(files):
            if not fname.endswith(".py"):
                continue
            rel = os.path.normpath(os.path.join(rel_base, fname))
            if rel == _HOME:
                continue    # the authority itself
            out.extend(scan_file(os.path.join(base, fname), rel))
    return out


def verify_batching_rule() -> List[Finding]:
    from itertools import product

    import numpy as np

    from ..ops.allocate_scan import (DEFAULT_BATCH_JOBS, AllocateConfig,
                                     derive_batching, make_allocate_cycle)
    out: List[Finding] = []

    def finding(key, what):
        out.append(Finding(family="obligations",
                           key=f"obligations:rule:{key}",
                           where="ops/allocate_scan.derive_batching",
                           what=what))

    # the rule, re-derived: batching is exact iff no ordering key can
    # move under a commit — drf/hdrf dynamic ordering moves job/ns keys,
    # ANY finite proportion deserved (zero included) can flip a queue
    # overused; dynamic keys must take the in-kernel-selection path
    for dj, dn, hd, hp in product((False, True), repeat=4):
        cfg = AllocateConfig(drf_job_order=dj, drf_ns_order=dn,
                             enable_hdrf=hd)
        got = derive_batching(cfg, has_proportion=hp)
        dynamic = dj or dn or hd or hp
        if got.batch_jobs != DEFAULT_BATCH_JOBS or \
                bool(got.batch_rounds) != dynamic:
            finding(f"combo:dj={dj}:dn={dn}:hd={hd}:hp={hp}",
                    f"derive_batching({cfg}) -> batch_jobs="
                    f"{got.batch_jobs}, batch_rounds={got.batch_rounds}; "
                    f"the static-segment rule requires batch_jobs="
                    f"{DEFAULT_BATCH_JOBS} and batch_rounds "
                    f"{'> 0' if dynamic else '== 0'} here")

    # deserved-array evidence: all-inf is static, one finite entry
    # (including 0) is dynamic
    neutral = np.full((2, 3), np.inf, np.float32)
    if derive_batching(AllocateConfig(),
                       queue_deserved=neutral).batch_rounds:
        finding("deserved:neutral",
                "all-infinite queue_deserved must derive the static-keys "
                "path (neutral deserved cannot move qshare)")
    finite = neutral.copy()
    finite[1, 0] = 0.0
    if not derive_batching(AllocateConfig(),
                           queue_deserved=finite).batch_rounds:
        finding("deserved:finite-zero",
                "a finite deserved entry (zero counts: the queue flips "
                "overused on the first commit) must derive the "
                "dynamic-key path")

    # manual settings pass through untouched (caller owns the precondition)
    for manual in (AllocateConfig(batch_jobs=4),
                   AllocateConfig(batch_rounds=16)):
        if derive_batching(manual, has_proportion=True) != manual:
            finding("manual-passthrough",
                    f"derive_batching must not rewrite explicit manual "
                    f"batching ({manual.batch_jobs}/{manual.batch_rounds})")

    # the illegal combination still raises with the documented message
    import jax

    from .entrypoints import _ALT_SIZE, _snap_extras
    snap, extras = _snap_extras(_ALT_SIZE)
    bad = AllocateConfig(batch_jobs=DEFAULT_BATCH_JOBS, drf_job_order=True)
    try:
        jax.eval_shape(make_allocate_cycle(bad), snap, extras)
        finding("illegal-combo:no-raise",
                "batch_jobs > 1 with dynamic ordering keys and no "
                "batch_rounds must raise in make_allocate_cycle — the "
                "one-place config assert is gone")
    except ValueError as e:
        if _ILLEGAL_MSG not in str(e):
            finding("illegal-combo:message",
                    f"the illegal-combination error dropped its "
                    f"documented message ({_ILLEGAL_MSG!r}): got {e}")
    except Exception as e:  # noqa: BLE001
        finding("illegal-combo:wrong-error",
                f"expected ValueError for the illegal combination, got "
                f"{type(e).__name__}: {e}")
    return out


def check_obligations(repo_root: Optional[str] = None) -> List[Finding]:
    return scan_sources(repo_root) + verify_batching_rule()
