"""Graphcheck allowlist: intentional findings, each with a justification.

An entry suppresses findings whose ``family`` matches and whose ``key``
contains ``match`` as a substring. Keep this list SHORT — the point of
graphcheck is that the repo passes with essentially no exceptions; an
entry needs a one-line reason a reviewer can audit.
"""

from __future__ import annotations

import dataclasses
from typing import Tuple


@dataclasses.dataclass(frozen=True)
class Allow:
    family: str
    match: str      # substring of Finding.key
    reason: str     # one line: why this finding is intentional


ALLOWLIST: Tuple[Allow, ...] = (
    # (empty — every finding of the first run was fixed at the source;
    #  add entries here only with a reviewable one-line justification)
)
