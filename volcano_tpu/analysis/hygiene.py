"""Graphcheck family 13: metrics exposition hygiene (ISSUE 17 satellite).

Strict Prometheus parsers require every metric family on /metrics to
carry a ``# HELP`` / ``# TYPE`` pair. The exposition layer
(metrics/metrics.py ``_meta_lines``) always emits the pair — but for a
name missing from the curated ``_HELP`` table it generates a filler text
from the metric name, which is exactly the drift PRs 3-12 kept fixing by
hand: a new counter lands, dashboards show "cycle replays total" instead
of an operator-useful sentence, and nobody notices until a reviewer
greps. This family makes the invariant mechanical:

- an AST scan over the package finds every statically-named metric
  emission — ``*.inc("name", ...)``, ``*.set_gauge("name", ...)``,
  ``*._hist("name", ...)``, including the local-alias idiom
  ``g = self.set_gauge; g("name", ...)`` — and each discovered name
  must have an EXPLICIT ``_HELP`` entry;
- a structural check on a live registry proves the exposition still
  emits the HELP/TYPE pair ahead of every sample family (counters,
  gauges, and the histogram bucket/count/sum series).

Dynamically-composed names (f-strings, variables) are out of scope for
the static half by construction; the structural half still covers them
at runtime via the generated-default path.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional

from . import Finding

#: Metrics registry methods whose first positional str argument is a
#: metric base name (metrics/metrics.py)
_METRIC_METHODS = frozenset({"inc", "set_gauge", "_hist"})


def _package_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class _EmissionVisitor(ast.NodeVisitor):
    """Collect statically-named metric emissions in one module."""

    def __init__(self, rel: str):
        self.rel = rel
        self.names: Dict[str, str] = {}
        self._aliases: set = set()

    def visit_Assign(self, node: ast.Assign) -> None:
        # the local-alias idiom: g = self.set_gauge
        if (isinstance(node.value, ast.Attribute)
                and node.value.attr in _METRIC_METHODS):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    self._aliases.add(t.id)
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        fn = node.func
        hit = ((isinstance(fn, ast.Attribute)
                and fn.attr in _METRIC_METHODS)
               or (isinstance(fn, ast.Name) and fn.id in self._aliases))
        if hit and node.args:
            arg = node.args[0]
            if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                self.names.setdefault(arg.value,
                                      f"{self.rel}:{node.lineno}")
        self.generic_visit(node)


def discovered_metric_names(root: Optional[str] = None) -> Dict[str, str]:
    """name -> "file.py:line" of its first statically-named emission,
    over every module in the volcano_tpu package."""
    root = root or _package_root()
    out: Dict[str, str] = {}
    for dirpath, _dirs, files in sorted(os.walk(root)):
        for fname in sorted(files):
            if not fname.endswith(".py"):
                continue
            path = os.path.join(dirpath, fname)
            rel = os.path.relpath(path, os.path.dirname(root))
            try:
                with open(path) as f:
                    tree = ast.parse(f.read(), filename=rel)
            except (OSError, SyntaxError):
                continue
            v = _EmissionVisitor(rel)
            v.visit(tree)
            for name, where in v.names.items():
                out.setdefault(name, where)
    return out


def _coverage_findings(names: Dict[str, str],
                       help_map: Dict[str, str]) -> List[Finding]:
    """Every discovered emission name needs an explicit _HELP entry.
    Shared by the live check and the planted-gauge test."""
    out: List[Finding] = []
    for name in sorted(set(names) - set(help_map)):
        out.append(Finding(
            family="hygiene",
            key=f"hygiene:help-missing:{name}",
            where=names[name],
            what=(f"metric '{name}' (emitted at {names[name]}) has no "
                  "explicit _HELP entry in metrics/metrics.py — the "
                  "exposition would fall back to a generated filler "
                  "text; write the operator-facing sentence")))
    return out


def _exposition_findings(metrics=None) -> List[Finding]:
    """Structural check: every sample family in the exposition is
    preceded by its ``# HELP`` / ``# TYPE`` pair."""
    out: List[Finding] = []
    if metrics is None:
        from ..metrics.metrics import Metrics
        metrics = Metrics()
        metrics.inc("schedule_attempts_total", labels={"result": "ok"})
        metrics.set_gauge("is_leader", None, 1.0)
        metrics.observe_cycle(0.001)        # histogram family
    declared = set()
    for line in metrics.exposition().splitlines():
        if line.startswith("# HELP volcano_") \
                or line.startswith("# TYPE volcano_"):
            declared.add(line.split()[2][len("volcano_"):])
            continue
        if not line.startswith("volcano_"):
            continue
        base = line.split("{")[0].split(" ")[0][len("volcano_"):]
        for suffix in ("_bucket", "_count", "_sum"):
            if base.endswith(suffix) and base[:-len(suffix)] in declared:
                base = base[:-len(suffix)]
                break
        if base not in declared:
            out.append(Finding(
                family="hygiene",
                key=f"hygiene:pair-missing:{base}",
                where="metrics/metrics.py",
                what=(f"exposition sample 'volcano_{base}' appears "
                      "without a preceding # HELP / # TYPE pair — "
                      "strict Prometheus parsers reject the payload "
                      "(keep _meta_lines ahead of every family)")))
    return out


def check_hygiene(repo_root: Optional[str] = None) -> List[Finding]:
    from ..metrics.metrics import _HELP
    root = (os.path.join(repo_root, "volcano_tpu")
            if repo_root else _package_root())
    findings = _coverage_findings(discovered_metric_names(root), _HELP)
    findings += _exposition_findings()
    return findings
