"""Recompilation-hazard lint.

Every jitted entry point must compile exactly once per problem-size
bucket: the arrays/schema.bucket grid exists so a production scheduler
pays one compile per shape family, and a Python-value-dependent shape or
branch (a host int folded into a shape, an `if` on a concrete value that
differs per call, a non-weak scalar captured per invocation) silently
turns that into a compile per CYCLE — the exact hazard class that makes a
1 s schedule period impossible.

The lint wraps each entry's raw callable with a trace counter, jits it,
and runs it twice per size with FRESH same-shaped inputs. Expected trace
count == number of distinct sizes; anything more is a finding naming the
entry point.
"""

from __future__ import annotations

from typing import List, Optional

from . import Finding


def check_recompile(fast: bool = False,
                    probes: Optional[list] = None) -> List[Finding]:
    import jax

    from .entrypoints import recompile_probes
    out: List[Finding] = []
    for name, build_fn, args_by_size in (
            probes if probes is not None else recompile_probes(fast=fast)):
        raw = build_fn()
        count = 0

        def counted(*args, _raw=raw):
            nonlocal count
            count += 1
            return _raw(*args)

        jfn = jax.jit(counted)
        try:
            for calls in args_by_size.values():
                # a size entry is one arg tuple (called twice — the second
                # same-shaped call must not retrace) or a list of arg
                # tuples (every call must land in the size's one bucket)
                if isinstance(calls, tuple):
                    calls = [calls, calls]
                for args in calls:
                    jax.block_until_ready(jfn(*args))
        except Exception as e:  # noqa: BLE001 — report, don't crash the CLI
            out.append(Finding(
                family="recompile", key=f"recompile:{name}:error",
                where=name,
                what=(f"entry point '{name}' failed to execute during the "
                      f"recompile lint: {type(e).__name__}: {e}")))
            continue
        expected = len(args_by_size)
        if count != expected:
            out.append(Finding(
                family="recompile",
                key=f"recompile:{name}:traces={count}:expected={expected}",
                where=name,
                what=(f"entry point '{name}' traced {count}x for "
                      f"{expected} problem-size bucket(s) — a "
                      "Python-value-dependent shape or control flow is "
                      "defeating the jit cache (one compile per shape "
                      "bucket is the budget)")))
    return out
