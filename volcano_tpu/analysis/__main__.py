"""CLI: ``python -m volcano_tpu.analysis`` (wrapped by scripts/graphcheck.sh).

Runs the graphcheck families (all of ``analysis.FAMILIES`` by default —
the help text is derived from the tuple so it cannot drift) over the
repo's real entry points on the CPU backend, writes a machine-readable
JSON report, prints human-readable findings, and exits with a stable
code:

    0  clean (no non-allowlisted findings)
    1  findings
    2  internal error (the analysis itself failed)
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def main(argv=None) -> int:
    from . import FAMILIES, run_graphcheck
    parser = argparse.ArgumentParser(
        prog="python -m volcano_tpu.analysis",
        description="graphcheck: trace-time static analysis of the "
                    "compiled scheduling cycle")
    parser.add_argument(
        "--json", default=os.environ.get("GRAPHCHECK_REPORT",
                                         "/tmp/graphcheck_report.json"),
        help="path for the machine-readable report "
             "(default: $GRAPHCHECK_REPORT or /tmp/graphcheck_report.json)")
    parser.add_argument(
        "--families", default=None,
        help="comma-separated subset of check families "
             f"(default: all {len(FAMILIES)}: {', '.join(FAMILIES)})")
    parser.add_argument(
        "--fast", action="store_true",
        help="prune the traced-entry set to a representative subset "
             "(the tier-1 test mode)")
    parser.add_argument(
        "--vmem-budget-bytes", type=int, default=None,
        help="override the per-core VMEM budget (default 12 MiB, the "
             "runtime auto-gate's bound)")
    parser.add_argument(
        "--cost-hbm-budget-bytes", type=int, default=None,
        help="override the cost family's per-chip HBM budget for the "
             "peak-live and north-star projection gates (default 16 GiB)")
    parser.add_argument(
        "--list-families", action="store_true",
        help="print the known families and exit")
    args = parser.parse_args(argv)

    if args.list_families:
        print("\n".join(FAMILIES))
        return 0

    # graphcheck is a CPU CI pass: never touch (or hang on) a TPU tunnel
    import jax
    try:
        jax.config.update("jax_platforms", "cpu")
    except RuntimeError:
        pass    # a backend already initialized (in-process caller owns it)

    families = ([f.strip() for f in args.families.split(",") if f.strip()]
                if args.families else None)
    try:
        report = run_graphcheck(
            families=families, fast=args.fast,
            vmem_budget_bytes=args.vmem_budget_bytes,
            cost_hbm_budget_bytes=args.cost_hbm_budget_bytes)
    except Exception as e:  # noqa: BLE001 — stable exit code for harnesses
        print(f"graphcheck: internal error: {type(e).__name__}: {e}",
              file=sys.stderr)
        import traceback
        traceback.print_exc(file=sys.stderr)
        return 2

    if args.json:
        with open(args.json, "w") as f:
            json.dump(report, f, indent=1, sort_keys=True)

    for fdict in report["findings"]:
        tag = "allowlisted" if fdict["allowlisted"] else "FINDING"
        line = f"[{tag}] {fdict['family']}: {fdict['what']}"
        if fdict["allowlisted"]:
            line += f" (allowed: {fdict['reason']})"
        print(line)
    stats = report["meta"].get("family_stats") or {}
    slowest = (max(stats, key=lambda k: stats[k]["elapsed_s"])
               if stats else None)
    slow_txt = (f", slowest family {slowest} "
                f"({stats[slowest]['elapsed_s']}s)" if slowest else "")
    print(f"graphcheck: {'CLEAN' if report['clean'] else 'DIRTY'} — "
          f"{report['blocking_count']} blocking / "
          f"{report['finding_count']} total findings, "
          f"{len(report['meta'].get('traced_entry_points', []))} entry "
          f"points traced, {report['elapsed_s']}s{slow_txt} "
          f"(report sha {report['report_sha256']}"
          + (f", written to {args.json})" if args.json else ")"))
    return 0 if report["clean"] else 1


if __name__ == "__main__":
    sys.exit(main())
