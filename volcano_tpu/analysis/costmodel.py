"""Graphcheck family 12: whole-cycle static cost model (ISSUE 17).

The paper's hot loop is the entire scheduling cycle as ONE compiled
program, and the ROADMAP's pod-slice target (100k nodes / 1M tasks in a
sub-second cycle) is blocked on hardware this CI lacks. Until then, the
only way an HBM blow-up or an O(nodes) cross-shard collective can be
caught is statically — so this family walks every real entry's closed
jaxpr (the same ``iter_eqns`` recursion the purity/dtype/gather families
use) and derives four whole-cycle numbers per entry:

1. **FLOPs + bytes touched** — a per-primitive cost table
   (``_eqn_flops``), trip-count-aware for control flow: ``scan`` bodies
   multiply by the static ``length`` param, ``while`` bodies by the
   widest carry-aval axis (the repo's while loops iterate a padded axis
   carried in the loop state — the job loop walks T task slots, the
   wavefront walks the candidate list — so the widest carry dim is the
   documented trip upper bound), ``cond`` takes the most expensive
   branch. Bytes touched sum every equation's input+output avals times
   its trip count: the unfused upper bound on HBM traffic.
2. **Peak live bytes** — a donation-aware liveness sweep over the
   top-level equation sequence (``peak_live_bytes``): the static HBM
   watermark the entry needs, the number that must clear the per-chip
   budget at pod scale.
3. **Collective bytes** — cross-device traffic of every explicit
   collective equation (``all_gather``/``psum``/``ppermute``/...)
   sized against the mesh axis it runs over, trip-aware like the FLOP
   walk, PLUS the GSPMD-inserted collectives of the compiled sharded
   module (``hlo_collective_bytes``) — where the real entry's traffic
   actually lives, since PR 7's design keeps its traced jaxpr
   collective-free. Gate: per-cycle cross-shard bytes may scale with devices and
   wave width (the trip multiplier prices the wave sweep), NEVER with
   the node axis — a full-node-axis ``all_gather`` (output elements >=
   2x nodes, the sharding family's threshold generalized to traced
   collectives) and a super-linear node-scaling exponent both flag.
4. **Arithmetic intensity + north-star projection** — each projection
   entry is traced at 2-3 problem sizes (tracing is cheap: shapes are
   abstract), per-component growth exponents are fit on the padded node
   axis (the synthetic mix holds tasks at 10x nodes, exactly the
   north-star ratio), and peak HBM / collective bytes are projected to
   100k nodes / 1M tasks against a configurable per-chip HBM budget
   (default 16 GiB, ``--cost-hbm-budget-bytes``).

Model caveats, on purpose:

- bytes touched is unfused (XLA fuses elementwise chains); it is an
  upper bound and a *ratio* metric across PRs, not a prediction.
- the liveness sweep charges an equation's inputs and outputs
  simultaneously at its definition point (XLA need not alias in
  place); donated entry invars die at their last use, non-donated
  invars and constvars stay live to the end — that asymmetry IS the
  donation contract (ops/fused_io.DeltaKernel donates the resident
  buffers on accelerators), and the fixture test pins the arithmetic.
- sub-jaxpr workspace (one iteration's internal peak) is added at the
  owning equation: a per-iteration upper bound for scan/while bodies.

The slow-marked fidelity test cross-checks the FLOP table against XLA's
own ``Compiled.cost_analysis()`` where available (exact on a canonical
matmul; an upper bound on real entries, whose while trips XLA counts
once).
"""

from __future__ import annotations

import math
import re
from typing import Dict, List, Optional, Tuple

from . import Finding

#: per-chip HBM budget the watermark and the north-star projection gate
#: against (v4/v5e class chips carry 16 GiB; --cost-hbm-budget-bytes)
DEFAULT_HBM_BUDGET_BYTES = 16 * 2 ** 30

#: the ROADMAP pod-slice target (projected onto the padded pow2 buckets
#: the pack path would actually allocate)
NS_NODES = 100_000
NS_TASKS = 1_000_000

#: ceiling on the fitted per-cycle collective-bytes growth exponent vs
#: the node axis: the sharded design's column syncs are O(N) (exponent
#: ~1), O(N^2) node-state re-materialization is the failure class; the
#: margin absorbs fit noise from additive O(1) mesh terms
COLLECTIVE_NODE_EXPONENT_LIMIT = 1.3

#: problem sizes (nodes, jobs, tasks_per_job) traced for the projection
#: fit — tasks stay at 10x nodes, the north-star mix, so one fitted
#: exponent covers both axes. Padded N doubles per point (128/256/512).
PROJECTION_SIZES_FAST = ((100, 250, 4), (200, 500, 4))
PROJECTION_SIZES_FULL = ((100, 250, 4), (200, 500, 4), (400, 1000, 4))

#: FLOP-free data movement: costs bytes, not arithmetic
_DATA_MOVEMENT = frozenset({
    "broadcast_in_dim", "reshape", "transpose", "slice", "dynamic_slice",
    "dynamic_update_slice", "gather", "concatenate", "pad", "iota", "rev",
    "squeeze", "expand_dims", "copy", "convert_element_type",
    "bitcast_convert_type", "stop_gradient", "device_put", "select_n",
})

#: polynomial-approximated elementwise ops: ~10 flops/element
_TRANSCENDENTAL = frozenset({
    "exp", "exp2", "expm1", "log", "log2", "log1p", "tanh", "logistic",
    "sqrt", "rsqrt", "cbrt", "pow", "integer_pow", "sin", "cos", "tan",
    "erf", "erfc", "erf_inv", "atan2", "lgamma", "digamma",
})

#: reductions: one flop per INPUT element
_REDUCTIONS = frozenset({
    "reduce_sum", "reduce_max", "reduce_min", "reduce_prod", "reduce_and",
    "reduce_or", "reduce_xor", "argmax", "argmin", "reduce_precision",
    "cumsum", "cummax", "cummin", "cumprod", "cumlogsumexp",
})

#: explicit cross-device collectives (shard_map bodies; GSPMD inserts
#: more at compile time — the sharding family audits that HLO side)
_COLLECTIVES = frozenset({
    "all_gather", "psum", "pmax", "pmin", "ppermute", "all_to_all",
    "reduce_scatter", "psum_scatter",
})


# --------------------------------------------------------------- cost table
def _aval_bytes(aval) -> int:
    """Static byte size of an abstract value (0 for tokens/opaque)."""
    shape = getattr(aval, "shape", None)
    dtype = getattr(aval, "dtype", None)
    if shape is None or dtype is None:
        return 0
    n = 1
    for d in shape:
        try:
            n *= int(d)
        except (TypeError, ValueError):
            return 0            # symbolic dim: price it as free
    return n * dtype.itemsize


def _elems(aval) -> int:
    shape = getattr(aval, "shape", None)
    if shape is None:
        return 0
    n = 1
    for d in shape:
        try:
            n *= int(d)
        except (TypeError, ValueError):
            return 0
    return n


def _dot_flops(eqn) -> int:
    """2 * output_elements * contracted_elements — the textbook count
    (exactly what XLA's cost_analysis reports for a plain matmul)."""
    (lc, _rc), _batch = eqn.params["dimension_numbers"]
    lhs_shape = getattr(eqn.invars[0].aval, "shape", ())
    contract = 1
    for d in lc:
        contract *= int(lhs_shape[d])
    out = sum(_elems(v.aval) for v in eqn.outvars)
    return 2 * out * contract


def _eqn_flops(eqn) -> int:
    """Per-primitive FLOP model. Deliberately coarse: exact for
    dot_general and reductions, 10/element for transcendentals,
    1/output-element for everything else arithmetic, 0 for pure data
    movement — good enough for growth exponents and cross-PR ratios."""
    name = eqn.primitive.name
    if name in _DATA_MOVEMENT or name in _COLLECTIVES:
        return 0
    if name == "dot_general":
        return _dot_flops(eqn)
    if name in _REDUCTIONS:
        return sum(_elems(getattr(v, "aval", None)) for v in eqn.invars)
    if name == "sort":
        n = max((_elems(getattr(v, "aval", None)) for v in eqn.invars),
                default=0)
        return n * max(1, int(math.log2(n)) if n > 1 else 1)
    if name in ("scatter", "scatter-add", "scatter_add", "scatter-mul",
                "scatter_mul", "scatter-min", "scatter-max", "scatter_min",
                "scatter_max"):
        # one update op per update element (operand copy is movement)
        upd = getattr(eqn.invars[-1], "aval", None)
        return _elems(upd)
    per = 10 if name in _TRANSCENDENTAL else 1
    return per * sum(_elems(getattr(v, "aval", None)) for v in eqn.outvars)


# ------------------------------------------------- trip-aware jaxpr walk
class CollectiveSite:
    """One traced collective equation: its per-invocation output size
    (the node-axis gate keys on it) and its trip-scaled per-cycle
    cross-device bytes."""

    __slots__ = ("prim", "loc", "out_elems", "bytes_per_cycle",
                 "axis_size")

    def __init__(self, prim, loc, out_elems, bytes_per_cycle, axis_size):
        self.prim = prim
        self.loc = loc
        self.out_elems = out_elems
        self.bytes_per_cycle = bytes_per_cycle
        self.axis_size = axis_size


class JaxprCost:
    """Accumulated cost of one (sub-)jaxpr: FLOPs, unfused HBM bytes
    touched, fleet-wide collective bytes, per-primitive breakdown, and
    the collective sites for the node-axis gate."""

    __slots__ = ("flops", "hbm_bytes", "collective_bytes", "by_prim",
                 "sites")

    def __init__(self):
        self.flops = 0
        self.hbm_bytes = 0
        self.collective_bytes = 0
        self.by_prim: Dict[str, List[int]] = {}
        self.sites: List[CollectiveSite] = []

    def add(self, other: "JaxprCost", mult: int = 1) -> None:
        self.flops += other.flops * mult
        self.hbm_bytes += other.hbm_bytes * mult
        self.collective_bytes += other.collective_bytes * mult
        for k, (f, b) in other.by_prim.items():
            cur = self.by_prim.setdefault(k, [0, 0])
            cur[0] += f * mult
            cur[1] += b * mult
        for s in other.sites:
            self.sites.append(CollectiveSite(
                s.prim, s.loc, s.out_elems, s.bytes_per_cycle * mult,
                s.axis_size))


def _axis_sizes(params, axis_env) -> int:
    """Product of the mesh-axis sizes a collective runs over."""
    names = params.get("axes") or params.get("axis_name") or ()
    if not isinstance(names, (tuple, list)):
        names = (names,)
    d = 1
    for n in names:
        d *= int(axis_env.get(n, 1))
    return max(d, int(params.get("axis_size", 1)))


def _collective_cost(eqn, axis_env) -> Tuple[int, int]:
    """(fleet-wide cross-device bytes, axis size) for one collective eqn.

    Avals inside shard_map bodies are per-device LOCAL views; the counts
    below are the standard ring-algorithm fleet totals: all_gather moves
    in_bytes*D*(D-1) = out_bytes*(D-1); psum (ring all-reduce)
    2*in_bytes*(D-1); ppermute one local buffer per device; all_to_all /
    reduce_scatter (D-1)/D of the local operand per device.
    """
    name = eqn.primitive.name
    in_b = sum(_aval_bytes(getattr(v, "aval", None)) for v in eqn.invars)
    out_b = sum(_aval_bytes(getattr(v, "aval", None)) for v in eqn.outvars)
    d = _axis_sizes(eqn.params, axis_env)
    if d <= 1:
        return 0, d
    if name == "all_gather":
        return out_b * (d - 1), d
    if name in ("psum", "pmax", "pmin"):
        return 2 * in_b * (d - 1), d
    if name == "ppermute":
        return in_b * d, d
    if name in ("all_to_all", "reduce_scatter", "psum_scatter"):
        return in_b * (d - 1), d
    return in_b * d, d


def _while_trip(eqn) -> int:
    """Trip-count upper bound for a ``while`` eqn from its carry avals:
    the widest carried axis (the repo's loops walk a padded axis held in
    the carry — T task slots for the job loop, the candidate list for
    the wavefront sweep). Scalar-only carries count as one trip."""
    nconsts = (int(eqn.params.get("cond_nconsts", 0))
               + int(eqn.params.get("body_nconsts", 0)))
    trip = 1
    for v in eqn.invars[nconsts:]:
        shape = getattr(getattr(v, "aval", None), "shape", ())
        for dim in shape or ():
            try:
                trip = max(trip, int(dim))
            except (TypeError, ValueError):
                continue
    return trip


def _sub_jaxprs(eqn) -> List:
    """Closed sub-jaxprs in an eqn's params (same discovery rule as
    jaxpr_audit.iter_eqns, kept in closed form for const access)."""
    from jax.core import ClosedJaxpr, Jaxpr
    subs = []
    for v in eqn.params.values():
        if isinstance(v, (ClosedJaxpr, Jaxpr)):
            subs.append(v)
        elif isinstance(v, (list, tuple)):
            subs += [x for x in v if isinstance(x, (ClosedJaxpr, Jaxpr))]
    return subs


def _inner(j):
    return j.jaxpr if hasattr(j, "jaxpr") else j


def jaxpr_cost(jaxpr, axis_env: Optional[dict] = None) -> JaxprCost:
    """Trip-count-aware cost of a (sub-)jaxpr. ``axis_env`` maps mesh
    axis names to sizes for collectives without an explicit axis_size
    param (threaded through shard_map bodies)."""
    from .jaxpr_audit import _loc
    axis_env = axis_env or {}
    acc = JaxprCost()
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        subs = _sub_jaxprs(eqn)
        if subs:
            env = axis_env
            if name == "shard_map":
                mesh = eqn.params.get("mesh")
                shape = getattr(mesh, "shape", None)
                if shape:
                    env = dict(axis_env, **{str(k): int(v)
                                            for k, v in dict(shape).items()})
            if name == "scan":
                trip = max(1, int(eqn.params.get("length", 1)))
                for s in subs:
                    acc.add(jaxpr_cost(_inner(s), env), trip)
            elif name == "while":
                trip = _while_trip(eqn)
                for s in subs:
                    acc.add(jaxpr_cost(_inner(s), env), trip)
            elif name == "cond":
                branches = [jaxpr_cost(_inner(s), env) for s in subs]
                if branches:
                    acc.add(max(branches, key=lambda c: c.flops))
            else:
                # pjit / custom_* / remat / pallas_call / shard_map: once
                # (pallas grids in this repo use whole-array BlockSpecs)
                for s in subs:
                    acc.add(jaxpr_cost(_inner(s), env))
            continue
        flops = _eqn_flops(eqn)
        moved = (sum(_aval_bytes(getattr(v, "aval", None))
                     for v in eqn.invars)
                 + sum(_aval_bytes(getattr(v, "aval", None))
                       for v in eqn.outvars))
        acc.flops += flops
        acc.hbm_bytes += moved
        cur = acc.by_prim.setdefault(name, [0, 0])
        cur[0] += flops
        cur[1] += moved
        if name in _COLLECTIVES:
            cbytes, d = _collective_cost(eqn, axis_env)
            acc.collective_bytes += cbytes
            out_e = sum(_elems(getattr(v, "aval", None))
                        for v in eqn.outvars)
            acc.sites.append(CollectiveSite(name, _loc(eqn), out_e,
                                            cbytes, d))
    return acc


# ------------------------------------------------------- liveness sweep
def _workspace(eqn) -> int:
    """Per-invocation internal peak of an eqn's sub-jaxprs (one
    iteration's workspace for scan/while; the widest branch for cond) —
    added at the owning equation in the top-level sweep."""
    best = 0
    for s in _sub_jaxprs(eqn):
        j = _inner(s)
        best = max(best, _sweep(j, donated_ids=frozenset()))
    return best


def _sweep(jaxpr, donated_ids=frozenset(), const_bytes: int = 0) -> int:
    """Liveness peak over one jaxpr's equation sequence (helper of
    :func:`peak_live_bytes`, which documents the model)."""
    n = len(jaxpr.eqns)
    defined = {}                                    # id(var) -> bytes
    last: Dict[int, int] = {}                       # id(var) -> last use
    for v in list(jaxpr.constvars) + list(jaxpr.invars):
        defined[id(v)] = _aval_bytes(v.aval)
        # non-donated inputs are caller-owned for the whole call; only
        # donated invars may die at their last use
        last[id(v)] = n if id(v) not in donated_ids else -1
    for i, eqn in enumerate(jaxpr.eqns):
        for v in eqn.invars:
            if id(v) in defined and last.get(id(v), n) != n:
                last[id(v)] = i
        for v in eqn.outvars:
            defined[id(v)] = _aval_bytes(getattr(v, "aval", None))
            last[id(v)] = i                         # dead unless used later
    for v in jaxpr.outvars:
        if id(v) in defined:
            last[id(v)] = n
    frees: Dict[int, List[int]] = {}
    for vid, i in last.items():
        if i < n:
            frees.setdefault(i, []).append(defined[vid])
    live = const_bytes + sum(defined[id(v)] for v in
                             list(jaxpr.constvars) + list(jaxpr.invars))
    peak = live
    for i, eqn in enumerate(jaxpr.eqns):
        out_b = sum(_aval_bytes(getattr(v, "aval", None))
                    for v in eqn.outvars)
        peak = max(peak, live + out_b + _workspace(eqn))
        live += out_b
        live -= sum(frees.get(i, []))
    return peak


def peak_live_bytes(closed, donated: Tuple[int, ...] = ()) -> int:
    """Donation-aware static HBM watermark of a closed jaxpr.

    Model: walk the top-level equation sequence; a value is live from
    its defining equation to its last use. Non-donated entry inputs and
    consts stay live to the end (the caller owns those buffers for the
    whole call); invar indices in ``donated`` die at their last use —
    exactly the XLA donation contract. An equation transiently holds its
    inputs AND outputs (no in-place aliasing assumed) plus its
    sub-jaxprs' per-iteration workspace. Deliberate upper bound; see the
    module docstring for the fixture-pinned arithmetic.
    """
    jaxpr = closed.jaxpr
    donated_ids = frozenset(id(jaxpr.invars[i]) for i in donated
                            if 0 <= i < len(jaxpr.invars))
    return _sweep(jaxpr, donated_ids=donated_ids)


# -------------------------------------------------- fit + projection
def fit_power(points) -> Tuple[float, float]:
    """Least-squares power-law fit ``y = c * x**e`` over (x, y) points
    in log-log space; returns (exponent, coefficient). Points with
    y <= 0 are clamped to 1 byte (log-safe); a single point fits a
    linear model through the origin exponent-1 style (e=1)."""
    pts = [(float(x), max(float(y), 1.0)) for x, y in points]
    if not pts:
        return 0.0, 0.0
    if len(pts) == 1:
        x, y = pts[0]
        return 1.0, y / x
    lx = [math.log(x) for x, _ in pts]
    ly = [math.log(y) for _, y in pts]
    mx = sum(lx) / len(lx)
    my = sum(ly) / len(ly)
    var = sum((a - mx) ** 2 for a in lx)
    if var == 0:
        return 0.0, math.exp(my)
    e = sum((a - mx) * (b - my) for a, b in zip(lx, ly)) / var
    c = math.exp(my - e * mx)
    return e, c


def project_power(points, x_target: float) -> Tuple[float, float]:
    """(projected y at x_target, fitted exponent) for (x, y) points."""
    e, c = fit_power(points)
    return c * float(x_target) ** e, e


def northstar_padded_nodes() -> int:
    """The padded node-axis width the pack path would allocate at the
    north-star scale (the projection's x target)."""
    from ..arrays.schema import bucket
    return bucket(NS_NODES)


def _projection_findings(entry: str, points, budget: int,
                         kind: str = "peak-live",
                         x_target: Optional[int] = None) -> List[Finding]:
    """Gate a fitted north-star projection against the HBM budget.
    Shared by the live check and the planted O(N^2) test."""
    x_ns = x_target or northstar_padded_nodes()
    value, exponent = project_power(points, x_ns)
    if value <= budget:
        return []
    return [Finding(
        family="cost",
        key=(f"cost:northstar:{entry}:{kind}:"
             f"projected={int(value)}:budget={budget}"),
        where=entry,
        what=(f"north-star projection ({NS_NODES} nodes / {NS_TASKS} "
              f"tasks, padded N={x_ns}) of {kind} bytes for '{entry}' is "
              f"{int(value):,} (growth exponent {exponent:.2f} fit over "
              f"{[int(x) for x, _ in points]}-node traces), over the "
              f"{budget:,}-byte per-chip HBM budget — the full-scale "
              "cycle cannot be resident; shard or re-tile the "
              "super-linear component before hardware ever sees it"))]


def _site_findings(sites, n_nodes: int, where: str) -> List[Finding]:
    """Per-collective node-axis gate: a traced all_gather whose OUTPUT
    reaches 2x the node axis re-materializes multi-column node state on
    every device (the sharding family's HLO threshold applied to
    explicit collectives, which an interpret-mode launch can hide from
    the HLO side). Shared by the live check and the planted test."""
    out: List[Finding] = []
    seen = set()
    for s in sites:
        if s.prim != "all_gather" or s.out_elems < 2 * n_nodes:
            continue
        key = f"cost:allgather:{where}:{s.loc}:{s.out_elems}"
        if key in seen:
            continue
        seen.add(key)
        out.append(Finding(
            family="cost", key=key, where=f"{where} @ {s.loc}",
            what=(f"traced all_gather output carries {s.out_elems} "
                  f"elements (>= 2*{n_nodes} nodes) across a "
                  f"{s.axis_size}-way mesh — per-cycle cross-shard bytes "
                  "must scale with devices and wave width, never the "
                  "node axis; keep the gather mesh-sized or column-wide "
                  "and resolve winners with the cross-shard combine")))
    return out


# ------------------------------------------------------------ entry cost
class EntryCost:
    """The per-entry summary the report's meta carries."""

    __slots__ = ("flops", "hbm_bytes", "peak_live_bytes",
                 "collective_bytes", "sites", "by_prim")

    def __init__(self, closed, donated=(), axis_env=None):
        cost = jaxpr_cost(closed.jaxpr, axis_env)
        self.flops = int(cost.flops)
        self.hbm_bytes = int(cost.hbm_bytes)
        self.collective_bytes = int(cost.collective_bytes)
        self.sites = cost.sites
        self.by_prim = cost.by_prim
        self.peak_live_bytes = int(peak_live_bytes(closed, donated))

    @property
    def arithmetic_intensity(self) -> float:
        return round(self.flops / self.hbm_bytes, 4) if self.hbm_bytes \
            else 0.0

    def to_meta(self) -> dict:
        top = sorted(self.by_prim.items(), key=lambda kv: -kv[1][1])[:5]
        return {
            "flops": self.flops,
            "hbm_bytes_touched": self.hbm_bytes,
            "peak_live_bytes": self.peak_live_bytes,
            "collective_bytes": self.collective_bytes,
            "arithmetic_intensity": self.arithmetic_intensity,
            "top_primitives_by_bytes": {
                k: {"flops": v[0], "bytes": v[1]} for k, v in top},
        }


def entry_cost(closed, donated=(), axis_env=None) -> EntryCost:
    return EntryCost(closed, donated=donated, axis_env=axis_env)


# ------------------------------------------------ compiled-HLO collectives
#: any collective op with its HLO dtype + output shape, async-start or
#: sync form; the -done halves restate the shape and are excluded so a
#: start/done pair counts once
_HLO_COLLECTIVE_RE = re.compile(
    r"=\s*([a-z0-9]+)\[([0-9,]*)\][^ ]*\s+"
    r"(all-gather|all-reduce|collective-permute|all-to-all|"
    r"reduce-scatter)(?:-start)?\(")

_HLO_ITEMSIZE = {"pred": 1, "s8": 1, "u8": 1, "f8e4m3fn": 1, "f8e5m2": 1,
                 "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
                 "s32": 4, "u32": 4, "f32": 4,
                 "s64": 8, "u64": 8, "f64": 8, "c64": 8, "c128": 16}


def hlo_collective_bytes(hlo_text: str, devices: int) -> int:
    """Fleet-wide per-cycle collective payload of a compiled GSPMD
    module: per collective op, output bytes scaled by the ring-algorithm
    device factor ((D-1) for gather/reduce flavors, D for permute).
    The model the node-scaling fit and north-star projection run on —
    explicit jaxpr collectives are the other half (jaxpr_cost)."""
    total = 0
    d = max(devices, 2)
    for m in _HLO_COLLECTIVE_RE.finditer(hlo_text):
        dtype, dims, op = m.group(1), m.group(2), m.group(3)
        elems = 1
        for x in dims.split(","):
            if x:
                elems *= int(x)
        nbytes = elems * _HLO_ITEMSIZE.get(dtype, 4)
        total += nbytes * (d if op == "collective-permute" else d - 1)
    return total


# --------------------------------------------------------------- the check
def _collective_audit(sizes, budget: int, meta: dict) -> List[Finding]:
    """Audit the REAL sharded update+cycle entry at 2 node sizes on a
    2-device mesh, both halves of the collective story:

    - jaxpr level: explicit collective equations (shard_map bodies) hit
      the per-site node-axis gate — zero on the real entry by design
      (PR 7's no-O(N)-gather contract), load-bearing for hand-written
      shard_map comms and the planted test;
    - HLO level: the GSPMD-inserted collectives of the compiled module
      (where the real traffic lives), totalled by
      :func:`hlo_collective_bytes`, fitted for a node-scaling exponent
      and projected to north-star scale against the per-chip budget.
    """
    import jax

    from ..arrays.schema import bucket
    from ..parallel import mesh_for_nodes
    from .sharding import _audit_kernel

    if jax.device_count() < 2:
        meta["audited"] = False
        meta["reason"] = "fewer than two devices visible"
        return []
    findings: List[Finding] = []
    points = []
    jaxpr_bytes = 0
    devices = 2
    where = f"ops/fused_io.ShardedDeltaKernel[{devices}dev]"
    for size in sizes:
        kernel = _audit_kernel(
            mesh_for_nodes(bucket(size[0]), devices),
            f"fused_cycle_costaudit{size[0]}", size=size)
        args = kernel.example_delta_args(256)
        closed = jax.make_jaxpr(kernel.traceable)(*args)
        env = {str(k): int(v)
               for k, v in dict(kernel.mesh.shape).items()}
        cost = jaxpr_cost(closed.jaxpr, env)
        findings += _site_findings(cost.sites, kernel.n_nodes, where)
        jaxpr_bytes = max(jaxpr_bytes, cost.collective_bytes)
        hlo = kernel._fn.lower(*args).compile().as_text()
        points.append((kernel.n_nodes,
                       hlo_collective_bytes(hlo, devices)
                       + cost.collective_bytes))
    per_cycle = points[-1][1]
    projected, exponent = project_power(points, northstar_padded_nodes())
    meta.update({
        "audited": True,
        "devices": devices,
        "points": [[int(x), int(y)] for x, y in points],
        "per_cycle_bytes": int(per_cycle),
        "jaxpr_explicit_bytes": int(jaxpr_bytes),
        "node_exponent": round(exponent, 3),
        "northstar_bytes": int(projected),
        "northstar_bytes_per_chip": int(projected / devices),
        "within_budget": projected / devices <= budget,
    })
    if per_cycle and exponent > COLLECTIVE_NODE_EXPONENT_LIMIT:
        findings.append(Finding(
            family="cost",
            key=(f"cost:collective-scaling:exponent="
                 f"{exponent:.2f}:limit={COLLECTIVE_NODE_EXPONENT_LIMIT}"),
            where=where,
            what=(f"per-cycle cross-shard collective bytes grow as "
                  f"N^{exponent:.2f} over {[p[0] for p in points]}-node "
                  f"compiles (limit {COLLECTIVE_NODE_EXPONENT_LIMIT}) — "
                  "cross-shard traffic must scale with devices and wave "
                  "width, never super-linearly with the node axis")))
    if projected / devices > budget:
        findings += _projection_findings(where, points, budget,
                                         kind="collective")
    return findings


def check_cost(traces, fast: bool = False,
               hbm_budget_bytes: Optional[int] = None,
               meta: Optional[dict] = None) -> List[Finding]:
    """The cost family: per-entry summaries + gates over the shared
    trace set, the north-star projection fit, and the sharded
    collective audit. ``meta`` (mutated in place when given) receives
    the numbers the JSON report and the bench ``cost`` block carry."""
    from .entrypoints import cost_projection_traces

    budget = hbm_budget_bytes or DEFAULT_HBM_BUDGET_BYTES
    meta = meta if meta is not None else {}
    meta["hbm_budget_bytes"] = budget
    meta["northstar"] = {"nodes": NS_NODES, "tasks": NS_TASKS,
                         "padded_nodes": northstar_padded_nodes()}
    findings: List[Finding] = []

    entries = meta.setdefault("entries", {})
    for tr in traces:
        ec = entry_cost(tr.closed, donated=getattr(tr, "donated", ()))
        entries[tr.name] = ec.to_meta()
        n = int(tr.dims.get("N", 0)) if tr.dims else 0
        if n:
            findings += _site_findings(ec.sites, n, tr.name)
        if ec.peak_live_bytes > budget:
            findings.append(Finding(
                family="cost",
                key=(f"cost:{tr.name}:peak={ec.peak_live_bytes}"
                     f":budget={budget}"),
                where=tr.name,
                what=(f"static peak live bytes of '{tr.name}' is "
                      f"{ec.peak_live_bytes:,} at the AUDIT size, over "
                      f"the {budget:,}-byte per-chip HBM budget")))

    # north-star projection: re-trace the projection entries at the fit
    # sizes (tracing is abstract — no compile, no real arrays)
    proj_meta = meta.setdefault("projection", {})
    for name, pts in cost_projection_traces(fast=fast):
        peak_pts = []
        for n_padded, closed, donated in pts:
            peak_pts.append((n_padded, peak_live_bytes(closed, donated)))
        projected, exponent = project_power(peak_pts,
                                            northstar_padded_nodes())
        proj_meta[name] = {
            "points": [[int(x), int(y)] for x, y in peak_pts],
            "peak_live_exponent": round(exponent, 3),
            "northstar_peak_live_bytes": int(projected),
            "within_budget": projected <= budget,
        }
        findings += _projection_findings(name, peak_pts, budget)

    coll_meta = meta.setdefault("collectives", {})
    findings += _collective_audit(
        PROJECTION_SIZES_FAST, budget, coll_meta)
    return findings


# ------------------------------------------------------------- bench hook
def bench_cost_meta(report_meta: Optional[dict]) -> Optional[dict]:
    """Flatten a graphcheck report's ``meta["cost"]`` into the bench
    ``cost`` block (fail-soft: None in, None out; every lookup
    null-safe). The headline numbers feed ``_regression_guard``."""
    cost = (report_meta or {}).get("cost") or {}
    entries = cost.get("entries") or {}
    if not entries:
        return None
    peak_entry = max(entries,
                     key=lambda k: entries[k].get("peak_live_bytes", 0))
    proj = cost.get("projection") or {}
    ns_peak = max((v.get("northstar_peak_live_bytes", 0)
                   for v in proj.values()), default=None)
    coll = cost.get("collectives") or {}
    scan = entries.get("allocate/scan") or entries[peak_entry]
    return {
        "hbm_budget_bytes": cost.get("hbm_budget_bytes"),
        "peak_live_bytes": entries[peak_entry].get("peak_live_bytes"),
        "peak_live_entry": peak_entry,
        "scan_flops": scan.get("flops"),
        "scan_arithmetic_intensity": scan.get("arithmetic_intensity"),
        "collective_bytes_per_cycle": coll.get("per_cycle_bytes"),
        "collective_node_exponent": coll.get("node_exponent"),
        "northstar": {
            "nodes": (cost.get("northstar") or {}).get("nodes"),
            "tasks": (cost.get("northstar") or {}).get("tasks"),
            "peak_live_bytes": ns_peak,
            "collective_bytes": coll.get("northstar_bytes"),
            "within_budget": (
                all(v.get("within_budget", True) for v in proj.values())
                and coll.get("within_budget", True)),
        },
    }
