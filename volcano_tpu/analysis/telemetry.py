"""Graphcheck family 7: the in-graph telemetry contract.

The telemetry tentpole (volcano_tpu/telemetry) rides INSIDE the compiled
cycle, so its failure modes are graph failure modes and belong in CI:

- **dtype**: every telemetry leaf must be i32/f32 — traced under
  enable_x64 with 32-bit inputs so any 64-bit counter (a weak-type
  promotion in an accumulator) is visible; checked both on the traced
  jaxpr of the telemetry=True build and on the result's telemetry leaf
  avals.
- **purity**: the telemetry=True build must not introduce host callbacks
  (the whole point of in-graph counters is avoiding them).
- **retrace**: the telemetry=True entry compiles once per shape bucket —
  re-invoking with fresh same-shaped inputs must not retrace (counters
  must not smuggle in value-dependent shapes). Full mode only; the fast
  tier-1 pass skips the extra compile.
- **DCE when disabled**: with telemetry=False (the default) the result's
  ``telemetry`` field is None and the flattened output carries exactly
  the pre-telemetry leaf count — nothing telemetry-shaped survives in the
  disabled build. (Equation-count identity vs the telemetry-free builder
  holds by construction: every counter sits behind ``if cfg.telemetry``;
  this check guards the output contract that construction relies on.)

Same shape for the preempt and backfill counter blocks.
"""

from __future__ import annotations

import dataclasses
from typing import List

from . import Finding

#: AllocateResult's non-telemetry leaf count (task_node, task_mode,
#: task_gpu, job_ready, job_pipelined, job_attempted, idle,
#: queue_allocated) — the disabled build must flatten to exactly this.
_ALLOCATE_LEAVES = 8
_OK_DTYPES = {"int32", "float32", "bool"}


def _leaf_findings(name: str, tel_tree) -> List[Finding]:
    """Findings for non-i32/f32 leaves in a telemetry pytree."""
    import jax
    out = []
    for i, leaf in enumerate(jax.tree.leaves(tel_tree)):
        dt = str(getattr(leaf, "dtype", ""))
        if dt not in ("int32", "float32"):
            out.append(Finding(
                family="telemetry",
                key=f"telemetry:{name}:leaf{i}:{dt}",
                where=f"{name} telemetry leaf {i}",
                what=(f"telemetry output leaf of dtype {dt} in '{name}' — "
                      "counter blocks must be pure i32/f32 (mosaic has no "
                      "64-bit types; the production x64-off config would "
                      "silently truncate)")))
    return out


def _jaxpr_findings(name: str, closed) -> List[Finding]:
    """Purity + 64-bit walk over a telemetry=True trace, reported under
    the telemetry family (the planted-leak surface of the test suite)."""
    from .jaxpr_audit import (CALLBACK_PRIMITIVES, WIDE_DTYPES, _loc,
                              iter_eqns)
    out = []
    seen = set()
    for eqn in iter_eqns(closed.jaxpr):
        pname = eqn.primitive.name
        if pname in CALLBACK_PRIMITIVES:
            key = f"telemetry:{name}:callback:{pname}"
            if key not in seen:
                seen.add(key)
                out.append(Finding(
                    family="telemetry", key=key, where=f"{name}",
                    what=(f"host callback primitive '{pname}' in the "
                          f"telemetry-enabled build of '{name}' — "
                          "telemetry must stay device-pure")))
            continue
        for v in eqn.outvars:
            dt = str(getattr(getattr(v, "aval", None), "dtype", ""))
            if dt in WIDE_DTYPES:
                loc = _loc(eqn)
                dedup = (loc, dt)
                if dedup in seen:
                    continue
                seen.add(dedup)
                out.append(Finding(
                    family="telemetry",
                    key=f"telemetry:{name}:{loc}:{pname}:{dt}",
                    where=f"{name} @ {loc}",
                    what=(f"{dt} intermediate ({pname}) in the "
                          f"telemetry-enabled build of '{name}': a 64-bit "
                          "leak the telemetry counters introduced — pin "
                          "the counter dtype at the source")))
    return out


def check_telemetry(fast: bool = False) -> List[Finding]:
    import jax
    import numpy as np

    from ..ops.allocate_scan import (AllocateConfig, derive_batching,
                                     make_allocate_cycle)
    from .entrypoints import _snap_extras

    findings: List[Finding] = []
    snap, extras = _snap_extras()
    cfg_off = dataclasses.replace(
        derive_batching(AllocateConfig(binpack_weight=1.0, enable_gpu=False),
                        has_proportion=False), use_pallas=False)
    cfg_on = dataclasses.replace(cfg_off, telemetry=True)

    # ---- DCE when disabled ------------------------------------------------
    out_off = jax.eval_shape(make_allocate_cycle(cfg_off), snap, extras)
    if out_off.telemetry is not None:
        findings.append(Finding(
            family="telemetry",
            key="telemetry:allocate:off-not-none",
            where="ops/allocate_scan telemetry=False",
            what=("telemetry=False build still returns a telemetry block — "
                  "the disabled path must dead-code-eliminate every "
                  "counter")))
    n_off = len(jax.tree.leaves(out_off))
    if n_off != _ALLOCATE_LEAVES:
        findings.append(Finding(
            family="telemetry",
            key=f"telemetry:allocate:off-leaves:{n_off}",
            where="ops/allocate_scan telemetry=False",
            what=(f"telemetry=False AllocateResult flattens to {n_off} "
                  f"leaves (expected {_ALLOCATE_LEAVES}) — a telemetry-"
                  "shaped output leaked into the disabled build")))

    # ---- telemetry=True: dtypes + purity under an x64 trace ---------------
    with jax.experimental.enable_x64():
        closed_on = jax.make_jaxpr(make_allocate_cycle(cfg_on))(snap, extras)
    findings += _jaxpr_findings("allocate/scan+telemetry", closed_on)
    out_on = jax.eval_shape(make_allocate_cycle(cfg_on), snap, extras)
    findings += _leaf_findings("allocate/scan", out_on.telemetry)

    # ---- preempt + backfill counter blocks --------------------------------
    from ..ops.backfill import make_backfill_pass
    from ..ops.preempt import PreemptConfig, make_preempt_cycle
    T = snap.tasks.resreq.shape[0]
    zeros_t = np.zeros(T, bool)
    pcfg_off = PreemptConfig(scoring=AllocateConfig(binpack_weight=1.0,
                                                    enable_gpu=False))
    pcfg_on = dataclasses.replace(pcfg_off, telemetry=True)
    pres_off = jax.eval_shape(make_preempt_cycle(pcfg_off), snap, extras,
                              zeros_t, zeros_t)
    if pres_off.telemetry is not None:
        findings.append(Finding(
            family="telemetry", key="telemetry:preempt:off-not-none",
            where="ops/preempt telemetry=False",
            what="telemetry=False preempt build still returns a counter "
                 "block"))
    pres_on = jax.eval_shape(make_preempt_cycle(pcfg_on), snap, extras,
                             zeros_t, zeros_t)
    findings += _leaf_findings("ops/preempt", pres_on.telemetry)
    bf_off = jax.eval_shape(make_backfill_pass(), snap)
    if len(bf_off) != 2:
        findings.append(Finding(
            family="telemetry", key="telemetry:backfill:off-arity",
            where="ops/backfill telemetry=False",
            what="telemetry=False backfill no longer returns exactly "
                 "(task_node, placed)"))
    bf_on = jax.eval_shape(make_backfill_pass(telemetry=True), snap)
    findings += _leaf_findings("ops/backfill", bf_on[2])

    # ---- conf plumbing: `telemetry: true` reaches the kernel config -------
    from ..framework.compiled_session import allocate_config_from_conf
    from ..framework.conf import DEFAULT_SCHEDULER_CONF, parse_conf
    sc = parse_conf("telemetry: true\n" + DEFAULT_SCHEDULER_CONF)
    if not allocate_config_from_conf(sc).telemetry:
        findings.append(Finding(
            family="telemetry", key="telemetry:conf:not-plumbed",
            where="framework/compiled_session",
            what="a conf with `telemetry: true` derives an AllocateConfig "
                 "with telemetry off — the conf plumb broke"))

    # ---- no per-cycle retraces with telemetry on (full mode: one compile) -
    if not fast:
        trace_n = [0]

        def counted(s, e):
            trace_n[0] += 1
            return make_allocate_cycle(cfg_on)(s, e)

        fn = jax.jit(counted)
        fn(snap, extras)
        fn(jax.tree.map(lambda x: x, snap), jax.tree.map(lambda x: x,
                                                         extras))
        if trace_n[0] != 1:
            findings.append(Finding(
                family="telemetry",
                key=f"telemetry:allocate:retrace:{trace_n[0]}",
                where="ops/allocate_scan telemetry=True",
                what=(f"telemetry-enabled cycle traced {trace_n[0]}x for "
                      "one shape bucket — counters introduced a "
                      "per-cycle retrace hazard")))
    return findings
