"""Graphcheck family 8: delta-upload + buffer-donation safety.

The device-resident snapshot path (ops/fused_io.DeltaKernel) donates the
three fused group buffers through the update+cycle entry so XLA updates
them in place. Donation makes a whole failure class possible that no unit
assertion sees until a driver TPU corrupts a cycle:

- **re-read after donation** — host code (or a second consumer of the
  same state) reading a buffer handle the entry already consumed. On TPU
  the memory is aliased into the outputs, so the read returns whatever the
  scatter wrote — silently. The framework's discipline is fail-fast
  invalidation with a one-dispatch deadline: an honored donation kills
  the handle at dispatch, and DeltaKernel deletes whatever the runtime
  left alive at the NEXT dispatch (when the depth-1 pipeline has drained
  the consumer, so the delete cannot block). This family runs real
  full-then-delta cycles and fails if a consumed handle is still
  readable one dispatch later.
- **donation off-contract** — the entry's donation must match the
  platform: the three resident buffers on accelerators (in-place
  scatter), NONE on the CPU backend, where XLA executes donated
  computations inline and would serialize the pipelined loop on compute
  (``ops/fused_io.donation_for_backend`` is the single authority).
- **host callback in the delta scatter** — the update half must stay as
  device-pure as the cycle itself; a callback smuggled into the scatter
  path re-serializes every cycle on a host round-trip. Checked on the
  traced jaxpr of the REAL update+cycle entry (the purity walk scoped to
  this family so a planted violation is attributable to the delta path).
- **delta/full divergence** — the scattered buffers must be bit-identical
  to freshly fused ones; the family replays one mutation through both
  paths and compares the packed decisions byte-for-byte.

All checks run on CPU with small REAL snapshots through the same
``arrays.pack`` path production uses.
"""

from __future__ import annotations

from typing import List

from . import Finding


def check_donation(fast: bool = False) -> List[Finding]:
    import dataclasses

    import jax
    import numpy as np

    from ..ops.allocate_scan import (AllocateConfig, derive_batching,
                                     make_allocate_cycle)
    from ..ops.fused_io import (DeltaKernel, ResidentState,
                                donation_for_backend)
    from .entrypoints import _snap_extras
    from .jaxpr_audit import CALLBACK_PRIMITIVES, _loc, iter_eqns

    findings: List[Finding] = []
    snap, extras = _snap_extras()
    cfg = dataclasses.replace(
        derive_batching(AllocateConfig(binpack_weight=1.0, enable_gpu=False),
                        has_proportion=False), use_pallas=False)
    cycle = make_allocate_cycle(cfg)
    kernel = DeltaKernel(cycle, (snap, extras))

    # ---- donation must match the platform contract ------------------------
    # accelerators donate the three resident buffers (in-place scatter);
    # the CPU backend must NOT donate — XLA executes donated computations
    # inline there, which serializes the pipelined loop on compute
    expected = donation_for_backend(
        n_residents=getattr(kernel, "n_residents", 3))
    if tuple(kernel.donate_argnums) != tuple(expected):
        findings.append(Finding(
            family="donation",
            key=f"donation:delta-entry:argnums:{kernel.donate_argnums}",
            where="ops/fused_io.DeltaKernel",
            what=(f"delta update+cycle entry donates {kernel.donate_argnums}"
                  f" but this backend's contract is {expected} — donation "
                  "on CPU forces synchronous dispatch; missing donation on "
                  "an accelerator re-allocates the full fused buffers "
                  "every cycle")))

    # ---- purity of the delta scatter (traced on the REAL entry) -----------
    with jax.experimental.enable_x64():
        closed = jax.make_jaxpr(kernel.traceable)(
            *kernel.example_delta_args())
    seen = set()
    for eqn in iter_eqns(closed.jaxpr):
        pname = eqn.primitive.name
        if pname in CALLBACK_PRIMITIVES and pname not in seen:
            seen.add(pname)
            findings.append(Finding(
                family="donation",
                key=f"donation:delta-entry:callback:{pname}",
                where=f"ops/fused_io delta entry @ {_loc(eqn)}",
                what=(f"host callback primitive '{pname}' in the delta "
                      "update+cycle entry — the scatter path must stay "
                      "device-pure (a callback re-serializes every "
                      "steady-state cycle on a host round-trip)")))

    # ---- live full -> delta cycles: invalidation + parity -----------------
    state = ResidentState()
    np.asarray(kernel.run(state, (snap, extras)))     # cold full upload
    handles_after_full = state.device
    # mutate one packed leaf in place (a priority bump: the smallest
    # realistic steady-state churn) and run the delta path
    prio = np.asarray(snap.tasks.priority)
    prio[0] = prio[0] + 1
    delta_packed = np.asarray(kernel.run(state, (snap, extras)))
    if state.last_kind != "delta":
        findings.append(Finding(
            family="donation",
            key=f"donation:delta-entry:no-delta:{state.last_kind}",
            where="ops/fused_io.DeltaKernel.run",
            what=("a one-element change took the "
                  f"'{state.last_kind}' path instead of a delta upload — "
                  "the steady-state O(dirty) contract is broken")))
    # the invalidation deadline: a consumed handle is dead no later than
    # the NEXT dispatched cycle (immediately under honored donation)
    np.asarray(kernel.run(state, (snap, extras)))     # idle delta cycle
    for i, h in enumerate(handles_after_full):
        try:
            np.asarray(h)
        except RuntimeError:
            continue        # deleted — the contract
        findings.append(Finding(
            family="donation",
            key=f"donation:delta-entry:re-read:buf{i}",
            where="ops/fused_io.ResidentState",
            what=(f"resident buffer {i} is still readable one dispatch "
                  "after the cycle that consumed it — the invalidation "
                  "discipline was lost, so a host re-read on TPU would "
                  "silently return post-scatter (aliased) data instead "
                  "of failing fast")))
    # delta-ingested decisions must equal a cold full-upload run
    kernel2 = DeltaKernel(cycle, (snap, extras))
    ref_mutated = np.asarray(kernel2.run(ResidentState(), (snap, extras)))
    if not np.array_equal(delta_packed, ref_mutated):
        findings.append(Finding(
            family="donation",
            key="donation:delta-entry:divergence",
            where="ops/fused_io.DeltaKernel",
            what=("delta-ingested cycle decisions differ from the "
                  "full-upload path on the same snapshot — the scatter is "
                  "not reproducing the fused buffers bit-exactly")))
    prio[0] = prio[0] - 1   # restore the shared packed snapshot
    return findings
