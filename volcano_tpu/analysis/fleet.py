"""Graphcheck family 10: multi-tenant batched-cycle isolation.

The fleet runtime (fleet/pool.py) serves B same-bucket tenants through
ONE compiled entry: stacked residents ``(B, n_g)``, one flat global-index
delta scatter, and the allocate cycle vmapped over the tenant axis. The
whole multi-tenancy contract rests on that entry never mixing tenant
rows — a reduction, broadcast, or reshape that crosses the leading axis
would leak one tenant's cluster state into another tenant's decisions
while every unit test on the flat cycle stays green. This family audits
the REAL batched entry three ways:

- **purity** — the batched jaxpr contains no host-callback primitives
  (the vmapped cycle must stay as device-pure as the flat one; the walk
  is scoped here so a planted violation is attributable to the fleet
  path).
- **tenant axis** — every output of the entry (the three scattered
  residents AND the packed decisions) carries the leading tenant axis at
  the bucket width: a dropped or transposed axis means rows are being
  flattened somewhere before the readback split.
- **value isolation** — the decisive check, at value level rather than
  graph level: run the entry on two stacked rows built from the same
  REAL packed snapshot, perturb ONE element of tenant row 1's input,
  and require tenant row 0's packed decisions — integrity digest words
  included — to stay bit-identical. vmap guarantees this by
  construction; the probe proves the guarantee survived whatever was
  composed around the vmap (the flat scatter, the digest concat, future
  edits). The planted-leak test (tests/test_fleet.py) flips
  ``fleet.pool._LEAK_FOR_TESTS`` and requires this probe to FIRE, so
  the check is known to be live.

Runs on CPU with small real snapshots through the same ``arrays.pack``
path production uses; reports nothing only if the fleet module is
absent.
"""

from __future__ import annotations

from typing import List

from . import Finding


def check_fleet(fast: bool = False) -> List[Finding]:
    import jax
    import numpy as np

    from ..fleet.pool import FleetDeltaKernel, normalize_config
    from ..ops.allocate_scan import (AllocateConfig, derive_batching,
                                     make_allocate_cycle)
    from ..ops.fused_io import fuse_into
    from .entrypoints import _snap_extras
    from .jaxpr_audit import CALLBACK_PRIMITIVES, _loc, iter_eqns

    findings: List[Finding] = []
    snap, extras = _snap_extras()
    tree = (snap, extras)
    cfg = normalize_config(derive_batching(
        AllocateConfig(binpack_weight=1.0, enable_gpu=False),
        has_proportion=False))
    width = 2
    kernel = FleetDeltaKernel(make_allocate_cycle(cfg), tree, width,
                              entry="graphcheck/fleet", integrity=True)

    # ---- purity of the batched entry (traced on the REAL entry) -----------
    closed = jax.make_jaxpr(kernel.traceable)(
        *kernel.example_batched_args())
    seen = set()
    for eqn in iter_eqns(closed.jaxpr):
        pname = eqn.primitive.name
        if pname in CALLBACK_PRIMITIVES and pname not in seen:
            seen.add(pname)
            findings.append(Finding(
                family="fleet",
                key=f"fleet:batched-entry:callback:{pname}",
                where=f"fleet/pool batched entry @ {_loc(eqn)}",
                what=(f"host callback primitive '{pname}' in the batched "
                      "fleet entry — the vmapped cycle must stay "
                      "device-pure (a callback re-serializes every "
                      "fleet cycle, for every tenant, on a host "
                      "round-trip)")))

    # ---- every output carries the leading tenant axis ---------------------
    out_names = ("fbuf", "ibuf", "bbuf", "packed_decisions")
    for name, var in zip(out_names, closed.jaxpr.outvars):
        shape = tuple(getattr(var.aval, "shape", ()))
        if len(shape) < 2 or shape[0] != width:
            findings.append(Finding(
                family="fleet",
                key=f"fleet:batched-entry:axis:{name}:{shape}",
                where="fleet/pool.FleetDeltaKernel",
                what=(f"batched entry output '{name}' has shape {shape} — "
                      f"expected a leading tenant axis of width {width}; "
                      "a dropped axis means tenant rows are flattened "
                      "before the per-tenant readback split")))

    # ---- value-level cross-tenant isolation probe -------------------------
    bufs = fuse_into(tree, kernel.spec, kernel.sizes)
    stacked = [np.stack([b, b]) for b in bufs]
    no_delta = []
    for b in bufs:
        no_delta += [np.zeros(0, np.int32), np.zeros(0, b.dtype)]

    def run(args):
        import jax.numpy as jnp
        outs = kernel.traceable(*(jnp.asarray(a) for a in args),
                                *(jnp.asarray(d) for d in no_delta))
        return np.asarray(outs[3])

    base_packed = run(stacked)
    perturbed = [s.copy() for s in stacked]
    # flip one element of tenant row 1 in EVERY non-empty group: an
    # arbitrary value change in ONE tenant's inputs — row 0's decisions
    # (and row-0 digest words) must not move
    for s in perturbed:
        if s.shape[1]:
            if s.dtype == np.bool_:
                s[1, 0] = ~s[1, 0]
            else:
                s[1, 0] = s[1, 0] + s.dtype.type(1)
    pert_packed = run(perturbed)
    if not np.array_equal(base_packed[0], pert_packed[0]):
        moved = int(np.sum(base_packed[0] != pert_packed[0]))
        findings.append(Finding(
            family="fleet",
            key=f"fleet:batched-entry:cross-tenant-flow:{moved}",
            where="fleet/pool.FleetDeltaKernel",
            what=(f"perturbing one element of tenant row 1's stacked "
                  f"inputs moved {moved} element(s) of tenant row 0's "
                  "packed decisions — cross-tenant data flow in the "
                  "batched entry; one tenant's cluster state is leaking "
                  "into another tenant's scheduling decisions")))
    return findings
