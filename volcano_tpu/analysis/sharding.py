"""Graphcheck family 9: node-axis sharded-cycle collective discipline.

The pod-scale execution mode (ops/fused_io.ShardedDeltaKernel +
parallel/sharding) splits the resident snapshot buffers along the node
axis and lets GSPMD partition the SAME cycle program the single-device
jit runs. Correctness is cheap to keep (decisions are bit-identical by
construction); what silently rots is the *communication volume*: one
mis-sharded intermediate and the partitioner inserts an all-gather that
re-materializes an O(nodes) tensor on every device, every cycle — the
distributed analog of the [M, N] gather class, and invisible to every
numeric test because the gathered values are correct.

This family compiles the REAL sharded update+cycle entry on a small
real snapshot and enforces two invariants on the compiled module:

- **no O(nodes) all-gather** — the compiled HLO may contain mesh-sized
  gathers (per-shard digests, routed-delta bookkeeping) and single
  node-axis COLUMN gathers (the scan carry syncing one f32[N, 1] score
  column is the collective analog of SelectBestNode and is priced into
  the design), but any all-gather whose output reaches 2x the node axis
  re-materializes multi-column node state and is flagged.
- **replicated decisions** — the packed decision vector must leave the
  entry fully replicated: every host reads the same bytes without a
  collective at readback time, and the per-shard digest tail stays
  comparable shard-local. Resident outputs must keep their declared
  input shardings (out == in: the zero inter-iteration resharding
  contract the live probe in ResidentState counts against).

Since ISSUE 14 the sharded cycle also honors ``use_pallas`` (the
shard-local candidate launch in ops/allocate_scan + ops/pallas_place),
so the family additionally audits the sharded+pallas entry:

- **shard-local pallas blocks** — every ``pallas_call`` in the traced
  entry must operate on shard-local node blocks (NL = nodes / mesh).
  A launch whose operand or result carries the FULL node axis means a
  full-axis gather fed the kernel — the exact O(nodes) leak the
  shard-local design exists to prevent (the gather itself may also trip
  the all-gather check, but an interpreted launch can hide it behind
  element-wise HLO, so the jaxpr-level block check is load-bearing).

Since ISSUE 20 the family also traces the *quarantine-rebuild* entry:
the mesh :func:`..parallel.sharding.mesh_for_nodes` produces after the
device-health registry quarantines a device (a non-prefix survivor
subset at the halved width cap) must satisfy the same collective,
replicated-decision, and out==in discipline — the elastic-mesh rung
serves real cycles on exactly that mesh.

With fewer than two local devices there is no mesh to audit and the
family reports nothing (the tier-1 test environment forces 8 virtual
CPU devices; scripts/graphcheck.sh exports the same default).
"""

from __future__ import annotations

import re
from typing import List

from . import Finding

#: all-gather (or its async start half) with its HLO output shape, e.g.
#: ``%all-gather = f32[128,4]{1,0} all-gather(...`` — shape dims group 1
_ALLGATHER_RE = re.compile(
    r"=\s*[a-z0-9]+\[([0-9,]*)\][^ ]*\s+all-gather(?:-start)?\(")


def _collective_findings(hlo_text: str, n_nodes: int,
                         where: str) -> List[Finding]:
    """Scan compiled HLO text for all-gathers whose output re-materializes
    O(nodes) state (output elements >= 2 * n_nodes). Shared by the live
    check and the planted-violation test."""
    findings: List[Finding] = []
    seen = set()
    for m in _ALLGATHER_RE.finditer(hlo_text):
        dims = [int(d) for d in m.group(1).split(",") if d]
        elems = 1
        for d in dims:
            elems *= d
        if elems < 2 * n_nodes:
            continue        # mesh-sized / single-column gathers are priced in
        shape = "x".join(str(d) for d in dims) or "scalar"
        if shape in seen:
            continue
        seen.add(shape)
        findings.append(Finding(
            family="sharding",
            key=f"sharding:allgather:{where}:{shape}",
            where=where,
            what=(f"compiled sharded cycle contains an all-gather with "
                  f"output shape [{shape}] ({elems} elements >= "
                  f"2*{n_nodes} nodes) — an O(nodes) re-materialization "
                  "on every device, every cycle; reshard the producing "
                  "intermediate instead of gathering it")))
    return findings


def _pallas_findings(closed, n_nodes: int, rows_per: int,
                     where: str) -> List[Finding]:
    """Walk a traced sharded entry for ``pallas_call`` eqns whose block
    shapes exceed the shard-local row count. Under the shard_map local
    view every node-axis operand is NL = rows_per wide; a dim equal to
    the FULL node axis proves a full-axis gather fed the launch. Shared
    by the live check and the planted-violation test."""
    from .jaxpr_audit import iter_eqns
    findings: List[Finding] = []
    if rows_per >= n_nodes:
        return findings         # single-shard mesh: nothing to leak
    seen = set()
    for eqn in iter_eqns(closed.jaxpr):
        if eqn.primitive.name != "pallas_call":
            continue
        for v in list(eqn.invars) + list(eqn.outvars):
            shape = getattr(getattr(v, "aval", None), "shape", None)
            if not shape or n_nodes not in shape:
                continue
            key = f"sharding:pallas-block:{where}:{tuple(shape)}"
            if key in seen:
                continue
            seen.add(key)
            findings.append(Finding(
                family="sharding", key=key, where=where,
                what=(f"pallas launch operand/result of shape "
                      f"{tuple(shape)} carries the full {n_nodes}-node "
                      f"axis inside a {rows_per}-row shard — a full-axis "
                      "gather is feeding the kernel; the launch must stay "
                      "shard-local (NL = nodes / mesh) with the winner "
                      "resolved by the in-graph cross-shard combine")))
    return findings


def planted_allgather_hlo(n_devices: int = 2, n_nodes: int = 32,
                          cols: int = 4) -> str:
    """Compile a deliberately mis-sharded program — a node-sharded
    (N, cols) input forced to a replicated output — and return its HLO
    text. The partitioner must insert a full [N, cols] all-gather, which
    ``_collective_findings`` provably flags (tests/test_graphcheck.py)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec

    mesh = Mesh(np.array(jax.devices()[:n_devices]), ("nodes",))
    node = NamedSharding(mesh, PartitionSpec("nodes", None))
    rep = NamedSharding(mesh, PartitionSpec())
    fn = jax.jit(lambda x: x + 1.0, in_shardings=node, out_shardings=rep)
    return fn.lower(jax.ShapeDtypeStruct((n_nodes, cols),
                                         jnp.float32)).compile().as_text()


def planted_gather_pallas(n_devices: int = 2, n_nodes: int = 32,
                          cols: int = 4):
    """Compile a deliberately broken shard-local launch — each shard
    all-gathers the FULL node axis and feeds it to a pallas launch —
    and return ``(closed_jaxpr, rows_per)``. ``_pallas_findings`` must
    flag the full-axis block (tests/test_graphcheck.py)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.experimental import pallas as pl
    from jax.experimental.shard_map import shard_map
    from jax.sharding import Mesh, PartitionSpec as P

    mesh = Mesh(np.array(jax.devices()[:n_devices]), ("nodes",))
    rows_per = n_nodes // n_devices

    def body(x_ref, o_ref):
        o_ref[:] = x_ref[:] * 2.0

    def local(x):
        full = jax.lax.all_gather(x, "nodes", axis=0, tiled=True)
        out = pl.pallas_call(
            body,
            out_shape=jax.ShapeDtypeStruct(full.shape, full.dtype),
            interpret=True)(full)
        off = jax.lax.axis_index("nodes") * rows_per
        return jax.lax.dynamic_slice_in_dim(out, off, rows_per)

    fn = shard_map(local, mesh=mesh, in_specs=P("nodes", None),
                   out_specs=P("nodes", None), check_rep=False)
    closed = jax.make_jaxpr(fn)(
        jax.ShapeDtypeStruct((n_nodes, cols), jnp.float32))
    return closed, rows_per


def _audit_kernel(mesh, entry: str, use_pallas=None, size=None):
    """Build the real sharded update+cycle entry on a small real snapshot
    (same pack path production uses) over ``mesh``. ``use_pallas``
    selects the kernel path exactly like the conf knob — "interpret"
    builds the shard-local pallas candidate launch (ISSUE 14).
    ``size`` overrides the audit problem size (the cost family's
    node-scaling fit traces the same entry at two node widths)."""
    import dataclasses

    from ..ops.allocate_scan import (AllocateConfig, derive_batching,
                                     make_allocate_cycle)
    from ..ops.fused_io import ShardedDeltaKernel
    from ..parallel import node_leaf_mask
    from .entrypoints import _AUDIT_SIZE, _snap_extras

    # the standard audit size (N=128): the node axis must dominate the
    # task/job axes so the O(tasks+jobs) packed-decision replication
    # stays clearly below the 2*N threshold
    snap, extras = _snap_extras(size or _AUDIT_SIZE)
    cfg = dataclasses.replace(
        derive_batching(AllocateConfig(binpack_weight=1.0, enable_gpu=False),
                        has_proportion=False), use_pallas=use_pallas)
    cycle = make_allocate_cycle(cfg, mesh=mesh)
    return ShardedDeltaKernel(cycle, (snap, extras), mesh,
                              node_leaf_mask((snap, extras)), entry=entry)


def check_sharding(fast: bool = False) -> List[Finding]:
    import jax

    from ..parallel import mesh_for_nodes

    if jax.device_count() < 2:
        return []               # no mesh to audit on a single device
    findings: List[Finding] = []

    # fast: the 2-device mesh (cheapest GSPMD compile that still
    # partitions), scan AND shard-local-pallas kernels; full:
    # additionally the widest mesh the node axis admits, where a
    # mis-sharded intermediate costs the most
    mesh2 = mesh_for_nodes(128, 2)
    meshes = [
        (2, _audit_kernel(mesh2, "fused_cycle_shardaudit2"), False),
        (2, _audit_kernel(mesh2, "fused_cycle_shardaudit2pl",
                          use_pallas="interpret"), True),
    ]
    # quarantine-rebuild entry (ISSUE 20): after a persistent device loss
    # the elastic-mesh rung serves on a NON-PREFIX survivor subset at the
    # halved width cap. The same collective / replicated-decision /
    # out==in discipline must hold on that rebuilt mesh, audited through
    # the real path — a strike-quarantined registry feeding
    # mesh_for_nodes — then restored so no health state leaks.
    if jax.device_count() >= 5:
        from ..parallel import HEALTH
        try:
            HEALTH.configure()
            loss = RuntimeError("graphcheck planted device loss")
            loss.device_ids = (jax.devices()[0].id,)
            for c in range(HEALTH.strikes):
                HEALTH.note_failure(loss, cycle=c, serving_width=8)
            qmesh = mesh_for_nodes(128, 8)
            dq = int(qmesh.devices.size)
            meshes.append((dq, _audit_kernel(
                qmesh, f"fused_cycle_shardaudit{dq}q"), False))
        finally:
            HEALTH.configure()
    if not fast and jax.device_count() >= 4:
        wide = mesh_for_nodes(128, jax.device_count())
        d = int(wide.devices.size)
        if d > 2:
            meshes.append((d, _audit_kernel(
                wide, f"fused_cycle_shardaudit{d}"), False))
            meshes.append((d, _audit_kernel(
                wide, f"fused_cycle_shardaudit{d}pl",
                use_pallas="interpret"), True))

    for d, kernel, pl_on in meshes:
        kind = "pallas," if pl_on else ""
        where = f"ops/fused_io.ShardedDeltaKernel[{kind}{d}dev]"
        args = kernel.example_delta_args(256)
        if pl_on:
            # jaxpr-level: every pallas launch must stay shard-local
            closed = jax.make_jaxpr(kernel.traceable)(*args)
            findings += _pallas_findings(closed, kernel.n_nodes,
                                         kernel.rows_per, where)
        # steady-state delta signature: what every warm cycle compiles
        compiled = kernel._fn.lower(*args).compile()
        findings += _collective_findings(compiled.as_text(),
                                         kernel.n_nodes, where)

        # replicated-decision + out==in resident-sharding discipline
        out_sh = compiled.output_shardings
        packed_sh = out_sh[-1]
        if not packed_sh.is_fully_replicated:
            findings.append(Finding(
                family="sharding",
                key=f"sharding:decisions-not-replicated:{d}dev",
                where=where,
                what=("the packed decision output is not fully replicated "
                      f"(sharding {packed_sh}) — hosts would need a "
                      "collective (or a cross-device copy) at readback, "
                      "and per-shard digest words would not be comparable "
                      "shard-local")))
        for i, (got, want) in enumerate(zip(out_sh[:6],
                                            kernel.resident_shardings)):
            ndim = 2 if i < 3 else 1
            if not got.is_equivalent_to(want, ndim):
                findings.append(Finding(
                    family="sharding",
                    key=f"sharding:resident-resharded:{d}dev:buf{i}",
                    where=where,
                    what=(f"resident output {i} leaves the entry with "
                          f"sharding {got} instead of its declared input "
                          f"sharding {want} — every iteration pays a "
                          "resharding copy, breaking the zero-copy "
                          "steady-state contract")))
    return findings
