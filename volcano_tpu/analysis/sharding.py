"""Graphcheck family 9: node-axis sharded-cycle collective discipline.

The pod-scale execution mode (ops/fused_io.ShardedDeltaKernel +
parallel/sharding) splits the resident snapshot buffers along the node
axis and lets GSPMD partition the SAME cycle program the single-device
jit runs. Correctness is cheap to keep (decisions are bit-identical by
construction); what silently rots is the *communication volume*: one
mis-sharded intermediate and the partitioner inserts an all-gather that
re-materializes an O(nodes) tensor on every device, every cycle — the
distributed analog of the [M, N] gather class, and invisible to every
numeric test because the gathered values are correct.

This family compiles the REAL sharded update+cycle entry on a small
real snapshot and enforces two invariants on the compiled module:

- **no O(nodes) all-gather** — the compiled HLO may contain mesh-sized
  gathers (per-shard digests, routed-delta bookkeeping) and single
  node-axis COLUMN gathers (the scan carry syncing one f32[N, 1] score
  column is the collective analog of SelectBestNode and is priced into
  the design), but any all-gather whose output reaches 2x the node axis
  re-materializes multi-column node state and is flagged.
- **replicated decisions** — the packed decision vector must leave the
  entry fully replicated: every host reads the same bytes without a
  collective at readback time, and the per-shard digest tail stays
  comparable shard-local. Resident outputs must keep their declared
  input shardings (out == in: the zero inter-iteration resharding
  contract the live probe in ResidentState counts against).

With fewer than two local devices there is no mesh to audit and the
family reports nothing (the tier-1 test environment forces 8 virtual
CPU devices; scripts/graphcheck.sh exports the same default).
"""

from __future__ import annotations

import re
from typing import List

from . import Finding

#: all-gather (or its async start half) with its HLO output shape, e.g.
#: ``%all-gather = f32[128,4]{1,0} all-gather(...`` — shape dims group 1
_ALLGATHER_RE = re.compile(
    r"=\s*[a-z0-9]+\[([0-9,]*)\][^ ]*\s+all-gather(?:-start)?\(")


def _collective_findings(hlo_text: str, n_nodes: int,
                         where: str) -> List[Finding]:
    """Scan compiled HLO text for all-gathers whose output re-materializes
    O(nodes) state (output elements >= 2 * n_nodes). Shared by the live
    check and the planted-violation test."""
    findings: List[Finding] = []
    seen = set()
    for m in _ALLGATHER_RE.finditer(hlo_text):
        dims = [int(d) for d in m.group(1).split(",") if d]
        elems = 1
        for d in dims:
            elems *= d
        if elems < 2 * n_nodes:
            continue        # mesh-sized / single-column gathers are priced in
        shape = "x".join(str(d) for d in dims) or "scalar"
        if shape in seen:
            continue
        seen.add(shape)
        findings.append(Finding(
            family="sharding",
            key=f"sharding:allgather:{where}:{shape}",
            where=where,
            what=(f"compiled sharded cycle contains an all-gather with "
                  f"output shape [{shape}] ({elems} elements >= "
                  f"2*{n_nodes} nodes) — an O(nodes) re-materialization "
                  "on every device, every cycle; reshard the producing "
                  "intermediate instead of gathering it")))
    return findings


def planted_allgather_hlo(n_devices: int = 2, n_nodes: int = 32,
                          cols: int = 4) -> str:
    """Compile a deliberately mis-sharded program — a node-sharded
    (N, cols) input forced to a replicated output — and return its HLO
    text. The partitioner must insert a full [N, cols] all-gather, which
    ``_collective_findings`` provably flags (tests/test_graphcheck.py)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec

    mesh = Mesh(np.array(jax.devices()[:n_devices]), ("nodes",))
    node = NamedSharding(mesh, PartitionSpec("nodes", None))
    rep = NamedSharding(mesh, PartitionSpec())
    fn = jax.jit(lambda x: x + 1.0, in_shardings=node, out_shardings=rep)
    return fn.lower(jax.ShapeDtypeStruct((n_nodes, cols),
                                         jnp.float32)).compile().as_text()


def _audit_kernel(mesh, entry: str):
    """Build the real sharded update+cycle entry on a small real snapshot
    (same pack path production uses) over ``mesh``."""
    import dataclasses

    from ..ops.allocate_scan import (AllocateConfig, derive_batching,
                                     make_allocate_cycle)
    from ..ops.fused_io import ShardedDeltaKernel
    from ..parallel import node_leaf_mask
    from .entrypoints import _snap_extras

    # the standard audit size (N=128): the node axis must dominate the
    # task/job axes so the O(tasks+jobs) packed-decision replication
    # stays clearly below the 2*N threshold
    snap, extras = _snap_extras()
    cfg = dataclasses.replace(
        derive_batching(AllocateConfig(binpack_weight=1.0, enable_gpu=False),
                        has_proportion=False), use_pallas=False)
    cycle = make_allocate_cycle(cfg)
    return ShardedDeltaKernel(cycle, (snap, extras), mesh,
                              node_leaf_mask((snap, extras)), entry=entry)


def check_sharding(fast: bool = False) -> List[Finding]:
    import jax

    from ..parallel import mesh_for_nodes

    if jax.device_count() < 2:
        return []               # no mesh to audit on a single device
    findings: List[Finding] = []

    # fast: the 2-device mesh (cheapest GSPMD compile that still
    # partitions); full: additionally the widest mesh the node axis
    # admits, where a mis-sharded intermediate costs the most
    kernel2 = _audit_kernel(mesh_for_nodes(128, 2), "fused_cycle_shardaudit2")
    meshes = [(2, kernel2)]
    if not fast and jax.device_count() >= 4:
        wide = mesh_for_nodes(128, jax.device_count())
        d = int(wide.devices.size)
        if d > 2:
            meshes.append((d, _audit_kernel(
                wide, f"fused_cycle_shardaudit{d}")))

    for d, kernel in meshes:
        where = f"ops/fused_io.ShardedDeltaKernel[{d}dev]"
        # steady-state delta signature: what every warm cycle compiles
        compiled = kernel._fn.lower(
            *kernel.example_delta_args(256)).compile()
        findings += _collective_findings(compiled.as_text(),
                                         kernel.n_nodes, where)

        # replicated-decision + out==in resident-sharding discipline
        out_sh = compiled.output_shardings
        packed_sh = out_sh[-1]
        if not packed_sh.is_fully_replicated:
            findings.append(Finding(
                family="sharding",
                key=f"sharding:decisions-not-replicated:{d}dev",
                where=where,
                what=("the packed decision output is not fully replicated "
                      f"(sharding {packed_sh}) — hosts would need a "
                      "collective (or a cross-device copy) at readback, "
                      "and per-shard digest words would not be comparable "
                      "shard-local")))
        for i, (got, want) in enumerate(zip(out_sh[:6],
                                            kernel.resident_shardings)):
            ndim = 2 if i < 3 else 1
            if not got.is_equivalent_to(want, ndim):
                findings.append(Finding(
                    family="sharding",
                    key=f"sharding:resident-resharded:{d}dev:buf{i}",
                    where=where,
                    what=(f"resident output {i} leaves the entry with "
                          f"sharding {got} instead of its declared input "
                          f"sharding {want} — every iteration pays a "
                          "resharding copy, breaking the zero-copy "
                          "steady-state contract")))
    return findings
