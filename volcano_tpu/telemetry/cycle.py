"""In-graph cycle telemetry counter blocks (the tentpole of ISSUE 3).

The reference scheduler answers "why did this task not place?" with
host-side prometheus counters incremented mid-loop
(pkg/scheduler/metrics/metrics.go:38-202 — ``unschedule_task_count`` with
reason labels, ``schedule_attempts_total``). The compiled TPU cycle cannot
host-callback (graphcheck purity family), so the same information is
reproduced as pure device-side accumulators: small i32/f32 arrays carried
through the cycle's ``while_loop`` and returned as ONE extra output,
fetched in the same packed readback the decisions already pay
(``AllocateResult.packed_decisions``). No callbacks, no extra transfers,
no per-cycle retraces.

Design constraints (enforced by the graphcheck ``telemetry`` family):

- every leaf is i32 or f32 — mosaic has no 64-bit types, and a 64-bit
  counter under the production x64-off config would silently truncate;
- the whole block hides behind ``AllocateConfig.telemetry`` (default
  False): when off, nothing is traced and the cycle's jaxpr is
  equation-count-identical to a build without telemetry, and the result's
  ``telemetry`` field is None (dead-code elimination by construction);
- counters are accumulated in the exact order the sequential pop order
  visits work, so the CPU reference oracle
  (runtime/cpu_reference.allocate_cpu with ``collect_telemetry=True``)
  reproduces them bit-for-bit on the scan path.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

#: dtype pins for every counter leaf, module-level so the graphcheck test
#: suite can plant a 64-bit leak (monkeypatching ``_F32 = jnp.float64``)
#: and prove the telemetry family fires on it.
_I32 = jnp.int32
_F32 = jnp.float32

#: predicate families of the allocate cycle's per-task node filter, in the
#: order the rejection counters index them. Counts are "live (valid AND
#: schedulable) nodes rejected by this family alone", summed over every
#: attempted (popped, non-best-effort) task — families are counted
#: INDEPENDENTLY, so one node failing three families counts in all three
#: (the reference's per-plugin predicate error strings, aggregated).
PRED_FAMILIES = (
    "template",       # selector/taints static template row (predicates.py)
    "tdm",            # revocable-zone window gates (tdm.go:149-167)
    "node_affinity",  # OR-of-terms required node affinity group mask
    "volume",         # volume-binding seam (unbindable / pinned claims)
    "locked",         # reservation node locks (reservation.go:56-63)
    "ports",          # k8s NodePorts conflicts (predicates.go:191)
    "pod_count",      # pod-slot exhaustion (predicates.go:213-230)
    "gpu",            # single-card GPU fit (gpu.go:27-56)
    "fit_now",        # resource fit vs current idle
    "fit_future",     # resource fit vs future idle (pipelining view)
    "pod_affinity",   # inter-pod (anti-)affinity (predicates.go:261-273)
)

#: end-of-cycle classification of pending non-best-effort tasks that got
#: no placement — the TPU-native ``unschedule_task_count{reason=...}``
#: label set.
UNPLACED_REASONS = (
    "job_not_popped",     # job never popped: overused queue, gang-invalid,
    #                       closed queue, or the round cap cut it off
    "job_failed",         # job popped and broke (no feasible node) or its
    #                       gang discarded / capacity-give-up fired
    "job_kept_leftover",  # job committed (ready/pipelined) but this task
    #                       was still beyond the cursor when the cycle ended
)

_N_SCALARS = 14

#: committed-per-wave histogram width of the wavefront placement stats
#: (ISSUE 16): bucket b counts waves that committed exactly b tasks, the
#: last bucket saturating (``min(commits, WAVE_BINS - 1)``). 17 covers the
#: full 0..16 range of every supported ``wave_width``.
WAVE_BINS = 17


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class CycleTelemetry:
    """Counter block of one allocate pass. All leaves i32/f32."""

    pred_reject: jax.Array     # i32[len(PRED_FAMILIES)]
    unplaced: jax.Array        # i32[len(UNPLACED_REASONS)]
    committed: jax.Array       # f32[R] resources committed (gang-kept)
    attempts: jax.Array        # i32: task evaluations (pops x tasks tried)
    placed_now: jax.Array      # i32: MODE_ALLOCATED placements. Scan path:
    #                            counted when MADE (a later gang discard
    #                            shows up in gang_discarded instead);
    #                            pallas paths: committed only (the kernel
    #                            discards internally before the wrapper
    #                            sees the mode rows)
    placed_future: jax.Array   # i32: MODE_PIPELINED placements (same
    #                            made-vs-committed split as placed_now)
    gang_discarded: jax.Array  # i32: placements undone by gang discard
    #                            (scan path only; kernel-internal discards
    #                            are invisible to the wrapper)
    argmax_ties: jax.Array     # i32: placements whose best-node argmax had
    #                            score ties (lowest index won — the
    #                            deterministic stand-in for rand.Intn)
    rounds: jax.Array          # i32: outer while_loop rounds
    pops: jax.Array            # i32: job pops (scan: ==rounds; batched
    #                            paths: sections/in-kernel pops)
    dyn_launches: jax.Array    # i32: dynamic-key pallas kernel launches
    dyn_pops: jax.Array        # i32: in-kernel pops across dyn launches
    dyn_early_stops: jax.Array  # i32: launches that popped fewer than the
    #                             requested budget (candidate miss / hdrf
    #                             guard / work exhausted)
    wave_commits: jax.Array    # i32: tasks committed by wavefront waves
    #                            (wave_width > 1 scan/sharded paths; 0
    #                            elsewhere). Counted when made, like
    #                            placed_now — a later gang discard does not
    #                            uncount (the counters measure the wave
    #                            mechanics, not the committed outcome).
    wave_truncations: jax.Array  # i32: waves cut short because a slot's
    #                              pre-wave top-C candidate list was
    #                              exhausted by earlier same-wave commits
    #                              (the in-graph conflict rule)
    wave_replays: jax.Array    # i32: task attempts deferred to the next
    #                            wave by a truncation (the conflicting slot
    #                            and every active successor in its window)
    waves: jax.Array           # i32: wavefront sweeps launched
    wave_hist: jax.Array       # i32[WAVE_BINS]: committed-per-wave
    #                            histogram (bucket min(commits, 16))

    @classmethod
    def zeros(cls, n_res: int) -> "CycleTelemetry":
        z = jnp.zeros((), _I32)
        return cls(
            pred_reject=jnp.zeros(len(PRED_FAMILIES), _I32),
            unplaced=jnp.zeros(len(UNPLACED_REASONS), _I32),
            committed=jnp.zeros(n_res, _F32),
            attempts=z, placed_now=z, placed_future=z, gang_discarded=z,
            argmax_ties=z, rounds=z, pops=z,
            dyn_launches=z, dyn_pops=z, dyn_early_stops=z,
            wave_commits=z, wave_truncations=z, wave_replays=z, waves=z,
            wave_hist=jnp.zeros(WAVE_BINS, _I32))

    def packed(self) -> jax.Array:
        """i32[cycle_telemetry_size(R)]: the block as one i32 vector,
        appended to the decision readback so the host still pays a single
        fetch per cycle. f32 leaves ride as bitcasts."""
        scalars = jnp.stack([
            self.attempts, self.placed_now, self.placed_future,
            self.gang_discarded, self.argmax_ties, self.rounds, self.pops,
            self.dyn_launches, self.dyn_pops, self.dyn_early_stops,
            self.wave_commits, self.wave_truncations, self.wave_replays,
            self.waves])
        return jnp.concatenate([
            self.pred_reject.astype(jnp.int32),
            self.unplaced.astype(jnp.int32),
            jax.lax.bitcast_convert_type(self.committed.astype(jnp.float32),
                                         jnp.int32),
            scalars.astype(jnp.int32),
            self.wave_hist.astype(jnp.int32)])


def cycle_telemetry_size(n_res: int) -> int:
    """Element count of CycleTelemetry.packed for an R-dim snapshot."""
    return (len(PRED_FAMILIES) + len(UNPLACED_REASONS) + n_res
            + _N_SCALARS + WAVE_BINS)


def unpack_cycle_telemetry(vec, n_res: int) -> dict:
    """Host-side inverse of :meth:`CycleTelemetry.packed`: an i32 numpy
    tail -> plain-python dict (ints / lists), JSON- and metrics-ready."""
    vec = np.asarray(vec, np.int32)
    nf, nr = len(PRED_FAMILIES), len(UNPLACED_REASONS)
    off = 0
    pred = vec[off:off + nf]; off += nf
    unpl = vec[off:off + nr]; off += nr
    committed = vec[off:off + n_res].view(np.float32); off += n_res
    names = ("attempts", "placed_now", "placed_future", "gang_discarded",
             "argmax_ties", "rounds", "pops", "dyn_launches", "dyn_pops",
             "dyn_early_stops", "wave_commits", "wave_truncations",
             "wave_replays", "waves")
    out = {
        "pred_reject": {f: int(v) for f, v in zip(PRED_FAMILIES, pred)},
        "unplaced": {r: int(v) for r, v in zip(UNPLACED_REASONS, unpl)},
        "committed": [float(v) for v in committed],
    }
    for k, v in zip(names, vec[off:off + _N_SCALARS]):
        out[k] = int(v)
    off += _N_SCALARS
    out["wave_hist"] = [int(v) for v in vec[off:off + WAVE_BINS]]
    return out


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class BackfillTelemetry:
    """Counter block of one backfill pass (ops/backfill.py)."""

    candidates: jax.Array  # i32: pending best-effort tasks considered
    placed: jax.Array      # i32: tasks placed

    def to_host(self) -> dict:
        return {"candidates": int(np.asarray(self.candidates)),
                "placed": int(np.asarray(self.placed))}


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class PreemptTelemetry:
    """Counter block of one preempt/reclaim pass (ops/preempt.py)."""

    evicted: jax.Array          # i32: victim tasks evicted
    pipelined_tasks: jax.Array  # i32: preemptor tasks pipelined
    attempted_jobs: jax.Array   # i32: preemptor jobs popped
    pipelined_jobs: jax.Array   # i32: preemptor gangs that got capacity
    rounds: jax.Array           # i32: outer loop rounds

    def to_host(self) -> dict:
        return {k: int(np.asarray(getattr(self, k)))
                for k in ("evicted", "pipelined_tasks", "attempted_jobs",
                          "pipelined_jobs", "rounds")}
