"""Bounded flight recorder: the last N cycle snapshots, host-stamped.

The reference exposes only cumulative prometheus counters; diagnosing "what
did cycle 1234 do" needs per-cycle snapshots. This ring keeps the most
recent ``capacity`` cycles — each entry a plain-JSON dict (host wall
timestamp, cycle latency, bind/evict counts, the in-graph CycleTelemetry
block when enabled, host-side stage timings) — and is served by the
dashboard's ``/api/telemetry`` endpoint. Bounded by construction: memory is
O(capacity), never O(uptime).
"""

from __future__ import annotations

import json
import threading
import time
from collections import deque
from typing import Dict, List, Optional


class FlightRecorder:
    def __init__(self, capacity: int = 64):
        if capacity < 1:
            raise ValueError("flight recorder capacity must be >= 1")
        self.capacity = capacity
        self._ring: deque = deque(maxlen=capacity)
        self._lock = threading.Lock()
        self._recorded = 0   # total ever recorded (ring drops the oldest)

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    @property
    def recorded_total(self) -> int:
        with self._lock:
            return self._recorded

    def record(self, now: Optional[float] = None, **snapshot) -> Dict:
        """Append one cycle snapshot (host wall timestamp added)."""
        entry = dict(snapshot)
        entry["wall_ts"] = now if now is not None else time.time()
        with self._lock:
            self._ring.append(entry)
            self._recorded += 1
            entry["seq"] = self._recorded
        return entry

    def snapshots(self) -> List[Dict]:
        """Oldest-first copies of the retained entries."""
        with self._lock:
            return [dict(e) for e in self._ring]

    def to_json(self) -> str:
        with self._lock:
            body = {"capacity": self.capacity,
                    "recorded_total": self._recorded,
                    "cycles": [dict(e) for e in self._ring]}
        return json.dumps(body)

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()

    # the scheduler (and so the recorder) rides VolcanoSystem's pickled
    # state file (vcctl --state); locks don't pickle — recreate on load
    def __getstate__(self):
        with self._lock:
            return {"capacity": self.capacity, "_ring": list(self._ring),
                    "_recorded": self._recorded}

    def __setstate__(self, state):
        self.capacity = state["capacity"]
        self._ring = deque(state["_ring"], maxlen=self.capacity)
        self._recorded = state["_recorded"]
        self._lock = threading.Lock()
