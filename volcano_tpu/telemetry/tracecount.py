"""Compile/retrace counters for the jitted cycle entry points.

A retrace on the hot path is a production incident (the graphcheck
``recompile`` family lints for it statically); this module counts the
live truth: how many times each jitted entry point actually TRACED vs how
many times it was CALLED. The trick is the standard one (shared with
analysis/recompile.py): a host-side counter increment placed inside the
traced Python function body runs only when jax traces it — a cache hit
never re-enters Python.

Counts are process-global and exported as gauges
(``volcano_jit_traces{entry=...}`` / ``volcano_jit_calls{entry=...}`` /
``volcano_jit_cache_hits{entry=...}``) by :func:`publish_gauges`, which the
scheduler loop calls once per cycle.
"""

from __future__ import annotations

import threading
from collections import defaultdict
from typing import Callable, Dict

_LOCK = threading.Lock()
_TRACES: Dict[str, int] = defaultdict(int)
_CALLS: Dict[str, int] = defaultdict(int)


def note_trace(entry: str) -> None:
    with _LOCK:
        _TRACES[entry] += 1


def note_call(entry: str) -> None:
    with _LOCK:
        _CALLS[entry] += 1


def counts() -> Dict[str, Dict[str, int]]:
    """{entry: {"traces": n, "calls": n, "cache_hits": n}} snapshot."""
    with _LOCK:
        entries = set(_TRACES) | set(_CALLS)
        return {e: {"traces": _TRACES[e], "calls": _CALLS[e],
                    "cache_hits": max(_CALLS[e] - _TRACES[e], 0)}
                for e in sorted(entries)}


def reset() -> None:
    with _LOCK:
        _TRACES.clear()
        _CALLS.clear()


def counted_jit(fn: Callable, entry: str, **jit_kwargs) -> Callable:
    """jax.jit(fn) with trace/call accounting under ``entry``.

    The wrapper is call-transparent (same signature, same result); the
    trace counter lives INSIDE the traced body so only real traces count.
    """
    import jax

    def _traced(*args, **kwargs):
        note_trace(entry)
        return fn(*args, **kwargs)

    jitted = jax.jit(_traced, **jit_kwargs)

    def wrapper(*args, **kwargs):
        note_call(entry)
        return jitted(*args, **kwargs)

    wrapper.__wrapped__ = fn
    wrapper.__name__ = getattr(fn, "__name__", entry)
    # AOT surface (jitted.lower(...).compile()): the warmup hooks compile
    # an entry ahead of the first cycle so a restart stops paying the
    # trace+compile inline (counts as a trace — the body runs)
    wrapper.lower = jitted.lower
    return wrapper


def publish_gauges(metrics=None) -> None:
    """Export the counters as gauges into the METRICS registry."""
    if metrics is None:
        from ..metrics import METRICS as metrics
    for entry, c in counts().items():
        labels = {"entry": entry}
        metrics.set_gauge("jit_traces", labels, c["traces"])
        metrics.set_gauge("jit_calls", labels, c["calls"])
        metrics.set_gauge("jit_cache_hits", labels, c["cache_hits"])
