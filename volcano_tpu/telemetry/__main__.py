"""Cycle timeline profiler CLI: ``python -m volcano_tpu.telemetry``.

Runs a short self-contained scheduler loop (the chaos probe's cluster and
churn, no faults) with span tracing on, then exports the Chrome
trace-event JSON (``--trace out.json``, loadable in Perfetto /
chrome://tracing) and optionally the structured event log
(``--events out.jsonl``). A summary — phase p50/p95/p99, pipeline
occupancy, event counts — is printed to stdout as JSON.

The loop churns AFTER run_once returns, i.e. while the one-deep
pipeline's dispatched cycle is still in flight: that ingest work is
exactly the host/device overlap the occupancy analyzer prices, so the
pipelined run reports a genuinely nonzero ``pipeline_overlap_fraction``
while ``--sync`` honestly reports ~0 (the window interior is all blocked
readback). scripts/tier1.sh's trace smoke pins both.
"""

from __future__ import annotations

import argparse
import json
import sys


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m volcano_tpu.telemetry",
        description="span-trace a short scheduler loop and export it")
    ap.add_argument("--trace", metavar="OUT.json",
                    help="write Chrome trace-event JSON here")
    ap.add_argument("--events", metavar="OUT.jsonl",
                    help="write the structured event log (JSONL) here")
    ap.add_argument("--merge", metavar="TRACE.json",
                    help="merge another trace's traceEvents (e.g. a "
                         "converted jax.profiler device trace)")
    ap.add_argument("--cycles", type=int, default=50)
    ap.add_argument("--sync", action="store_true",
                    help="synchronous loop (no pipeline) — occupancy ~0")
    ap.add_argument("--sharded", action="store_true",
                    help="run with sharding: true (per-shard occupancy)")
    args = ap.parse_args(argv)

    from . import spans
    from ..chaos.probe import _PROBE_CONF, _churn, _small_cluster
    from ..framework.conf import parse_conf
    from ..runtime.driver import step_cycle
    from ..runtime.fake_cluster import FakeCluster
    from ..runtime.scheduler import Scheduler

    spans.reset()
    conf = parse_conf(("sharding: true\n" if args.sharded else "")
                      + _PROBE_CONF)
    pipeline = not args.sync
    cluster = FakeCluster(_small_cluster())
    sched = Scheduler(cluster, conf=conf, pipeline=pipeline)
    for c in range(args.cycles):
        # ingest runs while the dispatched cycle is in flight — the
        # overlap the pipeline exists to buy
        def _ingest(c=c):
            with spans.span("loop.ingest", cat="ingest"):
                _churn(cluster, c)
        step_cycle(sched, now=1000.0 + c, ingest=_ingest)

    trace = spans.export_chrome_trace(args.trace, merge=args.merge)
    events_written = spans.export_event_log(args.events) \
        if args.events else None
    summary = {
        "cycles": args.cycles,
        "pipeline": pipeline,
        "sharded": args.sharded,
        "trace_path": args.trace,
        "trace_events": len(trace["traceEvents"]),
        "phases": spans.phase_stats(),
        "occupancy": spans.occupancy(),
        "events_logged": len(spans.events()),
        "events_written": events_written,
    }
    json.dump(summary, sys.stdout, indent=2)
    sys.stdout.write("\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
