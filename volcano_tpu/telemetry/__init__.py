"""Observability for the compiled scheduling cycle (ISSUE 3 tentpole).

Four layers, all host-callback-free on the hot path:

- :mod:`.cycle` — ``CycleTelemetry`` and friends: pure i32/f32 counter
  pytrees accumulated INSIDE the compiled cycle (per-predicate-family
  rejection counts, placed/pipelined/discarded task counts, pallas
  dyn-kernel pop/early-stop counts, argmax tie counts, unplaced-reason
  histograms), returned as one extra output and fetched in the same packed
  readback as the decisions. Gated by ``AllocateConfig.telemetry`` (default
  False — the off-jaxpr is equation-count-identical to no telemetry at
  all; graphcheck family 7 guards the contract).
- :mod:`.flight_recorder` — a bounded ring of the last N per-cycle
  snapshots with host timestamps, owned by the scheduler loop and the
  sidecar, served as JSON by the dashboard's ``/api/telemetry``.
- :mod:`.tracecount` — jit trace-vs-call counters for the compiled entry
  points, exported as ``volcano_jit_*`` gauges (a live retrace is the
  production analog of the graphcheck recompile family).
- :mod:`.spans` — host-side span tracing of the steady cycle (ISSUE 8):
  per-phase p50/p95/p99 latency rings, the pipeline-occupancy analyzer
  (``pipeline_overlap_fraction`` / ``bubble_ms`` against the in-flight
  device window), a Chrome trace-event exporter
  (``python -m volcano_tpu.telemetry --trace out.json``), and the
  structured event log for degradation transitions, digest trips, and
  recoveries. Host-only by construction: jaxprs and decisions are
  bit-identical with tracing on or off.

``/metrics`` keeps the cumulative prometheus families (the reference's
surface); ``/api/telemetry`` serves the per-cycle flight record — see
docs/architecture.md "Observability".
"""

from __future__ import annotations

from . import spans
from .cycle import (PRED_FAMILIES, UNPLACED_REASONS, BackfillTelemetry,
                    CycleTelemetry, PreemptTelemetry, cycle_telemetry_size,
                    unpack_cycle_telemetry)
from .flight_recorder import FlightRecorder
from .tracecount import counted_jit, publish_gauges

__all__ = [
    "PRED_FAMILIES", "UNPLACED_REASONS", "BackfillTelemetry",
    "CycleTelemetry", "PreemptTelemetry", "cycle_telemetry_size",
    "unpack_cycle_telemetry", "FlightRecorder", "counted_jit",
    "publish_gauges", "publish_cycle_telemetry", "spans",
]


def publish_cycle_telemetry(tel: dict, metrics=None) -> None:
    """Bridge one cycle's unpacked CycleTelemetry dict into the METRICS
    registry: labeled counters in the reference's metric vocabulary
    (``unschedule_task_count{reason=...}``,
    ``cycle_predicate_rejections{family=...}``) plus last-cycle gauges."""
    if metrics is None:
        from ..metrics import METRICS as metrics
    for fam, n in tel.get("pred_reject", {}).items():
        if n:
            metrics.inc("cycle_predicate_rejections", n,
                        labels={"family": fam})
    for reason, n in tel.get("unplaced", {}).items():
        if n:
            metrics.inc("unschedule_task_count", n,
                        labels={"reason": reason})
    metrics.inc("cycle_tasks_allocated", tel.get("placed_now", 0))
    metrics.inc("cycle_tasks_pipelined", tel.get("placed_future", 0))
    metrics.inc("cycle_gang_discarded_tasks", tel.get("gang_discarded", 0))
    metrics.inc("cycle_argmax_ties", tel.get("argmax_ties", 0))
    metrics.set_gauge("cycle_rounds", None, tel.get("rounds", 0))
    metrics.set_gauge("cycle_pops", None, tel.get("pops", 0))
    metrics.set_gauge("cycle_dyn_launches", None, tel.get("dyn_launches", 0))
    metrics.set_gauge("cycle_dyn_early_stops", None,
                      tel.get("dyn_early_stops", 0))
    # wavefront placement stats (ISSUE 16): counters for the totals, one
    # gauge for the last cycle's commit efficiency — commits out of
    # commit-or-replay attempts, the number the bench regression-guards
    metrics.inc("wave_commits_total", tel.get("wave_commits", 0))
    metrics.inc("wave_truncations_total", tel.get("wave_truncations", 0))
    metrics.inc("wave_replays_total", tel.get("wave_replays", 0))
    commits = tel.get("wave_commits", 0)
    if tel.get("waves", 0):
        metrics.set_gauge(
            "wave_commit_ratio", None,
            commits / max(commits + tel.get("wave_replays", 0), 1))
